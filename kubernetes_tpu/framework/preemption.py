"""Preemption: the generic Evaluator + the DefaultPreemption PostFilter.

Host orchestration mirrors /root/reference/pkg/scheduler/framework/
preemption/preemption.go (Evaluator.Preempt :232, findCandidates :307,
SelectCandidate/pickOneNodeForPreemption :395,:565, prepareCandidate :428)
and plugins/defaultpreemption/default_preemption.go (PostFilter :133,
SelectVictimsOnNode :219, PodEligibleToPreemptOthers :327,
GetOffsetAndNumCandidates :186) — with the per-node dry-run replaced by ONE
device sweep over victim prefixes (ops.preempt.preempt_sweep).

Victim ordering: pods on a node sort ascending by importance
(util.MoreImportantPod: priority, then start time) so the minimal feasible
prefix evicts the least-important pods first — the resource-space fixed
point of the reference's remove-all-then-reprieve loop.
"""

from __future__ import annotations

import logging
import random
from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.api.labels import label_selector_matches
from kubernetes_tpu.api.objects import (
    LABEL_POD_GROUP,
    Pod,
    pod_group_key,
)
from kubernetes_tpu.hub import Fenced, StaleRing, Unavailable
from kubernetes_tpu.framework.interface import (
    PostFilterPlugin,
    PreEnqueuePlugin,
    Status,
)
from kubernetes_tpu.ops import features as F
from kubernetes_tpu.ops.preempt import preempt_feasible_jit, preempt_sweep_jit
from kubernetes_tpu.utils.interner import NONE

import jax

logger = logging.getLogger("kubernetes_tpu.preemption")

# sentinel: the incremental victim-state update cannot represent the new
# cluster shape; fall back to a full rebuild
_REBUILD = object()

# row-scatter into the resident [N, K+1, C] victim cumsum (axis-0 rows)
_scatter_rows0_jit = jax.jit(lambda buf, idx, rows: buf.at[idx].set(rows),
                             donate_argnums=(0,))

MI = 1024 * 1024

# default_preemption.go:40-44 (DefaultPreemptionArgs defaults)
MIN_CANDIDATE_NODES_PERCENTAGE = 10
MIN_CANDIDATE_NODES_ABSOLUTE = 100

# bound on exact dry-run launches per preemption attempt: candidates tried
# (verification) + reprieve steps on the winner
MAX_VERIFY_CANDIDATES = 8
MAX_REPRIEVE_STEPS = 16


@dataclass
class Candidate:
    """One preemption candidate (candidate.go): a node + its victims."""

    node_name: str
    row: int
    victims: list[Pod]
    pdb_violations: int
    # True once an extender's ProcessPreemption pass ran: the victim list
    # is FINAL — verification may discard the candidate but must never
    # regrow or reprieve the list (the reference runs callExtenders after
    # the dry-run's reprieve, so extender trims are authoritative)
    victims_final: bool = False


class Evaluator:
    """Generic preemption evaluator over the device mirror."""

    def __init__(self, hub, get_mirror, get_caps, get_enabled_filters,
                 nominator, rng: random.Random | None = None):
        self.hub = hub
        # callables: the scheduler re-buckets the mirror/caps, and the
        # framework (which owns the filter config) is built after us
        self._get_mirror = get_mirror
        self._get_caps = get_caps
        self._get_enabled_filters = get_enabled_filters
        self.nominator = nominator
        self._rng = rng or random.Random(0)
        # request-row cache: a victim's packed resource row is immutable per
        # uid FOR A GIVEN MIRROR — a re-bucketed mirror changes res_cols and
        # ext-resource column order, so the cache is tied to the mirror
        # object and dropped when the scheduler rebuilds it
        self._res_rows: dict[tuple[str, bool], np.ndarray] = {}
        self._res_rows_mirror: object = None
        # async preemption (preemption.go:460 prepareCandidateAsync +
        # kep 4832): pods whose victims are still being evicted, and the
        # eviction work queue the scheduler drains between cycles
        self.preempting: set[str] = set()
        self._pending: list[tuple[Candidate, Pod]] = []
        # nominee status-clear writes deferred by a hub outage (the
        # local nomination is already dropped; only the API write waits)
        self._pending_clears: list[str] = []
        # scheduler-installed: activates preemptors whose flush produced no
        # deletion event (empty/already-deleted victim sets) — the gate
        # opener of last resort (see flush_evictions)
        self.activate_fn = None
        # scheduler-installed (pipelined waves): when True, a preemptor
        # whose eviction wave FIRED is also activated explicitly at flush
        # end — it re-probes on the very next wave instead of waiting out
        # the deletion event's backoff routing (its nominated reservation
        # protects the freed slot meanwhile)
        self.activate_flushed = False
        # scheduler-installed (pipelined waves): () -> live device free
        # matrix (the scheduler's resident free/nzr chain) or None. When
        # set and live, the sweep/probe fit baselines see in-flight waves
        # the snapshot free matrix has not absorbed yet
        self.live_free_fn = None
        # scheduler-installed: () -> [HTTPExtender]; candidates pass
        # through ProcessPreemption before selection (preemption.go:335)
        self.extenders_fn = None
        self.metrics = None     # SchedulerMetrics, set by the Scheduler
        # scheduler-installed fencing: () -> (epoch, lease_name) | ();
        # queued evictions and nomination clears carry the epoch of the
        # flush that lands them, so a deposed leader's backlog is
        # rejected (Fenced) instead of evicting pods the new leader may
        # have re-planned around
        self.fencing_fn = None
        self.fenced_metric = None   # (verb) -> None, set by the Scheduler
        # incremental victim-sweep state per preemptor priority (see
        # _collect_victims): row_gen-keyed victim lists + the resident
        # device cumsum, refreshed by row-scatter between bursts
        self._vic_state: dict[int, dict] = {}

    # ---------------- eligibility (default_preemption.go:327) -------------

    def pod_eligible_to_preempt_others(self, pod: Pod) -> tuple[bool, str]:
        if pod.spec.preemption_policy == "Never":
            return False, "preemptionPolicy=Never"
        nom = pod.status.nominated_node_name
        if nom:
            # if the nominated node has a terminating lower-priority pod, the
            # previous preemption is still in flight: wait for it
            mirror = self._get_mirror()
            row = mirror.row_of(nom)
            if row >= 0:
                snap_pods = self._pods_on_node(nom)
                for p in snap_pods:
                    if (p.metadata.deletion_timestamp is not None
                            and p.priority() < pod.priority()):
                        return False, "previous victims still terminating"
        return True, ""

    # ---------------- candidate discovery ----------------

    def _pods_on_node(self, node_name: str) -> list[Pod]:
        info = self.cache_snapshot.get(node_name)
        return [pi.pod for pi in info.pods] if info is not None else []

    def find_candidates(self, pod: Pod, snapshot,
                        resource_only: bool = False) -> list[Candidate]:
        """Device sweep + host assembly of (node, victims) candidates.
        ``resource_only``: the caller knows the pod's rejection was pure
        NodeResourcesFit, so the sweep's answer is exact and the
        full-filter dry-run machinery is skipped."""
        self.cache_snapshot = snapshot.node_info_map
        mirror = self._get_mirror()
        caps = self._get_caps()
        prio = pod.priority()
        prep = self._collect_victims(prio, snapshot, mirror, caps)
        if prep is None:
            return []
        victims_by_row, k_cap, cumsum, vic_cols, cumsum_np, cols_np = prep

        pblobs = mirror.pack_batch_blobs([pod], 1)
        cblobs = mirror.to_blobs()
        live_free = (self.live_free_fn()
                     if self.live_free_fn is not None else None)
        kmin = np.asarray(preempt_sweep_jit(
            cblobs, pblobs, mirror.well_known(), cumsum, vic_cols, caps,
            self._get_enabled_filters(pod), free=live_free))[0]
        self._kmin = kmin                     # reused by _minimize_victims
        self._victims_by_row = victims_by_row

        # candidate rows: full-filter feasibility with EVERY victim evicted
        # (the reference's remove-all first step, default_preemption.go:219,
        # evaluated for all nodes in one launch). This is the exact superset
        # of per-node-eviction feasibility for monotone filters; the chosen
        # candidate is re-verified with per-node masking before any eviction
        # happens, so an optimistic row costs one extra launch, never a
        # wrong eviction. Topology-blocked preemptors (a victim's
        # anti-affinity, a hard spread violation) find candidates here even
        # though they "fit" resource-wise — the gap the resource-only sweep
        # could not cover.
        if resource_only:
            # the pod was rejected ONLY by NodeResourcesFit: the resource
            # sweep's kmin IS the reference's remove-then-reprieve fixed
            # point (victims sorted ascending importance), so candidate
            # rows and minimal victim sets come straight from it — zero
            # additional dry-run launches on the hot preemption path
            return self._assemble_candidates(
                pod, kmin, victims_by_row, snapshot, mirror,
                mirror.free_matrix(), self.hub.list_pdbs())

        all_uids = {pi.pod.metadata.uid
                    for vs in victims_by_row.values() for pi in vs}
        # keep victims that could SATISFY the preemptor's required affinity
        # visible: masking them cluster-wide would under-approximate
        # feasibility (the reference only ever removes the candidate node's
        # own pods). A provider-victim on the chosen node itself is caught
        # by the exact per-node verification.
        aff = pod.spec.affinity
        aff_terms = (aff.pod_affinity.required
                     if aff is not None and aff.pod_affinity is not None
                     else [])
        if aff_terms:
            for vs in victims_by_row.values():
                for pi in vs:
                    v = pi.pod
                    for term in aff_terms:
                        ns_ok = (v.metadata.namespace
                                 == pod.metadata.namespace
                                 if not term.namespaces
                                 else v.metadata.namespace in term.namespaces)
                        if ns_ok and label_selector_matches(
                                term.label_selector, v.metadata.labels):
                            all_uids.discard(v.metadata.uid)
                            break
        r_cols = caps.res_cols
        freed = {}
        for row, vs in victims_by_row.items():
            full = np.zeros((r_cols,), np.float32)
            full[cols_np] = cumsum_np[row, len(vs), : len(cols_np)]
            freed[row] = full
        feas = self._dryrun_feasible(pod, all_uids, freed)
        rows = [row for row in victims_by_row if feas[row]]
        if not rows:
            return []

        # candidate subset: random offset + bounded count (preemption.go:307
        # GetOffsetAndNumCandidates)
        num_nodes = len(snapshot.node_info_list)
        want = max(num_nodes * MIN_CANDIDATE_NODES_PERCENTAGE // 100,
                   MIN_CANDIDATE_NODES_ABSOLUTE)
        rows.sort()
        off = self._rng.randrange(len(rows))
        picked = [rows[(off + i) % len(rows)]
                  for i in range(min(want, len(rows)))]

        pdbs = self.hub.list_pdbs()
        out = []
        for row in picked:
            vs = victims_by_row[row]
            # rank candidates by their minimal-victim ESTIMATE: the kmin
            # prefix when the resource sweep found one (exact for
            # resource-blocked preemptors), the full list otherwise —
            # select_candidate's pdb/priority/count keys would regress if
            # computed over pods that will never be evicted
            k = int(kmin[row])
            if k != NONE and 1 <= k <= len(vs):
                vs = vs[:k]
            victims = [pi.pod for pi in vs]
            out.append(Candidate(
                node_name=mirror.name_of_row(row) or "",
                row=row, victims=victims,
                pdb_violations=self._pdb_violations(victims, pdbs)))
        return out

    def _dryrun_feasible(self, pod: Pod, exclude_uids, freed_by_row
                         ) -> np.ndarray:
        """[N] bool: FULL filter set for ``pod`` with ``exclude_uids``
        masked out of the device pod table and each row's free resources
        raised by its freed vector (ops.preempt.preempt_feasible)."""
        mirror = self._get_mirror()
        caps = self._get_caps()
        tval = mirror.table_valid_mask(exclude_uids)
        live_free = (self.live_free_fn()
                     if self.live_free_fn is not None else None)
        # live chain wins when present: the probe's fit baseline then
        # includes waves still in flight (np.array forces a writable
        # host copy off the device buffer)
        free = (np.array(live_free, np.float32) if live_free is not None
                else mirror.free_matrix())
        for row, vec in freed_by_row.items():
            free[row] = free[row] + vec
        pblobs = mirror.pack_batch_blobs([pod], 1)
        enable = (mirror.table_has_topology()
                  or mirror.batch_has_topology([pod]))
        return np.asarray(preempt_feasible_jit(
            mirror.to_blobs(), pblobs, mirror.well_known(), caps,
            jnp.asarray(tval), jnp.asarray(free), enable,
            mirror.launch_d_cap(enable), self._get_enabled_filters(pod)))

    def _res_row_cached(self, pod: Pod, freed: bool = False) -> np.ndarray:
        """A pod's f32 resource row: demand (the preemptor's request)
        rounds UP; ``freed=True`` (a victim's contribution handed back
        to capacity) rounds DOWN — summing ceiled victim rows onto free
        would overstate post-eviction headroom and evict pods for a
        preemption that cannot succeed."""
        from kubernetes_tpu.api.resources import pod_request

        key = (pod.metadata.uid, freed)
        rr = self._res_rows.get(key)
        if rr is None:
            rr = np.asarray(self._get_mirror()._res_row(
                pod_request(pod), capacity=freed), np.float32)
            self._res_rows[key] = rr
        return rr

    def _minimize_victims(self, pod: Pod, cand: Candidate,
                          pdbs) -> Candidate | None:
        """Exact verification + reprieve for one candidate (the
        reference's per-node reprieve loop, default_preemption.go:219):

        1. Verify the pod actually fits with ONLY this node's victims
           evicted (full filters). A candidate from the optimistic
           all-evicted pass that fails here is discarded — no eviction ever
           happens on an unverified candidate.
        2. If the resource sweep found a feasible prefix, try it first: the
           prefix (least-important victims) is the resource-space reprieve
           fixed point, one launch to confirm.
        3. Otherwise reprieve victims one at a time — PDB-violating victims
           first, then most-important-first — keeping each reprieve that
           leaves the pod feasible (bounded by MAX_REPRIEVE_STEPS).
        """
        row = cand.row
        victims = list(cand.victims)        # ascending importance

        def feasible_with(vset: list[Pod]) -> bool:
            if not vset:
                return False
            freed = np.zeros_like(self._res_row_cached(vset[0],
                                                       freed=True))
            for v in vset:
                freed = freed + self._res_row_cached(v, freed=True)
            feas = self._dryrun_feasible(
                pod, {v.metadata.uid for v in vset}, {row: freed})
            return bool(feas[row])

        if cand.victims_final:
            # an extender trimmed this list: it is authoritative — verify
            # as-is; never regrow to the full set or reprieve further
            return cand if feasible_with(victims) else None

        kmin = getattr(self, "_kmin", None)
        k = int(kmin[row]) if kmin is not None else NONE
        from_prefix = k != NONE and len(victims) == k
        if not feasible_with(victims):
            # the candidate carried the kmin-trimmed ranking estimate; try
            # the node's full victim set before giving up (topology-blocked
            # preemptors may need more than the resource prefix)
            full = [pi.pod for pi in self._victims_by_row.get(row, [])]
            if len(full) > len(victims) and feasible_with(full):
                victims = full
                from_prefix = False
            else:
                return None                 # unverifiable candidate: discard
        elif from_prefix:
            # the verified set IS the resource sweep's minimal prefix: the
            # reprieve loop cannot shrink it further (each prefix k-1 was
            # already infeasible by kmin's minimality) — skip the per-victim
            # launches entirely for the resource-blocked common case
            return Candidate(
                node_name=cand.node_name, row=row, victims=victims,
                pdb_violations=self._pdb_violations(victims, pdbs))
        if k != NONE and 1 <= k < len(victims):
            prefix = victims[:k]
            if feasible_with(prefix):
                victims = prefix
        if len(victims) > 1:
            flags = self._pdb_violation_flags(victims, pdbs)
            # reprieve order: PDB-violating first, then priority desc,
            # then older first (filterPodsWithPDBViolation + reprievePod)
            order = sorted(
                range(len(victims)),
                key=lambda i: (not flags[i], -victims[i].priority(),
                               victims[i].metadata.creation_timestamp))
            kept = set()
            steps = 0
            for i in order:
                if steps >= MAX_REPRIEVE_STEPS or len(victims) - len(kept) <= 1:
                    break
                trial = [v for j, v in enumerate(victims)
                         if j != i and j not in kept]
                steps += 1
                if feasible_with(trial):
                    kept.add(i)
            victims = [v for j, v in enumerate(victims) if j not in kept]
        return Candidate(
            node_name=cand.node_name, row=row, victims=victims,
            pdb_violations=self._pdb_violations(victims, pdbs))

    @staticmethod
    def _pdb_violation_flags(victims: list[Pod], pdbs) -> list[bool]:
        """Per-victim: does evicting it violate some exhausted PDB?"""
        budget = {pdb.metadata.uid: pdb.disruptions_allowed for pdb in pdbs}
        flags = []
        for v in victims:
            matched = [pdb for pdb in pdbs
                       if pdb.metadata.namespace == v.metadata.namespace
                       and pdb.selector is not None
                       and label_selector_matches(pdb.selector,
                                                  v.metadata.labels)]
            flags.append(any(budget[pdb.metadata.uid] <= 0
                             for pdb in matched))
            for pdb in matched:
                budget[pdb.metadata.uid] -= 1
        return flags

    @staticmethod
    def _pdb_violations(victims: list[Pod], pdbs) -> int:
        """How many VICTIMS violate some PDB's disruptionsAllowed — each pod
        counts at most once even if it matches several exhausted PDBs
        (preemption.go filterPodsWithPDBViolation classifies per pod); every
        eviction still draws down each matching PDB's budget."""
        budget = {pdb.metadata.uid: pdb.disruptions_allowed for pdb in pdbs}
        violations = 0
        for v in victims:
            matched = [pdb for pdb in pdbs
                       if pdb.metadata.namespace == v.metadata.namespace
                       and pdb.selector is not None
                       and label_selector_matches(pdb.selector,
                                                  v.metadata.labels)]
            if any(budget[pdb.metadata.uid] <= 0 for pdb in matched):
                violations += 1
            for pdb in matched:
                budget[pdb.metadata.uid] -= 1
        return violations

    # ------------- extender pass (preemption.go:335 callExtenders) --------

    def call_extenders(self, pod: Pod,
                       candidates: list[Candidate]) -> list[Candidate]:
        """Run every preemption-capable interested extender over the
        candidate map: extenders veto nodes (omission) and trim victim
        lists (trims are FINAL — victims_final). An ignorable extender's
        transport failure is skipped; a non-ignorable one raises
        ExtenderError so the caller aborts the attempt as an ERROR, not
        a misleading 'no candidates' (preemption.go:349)."""
        from kubernetes_tpu.extender import ExtenderError

        extenders = self.extenders_fn() if self.extenders_fn else []
        relevant = [ext for ext in extenders
                    if ext.supports_preemption and ext.is_interested(pod)]
        if not relevant or not candidates:
            return candidates
        by_node = {c.node_name: c for c in candidates}
        node_to_victims = {c.node_name: list(c.victims)
                           for c in candidates}
        pdbs = {c.node_name: c.pdb_violations for c in candidates}
        for ext in relevant:
            try:
                survivors = ext.process_preemption(pod, node_to_victims,
                                                   pdbs)
            except ExtenderError as e:
                if ext.cfg.ignorable:
                    continue
                logger.warning("preemption extender failed: %s", e)
                raise
            # a node returned with NO victims is removed, like upstream
            # callExtenders deletes empty/unresolvable entries — an
            # empty-victim candidate would otherwise always win selection
            # while evicting nothing
            node_to_victims = {n: v for n, (v, _p) in survivors.items()
                               if v}
            pdbs = {n: p for n, (_v, p) in survivors.items() if _v}
            if not node_to_victims:
                return []
        out = []
        for node, victims in node_to_victims.items():
            c = by_node[node]
            if len(victims) < len(c.victims):
                # the extender TRIMMED a verified-minimal list: upstream
                # trusts the extender blindly; we add a cheap host
                # resource-sufficiency check and drop candidates whose
                # trimmed set can no longer free enough (a bad extender
                # must not cause a pointless eviction)
                if not self._resources_sufficient(pod, c.row, victims):
                    continue
            out.append(Candidate(node_name=c.node_name, row=c.row,
                                 victims=victims,
                                 pdb_violations=pdbs.get(node, 0),
                                 victims_final=True))
        return out

    def _resources_sufficient(self, pod: Pod, row: int,
                              victims: list[Pod]) -> bool:
        """Host arithmetic: do these victims' requests free enough on
        ``row`` for the pod to fit resource-wise? (Necessary, not
        sufficient, for topology-blocked preemptors — still strictly
        safer than upstream's unchecked trust in extender trims.)"""
        mirror = self._get_mirror()
        free = np.asarray(mirror.free_matrix()[row], np.float32)
        nom = getattr(mirror, "_nominated_req_of_row", {}).get(row)
        if nom is not None:
            free = free - np.asarray(nom, np.float32)
        req = np.asarray(self._res_row_cached(pod), np.float32)
        nnn = pod.status.nominated_node_name
        if nnn and mirror.row_of(nnn) == row:
            free = free + req
        freed = np.zeros_like(req)
        for v in victims:
            freed = freed + self._res_row_cached(v, freed=True)
        return bool(np.all(req <= free + freed))

    # ---------------- selection (preemption.go:565 pickOneNode) -----------

    @staticmethod
    def candidate_key(c: Candidate):
        """pickOneNodeForPreemption's ordering (preemption.go:565):
        fewest PDB violations, lowest max victim priority, lowest
        priority sum, fewest victims, latest-started important victim."""
        prios = [v.priority() for v in c.victims]
        high = max(prios) if prios else -(2 ** 31)
        # latest start of the highest-priority victim: prefer evicting
        # the youngest important pod
        starts = [v.metadata.creation_timestamp for v in c.victims
                  if v.priority() == high]
        latest = max(starts) if starts else 0.0
        return (c.pdb_violations, high, sum(prios), len(c.victims),
                -latest, c.node_name)

    @staticmethod
    def select_candidate(candidates: list[Candidate]) -> Candidate | None:
        if not candidates:
            return None
        return min(candidates, key=Evaluator.candidate_key)

    # ---------------- execution (preemption.go:428 prepareCandidate) ------

    def prepare_candidate(self, candidate: Candidate, pod: Pod) -> None:
        """Queue the eviction work (prepareCandidateAsync, kep 4832): the
        scheduler drains it via flush_evictions OUTSIDE the scheduling
        cycle, and the DefaultPreemption PreEnqueue gate keeps the
        preemptor parked until its victims are gone."""
        self.preempting.add(pod.metadata.uid)
        self._pending.append((candidate, pod))

    def has_pending(self) -> bool:
        """Whether flush_evictions has queued work (evictions or deferred
        nomination clears) — the scheduler's cue to time the flush as an
        eviction_flush phase instead of skipping the empty no-op."""
        return bool(self._pending or self._pending_clears)

    def flush_evictions(self) -> int:
        """Execute queued evictions; returns the number of preparations
        run. The preemptor leaves ``preempting`` BEFORE the last victim
        deletion so that deletion's cluster event finds the gate open and
        requeues it (preemption.go:528's ordering). A candidate whose
        victim set is empty — or whose victims were already deleted by an
        overlapping candidate this flush — produces NO deletion event, so
        its preemptor is activated explicitly (``activate_fn``): without
        that, two preemptors nominating the same node can deadlock parked
        behind each other's reservations."""
        # retry API nomination clears a previous outage deferred (the
        # local nominator entries are already gone, so only the status
        # write can be replayed)
        fargs = self.fencing_fn() if self.fencing_fn is not None else ()
        clears, self._pending_clears = self._pending_clears, []
        for uid in clears:
            try:
                self.hub.clear_nominated_node(uid, *fargs)
            except Unavailable:
                self._pending_clears.append(uid)
            except Fenced:
                self._note_fenced("clear_nominated_node")
                # deposed: the new leader owns preemption policy now —
                # drop the clear backlog AND the eviction backlog (a
                # re-elected leader replaying either under its newer
                # epoch would launder stale decisions) and ungate every
                # queued preemptor for the retry path
                self._pending_clears = []
                dropped, self._pending = self._pending, []
                stranded = []
                for _cand, p in dropped:
                    self.preempting.discard(p.metadata.uid)
                    stranded.append(p)
                if stranded and self.activate_fn is not None:
                    self.activate_fn(stranded)
                return 0
            except Exception:  # noqa: BLE001 — pod gone: nothing to clear
                pass
        work, self._pending = self._pending, []
        stranded = []
        try:
            self._flush_candidates(work, stranded, fargs)
        finally:
            # the activation of already-processed stranded preemptors
            # must fire even when an outage aborts the flush mid-way:
            # they are no longer in ``preempting`` and no deletion event
            # will requeue them (activate_fn is queue-local, hub-free)
            if stranded and self.activate_fn is not None:
                self.activate_fn(stranded)
        return len(work)

    def _note_fenced(self, verb: str) -> None:
        if self.fenced_metric is not None:
            self.fenced_metric(verb)
        logger.warning("preemption %s rejected: this scheduler's fencing "
                       "epoch was deposed; dropping the eviction backlog",
                       verb)

    def _flush_candidates(self, work: list, stranded: list,
                          fargs: tuple = ()) -> None:
        """One flush = plan, then ONE multi-delete wave (ISSUE 15).

        Phase A walks the backlog host-side (nomination clears, gang
        expansion, PDB/priority guards) into per-candidate victim plans;
        phase B opens every planned preemptor's gate and commits ALL
        victim deletions as one ``hub.delete_pods`` wave — a single lock
        acquisition / RPC instead of one per victim; phase C strands any
        candidate none of whose victims actually produced a deletion
        event. Hubs without the batched verb (sharded facades, old
        peers) keep the per-victim path with identical semantics."""
        batched = getattr(self.hub, "delete_pods", None)
        if not callable(batched):
            return self._flush_candidates_serial(work, stranded, fargs)
        listed: dict = {}

        def _list_once():
            if "pods" not in listed:
                listed["pods"] = self.hub.list_pods()
            return listed["pods"]

        plans: list = []            # (pod, victims) per surviving candidate
        for i, (candidate, pod) in enumerate(work):
            try:
                dropped = self.nominator.clear_for_node_below_priority(
                    candidate.node_name, pod.priority())
                for nominee in dropped:
                    try:
                        self.hub.clear_nominated_node(
                            nominee.metadata.uid, *fargs)
                    except Unavailable:
                        self._pending_clears.append(nominee.metadata.uid)
                victims, blocked = self._expand_gang_victims(
                    candidate.victims, pod, _list_once)
                if blocked:
                    logger.info("gang eviction for %s blocked: %s",
                                pod.key(), blocked)
                    self.preempting.discard(pod.metadata.uid)
                    stranded.append(pod)
                    continue
                plans.append((pod, victims))
            except Unavailable:
                # outage mid-planning: nothing is deleted yet — the
                # whole backlog (already-planned candidates included)
                # replays; every planning step is idempotent
                planned = {p.metadata.uid for (p, _v) in plans}
                self._pending = (
                    [w for w in work if w[1].metadata.uid in planned]
                    + work[i:] + self._pending)
                raise
        if not plans:
            return
        # phase B: gates open BEFORE any deletion event can fire (the
        # batched form of preemption.go:528's ordering), then one wave
        uids: list[str] = []
        owner: dict[str, int] = {}  # victim uid -> first plan claiming it
        for i, (pod, victims) in enumerate(plans):
            self.preempting.discard(pod.metadata.uid)
            for v in victims:
                if v.metadata.uid not in owner:
                    owner[v.metadata.uid] = i
                    uids.append(v.metadata.uid)
        try:
            gone = set(batched(uids, *fargs)) if uids else set()
        except Unavailable:
            # the wave's verdict is unknown: re-gate + requeue every
            # planned candidate; a replayed wave skips already-gone
            # victims, so replay is idempotent
            for pod, _v in plans:
                self.preempting.add(pod.metadata.uid)
            self._pending = ([w for w in work
                              if w[1].metadata.uid in
                              {p.metadata.uid for (p, _v) in plans}]
                             + self._pending)
            raise
        except StaleRing:
            # a ring slot froze mid-wave (segment export in flight):
            # partially-committed deletes already dispatched their
            # events; re-gate + requeue like the Unavailable case —
            # replay is idempotent — but swallow: the freeze heals on
            # its own (import / abort / FROZEN_TTL), no outage to note
            for pod, _v in plans:
                self.preempting.add(pod.metadata.uid)
            self._pending = ([w for w in work
                              if w[1].metadata.uid in
                              {p.metadata.uid for (p, _v) in plans}]
                             + self._pending)
            return
        except Fenced:
            self._note_fenced("delete_pod")
            for pod, _v in plans:
                stranded.append(pod)
            self._pending = []
            return
        for i, (pod, victims) in enumerate(plans):
            # a plan is "fired" only by a deletion it OWNS (first claim in
            # plan order — the serial path's exact discipline): a candidate
            # whose victims were all claimed by overlapping earlier plans
            # produces no deletion event of its own, so its preemptor must
            # be activated explicitly or two preemptors nominating the
            # same node deadlock in escalating backoff behind each other's
            # reservations
            fired = any(v.metadata.uid in gone
                        and owner[v.metadata.uid] == i for v in victims)
            # pipelined waves: a FIRED preemptor is activated too — its
            # re-probe rides the very next scheduling wave instead of
            # waiting for the deletion event's backoff routing (the
            # nominated reservation keeps the freed slot protected, and
            # queue.activate is a no-op for pods already runnable)
            if not fired or self.activate_flushed:
                stranded.append(pod)

    def _flush_candidates_serial(self, work: list, stranded: list,
                                 fargs: tuple = ()) -> None:
        # one cluster pod list per FLUSH, fetched lazily on the first
        # gang victim and shared by every candidate — per-candidate
        # list_pods() would pay a full-cluster RPC for each gang
        # eviction in the backlog
        listed: dict = {}

        def _list_once():
            if "pods" not in listed:
                listed["pods"] = self.hub.list_pods()
            return listed["pods"]

        for i, (candidate, pod) in enumerate(work):
            try:
                # lower-priority nominees on this node must re-evaluate:
                # drop the nomination AND clear the API status; the
                # update event re-activates them
                dropped = self.nominator.clear_for_node_below_priority(
                    candidate.node_name, pod.priority())
                for nominee in dropped:
                    try:
                        self.hub.clear_nominated_node(
                            nominee.metadata.uid, *fargs)
                    except Unavailable:
                        # the nominator entry is dropped for good — a
                        # retried candidate would find nothing to clear
                        # — so park the STATUS write itself for replay
                        self._pending_clears.append(nominee.metadata.uid)
                # whole-gang eviction: a victim that belongs to a gang
                # takes its ENTIRE gang with it (cluster-wide), never a
                # partial slice — a half-evicted gang would keep burning
                # nodes on a job that can no longer run
                victims, blocked = self._expand_gang_victims(
                    candidate.victims, pod, _list_once)
                if blocked:
                    # a pulled-in co-member is protected (exhausted PDB,
                    # or outranks the preemptor): the gang cannot be
                    # evicted whole, so nothing of it is evicted at all —
                    # strand the preemptor to re-evaluate other nodes
                    logger.info("gang eviction for %s blocked: %s",
                                pod.key(), blocked)
                    self.preempting.discard(pod.metadata.uid)
                    stranded.append(pod)
                    continue
                for victim in victims[:-1]:
                    try:
                        self.hub.delete_pod(victim.metadata.uid, *fargs)
                    except Unavailable:
                        raise           # outage ≠ "already gone"
                    except Fenced:
                        raise
                    except Exception:  # noqa: BLE001 — gone is fine
                        pass
                self.preempting.discard(pod.metadata.uid)
                fired = False
                if victims:
                    try:
                        self.hub.delete_pod(victims[-1].metadata.uid,
                                            *fargs)
                        fired = True
                    except Unavailable:
                        raise
                    except Fenced:
                        raise
                    except Exception:  # noqa: BLE001
                        pass
                # pipelined waves: activate fired preemptors too (see the
                # batched path) so the re-probe rides the next wave
                if not fired or self.activate_flushed:
                    stranded.append(pod)
            except Unavailable:
                # hub outage mid-candidate: requeue it and the whole
                # unprocessed tail so nothing is dropped on the floor.
                # Re-gate THIS candidate's preemptor: its discard may
                # already have run, and an ungated preemptor could fail
                # another cycle and enqueue a duplicate candidate before
                # this one replays. Every step above is idempotent on
                # replay (NotFound deletes are swallowed, set ops).
                self.preempting.add(pod.metadata.uid)
                self._pending = work[i:] + self._pending
                raise
            except Fenced:
                # deposed mid-flush: the new leader owns eviction policy.
                # Drop the WHOLE backlog (replaying it under a newer
                # epoch would launder stale decisions) and ungate every
                # affected preemptor so the new leader's informer events
                # — or their own retries — can pick them back up.
                self._note_fenced("delete_pod")
                for _cand, p in work[i:]:
                    self.preempting.discard(p.metadata.uid)
                    stranded.append(p)
                self._pending = []
                return

    def _expand_gang_victims(self, victims: list[Pod],
                             preemptor: Pod | None = None,
                             list_pods=None) -> tuple[list[Pod], str]:
        """All-or-nothing eviction: victims carrying a gang label pull in
        every BOUND member of their gang (one hub scan, only when a gang
        victim is actually present; ``list_pods`` lets the flush share a
        single scan across its whole backlog). Returns ``(victims,
        blocked)``: pulled-in co-members bypassed candidate selection, so
        they get their own guard here — one outranking the preemptor or
        violating an exhausted PDB blocks the WHOLE gang eviction
        (partial eviction is never an option)."""
        keys = {k for v in victims
                if LABEL_POD_GROUP in v.metadata.labels
                and (k := pod_group_key(v)) is not None}
        if not keys:
            return victims, ""
        have = {v.metadata.uid for v in victims}
        extra = []
        pods = list_pods() if list_pods is not None else \
            self.hub.list_pods()
        for p in pods:
            if p.metadata.uid in have or not p.spec.node_name:
                continue
            if pod_group_key(p) in keys:
                extra.append(p)
        if extra and preemptor is not None:
            outranking = [p for p in extra
                          if p.priority() >= preemptor.priority()]
            if outranking:
                return victims, (f"gang co-member {outranking[0].key()} "
                                 "outranks the preemptor")
            try:
                pdbs = self.hub.list_pdbs()
            except Unavailable:
                raise
            # the original victims evict in the same flush, so they draw
            # the PDB budgets down first — a co-member is only safe
            # against what remains, not against a fresh budget
            flags = self._pdb_violation_flags(victims + extra,
                                              pdbs)[len(victims):]
            if any(flags):
                protected = extra[flags.index(True)]
                return victims, (f"gang co-member {protected.key()} is "
                                 "protected by an exhausted PDB")
        return victims + extra, ""

    def _reprieve_by_resources(self, victims: list[Pod], pod: Pod,
                               row: int, free_mat: np.ndarray) -> list[Pod]:
        """The reference's reprieve pass, host-side: walk the victim set
        most-important-first (oldest first at equal priority) and re-add
        any victim whose eviction is NOT needed for the preemptor's
        resource fit (default_preemption.go:219's re-add loop). Pure
        arithmetic — the kmin prefix can contain useless small victims
        (e.g. freshly-bound tiny pods sorted youngest-first) that must
        never be evicted. ``free_mat`` is one hoisted free_matrix() copy
        per failure batch. The effective free mirrors the sweep's fit
        base: nominated reservations subtracted, the pod's OWN nomination
        handed back."""
        mirror = self._get_mirror()
        free = np.asarray(free_mat[row], np.float32)
        req = np.asarray(self._res_row_cached(pod), np.float32)
        nom = getattr(mirror, "_nominated_req_of_row", {}).get(row)
        if nom is not None:
            free = free - np.asarray(nom, np.float32)
        if pod.status.nominated_node_name \
                and mirror.row_of(pod.status.nominated_node_name) == row:
            free = free + req
        needed = np.maximum(req - free, 0.0)
        freed = np.zeros_like(req)
        rows = {}
        for v in victims:
            rows[v.metadata.uid] = self._res_row_cached(v, freed=True)
            freed = freed + rows[v.metadata.uid]
        kept: list[Pod] = list(victims)
        # most important first: priority desc, oldest first
        for v in sorted(victims,
                        key=lambda q: (-q.priority(),
                                       q.metadata.creation_timestamp)):
            if len(kept) <= 1:
                break
            trial = freed - rows[v.metadata.uid]
            if np.all(trial >= needed):
                freed = trial
                kept.remove(v)
        return kept

    def _collect_victims(self, prio: int, snapshot, mirror, caps):
        """(victims_by_row, k_cap, device cumsum [N, K+1, C], device
        vic_cols [C], host cumsum, host cols) for preemptors of ``prio``,
        or None when nothing is evictable. The trailing host pair backs
        full-width freed-vector expansion (find_candidates' dry-run).

        Per-node victims sort ascending by importance (evict
        least-important first): priority asc, then start time desc.
        Nodes with no victims are skipped: the sweep only selects rows
        with 1 <= kmin <= len(victims), and an empty row can never win.

        INCREMENTAL across bursts: per-row victim lists and cumsum rows
        are keyed on each NodeInfo's generation, so a burst 200ms after
        the last one recomputes only the rows commits touched (~2-4% at
        the PreemptionAsync shape) and row-scatters them into the
        device-resident cumsum — the full 20k-victim rebuild per burst
        was the dominant preemption host cost. The cumsum carries only
        the columns victims actually free (see ops.preempt.preempt_sweep)
        — the full [N, K+1, R] upload was the dominant per-burst cost on
        the tunnel."""
        st = self._vic_state.get(prio)
        if (st is not None and st["mirror"] is mirror
                and st["n"] == caps.nodes):
            upd = self._update_victims(st, prio, snapshot, mirror)
            if upd is not _REBUILD:
                return upd
        return self._rebuild_victims(prio, snapshot, mirror, caps)

    def _res_row_of(self, pi) -> np.ndarray:
        """Victim freed-amount row (floored — it adds back to capacity),
        via the (uid, freed=True) cache key space."""
        key = (pi.pod.metadata.uid, True)
        rr = self._res_rows.get(key)
        if rr is None:
            rr = np.asarray(self._get_mirror()._res_row(
                pi.request, capacity=True), np.float32)
            self._res_rows[key] = rr
        return rr

    @staticmethod
    def _victim_sort_key(pi):
        return (pi.pod.priority(), -pi.pod.metadata.creation_timestamp)

    def _state_tuple(self, st):
        if not st["victims_by_row"]:
            return None
        return (st["victims_by_row"], st["k_cap"], st["cumsum_dev"],
                st["vic_cols_dev"], st["cumsum_host"], st["cols_np"])

    def _rebuild_victims(self, prio: int, snapshot, mirror, caps):
        victims_by_row = {}
        row_gen: dict[int, int] = {}
        k_max = 0
        for info in snapshot.node_info_list:
            row = mirror.row_of(info.name)
            if row < 0:
                continue
            row_gen[row] = info.generation
            vs = [pi for pi in info.pods if pi.pod.priority() < prio]
            if not vs:
                continue
            vs.sort(key=self._victim_sort_key)
            victims_by_row[row] = vs
            k_max = max(k_max, len(vs))
        if self._res_rows_mirror is not mirror:
            self._res_rows.clear()
            self._res_rows_mirror = mirror
        if len(self._res_rows) > 200_000:
            self._res_rows.clear()
        if k_max == 0:
            st = {"mirror": mirror, "n": caps.nodes, "row_gen": row_gen,
                  "victims_by_row": {}, "k_cap": 0, "cols": (),
                  "cols_np": None, "pods_pos": 0, "c_pad": 0,
                  "incols_mask": None, "cumsum_host": None,
                  "cumsum_dev": None, "vic_cols_dev": None}
            self._save_vic_state(prio, st)
            return None
        # k headroom (min 8): commits between bursts add victims per row;
        # a k_cap growth reshapes the cumsum and recompiles the sweep
        # program mid-phase, which the headroom absorbs
        k_cap = 8
        while k_cap < k_max:
            k_cap *= 2
        # cumulative freed request per victim prefix (vectorized: the
        # per-victim python accumulation was the preemption hot spot at
        # 20k victims — one np.cumsum per node + a uid-keyed res-row cache)
        n = caps.nodes
        res_rows = self._res_rows
        # one flat [V_total, R] stack of every victim's res row, in
        # (node, victim-rank) order — the cumsum/scatter below is fully
        # vectorized (the per-row numpy loop was ~40% of burst host time
        # at 5k nodes)
        flat_rows: list[np.ndarray] = []
        row_ids = np.empty((len(victims_by_row),), np.int64)
        k_arr = np.empty((len(victims_by_row),), np.int64)
        for i, (row, vs) in enumerate(victims_by_row.items()):
            row_ids[i] = row
            k_arr[i] = len(vs)
            for pi in vs:
                key = (pi.pod.metadata.uid, True)
                rr = res_rows.get(key)
                if rr is None:
                    rr = np.asarray(mirror._res_row(
                        pi.request, capacity=True), np.float32)
                    res_rows[key] = rr
                flat_rows.append(rr)
        stacked_all = np.stack(flat_rows)                     # [V, R]
        active = set(np.nonzero(stacked_all.any(axis=0))[0].tolist())
        active.add(int(F.COL_PODS))
        cols = sorted(active)
        c_pad = 4
        while c_pad < len(cols):
            c_pad *= 2
        pods_pos = cols.index(int(F.COL_PODS))
        cols_np = np.asarray(cols, np.int64)
        # float64 accumulation: the GLOBAL running total over ~20k victims
        # exceeds float32's 2^24 integer-exact range (MiB-scale rows), and
        # cs[take] - base would cancel catastrophically, flipping boundary
        # fit decisions in the sweep; per-node differences cast back to
        # f32 exactly (they're node-local sums, far below 2^24)
        cs = np.cumsum(stacked_all[:, cols_np], axis=0,
                       dtype=np.float64)                      # [V, C]
        offsets = np.concatenate(([0], np.cumsum(k_arr)))[:-1]
        base = np.where((offsets > 0)[:, None],
                        cs[np.maximum(offsets - 1, 0)], 0.0)  # [NR, C]
        j = np.arange(1, k_cap + 1)
        # prefix j clamps to the row's victim count: padding prefixes
        # repeat the full-eviction sum ("no extras")
        jk = np.minimum(j[None, :], k_arr[:, None])           # [NR, K]
        take = offsets[:, None] + jk - 1
        vals = (cs[take] - base[:, None, :]).astype(np.float32)
        vals[..., pods_pos] = jk
        cumsum = np.zeros((n, k_cap + 1, c_pad), np.float32)
        # padding columns alias col 0 in vic_cols; +BIG so they never bind
        cumsum[:, :, len(cols):] = 3.0e38
        cumsum[row_ids, 1:, : len(cols)] = vals
        # padding entries MUST alias an ACTIVE column (cols[0]), never a
        # blanket column 0: aliasing an inactive column would add it to the
        # kernel's col_freed mask (dropping it from the base-only check)
        # while the +BIG padding cumsum makes the subset check vacuous for
        # it — silently deleting that resource constraint from the sweep
        vic_cols = np.full((c_pad,), cols_np[0], np.int32)
        vic_cols[: len(cols)] = cols_np
        incols_mask = np.zeros((stacked_all.shape[1],), bool)
        incols_mask[cols_np] = True
        st = {"mirror": mirror, "n": n, "row_gen": row_gen,
              "victims_by_row": victims_by_row, "k_cap": k_cap,
              "cols": tuple(cols), "cols_np": cols_np,
              "pods_pos": pods_pos, "c_pad": c_pad,
              "incols_mask": incols_mask,
              # host copy rides along for full-width freed-vector
              # expansion (find_candidates' dry-run path)
              "cumsum_host": cumsum,
              "cumsum_dev": jnp.asarray(cumsum),
              "vic_cols_dev": jnp.asarray(vic_cols)}
        self._save_vic_state(prio, st)
        return self._state_tuple(st)

    def _save_vic_state(self, prio: int, st: dict) -> None:
        self._vic_state[prio] = st
        while len(self._vic_state) > 4:     # bound distinct-priority states
            self._vic_state.pop(next(iter(self._vic_state)))

    def _update_victims(self, st: dict, prio: int, snapshot, mirror):
        """Refresh only rows whose NodeInfo generation moved; row-scatter
        their cumsum slices into the device-resident buffer. Returns the
        state tuple (or None when nothing is evictable), or _REBUILD when
        the static shape no longer fits (k_cap overflow, a new active
        resource column, node set shrank)."""
        row_gen = st["row_gen"]
        vbr = st["victims_by_row"]
        k_cap = st["k_cap"]
        dirty: list[int] = []
        seen = 0
        for info in snapshot.node_info_list:
            row = mirror.row_of(info.name)
            if row < 0:
                continue
            seen += 1
            g = info.generation
            if row_gen.get(row) == g:
                continue
            vs = [pi for pi in info.pods if pi.pod.priority() < prio]
            if len(vs) > k_cap:
                return _REBUILD
            row_gen[row] = g
            vs.sort(key=self._victim_sort_key)
            if vs:
                vbr[row] = vs
            else:
                vbr.pop(row, None)
            dirty.append(row)
        if seen != len(row_gen):
            # nodes left the snapshot: stale rows would keep serving
            # cumsum entries — rare enough that a rebuild is fine
            return _REBUILD
        if not dirty:
            return self._state_tuple(st)
        if st["cumsum_host"] is None:
            # state was the "nothing evictable" marker; first victims
            # appeared -> allocate via a rebuild
            return _REBUILD
        cols_np, pods_pos = st["cols_np"], st["pods_pos"]
        c_pad, incols = st["c_pad"], st["incols_mask"]
        n_cols = len(cols_np)
        block = np.zeros((len(dirty), k_cap + 1, c_pad), np.float32)
        block[:, :, n_cols:] = 3.0e38
        # vectorized over ALL dirty rows at once (one flat victim stack +
        # segment prefix-sums) — a per-row python loop here cost 100-250ms
        # after a 2048-pod batch dirtied ~40% of the cluster
        flat: list[np.ndarray] = []
        k_arr = np.zeros((len(dirty),), np.int64)
        for i, row in enumerate(dirty):
            vs = vbr.get(row)
            if not vs:
                continue
            k_arr[i] = len(vs)
            for pi in vs:
                flat.append(self._res_row_of(pi))
        if flat:
            stacked = np.stack(flat)                          # [V, R]
            if stacked[:, ~incols].any():
                return _REBUILD     # a victim frees a column the compiled
                                    # sweep doesn't carry
            # float64 accumulation + per-row rebase: see _rebuild_victims
            cs = np.cumsum(stacked[:, cols_np], axis=0,
                           dtype=np.float64)                  # [V, C]
            offsets = np.concatenate(([0], np.cumsum(k_arr)))[:-1]
            base = np.where((offsets > 0)[:, None],
                            cs[np.maximum(offsets - 1, 0)], 0.0)
            j = np.arange(1, k_cap + 1)
            jk = np.minimum(j[None, :], np.maximum(k_arr, 1)[:, None])
            # clamp: a victimless TRAILING dirty row has offset == V, and
            # its jk floor of 1 would index cs[V] out of bounds; the
            # garbage it reads is overwritten by the k_arr==0 zeroing
            take = np.minimum(offsets[:, None] + jk - 1, len(flat) - 1)
            vals = (cs[take] - base[:, None, :]).astype(np.float32)
            vals[..., pods_pos] = jk
            vals[k_arr == 0] = 0.0      # rows whose victims all vanished
            block[:, 1:, :n_cols] = vals
        st["cumsum_host"][dirty] = block
        # pow2-pad the scatter (idempotent duplicate of the last row) so
        # XLA compiles one kernel per bucket, not per dirty-count
        k = 1
        while k < len(dirty):
            k *= 2
        idx = np.asarray(dirty + [dirty[-1]] * (k - len(dirty)), np.int32)
        st["cumsum_dev"] = _scatter_rows0_jit(
            st["cumsum_dev"], jnp.asarray(idx),
            jnp.asarray(st["cumsum_host"][idx]))
        return self._state_tuple(st)

    def _assemble_candidates(self, pod: Pod, kmin, victims_by_row,
                             snapshot, mirror, free_mat, pdbs,
                             exclude_rows: set | None = None,
                             limit: int | None = None) -> list[Candidate]:
        """kmin rows -> reprieved Candidates, with the reference's
        randomized percentage-bounded sampling (preemption.go:307
        GetOffsetAndNumCandidates). Shared by the single-pod resource_only
        path and batch_preempt so their semantics cannot diverge."""
        rows = [row for row, vs in victims_by_row.items()
                if (exclude_rows is None or row not in exclude_rows)
                and kmin[row] != NONE and 1 <= kmin[row] <= len(vs)]
        if not rows:
            return []
        rows.sort()
        num_nodes = len(snapshot.node_info_list)
        want = max(num_nodes * MIN_CANDIDATE_NODES_PERCENTAGE // 100,
                   MIN_CANDIDATE_NODES_ABSOLUTE)
        if limit is not None:
            want = min(want, limit)
        off = self._rng.randrange(len(rows))
        picked = [rows[(off + i) % len(rows)]
                  for i in range(min(want, len(rows)))]
        out = []
        for row in picked:
            vs = self._reprieve_by_resources(
                [pi.pod for pi in victims_by_row[row][: int(kmin[row])]],
                pod, row, free_mat)
            out.append(Candidate(
                node_name=mirror.name_of_row(row) or "", row=row,
                victims=vs,
                pdb_violations=self._pdb_violations(vs, pdbs)))
        return out

    def begin_batch_preempt(self, jobs, snapshot) -> tuple:
        """Dispatch ONE sweep for a burst of fit-only preemptors of equal
        priority WITHOUT blocking on the device: the kmin results stay
        device-resident until finish_batch_preempt pulls them, so the
        scheduling drain keeps dispatching while the sweep computes
        (the device half of prepareCandidateAsync, kep 4832).

        Returns (handle | None, immediate): ``immediate`` resolves pods
        that never needed a sweep (ineligible, nothing evictable)."""
        self.cache_snapshot = snapshot.node_info_map
        mirror = self._get_mirror()
        caps = self._get_caps()
        immediate: dict[str, tuple] = {}
        eligible = []
        for qp in list(jobs):
            ok, why = self.pod_eligible_to_preempt_others(qp.pod)
            if ok:
                eligible.append(qp)
            else:
                immediate[qp.uid] = (None, Status.unschedulable(
                    f"not eligible for preemption: {why}",
                    plugin="DefaultPreemption"))
        if not eligible:
            return None, immediate
        prio = eligible[0].pod.priority()
        prep = self._collect_victims(prio, snapshot, mirror, caps)
        if prep is None:
            immediate.update(
                {qp.uid: (None, Status.unschedulable(
                    "no preemption candidates",
                    plugin="DefaultPreemption")) for qp in eligible})
            return None, immediate
        victims_by_row = prep[0]
        return (eligible, victims_by_row, self._vic_state[prio], mirror,
                snapshot), immediate

    def _host_static_ok(self, pod: Pod, node_name: str) -> bool:
        """Host mirror of the device pipeline's commit-invariant filters
        (models.pipeline.static_filters) for one (pod, node): validity,
        NodeName, NodeUnschedulable, TaintToleration, NodeAffinity,
        NodePorts. Evaluated lazily on candidate-window rows only."""
        from kubernetes_tpu.api.labels import (
            find_untolerated_taint,
            pod_matches_node_selector_and_affinity,
        )
        from kubernetes_tpu.api.objects import Taint

        info = self.cache_snapshot.get(node_name)
        if info is None or info.node is None:
            return False
        node = info.node
        if pod.spec.node_name and pod.spec.node_name != node_name:
            return False
        taints = list(node.spec.taints)
        if node.spec.unschedulable:
            # the NodeUnschedulable plugin's simulated taint
            from kubernetes_tpu.backend.mirror import TAINT_UNSCHEDULABLE

            taints.append(Taint(key=TAINT_UNSCHEDULABLE, value="",
                                effect="NoSchedule"))
        if find_untolerated_taint(taints, pod.spec.tolerations) is not None:
            return False
        if not pod_matches_node_selector_and_affinity(pod, node):
            return False
        for c in pod.spec.containers:
            for p in c.ports:
                if p.host_port and info.used_ports.conflicts(
                        p.host_ip or "0.0.0.0", p.protocol or "TCP",
                        p.host_port):
                    return False
        return True

    def _host_kmin(self, pod: Pod, st: dict, mirror, free_mat: np.ndarray
                   ) -> np.ndarray:
        """[N] i32 minimal victim-prefix making ``pod`` fit per node,
        NONE where eviction cannot help — the HOST evaluation of
        ops.preempt.preempt_sweep's resource half over the incremental
        cumsum. Runs in ~2ms of numpy: a device sweep here would queue
        behind the drain's in-flight launches and cost 100-1000ms of
        wall per burst (measured), pure numpy never touches the device.
        Static filters are NOT folded in — the caller checks them lazily
        on visited window rows via _host_static_ok."""
        cumsum = st["cumsum_host"]                    # [N, K+1, C_pad]
        cols_np = st["cols_np"]
        n_cols = len(cols_np)
        base = free_mat.copy()
        nom = getattr(mirror, "_nominated_req_of_row", {})
        for row, vec in nom.items():
            base[row] = base[row] - vec
        req = self._res_row_cached(pod)
        nnn = pod.status.nominated_node_name
        if nnn:
            own = mirror.row_of(nnn)
            if own >= 0:
                base[own] = base[own] + req
        # allocatable bound: rows where the request can never fit
        off, size = mirror.node_codec._f32_off["allocatable"]
        alloc = mirror.node_f32[:, off:off + size]
        unresolvable = (req[None, :] > alloc).any(axis=1)
        col_freed = np.zeros((base.shape[1],), bool)
        col_freed[cols_np] = True
        ok_rest = np.all((req[None, :] <= base) | col_freed[None, :],
                         axis=1)
        eff = base[:, None, cols_np] + cumsum[:, :, :n_cols]
        fit = ok_rest[:, None] & np.all(req[cols_np][None, None, :] <= eff,
                                        axis=2)      # [N, K+1]
        kmin = fit.argmax(axis=1).astype(np.int32)
        ok = fit.any(axis=1) & ~unresolvable
        return np.where(ok, kmin, np.int32(NONE))

    def finish_batch_preempt(self, handle) -> dict:
        """Assign nodes/victims for a burst, entirely host-side: numpy
        kmin over the incremental cumsum, rotation-sampled candidate
        windows (GetOffsetAndNumCandidates, preemption.go:307), lazy
        static filtering, reprieve. Burst-local row exclusion: two
        preemptors never target the same capacity.
        {uid: (nominated_node | None, Status)}."""
        eligible, victims_by_row, st, mirror, snapshot = handle
        self.cache_snapshot = snapshot.node_info_map
        out: dict[str, tuple] = {}
        free_mat = mirror.free_matrix()
        pdbs = self.hub.list_pdbs()
        used_rows: set[int] = set()
        for qp in eligible:
            kmin = self._host_kmin(qp.pod, st, mirror, free_mat)
            rows = np.nonzero((kmin != NONE) & (kmin >= 1))[0]
            window: list[tuple[int, int]] = []
            if len(rows):
                off = self._rng.randrange(len(rows))
                for i in range(len(rows)):
                    row = int(rows[(off + i) % len(rows)])
                    vs = victims_by_row.get(row)
                    k = int(kmin[row])
                    if (vs is None or row in used_rows or k > len(vs)
                            or not self._host_static_ok(
                                qp.pod, mirror.name_of_row(row) or "")):
                        continue
                    window.append((row, k))
                    if len(window) >= MAX_VERIFY_CANDIDATES:
                        break
            candidates = []
            for row, k in window:
                vs = self._reprieve_by_resources(
                    [pi.pod for pi in victims_by_row[row][:k]],
                    qp.pod, row, free_mat)
                candidates.append(Candidate(
                    node_name=mirror.name_of_row(row) or "", row=row,
                    victims=vs,
                    pdb_violations=self._pdb_violations(vs, pdbs)))
            try:
                candidates = self.call_extenders(qp.pod, candidates)
            except Exception as e:  # noqa: BLE001 — non-ignorable
                # extender failure: abort THIS preemptor's attempt as an
                # error (retried with error backoff), not 'no candidates'
                out[qp.uid] = (None, Status.error(
                    f"preemption extender: {e}",
                    plugin="DefaultPreemption"))
                continue
            if not candidates:
                out[qp.uid] = (None, Status.unschedulable(
                    "no preemption candidates",
                    plugin="DefaultPreemption"))
                continue
            best = self.select_candidate(candidates)
            if self.metrics is not None:
                self.metrics.preemption_attempts.inc()
                self.metrics.preemption_victims.observe(len(best.victims))
            self.prepare_candidate(best, qp.pod)
            self.nominator.add(qp.pod, best.node_name)
            used_rows.add(best.row)
            out[qp.uid] = (best.node_name, Status())
        return out

    def batch_preempt(self, jobs, snapshot) -> dict:
        """Synchronous begin+finish (the pre-async path and tests)."""
        handle, immediate = self.begin_batch_preempt(jobs, snapshot)
        if handle is not None:
            immediate.update(self.finish_batch_preempt(handle))
        return immediate

    # ---------------- the whole PostFilter flow ----------------

    def host_preempt(self, pod: Pod, snapshot) -> tuple[str | None, Status]:
        """Rung-bottom SERIAL preemption (ISSUE 15): pure host-side
        candidate selection + the queued eviction path, for the fallback
        ladder's bottom rung — a fully device-dead scheduler used to PARK
        preemptors (the device sweep was the only candidate source), so
        it could never free capacity. Covers the static-predicate +
        resource-fit subset over the snapshot; topology preemptors stay
        parked for the device retry (the host path cannot evaluate their
        terms). Victim ordering and candidate selection reuse the
        evaluator's exact keys (_victim_sort_key, candidate_key), so
        where both paths apply they pick the same node."""
        from kubernetes_tpu.api.labels import (
            find_untolerated_taint,
            pod_matches_node_selector_and_affinity,
        )
        from kubernetes_tpu.api.resources import pod_request

        self.cache_snapshot = snapshot.node_info_map
        ok, why = self.pod_eligible_to_preempt_others(pod)
        if not ok:
            return None, Status.unschedulable(
                f"not eligible for preemption: {why}",
                plugin="DefaultPreemption")
        req = pod_request(pod)
        prio = pod.priority()
        pdbs = self.hub.list_pdbs()
        candidates: list[Candidate] = []
        for ni in snapshot.node_info_list:
            node = ni.node
            if node is None or node.spec.unschedulable:
                continue
            if not pod_matches_node_selector_and_affinity(pod, node):
                continue
            if find_untolerated_taint(node.spec.taints,
                                      pod.spec.tolerations) is not None:
                continue
            lower = sorted((pi for pi in ni.pods
                            if pi.pod.priority() < prio),
                           key=self._victim_sort_key)
            if not lower:
                continue
            alloc = ni.allocatable
            free_cpu = alloc.milli_cpu - ni.requested.milli_cpu
            free_mem = alloc.memory - ni.requested.memory
            free_eph = (alloc.ephemeral_storage
                        - ni.requested.ephemeral_storage)
            free_scalar = {k: alloc.scalar.get(k, 0)
                           - ni.requested.scalar.get(k, 0)
                           for k in set(alloc.scalar)
                           | set(ni.requested.scalar)
                           | set(req.scalar)}
            victims: list[Pod] = []

            def _fits() -> bool:
                if (alloc.allowed_pod_number > 0
                        and len(ni.pods) - len(victims) + 1
                        > alloc.allowed_pod_number):
                    return False
                return (req.milli_cpu <= free_cpu
                        and req.memory <= free_mem
                        and req.ephemeral_storage <= free_eph
                        and all(v <= free_scalar.get(k, 0)
                                for k, v in req.scalar.items()))

            # minimal prefix, least-important victims first (the resource
            # fixed point of remove-all-then-reprieve)
            for pi in lower:
                if _fits():
                    break
                victims.append(pi.pod)
                free_cpu += pi.request.milli_cpu
                free_mem += pi.request.memory
                free_eph += pi.request.ephemeral_storage
                for k, v in pi.request.scalar.items():
                    free_scalar[k] = free_scalar.get(k, 0) + v
            if not _fits():
                continue
            if not victims:
                continue        # fits with no eviction: not a preemption
            candidates.append(Candidate(
                node_name=ni.name, row=-1, victims=victims,
                pdb_violations=self._pdb_violations(victims, pdbs)))
        best = self.select_candidate(candidates)
        if best is None:
            return None, Status.unschedulable(
                "no preemption candidates (host mini-path)",
                plugin="DefaultPreemption")
        if self.metrics is not None:
            self.metrics.preemption_attempts.inc()
            self.metrics.preemption_victims.observe(len(best.victims))
        self.prepare_candidate(best, pod)
        self.nominator.add(pod, best.node_name)
        return best.node_name, Status()

    def preempt(self, pod: Pod, snapshot,
                reject_counts=None,
                host_rejects=None) -> tuple[str | None, Status]:
        self.cache_snapshot = snapshot.node_info_map
        ok, why = self.pod_eligible_to_preempt_others(pod)
        if not ok:
            return None, Status.unschedulable(
                f"not eligible for preemption: {why}",
                plugin="DefaultPreemption")
        # fit-only rejection => the resource sweep alone is exact
        from kubernetes_tpu.models.pipeline import FILTER_PLUGINS

        fit_idx = FILTER_PLUGINS.index("NodeResourcesFit")
        resource_only = (
            reject_counts is not None and not host_rejects
            and all(c == 0 for i, c in enumerate(reject_counts)
                    if i != fit_idx))
        candidates = self.find_candidates(pod, snapshot,
                                          resource_only=resource_only)
        pdbs = self.hub.list_pdbs()
        extenders = self.extenders_fn() if self.extenders_fn else []
        has_preempt_ext = any(
            ext.supports_preemption and ext.is_interested(pod)
            for ext in extenders)
        if has_preempt_ext and not resource_only:
            # the reference runs callExtenders AFTER the dry-run's
            # reprieve (preemption.go:335): minimize candidates first so
            # extenders see — and freeze — MINIMAL victim lists. Bounded
            # to MAX_VERIFY_CANDIDATES best-first (the selection order),
            # not positionally: minimization costs device launches, and
            # find_candidates can return one candidate per feasible row
            candidates = sorted(
                candidates,
                key=Evaluator.candidate_key)[:MAX_VERIFY_CANDIDATES]
            candidates = [m for c in candidates
                          if (m := self._minimize_victims(pod, c,
                                                          pdbs)) is not None]
        try:
            candidates = self.call_extenders(pod, candidates)
        except Exception as e:  # noqa: BLE001 — non-ignorable extender
            return None, Status.error(f"preemption extender: {e}",
                                      plugin="DefaultPreemption")
        for _ in range(min(len(candidates), MAX_VERIFY_CANDIDATES)):
            best = self.select_candidate(candidates)
            if best is None:
                break
            if resource_only or best.victims_final:
                final = best        # sweep-exact / extender-final lists:
                                    # already verified (minimized above or
                                    # resource-checked in call_extenders)
            else:
                final = self._minimize_victims(pod, best, pdbs)
            if final is not None:
                if self.metrics is not None:
                    self.metrics.preemption_attempts.inc()
                    self.metrics.preemption_victims.observe(
                        len(final.victims))
                self.prepare_candidate(final, pod)
                self.nominator.add(pod, final.node_name)
                return final.node_name, Status()
            candidates = [c for c in candidates if c is not best]
        return None, Status.unschedulable(
            "no preemption candidates", plugin="DefaultPreemption")


class DefaultPreemption(PostFilterPlugin, PreEnqueuePlugin):
    """PostFilter plugin wrapper (default_preemption.go:133) + the
    PreEnqueue gate (:146): while a pod's async preemption is in flight it
    must not re-enter the activeQ — it would just fail again against a
    node whose victims haven't finished going away."""

    NAME = "DefaultPreemption"

    def __init__(self, evaluator: Evaluator):
        self.evaluator = evaluator

    def name(self) -> str:
        return self.NAME

    def pre_enqueue(self, pod: Pod) -> Status:
        if pod.metadata.uid in self.evaluator.preempting:
            return Status.unschedulable(
                "waiting for the preemption for this pod to be finished",
                plugin=self.NAME, resolvable=False)
        return Status()

    def post_filter(self, state, pod: Pod, diagnosis
                    ) -> tuple[str | None, Status]:
        snapshot = diagnosis.get("snapshot") if diagnosis else None
        if snapshot is None:
            return None, Status.unschedulable("no snapshot in diagnosis",
                                              plugin=self.NAME)
        return self.evaluator.preempt(
            pod, snapshot,
            reject_counts=diagnosis.get("reject_counts"),
            host_rejects=diagnosis.get("host_rejects"))
