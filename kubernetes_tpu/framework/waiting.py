"""waitingPodsMap: pods parked by a Permit plugin returning WAIT.

Equivalent of /root/reference/pkg/scheduler/framework/runtime/
waiting_pods_map.go: a WAIT-ing pod keeps its reservation (it stays
assumed in the cache) until every waiting plugin allows it, one rejects
it, or its timeout passes. Permit plugins reach running waiting pods via
Framework.waiting_pods to Allow/Reject them (interface.go:684).
"""

from __future__ import annotations

import threading
from typing import Optional

from kubernetes_tpu.framework.interface import Status

# a plugin returning WAIT with timeout 0 gets the max (runtime/
# waiting_pods_map.go:58 maxTimeout = 15 minutes)
MAX_PERMIT_TIMEOUT = 15 * 60.0


class WaitingPod:
    """waitingPod (waiting_pods_map.go:50): one pod + its pending plugins
    and the earliest hard deadline."""

    def __init__(self, qp, node_name: str, state,
                 plugin_timeouts: dict[str, float], now: float):
        self.qp = qp
        self.node_name = node_name
        self.state = state
        # per-plugin hard deadlines (the reference arms one AfterFunc timer
        # per WAIT plugin): the pod is rejected when ANY pending plugin's
        # timer fires, so the effective deadline is the EARLIEST one still
        # pending - and it relaxes as plugins allow
        self.deadlines: dict[str, float] = {
            name: now + (t if t > 0 else MAX_PERMIT_TIMEOUT)
            for name, t in plugin_timeouts.items()}
        self.pending: set[str] = set(plugin_timeouts)
        self.rejected: Optional[Status] = None

    @property
    def uid(self) -> str:
        return self.qp.uid

    def deadline_info(self) -> tuple[float, str]:
        # (earliest pending deadline, its plugin)
        if not self.pending:
            return float("inf"), ""
        plugin = min(self.pending, key=lambda p: self.deadlines[p])
        return self.deadlines[plugin], plugin

    def allow(self, plugin: str) -> None:
        self.pending.discard(plugin)

    def reject(self, plugin: str, msg: str) -> None:
        self.rejected = Status.unschedulable(
            f"rejected while waiting at permit: {msg}", plugin=plugin)

    def is_allowed(self) -> bool:
        return not self.pending and self.rejected is None


class WaitingPodsMap:
    """Thread-safe uid -> WaitingPod registry + ready/expired harvesting."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._pods: dict[str, WaitingPod] = {}

    def add(self, wp: WaitingPod) -> None:
        with self._lock:
            self._pods[wp.uid] = wp

    def get(self, uid: str) -> Optional[WaitingPod]:
        with self._lock:
            return self._pods.get(uid)

    def remove(self, uid: str) -> Optional[WaitingPod]:
        with self._lock:
            return self._pods.pop(uid, None)

    def __len__(self) -> int:
        return len(self._pods)

    def iterate(self):
        with self._lock:
            return list(self._pods.values())

    def harvest(self, now: float) -> tuple[list[WaitingPod],
                                           list[tuple[WaitingPod, Status]]]:
        """(allowed pods ready to bind, rejected/timed-out pods with their
        status); both sets leave the map."""
        ready: list[WaitingPod] = []
        failed: list[tuple[WaitingPod, Status]] = []
        with self._lock:
            for uid in list(self._pods):
                wp = self._pods[uid]
                if wp.rejected is not None:
                    failed.append((wp, wp.rejected))
                    del self._pods[uid]
                elif wp.is_allowed():
                    ready.append(wp)
                    del self._pods[uid]
                else:
                    deadline, plugin = wp.deadline_info()
                    if now >= deadline:
                        failed.append((wp, Status.unschedulable(
                            "timed out waiting at permit",
                            plugin=plugin or "Permit")))
                        del self._pods[uid]
        return ready, failed
