"""HTTP transport for the Hub: the apiserver side of the wire.

Serves a Hub over real HTTP so a scheduler in another process/host talks
LIST+WATCH exactly like the reference's client-go does to its apiserver
(SURVEY.md §5.8):

* ``POST /call`` — RPC for every public Hub method (the typed REST
  verbs; Conflict/NotFound map to 409/404 like the apiserver's status
  codes).
* ``GET /watch?kind=pods&replay=1`` — chunked event stream (the WATCH
  verb): with replay, the current objects arrive as synthetic adds
  under the hub lock (a consistent LIST) followed by a
  ``{"synced": true, "rv": N}`` marker (WaitForCacheSync's signal, N =
  the global revision the stream is consistent at), then live events for
  the life of the connection. Every event carries its journal revision
  (``"rv"``) so clients can track their resume point.
* ``GET /watch?kind=pods&since_rv=N`` — watch-RESUME: instead of a full
  LIST, journal events after revision N replay (then the sync marker,
  then live events). When the gap has been compacted away the server
  answers **410** ``{"error": "RvTooOld"}`` — the apiserver's "too old
  resource version" — and the client falls back to a relist.
* ``GET /watch?kinds=pods,nodes`` — MULTIPLEXED watch: one connection
  carries several kinds' streams, each event tagged with its ``kind``.
  One relay (or reflector bundle) holds one upstream socket instead of
  one per kind; ``since_rv`` applies to every kind at once because the
  revision space is global.

Wire codec (fabric.codec): the client may offer the compact binary
codec — ``X-KTPU-Codec: bin1;fp=<registry fingerprint>`` on /call,
``codec=bin1&fp=<fp>`` on /watch. The server answers in binary (and
says so: response header / ``application/x-ktpu-frames`` content type)
ONLY on an exact fingerprint match and when the codec is enabled
(``HubServer(codecs=...)``); anything else falls back to the
self-describing JSON wire, so old clients, JSON-only servers, and
JSON-era middleboxes (the chaos proxy strips the offer) all keep
working. A binary /call body against a fingerprint-mismatched server
answers 400 ``CodecMismatch`` and the client re-pins JSON.

The in-process Hub stays the fast path for benchmarks; this transport
exists so "real list/watch client" is an actual network boundary, not an
interface comment.
"""

from __future__ import annotations

import json
import queue
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from kubernetes_tpu.fabric import codec as binwire
from kubernetes_tpu.hub import (
    Conflict,
    EventHandlers,
    Hub,
    NotFound,
    RvTooOld,
)
from kubernetes_tpu.utils.wire import from_wire, to_wire

# Hub methods reachable over /call (everything the scheduler, tests, and
# controllers use; watch_* goes over /watch instead)
CALL_METHODS = frozenset({
    "create_node", "update_node", "delete_node", "get_node", "list_nodes",
    "create_pod", "update_pod", "delete_pod", "delete_pods", "get_pod",
    "list_pods",
    "bind", "patch_pod_condition", "clear_nominated_node",
    "create_namespace", "update_namespace", "delete_namespace",
    "list_namespaces",
    "create_pdb", "update_pdb", "delete_pdb", "list_pdbs",
    "create_pvc", "update_pvc", "delete_pvc", "get_pvc", "list_pvcs",
    "create_pv", "update_pv", "delete_pv", "get_pv", "list_pvs",
    "create_storage_class", "get_storage_class",
    "create_resource_claim", "update_resource_claim",
    "delete_resource_claim", "get_resource_claim", "list_resource_claims",
    "create_resource_slice", "delete_resource_slice",
    "list_resource_slices",
    "create_resource_claim_template", "get_resource_claim_template",
    "create_device_class", "get_device_class", "list_device_classes",
    "create_csi_capacity", "update_csi_capacity", "list_csi_capacities",
    "set_pod_claim_statuses",
    "create_pod_group", "update_pod_group", "delete_pod_group",
    "get_pod_group", "list_pod_groups",
    "create_priority_class", "list_priority_classes",
    "record_event", "list_events",
    "get_journal_stats",
    "shard_map",
    "list_changes",
    "leases.get", "leases.update",
    # fabric (out-of-process control plane): fencing reads, the shared
    # revision allocator, the shard/relay/router registries + ring map
    # on the state shard, and the ring-rebalance segment verbs on shard
    # processes (fabric.cluster)
    "leases.epoch_of",
    "rv.next", "rv.advance_to", "rv.last",
    "fabric_register_shard", "fabric_register_relay",
    "fabric_register_router", "fabric_topology", "fabric_shards",
    "fabric_ring", "fabric_set_ring",
    # scheduler scale-out: replica registry + pending-pod slice ring
    # (the crc32 ring's second consumer)
    "fabric_register_scheduler", "fabric_unregister_scheduler",
    "fabric_schedulers", "fabric_sched_ring", "fabric_set_sched_ring",
    "export_segment", "import_segment", "drop_segment",
    "abort_export", "reconcile_ring",
    "rebalance_segment",
    # replicated state core (fabric.replica): the Raft-lite RPCs plus
    # the status verb clients use for leader discovery
    "replica_append_entries", "replica_request_vote",
    "fabric_replica_status",
})

WATCH_KINDS = ("pods", "nodes", "namespaces", "pvcs", "pvs",
               "resource_claims", "resource_slices",
               "resource_claim_templates", "csi_capacities",
               "pod_groups")

_ERROR_STATUS = {"Conflict": 409, "NotFound": 404, "ValueError": 400,
                 "TypeError": 400, "Fenced": 403, "CodecMismatch": 400,
                 # the router's verdict when a shard process is down
                 # mid-restart: 503 is the retryable gateway answer —
                 # idempotent reads retry through it, writes surface
                 # Unavailable to the caller's own reconciliation
                 "Unavailable": 503,
                 # replica-set redirects (421 Misdirected Request): the
                 # caller re-resolves the leader instead of erroring —
                 # deliberately NOT in the client's retryable-HTTP set,
                 # so the typed verdict (with its leader hint) surfaces
                 "NotLeader": 421,
                 # a pod write routed on a stale ring epoch: the caller
                 # re-reads the ring and retries the current owner
                 "StaleRing": 409,
                 # flow control (fabric.flowcontrol): the caller's
                 # priority level is past its concurrency + queue
                 # bounds — Retry-After rides the response header AND
                 # the message (surviving the {error, message}
                 # envelope); idempotent verbs retry with the hint,
                 # writes surface the typed verdict
                 "TooManyRequests": 429}

FRAMES_CONTENT_TYPE = "application/x-ktpu-frames"


class WatchParams:
    """Parsed /watch query: shared by the hub's handler, the relay's,
    and the fabric router's so the servers cannot drift apart on the
    wire. ``cursors`` is the PER-SHARD resume map (``cursors=
    pods-0:95,pods-1:101``): shard streams through the router are
    rv-ordered per shard but not across shards, so a single max-rv
    resume point could silently skip a slower shard's events — the
    composite cursor resumes every shard at exactly what this client
    saw from it. A single hub ignores it (one shard, one cursor)."""

    __slots__ = ("kinds", "mux", "replay", "since_rv", "use_bin",
                 "cursors")

    def __init__(self, kinds, mux, replay, since_rv, use_bin,
                 cursors=None):
        self.kinds = kinds
        self.mux = mux
        self.replay = replay
        self.since_rv = since_rv
        self.use_bin = use_bin
        self.cursors = cursors


def format_cursors(cursors: dict) -> str:
    """{shard: rv} -> the wire's ``cursors=`` value."""
    return ",".join(f"{s}:{r}" for s, r in sorted(cursors.items()))


def parse_watch_query(q: dict, codecs=(binwire.CODEC_BINARY,
                                       binwire.CODEC_JSON)):
    """parse_qs dict -> (WatchParams, None) or (None, error message).
    ``kinds=a,b`` selects the multiplexed wire (events kind-tagged);
    binary framing applies only when offered AND the registry
    fingerprints match AND the server speaks it."""
    kinds_raw = q.get("kinds", [""])[0]
    if kinds_raw:
        kinds = [k for k in kinds_raw.split(",") if k]
        mux = True
    else:
        kinds = [q.get("kind", [""])[0]]
        mux = False
    for kind in kinds:
        if kind not in WATCH_KINDS:
            return None, f"unknown watch kind {kind!r}"
    since_raw = q.get("since_rv", [""])[0]
    try:
        since_rv = int(since_raw) if since_raw else None
    except ValueError:
        return None, f"bad since_rv {since_raw!r}"
    cursors_raw = q.get("cursors", [""])[0]
    cursors = None
    if cursors_raw:
        cursors = {}
        for part in cursors_raw.split(","):
            shard, sep, rv = part.partition(":")
            if not sep or not shard:
                return None, f"bad cursors entry {part!r}"
            try:
                cursors[shard] = int(rv)
            except ValueError:
                return None, f"bad cursors entry {part!r}"
    use_bin = (binwire.CODEC_BINARY in codecs
               and q.get("codec", [""])[0] == binwire.CODEC_BINARY
               and q.get("fp", [""])[0]
               == binwire.registry_fingerprint())
    return WatchParams(kinds, mux, q.get("replay", ["1"])[0] == "1",
                       since_rv, use_bin, cursors), None


def make_stream_writers(wfile, use_bin: bool, mux: bool):
    """-> (write_obj, write_event): the chunked watch-stream writers,
    one implementation for every server speaking this wire (hub and
    relay). ``write_obj`` emits markers/keepalives; ``write_event``
    takes (kind, type, rv, old, new[, trace]) with RAW objects and
    serializes per the stream's codec; ``trace`` (the commit's
    TraceContext) rides inside the event body on BOTH codecs, so a
    JSON-era middlebox re-chunking the stream passes it through."""
    def write_chunk(blob: bytes) -> None:
        wfile.write(f"{len(blob):x}\r\n".encode() + blob + b"\r\n")
        wfile.flush()

    def write_obj(obj: dict) -> None:
        if use_bin:
            write_chunk(binwire.frame(binwire.encode(obj)))
        else:
            write_chunk(json.dumps(obj).encode() + b"\n")

    def write_event(kind: str, etype: str, rv: int, old, new,
                    trace=None, shard=None) -> None:
        d = {"type": etype, "rv": rv}
        if mux:
            d["kind"] = kind
        if shard is not None:
            # source-shard tag: the fabric router/relay stamp it so
            # clients can keep per-shard resume cursors
            d["sh"] = shard
        if use_bin:
            d["old"], d["new"] = old, new
            if trace is not None:
                d["trace"] = trace
            write_chunk(binwire.frame(binwire.encode(d)))
        else:
            d["old"], d["new"] = to_wire(old), to_wire(new)
            if trace is not None:
                d["trace"] = to_wire(trace)
            write_chunk(json.dumps(d).encode() + b"\n")

    return write_obj, write_event


class CodecMismatch(Exception):
    """A binary /call body arrived but the registry fingerprints (or
    enabled codecs) disagree: the positional struct layout cannot be
    trusted. The client re-pins JSON on this verdict."""


def _parse_codec_header(value: str | None) -> tuple[str, bool]:
    """-> (body_codec, offered_binary). ``X-KTPU-Codec: bin1;fp=X`` is a
    binary body; ``json;accept=bin1;fp=X`` is a JSON body whose sender
    can READ binary (the probe). Either form offers binary only when
    the fingerprint matches ours exactly."""
    if not value:
        return "json", False
    parts = [p.strip() for p in value.split(";")]
    body = parts[0] if parts[0] in (binwire.CODEC_BINARY,
                                    binwire.CODEC_JSON) else "json"
    fp = next((p[3:] for p in parts[1:] if p.startswith("fp=")), None)
    accept = body == binwire.CODEC_BINARY or any(
        p == f"accept={binwire.CODEC_BINARY}" for p in parts[1:])
    return body, accept and fp == binwire.registry_fingerprint()


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "kubernetes-tpu-hub/2"

    def log_message(self, *args) -> None:  # quiet
        pass

    @property
    def hub(self) -> Hub:
        return self.server.hub  # type: ignore[attr-defined]

    @property
    def _bin_enabled(self) -> bool:
        return binwire.CODEC_BINARY in \
            self.server.codecs  # type: ignore[attr-defined]

    def _json(self, status: int, payload: dict,
              headers: dict | None = None) -> None:
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if headers:
            for k, v in headers.items():
                self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _text(self, status: int, body: str) -> None:
        data = body.encode()
        self.send_response(status)
        self.send_header("Content-Type", "text/plain; charset=utf-8")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_POST(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
        if self.path != "/call":
            self._json(404, {"error": "NotFound", "message": self.path})
            return
        length = int(self.headers.get("Content-Length", "0"))
        body_codec, negotiated = _parse_codec_header(
            self.headers.get(binwire.WIRE_HEADER))
        negotiated = negotiated and self._bin_enabled
        try:
            raw = self.rfile.read(length)
            if body_codec == binwire.CODEC_BINARY:
                if not negotiated:
                    raise CodecMismatch(
                        "binary body without a fingerprint match "
                        f"(server fp {binwire.registry_fingerprint()})")
                req = binwire.decode(raw)
                args = list(req.get("args", []))
            else:
                req = json.loads(raw)
                args = [from_wire(a) for a in req.get("args", [])]
            method = req["method"]
            if method not in CALL_METHODS:
                raise ValueError(f"unknown method {method!r}")
            target = self.hub
            for part in method.split("."):
                target = getattr(target, part)
            flow = getattr(self.server, "flow", None)
            if flow is not None:
                # admission AFTER arg decode (classification reads the
                # args' tenant) but AROUND the dispatch, so a queued
                # request holds no hub lock while it waits for a seat
                with flow.admission(method, args,
                                    self.headers.get("X-KTPU-Identity")):
                    result = target(*args)
            else:
                result = target(*args)
        except Exception as e:  # noqa: BLE001 — mapped to wire errors
            name = type(e).__name__
            headers = None
            if name == "TooManyRequests":
                ra = getattr(e, "retry_after", 0.0) or 0.0
                headers = {"Retry-After": f"{ra:.3f}"}
            self._json(_ERROR_STATUS.get(name, 500),
                       {"error": name, "message": str(e)},
                       headers=headers)
            return
        if negotiated:
            out = binwire.encode({"result": result})
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ktpu-bin")
            self.send_header(binwire.WIRE_HEADER, binwire.offer())
            self.send_header("Content-Length", str(len(out)))
            self.end_headers()
            self.wfile.write(out)
        else:
            self._json(200, {"result": to_wire(result)})

    def do_GET(self) -> None:  # noqa: N802
        path = self.path.partition("?")[0]
        if path in ("/healthz", "/livez"):
            # fleet health: every fabric component answers /healthz so
            # the FleetView collector (telemetry.fleet) can probe it.
            # A hub may override the verdict (a state REPLICA answers
            # 200-with-role — a follower is healthy, not degraded).
            hz = getattr(self.hub, "healthz", None)
            if hz is not None:
                code, text = hz()
                self._text(code, text)
            else:
                self._text(200, "ok")
            return
        if path == "/metrics":
            from kubernetes_tpu.telemetry.fleet import (
                hub_metrics_text,
                process_identity_text,
            )

            # identity first: pid + listen port distinguish two shard
            # processes of the same shard name across a restart
            body = process_identity_text(
                getattr(self.hub, "shard_name", "hub"),
                self.server.server_address[1]) \
                + hub_metrics_text(self.hub)
            extra = getattr(self.hub, "extra_metrics_text", None)
            if extra is not None:
                # component-specific gauges (a state replica's
                # role/term/log-index rows) ride the same exposition
                body += extra()
            flow = getattr(self.server, "flow", None)
            if flow is not None:
                # admission-control rows (hub_flow_*) for this server
                body += flow.metrics_text()
            self._text(200, body)
            return
        if not self.path.startswith("/watch"):
            self._json(404, {"error": "NotFound", "message": self.path})
            return
        from urllib.parse import parse_qs, urlparse

        q = parse_qs(urlparse(self.path).query)
        params, err = parse_watch_query(
            q, self.server.codecs)  # type: ignore[attr-defined]
        if params is None:
            self._json(400, {"error": "ValueError", "message": err})
            return
        kinds, mux = params.kinds, params.mux
        replay, since_rv = params.replay, params.since_rv
        use_bin = params.use_bin
        events: queue.Queue = queue.Queue(maxsize=100000)
        overflow = threading.Event()

        def make_push(kind: str):
            def push(ev):
                try:
                    events.put_nowait((kind, ev))
                except queue.Full:
                    # a silent gap would be an undetectable stale cache;
                    # close the stream instead — the client reflector
                    # reconnects, resuming from its last-seen rv (or
                    # relisting when the journal compacted the gap away)
                    overflow.set()
            return push

        # registration under the hub lock makes replay a consistent LIST
        # (or, with since_rv, a consistent journal suffix) PER KIND:
        # replayed events land in the queue before any live event of
        # that kind. A multiplexed registration is kind-by-kind — the
        # informer contract needs per-object (hence per-kind) ordering,
        # not a cross-kind snapshot.
        handlers: list[EventHandlers] = []
        cur_rv = 0
        try:
            for kind in kinds:
                h = EventHandlers(on_event=make_push(kind))
                rv = getattr(self.hub, f"watch_{kind}")(
                    h, replay=replay, since_rv=since_rv)
                handlers.append(h)
                cur_rv = max(cur_rv, rv)
        except RvTooOld as e:
            # the 410-Gone analog: this resume point was compacted away
            for h in handlers:
                self.hub.unwatch(h)
            self._json(410, {"error": "RvTooOld", "message": str(e),
                             "compacted_rv": e.compacted_rv})
            return
        self.send_response(200)
        self.send_header("Content-Type",
                         FRAMES_CONTENT_TYPE if use_bin
                         else "application/jsonlines")
        if use_bin:
            self.send_header(binwire.WIRE_HEADER, binwire.offer())
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        write_obj, write_event = make_stream_writers(self.wfile,
                                                     use_bin, mux)
        try:
            if replay or since_rv is not None:
                # drain the synchronous replay (LIST or journal suffix),
                # then mark sync
                while True:
                    try:
                        kind, ev = events.get_nowait()
                    except queue.Empty:
                        break
                    write_event(kind, ev.type, ev.rv, ev.old, ev.new,
                                ev.trace, ev.shard)
            write_obj({"synced": True, "rv": cur_rv})
            while not self.server.stopping \
                    and not overflow.is_set():  # type: ignore[attr-defined]
                try:
                    kind, ev = events.get(timeout=1.0)
                except queue.Empty:
                    write_obj({})  # keepalive; also detects dead peers
                    continue
                write_event(kind, ev.type, ev.rv, ev.old, ev.new,
                            ev.trace, ev.shard)
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            for h in handlers:
                self.hub.unwatch(h)


class HubServer:
    """hub = Hub(); HubServer(hub).start() -> serve on 127.0.0.1:port.

    ``codecs`` lists the wire codecs this server speaks; dropping
    ``bin1`` makes a JSON-only server (how the negotiation tests model
    an old peer — binary clients must degrade transparently).

    ``flow`` (a :class:`fabric.flowcontrol.FlowController`) bounds
    /call admission per priority level; None (the default) keeps the
    historical unbounded-admission wire."""

    def __init__(self, hub: Hub, host: str = "127.0.0.1", port: int = 0,
                 codecs: tuple[str, ...] = (binwire.CODEC_BINARY,
                                            binwire.CODEC_JSON),
                 flow=None):
        self.hub = hub
        self.flow = flow

        class _Server(ThreadingHTTPServer):
            # a deep accept backlog: overload shedding is the flow
            # controller's job (typed 429 + Retry-After the client can
            # account for), and the stdlib default of 5 turns a client
            # stampede into silent kernel SYN drops — an untyped
            # rejection that surfaces as a 1s connect retransmit
            request_queue_size = 128

        self._httpd = _Server((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.hub = hub                 # type: ignore[attr-defined]
        self._httpd.codecs = codecs           # type: ignore[attr-defined]
        self._httpd.stopping = False          # type: ignore[attr-defined]
        self._httpd.flow = flow               # type: ignore[attr-defined]
        self._thread: threading.Thread | None = None

    @property
    def address(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "HubServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="hub-server")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.stopping = True           # type: ignore[attr-defined]
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
