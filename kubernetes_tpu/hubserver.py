"""HTTP transport for the Hub: the apiserver side of the wire.

Serves a Hub over real HTTP so a scheduler in another process/host talks
LIST+WATCH exactly like the reference's client-go does to its apiserver
(SURVEY.md §5.8):

* ``POST /call`` — JSON-RPC for every public Hub method (the typed REST
  verbs; Conflict/NotFound map to 409/404 like the apiserver's status
  codes).
* ``GET /watch?kind=pods&replay=1`` — chunked JSON-lines event stream
  (the WATCH verb): with replay, the current objects arrive as synthetic
  adds under the hub lock (a consistent LIST) followed by a
  ``{"synced": true, "rv": N}`` marker (WaitForCacheSync's signal, N =
  the global revision the stream is consistent at), then live events for
  the life of the connection. Every event line carries its journal
  revision (``"rv"``) so clients can track their resume point.
* ``GET /watch?kind=pods&since_rv=N`` — watch-RESUME: instead of a full
  LIST, journal events after revision N replay (then the sync marker,
  then live events). When the gap has been compacted away the server
  answers **410** ``{"error": "RvTooOld"}`` — the apiserver's "too old
  resource version" — and the client falls back to a relist.

The in-process Hub stays the fast path for benchmarks; this transport
exists so "real list/watch client" is an actual network boundary, not an
interface comment.
"""

from __future__ import annotations

import json
import queue
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from kubernetes_tpu.hub import (
    Conflict,
    EventHandlers,
    Hub,
    NotFound,
    RvTooOld,
)
from kubernetes_tpu.utils.wire import from_wire, to_wire

# Hub methods reachable over /call (everything the scheduler, tests, and
# controllers use; watch_* goes over /watch instead)
CALL_METHODS = frozenset({
    "create_node", "update_node", "delete_node", "get_node", "list_nodes",
    "create_pod", "update_pod", "delete_pod", "get_pod", "list_pods",
    "bind", "patch_pod_condition", "clear_nominated_node",
    "create_namespace", "update_namespace", "delete_namespace",
    "list_namespaces",
    "create_pdb", "update_pdb", "delete_pdb", "list_pdbs",
    "create_pvc", "update_pvc", "delete_pvc", "get_pvc", "list_pvcs",
    "create_pv", "update_pv", "delete_pv", "get_pv", "list_pvs",
    "create_storage_class", "get_storage_class",
    "create_resource_claim", "update_resource_claim",
    "delete_resource_claim", "get_resource_claim", "list_resource_claims",
    "create_resource_slice", "delete_resource_slice",
    "list_resource_slices",
    "create_resource_claim_template", "get_resource_claim_template",
    "create_device_class", "get_device_class", "list_device_classes",
    "create_csi_capacity", "update_csi_capacity", "list_csi_capacities",
    "set_pod_claim_statuses",
    "create_pod_group", "update_pod_group", "delete_pod_group",
    "get_pod_group", "list_pod_groups",
    "create_priority_class", "list_priority_classes",
    "record_event", "list_events",
    "get_journal_stats",
    "leases.get", "leases.update",
})

WATCH_KINDS = ("pods", "nodes", "namespaces", "pvcs", "pvs",
               "resource_claims", "resource_slices",
               "resource_claim_templates", "csi_capacities",
               "pod_groups")

_ERROR_STATUS = {"Conflict": 409, "NotFound": 404, "ValueError": 400,
                 "TypeError": 400, "Fenced": 403}


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "kubernetes-tpu-hub/1"

    def log_message(self, *args) -> None:  # quiet
        pass

    @property
    def hub(self) -> Hub:
        return self.server.hub  # type: ignore[attr-defined]

    def _json(self, status: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
        if self.path != "/call":
            self._json(404, {"error": "NotFound", "message": self.path})
            return
        length = int(self.headers.get("Content-Length", "0"))
        try:
            req = json.loads(self.rfile.read(length))
            method = req["method"]
            if method not in CALL_METHODS:
                raise ValueError(f"unknown method {method!r}")
            target = self.hub
            for part in method.split("."):
                target = getattr(target, part)
            args = [from_wire(a) for a in req.get("args", [])]
            result = target(*args)
        except Exception as e:  # noqa: BLE001 — mapped to wire errors
            name = type(e).__name__
            self._json(_ERROR_STATUS.get(name, 500),
                       {"error": name, "message": str(e)})
            return
        self._json(200, {"result": to_wire(result)})

    def do_GET(self) -> None:  # noqa: N802
        if not self.path.startswith("/watch"):
            self._json(404, {"error": "NotFound", "message": self.path})
            return
        from urllib.parse import parse_qs, urlparse

        q = parse_qs(urlparse(self.path).query)
        kind = q.get("kind", [""])[0]
        replay = q.get("replay", ["1"])[0] == "1"
        since_raw = q.get("since_rv", [""])[0]
        try:
            since_rv = int(since_raw) if since_raw else None
        except ValueError:
            self._json(400, {"error": "ValueError",
                             "message": f"bad since_rv {since_raw!r}"})
            return
        if kind not in WATCH_KINDS:
            self._json(400, {"error": "ValueError",
                             "message": f"unknown watch kind {kind!r}"})
            return
        events: queue.Queue = queue.Queue(maxsize=100000)
        overflow = threading.Event()

        def push(ev):
            try:
                events.put_nowait({"type": ev.type, "rv": ev.rv,
                                   "old": to_wire(ev.old),
                                   "new": to_wire(ev.new)})
            except queue.Full:
                # a silent gap would be an undetectable stale cache; close
                # the stream instead — the client reflector reconnects,
                # resuming from its last-seen rv (or relisting when the
                # journal has compacted the gap away)
                overflow.set()

        h = EventHandlers(on_event=push)
        # registration under the hub lock makes replay a consistent LIST
        # (or, with since_rv, a consistent journal suffix): replayed
        # events land in the queue before any live event
        try:
            cur_rv = getattr(self.hub, f"watch_{kind}")(
                h, replay=replay, since_rv=since_rv)
        except RvTooOld as e:
            # the 410-Gone analog: this resume point was compacted away
            self._json(410, {"error": "RvTooOld", "message": str(e),
                             "compacted_rv": e.compacted_rv})
            return
        self.send_response(200)
        self.send_header("Content-Type", "application/jsonlines")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        def write_line(obj) -> None:
            line = json.dumps(obj).encode() + b"\n"
            self.wfile.write(f"{len(line):x}\r\n".encode() + line + b"\r\n")
            self.wfile.flush()

        try:
            if replay or since_rv is not None:
                # drain the synchronous replay (LIST or journal suffix),
                # then mark sync
                while True:
                    try:
                        write_line(events.get_nowait())
                    except queue.Empty:
                        break
            write_line({"synced": True, "rv": cur_rv})
            while not self.server.stopping \
                    and not overflow.is_set():  # type: ignore[attr-defined]
                try:
                    ev = events.get(timeout=1.0)
                except queue.Empty:
                    write_line({})  # keepalive; also detects dead peers
                    continue
                write_line(ev)
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            self.hub.unwatch(h)


class HubServer:
    """hub = Hub(); HubServer(hub).start() -> serve on 127.0.0.1:port."""

    def __init__(self, hub: Hub, host: str = "127.0.0.1", port: int = 0):
        self.hub = hub
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.hub = hub                 # type: ignore[attr-defined]
        self._httpd.stopping = False          # type: ignore[attr-defined]
        self._thread: threading.Thread | None = None

    @property
    def address(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "HubServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="hub-server")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.stopping = True           # type: ignore[attr-defined]
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
