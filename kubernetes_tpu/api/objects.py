"""The scheduler-facing object model.

A from-scratch, Python-native equivalent of the slice of ``k8s.io/api/core/v1``
the kube-scheduler consumes (Pod, Node, affinity/taint/spread types) plus
``scheduling.k8s.io/v1`` PriorityClass. Field coverage follows what the
reference scheduler's plugins actually read (see SURVEY.md section 2.4);
reference type definitions live in
/root/reference/staging/src/k8s.io/api/core/v1/types.go.

Objects are plain mutable dataclasses; the hub/cache layers treat stored
objects as immutable and replace them wholesale on update (copy-on-write via
``clone()``), which is what makes the generation-diffed device mirror sound.
"""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field, replace
from typing import Optional

# --- well-known constants -------------------------------------------------

DEFAULT_SCHEDULER_NAME = "default-scheduler"

# taint effects
NO_SCHEDULE = "NoSchedule"
PREFER_NO_SCHEDULE = "PreferNoSchedule"
NO_EXECUTE = "NoExecute"

# selector / toleration operators
OP_IN = "In"
OP_NOT_IN = "NotIn"
OP_EXISTS = "Exists"
OP_DOES_NOT_EXIST = "DoesNotExist"
OP_GT = "Gt"
OP_LT = "Lt"
OP_EQUAL = "Equal"

# topology spread UnsatisfiableConstraintAction
DO_NOT_SCHEDULE = "DoNotSchedule"
SCHEDULE_ANYWAY = "ScheduleAnyway"

# NodeInclusionPolicy
POLICY_HONOR = "Honor"
POLICY_IGNORE = "Ignore"

# well-known topology label keys
LABEL_HOSTNAME = "kubernetes.io/hostname"
LABEL_ZONE = "topology.kubernetes.io/zone"
LABEL_REGION = "topology.kubernetes.io/region"

# pod phases
PHASE_PENDING = "Pending"
PHASE_RUNNING = "Running"
PHASE_SUCCEEDED = "Succeeded"
PHASE_FAILED = "Failed"

# pod condition types
POD_SCHEDULED = "PodScheduled"

_uid_counter = itertools.count(1)


def new_uid() -> str:
    return f"uid-{next(_uid_counter)}"


# --- metadata ---------------------------------------------------------------


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = field(default_factory=new_uid)
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    creation_timestamp: float = field(default_factory=time.time)
    resource_version: int = 0
    deletion_timestamp: Optional[float] = None


# --- label selectors (metav1.LabelSelector) ---------------------------------


@dataclass
class LabelSelectorRequirement:
    key: str
    operator: str  # In / NotIn / Exists / DoesNotExist
    values: list[str] = field(default_factory=list)


@dataclass
class LabelSelector:
    match_labels: dict[str, str] = field(default_factory=dict)
    match_expressions: list[LabelSelectorRequirement] = field(default_factory=list)


# --- node selectors (v1.NodeSelector) ---------------------------------------


@dataclass
class NodeSelectorRequirement:
    key: str
    operator: str  # In / NotIn / Exists / DoesNotExist / Gt / Lt
    values: list[str] = field(default_factory=list)


@dataclass
class NodeSelectorTerm:
    match_expressions: list[NodeSelectorRequirement] = field(default_factory=list)
    match_fields: list[NodeSelectorRequirement] = field(default_factory=list)


@dataclass
class NodeSelector:
    node_selector_terms: list[NodeSelectorTerm] = field(default_factory=list)


@dataclass
class PreferredSchedulingTerm:
    weight: int
    preference: NodeSelectorTerm


@dataclass
class NodeAffinity:
    required: Optional[NodeSelector] = None  # requiredDuringSchedulingIgnoredDuringExecution
    preferred: list[PreferredSchedulingTerm] = field(default_factory=list)


# --- pod (anti)affinity ------------------------------------------------------


@dataclass
class PodAffinityTerm:
    topology_key: str
    label_selector: Optional[LabelSelector] = None
    namespaces: list[str] = field(default_factory=list)
    namespace_selector: Optional[LabelSelector] = None
    match_label_keys: list[str] = field(default_factory=list)
    mismatch_label_keys: list[str] = field(default_factory=list)


@dataclass
class WeightedPodAffinityTerm:
    weight: int
    pod_affinity_term: PodAffinityTerm


@dataclass
class PodAffinity:
    required: list[PodAffinityTerm] = field(default_factory=list)
    preferred: list[WeightedPodAffinityTerm] = field(default_factory=list)


@dataclass
class PodAntiAffinity:
    required: list[PodAffinityTerm] = field(default_factory=list)
    preferred: list[WeightedPodAffinityTerm] = field(default_factory=list)


@dataclass
class Affinity:
    node_affinity: Optional[NodeAffinity] = None
    pod_affinity: Optional[PodAffinity] = None
    pod_anti_affinity: Optional[PodAntiAffinity] = None


# --- taints & tolerations -----------------------------------------------------


@dataclass
class Taint:
    # Field order matches v1.Taint (types.go): Key, Value, Effect.
    key: str
    value: str = ""
    effect: str = ""  # NoSchedule / PreferNoSchedule / NoExecute


@dataclass
class Toleration:
    key: str = ""  # empty + Exists tolerates everything
    operator: str = OP_EQUAL  # Exists / Equal
    value: str = ""
    effect: str = ""  # empty matches all effects
    toleration_seconds: Optional[int] = None

    def tolerates(self, taint: Taint) -> bool:
        """Reference: k8s.io/api/core/v1/toleration.go ToleratesTaint."""
        if self.effect and self.effect != taint.effect:
            return False
        if self.key and self.key != taint.key:
            return False
        if self.operator == OP_EXISTS:
            return True
        # Equal (or empty operator, which defaults to Equal)
        return self.value == taint.value


# --- topology spread ----------------------------------------------------------


@dataclass
class TopologySpreadConstraint:
    max_skew: int
    topology_key: str
    when_unsatisfiable: str  # DoNotSchedule / ScheduleAnyway
    label_selector: Optional[LabelSelector] = None
    min_domains: Optional[int] = None
    node_affinity_policy: str = POLICY_HONOR
    node_taints_policy: str = POLICY_IGNORE
    match_label_keys: list[str] = field(default_factory=list)


# --- containers & resources -----------------------------------------------------


@dataclass
class ResourceRequirements:
    requests: dict[str, str] = field(default_factory=dict)
    limits: dict[str, str] = field(default_factory=dict)


@dataclass
class ContainerPort:
    host_port: int = 0
    container_port: int = 0
    protocol: str = "TCP"
    host_ip: str = ""


@dataclass
class Container:
    name: str = ""
    image: str = ""
    resources: ResourceRequirements = field(default_factory=ResourceRequirements)
    ports: list[ContainerPort] = field(default_factory=list)
    restart_policy: Optional[str] = None  # "Always" on an init container = sidecar


# --- pod ------------------------------------------------------------------------


@dataclass
class PodSchedulingGate:
    name: str


@dataclass
class PodSpec:
    node_name: str = ""
    scheduler_name: str = DEFAULT_SCHEDULER_NAME
    containers: list[Container] = field(default_factory=list)
    init_containers: list[Container] = field(default_factory=list)
    overhead: dict[str, str] = field(default_factory=dict)
    node_selector: dict[str, str] = field(default_factory=dict)
    affinity: Optional[Affinity] = None
    tolerations: list[Toleration] = field(default_factory=list)
    topology_spread_constraints: list[TopologySpreadConstraint] = field(default_factory=list)
    priority: Optional[int] = None
    priority_class_name: str = ""
    preemption_policy: str = "PreemptLowerPriority"  # or "Never"
    scheduling_gates: list[PodSchedulingGate] = field(default_factory=list)
    host_network: bool = False
    volumes: list = field(default_factory=list)
    resource_claims: list = field(default_factory=list)  # PodResourceClaim


@dataclass
class PodCondition:
    type: str
    status: str  # "True" / "False" / "Unknown"
    reason: str = ""
    message: str = ""
    last_transition_time: float = 0.0


@dataclass
class PodStatus:
    phase: str = PHASE_PENDING
    conditions: list[PodCondition] = field(default_factory=list)
    nominated_node_name: str = ""
    # pod.status.resourceClaimStatuses: generated claim name per
    # resourceClaimTemplateName entry (written by the resourceclaim
    # controller, read by the DRA plugin's claim-ref resolution)
    resource_claim_statuses: dict[str, str] = field(default_factory=dict)


@dataclass
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    @property
    def uid(self) -> str:
        return self.metadata.uid

    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    def priority(self) -> int:
        return self.spec.priority if self.spec.priority is not None else 0

    def clone(self) -> "Pod":
        """Copy safe for *assigning* top-level metadata/spec/status fields (the
        only mutations the scheduler performs: nodeName, conditions,
        nominatedNodeName, labels). Deeper structures (containers, affinity,
        tolerations...) are shared and must never be mutated in place.

        Shallow ``copy.copy`` per level instead of dataclasses.replace: the
        clone runs once per commit and per hub write — replace() re-derives
        the field list every call and was the hottest line of the commit
        path."""
        import copy as _copy

        c = _copy.copy(self)
        c.metadata = _copy.copy(self.metadata)
        c.metadata.labels = dict(self.metadata.labels)
        c.spec = _copy.copy(self.spec)
        c.status = _copy.copy(self.status)
        c.status.conditions = list(self.status.conditions)
        # containers/overhead are shared, so the parsed resource-request memo
        # (api.resources.pod_request) stays valid for the copy
        memo = self.__dict__.get("_request_memo")
        if memo is not None:
            c._request_memo = memo
        return c


# --- node -------------------------------------------------------------------------


@dataclass
class ContainerImage:
    names: list[str] = field(default_factory=list)
    size_bytes: int = 0


@dataclass
class NodeSpec:
    unschedulable: bool = False
    taints: list[Taint] = field(default_factory=list)


@dataclass
class NodeStatus:
    capacity: dict[str, str] = field(default_factory=dict)
    allocatable: dict[str, str] = field(default_factory=dict)
    images: list[ContainerImage] = field(default_factory=list)


@dataclass
class Node:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodeSpec = field(default_factory=NodeSpec)
    status: NodeStatus = field(default_factory=NodeStatus)

    @property
    def name(self) -> str:
        return self.metadata.name

    def clone(self) -> "Node":
        """Same contract as Pod.clone(): top-level field assignment only;
        nested structures shared, never mutated in place."""
        return replace(
            self,
            metadata=replace(self.metadata, labels=dict(self.metadata.labels)),
            spec=replace(self.spec, taints=list(self.spec.taints)),
            status=replace(self.status),
        )


# --- namespace -----------------------------------------------------------------------


@dataclass
class Namespace:
    """v1.Namespace (labels are what the scheduler consumes: affinity
    namespaceSelector unrolling, interpodaffinity/plugin.go:123)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)

    @property
    def name(self) -> str:
        return self.metadata.name


# --- pod disruption budget ------------------------------------------------------------


@dataclass
class PodDisruptionBudget:
    """policy/v1 PodDisruptionBudget — the slice preemption consumes:
    selector + status.disruptionsAllowed (preemption.go filterPodsWithPDB
    reads DisruptionsAllowed to rank candidates by violation count)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    selector: Optional[LabelSelector] = None
    min_available: Optional[int] = None
    max_unavailable: Optional[int] = None
    disruptions_allowed: int = 0  # status.disruptionsAllowed


# --- volumes (the slices the volume plugin family consumes) --------------------------


@dataclass
class PersistentVolumeClaimVolumeSource:
    claim_name: str
    read_only: bool = False


@dataclass
class Volume:
    """v1.Volume — the sources the scheduler's volume plugins inspect:
    PVC references (zone/limits/binding) and the directly-attached disk
    types VolumeRestrictions guards (volume_restrictions.go:77-120)."""

    name: str = ""
    persistent_volume_claim: Optional[PersistentVolumeClaimVolumeSource] = None
    gce_pd_name: str = ""        # GCEPersistentDisk.PDName
    aws_ebs_volume_id: str = ""  # AWSElasticBlockStore.VolumeID
    iscsi_iqn: str = ""          # ISCSI.IQN + lun as "iqn:lun"
    rbd_image: str = ""          # RBD "pool:image"
    read_only: bool = False


# access modes (core/types.go)
READ_WRITE_ONCE = "ReadWriteOnce"
READ_ONLY_MANY = "ReadOnlyMany"
READ_WRITE_MANY = "ReadWriteMany"
READ_WRITE_ONCE_POD = "ReadWriteOncePod"


@dataclass
class PersistentVolumeClaimSpec:
    access_modes: list[str] = field(default_factory=list)
    storage_class_name: str = ""
    volume_name: str = ""            # bound PV name ("" = unbound)
    requests: dict[str, str] = field(default_factory=dict)  # {"storage": ...}


@dataclass
class PersistentVolumeClaimStatus:
    phase: str = "Pending"           # Pending / Bound / Lost


@dataclass
class PersistentVolumeClaim:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PersistentVolumeClaimSpec = field(
        default_factory=PersistentVolumeClaimSpec)
    status: PersistentVolumeClaimStatus = field(
        default_factory=PersistentVolumeClaimStatus)

    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    def clone(self) -> "PersistentVolumeClaim":
        import copy

        return copy.deepcopy(self)


@dataclass
class ClaimRef:
    namespace: str = ""
    name: str = ""
    uid: str = ""


@dataclass
class PersistentVolumeSpec:
    capacity: dict[str, str] = field(default_factory=dict)  # {"storage": ..}
    access_modes: list[str] = field(default_factory=list)
    storage_class_name: str = ""
    claim_ref: Optional[ClaimRef] = None
    # volume_binding.go checks PV.Spec.NodeAffinity.Required against node
    node_affinity: Optional["NodeSelector"] = None
    csi_driver: str = ""             # CSI.Driver (NodeVolumeLimits)


@dataclass
class PersistentVolumeStatus:
    phase: str = "Available"         # Available / Bound / Released


@dataclass
class PersistentVolume:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PersistentVolumeSpec = field(default_factory=PersistentVolumeSpec)
    status: PersistentVolumeStatus = field(
        default_factory=PersistentVolumeStatus)

    def clone(self) -> "PersistentVolume":
        import copy

        return copy.deepcopy(self)


VOLUME_BINDING_IMMEDIATE = "Immediate"
VOLUME_BINDING_WAIT = "WaitForFirstConsumer"


@dataclass
class TopologySelectorTerm:
    """StorageClass.allowedTopologies entry: every requirement must match
    the node's labels (v1helper.MatchTopologySelectorTerms)."""

    match_label_expressions: list["TopologySelectorLabelRequirement"] = \
        field(default_factory=list)


@dataclass
class TopologySelectorLabelRequirement:
    key: str = ""
    values: list[str] = field(default_factory=list)


@dataclass
class StorageClass:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    provisioner: str = ""
    volume_binding_mode: str = VOLUME_BINDING_IMMEDIATE
    allowed_topologies: list[TopologySelectorTerm] = \
        field(default_factory=list)


@dataclass
class CSIStorageCapacity:
    """storage.k8s.io CSIStorageCapacity: a CSI driver's published
    capacity for one storage class in one topology segment — the input to
    VolumeBinding's dynamic-provisioning capacity check and Score
    (volumebinding/binder.go hasEnoughCapacity)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    storage_class_name: str = ""
    # label selector over NODE labels delimiting the topology segment;
    # None = the whole cluster
    node_topology: Optional[LabelSelector] = None
    capacity: str = "0"


# --- dynamic resource allocation (resource.k8s.io slices/claims) ----------------------


@dataclass
class PodResourceClaim:
    """pod.spec.resourceClaims entry: a named reference to a
    ResourceClaim — direct by name, or via a ResourceClaimTemplate the
    resourceclaim controller instantiates per pod."""

    name: str
    resource_claim_name: str = ""
    resource_claim_template_name: str = ""


@dataclass
class DeviceSelector:
    """resourceclaim selectors entry: a CEL expression over one device's
    driver/attributes/capacity (resource.k8s.io CELDeviceSelector;
    evaluated by utils.cel)."""

    cel_expression: str = ""


ALLOCATION_MODE_EXACT = "ExactCount"
ALLOCATION_MODE_ALL = "All"


@dataclass
class DeviceSubRequest:
    """One alternative of a firstAvailable request (DRAPrioritizedList):
    tried in order, first satisfiable wins."""

    name: str
    device_class_name: str = ""
    count: int = 1
    allocation_mode: str = ALLOCATION_MODE_EXACT
    selectors: list[DeviceSelector] = field(default_factory=list)


@dataclass
class DeviceRequest:
    """resourceclaim.spec.devices.requests entry: ExactCount/All modes,
    CEL selectors, adminAccess, or a firstAvailable alternatives list
    (exactly one of deviceClassName / firstAvailable is set)."""

    name: str
    device_class_name: str = ""
    count: int = 1
    allocation_mode: str = ALLOCATION_MODE_EXACT
    selectors: list[DeviceSelector] = field(default_factory=list)
    admin_access: bool = False
    first_available: list[DeviceSubRequest] = field(default_factory=list)


@dataclass
class DeviceConstraint:
    """spec.devices.constraints entry: all devices allocated for the
    listed requests (all requests when empty) must carry the SAME value
    of match_attribute."""

    requests: list[str] = field(default_factory=list)
    match_attribute: str = ""


@dataclass
class DeviceAllocationResult:
    request: str = ""
    driver: str = ""
    pool: str = ""
    device: str = ""
    admin_access: bool = False


@dataclass
class AllocationResult:
    node_name: str = ""
    devices: list[DeviceAllocationResult] = field(default_factory=list)


@dataclass
class ResourceClaimStatus:
    allocation: Optional[AllocationResult] = None
    reserved_for: list[str] = field(default_factory=list)   # pod uids


@dataclass
class ResourceClaimSpec:
    device_requests: list[DeviceRequest] = field(default_factory=list)
    constraints: list[DeviceConstraint] = field(default_factory=list)


@dataclass
class ResourceClaim:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ResourceClaimSpec = field(default_factory=ResourceClaimSpec)
    status: ResourceClaimStatus = field(default_factory=ResourceClaimStatus)

    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    def clone(self) -> "ResourceClaim":
        import copy

        return copy.deepcopy(self)


@dataclass
class ResourceClaimTemplate:
    """resource.k8s.io ResourceClaimTemplate: the spec stamped into a
    fresh per-pod ResourceClaim by the resourceclaim controller."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ResourceClaimSpec = field(default_factory=ResourceClaimSpec)

    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"


@dataclass
class Device:
    """One device in a ResourceSlice: attributes (bool/int/string values,
    optionally 'domain/name'-qualified keys) + capacity quantities feed
    CEL selectors; device_class_name is the legacy direct-match shortcut
    kept for slices that publish pre-classified devices."""

    name: str
    device_class_name: str = ""
    attributes: dict[str, object] = field(default_factory=dict)
    capacity: dict[str, str] = field(default_factory=dict)


@dataclass
class ResourceSlice:
    """resource.k8s.io ResourceSlice: one driver's device inventory on one
    node (the publication a DRA driver makes)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    node_name: str = ""
    driver: str = ""
    pool: str = ""
    devices: list[Device] = field(default_factory=list)


@dataclass
class DeviceClass:
    """resource.k8s.io DeviceClass: CEL selectors over devices; a request
    naming this class matches the devices its selectors accept."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    selectors: list[DeviceSelector] = field(default_factory=list)


# --- pod groups (gang scheduling / multi-tenant job queues) ---------------------------

# labels binding a pod to its gang and tenant queue (the coscheduling
# convention of pod-group.scheduling.sigs.k8s.io, namespaced to this build)
LABEL_POD_GROUP = "scheduling.k8s.io/pod-group"
LABEL_QUEUE = "scheduling.k8s.io/queue"


@dataclass
class PodGroup:
    """scheduling.sigs.k8s.io PodGroup analog (the Kant/coscheduling gang
    contract): pods carrying ``LABEL_POD_GROUP: <name>`` in this namespace
    form one gang. The job queue releases the gang into the scheduling
    batch only when ``min_member`` members are present (and the tenant's
    quota fits them); the gang Permit plugin then holds reserved members
    in the wait room until ``min_member`` have reserved, committing all
    binds together — or rolling every reservation back atomically after
    ``schedule_timeout_seconds``."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    min_member: int = 1
    queue: str = "default"                # tenant / job-queue name
    priority: int = 0                     # gang priority (informational;
                                          # pod spec.priority drives order)
    schedule_timeout_seconds: float = 30.0

    @property
    def name(self) -> str:
        return self.metadata.name

    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"


def pod_group_key(pod: "Pod") -> Optional[str]:
    """The gang key ("namespace/groupname") a pod belongs to, or None."""
    g = pod.metadata.labels.get(LABEL_POD_GROUP)
    return f"{pod.metadata.namespace}/{g}" if g else None


# --- priority class ------------------------------------------------------------------


@dataclass
class PriorityClass:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    value: int = 0
    global_default: bool = False
    preemption_policy: str = "PreemptLowerPriority"


# --- events (core/v1 Event) --------------------------------------------------


@dataclass
class Event:
    """core/v1 Event analog: an object-level notice a controller records
    against a referenced object (``ref_kind``/``ref_key``), deduped by
    (ref, reason) with a bump of ``count`` — how failures that have no
    natural status field (a DeviceClass whose CEL selector does not
    compile) become visible instead of silently parking pods."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    ref_kind: str = ""
    ref_key: str = ""            # "namespace/name" or bare name
    reason: str = ""
    message: str = ""
    count: int = 1
