"""Host-side (reference-semantics) selector matching.

These are the exact-semantics oracles for the device kernels in
``kubernetes_tpu.ops`` and the host fallback path. Reference:
/root/reference/staging/src/k8s.io/apimachinery/pkg/labels (label selectors),
k8s.io/component-helpers/scheduling/corev1/nodeaffinity (node selectors, used
by the NodeAffinity plugin at
/root/reference/pkg/scheduler/framework/plugins/nodeaffinity/node_affinity.go),
and v1helper taint/toleration matching.
"""

from __future__ import annotations

import re
from typing import Optional

from kubernetes_tpu.api.objects import (
    LABEL_HOSTNAME,
    NO_EXECUTE,
    NO_SCHEDULE,
    OP_DOES_NOT_EXIST,
    OP_EXISTS,
    OP_GT,
    OP_IN,
    OP_LT,
    OP_NOT_IN,
    LabelSelector,
    Node,
    NodeSelector,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    Pod,
    Taint,
    Toleration,
)


_INT_RE = re.compile(r"^[+-]?[0-9]+$")


def parse_strict_int(s: str) -> Optional[int]:
    """Base-10 integer parse matching Go strconv.ParseInt: optional sign,
    digits only. Returns None on anything else (underscores, spaces, hex...)."""
    if not _INT_RE.match(s):
        return None
    return int(s)


def requirements_match(exprs, labels: dict[str, str]) -> bool:
    """Evaluate (key, operator, values) requirement tuples against a label
    set — apimachinery labels.Requirement semantics (the host oracle twin of
    the device kernel ops.topology.sel_match). exprs=None (nil selector)
    matches nothing; empty list matches everything."""
    if exprs is None:
        return False
    for key, op, values in exprs:
        present = key in labels
        val = labels.get(key)
        if op == OP_IN:
            ok = present and val in values
        elif op == OP_NOT_IN:
            ok = not present or val not in values
        elif op == OP_EXISTS:
            ok = present
        elif op == OP_DOES_NOT_EXIST:
            ok = not present
        else:
            ok = False  # unrecognized operator: no-match
        if not ok:
            return False
    return True


def selector_requirements(sel: LabelSelector):
    """A LabelSelector as (key, operator, values) requirement tuples."""
    return ([(k, OP_IN, [v]) for k, v in sel.match_labels.items()]
            + [(e.key, e.operator, list(e.values))
               for e in sel.match_expressions])


def label_selector_matches(sel: Optional[LabelSelector], labels: dict[str, str]) -> bool:
    """metav1.LabelSelector semantics. A nil selector matches nothing; an empty
    selector matches everything (apimachinery LabelSelectorAsSelector)."""
    if sel is None:
        return False
    return requirements_match(selector_requirements(sel), labels)


def _node_selector_requirement_matches(
    req: NodeSelectorRequirement, labels: dict[str, str]
) -> bool:
    present = req.key in labels
    val = labels.get(req.key)
    if req.operator == OP_IN:
        return present and val in req.values
    if req.operator == OP_NOT_IN:
        return not present or val not in req.values
    if req.operator == OP_EXISTS:
        return present
    if req.operator == OP_DOES_NOT_EXIST:
        return not present
    if req.operator in (OP_GT, OP_LT):
        # both sides parsed as base-10 integers (strconv.ParseInt semantics:
        # optional sign, digits only — no underscores/whitespace); non-integer
        # => no match
        if not present or len(req.values) != 1:
            return False
        lhs = parse_strict_int(val)  # type: ignore[arg-type]
        rhs = parse_strict_int(req.values[0])
        if lhs is None or rhs is None:
            return False
        return lhs > rhs if req.operator == OP_GT else lhs < rhs
    # unrecognized operator: no-match (device parity via OP_UNKNOWN)
    return False


def _match_fields_matches(req: NodeSelectorRequirement, node_name: str) -> bool:
    # the only supported matchField is metadata.name (nodeaffinity validation)
    if req.key != "metadata.name":
        return False
    if req.operator == OP_IN:
        return node_name in req.values
    if req.operator == OP_NOT_IN:
        return node_name not in req.values
    return False


def node_selector_term_matches(term: NodeSelectorTerm, node: Node) -> bool:
    """A term with no expressions and no fields matches nothing; otherwise all
    requirements must match (AND)."""
    if not term.match_expressions and not term.match_fields:
        return False
    for req in term.match_expressions:
        if not _node_selector_requirement_matches(req, node.metadata.labels):
            return False
    for req in term.match_fields:
        if not _match_fields_matches(req, node.metadata.name):
            return False
    return True


def node_selector_matches(sel: Optional[NodeSelector], node: Node) -> bool:
    """OR over terms; nil selector matches everything, empty term list nothing."""
    if sel is None:
        return True
    return any(node_selector_term_matches(t, node) for t in sel.node_selector_terms)


def pod_matches_node_selector_and_affinity(pod: Pod, node: Node) -> bool:
    """The required half of the NodeAffinity plugin's Filter
    (node_affinity.go:206-228): spec.nodeSelector AND
    affinity.nodeAffinity.required."""
    for k, v in pod.spec.node_selector.items():
        if node.metadata.labels.get(k) != v:
            return False
    aff = pod.spec.affinity
    if aff and aff.node_affinity and aff.node_affinity.required is not None:
        if not node_selector_matches(aff.node_affinity.required, node):
            return False
    return True


def find_untolerated_taint(
    taints: list[Taint],
    tolerations: list[Toleration],
    *,
    effects: tuple[str, ...] = (NO_SCHEDULE, NO_EXECUTE),
) -> Optional[Taint]:
    """First taint with an effect in ``effects`` that no toleration tolerates
    (v1helper.FindMatchingUntoleratedTaint, used by the TaintToleration Filter)."""
    for t in taints:
        if t.effect not in effects:
            continue
        if not any(tol.tolerates(t) for tol in tolerations):
            return t
    return None


