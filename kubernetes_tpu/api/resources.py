"""Resource vectors and pod resource-request aggregation.

Host-side equivalent of ``framework.Resource``
(/root/reference/pkg/scheduler/framework/types.go:846) and
``computePodResourceRequest``
(/root/reference/pkg/scheduler/framework/plugins/noderesources/fit.go:219):
pod request = max(sum(app containers), max(init containers)) + overhead,
with restartable (sidecar) init containers added to the running sum.

``NonZeroRequest`` mirrors types.go:799-803: containers with no cpu/memory
request count as 100m CPU / 200Mi memory for *scoring* (never for fit).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kubernetes_tpu.api.objects import Container, Pod
from kubernetes_tpu.utils.quantity import parse_bytes, parse_cpu_milli, parse_int

CPU = "cpu"
MEMORY = "memory"
EPHEMERAL_STORAGE = "ephemeral-storage"
PODS = "pods"

# scoring defaults for request-less containers (types.go DefaultMilliCPURequest /
# DefaultMemoryRequest)
DEFAULT_MILLI_CPU_REQUEST = 100
DEFAULT_MEMORY_REQUEST = 200 * 1024 * 1024


def _is_native(name: str) -> bool:
    return name in (CPU, MEMORY, EPHEMERAL_STORAGE, PODS)


@dataclass
class Resource:
    """Dense resource vector: native columns + sparse scalar (extended) resources."""

    milli_cpu: int = 0
    memory: int = 0
    ephemeral_storage: int = 0
    allowed_pod_number: int = 0
    scalar: dict[str, int] = field(default_factory=dict)

    @classmethod
    def from_map(cls, m: dict[str, str]) -> "Resource":
        r = cls()
        for name, q in m.items():
            if name == CPU:
                r.milli_cpu = parse_cpu_milli(q)
            elif name == MEMORY:
                r.memory = parse_bytes(q)
            elif name == EPHEMERAL_STORAGE:
                r.ephemeral_storage = parse_bytes(q)
            elif name == PODS:
                r.allowed_pod_number = parse_int(q)
            else:
                r.scalar[name] = parse_int(q)
        return r

    def add(self, other: "Resource") -> None:
        self.milli_cpu += other.milli_cpu
        self.memory += other.memory
        self.ephemeral_storage += other.ephemeral_storage
        for k, v in other.scalar.items():
            self.scalar[k] = self.scalar.get(k, 0) + v

    def sub(self, other: "Resource") -> None:
        self.milli_cpu -= other.milli_cpu
        self.memory -= other.memory
        self.ephemeral_storage -= other.ephemeral_storage
        for k, v in other.scalar.items():
            self.scalar[k] = self.scalar.get(k, 0) - v

    def set_max(self, other: "Resource") -> None:
        self.milli_cpu = max(self.milli_cpu, other.milli_cpu)
        self.memory = max(self.memory, other.memory)
        self.ephemeral_storage = max(self.ephemeral_storage, other.ephemeral_storage)
        for k, v in other.scalar.items():
            self.scalar[k] = max(self.scalar.get(k, 0), v)

    def clone(self) -> "Resource":
        return Resource(self.milli_cpu, self.memory, self.ephemeral_storage,
                        self.allowed_pod_number, dict(self.scalar))

    def is_zero(self) -> bool:
        return (self.milli_cpu == 0 and self.memory == 0
                and self.ephemeral_storage == 0
                and not any(self.scalar.values()))


def _container_request(c: Container, non_zero: bool = False) -> Resource:
    r = Resource.from_map(c.resources.requests)
    if non_zero:
        if CPU not in c.resources.requests:
            r.milli_cpu = DEFAULT_MILLI_CPU_REQUEST
        if MEMORY not in c.resources.requests:
            r.memory = DEFAULT_MEMORY_REQUEST
    return r


def pod_request(pod: Pod, *, non_zero: bool = False) -> Resource:
    """Aggregate pod resource request (fit.go:219 computePodResourceRequest).

    With ``non_zero=True``, cpu/memory of request-less containers default to
    100m / 200Mi — the scoring-path semantics of NonZeroRequested.

    The result is memoized on the pod object (specs are treated as immutable
    by the hub/cache copy-on-write contract, api.objects module docstring);
    callers must NOT mutate the returned Resource. Quantity-string parsing
    otherwise dominates the per-pod host cost of the scheduling hot path.
    """
    cache = pod.__dict__.get("_request_memo")
    if cache is None:
        cache = pod._request_memo = [None, None]
    memo = cache[1 if non_zero else 0]
    if memo is not None:
        return memo
    total = Resource()
    for c in pod.spec.containers:
        total.add(_container_request(c, non_zero))

    # restartable (sidecar) init containers accumulate; regular init containers
    # impose a running max over (their own request + accumulated sidecars).
    sidecar_sum = Resource()
    init_max = Resource()
    for c in pod.spec.init_containers:
        r = _container_request(c, non_zero)
        if c.restart_policy == "Always":
            sidecar_sum.add(r)
            init_max.set_max(sidecar_sum)
        else:
            peak = sidecar_sum.clone()
            peak.add(r)
            init_max.set_max(peak)
    total.add(sidecar_sum)
    # max(sum-of-app+sidecars, peak-init)
    total.set_max(init_max)

    if pod.spec.overhead:
        total.add(Resource.from_map(pod.spec.overhead))
    cache[1 if non_zero else 0] = total
    return total
