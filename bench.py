"""Headline benchmark: batched scheduling throughput at 5k nodes.

Mirrors the reference's scheduler_perf SchedulingBasic/5000Nodes_10000Pods
workload (test/integration/scheduler_perf/misc/performance-config.yaml:63,
CI threshold 270 pods/s): 5000 nodes, pending pods drained in batches
through the device pipeline. The drain uses the TPU-native fast path:

- parallel-rounds auction commit (pipeline._rounds_commit) instead of the
  per-pod scan — O(rounds) of [B, N] work, not B sequential steps;
- device-resident (free, nonzero_requested) state chained launch-to-launch,
  so the drain does NO host->device mirror re-sync between batches;
- results pulled after the whole chain is dispatched (the axon/TPU link's
  per-round-trip latency is paid once per batch, overlapped with compute);
- winners then committed through the production assume -> snapshot -> mirror
  path (the serial loop's assume step, schedule_one.go:938).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline is the multiple of the reference's 270 pods/s threshold.
"""

from __future__ import annotations

import json
import os
import sys
import time

_repo = os.path.dirname(os.path.abspath(__file__))
if _repo not in sys.path:
    sys.path.insert(0, _repo)

BASELINE_PODS_PER_SEC = 270.0  # misc/performance-config.yaml:63
NUM_NODES = 5000
NUM_PODS = 10000
BATCH = 2048


def main() -> None:
    from kubernetes_tpu.utils import jaxsetup

    jaxsetup.setup(os.path.join(_repo, ".jax_cache"))
    import numpy as np

    from kubernetes_tpu.models.pipeline import default_weights, launch_batch
    from kubernetes_tpu.models.testbed import build_cluster, make_pod
    from kubernetes_tpu.ops.features import Capacities

    t0 = time.time()
    caps = Capacities(nodes=8192, pods=16384)
    cache, snap, mirror = build_cluster(NUM_NODES, caps=caps)
    wk = mirror.well_known()
    weights = default_weights()
    pods = [make_pod(i) for i in range(NUM_PODS)]
    import jax
    print(f"setup {time.time() - t0:.1f}s on {jax.devices()[0].platform}",
          file=sys.stderr)

    # warmup / compile both chain variants (state absent and present)
    t0 = time.time()
    spec = mirror.prepare_launch(pods[:BATCH], BATCH)
    out = launch_batch(spec, wk, weights, caps, serial_scan=False)
    _ = np.asarray(out.node_row)
    out = launch_batch(spec, wk, weights, caps, serial_scan=False,
                       state=(out.free, out.nzr))
    _ = np.asarray(out.node_row)
    print(f"compile+first-run {time.time() - t0:.1f}s", file=sys.stderr)

    import jax.numpy as jnp
    concat = jax.jit(lambda xs: jnp.concatenate(xs))

    t0 = time.time()
    scheduled = 0
    state = None
    launches = []
    for start in range(0, NUM_PODS, BATCH):
        chunk = pods[start:start + BATCH]
        spec = mirror.prepare_launch(chunk, BATCH)
        out = launch_batch(spec, wk, weights, caps, serial_scan=False,
                           state=state)
        state = (out.free, out.nzr)
        launches.append((chunk, out))
    # ONE device->host round trip for the whole drain's placements
    all_rows = np.asarray(concat([out.node_row for _, out in launches]))
    off = 0
    for chunk, out in launches:
        rows = all_rows[off: off + len(chunk)]
        off += BATCH
        # commit winners through the production assume path so the cache /
        # snapshot / mirror end state matches what the launches computed
        for pod, row in zip(chunk, rows.tolist()):
            if row < 0:
                continue
            scheduled += 1
            bound = pod.clone()
            bound.spec.node_name = mirror.name_of_row(row)
            cache.assume_pod(bound)
    cache.update_snapshot(snap)
    mirror.sync(snap)
    elapsed = time.time() - t0
    assert scheduled == NUM_PODS, f"only {scheduled}/{NUM_PODS} pods placed"

    pods_per_sec = NUM_PODS / elapsed
    print(json.dumps({
        "metric": "scheduling_throughput_5000nodes",
        "value": round(pods_per_sec, 1),
        "unit": "pods/sec",
        "vs_baseline": round(pods_per_sec / BASELINE_PODS_PER_SEC, 2),
    }))


if __name__ == "__main__":
    main()
