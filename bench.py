"""Headline benchmark: batched scheduling throughput at 5k nodes.

Mirrors the reference's scheduler_perf SchedulingBasic/5000Nodes_10000Pods
workload (test/integration/scheduler_perf/misc/performance-config.yaml:63,
CI threshold 270 pods/s): 5000 nodes, pending pods drained in batches of 256
through the device pipeline (pack → one XLA launch per batch → winners back).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline is the multiple of the reference's 270 pods/s threshold.
"""

from __future__ import annotations

import json
import os
import sys
import time

_repo = os.path.dirname(os.path.abspath(__file__))
if _repo not in sys.path:
    sys.path.insert(0, _repo)

BASELINE_PODS_PER_SEC = 270.0  # misc/performance-config.yaml:63
NUM_NODES = 5000
NUM_PODS = 10000
BATCH = 256


def main() -> None:
    from kubernetes_tpu.utils import jaxsetup

    jaxsetup.setup(os.path.join(_repo, ".jax_cache"))
    import jax

    from kubernetes_tpu.models.pipeline import default_weights, schedule_batch_jit
    from kubernetes_tpu.models.testbed import build_cluster, make_pod
    from kubernetes_tpu.ops.features import Capacities

    t0 = time.time()
    caps = Capacities(nodes=8192, pods=16384)
    cache, snap, mirror = build_cluster(NUM_NODES, caps=caps)
    wk = mirror.well_known()
    weights = default_weights()
    pods = [make_pod(i) for i in range(NUM_PODS)]
    print(f"setup {time.time() - t0:.1f}s on {jax.devices()[0].platform}",
          file=sys.stderr)

    # warmup / compile
    t0 = time.time()
    cblobs, pblobs, topo, d_cap = mirror.prepare_launch(pods[:BATCH], BATCH)
    jax.block_until_ready(schedule_batch_jit(cblobs, pblobs, wk, weights,
                                             caps, topo, d_cap))
    print(f"compile+first-run {time.time() - t0:.1f}s", file=sys.stderr)

    t0 = time.time()
    scheduled = 0
    for start in range(0, NUM_PODS, BATCH):
        chunk = pods[start:start + BATCH]
        cblobs, pblobs, topo, d_cap = mirror.prepare_launch(chunk, BATCH)
        out = schedule_batch_jit(cblobs, pblobs, wk, weights, caps,
                                 topo, d_cap)
        rows = out.node_row[: len(chunk)]
        # commit winners through the production assume->snapshot->mirror path
        # so every batch schedules against the progressively filled cluster
        # (the serial loop's assume step, schedule_one.go:938)
        for pod, row in zip(chunk, rows.tolist()):
            if row < 0:
                continue
            scheduled += 1
            bound = pod.clone()
            bound.spec.node_name = mirror.name_of_row(row)
            cache.assume_pod(bound)
        cache.update_snapshot(snap)
        mirror.sync(snap)
    elapsed = time.time() - t0
    assert scheduled == NUM_PODS, f"only {scheduled}/{NUM_PODS} pods placed"

    pods_per_sec = NUM_PODS / elapsed
    print(json.dumps({
        "metric": "scheduling_throughput_5000nodes",
        "value": round(pods_per_sec, 1),
        "unit": "pods/sec",
        "vs_baseline": round(pods_per_sec / BASELINE_PODS_PER_SEC, 2),
    }))


if __name__ == "__main__":
    main()
