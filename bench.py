"""Headline benchmark: production-path scheduling throughput, 5 workloads.

Drives the 5 BASELINE workloads (scheduler_perf shapes: SchedulingBasic,
SchedulingNodeAffinity, SchedulingPodAntiAffinity, TopologySpreading,
PreemptionAsync) through the PRODUCTION Scheduler loop — pods created via
hub.create_pod, popped from the PriorityQueue, packed into the HBM mirror,
scheduled by the fused device pipeline, committed through the framework's
reserve/permit/bind points, bindings written to the hub — exactly the path
a real cluster would run. Throughput is observed from the hub watch stream
by a 1s-window collector (util.go:442-630 equivalent).

Each workload is preceded by a tiny warmup pass at identical capacity
buckets (= identical XLA program shapes), so compilation happens outside
the measured phase; the measured run reuses the cached executables.

Prints ONE JSON line: the headline SchedulingBasic number vs the
reference's 270 pods/s CI floor (misc/performance-config.yaml:63), with
per-workload results (value, threshold, vs_baseline, window percentiles)
under "workloads".
"""

from __future__ import annotations

import json
import os
import sys
import time

_repo = os.path.dirname(os.path.abspath(__file__))
if _repo not in sys.path:
    sys.path.insert(0, _repo)

BASELINE_PODS_PER_SEC = 270.0  # misc/performance-config.yaml:63


def main() -> None:
    from kubernetes_tpu.utils import jaxsetup

    jaxsetup.setup(os.path.join(_repo, ".jax_cache"))
    import jax

    from kubernetes_tpu.perf.harness import run_workload
    from kubernetes_tpu.perf.workloads import BENCH_WORKLOADS

    smoke = "--smoke" in sys.argv
    print(f"platform: {jax.devices()[0].platform}", file=sys.stderr)
    results = {}
    headline = None
    for factory in BENCH_WORKLOADS:
        # warmup: same capacities => same jitted program shapes; tiny counts
        t0 = time.time()
        run_workload(factory(), scale=0.005)
        t_warm = time.time() - t0
        t0 = time.time()
        r = run_workload(factory(), scale=0.02 if smoke else 1.0)
        t_full = time.time() - t0
        print(f"{r['name']}: {r.get('pods_per_sec', 0):.1f} pods/s "
              f"(threshold {r['threshold']}, warm {t_warm:.1f}s, "
              f"run {t_full:.1f}s)", file=sys.stderr)
        short = r["name"].split("/")[0]
        results[short] = {k: r[k] for k in (
            "name", "pods_per_sec", "threshold", "vs_baseline", "passed",
            "pods_scheduled", "elapsed_s", "p50", "p90", "p95", "p99",
            "metrics")
            if k in r}
        if short == "SchedulingBasic":
            headline = r

    assert headline is not None
    print(json.dumps({
        "metric": "scheduling_throughput_5000nodes_production_path",
        "value": round(headline["pods_per_sec"], 1),
        "unit": "pods/sec",
        "vs_baseline": round(headline["pods_per_sec"] / BASELINE_PODS_PER_SEC,
                             2),
        "workloads": results,
    }))


if __name__ == "__main__":
    main()
