"""Headline benchmark: production-path scheduling throughput, 30 workloads.

Drives EVERY thresholded reference scheduler_perf workload (BASELINE.md's
full table: the 5 BASELINE.json headliners plus the affinity, spreading,
churn, gated, daemonset, unschedulable, DRA and feature-gate-variant
shapes) through the
PRODUCTION Scheduler loop — pods created via
hub.create_pod, popped from the PriorityQueue, packed into the HBM mirror,
scheduled by the fused device pipeline, committed through the framework's
reserve/permit/bind points, bindings written to the hub — exactly the path
a real cluster would run. Throughput is observed from the hub watch stream
by a 1s-window collector (util.go:442-630 equivalent).

Each workload runs in its OWN subprocess (kubernetes_tpu.perf.run_one),
matching the reference harness's per-workload process isolation: in one
shared process, earlier workloads' device-memory/executable pressure
shows up as multi-second stalls in later measured phases. Each subprocess
does a tiny same-shapes warmup pass first, and the on-disk XLA compile
cache carries compilations across processes and rounds.

Prints ONE JSON line: the headline SchedulingBasic number vs the
reference's 270 pods/s CI floor (misc/performance-config.yaml:63), with
per-workload results (value, threshold, vs_baseline, window percentiles)
under "workloads".
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

_repo = os.path.dirname(os.path.abspath(__file__))

BASELINE_PODS_PER_SEC = 270.0  # misc/performance-config.yaml:63

# the committed artifact README.md's bench table is generated from; a
# new measurement round commits a new artifact and re-points this
README_BENCH_ARTIFACT = "BENCH_r19_builder.json"
_TABLE_BEGIN = "<!-- BENCH_TABLE_BEGIN"
_TABLE_END = "<!-- BENCH_TABLE_END -->"


def readme_bench_table(artifact: dict) -> str:
    """Render the README bench table MECHANICALLY from a bench artifact —
    hand-edited numbers drift from the committed measurements (round-5
    shipped a 243 pods/s claim over a 44.8 artifact row); generated rows
    cannot."""
    lines = ["| workload | pods/s | floor | multiple |",
             "|---|---|---|---|"]
    for w in artifact["workloads"].values():
        floor = w.get("threshold") or 0
        mult = w["pods_per_sec"] / floor if floor else 0.0
        lines.append(f"| {w['name']} | {w['pods_per_sec']:,.1f} "
                     f"| {floor:g} | {mult:.1f}× |")
    return "\n".join(lines)


def readme_check(write: bool = False,
                 artifact_path: str | None = None) -> bool:
    """--readme-check: diff README.md's generated bench-table block
    against the committed artifact; False (CI-red) on mismatch.
    --readme-update (write=True) rewrites the block in place."""
    artifact_path = artifact_path or os.path.join(_repo,
                                                  README_BENCH_ARTIFACT)
    with open(artifact_path) as f:
        artifact = json.load(f)
    readme_path = os.path.join(_repo, "README.md")
    with open(readme_path) as f:
        readme = f.read()
    begin = readme.find(_TABLE_BEGIN)
    end = readme.find(_TABLE_END)
    if begin < 0 or end < 0 or end < begin:
        print("README.md: bench-table markers missing/corrupt "
              f"({_TABLE_BEGIN} ... {_TABLE_END})", file=sys.stderr)
        return False
    # keep the marker line (it names the artifact) — regenerate between
    # the end of that line and the END marker
    body_start = readme.index("\n", begin) + 1
    want = readme_bench_table(artifact) + "\n"
    have = readme[body_start:end]
    if have == want:
        return True
    if write:
        with open(readme_path, "w") as f:
            f.write(readme[:body_start] + want + readme[end:])
        print(f"README.md bench table regenerated from "
              f"{os.path.basename(artifact_path)}", file=sys.stderr)
        return True
    import difflib

    diff = difflib.unified_diff(
        have.splitlines(keepends=True), want.splitlines(keepends=True),
        fromfile="README.md (committed)",
        tofile=f"{os.path.basename(artifact_path)} (generated)")
    sys.stderr.writelines(diff)
    print("README bench table does not match the committed artifact; "
          "run `python bench.py --readme-update`", file=sys.stderr)
    return False

BENCH_WORKLOAD_FNS = (
    "scheduling_basic",
    "scheduling_node_affinity",
    "scheduling_pod_anti_affinity",
    "topology_spreading",
    "preemption_async",
    "unschedulable",
    "unschedulable_qhints",
    "mixed_churn",
    "scheduling_daemonset",
    "scheduling_while_gated",
    "preferred_pod_affinity",
    "preferred_pod_anti_affinity",
    "ns_selector_anti_affinity",
    "dra_steady_state",
    "dra_steady_state_templates",
    "dra_steady_state_cel_in",
    "dra_multi_request",
    "scheduling_pod_affinity",
    "mixed_scheduling_base_pod",
    "ns_selector_pod_affinity",
    "ns_selector_preferred_affinity",
    "gated_pods_with_pod_affinity",
    "preferred_topology_spreading",
    "scheduling_with_node_inclusion_policy",
    "scheduling_basic_qhints",
    "preemption_async_enabled",
    "ns_selector_preferred_anti_affinity",
    "multi_tenant_gang_storm",
    "quota_exhaustion_churn",
    "gang_preemption",
    "gang_topology_packing",
)

# the ROADMAP's sub-10x offenders, profiled with the flight recorder's
# per-phase attribution by --profile (mirrors workloads.PROFILE_WORKLOADS
# by name; tests/test_perf_harness.py asserts the two stay in sync)
PROFILE_WORKLOAD_FNS = (
    "scheduling_daemonset",
    "mixed_churn",
    "preferred_pod_anti_affinity",
    "preferred_topology_spreading",
    "ns_selector_preferred_affinity",
    "ns_selector_preferred_anti_affinity",
    "dra_steady_state",
    "dra_steady_state_templates",
    "multi_tenant_gang_storm",
    "quota_exhaustion_churn",
    "gang_preemption",
    "gang_topology_packing",
)

# the always-on recorder's cost ceiling: what makes "every cycle, every
# phase" viable instead of sampling-on-slow
TRACE_OVERHEAD_BUDGET = 0.02   # <2% p50 cycle time

# --ab-scorer: learned-vs-hand-tuned phase-total latency parity bar
AB_LATENCY_BUDGET = 0.03       # <3% phase-total delta on SchedulingBasic


def run_ab_scorer(smoke: bool = False, scale: float = 0.1,
                  generations: int = 1) -> dict:
    """--ab-scorer: the learned-scoring quality harness, end to end in
    one process — (1) a hand-tuned collection run of SchedulingBasic
    with the trace export on, (2) replay-train a checkpoint from the
    exported placement rows, (3) paired A/B of hand-tuned vs learned on
    the same workloads with the SAME tie-break seed, reporting latency
    parity (non-view flight-recorder phase totals) and the quality
    metrics (preemptions, spread imbalance, time-to-bind p99, and —
    now that the arms export the v3 alternative rows — per-placement
    regret mean/p99) the harness records per workload. ``--generations
    N`` (ROADMAP item 4's gate) additionally closes the loop N-1 more
    times: each refresh generation re-collects traces under the LIVE
    learned policy, retrains through the learn-loop daemon body, and
    passes the promotion gate before the next collection hot-reloads
    the winner. The artifact rows are shaped for embedding in
    BENCH_r08+ files (quality columns ride "workloads")."""
    import shutil
    import tempfile

    # the workdir holds the rotation-disabled trace export (can exceed
    # 64MiB at full scale) + the checkpoint: cleaned on EVERY exit path
    workdir = tempfile.mkdtemp(prefix="ab_scorer_")
    try:
        return _ab_scorer_run(workdir, smoke, scale, generations)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def _ab_scorer_run(workdir: str, smoke: bool, scale: float,
                   generations: int = 1) -> dict:
    from kubernetes_tpu.utils import jaxsetup

    jaxsetup.setup(os.path.join(_repo, ".jax_cache"))

    from kubernetes_tpu.config.types import Plugin, default_config
    from kubernetes_tpu.learn.checkpoint import save_checkpoint
    from kubernetes_tpu.learn.replay import build_dataset
    from kubernetes_tpu.learn.train import TrainConfig, train
    from kubernetes_tpu.perf.harness import run_workload
    from kubernetes_tpu.perf import workloads as W
    from kubernetes_tpu.utils.tracing import VIEW_PHASES

    tie_seed = 2026_0801

    def shrink(factory, **kw):
        """Smoke variant: small cluster AND small capacity buckets, so
        the in-process smoke never compiles the 8192-node programs —
        same trick as trace_overhead_smoke."""
        def make():
            w = factory(**kw)
            w.node_capacity = 64
            w.pod_capacity = 2048
            w.batch_size = 32
            w.warm_full_nodes = False
            return w
        return make

    if smoke:
        scale = 1.0
        ab_factories = (
            ("SchedulingBasic", shrink(W.scheduling_basic, init_nodes=32,
                                       init_pods=16, measure_pods=200)),
            ("TopologySpreading", shrink(W.topology_spreading,
                                         init_nodes=32, init_pods=64,
                                         measure_pods=96)),
            # 24 nodes x 4 cpu hold ~96 of the 900m init pods: keep the
            # init phase under capacity or it can never complete
            ("PreemptionAsync", shrink(W.preemption_async, init_nodes=24,
                                       init_pods=80, measure_pods=48)),
        )
        collection = ab_factories[0][1]
    else:
        ab_factories = (("SchedulingBasic", W.scheduling_basic),
                        ("TopologySpreading", W.topology_spreading),
                        ("PreemptionAsync", W.preemption_async))
        collection = W.scheduling_basic

    def base_cfg():
        c = default_config()
        c.tie_break_seed = tie_seed
        return c

    trace_path = os.path.join(workdir, "traces.jsonl")
    ckpt_path = os.path.join(workdir, "scorer.json")

    # 1. collection: hand-tuned SchedulingBasic with the export on
    # (feature vectors opted in — they ARE the training substrate;
    # rotation off for this bounded-lifetime run so a >64MiB collection
    # cannot silently rotate early examples out of the dataset)
    def export_into(c, path):
        c.trace_export_path = path
        c.trace_export_features = True
        # the v3 alternative rows: the regret substrate (and the
        # learn-loop's counterfactual fine-tune input)
        c.trace_export_alts = True
        c.trace_export_max_bytes = 0
        return c

    cfg = export_into(base_cfg(), trace_path)
    print("ab-scorer: collection run (trace export)...", file=sys.stderr)
    run_workload(collection(), scale=scale, config=cfg)

    # 2. replay-train the scorer from the exported placement rows
    ds = build_dataset([trace_path])
    params, info = train(ds, TrainConfig(
        seed=0, meta={"version": 1, "source": "ab_scorer"}))
    doc = save_checkpoint(ckpt_path, params, meta=info)
    print(f"ab-scorer: trained on {len(ds)} examples "
          f"(bc loss {info['bc_loss_first']} -> {info['bc_loss_last']})",
          file=sys.stderr)

    def learned_cfg():
        c = base_cfg()
        prof = c.profiles[0]
        prof.plugins.score.enabled.append(Plugin("LearnedScore", 1.0))
        prof.plugin_config["LearnedScore"] = {
            "checkpoint_path": ckpt_path}
        return c

    def phase_total(res: dict) -> float:
        return sum(p["total_s"]
                   for ph, p in res.get("flight", {})
                   .get("phases", {}).items()
                   if ph not in VIEW_PHASES)

    def arm(res: dict) -> dict:
        return {
            "pods_per_sec": res.get("pods_per_sec"),
            "phase_total_s": round(phase_total(res), 4),
            "quality": res.get("quality", {}),
        }

    out = {}
    improved_any = []
    for name, factory in ab_factories:
        pair = {}
        for arm_name, cfg_fn in (("hand", base_cfg),
                                 ("learned", learned_cfg)):
            # per-arm tiny compile pass, then the measured run — the
            # learned arm compiles a different program (the MLP term).
            # BOTH passes export (alts on) so the measured run reuses
            # the warm pass's with_alts program AND its quality row
            # carries the regret columns; the export rides both arms
            # symmetrically, so latency parity is unaffected
            run_workload(factory(), scale=0.05 if smoke else 0.005,
                         config=export_into(cfg_fn(), os.path.join(
                             workdir, f"warm_{name}_{arm_name}.jsonl")))
            pair[arm_name] = run_workload(
                factory(), scale=scale, profile=True,
                config=export_into(cfg_fn(), os.path.join(
                    workdir, f"ab_{name}_{arm_name}.jsonl")))
        hand, learned = arm(pair["hand"]), arm(pair["learned"])
        ht, lt = hand["phase_total_s"], learned["phase_total_s"]
        delta = (lt - ht) / ht if ht > 0 else 0.0
        qd = {}
        better = []
        for k in ("preemptions", "spread_stddev", "spread_max_min",
                  "time_to_bind_p99_ms", "regret_mean", "regret_p99"):
            if k not in hand["quality"] or k not in learned["quality"]:
                # a metric missing on EITHER side (e.g. the regret
                # block failed in one arm) is "no data", never a
                # default-0 fabricated win
                continue
            hv = hand["quality"][k]
            lv = learned["quality"][k]
            qd[k] = round(lv - hv, 3)
            # "improved" needs a >=1% relative drop — a sub-noise float
            # delta must not satisfy the quality acceptance criterion
            if hv > 0 and lv < hv and (hv - lv) >= 0.01 * hv:
                better.append(k)
        if better:
            improved_any.append(name)
        out[name] = {"hand": hand, "learned": learned,
                     "latency_delta_pct": round(delta * 100.0, 2),
                     "quality_delta": qd, "improved": better}
        print(f"ab-scorer {name}: phase-total {ht:.3f}s -> {lt:.3f}s "
              f"({delta * 100:+.2f}%), improved: {better or 'none'}",
              file=sys.stderr)
    # ----- refresh generations (ROADMAP item 4's 3-generation gate):
    # collect under the LIVE learned policy -> learn-loop body
    # (retrain + regret fine-tune + promotion gate) -> the next
    # collection's scheduler loads whatever the gate published
    gens = []
    if generations > 1:
        from kubernetes_tpu.learn.loop import LearnLoop, LoopConfig

        loop_traces = os.path.join(workdir, "loop_traces.jsonl")
        loop = LearnLoop(LoopConfig(
            trace_path=loop_traces,
            staging_dir=os.path.join(workdir, "staging"),
            live_path=ckpt_path,
            min_new_rows=32, min_holdout_rows=8,
            bc_epochs=80 if smoke else 200,
            ft_epochs=40 if smoke else 100))
        for _g in range(2, generations + 1):
            res = run_workload(collection(), scale=scale,
                               config=export_into(learned_cfg(),
                                                  loop_traces))
            rep = loop.run_once()
            row = {"generation": rep.get("generation"),
                   "version": rep.get("version"),
                   "status": rep.get("status"),
                   "gate": rep.get("gate"),
                   "regret": rep.get("regret"),
                   "pods_per_sec": res.get("pods_per_sec"),
                   "quality": res.get("quality")}
            gens.append(row)
            print(f"ab-scorer generation {rep.get('generation')}: "
                  f"{rep.get('status')} (version {rep.get('version')}, "
                  f"gate {rep.get('gate')})", file=sys.stderr)

    basic = out.get("SchedulingBasic", {})
    # the 3% parity bar is a FULL-SCALE property (phase totals measured
    # in seconds); smoke phase totals are ~0.1s of mostly dispatch
    # overhead, so the smoke bar is advisory-loose — it exists to catch
    # "the learned arm got 2x slower", not to measure parity
    budget = AB_LATENCY_BUDGET if not smoke else 0.15
    result = {
        "metric": "ab_scorer",
        "unit": "quality",
        "smoke": smoke,
        "tie_break_seed": tie_seed,
        "scale": scale,
        "checkpoint": {k: doc["meta"].get(k)
                       for k in ("version", "fingerprint", "examples",
                                 "bc_loss_last")},
        "latency_budget_pct": budget * 100.0,
        "latency_ok": (basic.get("latency_delta_pct", 0.0)
                       <= budget * 100.0),
        "improved_workloads": improved_any,
        "workloads": out,
    }
    if gens:
        result["generations"] = gens
    return result


def run_profile(smoke: bool = False) -> dict:
    """--profile: run the sub-10x offender workloads with the flight
    recorder's breakdown in each subprocess result, print a per-phase
    p50/p99 table (incl. host-plugin and DRA-allocator time) to stderr
    and the artifact JSON line to stdout."""
    env = dict(os.environ)
    env["PYTHONPATH"] = _repo + os.pathsep + env.get("PYTHONPATH", "")
    scale = "0.02" if smoke else "1.0"
    out = {}
    for fn in PROFILE_WORKLOAD_FNS:
        try:
            proc = subprocess.run(
                [sys.executable, "-m", "kubernetes_tpu.perf.run_one", fn,
                 "--scale", scale, "--profile"],
                capture_output=True, text=True, timeout=1800, env=env,
                cwd=_repo)
        except subprocess.TimeoutExpired:
            print(f"{fn}: TIMEOUT after 1800s", file=sys.stderr)
            continue
        if proc.returncode != 0:
            print(f"{fn}: FAILED\n{proc.stderr[-2000:]}", file=sys.stderr)
            continue
        r = json.loads(proc.stdout.strip().splitlines()[-1])
        fl = r.get("flight", {})
        out[r["name"]] = {
            "name": r["name"],
            "pods_per_sec": r.get("pods_per_sec"),
            "threshold": r.get("threshold"),
            "flight": fl,
        }
        print(f"\n{r['name']}: {r.get('pods_per_sec', 0):.1f} pods/s — "
              f"host-tail share {fl.get('host_tail_share', 0):.1%}, "
              f"{fl.get('cycles_recorded', 0)} cycles recorded",
              file=sys.stderr)
        occ = fl.get("occupancy") or {}
        if occ:
            # pipelined waves: how much of each cycle's wall the device
            # launch actually covered (mean near 1.0 = pipeline full)
            print(f"  occupancy: mean {occ['mean']:.1%}, "
                  f"p50 {occ['p50']:.1%}, p99 {occ['p99']:.1%} "
                  f"over {occ['n']} cycles", file=sys.stderr)
        print(f"  {'phase':<18} {'p50_ms':>9} {'p99_ms':>9} "
              f"{'count':>7} {'total_s':>9}", file=sys.stderr)
        for phase, p in sorted(fl.get("phases", {}).items(),
                               key=lambda kv: -kv[1]["total_s"]):
            print(f"  {phase:<18} {p['p50_ms']:>9.3f} {p['p99_ms']:>9.3f} "
                  f"{p['count']:>7} {p['total_s']:>9.3f}", file=sys.stderr)
        plugins = sorted(fl.get("plugins", {}).items(),
                         key=lambda kv: -kv[1]["total_s"])[:8]
        if plugins:
            print(f"  {'plugin/point':<34} {'p50_ms':>9} {'p99_ms':>9} "
                  f"{'total_s':>9}", file=sys.stderr)
            for key, p in plugins:
                print(f"  {key:<34} {p['p50_ms']:>9.3f} "
                      f"{p['p99_ms']:>9.3f} {p['total_s']:>9.3f}",
                      file=sys.stderr)
        dev = fl.get("device")
        if dev:
            # the DeviceProfiler column: compiles by attributed cause +
            # resident HBM footprint — the "why does the device path
            # stall" answer next to the phase table
            causes = ", ".join(f"{k}={v}" for k, v in
                               sorted(dev["compile_causes"].items()))
            print(f"  device: {dev['launches']} launches, "
                  f"{dev['compiles']} compiles ({causes or 'none'}), "
                  f"{len(dev['shapes'])} shapes, "
                  f"{dev['buffer_total_mib']} MiB resident",
                  file=sys.stderr)
    # the fabric row: fanout smoke (small variant) — e2e joined-trace
    # SLO (created->acked p99) + fleet health next to the host tails
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "kubernetes_tpu.fabric.fanout",
             "--smoke"],
            capture_output=True, text=True, timeout=600, env=env,
            cwd=_repo)
        if proc.returncode == 0 and proc.stdout.strip():
            fr = json.loads(proc.stdout.strip().splitlines()[-1])
            out["FanoutSmoke"] = {
                "name": "FanoutSmoke",
                "e2e": fr.get("e2e"),
                "events_traced_frac": fr.get("events_traced_frac"),
                "ok": fr.get("ok"),
            }
            e2e = fr.get("e2e", {})
            lat = e2e.get("created_to_acked", {})
            print(f"\nFanoutSmoke: created->acked p99 "
                  f"{lat.get('p99_s', '?')}s over {lat.get('count', 0)} "
                  f"pods, joinable {e2e.get('joinable_frac', 0):.0%}, "
                  f"fleet {e2e.get('fleet', {}).get('healthy', 0)}/"
                  f"{e2e.get('fleet', {}).get('endpoints', 0)} healthy",
                  file=sys.stderr)
        else:
            print(f"fanout smoke (profile row): FAILED\n"
                  f"{proc.stderr[-1500:]}", file=sys.stderr)
    except subprocess.TimeoutExpired:
        print("fanout smoke (profile row): TIMEOUT", file=sys.stderr)
    return {
        "metric": "phase_profile",
        "unit": "ms",
        "workloads": out,
    }


def trace_overhead_smoke(pairs: int = 4) -> dict:
    """--trace-overhead: the always-on recorder's bar — <2% p50
    cycle-time cost. One process (shared compile cache), a fixed-seed
    shrunk SchedulingBasic, alternating recorder-off/on runs, EXACT raw
    per-cycle durations pooled per arm (the histogram's power-of-2
    buckets would quantize a 2% delta away), medians compared. The ON
    arm also runs the SLO watchdog + an armed autopsy store, so the
    budget covers the whole observability stack: recorder, timelines,
    incident hooks, and breach detection."""
    import tempfile

    from kubernetes_tpu.utils import jaxsetup

    jaxsetup.setup(os.path.join(_repo, ".jax_cache"))
    from kubernetes_tpu.config.types import default_config
    from kubernetes_tpu.perf.harness import run_workload
    from kubernetes_tpu.perf.workloads import scheduling_basic

    def make():
        # ~16 pods/cycle x ~16 cycles per run: enough samples per arm
        # for a stable median without a minutes-long smoke
        w = scheduling_basic(init_nodes=32, init_pods=16,
                             measure_pods=240)
        w.node_capacity = 64
        w.pod_capacity = 512
        w.batch_size = 16
        return w

    autopsy_dir = tempfile.mkdtemp(prefix="bench-trace-autopsy-")

    def cfg(recorder_on: bool):
        c = default_config()
        if not recorder_on:
            c.flight_recorder_capacity = 0
        else:
            # the full observability stack on the measured arm: the
            # watchdog evaluates every maintenance pass and the store
            # is armed (no breaches expected on this clean workload,
            # but the hot-path hook checks are what the budget prices)
            c.autopsy_dir = autopsy_dir
            c.watchdog_interval_s = 0.0
        return c

    run_workload(make(), scale=0.1, config=cfg(True))   # compile pass
    arms: dict[bool, list[float]] = {False: [], True: []}
    for _ in range(pairs):
        for on in (False, True):    # alternate so drift hits both arms
            times: list[float] = []
            run_workload(make(), config=cfg(on), cycle_times=times)
            arms[on].extend(times)

    def p50(xs: list[float]) -> float:
        xs = sorted(xs)
        return xs[len(xs) // 2]

    off_p50, on_p50 = p50(arms[False]), p50(arms[True])
    # 100us absolute floor: on a loaded CI box two sub-5ms medians can
    # sit 2% apart from scheduler-unrelated jitter alone
    ok = on_p50 <= off_p50 * (1.0 + TRACE_OVERHEAD_BUDGET) + 100e-6
    return {
        "metric": "trace_overhead",
        "cycle_p50_off_ms": round(off_p50 * 1e3, 3),
        "cycle_p50_on_ms": round(on_p50 * 1e3, 3),
        "delta_pct": round((on_p50 - off_p50) / off_p50 * 100.0, 2),
        "budget_pct": TRACE_OVERHEAD_BUDGET * 100.0,
        "cycles_per_arm": len(arms[True]),
        "ok": ok,
    }


def run_scaleout_bench(smoke: bool = False, replicas: int = 4,
                       timeout_s: float = 300.0) -> dict:
    """--scaleout: horizontal scale-out throughput A/B. Two arms on a
    fresh proc fabric each: ONE scheduler OS process vs ``replicas``
    scheduler OS processes (``python -m kubernetes_tpu --hub <router>
    --slices``), draining an identical partition-friendly workload
    (pods spread over 32 namespaces, plain 50m-cpu requests — no gang
    coupling, so slices are independent). OS processes, not threads:
    in-process replicas share one GIL and could never show real
    scaling. ``ok`` iff the multi-replica arm clears 3x the
    single-replica arm's pods/s (acceptance floor) — the single-
    replica arm IS the no-regression reference, measured on the same
    fabric, same workload, same commit. With fewer cores than replica
    processes the floor is unmeasurable (``hardware_limited`` in the
    report); both arms then gate on completeness only."""
    import tempfile
    import time as _time

    pods = 200 if smoke else 800
    nodes = 16
    env = dict(os.environ)
    env["PYTHONPATH"] = _repo + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")

    def run_arm(n_replicas: int) -> dict:
        from kubernetes_tpu.fabric.supervisor import spawn_local_cluster
        from kubernetes_tpu.hubclient import RemoteHub
        from kubernetes_tpu.testing import MakeNode, MakePod

        wal_dir = tempfile.mkdtemp(prefix="scaleout-bench-")
        cluster = spawn_local_cluster(pod_shards=2, wal_dir=wal_dir)
        admin = RemoteHub(cluster.router_url, timeout=10.0,
                          retry_deadline=3.0)
        procs = []
        try:
            for i in range(nodes):
                admin.create_node(MakeNode().name(f"bn-{i}")
                                  .capacity(cpu="64", memory="256Gi",
                                            pods="440").obj())
            for i in range(n_replicas):
                procs.append(subprocess.Popen(
                    [sys.executable, "-m", "kubernetes_tpu",
                     "--hub", cluster.router_url, "--slices",
                     "--slice-heartbeat", "0.25",
                     "--id", f"bench-{i}", "--secure-port", "0"],
                    env=env, cwd=_repo,
                    stdout=subprocess.DEVNULL,
                    stderr=subprocess.DEVNULL))
            # wait for every replica to join the slice ring (startup —
            # JAX import included — must not count against pods/s)
            t0 = _time.monotonic()
            while _time.monotonic() - t0 < 120.0:
                try:
                    if len(admin.fabric_schedulers()) >= n_replicas \
                            and admin.fabric_sched_ring()["slots"]:
                        break
                except Exception:  # noqa: BLE001 — fabric warming up
                    pass
                _time.sleep(0.2)
            else:
                raise RuntimeError(
                    f"{n_replicas} replicas never joined the ring")
            _time.sleep(1.0)     # let the slice map settle
            t_start = _time.monotonic()
            for i in range(pods):
                admin.create_pod(MakePod().name(f"bp-{i}")
                                 .namespace(f"bns-{i % 32}")
                                 .req(cpu="50m").obj())
            deadline = _time.monotonic() + timeout_s
            bound = 0
            while _time.monotonic() < deadline:
                bound = sum(1 for p in admin.list_pods()
                            if p.spec.node_name)
                if bound >= pods:
                    break
                _time.sleep(0.1)
            elapsed = _time.monotonic() - t_start
            return {"replicas": n_replicas, "pods": pods,
                    "bound": bound, "elapsed_s": round(elapsed, 2),
                    "pods_per_sec": round(bound / elapsed, 1)
                    if elapsed > 0 else 0.0,
                    "complete": bound >= pods}
        finally:
            for p in procs:
                p.terminate()
            for p in procs:
                try:
                    p.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    p.kill()
            try:
                admin.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
            cluster.stop()

    single = run_arm(1)
    multi = run_arm(replicas)
    speedup = (multi["pods_per_sec"] / single["pods_per_sec"]
               if single["pods_per_sec"] else 0.0)
    # the 3x floor is a PARALLELISM claim: N CPU-bound scheduler
    # processes (plus the fabric's own) need at least that many cores
    # to demonstrate it. On a smaller box the arms still gate
    # correctness (every pod bound, both arms complete) but the
    # speedup number only measures contention — report it honestly
    # instead of failing hardware that can't show the win
    cores = os.cpu_count() or 1
    hardware_limited = cores < replicas + 1
    return {"metric": "scaleout", "single": single, "multi": multi,
            "speedup": round(speedup, 2), "floor": 3.0,
            "cores": cores, "hardware_limited": hardware_limited,
            "ok": (single["complete"] and multi["complete"]
                   and (speedup >= 3.0 or hardware_limited))}


def main() -> None:
    if "--readme-check" in sys.argv or "--readme-update" in sys.argv:
        # red-suite gate next to --chaos-smoke: published README numbers
        # must be the committed artifact's, mechanically
        ok = readme_check(write="--readme-update" in sys.argv)
        sys.exit(0 if ok else 1)
    if "--profile" in sys.argv:
        # per-phase attribution for the sub-10x offenders: the BENCH
        # artifact row the next VERDICT reads instead of guessing where
        # Daemonset/MixedChurn/DRA host time goes
        print(json.dumps(run_profile(smoke="--smoke" in sys.argv)))
        return
    if "--ab-scorer" in sys.argv:
        # learned-scoring quality gate: collection -> replay-train ->
        # paired hand-vs-learned A/B with one tie-break seed; artifact
        # rows carry the quality columns (incl. regret) for BENCH_r08+
        # files. --generations N additionally exercises N-1 learn-loop
        # refresh generations (retrain -> gate -> promote -> reload)
        scale = 0.1
        if "--scale" in sys.argv:
            scale = float(sys.argv[sys.argv.index("--scale") + 1])
        generations = 1
        if "--generations" in sys.argv:
            generations = int(
                sys.argv[sys.argv.index("--generations") + 1])
        r = run_ab_scorer(smoke="--smoke" in sys.argv, scale=scale,
                          generations=generations)
        print(json.dumps(r))
        if not r["latency_ok"]:
            print(f"ab-scorer: SchedulingBasic phase-total delta "
                  f"{r['workloads']['SchedulingBasic']['latency_delta_pct']}"
                  f"% exceeds {r['latency_budget_pct']:.0f}% budget",
                  file=sys.stderr)
        sys.exit(0 if r["latency_ok"] else 1)
    if "--scaleout" in sys.argv:
        # scale-out throughput gate (ISSUE 16 acceptance): N scheduler
        # processes over the slice ring must clear 3x one process's
        # pods/s, with the single-process arm measured fresh as the
        # no-regression reference
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        r = run_scaleout_bench(smoke="--smoke" in sys.argv)
        print(json.dumps(r))
        if r["hardware_limited"]:
            print(f"scaleout: only {r['cores']} core(s) for "
                  f"{r['multi']['replicas']} replica processes — "
                  f"speedup {r['speedup']}x measures contention, not "
                  f"scaling; gating on correctness only",
                  file=sys.stderr)
        elif not r["ok"]:
            print(f"scaleout: {r['multi']['pods_per_sec']} pods/s with "
                  f"{r['multi']['replicas']} replicas is "
                  f"{r['speedup']}x single ({r['single']['pods_per_sec']}"
                  f" pods/s); floor {r['floor']}x", file=sys.stderr)
        sys.exit(0 if r["ok"] else 1)
    if "--trace-overhead" in sys.argv:
        # red-suite gate next to --chaos-smoke: the always-on recorder
        # must stay under its <2% p50 cycle-time budget
        r = trace_overhead_smoke()
        print(json.dumps(r))
        if not r["ok"]:
            print(f"trace overhead over budget: recorder-on p50 "
                  f"{r['cycle_p50_on_ms']}ms vs off "
                  f"{r['cycle_p50_off_ms']}ms "
                  f"({r['delta_pct']:+.2f}% > {r['budget_pct']:.0f}%)",
                  file=sys.stderr)
        sys.exit(0 if r["ok"] else 1)
    if "--fanout-smoke" in sys.argv:
        # red-suite gate for the control-plane fabric (ISSUE 9): 10k
        # kubelet-analog reflectors through a 2-level relay tree with
        # chaos watch cuts on the upstream streams. Invariants: the hub
        # holds <= relay-count pod sockets, every cut heals by journal
        # RESUME (0 relists, exact event counts at every subscriber),
        # downstream reconnects are served from relay rings, slow
        # subscribers are evicted + recover, the binary codec carries
        # the storm in <= 1/3 the JSON bytes, and a steady-state drift
        # sentinel pass issues 0 full LISTs.
        env = dict(os.environ)
        env["PYTHONPATH"] = _repo + os.pathsep + env.get("PYTHONPATH", "")
        env.setdefault("JAX_PLATFORMS", "cpu")
        # both deployment modes, side by side in the artifact: the
        # in-process fabric (PR 9's tree) and the PROCESS-MODE fabric
        # (shard processes + stateless router + auto-discovered
        # relays, ISSUE 11) — the `procs` column proves the split
        # behaves identically where it matters (0 relists, exact
        # counts) and reports what it costs
        combined: dict = {}
        rc = 0
        for label, extra in (("inproc", []), ("procs", ["--procs"])):
            cmd = [sys.executable, "-m", "kubernetes_tpu.fabric.fanout",
                   *extra]
            if "--smoke" in sys.argv:
                cmd.append("--smoke")
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=1200, env=env, cwd=_repo)
            out = proc.stdout.strip().splitlines()
            try:
                combined[label] = json.loads(out[-1]) if out else \
                    {"ok": False, "error": "no output"}
            except ValueError:
                combined[label] = {"ok": False,
                                   "error": out[-1][:500]}
            if proc.returncode != 0:
                rc = proc.returncode or 1
                print(f"fanout smoke ({label}) FAILED\n"
                      f"{proc.stderr[-2000:]}", file=sys.stderr)
        combined["ok"] = all(combined[k].get("ok")
                             for k in ("inproc", "procs"))
        print(json.dumps(combined))
        sys.exit(rc if rc else (0 if combined["ok"] else 1))
    if "--scenario" in sys.argv:
        # replay one named regime (or a trace file) against the real
        # fabric with trace-time SLO + exactly-once gates; the printed
        # row carries the scenario SLO columns for BENCH_* artifacts
        from kubernetes_tpu.scenario.generators import generate
        from kubernetes_tpu.scenario.replay import replay_trace
        from kubernetes_tpu.scenario.trace import load_trace

        arg = sys.argv[sys.argv.index("--scenario") + 1]
        speed = (float(sys.argv[sys.argv.index("--speed") + 1])
                 if "--speed" in sys.argv else 3.0)
        seed = (int(sys.argv[sys.argv.index("--seed") + 1])
                if "--seed" in sys.argv else 0)
        tr = (load_trace(arg) if os.path.exists(arg)
              else generate(arg, seed=seed))
        rep = replay_trace(tr, speed=speed)
        print(json.dumps({
            "metric": "scenario_replay",
            "scenario": rep["name"],
            "speed": rep["speed"],
            "time_to_bind_p50_ms": rep["stats"]["time_to_bind_p50_ms"],
            "time_to_bind_p99_ms": rep["stats"]["time_to_bind_p99_ms"],
            "time_to_bind_max_ms": rep["stats"]["time_to_bind_max_ms"],
            "slo_ok": rep["slo"]["ok"],
            "audit_ok": rep["audit"]["ok"],
            "hardware_limited": rep["pacing"]["hardware_limited"],
            "report": rep,
        }))
        sys.exit(0 if rep["ok"] else 1)
    if "--overload" in sys.argv:
        # overload row: priority-pod time-to-bind under the best-effort
        # stampede regime (SLO judged over priority uids only — the
        # shed best-effort tail is the protection working), plus the
        # flow-control shed accounting from the overload storm
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from kubernetes_tpu.chaos import run_overload_storm
        from kubernetes_tpu.scenario.generators import generate
        from kubernetes_tpu.scenario.replay import replay_trace

        seed = (int(sys.argv[sys.argv.index("--seed") + 1])
                if "--seed" in sys.argv else 0)
        tr = generate("overload_stampede", seed=seed)
        rep = replay_trace(tr, speed=3.0)
        storm = run_overload_storm(seed=seed)
        print(json.dumps({
            "metric": "overload",
            "scenario": rep["name"],
            "speed": rep["speed"],
            "priority_pods": rep["slo_pods"],
            "pods": rep["pods"],
            "prio_time_to_bind_p50_ms":
                rep["stats"]["time_to_bind_p50_ms"],
            "prio_time_to_bind_p99_ms":
                rep["stats"]["time_to_bind_p99_ms"],
            "slo_ok": rep["slo"]["ok"],
            "audit_ok": rep["audit"]["ok"],
            "storm_shed_429s": storm["server_rejected"]["best-effort"],
            "storm_probe_p99_s": storm["probe_p99_s"],
            "storm_ok": storm["ok"],
            "hardware_limited": rep["pacing"]["hardware_limited"],
            "report": rep,
        }))
        sys.exit(0 if (rep["ok"] and storm["ok"]) else 1)
    if "--scenario-fuzz" in sys.argv:
        # EXPLICIT opt-in (not part of any battery): adversarial search
        # over regime parameter space under a wall-clock budget;
        # SLO-breaching traces are auto-filed as regression gates
        from kubernetes_tpu.scenario.fuzz import fuzz

        budget = (float(sys.argv[sys.argv.index("--budget") + 1])
                  if "--budget" in sys.argv else 120.0)
        seed = (int(sys.argv[sys.argv.index("--seed") + 1])
                if "--seed" in sys.argv else 0)
        objective = ("regret" if "--objective-regret" in sys.argv
                     else "p99")
        out_dir = os.path.join(_repo, "tests", "regression_traces")
        rep = fuzz(budget_s=budget, seed=seed, objective=objective,
                   out_dir=out_dir,
                   log=lambda s: print(s, file=sys.stderr, flush=True))
        print(json.dumps({
            "metric": "scenario_fuzz",
            "objective": rep["objective"],
            "budget_s": rep["budget_s"],
            "elapsed_s": rep["elapsed_s"],
            "candidates": rep["candidates"],
            "worst": rep["worst"],
            "filed": rep["filed"],
        }))
        sys.exit(0)
    if "--chaos-smoke" in sys.argv:
        # red-suite gate: the full storm battery — the smoke scenario
        # (call faults + watch cut + partition through the proxy), the
        # device-fault storm (fallback ladder + poison-pod quarantine),
        # and the 1k-pod crash storm (watch cuts + leader kill +
        # kill-and-restart). Invariants: every pod bound exactly once
        # (fencing + bind-once), zero daemon deaths, poison quarantined
        # with a hub Event, cache-hub converged.
        env = dict(os.environ)
        env["PYTHONPATH"] = _repo + os.pathsep + env.get("PYTHONPATH", "")
        env.setdefault("JAX_PLATFORMS", "cpu")
        proc = subprocess.run(
            [sys.executable, "-m", "kubernetes_tpu.chaos",
             "--storm", "all"],
            capture_output=True, text=True, timeout=1200, env=env,
            cwd=_repo)
        out = proc.stdout.strip().splitlines()
        print(out[-1] if out else '{"ok": false, "error": "no output"}')
        if proc.returncode != 0:
            print(f"chaos smoke FAILED\n{proc.stderr[-2000:]}",
                  file=sys.stderr)
            sys.exit(proc.returncode)
        # the trace-overhead gate rides along: one red-suite invocation
        # covers both "survives storms" and "the always-on recorder
        # stays under its <2% budget"
        r = trace_overhead_smoke()
        print(json.dumps(r))
        if not r["ok"]:
            print("trace overhead over budget (see --trace-overhead)",
                  file=sys.stderr)
        sys.exit(0 if r["ok"] else 1)
    smoke = "--smoke" in sys.argv
    scale = "0.02" if smoke else "1.0"
    # --regret: every workload row additionally carries the
    # per-placement regret_mean/regret_p99 quality columns (runs with a
    # throwaway alt-exporting trace file — opt-in because the alt
    # top_k + export I/O are a measured-perf change)
    regret_args = ["--regret"] if "--regret" in sys.argv else []
    results = {}
    headline = None
    env = dict(os.environ)
    env["PYTHONPATH"] = _repo + os.pathsep + env.get("PYTHONPATH", "")
    if not smoke and "--no-test-gate" not in sys.argv:
        # a round must not publish benchmark numbers over a red suite:
        # run the CI gate first and REFUSE on failure (the tests force
        # the virtual-CPU platform via tests/conftest.py, so this never
        # touches the TPU the measurements need)
        print("bench: running the test gate (pytest -q)...",
              file=sys.stderr)
        try:
            gate = subprocess.run(
                [sys.executable, "-m", "pytest", "tests/", "-q",
                 "--maxfail", "5"],
                capture_output=True, text=True, timeout=3600, env=env,
                cwd=_repo)
        except subprocess.TimeoutExpired:
            print("bench: TEST SUITE TIMED OUT — refusing to benchmark",
                  file=sys.stderr)
            sys.exit(1)
        if gate.returncode != 0:
            print("bench: TEST SUITE RED — refusing to benchmark\n"
                  + gate.stdout[-3000:] + "\n" + gate.stderr[-1500:],
                  file=sys.stderr)
            sys.exit(1)
        print("bench: test gate green", file=sys.stderr)
    for fn in BENCH_WORKLOAD_FNS:
        try:
            proc = subprocess.run(
                [sys.executable, "-m", "kubernetes_tpu.perf.run_one", fn,
                 "--scale", scale, *regret_args],
                capture_output=True, text=True, timeout=1800, env=env,
                cwd=_repo)
        except subprocess.TimeoutExpired:
            # a wedged workload must not kill the whole bench: report and
            # keep measuring the rest
            print(f"{fn}: TIMEOUT after 1800s", file=sys.stderr)
            continue
        if proc.returncode != 0:
            print(f"{fn}: FAILED\n{proc.stderr[-2000:]}", file=sys.stderr)
            continue
        r = json.loads(proc.stdout.strip().splitlines()[-1])
        print(f"{r['name']}: {r.get('pods_per_sec', 0):.1f} pods/s "
              f"(threshold {r['threshold']}, warm {r.get('warm_s')}s, "
              f"run {r.get('run_s')}s)", file=sys.stderr)
        short = r["name"].split("/")[0]
        if short in results:
            short = r["name"]   # variant rows (e.g. _QueueingHintsEnabled)
        results[short] = {k: r[k] for k in (
            "name", "pods_per_sec", "threshold", "vs_baseline", "passed",
            "pods_scheduled", "elapsed_s", "p50", "p90", "p95", "p99",
            "metrics", "quality")
            if k in r}
        if short == "SchedulingBasic":
            headline = r

    assert headline is not None, "SchedulingBasic must produce a result"
    print(json.dumps({
        "metric": "scheduling_throughput_5000nodes_production_path",
        "value": round(headline["pods_per_sec"], 1),
        "unit": "pods/sec",
        "vs_baseline": round(headline["pods_per_sec"] / BASELINE_PODS_PER_SEC,
                             2),
        "workloads": results,
    }))


if __name__ == "__main__":
    main()
