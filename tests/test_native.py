"""C++ host extension (kubernetes_tpu.native): parity with the pure-Python
engines it replaces. Skips cleanly when no toolchain built the module."""

import random

import pytest

from kubernetes_tpu.native import mod as native


def test_native_module_loads():
    # the environment bakes in g++ (SURVEY env notes); if this fails the
    # production heaps/parsers silently run the Python engines, which is
    # correct but slower — surface it
    assert native is not None


needs_native = pytest.mark.skipif(native is None, reason="no native build")


@needs_native
def test_quantity_parity_fuzz():
    from kubernetes_tpu.utils.quantity import parse_quantity
    import math

    rng = random.Random(7)
    suffixes = ["", "m", "k", "M", "G", "T", "Ki", "Mi", "Gi", "Ti", "u",
                "n", "E", "P", "Ei", "Pi"]
    for _ in range(2000):
        mant = rng.choice([
            str(rng.randint(0, 10**9)),
            f"{rng.randint(0, 10**6)}.{rng.randint(0, 999)}",
            f"{rng.randint(1, 999)}e{rng.randint(0, 6)}",
        ])
        s = mant + rng.choice(suffixes)
        want_milli = math.ceil(parse_quantity(s) * 1000)
        want_ceil = math.ceil(parse_quantity(s))
        if abs(want_milli) < 2**63:
            assert native.parse_milli(s) == want_milli, s
        if abs(want_ceil) < 2**63:
            assert native.parse_ceil(s) == want_ceil, s
    for bad in ["", "abc", "1.2.3", "12X", "e5", "1ee4", "5mi"]:
        with pytest.raises((ValueError, OverflowError)):
            native.parse_milli(bad)


@needs_native
def test_heap_parity_fuzz():
    """Random add/update/pop/delete stream: native KeyedHeap == Python
    engine, including update-in-place and duplicate sort keys."""
    from kubernetes_tpu.backend.heap import Heap

    class Item:
        def __init__(self, uid, a, b):
            self.uid, self.a, self.b = uid, a, b

    def mk_pair():
        py = Heap(lambda x: x.uid, lambda p, q: (p.a, p.b) < (q.a, q.b))
        nat = Heap(lambda x: x.uid, lambda p, q: False,
                   sort_key_fn=lambda x: (x.a, x.b))
        assert nat._nh is not None
        return py, nat

    rng = random.Random(11)
    py, nat = mk_pair()
    live = set()
    for step in range(4000):
        op = rng.random()
        if op < 0.5 or not live:
            uid = f"u{rng.randint(0, 200)}"
            it = Item(uid, rng.randint(0, 20) * 1.0, rng.random())
            py.add(it)
            nat.add(it)
            live.add(uid)
        elif op < 0.75:
            a, b = py.pop(), nat.pop()
            assert (a is None) == (b is None)
            if a is not None:
                # ties on (a, b) are broken arbitrarily but both engines
                # must agree on the sort key of what they surface
                assert (a.a, a.b) == (b.a, b.b)
                live.discard(a.uid)
                if a.uid != b.uid:       # tie: realign engines
                    py.delete(b.uid)
                    nat.delete(a.uid)
                    live.discard(b.uid)
        else:
            uid = rng.choice(sorted(live))
            a, b = py.delete(uid), nat.delete(uid)
            assert (a is None) == (b is None)
            live.discard(uid)
        assert len(py) == len(nat)
    while True:
        a, b = py.pop(), nat.pop()
        assert (a is None) == (b is None)
        if a is None:
            break
        assert (a.a, a.b) == (b.a, b.b)


@needs_native
def test_heap_degrades_on_exotic_sort_key():
    from kubernetes_tpu.backend.heap import Heap

    h = Heap(lambda x: x[0], lambda p, q: str(p[1]) < str(q[1]),
             sort_key_fn=lambda x: (x[1],))
    h.add(("a", 2.0))
    h.add(("b", "not-a-number"))       # degrade to the Python engine
    assert h._nh is None
    h.add(("c", 1.0))
    assert len(h) == 3
    assert h.pop()[0] == "c"           # less_fn ordering after degrade


@needs_native
def test_quantity_suffix_and_whitespace_edge_cases():
    """Review regressions: E/Ei are SUFFIXES unless digits follow the 'e';
    trailing whitespace parses like the Decimal path."""
    assert native.parse_ceil("1Ei") == 1 << 60
    assert native.parse_ceil("1E") == 10**18
    assert native.parse_ceil("2.5E") == 25 * 10**17
    assert native.parse_ceil("1e2") == 100
    assert native.parse_ceil(" 1 ") == 1
    assert native.parse_ceil("1\n") == 1
    # milli of 1Ei exceeds int64: native signals overflow, wrapper falls
    # back to the exact Decimal path
    with pytest.raises(OverflowError):
        native.parse_milli("1Ei")
    from kubernetes_tpu.utils.quantity import parse_bytes, parse_cpu_milli
    assert parse_bytes("1Ei") == 1 << 60
    assert parse_cpu_milli("1Ei") == (1 << 60) * 1000


@needs_native
def test_heap_degrades_on_wide_sort_key():
    from kubernetes_tpu.backend.heap import Heap

    h = Heap(lambda x: x[0], lambda p, q: p[1:] < q[1:],
             sort_key_fn=lambda x: x[1:])
    h.add(("a", 1.0, 1.0, 2.0))
    assert h._nh is None, "3-tuple sort key must degrade, not truncate"
    h.add(("b", 1.0, 1.0, 1.0))
    assert h.pop()[0] == "b"


# suite-tier discipline (tests/test_markers.py): area marker
pytestmark = pytest.mark.core
