"""Batched pipeline: as-if-serial commit semantics + driver entry points.

The key property (SURVEY.md §7.2 hard part 2): scheduling a batch in one
launch must produce the same placements as running the serial loop pod by
pod with an assume between pods (schedule_one.go:65 comment)."""

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.models.pipeline import (
    FILTER_PLUGINS,
    default_weights,
    schedule_batch_jit,
)
from kubernetes_tpu.models.testbed import build_cluster, make_pod
from kubernetes_tpu.ops.features import Capacities

CAPS = Capacities(nodes=16, pods=64)


def _run(mirror, pods, batch=8):
    return schedule_batch_jit(mirror.to_blobs(),
                              mirror.pack_batch_blobs(pods, batch),
                              mirror.well_known(), default_weights(), CAPS)


def test_batch_places_all_when_space():
    _, snap, mirror = build_cluster(4, caps=CAPS)
    pods = [make_pod(i) for i in range(6)]
    out = _run(mirror, pods)
    rows = np.asarray(out.node_row)
    assert (rows[:6] >= 0).all()
    assert (rows[6:] == -1).all()  # padding rows stay unscheduled
    assert (np.asarray(out.feasible_count)[:6] == 4).all()


def test_in_batch_resource_exhaustion():
    """Nodes fit exactly one big pod each: the batch must spread, and the
    (n+1)th big pod must be unschedulable — proves pod b sees pod b-1's
    commit inside one launch."""
    _, snap, mirror = build_cluster(3, caps=CAPS)
    pods = [make_pod(i, cpu="20", mem="100Gi") for i in range(4)]  # node: 32 cpu
    out = _run(mirror, pods)
    rows = np.asarray(out.node_row)[:4]
    assert (rows[:3] >= 0).all()
    assert len(set(rows[:3].tolist())) == 3, "one big pod per node"
    assert rows[3] == -1, "fourth big pod must not fit anywhere"
    # first-fail attribution: rejected by NodeResourcesFit
    fit_idx = FILTER_PLUGINS.index("NodeResourcesFit")
    assert np.asarray(out.reject_counts)[3, fit_idx] == 3


def test_in_batch_host_port_conflict():
    """Two pods with the same hostPort in one batch must not co-locate
    (as-if-serial NodePorts, types.go:1291)."""
    from kubernetes_tpu.api.objects import Container, ContainerPort

    _, snap, mirror = build_cluster(2, caps=CAPS)
    pods = []
    for i in range(3):
        p = make_pod(i)
        p.spec.containers[0].ports = [ContainerPort(host_port=8080)]
        pods.append(p)
    out = _run(mirror, pods)
    rows = np.asarray(out.node_row)[:3]
    assert rows[0] >= 0 and rows[1] >= 0
    assert rows[0] != rows[1], "same hostPort pods must spread"
    assert rows[2] == -1, "third pod: both nodes' port taken in-batch"
    ports_idx = FILTER_PLUGINS.index("NodePorts")
    assert np.asarray(out.reject_counts)[2, ports_idx] == 2


def test_matches_serial_oracle():
    """One launch over B pods == B launches of batch-size-1 with host-side
    re-sync between them."""
    pods = [make_pod(i, cpu="3", mem="1Gi") for i in range(10)]

    _, _, mirror = build_cluster(5, caps=CAPS)
    batched = np.asarray(_run(mirror, pods, batch=16).node_row)[:10]

    cache2, snap2, mirror2 = build_cluster(5, caps=CAPS)
    serial = []
    for p in pods:
        out = _run(mirror2, [p], batch=1)
        row = int(out.node_row[0])
        serial.append(row)
        if row >= 0:
            name = mirror2.name_of_row(row)
            p2 = p.clone()
            p2.spec.node_name = name
            cache2.assume_pod(p2)
            cache2.update_snapshot(snap2)
            mirror2.sync(snap2)
    assert batched.tolist() == serial


def test_graft_entry_compiles():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    assert (np.asarray(out.node_row) >= 0).all()


def test_dryrun_multichip():
    import __graft_entry__ as ge

    ge.dryrun_multichip(min(8, len(jax.devices())))
