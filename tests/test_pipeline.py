"""Batched pipeline: as-if-serial commit semantics + driver entry points.

The key property (SURVEY.md §7.2 hard part 2): scheduling a batch in one
launch must produce the same placements as running the serial loop pod by
pod with an assume between pods (schedule_one.go:65 comment)."""

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.models.pipeline import (
    FILTER_PLUGINS,
    default_weights,
    schedule_batch_jit,
)
from kubernetes_tpu.models.testbed import build_cluster, make_pod
from kubernetes_tpu.ops.features import Capacities

CAPS = Capacities(nodes=16, pods=64)


def _run(mirror, pods, batch=8):
    return schedule_batch_jit(mirror.to_blobs(),
                              mirror.pack_batch_blobs(pods, batch),
                              mirror.well_known(), default_weights(), CAPS)


def test_batch_places_all_when_space():
    _, snap, mirror = build_cluster(4, caps=CAPS)
    pods = [make_pod(i) for i in range(6)]
    out = _run(mirror, pods)
    rows = np.asarray(out.node_row)
    assert (rows[:6] >= 0).all()
    assert (rows[6:] == -1).all()  # padding rows stay unscheduled
    assert (np.asarray(out.feasible_count)[:6] == 4).all()


def test_in_batch_resource_exhaustion():
    """Nodes fit exactly one big pod each: the batch must spread, and the
    (n+1)th big pod must be unschedulable — proves pod b sees pod b-1's
    commit inside one launch."""
    _, snap, mirror = build_cluster(3, caps=CAPS)
    pods = [make_pod(i, cpu="20", mem="100Gi") for i in range(4)]  # node: 32 cpu
    out = _run(mirror, pods)
    rows = np.asarray(out.node_row)[:4]
    assert (rows[:3] >= 0).all()
    assert len(set(rows[:3].tolist())) == 3, "one big pod per node"
    assert rows[3] == -1, "fourth big pod must not fit anywhere"
    # first-fail attribution: rejected by NodeResourcesFit
    fit_idx = FILTER_PLUGINS.index("NodeResourcesFit")
    assert np.asarray(out.reject_counts)[3, fit_idx] == 3


def test_in_batch_host_port_conflict():
    """Two pods with the same hostPort in one batch must not co-locate
    (as-if-serial NodePorts, types.go:1291)."""
    from kubernetes_tpu.api.objects import Container, ContainerPort

    _, snap, mirror = build_cluster(2, caps=CAPS)
    pods = []
    for i in range(3):
        p = make_pod(i)
        p.spec.containers[0].ports = [ContainerPort(host_port=8080)]
        pods.append(p)
    out = _run(mirror, pods)
    rows = np.asarray(out.node_row)[:3]
    assert rows[0] >= 0 and rows[1] >= 0
    assert rows[0] != rows[1], "same hostPort pods must spread"
    assert rows[2] == -1, "third pod: both nodes' port taken in-batch"
    ports_idx = FILTER_PLUGINS.index("NodePorts")
    assert np.asarray(out.reject_counts)[2, ports_idx] == 2


def test_matches_serial_oracle():
    """One launch over B pods == B launches of batch-size-1 with host-side
    re-sync between them."""
    pods = [make_pod(i, cpu="3", mem="1Gi") for i in range(10)]

    _, _, mirror = build_cluster(5, caps=CAPS)
    batched = np.asarray(_run(mirror, pods, batch=16).node_row)[:10]

    cache2, snap2, mirror2 = build_cluster(5, caps=CAPS)
    serial = []
    for p in pods:
        out = _run(mirror2, [p], batch=1)
        row = int(out.node_row[0])
        serial.append(row)
        if row >= 0:
            name = mirror2.name_of_row(row)
            p2 = p.clone()
            p2.spec.node_name = name
            cache2.assume_pod(p2)
            cache2.update_snapshot(snap2)
            mirror2.sync(snap2)
    assert batched.tolist() == serial


def test_graft_entry_compiles():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    assert (np.asarray(out.node_row) >= 0).all()


def test_dryrun_multichip():
    import __graft_entry__ as ge

    ge.dryrun_multichip(min(8, len(jax.devices())))


def test_pct_nodes_to_score_knob():
    """percentageOfNodesToScore (schedule_one.go:668-694): with the knob
    set, selection happens among a rotating feasible subset; with it unset
    (or >=100) all nodes are scored. At small clusters the
    minFeasibleNodesToFind=100 floor keeps the knob a no-op."""
    caps = Capacities(nodes=256, pods=64)
    _, snap, mirror = build_cluster(200, caps=caps)
    pods = [make_pod(i) for i in range(8)]
    cb = mirror.to_blobs()
    pb = mirror.pack_batch_blobs(pods, 8)
    wk = mirror.well_known()
    w = default_weights()
    full = schedule_batch_jit(cb, pb, wk, w, caps)
    # floor: 200 * 50% = 100 = minFeasibleNodesToFind, but all 200 nodes
    # are feasible so the window truncates to the first 100 visited
    capped = schedule_batch_jit(cb, pb, wk, w, caps, pct_nodes=50)
    rows_f = np.asarray(full.node_row)
    rows_c = np.asarray(capped.node_row)
    assert (rows_c >= 0).all(), "capped run must still place every pod"
    # the capped run only ever reports <= k feasible nodes
    assert (np.asarray(capped.feasible_count) <= 100).all()
    assert (np.asarray(full.feasible_count) == 200).all()
    # explicit 0 = the reference's ADAPTIVE percentage (49% at 200 nodes
    # -> k=max(100, 98)=100): truncates exactly like pct=50 here
    from kubernetes_tpu.models.pipeline import ADAPTIVE_PCT
    adaptive = schedule_batch_jit(cb, pb, wk, w, caps,
                                  pct_nodes=ADAPTIVE_PCT)
    assert (np.asarray(adaptive.feasible_count) <= 100).all()
    # pct=100 never truncates: byte-identical placements to the default
    same = schedule_batch_jit(cb, pb, wk, w, caps, pct_nodes=100)
    np.testing.assert_array_equal(rows_f, np.asarray(same.node_row))
    np.testing.assert_array_equal(np.asarray(full.feasible_count),
                                  np.asarray(same.feasible_count))


def test_pct_nodes_rotates_start_index():
    """The visit window advances between pods (nextStartNodeIndex,
    schedule_one.go:620): with k=100 over 200 identical feasible nodes,
    consecutive pods must not all pick from the same leading window."""
    caps = Capacities(nodes=256, pods=64)
    _, snap, mirror = build_cluster(200, caps=caps)
    pods = [make_pod(i) for i in range(8)]
    out = schedule_batch_jit(mirror.to_blobs(),
                            mirror.pack_batch_blobs(pods, 8),
                            mirror.well_known(), default_weights(), caps,
                            pct_nodes=50)
    rows = np.asarray(out.node_row)
    # pod 0 picks inside nodes [0,100); pod 1's window starts at 100
    assert rows[0] < 100
    assert rows[1] >= 100
    # windows alternate [0,100) / [100,200) for the whole batch, and the
    # rotation wraps over the 200 REAL nodes (not the 256-row padded
    # bucket): 8 pods x 100 processed -> nextStartNodeIndex back at 0
    assert all(r < 100 for r in rows[0::2])
    assert all(r >= 100 for r in rows[1::2])
    assert int(out.pct_start) == 0


def test_pct_nodes_start_carries_across_launches():
    """The rotation survives ACROSS launches via BatchResult.pct_start (the
    Scheduler's persistent nextStartNodeIndex, schedule_one.go:620): a
    launch seeded with a prior launch's final offset opens its first
    window there, not at node 0. 150 valid nodes / k=100 makes the seeded
    window [start, start+100) unambiguous."""
    caps = Capacities(nodes=256, pods=64)
    _, snap, mirror = build_cluster(150, caps=caps)
    pods = [make_pod(i) for i in range(8)]
    cb = mirror.to_blobs()
    pb = mirror.pack_batch_blobs(pods, 8)
    wk = mirror.well_known()
    w = default_weights()
    out = schedule_batch_jit(cb, pb, wk, w, caps, pct_nodes=50)
    start1 = int(out.pct_start)
    assert start1 > 0
    out2 = schedule_batch_jit(cb, pb, wk, w, caps, pct_nodes=50,
                              pct_start=out.pct_start)
    rows2 = np.asarray(out2.node_row)
    # pod 0's window is the 100 feasible nodes visited from start1; when
    # that window doesn't wrap (start1 <= 50) every candidate is >= start1
    if start1 <= 50:
        assert rows2[0] >= start1, (start1, rows2[0])
    # and the seeded trajectory ends at a different offset
    assert int(out2.pct_start) != start1


# suite-tier discipline (tests/test_markers.py): area marker
import pytest  # noqa: E402
pytestmark = pytest.mark.core
