"""VolumeBinding dynamic provisioning + storage-capacity scoring
(reference: plugins/volumebinding volume_binding.go Score :464,
binder.go checkVolumeProvisions/hasEnoughCapacity; CSIStorageCapacity).
"""

from kubernetes_tpu.api.objects import (
    CSIStorageCapacity,
    Container,
    LABEL_HOSTNAME,
    LabelSelector,
    Node,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    PersistentVolumeClaim,
    PersistentVolumeClaimSpec,
    PersistentVolumeClaimVolumeSource,
    Pod,
    PodSpec,
    READ_WRITE_ONCE,
    ResourceRequirements,
    StorageClass,
    TopologySelectorLabelRequirement,
    TopologySelectorTerm,
    VOLUME_BINDING_WAIT,
    Volume,
)
from kubernetes_tpu.config.types import default_config
from kubernetes_tpu.hub import Hub
from kubernetes_tpu.ops.features import Capacities
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.testing.fakes import FakePVController


def mknode(name, labels=None):
    lab = {LABEL_HOSTNAME: name}
    lab.update(labels or {})
    return Node(metadata=ObjectMeta(name=name, labels=lab),
                spec=NodeSpec(),
                status=NodeStatus(allocatable={"cpu": "16",
                                               "memory": "32Gi",
                                               "pods": "110"}))


def mkpod(name, claim):
    return Pod(metadata=ObjectMeta(name=name),
               spec=PodSpec(
                   containers=[Container(name="c",
                                         resources=ResourceRequirements(
                                             requests={"cpu": "100m"}))],
                   volumes=[Volume(name=claim,
                                   persistent_volume_claim=(
                                       PersistentVolumeClaimVolumeSource(
                                           claim_name=claim)))]))


def mkpvc(name, sc, storage="10Gi"):
    return PersistentVolumeClaim(
        metadata=ObjectMeta(name=name),
        spec=PersistentVolumeClaimSpec(
            access_modes=[READ_WRITE_ONCE], storage_class_name=sc,
            requests={"storage": storage}))


def wait_sc(name="fast"):
    return StorageClass(metadata=ObjectMeta(name=name),
                        provisioner="csi.example.com",
                        volume_binding_mode=VOLUME_BINDING_WAIT)


def mkcap(name, sc, capacity, node=None):
    sel = None
    if node:
        sel = LabelSelector(match_labels={LABEL_HOSTNAME: node})
    return CSIStorageCapacity(metadata=ObjectMeta(name=name),
                              storage_class_name=sc,
                              node_topology=sel, capacity=capacity)


def mksched(hub):
    cfg = default_config()
    cfg.batch_size = 16
    return Scheduler(hub, cfg, caps=Capacities(nodes=16, pods=64))


def bound(hub, pod):
    return hub.get_pod(pod.metadata.uid).spec.node_name


def test_capacity_filter_rejects_insufficient_nodes():
    """hasEnoughCapacity: a node whose published capacity is below the
    claim's request cannot host the provisioning."""
    hub = Hub()
    sched = mksched(hub)
    hub.create_node(mknode("small"))
    hub.create_node(mknode("big"))
    hub.create_storage_class(wait_sc())
    hub.create_csi_capacity(mkcap("c-small", "fast", "5Gi", node="small"))
    hub.create_csi_capacity(mkcap("c-big", "fast", "100Gi", node="big"))
    hub.create_pvc(mkpvc("data", "fast", storage="10Gi"))
    p = mkpod("p", "data")
    hub.create_pod(p)
    sched.run_until_idle()
    assert bound(hub, p) == "big"


def test_no_capacity_objects_means_no_capacity_check():
    """A class whose driver publishes nothing skips the capacity check
    (the CSIDriver gate in the reference)."""
    hub = Hub()
    sched = mksched(hub)
    hub.create_node(mknode("n1"))
    hub.create_storage_class(wait_sc())
    hub.create_pvc(mkpvc("data", "fast", storage="10Ti"))
    p = mkpod("p", "data")
    hub.create_pod(p)
    sched.run_until_idle()
    assert bound(hub, p) == "n1"


def test_allowed_topologies_restrict_provisioning():
    """Class allowedTopologies gate provisioning to matching nodes
    (MatchTopologySelectorTerms)."""
    hub = Hub()
    sched = mksched(hub)
    hub.create_node(mknode("ssd-node", labels={"disk": "ssd"}))
    hub.create_node(mknode("hdd-node", labels={"disk": "hdd"}))
    sc = wait_sc()
    sc.allowed_topologies = [TopologySelectorTerm(
        match_label_expressions=[TopologySelectorLabelRequirement(
            key="disk", values=["ssd"])])]
    hub.create_storage_class(sc)
    hub.create_pvc(mkpvc("data", "fast"))
    p = mkpod("p", "data")
    hub.create_pod(p)
    sched.run_until_idle()
    assert bound(hub, p) == "ssd-node"


def test_capacity_score_prefers_tighter_fit():
    """Score = utilization through the default 0->0, 100->10 shape: with
    both nodes sufficient, the node whose published capacity yields the
    HIGHER utilization (tighter fit) wins (volume_binding.go:505)."""
    hub = Hub()
    sched = mksched(hub)
    hub.create_node(mknode("roomy"))
    hub.create_node(mknode("snug"))
    hub.create_storage_class(wait_sc())
    hub.create_csi_capacity(mkcap("c-roomy", "fast", "100Gi",
                                  node="roomy"))
    hub.create_csi_capacity(mkcap("c-snug", "fast", "12Gi", node="snug"))
    hub.create_pvc(mkpvc("data", "fast", storage="10Gi"))
    p = mkpod("p", "data")
    hub.create_pod(p)
    sched.run_until_idle()
    assert bound(hub, p) == "snug"


def test_dynamic_provisioning_end_to_end():
    """PreBind writes the selected-node annotation; the fake PV
    controller (test/integration/util/util.go:150) provisions and binds;
    the claim ends Bound to a node-pinned PV."""
    hub = Hub()
    FakePVController(hub)
    sched = mksched(hub)
    hub.create_node(mknode("n1"))
    hub.create_storage_class(wait_sc())
    hub.create_csi_capacity(mkcap("c1", "fast", "50Gi", node="n1"))
    hub.create_pvc(mkpvc("data", "fast"))
    p = mkpod("p", "data")
    hub.create_pod(p)
    sched.run_until_idle()
    assert bound(hub, p) == "n1"
    pvc = hub.get_pvc("default", "data")
    assert pvc.spec.volume_name == "provisioned-data"
    assert pvc.status.phase == "Bound"
    pv = hub.get_pv("provisioned-data")
    assert pv is not None
    assert pv.spec.claim_ref.name == "data"
    sel = pv.spec.node_affinity.node_selector_terms[0]
    assert sel.match_expressions[0].values == ["n1"]


def test_capacity_event_requeues_parked_pod():
    """A pod parked on 'not enough free storage' requeues when the driver
    publishes new capacity (the CSIStorageCapacity Add event upstream
    VolumeBinding registers)."""
    hub = Hub()
    clock = [1000.0]
    cfg = default_config()
    cfg.batch_size = 16
    sched = Scheduler(hub, cfg, caps=Capacities(nodes=16, pods=64),
                      now=lambda: clock[0])
    hub.create_node(mknode("n1"))
    hub.create_storage_class(wait_sc())
    hub.create_csi_capacity(mkcap("c1", "fast", "1Gi", node="n1"))
    hub.create_pvc(mkpvc("data", "fast", storage="10Gi"))
    p = mkpod("p", "data")
    hub.create_pod(p)
    sched.run_until_idle()
    assert bound(hub, p) in ("", None)
    # driver publishes more capacity -> requeue and schedule
    cap = [c for c in hub.list_csi_capacities()
           if c.metadata.name == "c1"][0]
    new = CSIStorageCapacity(metadata=cap.metadata,
                             storage_class_name="fast",
                             node_topology=cap.node_topology,
                             capacity="50Gi")
    hub.update_csi_capacity(new)
    for _ in range(4):
        sched.run_until_idle()
        clock[0] += 3.0
        sched.queue.flush_backoff_completed()
    sched.run_until_idle()
    assert bound(hub, p) == "n1"


# suite-tier discipline (tests/test_markers.py): area marker
import pytest  # noqa: E402
pytestmark = pytest.mark.core
