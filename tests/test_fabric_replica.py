"""Replicated state core (ISSUE 13): leader election, majority-ack log
replication for rv/fencing/ring, WAL log replay, leader-lease reads,
NotLeader redirects, and the retry-idempotency audit.

Everything here is in-thread (real HTTP, real Raft-lite RPCs, fast
election timeouts) and runs at seconds scale in tier-1; the kill -9
storm batteries live in ``chaos --storm state`` and the fanout procs
smoke (slow-marked / bench-gated).
"""

from __future__ import annotations

import time
import urllib.request

import pytest

from kubernetes_tpu.fabric.replica import (
    ReplicaClient,
    StateReplica,
)
from kubernetes_tpu.hub import NotLeader, Unavailable
from kubernetes_tpu.hubclient import RemoteHub
from kubernetes_tpu.hubserver import HubServer
from kubernetes_tpu.leaderelection import Lease

pytestmark = pytest.mark.fabric_replica

FAST = {"heartbeat_s": 0.05, "election_timeout_s": (0.25, 0.5)}


class _Trio:
    """Three in-thread replicas behind real HubServers."""

    def __init__(self, tmp_path, names=("state-0", "state-1", "state-2"),
                 pod_shards=("pods-0", "pods-1"),
                 log_compact_threshold: int = 4096):
        self.tmp = tmp_path
        self.names = list(names)
        self.pod_shards = list(pod_shards)
        self.compact = log_compact_threshold
        self.replicas: dict[str, StateReplica] = {}
        self.servers: dict[str, HubServer] = {}
        for n in self.names:
            self.replicas[n] = self._make(n)
            self.servers[n] = HubServer(self.replicas[n])
        self.peer_map = {n: self.servers[n].address for n in self.names}
        for n in self.names:
            self.replicas[n].set_peers(self.peer_map)
            self.servers[n].start()
        for n in self.names:
            self.replicas[n].start()

    def _make(self, name: str) -> StateReplica:
        return StateReplica(name, pod_shards=self.pod_shards,
                            wal_path=str(self.tmp / f"{name}.wal"),
                            log_compact_threshold=self.compact,
                            **FAST)

    def client(self) -> ReplicaClient:
        return ReplicaClient(list(self.peer_map.values()))

    def leader_name(self, timeout_s: float = 10.0) -> str:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            for n, r in self.replicas.items():
                if r.fabric_replica_status()["role"] == "leader":
                    return n
            time.sleep(0.05)
        raise AssertionError("no leader elected")

    def kill(self, name: str) -> None:
        """In-thread kill -9 analog: the server stops answering and the
        replica's ticker halts — no drain, no clean WAL close."""
        self.servers[name].stop()
        self.replicas[name].close()

    def restart(self, name: str) -> StateReplica:
        """Rebuild from the same WAL onto the SAME pinned port (the
        etcd static-bootstrap model the supervisor uses)."""
        port = int(self.peer_map[name].rsplit(":", 1)[1])
        r = self._make(name)
        r.set_peers(self.peer_map)
        srv = HubServer(r, port=port).start()
        r.start()
        self.replicas[name] = r
        self.servers[name] = srv
        return r

    def stop(self) -> None:
        for n in self.names:
            try:
                self.servers[n].stop()
            except Exception:  # noqa: BLE001 — already stopped
                pass
            try:
                self.replicas[n].close()
            except Exception:  # noqa: BLE001
                pass


@pytest.fixture()
def trio(tmp_path):
    t = _Trio(tmp_path)
    yield t
    t.stop()


def test_election_and_replicated_allocation(trio):
    client = trio.client()
    try:
        leader = trio.leader_name()
        # exactly one leader
        roles = [r.fabric_replica_status()["role"]
                 for r in trio.replicas.values()]
        assert roles.count("leader") == 1, roles
        # rv allocation is monotone through the quorum
        seen = [client.rv.next() for _ in range(8)]
        assert seen == sorted(seen) and len(set(seen)) == 8
        # ...and every replica converges to the same applied counter
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            rvs = {r.fabric_replica_status()["applied_rv"]
                   for r in trio.replicas.values()}
            if rvs == {seen[-1]}:
                break
            time.sleep(0.05)
        assert rvs == {seen[-1]}, rvs
        # a write addressed directly to a follower answers NotLeader
        # with a redirect hint that names the leader
        follower = next(n for n in trio.names if n != leader)
        direct = RemoteHub(trio.peer_map[follower], timeout=5.0)
        try:
            with pytest.raises(NotLeader) as ei:
                direct.rv.next()
            assert ei.value.leader_url == trio.peer_map[leader]
            assert ei.value.term >= 1
        finally:
            direct.close()
    finally:
        client.close()


def test_follower_reads_within_staleness_bound(trio):
    client = trio.client()
    try:
        leader = trio.leader_name()
        client.rv.next()
        follower = next(n for n in trio.names if n != leader)
        direct = RemoteHub(trio.peer_map[follower], timeout=5.0)
        try:
            # non-fencing reads serve from a follower inside the
            # leader-lease staleness bound...
            ring = direct.fabric_ring()
            assert ring["epoch"] == 1 and len(ring["slots"]) == 64
            assert "replicas" in direct.fabric_topology()
            # ...but fencing reads are leader-only: a lagging follower
            # answering epoch_of would un-fence a deposed scheduler
            with pytest.raises(NotLeader):
                direct.leases.epoch_of("kube-scheduler")
        finally:
            direct.close()
    finally:
        client.close()


def test_leader_kill_failover_no_rv_reuse_epoch_monotone(trio):
    client = trio.client()
    try:
        # epoch 1: acquire; some allocation traffic
        client.leases.update(Lease(name="kube-scheduler",
                                   holder_identity="a",
                                   renew_time=1.0, acquire_time=1.0),
                             None)
        assert client.leases.epoch_of("kube-scheduler") == 1
        before = [client.rv.next() for _ in range(6)]
        leader = trio.leader_name()
        trio.kill(leader)
        # the client rides out the election and keeps allocating —
        # never reusing or reissuing a committed revision
        after = [client.rv.next() for _ in range(6)]
        allrv = before + after
        assert len(set(allrv)) == len(allrv), "rv reused across failover"
        assert min(after) > max(before), "rv went backwards"
        # fencing state survived: the epoch is monotone, and a steal
        # through the NEW quorum bumps it exactly once
        assert client.leases.epoch_of("kube-scheduler") == 1
        client.leases.update(Lease(name="kube-scheduler",
                                   holder_identity="b",
                                   renew_time=2.0, acquire_time=2.0),
                             "a")
        assert client.leases.epoch_of("kube-scheduler") == 2
    finally:
        client.close()


def test_wal_replay_rejoins_log_consistent(trio):
    client = trio.client()
    try:
        for _ in range(5):
            client.rv.next()
        client.leases.update(Lease(name="kube-scheduler",
                                   holder_identity="x",
                                   renew_time=1.0, acquire_time=1.0),
                             None)
        ring = client.fabric_ring()
        assert client.fabric_set_ring(
            {"epoch": 2, "slots": ring["slots"]}, 1)
        leader = trio.leader_name()
        victim = next(n for n in trio.names if n != leader)
        trio.kill(victim)
        post_kill = [client.rv.next() for _ in range(4)]
        # restart from the WAL: the log replays, the leader catches the
        # rejoined follower up, and its applied state machine matches
        r2 = trio.restart(victim)
        deadline = time.monotonic() + 10
        caught = False
        while time.monotonic() < deadline:
            st = r2.fabric_replica_status()
            if st["applied_rv"] >= max(post_kill):
                caught = True
                break
            time.sleep(0.05)
        assert caught, r2.fabric_replica_status()
        assert r2._sm_ring["epoch"] == 2
        assert r2._sm_leases.epoch_of("kube-scheduler") == 1
        assert r2.fabric_replica_status()["role"] == "follower"
    finally:
        client.close()


def test_retry_budget_audit_cas_and_epoch_of_idempotent(trio):
    """The ISSUE-13 retry audit: under the replica protocol a
    timeout-retried ``fabric_set_ring`` CAS never double-applies (the
    duplicate answers False and the epoch bumps exactly once), repeated
    ``leases.epoch_of`` reads are stable, and a retried ``rv.next``
    burns a gap — a fresh value, never a reissued one."""
    client = trio.client()
    try:
        ring = client.fabric_ring()
        new_ring = {"epoch": 2, "slots": ring["slots"]}
        assert client.fabric_set_ring(new_ring, 1) is True
        # the blind retry of an already-committed CAS: False, and the
        # epoch did NOT bump twice
        assert client.fabric_set_ring(new_ring, 1) is False
        assert client.fabric_ring()["epoch"] == 2
        # epoch_of is a pure read: stable across retries
        client.leases.update(Lease(name="kube-scheduler",
                                   holder_identity="x",
                                   renew_time=1.0, acquire_time=1.0),
                             None)
        assert [client.leases.epoch_of("kube-scheduler")
                for _ in range(3)] == [1, 1, 1]
        # a retried rv.next draws a FRESH revision (gap-burn, the
        # journal's contract) — never the same one twice
        a, b = client.rv.next(), client.rv.next()
        assert b > a
    finally:
        client.close()


def test_follower_healthz_and_replica_metrics(trio):
    """ISSUE-13 telemetry satellite: followers answer /healthz with
    200-with-role (healthy, not degraded), /metrics carries the
    fabric_state_* gauges, and FleetView summary rows say who leads."""
    from kubernetes_tpu.telemetry.fleet import FleetView, parse_exposition

    leader = trio.leader_name()
    follower = next(n for n in trio.names if n != leader)
    with urllib.request.urlopen(trio.peer_map[follower] + "/healthz",
                                timeout=5.0) as resp:
        assert resp.status == 200
        body = resp.read().decode()
    assert body.startswith("ok") and "role=follower" in body
    with urllib.request.urlopen(trio.peer_map[follower] + "/metrics",
                                timeout=5.0) as resp:
        exp = parse_exposition(resp.read().decode())
    names = {s.name for s in exp.samples}
    assert {"fabric_state_replica_role", "fabric_state_term",
            "fabric_state_log_index",
            "fabric_state_commit_index"} <= names
    role_samples = [s for s in exp.samples
                    if s.name == "fabric_state_replica_role"]
    assert role_samples[0].labels["role"] == "follower"
    assert role_samples[0].labels["replica"] == follower
    # FleetView: every replica healthy, exactly one leader row
    fleet = FleetView([{"component": "state", "shard": n, "url": u}
                       for n, u in trio.peer_map.items()])
    summary = fleet.summary()
    assert summary["ok"], summary
    roles = [r["role"] for r in summary["endpoints"]]
    assert roles.count("leader") == 1
    assert roles.count("follower") == 2


def test_replica_client_discovers_full_set(trio):
    """A client pointed at ONE member learns the rest from the status
    verb and can therefore survive that member's death."""
    some_url = list(trio.peer_map.values())[0]
    client = ReplicaClient([some_url])
    try:
        rows = client.replica_status()
        assert len(rows) >= 1
        # after discovery, the full set is known
        rows = client.replica_status()
        assert len(rows) == 3, rows
        assert client.rv.next() >= 1
    finally:
        client.close()


def test_log_compaction_bounds_wal_and_snapshot_install(tmp_path):
    """The log and WAL must not grow with every rv the fleet ever
    drew: past the threshold, applied entries compact behind a
    state-machine snapshot (bounded memory + bounded WAL), and a
    follower whose WAL is GONE rejoins via leader snapshot install."""
    import os

    trio = _Trio(tmp_path, log_compact_threshold=24)
    client = trio.client()
    try:
        client.leases.update(Lease(name="kube-scheduler",
                                   holder_identity="x",
                                   renew_time=1.0, acquire_time=1.0),
                             None)
        for _ in range(120):
            client.rv.next()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if all(r.fabric_replica_status()["applied_rv"] == 120
                   for r in trio.replicas.values()):
                break
            time.sleep(0.05)
        for n, r in trio.replicas.items():
            st = r.fabric_replica_status()
            assert st["applied_rv"] == 120, (n, st)
            assert len(r._log) <= 30, \
                f"{n}: log not compacted ({len(r._log)} entries)"
            assert st["compact_floor"] > 0
            wal = os.path.getsize(str(tmp_path / f"{n}.wal"))
            assert wal < 200_000, f"{n}: WAL unbounded ({wal}B)"
        # a follower that lost its ENTIRE WAL (disk replaced) catches
        # up from the leader's snapshot, state machine included
        leader = trio.leader_name()
        victim = next(n for n in trio.names if n != leader)
        trio.kill(victim)
        os.remove(str(tmp_path / f"{victim}.wal"))
        for _ in range(30):
            client.rv.next()
        r2 = trio.restart(victim)
        deadline = time.monotonic() + 15
        caught = False
        while time.monotonic() < deadline:
            if r2.fabric_replica_status()["applied_rv"] >= 150:
                caught = True
                break
            time.sleep(0.05)
        assert caught, r2.fabric_replica_status()
        assert r2._floor_idx > 0, "rejoin must be a snapshot install"
        assert r2._sm_leases.epoch_of("kube-scheduler") == 1
    finally:
        client.close()
        trio.stop()


@pytest.mark.slow
def test_state_storm_small():
    """The replicated-state kill -9 battery at reduced scale (the full
    300-pod run is ``chaos --storm state`` inside bench.py
    --chaos-smoke's 'all')."""
    from kubernetes_tpu.chaos import run_state_storm

    r = run_state_storm(pods=80, nodes=8, timeout_s=180)
    assert r["ok"], r
    assert r["duplicate_binds"] == {}
    assert r["rv_reused"] == 0
    assert r["stale_epoch_fenced"]
    assert r["client_relists"] == 0
    assert r["rebalance"]["result"] in ("completed", "rolled_back")


def test_quorum_loss_parks_writes(trio, tmp_path):
    """Majority gone: the survivor parks writes (Unavailable) instead
    of answering from a minority — the failure-ladder's 'quorum loss'
    rung."""
    client = trio.client()
    try:
        client.rv.next()
        leader = trio.leader_name()
        others = [n for n in trio.names if n != leader]
        trio.kill(others[0])
        trio.kill(others[1])
        # give the survivor time to lose its lease
        time.sleep(1.0)
        short = ReplicaClient([trio.peer_map[leader]],
                              redirect_deadline_s=1.5)
        try:
            with pytest.raises(Unavailable):
                short.rv.next()
        finally:
            short.close()
    finally:
        client.close()
