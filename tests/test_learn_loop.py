"""The closed learning loop (ISSUE 14): export cursor tailing
(torn lines, rotation, restart resume), per-placement regret, the
replay-scoring promotion gate, the retrain daemon body (retrain →
gate → promote / reject / rollback), version auto-bump, and the
tier-1 one-cycle smoke: export → retrain → gate → promote →
scheduler hot-reload.
"""

import json
import os

import numpy as np
import pytest

from kubernetes_tpu.api.objects import (
    Container,
    LABEL_HOSTNAME,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    ResourceRequirements,
)
from kubernetes_tpu.config.types import Plugin, default_config
from kubernetes_tpu.hub import Hub
from kubernetes_tpu.learn import regret as RG
from kubernetes_tpu.learn.checkpoint import (
    load_checkpoint,
    next_version,
    save_checkpoint,
)
from kubernetes_tpu.learn.loop import (
    ExportCursor,
    LearnLoop,
    LoopConfig,
    WalTail,
)
from kubernetes_tpu.learn.replay import iter_placement_rows
from kubernetes_tpu.ops.features import Capacities
from kubernetes_tpu.ops.learned import NUM_FEATURES
from kubernetes_tpu.scheduler import Scheduler

pytestmark = pytest.mark.learn_loop


def _line(t, placements, v=3):
    return json.dumps({"v": v, "cycle": 1, "start": t, "pods": 1,
                       "phases_ms": {}, "placements": placements})


def _row(uid, node, score=100.0, alt=None, feat=None):
    r = {"pod": f"default/{uid}", "uid": uid, "node": node,
         "score": score}
    if alt is not None:
        r["alt"] = alt
    if feat is not None:
        r["feat"] = feat
    return r


def _write_lines(path, lines, mode="a"):
    with open(path, mode) as f:
        for ln in lines:
            f.write(ln + "\n")


def _feat(hot):
    f = [0.0] * NUM_FEATURES
    f[0 if hot else 1] = 1.0
    return f


def _linear_policy(idx, gain=100.0):
    """((W, b),) scoring feature ``idx`` at ``gain`` — a handcrafted
    deterministic policy for gate tests (no training involved)."""
    w = np.zeros((NUM_FEATURES, 1), np.float32)
    w[idx, 0] = gain
    return ((w, np.zeros((1,), np.float32)),)


# ------------------------------------------------------ export cursor


def test_cursor_consumes_only_complete_lines(tmp_path):
    path = str(tmp_path / "t.jsonl")
    _write_lines(path, [_line(1.0, [_row("a", "n1")])])
    with open(path, "a") as f:
        f.write('{"v": 3, "torn')         # a live writer mid-line
    cur = ExportCursor(path)
    lines = cur.read_lines()
    assert len(lines) == 1
    # the torn tail is NOT consumed; completing it yields exactly it
    with open(path, "a") as f:
        f.write('...": 1}\n')
    assert len(cur.read_lines()) == 1
    assert cur.read_lines() == []


def test_cursor_survives_rotation(tmp_path):
    path = str(tmp_path / "t.jsonl")
    _write_lines(path, [_line(float(i), [_row(f"u{i}", "n1")])
                        for i in range(3)])
    cur = ExportCursor(path)
    assert len(cur.read_lines()) == 3
    # two more lines land, then the keep-last-1 rotation happens before
    # the next poll: the cursor must drain the rotated remainder AND
    # the fresh file, no gaps, no duplicates
    _write_lines(path, [_line(3.0, [_row("u3", "n1")])])
    os.replace(path, path + ".1")
    _write_lines(path, [_line(4.0, [_row("u4", "n1")])], mode="w")
    lines = cur.read_lines()
    uids = [r["uid"] for r in iter_placement_rows(
        [json.loads(x) for x in lines])]
    assert uids == ["u3", "u4"]
    assert cur.missed_rotations == 0


def test_cursor_absent_live_file_never_reconsumes_rotated(tmp_path):
    """Daemon attached before the scheduler created the export (or
    after a failed rotation disabled it): repeated polls over a lone
    ``.1`` predecessor must consume it exactly once, not every poll."""
    path = str(tmp_path / "t.jsonl")
    _write_lines(path + ".1", [_line(float(i), [_row(f"u{i}", "n1")])
                               for i in range(3)])
    cur = ExportCursor(path)
    assert len(cur.read_lines()) == 3
    assert cur.read_lines() == []        # the duplicate-storm repro
    assert cur.read_lines() == []
    # the live file appearing later attaches cleanly from byte 0
    _write_lines(path, [_line(9.0, [_row("u9", "n1")])])
    assert len(cur.read_lines()) == 1
    # and a restart restores BOTH cursors (live + predecessor)
    cur2 = ExportCursor(path)
    cur2.restore(cur.state())
    assert cur2.read_lines() == []


def test_cursor_restart_resumes_without_duplicates(tmp_path):
    """The satellite: a daemon restart mid-tail restores its cursor
    from the persisted state and never re-reads consumed rows."""
    path = str(tmp_path / "t.jsonl")
    _write_lines(path, [_line(float(i), [_row(f"u{i}", "n1")])
                        for i in range(5)])
    cur = ExportCursor(path)
    assert len(cur.read_lines()) == 5
    st = cur.state()
    # "restart": a fresh cursor restored from the persisted state
    cur2 = ExportCursor(path)
    cur2.restore(st)
    assert cur2.read_lines() == []
    _write_lines(path, [_line(9.0, [_row("u9", "n1")])])
    assert len(cur2.read_lines()) == 1


def test_wal_tail_is_incremental_and_compaction_safe(tmp_path):
    """The daemon body stays O(new WAL events): a poll with no growth
    reads nothing, appended records merge in, and a compacted
    (shrunken) WAL re-merges idempotently from byte 0."""
    from kubernetes_tpu.utils.wire import to_wire

    wal = str(tmp_path / "hub.wal")

    def rec(uid):
        p = Pod(metadata=ObjectMeta(name=uid, uid=uid),
                spec=PodSpec(node_name="n1"))
        return json.dumps({"kind": "pods", "type": "delete",
                           "old": to_wire(p)})

    _write_lines(wal, [rec("U1")])
    t = WalTail(wal)
    ev, _dom = t.outcomes()
    assert ev == {"U1"}
    off = t.offset
    assert t.outcomes()[0] == {"U1"} and t.offset == off  # no re-read
    _write_lines(wal, [rec("U2")])
    assert t.outcomes()[0] == {"U1", "U2"} and t.offset > off
    # compaction rewrote the WAL smaller: re-merge from 0, keep the
    # union (apply_wal_record is idempotent)
    _write_lines(wal, [rec("U3")], mode="w")
    assert t.outcomes()[0] == {"U1", "U2", "U3"}


def test_wal_tail_disables_loudly_on_binary_wal(tmp_path):
    """A bin1 (fabric-default) WAL must disable outcome harvesting
    with an error — not silently yield no labels while re-reading the
    binary bytes every poll."""
    wal = str(tmp_path / "hub.wal")
    with open(wal, "wb") as f:
        f.write(b"\x00\x12\x08binary-frame-no-newline")
    t = WalTail(wal)
    assert t.outcomes() == (set(), {})
    assert t.disabled is True
    # subsequent polls are O(1): no re-sniff churn, still empty
    assert t.outcomes() == (set(), {})


# ------------------------------------------------------------- regret


def test_regret_zero_when_chosen_was_best_and_stuck():
    rows = [dict(_row("a", "n1", score=90.0,
                      alt=[["n2", 80.0], ["n3", 70.0]]), t=1.0)]
    recs = RG.compute_regret(rows)
    assert len(recs) == 1 and recs[0]["regret"] == 0.0


def test_regret_positive_on_eviction_and_better_alternative():
    rows = [dict(_row("a", "n1", score=90.0, alt=[["n2", 85.0]]), t=1.0),
            dict(_row("b", "n1", score=60.0, alt=[["n2", 80.0]]), t=1.0)]
    recs = RG.compute_regret(rows, evicted={"a"})
    by = {r["uid"]: r for r in recs}
    # a was evicted: its realized value collapses below the runner-up
    assert by["a"]["regret"] == pytest.approx(85.0 - 90.0 * 0.25)
    # b simply chose a worse node than its counterfactual
    assert by["b"]["regret"] == pytest.approx(20.0)
    s = RG.summarize_regret(recs)
    assert s["count"] == 2 and s["regret_mean"] > 0
    assert s["regret_p99"] >= s["regret_p50"]
    # rows without alternatives carry no counterfactual: excluded
    assert RG.summarize_regret(RG.compute_regret(
        [dict(_row("c", "n1", score=10.0), t=1.0)]))["count"] == 0


def _gate_rows():
    """40 held-out rows: 10 'hot' placements (feature 0) that were
    evicted AND landed in one crowded domain; 30 clean placements
    (feature 1) spread over distinct domains."""
    rows = []
    node_domain = {}
    evicted = set()
    for i in range(10):
        uid, node = f"bad{i}", f"h{i}"
        rows.append(dict(_row(uid, node, score=50.0, feat=_feat(True)),
                         t=float(i)))
        node_domain[node] = "hot"
        evicted.add(uid)
    for i in range(30):
        uid, node = f"ok{i}", f"c{i}"
        rows.append(dict(_row(uid, node, score=50.0, feat=_feat(False)),
                         t=float(10 + i)))
        node_domain[node] = f"dom-{i}"
    return rows, evicted, node_domain


def test_gate_promotes_candidate_that_avoids_bad_outcomes():
    rows, evicted, node_domain = _gate_rows()
    bad = _linear_policy(0)      # prefers the evicted+crowded rows
    good = _linear_policy(1)     # prefers the clean rows
    verdict = RG.gate_candidate(good, bad, rows, evicted, node_domain)
    assert verdict["promote"] is True
    assert set(verdict["wins"]) >= {"preemptions", "spread"}
    assert verdict["latency_ok"] is True
    # and the mirror image is rejected with the same metrics as losses
    verdict2 = RG.gate_candidate(bad, good, rows, evicted, node_domain)
    assert verdict2["promote"] is False
    assert set(verdict2["losses"]) >= {"preemptions", "spread"}


def test_gate_time_to_bind_axis_uses_anchor_rows():
    """Failed-attempt anchor rows (node None, no feat) establish
    first_seen: with them present, a policy preferring the slow-bound
    placements scores a worse weighted ttb p99 than one preferring the
    fast ones — the axis must discriminate, not permanently tie at 0."""
    rows = []
    for i in range(8):       # slow pods: first attempt at t, bind at t+9
        uid = f"slow{i}"
        # anchor rows deliberately AFTER the bound row (run_once
        # appends them to the holdout): _ttb_map must be
        # order-independent for the axis to discriminate
        rows.append(dict(_row(uid, f"s{i}", score=50.0,
                              feat=_feat(True)), t=float(i) + 9.0))
        rows.append({"uid": uid, "node": None, "t": float(i)})
    for i in range(8):       # fast pods: bind on the first attempt
        rows.append(dict(_row(f"fast{i}", f"f{i}", score=50.0,
                              feat=_feat(False)), t=20.0 + i))
    likes_slow = RG.replay_quality(_linear_policy(0), rows)
    likes_fast = RG.replay_quality(_linear_policy(1), rows)
    assert likes_slow["time_to_bind_p99_s"] \
        > likes_fast["time_to_bind_p99_s"]


def test_gate_bootstrap_promotes_without_live():
    rows, evicted, node_domain = _gate_rows()
    v = RG.gate_candidate(_linear_policy(1), None, rows, evicted,
                          node_domain)
    assert v["promote"] and v["bootstrap"]


# ------------------------------------------------------- loop daemon


def _loop_cfg(tmp_path, **kw):
    kw.setdefault("trace_path", str(tmp_path / "traces.jsonl"))
    kw.setdefault("staging_dir", str(tmp_path / "staging"))
    kw.setdefault("live_path", str(tmp_path / "live.json"))
    kw.setdefault("min_new_rows", 8)
    kw.setdefault("min_holdout_rows", 2)
    kw.setdefault("bc_epochs", 30)
    kw.setdefault("ft_epochs", 10)
    return LoopConfig(**kw)


def _trainable_lines(n, start=0.0):
    lines = []
    for i in range(n):
        hot = i % 2 == 0
        lines.append(_line(start + i, [
            _row(f"u{i}", f"n{i % 4}", score=50.0 + i,
                 alt=[[f"n{(i + 1) % 4}", 45.0 + i]],
                 feat=_feat(hot))]))
    return lines


def test_loop_waits_below_min_rows(tmp_path):
    cfg = _loop_cfg(tmp_path)
    _write_lines(cfg.trace_path, _trainable_lines(3))
    loop = LearnLoop(cfg)
    rep = loop.run_once()
    assert rep["status"] == "waiting" and rep["new_trainable"] == 3
    assert not os.path.exists(cfg.live_path)
    # cursor state persisted even while waiting: a restarted daemon
    # does not re-count the same rows (the satellite's no-duplicate
    # guarantee covers the whole loop, not just the cursor class)
    loop2 = LearnLoop(_loop_cfg(tmp_path))
    rep2 = loop2.run_once()
    assert rep2["new_rows"] == 0
    # ...but the sub-threshold window SURVIVED the restart (row spool +
    # persisted pending): one-shot `--once` invocations accumulate to
    # the retrain threshold instead of dropping every small window
    assert rep2["pending"] == 3 and rep2["buffer"] == 3
    _write_lines(cfg.trace_path, _trainable_lines(21, start=50.0))
    loop3 = LearnLoop(_loop_cfg(tmp_path))
    rep3 = loop3.run_once()
    assert rep3["pending"] == 24
    assert rep3["status"] in ("promoted", "rejected")


def test_loop_bootstrap_retrains_and_promotes(tmp_path):
    cfg = _loop_cfg(tmp_path)
    _write_lines(cfg.trace_path, _trainable_lines(24))
    loop = LearnLoop(cfg)
    rep = loop.run_once()
    assert rep["status"] == "promoted", rep
    assert rep["generation"] == 1 and rep["gate"]["bootstrap"]
    params, meta = load_checkpoint(cfg.live_path)
    assert meta["generation"] == 1 and meta["promoted"] is True
    assert meta["version"] == rep["version"] == 1
    assert "regret" in meta and "holdout_regret" in meta
    assert loop.metrics.promotions.value() == 1.0
    # the staged candidate survives next to the promoted copy
    assert os.path.exists(os.path.join(cfg.staging_dir,
                                       "scorer-g1.json"))
    # second round with fresh rows: version strictly advances (the
    # monotonic guarantee behind the checkpoint-version gauge)
    _write_lines(cfg.trace_path, _trainable_lines(24, start=100.0))
    rep2 = loop.run_once()
    assert rep2["generation"] == 2
    assert rep2["version"] == 2
    assert rep2["status"] in ("promoted", "rejected")


def test_loop_rejection_leaves_last_good_live(tmp_path, monkeypatch):
    """The satellite: a regressing candidate generation must leave
    last-good live and increment rejected_total."""
    cfg = _loop_cfg(tmp_path)
    _write_lines(cfg.trace_path, _trainable_lines(24))
    loop = LearnLoop(cfg)
    assert loop.run_once()["status"] == "promoted"
    live_before = open(cfg.live_path).read()

    # next generation regresses: force the gate's verdict (the gate
    # logic itself is covered by the crafted-policy tests above)
    def refuse(cand, live, rows, *a, **kw):
        return {"promote": False, "bootstrap": False, "wins": [],
                "losses": ["preemptions", "spread"], "latency_ok": True}

    monkeypatch.setattr("kubernetes_tpu.learn.regret.gate_candidate",
                        refuse)
    _write_lines(cfg.trace_path, _trainable_lines(24, start=100.0))
    rep = loop.run_once()
    assert rep["status"] == "rejected"
    assert loop.metrics.rejected.value() == 1.0
    # the live checkpoint is byte-identical: the regressing candidate
    # never reached the watcher's path
    assert open(cfg.live_path).read() == live_before
    # but the candidate WAS staged for inspection
    assert os.path.exists(os.path.join(cfg.staging_dir,
                                       "scorer-g2.json"))


def test_loop_rolls_back_on_post_promotion_regret_regression(tmp_path):
    """Generation 2 went live (displacing generation 1 into
    last-good); the traffic it schedules regresses on regret — the
    loop republishes last-good with a fresh version bump."""
    cfg = _loop_cfg(tmp_path, min_rollback_rows=4)
    loop = LearnLoop(cfg)
    # the promoted world: gen 2 serving live, gen 1 preserved
    save_checkpoint(os.path.join(cfg.staging_dir, "last-good.json"),
                    _linear_policy(1), meta={"version": 1,
                                             "generation": 1,
                                             "promoted": True})
    save_checkpoint(cfg.live_path, _linear_policy(0),
                    meta={"version": 2, "generation": 2,
                          "promoted": True})
    loop.state["generation"] = 2
    loop.state["version"] = 2
    loop.state["promoted"] = {"generation": 2, "version": 2,
                              "regret_mean": 0.0, "at": 0.0}
    loop._save_state()
    # traffic scheduled under generation 2 goes bad: every placement's
    # counterfactual beats the chosen node by a mile
    # ...at a LOW rate: each poll alone is under min_rollback_rows=4,
    # but evidence accumulates across polls until the bar is met
    _write_lines(cfg.trace_path, [_line(200.0 + i, [
        _row(f"r{i}", "n1", score=10.0, alt=[["n2", 90.0]])])
        for i in range(2)])
    rep0 = loop.run_once()
    assert "rollback" not in rep0       # 2 rows of evidence: not yet
    _write_lines(cfg.trace_path, [_line(210.0 + i, [
        _row(f"s{i}", "n1", score=10.0, alt=[["n2", 90.0]])])
        for i in range(3)])
    rep = loop.run_once()               # cumulative 5 >= 4: rolls back
    assert "rollback" in rep, rep
    assert loop.metrics.rollbacks.value() == 1.0
    _, meta = load_checkpoint(cfg.live_path)
    assert meta["rolled_back_from"] == 2
    assert meta["generation"] == 1      # last-good is serving again
    # republished with a FRESH version so the watcher's mtime/version
    # view moves forward, never backwards
    assert meta["version"] == 3
    assert loop.state["promoted"] is None
    # a restarted daemon (same state file) does not rollback again
    loop2 = LearnLoop(_loop_cfg(tmp_path, min_rollback_rows=4))
    assert loop2.state["promoted"] is None


# ------------------------------------------- version auto-bump (CLI)


def test_next_version_and_train_cli_autobump(tmp_path, capsys):
    from kubernetes_tpu.learn.__main__ import main

    out = str(tmp_path / "ck.json")
    assert next_version(out) == 1
    assert main(["train", "--synthetic", "64", "--out", out,
                 "--bc-epochs", "20", "--ft-epochs", "5"]) == 0
    v1 = json.loads(capsys.readouterr().out)["meta"]["version"]
    assert v1 == 1
    # the forgotten-flag case: retraining over an existing checkpoint
    # continues its sequence instead of republishing version 1
    assert main(["train", "--synthetic", "64", "--out", out,
                 "--bc-epochs", "20", "--ft-epochs", "5"]) == 0
    v2 = json.loads(capsys.readouterr().out)["meta"]["version"]
    assert v2 == 2
    assert next_version(out) == 3
    # an explicit flag still wins (operator override)
    assert main(["train", "--synthetic", "64", "--out", out,
                 "--bc-epochs", "20", "--ft-epochs", "5",
                 "--version", "9"]) == 0
    assert json.loads(capsys.readouterr().out)["meta"]["version"] == 9


# ----------------------------------------- tier-1 one-cycle smoke ---


def _mknode(i):
    return Node(metadata=ObjectMeta(name=f"node-{i}",
                                    labels={LABEL_HOSTNAME: f"node-{i}"}),
                status=NodeStatus(allocatable={"cpu": "8",
                                               "memory": "16Gi",
                                               "pods": "110"}))


def _mkpod(name):
    return Pod(metadata=ObjectMeta(name=name),
               spec=PodSpec(containers=[Container(
                   name="c", resources=ResourceRequirements(
                       requests={"cpu": "100m"}))]))


def test_one_cycle_closed_loop_smoke(tmp_path):
    """The ROADMAP-4 proof at seconds scale: a collection run exports
    v3 rows (features + alternatives), `learn loop --once` retrains
    from the tail, the gate promotes the candidate into the live path,
    and the RUNNING scheduler hot-reloads the promoted generation on
    its next cycle."""
    export = str(tmp_path / "traces.jsonl")
    live = str(tmp_path / "live.json")
    cfg = default_config()
    cfg.batch_size = 16
    cfg.trace_export_path = export
    cfg.trace_export_features = True
    cfg.trace_export_alts = True
    prof = cfg.profiles[0]
    prof.plugins.score.enabled.append(Plugin("LearnedScore", 1.0))
    prof.plugin_config["LearnedScore"] = {"checkpoint_path": live}
    hub = Hub()
    sched = Scheduler(hub, cfg, caps=Capacities(nodes=16, pods=64))
    try:
        mgr = sched._profile_cfg["default-scheduler"]["learned"]
        for i in range(4):
            hub.create_node(_mknode(i))
        for i in range(12):
            hub.create_pod(_mkpod(f"p{i}"))
        sched.run_until_idle()
        assert mgr.params() is None      # nothing published yet
        # the export carries v3 placement rows with feat + alt
        rows = [r for ln in (json.loads(x) for x in open(export)
                             if x.strip())
                for r in ln.get("placements", [])]
        placed = [r for r in rows if r["node"]]
        assert placed and all("alt" in r and "feat" in r
                              for r in placed)
        # at least one COUNTERFACTUAL (non-chosen) candidate exists;
        # the chosen node's own entry may ride along (it is the
        # single-basis chosen value on the auction path)
        assert any(any(nm != r["node"] for nm, _s in r["alt"])
                   for r in placed)

        # the daemon body: tail -> retrain -> gate -> promote
        loop = LearnLoop(LoopConfig(
            trace_path=export, staging_dir=str(tmp_path / "staging"),
            live_path=live, min_new_rows=8, min_holdout_rows=2,
            bc_epochs=30, ft_epochs=10))
        rep = loop.run_once()
        assert rep["status"] == "promoted", rep
        assert os.path.exists(live)

        # the running scheduler hot-reloads the promoted generation
        os.utime(live, (2e9, 2e9))       # coarse-clock mtime nudge
        for i in range(4):
            hub.create_pod(_mkpod(f"q{i}"))
        sched.run_until_idle()
        assert mgr.params() is not None
        assert mgr.version == rep["version"]
        assert mgr.generation == rep["generation"] == 1
        # /debug/scorer view: generation + the gate's regret summaries
        st = mgr.stats()
        assert st["generation"] == 1
        assert st["promoted"] is True and "holdout_regret" in st
        assert sched.metrics.learned_reloads.value(
            profile="default-scheduler", generation="1") >= 0.0
        assert sched.stats["device_fallbacks"] == 0
    finally:
        sched.close()
