"""Scheduler restart + churn integration (SURVEY §5.4 stateless-by-design,
VERDICT round-3 Weak #8): a brand-new scheduler over surviving hub state
must rebuild everything from replay — bound pods, pending pods,
nominations — and keep scheduling correctly under node/pod churn."""

import random

from kubernetes_tpu.api.objects import (
    Container,
    LABEL_HOSTNAME,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    ResourceRequirements,
)
from kubernetes_tpu.config.types import default_config
from kubernetes_tpu.hub import Hub
from kubernetes_tpu.ops.features import Capacities
from kubernetes_tpu.scheduler import Scheduler


class Clock:
    def __init__(self):
        self.t = 1000.0

    def now(self):
        return self.t


def mknode(i, cpu="8"):
    return Node(metadata=ObjectMeta(name=f"node-{i}",
                                    labels={LABEL_HOSTNAME: f"node-{i}"}),
                status=NodeStatus(allocatable={"cpu": cpu,
                                               "memory": "16Gi",
                                               "pods": "110"}))


def mkpod(name, cpu="500m", prio=0):
    return Pod(metadata=ObjectMeta(name=name),
               spec=PodSpec(containers=[Container(
                   name="c", resources=ResourceRequirements(
                       requests={"cpu": cpu, "memory": "128Mi"}))],
                   priority=prio))


def mksched(hub, clock):
    cfg = default_config()
    cfg.batch_size = 16
    return Scheduler(hub, cfg, caps=Capacities(nodes=16, pods=128),
                     now=clock.now)


def drain(sched, clock, rounds=6):
    for _ in range(rounds):
        sched.run_until_idle()
        clock.t += 3.0
        sched.queue.flush_backoff_completed()


def bound(hub, pod):
    p = hub.get_pod(pod.metadata.uid)
    return p.spec.node_name if p else None


def test_restart_replays_bound_and_pending_state():
    hub = Hub()
    clock = Clock()
    s1 = mksched(hub, clock)
    for i in range(3):
        hub.create_node(mknode(i))
    done = [mkpod(f"a{i}") for i in range(6)]
    for p in done:
        hub.create_pod(p)
    drain(s1, clock)
    assert all(bound(hub, p) for p in done)
    # pending pods created while the old scheduler is gone
    s1.close()
    pending = [mkpod(f"b{i}") for i in range(4)]
    for p in pending:
        hub.create_pod(p)

    s2 = mksched(hub, clock)
    # the replayed cache must already account the 6 bound pods
    assert s2.cache.pod_count() == 6
    drain(s2, clock)
    assert all(bound(hub, p) for p in pending)
    assert s2.stats["scheduled"] == 4, "only the new pods were scheduled"
    # capacity accounting survived: total cpu committed = 10 x 500m
    committed = sum(n["requested_milli_cpu"]
                    for n in s2.cache.dump()["nodes"].values())
    assert committed == 5000, f"replayed+new cpu accounting: {committed}m"
    assert s2.cache.assumed_pod_count() == 0
    s2.close()


def test_restart_preserves_nominations():
    """A preemptor nominated before the crash keeps its reservation: the
    new scheduler re-seeds the nominator from status.nominatedNodeName and
    no other pod steals the freed room."""
    hub = Hub()
    clock = Clock()
    s1 = mksched(hub, clock)
    # strict-alternation arm: the pipelined default fires the eviction
    # flush and re-dispatches the activated preemptor inside the same
    # drain, so "crashed after nominating but before binding" is only
    # constructible with next-wave activation off
    s1.preemption.activate_flushed = False
    hub.create_node(mknode(0, cpu="2"))
    low = [mkpod(f"low{i}", cpu="1") for i in range(2)]
    for p in low:
        hub.create_pod(p)
    drain(s1, clock)
    high = mkpod("high", cpu="2", prio=100)
    hub.create_pod(high)
    # one cycle: nominate + queue evictions, then "crash" BEFORE binding
    s1.run_until_idle()
    nominated = hub.get_pod(high.metadata.uid).status.nominated_node_name
    assert nominated == "node-0"
    s1.close()

    s2 = mksched(hub, clock)
    assert s2.nominator.node_of(high.metadata.uid) == "node-0", \
        "nominator re-seeded from status.nominatedNodeName on replay"
    # a greedy filler arrives; the nomination must hold the room
    filler = mkpod("filler", cpu="1500m")
    hub.create_pod(filler)
    drain(s2, clock)
    assert bound(hub, high) == "node-0", "nomination survived the restart"
    assert bound(hub, filler) == "", "reserved room not stolen"
    s2.close()


def test_hub_restart_replays_wal_and_scheduler_rebuilds(tmp_path):
    """The HUB dies this time, not the scheduler: a WAL-backed hub comes
    back from its journal file with stores, revision counter, and
    journal rings intact — a fresh scheduler over the reborn hub
    replays bound state and keeps scheduling, and a watcher holding a
    pre-restart rv resumes across the restart."""
    wal = str(tmp_path / "hub.wal")
    clock = Clock()
    h1 = Hub(wal_path=wal)
    s1 = mksched(h1, clock)
    for i in range(3):
        h1.create_node(mknode(i))
    done = [mkpod(f"a{i}") for i in range(6)]
    for p in done:
        h1.create_pod(p)
    drain(s1, clock)
    assert all(bound(h1, p) for p in done)
    resume_rv = h1.current_rv
    s1.close()
    h1.close()                       # the hub process dies

    h2 = Hub(wal_path=wal)           # ...and restarts over the same WAL
    assert h2.current_rv == resume_rv
    assert all(bound(h2, p) for p in done), "bindings survived the WAL"
    # a watcher with a pre-restart rv resumes: only post-restart events
    from kubernetes_tpu.hub import EventHandlers

    resumed = []
    h2.watch_pods(EventHandlers(
        on_add=lambda o: resumed.append(o.metadata.name)),
        since_rv=resume_rv)
    assert resumed == []
    s2 = mksched(h2, clock)
    assert s2.cache.pod_count() == 6, "cache rebuilt from WAL-replayed hub"
    pending = [mkpod(f"b{i}") for i in range(4)]
    for p in pending:
        h2.create_pod(p)
    assert sorted(resumed) == sorted(p.metadata.name for p in pending)
    drain(s2, clock)
    assert all(bound(h2, p) for p in pending)
    committed = sum(n["requested_milli_cpu"]
                    for n in s2.cache.dump()["nodes"].values())
    assert committed == 5000, f"replayed+new cpu accounting: {committed}m"
    s2.close()
    h2.close()


def test_scheduling_under_node_churn():
    """Nodes appear and disappear while pods flow: no pod lands on a
    deleted node, and everything schedulable eventually binds."""
    hub = Hub()
    clock = Clock()
    rng = random.Random(7)
    sched = mksched(hub, clock)
    nodes = {}
    for i in range(4):
        n = mknode(i)
        nodes[i] = n
        hub.create_node(n)
    pods = []
    next_node = 4
    for wave in range(6):
        for j in range(5):
            p = mkpod(f"w{wave}-p{j}", cpu="200m")
            pods.append(p)
            hub.create_pod(p)
        # churn: drop one node, add another
        if rng.random() < 0.7 and len(nodes) > 2:
            victim = rng.choice(list(nodes))
            hub.delete_node(nodes.pop(victim).metadata.uid)
        n = mknode(next_node)
        nodes[next_node] = n
        hub.create_node(n)
        next_node += 1
        drain(sched, clock, rounds=2)
    drain(sched, clock)
    # bound-to-since-deleted-node is legal (the API keeps the stale binding;
    # that's the kubelet's problem in the reference) — only placement
    # completeness and cache/hub agreement are asserted here
    placed = sum(1 for p in pods if bound(hub, p))
    assert placed == len(pods), f"{placed}/{len(pods)} placed under churn"
    # the scheduler's view agrees with the hub: everything except
    # deleted-NODE stragglers (pods bound to since-deleted nodes keep the
    # node alive in the cache, like the reference) must match — including
    # pod existence AND placement lines
    problems = [x for x in sched.cache.compare_with_hub(hub)
                if not (x.startswith("node ")
                        and "in cache but not in apiserver" in x)]
    assert not problems, problems
    sched.close()


# suite-tier discipline (tests/test_markers.py): area marker
import pytest  # noqa: E402
pytestmark = pytest.mark.core
