"""Differential fuzz for the fabric binary codec (ISSUE 9 satellite).

Property: for ANY API object, the binary wire and the JSON wire agree —
``codec.decode(codec.encode(x))`` equals
``wire.from_wire(json.loads(json.dumps(wire.to_wire(x))))`` equals
``x``. The two codecs share nothing but the class registry, so a
divergence here is a positional-field bug (bin) or a tag bug (JSON)
before it becomes silent wire corruption.

Runs every negotiated kind (Pod, Node, PodGroup, ResourceClaim, Event,
Lease and the rest of the registry's hub-stored kinds) over randomized
objects: 200 seeds in tier-1, 1000 more under ``-m slow``.

The size claim is pinned too: the binary wire must carry a
representative event corpus in ≤ 1/3 the JSON bytes (the --fanout-smoke
wire_ratio gate's unit-test twin).
"""

from __future__ import annotations

import json
import random
import string

import pytest

from kubernetes_tpu.api.objects import (
    DeviceAllocationResult,
    DeviceConstraint,
    DeviceRequest,
    DeviceSelector,
    Event,
    ObjectMeta,
    Pod,
    PodCondition,
    PodGroup,
    ResourceClaim,
    ResourceClaimSpec,
    ResourceClaimStatus,
    AllocationResult,
)
from kubernetes_tpu.fabric import codec
from kubernetes_tpu.leaderelection import Lease
from kubernetes_tpu.testing import MakeNode, MakePod
from kubernetes_tpu.utils.wire import from_wire, to_wire

# strings exercising escaping, unicode, and the fixstr/str8+ boundary
_NASTY = ["", "a", 'quo"te', "back\\slash", "new\nline", "tab\there",
          "ünïcødé-✓", "x" * 31, "y" * 32, "z" * 300,
          "{\"json\": [1,2]}"]


def _rs(rng: random.Random, n: int = 12) -> str:
    if rng.random() < 0.25:
        return rng.choice(_NASTY)
    return "".join(rng.choices(string.ascii_lowercase + string.digits
                               + "-./_", k=rng.randint(1, n)))


def _labels(rng: random.Random) -> dict:
    return {_rs(rng): _rs(rng) for _ in range(rng.randint(0, 4))}


def _meta(rng: random.Random) -> ObjectMeta:
    return ObjectMeta(name=_rs(rng), namespace=_rs(rng, 8),
                      labels=_labels(rng), annotations=_labels(rng),
                      creation_timestamp=rng.random() * 2e9,
                      resource_version=rng.randint(0, 2**48))


def _pod(rng: random.Random) -> Pod:
    mk = MakePod().name(_rs(rng)).namespace(_rs(rng, 8)) \
        .labels(_labels(rng))
    if rng.random() < 0.8:
        mk = mk.req(cpu=f"{rng.randint(1, 4000)}m",
                    memory=f"{rng.randint(1, 64)}Gi")
    if rng.random() < 0.3:
        mk = mk.priority(rng.randint(-100, 10**9))
    if rng.random() < 0.3:
        mk = mk.node_name(_rs(rng))
    if rng.random() < 0.25:
        mk = mk.toleration(key=_rs(rng), value=_rs(rng),
                           effect="NoSchedule")
    if rng.random() < 0.2:
        mk = mk.node_affinity_in(_rs(rng), [_rs(rng), _rs(rng)])
    if rng.random() < 0.2:
        mk = mk.pod_anti_affinity("zone", {_rs(rng): _rs(rng)})
    if rng.random() < 0.2:
        mk = mk.spread_constraint(rng.randint(1, 5), "zone",
                                  "DoNotSchedule",
                                  {_rs(rng): _rs(rng)})
    pod = mk.obj()
    pod.metadata.annotations = _labels(rng)
    if rng.random() < 0.3:
        pod.status.phase = rng.choice(["Pending", "Running", "Failed"])
        pod.status.nominated_node_name = _rs(rng)
        pod.status.conditions = [PodCondition(
            type="PodScheduled",
            status=rng.choice(["True", "False", "Unknown"]),
            reason=_rs(rng), message=_rs(rng, 40),
            last_transition_time=rng.random() * 2e9)]
    if rng.random() < 0.2:
        pod.status.resource_claim_statuses = _labels(rng)
    return pod


def _node(rng: random.Random):
    mk = MakeNode().name(_rs(rng)).capacity(
        cpu=str(rng.randint(1, 256)),
        memory=f"{rng.randint(1, 2048)}Gi",
        pods=str(rng.randint(1, 500)))
    for k, v in _labels(rng).items():
        mk = mk.label(k, v)
    if rng.random() < 0.3:
        mk = mk.taint(_rs(rng), _rs(rng), "NoSchedule")
    if rng.random() < 0.15:
        mk = mk.unschedulable()
    if rng.random() < 0.2:
        mk = mk.image(_rs(rng, 30), rng.randint(0, 2**40))
    return mk.obj()


def _pod_group(rng: random.Random) -> PodGroup:
    return PodGroup(metadata=_meta(rng),
                    min_member=rng.randint(1, 64),
                    queue=_rs(rng, 8), priority=rng.randint(-10, 10),
                    schedule_timeout_seconds=rng.random() * 300)


def _claim(rng: random.Random) -> ResourceClaim:
    reqs = [DeviceRequest(
        name=_rs(rng, 6), device_class_name=_rs(rng, 8),
        count=rng.randint(1, 8),
        selectors=[DeviceSelector(cel_expression=_rs(rng, 40))
                   for _ in range(rng.randint(0, 2))],
        admin_access=rng.random() < 0.1)
        for _ in range(rng.randint(0, 3))]
    cons = [DeviceConstraint(requests=[r.name for r in reqs],
                             match_attribute=_rs(rng))
            for _ in range(rng.randint(0, 1))]
    status = ResourceClaimStatus()
    if rng.random() < 0.4:
        status = ResourceClaimStatus(
            allocation=AllocationResult(
                node_name=_rs(rng),
                devices=[DeviceAllocationResult(
                    request=_rs(rng, 6), driver=_rs(rng, 8),
                    pool=_rs(rng, 6), device=_rs(rng, 6))]),
            reserved_for=[_rs(rng) for _ in range(rng.randint(0, 3))])
    return ResourceClaim(metadata=_meta(rng),
                         spec=ResourceClaimSpec(device_requests=reqs,
                                                constraints=cons),
                         status=status)


def _event(rng: random.Random) -> Event:
    return Event(metadata=_meta(rng), ref_kind=_rs(rng, 10),
                 ref_key=f"{_rs(rng, 8)}/{_rs(rng, 8)}",
                 reason=_rs(rng), message=_rs(rng, 60),
                 count=rng.randint(1, 10**6))


def _lease(rng: random.Random) -> Lease:
    return Lease(name=_rs(rng), holder_identity=_rs(rng),
                 lease_duration_seconds=rng.random() * 60,
                 acquire_time=rng.random() * 2e9,
                 renew_time=rng.random() * 2e9,
                 lease_transitions=rng.randint(0, 1000),
                 epoch=rng.randint(0, 2**40))


_GENS = (_pod, _node, _pod_group, _claim, _event, _lease)


def _one_round(seed: int) -> None:
    rng = random.Random(seed)
    for gen in _GENS:
        obj = gen(rng)
        # the JSON path (the wire the hub already speaks)
        via_json = from_wire(json.loads(json.dumps(to_wire(obj))))
        # the binary path
        blob = codec.encode(obj)
        via_bin = codec.decode(blob)
        assert via_bin == obj, f"bin1 diverged on {gen.__name__}[{seed}]"
        assert via_json == obj, f"JSON diverged on {gen.__name__}[{seed}]"
        assert via_bin == via_json


@pytest.mark.fabric
@pytest.mark.parametrize("seed", range(200))
def test_codec_differential_tier1(seed):
    _one_round(seed)


@pytest.mark.fabric
@pytest.mark.slow
@pytest.mark.parametrize("seed", range(200, 1200))
def test_codec_differential_slow(seed):
    _one_round(seed)


@pytest.mark.fabric
def test_codec_event_dicts_roundtrip():
    """The watch wire's envelope shape (event dicts wrapping objects,
    sync markers, keepalives) — what actually crosses the stream."""
    rng = random.Random(7)
    pod = _pod(rng)
    for env in ({"type": "add", "rv": 12, "old": None, "new": pod},
                {"type": "delete", "rv": 2**33, "kind": "pods",
                 "old": pod, "new": None},
                {"synced": True, "rv": 99},
                {}):
        assert codec.decode(codec.encode(env)) == env


@pytest.mark.fabric
def test_codec_wire_size_at_least_3x_smaller():
    """The fanout smoke's wire_ratio gate, unit-sized: a representative
    pod/node event corpus must shrink >= 3x on the binary wire."""
    rng = random.Random(11)
    jb = bb = 0
    for i in range(60):
        obj = (_pod if i % 2 else _node)(rng)
        ev = {"type": "add", "rv": i + 1, "old": None, "new": obj}
        jb += len(json.dumps(to_wire(ev)).encode()) + 1
        bb += len(codec.frame(codec.encode(ev)))
    assert jb / bb >= 3.0, f"ratio {jb / bb:.2f} < 3.0 ({jb}/{bb})"


@pytest.mark.fabric
def test_codec_rejects_unknown_kind_and_trailing_bytes():
    class NotRegistered:
        pass

    with pytest.raises(TypeError):
        codec.encode(NotRegistered())
    with pytest.raises(ValueError):
        codec.decode(codec.encode({"a": 1}) + b"\x00")


@pytest.mark.fabric
def test_codec_scalar_edge_values():
    for v in (0, 1, 127, 128, 255, 256, 65535, 65536, 2**32 - 1, 2**32,
              2**63 - 1, -1, -32, -33, -128, -129, -2**31, -2**63,
              0.0, -0.5, 1e300, True, False, None,
              [], {}, set(), b"", b"\x00\xff" * 200):
        assert codec.decode(codec.encode(v)) == v
