"""Chaos invariant suite: the scheduler + hub client under injected
faults (kubernetes_tpu/chaos.py). Every scenario asserts the storm
invariants from the fault model (README "Fault model"):

* no double-bind (the hub's bind-once Conflict + informer reconciliation),
* no lost or wedged pod (degraded mode parks with backoff, never drops),
* cache–hub convergence after the storm (reflector relist diff),
* leader failover within the lease duration when the holder is cut off.
"""

import threading
import time

import pytest

from kubernetes_tpu.chaos import ChaosConfig, ChaosHub, ChaosProxy
from kubernetes_tpu.config.types import default_config
from kubernetes_tpu.hub import EventHandlers, Hub, Unavailable
from kubernetes_tpu.hubclient import RemoteHub
from kubernetes_tpu.hubserver import HubServer
from kubernetes_tpu.leaderelection import LeaderElector
from kubernetes_tpu.ops.features import Capacities
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.testing import MakeNode, MakePod
from kubernetes_tpu.utils.backoff import Backoff, RetryBudget, retry_call

pytestmark = pytest.mark.chaos


# ------------------------------------------------------------------ utils


def test_backoff_decorrelated_jitter_bounds():
    import random

    bo = Backoff(base=0.05, cap=1.0, rng=random.Random(1))
    prev = 0.05
    for _ in range(50):
        s = bo.next()
        assert 0.05 <= s <= min(1.0, max(prev * 3, 0.05) + 1e-9)
        prev = s
    bo.reset()
    assert bo.next() <= 0.15 + 1e-9   # back to base * 3 ceiling


def test_retry_budget_exhausts_and_refills():
    clock = [0.0]
    budget = RetryBudget(budget=3.0, refill_per_sec=1.0,
                         now=lambda: clock[0])
    assert all(budget.try_spend() for _ in range(3))
    assert not budget.try_spend()          # dry: fail fast
    clock[0] += 2.0
    assert budget.try_spend()              # refilled 2 tokens
    assert budget.try_spend()
    assert not budget.try_spend()


def test_retry_call_deadline_and_success():
    clock = [0.0]
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("boom")
        return "ok"

    assert retry_call(flaky, retry_on=(OSError,), deadline=10.0,
                      sleep=lambda s: clock.__setitem__(0, clock[0] + s),
                      now=lambda: clock[0]) == "ok"
    calls.clear()
    with pytest.raises(OSError):
        retry_call(lambda: (_ for _ in ()).throw(OSError("x")),
                   retry_on=(OSError,), deadline=0.0,
                   sleep=lambda s: None, now=lambda: clock[0])


# -------------------------------------------------------------- ChaosHub


def test_chaoshub_injects_and_heals():
    hub = Hub()
    chub = ChaosHub(hub, ChaosConfig(seed=3, call_error_rate=1.0))
    with pytest.raises(Unavailable):
        chub.create_node(MakeNode().name("n").obj())
    chub.set_fault(call_error_rate=0.0)
    chub.create_node(MakeNode().name("n").obj())
    assert hub.get_node("n") is not None
    chub.partition_for(30.0)
    with pytest.raises(Unavailable):
        chub.list_pods()
    with pytest.raises(Unavailable):
        chub.leases.get("x")               # leases are RPCs too
    chub.heal()
    assert chub.list_pods() == []
    stats = chub.chaos_stats()
    assert stats["injected_errors"] == 3
    assert stats["calls_seen"] >= 5


def test_chaoshub_deterministic_by_seed():
    def draw_sequence(seed):
        hub = Hub()
        chub = ChaosHub(hub, ChaosConfig(seed=seed, call_error_rate=0.5))
        out = []
        for _ in range(40):
            try:
                chub.list_pods()
                out.append(0)
            except Unavailable:
                out.append(1)
        return out

    assert draw_sequence(11) == draw_sequence(11)
    assert draw_sequence(11) != draw_sequence(12)


# ------------------------------------------------------------ ChaosProxy


@pytest.fixture()
def proxied_hub():
    hub = Hub()
    server = HubServer(hub).start()
    proxy = ChaosProxy(server.address, config=ChaosConfig(seed=5)).start()
    client = RemoteHub(proxy.address, timeout=10.0, retry_deadline=5.0,
                       retry_base=0.01, retry_cap=0.1)
    yield hub, proxy, client
    client.close()
    proxy.stop()
    server.stop()


def test_idempotent_calls_retry_through_flaky_proxy(proxied_hub):
    hub, proxy, client = proxied_hub
    hub.create_node(MakeNode().name("n1").obj())
    proxy.set_fault(call_error_rate=0.5)
    for _ in range(10):                    # each likely hits ≥1 injected 503
        assert client.get_node("n1") is not None
    assert client.resilience_stats()["retries"] > 0
    assert proxy.stats["injected_errors"] > 0


def test_nonidempotent_calls_fail_fast_as_unavailable(proxied_hub):
    hub, proxy, client = proxied_hub
    proxy.set_fault(call_error_rate=1.0)
    before = client.resilience_stats()["retries"]
    with pytest.raises(Unavailable):
        client.create_pod(MakePod().name("p").obj())
    assert client.resilience_stats()["retries"] == before  # no blind replay
    assert not client.connected
    proxy.set_fault(call_error_rate=0.0)
    p = MakePod().name("p").obj()
    client.create_pod(p)
    assert client.connected
    assert hub.get_pod(p.metadata.uid) is not None


def test_watch_cuts_reconnect_without_loss_or_dupes(proxied_hub):
    hub, proxy, client = proxied_hub
    proxy.set_fault(watch_cut_every=3)     # die every third event
    added = []
    client.watch_nodes(EventHandlers(
        on_add=lambda o: added.append(o.metadata.name)))
    names = [f"n-{i}" for i in range(12)]
    for name in names:
        hub.create_node(MakeNode().name(name).obj())
        time.sleep(0.02)
    deadline = time.time() + 20
    while time.time() < deadline and len(set(added)) < len(names):
        time.sleep(0.05)
    assert sorted(set(added)) == sorted(names), \
        "every add must survive the cuts"
    assert len(added) == len(names), "relist must not duplicate adds"
    assert client.resilience_stats()["watch_reconnects"] > 0
    assert proxy.stats["injected_cuts"] > 0


def test_initial_watch_survives_hub_binding_late():
    """The first connect() is guarded: a client whose hub isn't listening
    yet must come up once the hub does (scheduler startup vs hub race)."""
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    client = RemoteHub(f"http://127.0.0.1:{port}", timeout=10.0,
                       retry_deadline=8.0, retry_base=0.02, retry_cap=0.2)
    hub = Hub()
    hub.create_node(MakeNode().name("late").obj())
    seen = []
    err = []

    def start_watch():
        try:
            client.watch_nodes(EventHandlers(
                on_add=lambda o: seen.append(o.metadata.name)))
        except Exception as e:  # noqa: BLE001
            err.append(e)

    t = threading.Thread(target=start_watch, daemon=True)
    t.start()
    time.sleep(0.5)                        # client is retrying against ECONNREFUSED
    server = HubServer(hub, port=port).start()
    try:
        t.join(timeout=10)
        assert not err, f"guarded connect must not raise: {err}"
        assert seen == ["late"]
    finally:
        client.close()
        server.stop()


def test_watcher_handles_pruned_on_reconnect(proxied_hub):
    hub, proxy, client = proxied_hub
    proxy.set_fault(watch_cut_every=1)     # cut at the 2nd live event
    client.watch_nodes(EventHandlers(on_add=lambda o: None))
    deadline = time.time() + 15
    i = 0
    while time.time() < deadline \
            and client.resilience_stats()["watch_reconnects"] < 3:
        hub.create_node(MakeNode().name(f"n-{i}").obj())
        i += 1
        time.sleep(0.1)
    assert client.resilience_stats()["watch_reconnects"] >= 3
    # one reflector = at most one live handle tracked, not one per reconnect
    assert len(client._watchers) <= 1


# ---------------------------------------------------- scheduler scenarios


def _wait(pred, timeout_s: float, interval: float = 0.05) -> bool:
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


def test_scheduler_survives_partition_during_binding():
    """Partition the wire while bindings are in flight: afterwards every
    pod is bound exactly once, nothing is lost, and the cache converges
    against the hub (the ISSUE's headline invariant)."""
    hub = Hub()
    server = HubServer(hub).start()
    proxy = ChaosProxy(server.address, config=ChaosConfig(seed=9)).start()
    client = RemoteHub(proxy.address, timeout=10.0, retry_deadline=2.0,
                       retry_base=0.01, retry_cap=0.1)
    for i in range(6):
        hub.create_node(MakeNode().name(f"n-{i}").capacity(cpu="64").obj())
    cfg = default_config()
    cfg.batch_size = 8
    sched = Scheduler(client, cfg, caps=Capacities(nodes=16, pods=256))
    try:
        sched.start()
        pods = [MakePod().name(f"p-{i}").req(cpu="100m").obj()
                for i in range(48)]
        for p in pods:
            hub.create_pod(p)

        def bound_count():
            return sum(1 for p in hub.list_pods() if p.spec.node_name)

        assert _wait(lambda: bound_count() >= 4, 30), "no binding started"
        proxy.partition_for(1.5)           # mid-storm partition
        assert _wait(lambda: bound_count() == len(pods), 60), \
            f"lost pods: {len(pods) - bound_count()} unbound"
        # exactly-once: every pod bound to exactly one node, and the
        # hub's bind-once Conflict means no uid can be double-bound
        for p in hub.list_pods():
            assert p.spec.node_name, f"{p.metadata.name} unbound"
        # convergence: reflector relist + assume/confirm settle
        assert _wait(lambda: not sched.cache.compare_with_hub(hub), 20), \
            sched.cache.compare_with_hub(hub)
    finally:
        sched.close()
        client.close()
        proxy.stop()
        server.stop()


def test_scheduler_parks_not_errors_when_hub_unreachable():
    """Full outage (in-process ChaosHub partition): the drain loop parks
    pods with backoff instead of erroring them, preserves assumed state,
    and schedules everything once the hub heals."""
    hub = Hub()
    chub = ChaosHub(hub)
    for i in range(4):
        chub.create_node(MakeNode().name(f"n-{i}").capacity(cpu="32").obj())
    cfg = default_config()
    cfg.batch_size = 8
    sched = Scheduler(chub, cfg, caps=Capacities(nodes=8, pods=64))
    try:
        for i in range(10):
            chub.create_pod(MakePod().name(f"p-{i}").req(cpu="100m").obj())
        chub.partition_for(600.0)
        attempted = sched.run_until_idle()      # must NOT raise
        assert attempted > 0
        assert sched.stats["errors"] == 0, "outage must not count as errors"
        assert sched.stats["parked_unreachable"] > 0
        assert sched.hub_degraded()
        assert sum(1 for p in hub.list_pods() if p.spec.node_name) == 0
        chub.heal()
        sched.run_maintenance()                 # probe clears degraded
        assert not sched.hub_degraded()
        deadline = time.time() + 15
        while time.time() < deadline:
            time.sleep(0.3)                     # let the park backoff lapse
            sched.run_maintenance()
            if sched.run_until_idle() == 0 and all(
                    p.spec.node_name for p in hub.list_pods()):
                break
        assert all(p.spec.node_name for p in hub.list_pods()), \
            "parked pods must schedule after heal"
        assert sched.cache.compare_with_hub(hub) == []
    finally:
        sched.close()


def test_assumed_pods_preserved_while_degraded():
    """cleanup_assumed_pods must not expire optimistic placements while
    their confirm events cannot arrive (double-scheduling guard)."""
    clock = [1000.0]
    hub = Hub()
    chub = ChaosHub(hub)
    chub.create_node(MakeNode().name("n-0").capacity(cpu="32").obj())
    cfg = default_config()
    cfg.async_binding = False
    sched = Scheduler(chub, cfg, caps=Capacities(nodes=8, pods=64),
                      now=lambda: clock[0])
    try:
        pod = MakePod().name("p").req(cpu="100m").obj()
        chub.create_pod(pod)
        sched.run_until_idle()
        assert hub.get_pod(pod.metadata.uid).spec.node_name
        # simulate: confirm event never arrived (drop it from the cache's
        # view by assuming a fresh pod directly)
        ghost = MakePod().name("ghost").req(cpu="100m").obj()
        ghost.spec.node_name = "n-0"
        sched.cache._ttl = 30.0             # default 0 = never expire
        sched.cache.assume_pod(ghost)
        sched.cache.finish_binding(ghost)   # start the expiry clock
        chub.partition_for(3600.0)
        sched._hub_down = True
        clock[0] += 600.0                       # way past assume TTL + flush
        sched.run_maintenance()                 # degraded: no expiry
        assert sched.cache.assumed_pod_count() >= 1
        chub.heal()
        sched._hub_down = False
        clock[0] += 31.0                        # reopen the 30s flush gate
        sched.run_maintenance()                 # healthy: expiry resumes
        assert sched.cache.assumed_pod_count() == 0
    finally:
        sched.close()


# ------------------------------------------------------- leader election


def test_leader_failover_within_lease_duration():
    """Cut the leader off from the lease store: it steps down by the
    renew deadline and a healthy peer takes over within lease_duration."""
    hub = Hub()
    server = HubServer(hub).start()
    proxy = ChaosProxy(server.address).start()
    cut_client = RemoteHub(proxy.address, timeout=5.0, retry_deadline=0.2,
                           retry_base=0.01, retry_cap=0.05)
    clock = time.monotonic
    lease_duration, renew_deadline = 2.0, 1.0
    a = LeaderElector(cut_client.leases, "a",
                      lease_duration=lease_duration,
                      renew_deadline=renew_deadline, retry_period=0.1,
                      now=clock)
    b = LeaderElector(hub.leases, "b", lease_duration=lease_duration,
                      renew_deadline=renew_deadline, retry_period=0.1,
                      now=clock)
    try:
        assert a.tick() and a.is_leader()
        assert not b.tick()                    # lease held by a
        t0 = clock()
        proxy.partition_for(3600.0)            # a is cut off
        stepped_down = failover = None
        deadline = clock() + 3 * lease_duration
        while clock() < deadline and failover is None:
            a.tick()                           # must not raise
            if stepped_down is None and not a.is_leader():
                stepped_down = clock() - t0
            if b.tick():
                failover = clock() - t0
            time.sleep(0.05)
        assert stepped_down is not None, "cut-off leader never stepped down"
        assert stepped_down <= renew_deadline + 1.0
        assert failover is not None, "peer never took over"
        assert failover <= lease_duration + 1.0, \
            f"failover took {failover:.1f}s > lease_duration"
        assert a.transport_errors > 0
        assert not a.is_leader() and b.is_leader()
    finally:
        cut_client.close()
        proxy.stop()
        server.stop()


def test_elector_release_survives_dead_store():
    class DeadStore:
        def get(self, name):
            raise OSError("store down")

        def update(self, lease, expect_holder):
            raise OSError("store down")

    el = LeaderElector(DeadStore(), "x", retry_period=0.0)
    assert el.tick() is False                  # no crash
    el._leading = True                         # pretend we were leading
    el._last_renew = el.now()
    el.release()                               # best-effort, no crash
    assert not el.is_leader()
    assert el.transport_errors >= 2


# ------------------------------------------------------------ serving


def test_readyz_reflects_degraded_state():
    import urllib.error
    import urllib.request

    from kubernetes_tpu.serving import ServingEndpoints

    hub = Hub()
    chub = ChaosHub(hub)
    sched = Scheduler(chub, default_config(),
                      caps=Capacities(nodes=8, pods=64))
    serving = ServingEndpoints(sched)
    serving.start()
    try:
        url = f"http://127.0.0.1:{serving.port}/readyz"
        assert urllib.request.urlopen(url).status == 200
        sched._hub_down = True
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(url)
        assert ei.value.code == 503
        # /metrics exposes the resilience surface
        sched._export_resilience_metrics()
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{serving.port}/metrics").read().decode()
        assert "scheduler_hub_degraded 1.0" in text
        assert "chaos_injected_faults" in text
        sched._hub_down = False
    finally:
        serving.stop()
        sched.close()


# --------------------------------------- watch-resume at Daemonset scale


def test_midwatch_cut_at_15k_nodes_resumes_with_zero_relists():
    """The Daemonset-15k reconnect storm the journal exists to kill: a
    reflector synced over 15 000 nodes loses its stream mid-watch; the
    reconnect must RESUME from since_rv (replaying only the gap events)
    — zero relists, zero duplicate adds — because a full 15k-object
    relist per reconnect is exactly the L0 cost etcd's revision-resumed
    watches avoid."""
    hub = Hub()                      # default ring >> the gap size
    server = HubServer(hub).start()
    proxy = ChaosProxy(server.address, config=ChaosConfig(seed=3)).start()
    client = RemoteHub(proxy.address, timeout=30.0, retry_base=0.01,
                       retry_cap=0.1)
    n_nodes = 15_000
    for i in range(n_nodes):
        hub.create_node(MakeNode().name(f"n{i}").obj())
    adds, updates = [], []
    try:
        client.watch_nodes(EventHandlers(
            on_add=lambda o: adds.append(o.metadata.name),
            on_update=lambda old, new: updates.append(new.metadata.name)))
        assert len(adds) == n_nodes, "initial LIST replay synced"
        # cut the stream on the next live event (that event is dropped
        # from the wire — only the journal can deliver it now)
        proxy.set_fault(watch_cut_rate=1.0)
        upd = hub.get_node("n0").clone()
        upd.metadata.labels["touched"] = "1"
        hub.update_node(upd)
        # while the stream is down, more of the gap accumulates
        deadline = time.time() + 10
        while proxy.stats["injected_cuts"] < 1 and time.time() < deadline:
            time.sleep(0.02)
        proxy.set_fault(watch_cut_rate=0.0)
        for i in range(1, 6):
            u = hub.get_node(f"n{i}").clone()
            u.metadata.labels["touched"] = "1"
            hub.update_node(u)
        deadline = time.time() + 30
        while time.time() < deadline and len(updates) < 6:
            time.sleep(0.05)
        assert sorted(set(updates)) == [f"n{i}" for i in range(6)], \
            "every gap event must arrive through the journal resume"
        stats = client.resilience_stats()
        assert stats["watch_resumes"] >= 1, stats
        assert stats["watch_relists"] == 0, \
            f"a 15k-node relist storm happened: {stats}"
        assert len(adds) == n_nodes, "no duplicate adds (no relist ran)"
        assert proxy.stats["injected_cuts"] >= 1
    finally:
        client.close()
        proxy.stop()
        server.stop()


# ------------------------- the self-healing core (fencing / ladder / etc)


def test_inflight_async_bind_rejected_fenced_after_failover():
    """Satellite: leader failover with in-flight async binds. The old
    leader's late Hub.bind must be rejected Fenced (no double-place);
    the new leader then schedules the pod exactly once."""
    from kubernetes_tpu.leaderelection import LeaderElector

    hub = Hub()
    hub.create_node(MakeNode().name("n").capacity(cpu="8").obj())
    elector_a = LeaderElector(hub.leases, "a", lease_duration=0.5,
                              renew_deadline=0.3, retry_period=0.05,
                              now=time.monotonic)
    elector_b = LeaderElector(hub.leases, "b", lease_duration=0.5,
                              renew_deadline=0.3, retry_period=0.05,
                              now=time.monotonic)

    class StallHub:
        """Delegating hub whose bind stalls long enough for the caller
        to be deposed mid-flight (the async binder pool race)."""

        def __init__(self, inner):
            self._inner = inner
            self.stall = None       # callable run before the first bind

        def bind(self, *args):
            if self.stall is not None:
                stall, self.stall = self.stall, None
                stall()
            return self._inner.bind(*args)

        def __getattr__(self, name):
            return getattr(self._inner, name)

    shub = StallHub(hub)
    cfg = default_config()
    sched_a = Scheduler(shub, cfg, caps=Capacities(nodes=8, pods=64))
    sched_a._elector = elector_a
    assert elector_a.tick() and elector_a.epoch == 1
    binds = []
    hub.watch_pods(EventHandlers(
        on_update=lambda old, new: binds.append(new.metadata.uid)
        if not old.spec.node_name and new.spec.node_name else None),
        replay=False)

    def depose_a():
        # runs on the binder thread, after a's launch chose a node but
        # before its bind lands: a's lease expires, b acquires
        time.sleep(0.6)
        assert elector_b.try_acquire_or_renew()
        assert elector_b.epoch == 2

    shub.stall = depose_a
    pod = MakePod().name("p").req(cpu="100m").obj()
    hub.create_pod(pod)
    try:
        sched_a.run_until_idle()           # must not raise
        assert hub.get_pod(pod.metadata.uid).spec.node_name == "", \
            "deposed leader's in-flight bind must be rejected"
        assert sched_a.stats["fenced"] == 1
        assert sched_a.metrics.fenced_writes.value(verb="bind") == 1, \
            "the BIND must be what was fenced (not a follow-on patch)"
        assert sched_a.stats["errors"] == 0, \
            "a fenced bind is not a scheduler error"
        assert sched_a.cache.assumed_pod_count() == 0, \
            "fenced bind must release its optimistic claim"
        # the new leader schedules it exactly once
        sched_b = Scheduler(hub, cfg, caps=Capacities(nodes=8, pods=64))
        sched_b._elector = elector_b
        try:
            sched_b.run_until_idle()
            assert hub.get_pod(pod.metadata.uid).spec.node_name == "n"
            assert binds == [pod.metadata.uid], \
                f"pod must bind exactly once, saw {binds}"
        finally:
            sched_b.close()
    finally:
        sched_a.close()


@pytest.mark.quarantine
def test_rebucket_nonconvergence_parks_batch_daemon_survives(monkeypatch):
    """Satellite regression: the re-bucketing RuntimeError used to
    escape the scheduling loop and kill the daemon; now the batch
    degrades to the host path and everything still schedules."""
    from kubernetes_tpu.backend.mirror import CapacityError, Mirror

    hub = Hub()
    for i in range(2):
        hub.create_node(MakeNode().name(f"n-{i}").capacity(cpu="8").obj())
    cfg = default_config()
    sched = Scheduler(hub, cfg, caps=Capacities(nodes=8, pods=64))

    def always_overflow(self, pods, batch_size):
        raise CapacityError("nodes", 64)

    monkeypatch.setattr(Mirror, "prepare_launch", always_overflow)
    try:
        for i in range(6):
            hub.create_pod(MakePod().name(f"p-{i}").req(cpu="100m").obj())
        sched.run_until_idle()             # must not raise
        assert sched.stats["device_fallbacks"] >= 1
        assert all(p.spec.node_name for p in hub.list_pods()), \
            "host fallback must still place the batch"
    finally:
        sched.close()


def test_keepalive_backs_off_on_persistent_error(monkeypatch):
    """Satellite: a persistent scheduling-loop error must not busy-spin
    the keep-alive — decorrelated backoff paces retries and
    scheduler_cycle_crashes_total counts them."""
    hub = Hub()
    sched = Scheduler(hub, default_config(),
                      caps=Capacities(nodes=8, pods=64))
    monkeypatch.setattr(
        sched, "run_maintenance",
        lambda: (_ for _ in ()).throw(RuntimeError("persistent")))
    stop = threading.Event()
    t = threading.Thread(target=sched.run, args=(stop,), daemon=True)
    t.start()
    time.sleep(1.2)
    stop.set()
    t.join(timeout=5)
    try:
        crashes = sched.metrics.cycle_crashes.value()
        assert crashes >= 1, "keep-alive must record the crash"
        assert crashes <= 5, \
            f"{crashes} crashes in 1.2s: the keep-alive is busy-spinning"
        assert isinstance(sched.daemon_error, RuntimeError)
    finally:
        sched.close()


def test_condition_patch_drops_are_counted():
    """Satellite: degraded-mode (and fenced) condition-patch drops are
    counted so operators can see lost status."""
    from kubernetes_tpu.api.objects import PodCondition
    from kubernetes_tpu.leaderelection import Lease

    hub = Hub()
    chub = ChaosHub(hub)
    sched = Scheduler(chub, default_config(),
                      caps=Capacities(nodes=8, pods=64))
    try:
        pod = MakePod().name("p").req(cpu="100m").obj()
        hub.create_pod(pod)
        cond = PodCondition(type="PodScheduled", status="False",
                            reason="Unschedulable")
        chub.partition_for(60.0)
        sched._patch_condition_best_effort(pod, cond)
        m = sched.metrics.condition_patches_dropped
        assert m.value(reason="unavailable") == 1
        chub.heal()
        # fenced drop: our epoch predates an acquisition we never made
        hub.leases.update(Lease(name="kube-scheduler",
                                holder_identity="other"), None)

        class Tok:
            epoch = 0
            lease_name = "kube-scheduler"

        sched._elector = Tok()
        sched._patch_condition_best_effort(pod, cond)
        assert m.value(reason="fenced") == 1
        assert sched.metrics.fenced_writes.value(
            verb="patch_pod_condition") == 1
    finally:
        sched._elector = None
        sched.close()


def test_fenced_error_roundtrips_the_wire(proxied_hub):
    """Fenced must survive the HTTP hop typed (the RPC layer's analog
    of the apiserver's 403), not decay into RemoteError."""
    from kubernetes_tpu.hub import Fenced
    from kubernetes_tpu.leaderelection import Lease

    hub, proxy, client = proxied_hub
    pod = MakePod().name("p").req(cpu="100m").obj()
    hub.create_pod(pod)
    hub.create_node(MakeNode().name("n").obj())
    hub.leases.update(Lease(name="kube-scheduler",
                            holder_identity="leader"), None)
    with pytest.raises(Fenced):
        client.bind(pod, "n", 0, "kube-scheduler")
    assert hub.get_pod(pod.metadata.uid).spec.node_name == ""
    client.bind(pod, "n", hub.leases.epoch_of("kube-scheduler"),
                "kube-scheduler")
    assert hub.get_pod(pod.metadata.uid).spec.node_name == "n"


def test_deposed_leader_evictions_and_clears_are_fenced():
    """Regression (ROADMAP carried-over gap): a deposed leader's QUEUED
    preemption evictions and nomination clears must be rejected Fenced at
    the hub — the new leader may have re-planned around those victims —
    and the whole backlog dropped, not replayed under a newer epoch."""
    from kubernetes_tpu.framework.preemption import Candidate
    from kubernetes_tpu.leaderelection import Lease

    hub = Hub()
    hub.create_node(MakeNode().name("n").capacity(cpu="8").obj())
    victim = MakePod().name("victim").req(cpu="100m").obj()
    victim.spec.node_name = "n"
    hub.create_pod(victim)
    nominee = MakePod().name("nominee").req(cpu="100m").obj()
    nominee.status.nominated_node_name = "n"
    hub.create_pod(nominee)
    sched = Scheduler(hub, default_config(),
                      caps=Capacities(nodes=8, pods=64))
    try:
        # another scheduler took the lease: our (fake) elector's epoch 0
        # predates its acquisition — every fenced write must bounce
        hub.leases.update(Lease(name="kube-scheduler",
                                holder_identity="other"), None)

        class Tok:
            epoch = 0
            lease_name = "kube-scheduler"

        sched._elector = Tok()
        preemptor = MakePod().name("preemptor").req(cpu="100m").obj()
        sched.preemption.prepare_candidate(
            Candidate(node_name="n", row=0, victims=[victim],
                      pdb_violations=0), preemptor)
        sched.preemption.flush_evictions()
        assert hub.get_pod(victim.metadata.uid) is not None, \
            "a deposed leader's queued eviction must NOT land"
        assert sched.metrics.fenced_writes.value(verb="delete_pod") == 1
        assert not sched.preemption._pending, \
            "the eviction backlog must be dropped, not replayed"
        assert preemptor.metadata.uid not in sched.preemption.preempting, \
            "stranded preemptors must be ungated for the retry path"
        # deferred nomination-clear replays are fenced the same way
        sched.preemption._pending_clears.append(nominee.metadata.uid)
        sched.preemption.flush_evictions()
        assert hub.get_pod(
            nominee.metadata.uid).status.nominated_node_name == "n", \
            "a deposed leader's queued nomination clear must NOT land"
        assert sched.metrics.fenced_writes.value(
            verb="clear_nominated_node") == 1
        assert not sched.preemption._pending_clears
        # re-elected with the CURRENT epoch, the same flush goes through
        class Tok2:
            epoch = hub.leases.epoch_of("kube-scheduler")
            lease_name = "kube-scheduler"

        sched._elector = Tok2()
        sched.preemption._pending_clears.append(nominee.metadata.uid)
        sched.preemption.flush_evictions()
        assert hub.get_pod(
            nominee.metadata.uid).status.nominated_node_name == ""
    finally:
        sched._elector = None
        sched.close()


@pytest.mark.quarantine
def test_device_fault_storm_ladder_and_quarantine():
    """The device-fault storm gate, small: injected launch errors +
    NaN-poisoned results + a genuine poison pod; every healthy pod
    binds, the poison pod is quarantined with a hub Event, zero daemon
    deaths (bench.py --chaos-smoke runs the full battery)."""
    from kubernetes_tpu.chaos import run_device_storm

    report = run_device_storm(pods=24, nodes=4, seed=11)
    assert report["ok"], report


@pytest.mark.quarantine
def test_quarantine_releases_with_escalating_backoff():
    """A quarantined pod re-enters the queue after its backoff and, on
    re-offense, re-quarantines with a doubled window."""
    from kubernetes_tpu.chaos import make_poison_pod

    clock = [1000.0]
    hub = Hub()
    hub.create_node(MakeNode().name("n").capacity(cpu="8").obj())
    sched = Scheduler(hub, default_config(),
                      caps=Capacities(nodes=8, pods=64),
                      now=lambda: clock[0])
    try:
        poison = make_poison_pod("bad")
        hub.create_pod(poison)
        sched.run_until_idle()
        uid = poison.metadata.uid
        assert uid in sched.quarantined_uids()
        until1 = sched._quarantine[uid]["until"]
        assert until1 - clock[0] == pytest.approx(5.0)
        clock[0] = until1 + 0.1
        sched.run_maintenance()                # released back to queue
        assert uid not in sched.quarantined_uids()
        sched.run_until_idle()                 # re-offends immediately
        assert uid in sched.quarantined_uids()
        until2 = sched._quarantine[uid]["until"]
        assert until2 - clock[0] == pytest.approx(10.0), \
            "re-offense must double the quarantine window"
        events = [e for e in hub.list_events(ref_kind="Pod")
                  if e.reason == "Quarantined"]
        assert events and events[0].count >= 1
    finally:
        sched.close()


@pytest.mark.quarantine
def test_quarantine_holds_through_informer_updates():
    """A controller status patch (or relist replay) for a quarantined
    pod must not re-queue it — that would reset the escalating backoff;
    the freshened spec rides along for the eventual release."""
    from kubernetes_tpu.chaos import make_poison_pod

    clock = [1000.0]
    hub = Hub()
    hub.create_node(MakeNode().name("n").capacity(cpu="8").obj())
    sched = Scheduler(hub, default_config(),
                      caps=Capacities(nodes=8, pods=64),
                      now=lambda: clock[0])
    try:
        poison = make_poison_pod("bad")
        hub.create_pod(poison)
        sched.run_until_idle()
        uid = poison.metadata.uid
        assert uid in sched.quarantined_uids()
        # a controller annotates the pod mid-quarantine
        upd = hub.get_pod(uid).clone()
        upd.metadata.labels["touched"] = "1"
        hub.update_pod(upd)
        assert uid in sched.quarantined_uids()
        assert sched.queue.pending_counts() == {
            k: 0 for k in sched.queue.pending_counts()}, \
            "the update must not re-queue the quarantined pod"
        assert sched.run_until_idle() == 0
        # release re-fetches hub truth, so the newest spec comes back
        clock[0] = sched._quarantine[uid]["until"] + 0.1
        sched.run_maintenance()
        assert uid not in sched.quarantined_uids()
        assert sched.queue.pending_counts()["active"] == 1
    finally:
        sched.close()


# ------------------------------------------------- the full storm (slow)


@pytest.mark.slow
def test_chaos_smoke_storm():
    """scheduler + kubemark hollow nodes through the proxy under call
    faults, watch cuts, and a partition (bench.py --chaos-smoke's gate)."""
    from kubernetes_tpu.chaos import run_smoke

    report = run_smoke(pods=30, nodes=6, seed=7)
    assert report["ok"], report


@pytest.mark.slow
@pytest.mark.quarantine
def test_chaos_crash_storm():
    """The acceptance storm, scaled down for the suite: device faults +
    watch cuts + leader kill + kill-and-restart; every pod bound exactly
    once, poison quarantined, zero daemon deaths (bench.py --chaos-smoke
    runs it at >=1k pods)."""
    from kubernetes_tpu.chaos import run_crash_storm

    report = run_crash_storm(pods=150, nodes=8, seed=13, timeout_s=120.0)
    assert report["ok"], report


@pytest.mark.slow
@pytest.mark.gang
def test_chaos_gang_storm():
    """Gang atomicity under leader kill mid-commit, scaled down for the
    suite: every gang lands fully or not at all (zero partial gangs on
    the bind ledger), no duplicate binds, no leaked assumed pods
    (bench.py --chaos-smoke runs it at full size)."""
    from kubernetes_tpu.chaos import run_gang_storm

    report = run_gang_storm(gangs=6, nodes=10, seed=17, timeout_s=150.0)
    assert report["ok"], report
