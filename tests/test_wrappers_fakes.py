"""Fluent wrappers + scripted fake plugins (pkg/scheduler/testing
equivalents) exercised through the REAL Scheduler loop — the same
pattern as the reference's fake-plugin framework tests
(testing/framework/fake_plugins.go driving schedule_one_test.go)."""

import numpy as np

from kubernetes_tpu.config.types import default_config
from kubernetes_tpu.hub import Hub
from kubernetes_tpu.ops.features import Capacities
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.testing import (
    FakeReservePlugin,
    FakeScorePlugin,
    MakeNode,
    MakePod,
    MatchFilterPlugin,
    fake_profile,
    fake_registry,
)

CAPS = Capacities(nodes=16, pods=64)


def _sched(hub, *fakes, caps=CAPS, **instances):
    cfg = default_config()
    cfg.batch_size = 8
    cfg.profiles = [fake_profile(*fakes)]
    return Scheduler(hub, cfg, caps=caps,
                     registry=fake_registry(**instances))


def test_wrappers_build_schedulable_objects():
    hub = Hub()
    for i in range(4):
        hub.create_node(MakeNode().name(f"wn-{i}")
                        .label("zone", f"z{i % 2}")
                        .capacity(cpu="8", memory="32Gi").obj())
    sched = _sched(hub)
    pod = (MakePod().name("w-pod").req(cpu="500m", memory="1Gi")
           .priority(5)
           .node_affinity_in("zone", ["z1"])
           .toleration("k", "v", "NoSchedule")
           .obj())
    hub.create_pod(pod)
    sched.run_until_idle()
    bound = hub.get_pod(pod.metadata.uid)
    assert bound.spec.node_name in ("wn-1", "wn-3"), bound.spec.node_name
    sched.close()


def test_match_filter_fake_restricts_to_named_node():
    hub = Hub()
    for i in range(6):
        hub.create_node(MakeNode().name(f"node-{i}").obj())
    sched = _sched(hub, MatchFilterPlugin.NAME)
    pod = MakePod().name("node-3").req(cpu="100m").obj()
    hub.create_pod(pod)
    sched.run_until_idle()
    assert hub.get_pod(pod.metadata.uid).spec.node_name == "node-3"
    sched.close()


def test_fake_score_steers_selection():
    hub = Hub()
    for i in range(5):
        hub.create_node(MakeNode().name(f"node-{i}").obj())
    fake = FakeScorePlugin(lambda name: 100.0 if name == "node-4" else 0.0)
    sched = _sched(hub, FakeScorePlugin.NAME, FakeScore=fake)
    pod = MakePod().name("steered").req(cpu="100m").obj()
    hub.create_pod(pod)
    sched.run_until_idle()
    assert hub.get_pod(pod.metadata.uid).spec.node_name == "node-4"
    assert len(fake.calls) == 5, "scored once per node"
    sched.close()


def test_fake_reserve_failure_unreserves_and_requeues():
    hub = Hub()
    hub.create_node(MakeNode().name("only").obj())
    fake = FakeReservePlugin(fail=True)
    sched = _sched(hub, FakeReservePlugin.NAME, FakeReserve=fake)
    pod = MakePod().name("rejected").req(cpu="100m").obj()
    hub.create_pod(pod)
    sched.run_until_idle()
    assert hub.get_pod(pod.metadata.uid).spec.node_name == ""
    assert fake.reserved, "reserve ran"
    assert fake.unreserved == fake.reserved, \
        "failed reserve must roll back via unreserve (schedule_one.go:212)"
    sched.close()


# suite-tier discipline (tests/test_markers.py): area marker
import pytest  # noqa: E402
pytestmark = pytest.mark.core
