"""The parallel-rounds auction commit (pipeline._rounds_commit), the
device-state chain, random tie-breaking, and subset pod-blob transfers.

The auction replaces the reference's serial per-pod assume loop
(schedule_one.go:66) for constraint-free batches: placement CHOICES may
differ from the as-if-serial scan, but every placement must satisfy the same
feasibility invariants (no node ever overcommitted), and the final balance
must track the serial loop's (selectHost's reservoir-sampled tie-break,
schedule_one.go:865)."""

import collections

import numpy as np

from kubernetes_tpu.models.pipeline import (
    default_weights,
    launch_batch,
    schedule_batch_jit,
)
from kubernetes_tpu.models.testbed import build_cluster, make_pod
from kubernetes_tpu.ops.features import Capacities

CAPS = Capacities(nodes=64, pods=256)


def _drive(num_nodes, pods, serial_scan, batch=64):
    cache, snap, mirror = build_cluster(num_nodes, caps=CAPS)
    spec = mirror.prepare_launch(pods, batch)
    out = launch_batch(spec, mirror.well_known(), default_weights(), CAPS,
                       serial_scan=serial_scan)
    return mirror, out


def test_auction_places_all_when_feasible():
    pods = [make_pod(i) for i in range(48)]
    _, out = _drive(16, pods, serial_scan=False)
    rows = np.asarray(out.node_row)[:48]
    assert (rows >= 0).all()


def test_auction_never_overcommits():
    """Tight capacity: each node fits exactly 2 of these pods by CPU; the
    auction must not place a 3rd anywhere, exactly like the serial scan."""
    pods = [make_pod(i, cpu="14000m") for i in range(20)]  # 2×14 < 32 < 3×14
    _, out_a = _drive(8, pods, serial_scan=False, batch=32)
    _, out_s = _drive(8, pods, serial_scan=True, batch=32)
    for out in (out_a, out_s):
        rows = [r for r in np.asarray(out.node_row)[:20].tolist() if r >= 0]
        assert len(rows) == 16, "8 nodes x 2 pods"
        counts = collections.Counter(rows)
        assert max(counts.values()) == 2
    # the 4 unplaced pods are rejected by NodeResourcesFit
    from kubernetes_tpu.models.pipeline import FILTER_PLUGINS
    fit_idx = FILTER_PLUGINS.index("NodeResourcesFit")
    rej = np.asarray(out_a.reject_counts)
    unplaced = np.asarray(out_a.node_row)[:20] < 0
    assert (rej[:20][unplaced, fit_idx] > 0).all()


def test_auction_balance_tracks_serial():
    """Equal-score nodes: the auction's one-accept-per-node rounds + random
    tie-break must spread like the serial loop (no hotspotting)."""
    pods = [make_pod(i) for i in range(40)]
    _, out = _drive(40, pods, serial_scan=False)
    rows = np.asarray(out.node_row)[:40].tolist()
    counts = collections.Counter(rows)
    assert max(counts.values()) <= 2
    assert len(counts) >= 30, "ties must spread, not hotspot the lowest row"


def test_scan_tie_break_spreads():
    """The scan path's perturbed argmax: equal-score nodes pick uniformly
    (selectHost's reservoir sample), not first-index."""
    pods = [make_pod(i, cpu="0m", mem="0Mi") for i in range(16)]
    _, out = _drive(32, pods, serial_scan=True)
    rows = np.asarray(out.node_row)[:16].tolist()
    # zero-request pods never change utilization: every node always ties.
    # first-index argmax would put ALL pods on one row.
    assert len(set(rows)) >= 8


def test_chained_state_sees_prior_batch():
    """Launch 2 fed launch 1's (free, nzr) must respect its commitments
    without any host mirror resync."""
    cache, snap, mirror = build_cluster(4, caps=CAPS)
    wk = mirror.well_known()
    weights = default_weights()
    # each node fits exactly one 20-cpu pod (32 allocatable)
    first = [make_pod(i, cpu="20000m") for i in range(4)]
    second = [make_pod(100 + i, cpu="20000m") for i in range(4)]
    spec1 = mirror.prepare_launch(first, 8)
    out1 = launch_batch(spec1, wk, weights, CAPS, serial_scan=False)
    assert (np.asarray(out1.node_row)[:4] >= 0).all()
    spec2 = mirror.prepare_launch(second, 8)
    out2 = launch_batch(spec2, wk, weights, CAPS, serial_scan=False,
                        state=(out1.free, out1.nzr))
    rows2 = np.asarray(out2.node_row)[:4]
    assert (rows2 < 0).all(), "chained state must carry batch 1's commits"
    # without the chain the stale mirror would wrongly admit them
    out_stale = launch_batch(spec2, wk, weights, CAPS, serial_scan=False)
    assert (np.asarray(out_stale.node_row)[:4] >= 0).all()


def test_subset_blobs_match_full_schema():
    """prepare_launch ships only the active-feature fields; results must be
    identical to the full-schema transfer (same pods, same cluster)."""
    cache, snap, mirror = build_cluster(12, caps=CAPS)
    pods = [make_pod(i) for i in range(10)]
    spec = mirror.prepare_launch(pods, 16)
    assert spec.pfields is not None
    # the subset must be materially smaller than the full schema
    full_i32 = mirror.pod_codec.i32_size
    sub_i32 = spec.pblobs.i32.shape[-1]
    assert sub_i32 < full_i32 // 4
    out_sub = launch_batch(spec, mirror.well_known(), default_weights(), CAPS)
    pblobs_full = mirror.pack_batch_blobs(pods, 16)
    out_full = schedule_batch_jit(
        mirror.to_blobs(), pblobs_full, mirror.well_known(),
        default_weights(), CAPS, spec.enable_topology, spec.d_cap,
        serial_scan=True)
    # same launch mode for comparability: rerun subset through the scan
    out_sub2 = launch_batch(spec, mirror.well_known(), default_weights(),
                            CAPS, serial_scan=True)
    assert (np.asarray(out_sub2.node_row)[:10]
            == np.asarray(out_full.node_row)[:10]).all()
    assert (np.asarray(out_sub2.reject_counts)
            == np.asarray(out_full.reject_counts)).all()
    assert (np.asarray(out_sub.node_row)[:10] >= 0).all()


def test_subset_blobs_with_tolerations_and_affinity():
    """A batch that activates nodeaffinity ships the selector fields and
    matches the full-schema result."""
    from kubernetes_tpu.api.objects import (
        Affinity, Container, NodeAffinity, NodeSelector, NodeSelectorTerm,
        LabelSelectorRequirement, ObjectMeta, Pod, PodSpec,
        ResourceRequirements, Toleration,
    )

    def sel_pod(i, zone):
        req = NodeSelector(node_selector_terms=[NodeSelectorTerm(
            match_expressions=[LabelSelectorRequirement(
                key="topology.kubernetes.io/zone", operator="In",
                values=[zone])])])
        return Pod(
            metadata=ObjectMeta(name=f"sp-{i}"),
            spec=PodSpec(
                containers=[Container(name="c",
                                      resources=ResourceRequirements(
                                          requests={"cpu": "100m"}))],
                affinity=Affinity(node_affinity=NodeAffinity(required=req)),
                tolerations=[Toleration(key="k", operator="Exists")],
            ))

    cache, snap, mirror = build_cluster(8, caps=CAPS, zones=2)
    pods = [sel_pod(i, f"zone-{i % 2}") for i in range(6)]
    spec = mirror.prepare_launch(pods, 8)
    assert "nodeaffinity" in spec.active
    assert "sel_col" in spec.pfields
    out = launch_batch(spec, mirror.well_known(), default_weights(), CAPS)
    rows = np.asarray(out.node_row)[:6]
    assert (rows >= 0).all()
    for i, r in enumerate(rows.tolist()):
        name = mirror.name_of_row(r)
        node_zone = int(name.split("-")[1]) % 2
        assert node_zone == i % 2, "nodeSelector zone must be honored"


# suite-tier discipline (tests/test_markers.py): area marker
import pytest  # noqa: E402
pytestmark = pytest.mark.core
