"""Differential fuzz: the batched device DRA allocator (ops/dra.py +
plugins.dra.DeviceAllocatorView) against the legacy host serial
allocator (DynamicResources.allocate_claim), over randomized
inventories, selectors, claims, and pre-allocated (in-use) devices.

Parity contract: for every pod the builder routes to the device path,
the device [pod, node] feasibility mask must EQUAL the host filter's
verdict on every mirrored node. Device CHOICE is allowed to differ only
among score-ties and is not asserted here — the actual pick still runs
through the host allocator at Reserve (commit-time bookkeeping), so the
two can never diverge on what gets written to the API.

Pods the builder refuses (matchAttribute constraints, firstAvailable,
adminAccess, unparseable selectors) are asserted to carry exactly such a
feature — the host path (unchanged, covered by test_dra_structured)
keeps owning them.

Shapes are pinned (8 nodes x <=8 devices, <=2 requests/pod) so the
whole sweep shares two jitted programs; the tier-1 run covers 200
seeds, the `slow` sweep 1000.
"""

from __future__ import annotations

import random

import numpy as np
import pytest

from kubernetes_tpu.api.objects import (
    ALLOCATION_MODE_ALL,
    AllocationResult,
    Device,
    DeviceAllocationResult,
    DeviceClass,
    DeviceConstraint,
    DeviceRequest,
    DeviceSelector,
    DeviceSubRequest,
    ObjectMeta,
    Pod,
    PodResourceClaim,
    PodSpec,
    ResourceClaim,
    ResourceClaimSpec,
    ResourceSlice,
)
from kubernetes_tpu.hub import Hub
from kubernetes_tpu.ops.dra import batch_feasible_jit
from kubernetes_tpu.plugins.dra import DynamicResources

pytestmark = pytest.mark.dra

N_NODES = 8
DRIVER = "fuzz.example.com"
MODELS = ("m0", "m1", "m2", "m3")
CLASSES = ("cls-a", "cls-b")


def _mk_device(rng: random.Random, d: int) -> Device:
    attrs = {}
    if rng.random() < 0.9:
        attrs["model"] = rng.choice(MODELS)
    if rng.random() < 0.7:
        attrs["flag"] = rng.random() < 0.5
    if rng.random() < 0.5:
        attrs["gen"] = rng.randrange(4)
    cap = {}
    if rng.random() < 0.6:
        cap["size"] = str(rng.randrange(1, 5))
    return Device(name=f"dev-{d}",
                  device_class_name=rng.choice(("", *CLASSES)),
                  attributes=attrs, capacity=cap)


def _mk_selector(rng: random.Random) -> str:
    kind = rng.randrange(5)
    if kind == 0:
        return f"device.attributes['{DRIVER}'].flag"
    if kind == 1:
        return (f"device.attributes['{DRIVER}'].model == "
                f"'{rng.choice(MODELS)}'")
    if kind == 2:
        picks = rng.sample(MODELS, 2)
        return (f"device.attributes['{DRIVER}'].model in "
                f"['{picks[0]}', '{picks[1]}']")
    if kind == 3:
        return (f"device.capacity['{DRIVER}'].size"
                f".compareTo(quantity('{rng.randrange(1, 4)}')) >= 0")
    return f"device.attributes['{DRIVER}'].gen >= {rng.randrange(3)}"


def _mk_request(rng: random.Random, name: str, expressible: bool
                ) -> DeviceRequest:
    req = DeviceRequest(name=name)
    roll = rng.random()
    if roll < 0.35:
        req.device_class_name = rng.choice(CLASSES)
    else:
        req.selectors = [DeviceSelector(cel_expression=_mk_selector(rng))
                         for _ in range(rng.randrange(1, 3))]
    if rng.random() < 0.15:
        req.allocation_mode = ALLOCATION_MODE_ALL
    else:
        req.count = rng.randrange(1, 4)
    if not expressible:
        # one deliberately inexpressible feature: the builder must
        # route this pod to the host path
        feat = rng.randrange(3)
        if feat == 0:
            req.admin_access = True
        elif feat == 1:
            req.first_available = [DeviceSubRequest(
                name="alt", device_class_name=CLASSES[0])]
        else:
            req.selectors = [DeviceSelector(
                cel_expression="this is ((( not CEL")]
    return req


def _scenario(seed: int):
    """One randomized cluster: slices + classes + claims + pods, some
    devices pre-allocated by blocker claims."""
    rng = random.Random(seed)
    hub = Hub()
    for name in CLASSES:
        if rng.random() < 0.5:
            hub.create_device_class(DeviceClass(
                metadata=ObjectMeta(name=name),
                selectors=[DeviceSelector(
                    cel_expression=_mk_selector(rng))]))
        # else: no class object -> legacy direct device_class_name match
    node_names = [f"n{i}" for i in range(rng.randrange(3, N_NODES + 1))]
    all_triples = []
    for i, node in enumerate(node_names):
        devs = [_mk_device(rng, d) for d in range(rng.randrange(0, 7))]
        if devs:
            hub.create_resource_slice(ResourceSlice(
                metadata=ObjectMeta(name=f"slice-{node}"),
                node_name=node, driver=DRIVER, pool=f"pool-{node}",
                devices=devs))
            all_triples += [(node, DRIVER, f"pool-{node}", d.name)
                            for d in devs]
    plugin = DynamicResources(hub)
    # blocker claims: pre-allocated devices populate the in-use ledger
    rng.shuffle(all_triples)
    n_used = rng.randrange(0, max(1, len(all_triples) // 2 + 1))
    for k, (node, drv, pool, dev) in enumerate(all_triples[:n_used]):
        blocker = ResourceClaim(
            metadata=ObjectMeta(name=f"blocker-{k}"))
        blocker.status.allocation = AllocationResult(
            node_name=node,
            devices=[DeviceAllocationResult(
                request="r", driver=drv, pool=pool, device=dev)])
        hub.create_resource_claim(blocker)
    pods = []
    for p in range(rng.randrange(1, 5)):
        expressible = rng.random() < 0.8
        reqs = [_mk_request(rng, f"r{q}", expressible or q > 0)
                for q in range(rng.randrange(1, 3))]
        spec = ResourceClaimSpec(device_requests=reqs)
        if not expressible and rng.random() < 0.3:
            spec.constraints = [DeviceConstraint(match_attribute="model")]
        claim = ResourceClaim(metadata=ObjectMeta(name=f"claim-{p}"),
                              spec=spec)
        if rng.random() < 0.15 and all_triples:
            # pre-allocated claim: the pod is pinned to its node
            node, drv, pool, dev = rng.choice(all_triples)
            claim.status.allocation = AllocationResult(
                node_name=node,
                devices=[DeviceAllocationResult(
                    request="r0", driver=drv, pool=pool, device=dev)])
        hub.create_resource_claim(claim)
        pods.append((Pod(metadata=ObjectMeta(name=f"pod-{p}"),
                         spec=PodSpec(resource_claims=[PodResourceClaim(
                             name="c", resource_claim_name=f"claim-{p}")])),
                     expressible))
    return hub, plugin, node_names, pods


def _host_mask(plugin: DynamicResources, pod: Pod,
               node_names: list[str]) -> list[bool]:
    """The host filter's verdict, claim-for-claim (DynamicResources
    .filter semantics: pin checks for allocated claims, greedy
    allocate_claim with local in-use threading for the rest)."""
    claims = [c for _r, c in plugin._pod_claims(pod)]
    assert all(c is not None for c in claims)
    exclude = {c.key() for c in claims if c.status.allocation is None}
    in_use = plugin._in_use_view(exclude)
    out = []
    for node in node_names:
        ok = True
        local = set(in_use)
        for claim in claims:
            alloc = claim.status.allocation
            if alloc is not None:
                if alloc.node_name and alloc.node_name != node:
                    ok = False
                    break
                continue
            picked = plugin.allocate_claim(claim, node, local)
            if picked is None:
                ok = False
                break
            local |= {(d.driver, d.pool, d.device)
                      for d in picked if not d.admin_access}
        out.append(ok)
    return out


def _run_cases(seeds) -> tuple[int, int]:
    routed_total = fallback_total = 0
    for seed in seeds:
        hub, plugin, node_names, pods = _scenario(seed)
        row_of = {n: i for i, n in enumerate(node_names)}.__getitem__
        batch, _stats = plugin.build_device_batch(
            [p for p, _e in pods],
            lambda n: row_of(n) if n in set(node_names) else -1,
            N_NODES, len(pods))
        routed = plugin._device_routed
        dev_mask = (np.asarray(batch_feasible_jit(batch))
                    if batch is not None else None)
        for b, (pod, expressible) in enumerate(pods):
            if pod.metadata.uid not in routed:
                # the builder may only refuse inexpressible pods
                assert not expressible, \
                    f"seed {seed}: expressible pod {b} not routed"
                fallback_total += 1
                continue
            routed_total += 1
            host = _host_mask(plugin, pod, node_names)
            dev = [bool(dev_mask[b, row_of(n)]) for n in node_names]
            assert dev == host, (
                f"seed {seed} pod {b}: device {dev} != host {host}\n"
                f"claims: {[c.spec for _r, c in plugin._pod_claims(pod)]}")
    return routed_total, fallback_total


def test_allocation_parity_fuzz_200():
    """Tier-1 sweep: >= 200 randomized scenarios, identical feasible
    sets between the device kernel and the host serial allocator."""
    routed, _fallback = _run_cases(range(200))
    # the generator makes ~80% of pods expressible; demand real coverage
    assert routed >= 300, f"only {routed} device-routed pods exercised"


@pytest.mark.slow
def test_allocation_parity_fuzz_long():
    """The long-seed sweep (kept out of tier-1's time budget)."""
    routed, _fallback = _run_cases(range(200, 1200))
    assert routed >= 1500


def test_inexpressible_features_route_to_host():
    """Spot-check the routing boundary: constraints / firstAvailable /
    adminAccess / broken selectors never reach the device kernel."""
    hub = Hub()
    hub.create_resource_slice(ResourceSlice(
        metadata=ObjectMeta(name="s"), node_name="n0", driver=DRIVER,
        pool="p", devices=[Device(name="d0", device_class_name="cls-a")]))
    plugin = DynamicResources(hub)
    specs = [
        ResourceClaimSpec(device_requests=[DeviceRequest(
            name="r", device_class_name="cls-a", admin_access=True)]),
        ResourceClaimSpec(device_requests=[DeviceRequest(
            name="r", first_available=[DeviceSubRequest(
                name="a", device_class_name="cls-a")])]),
        ResourceClaimSpec(
            device_requests=[DeviceRequest(name="r",
                                           device_class_name="cls-a")],
            constraints=[DeviceConstraint(match_attribute="model")]),
        ResourceClaimSpec(device_requests=[DeviceRequest(
            name="r", selectors=[DeviceSelector(
                cel_expression="((not cel")])]),
        ResourceClaimSpec(device_requests=[DeviceRequest(
            name="r", device_class_name="cls-a", count=0)]),
    ]
    pods = []
    for i, spec in enumerate(specs):
        hub.create_resource_claim(ResourceClaim(
            metadata=ObjectMeta(name=f"c{i}"), spec=spec))
        pods.append(Pod(metadata=ObjectMeta(name=f"p{i}"),
                        spec=PodSpec(resource_claims=[PodResourceClaim(
                            name="c", resource_claim_name=f"c{i}")])))
    batch, stats = plugin.build_device_batch(
        pods, lambda n: 0 if n == "n0" else -1, N_NODES, len(pods))
    assert batch is None and stats["fallback"] == len(specs)
    assert plugin._device_routed == frozenset()
    # the broken selector surfaced the same CELSelectorError the host
    # path records
    assert plugin.cel_error_stats(), "parse failure must surface"


def test_profile_with_dra_disabled_skips_device_allocator():
    """A profile that disables the DynamicResources filter must keep
    scheduling claim pods UNFILTERED (pre-device-allocator behavior):
    the fused gate is per-profile, so no DRA verdict — device or host —
    may reject its pods."""
    from kubernetes_tpu.config.types import Plugin, default_config
    from kubernetes_tpu.ops.features import Capacities
    from kubernetes_tpu.scheduler import Scheduler
    from tests.test_dra import mkclaim, mknode, mkpod

    hub = Hub()
    cfg = default_config()
    cfg.batch_size = 8
    # disable the plugin wholesale (the delegation shape: claims handed
    # to an external component) — multi_point removal takes it out of
    # filter AND reserve/pre_bind
    cfg.profiles[0].plugins.multi_point.disabled.append(
        Plugin(name="DynamicResources"))
    sched = Scheduler(hub, cfg, caps=Capacities(nodes=16, pods=64))
    assert sched._profile_cfg[sched._profile_name]["dra_filter"] is False
    hub.create_node(mknode("bare"))     # no slices anywhere
    hub.create_resource_claim(mkclaim("c1"))
    pod = mkpod("p", claim="c1")
    hub.create_pod(pod)
    sched.run_until_idle()
    # with the filter disabled the claim is not enforced: the pod lands
    # on the device-less node instead of parking unschedulable
    assert hub.get_pod(pod.metadata.uid).spec.node_name == "bare"
    assert sched._dra.device_view.stats["device_pods"] == 0
    sched.close()


def test_device_fallback_ladder_still_schedules_dra_batch():
    """Acceptance: a device-path fault on a DRA batch degrades to the
    host path (which re-enables the host DRA filter) and the pod still
    lands on the device-backed node — the daemon never dies."""
    from kubernetes_tpu.chaos import DeviceChaos, DeviceChaosConfig
    from kubernetes_tpu.config.types import default_config
    from kubernetes_tpu.ops.features import Capacities
    from kubernetes_tpu.scheduler import Scheduler
    from tests.test_dra import mkclaim, mknode, mkpod, mkslice

    hub = Hub()
    cfg = default_config()
    cfg.batch_size = 8
    sched = Scheduler(hub, cfg, caps=Capacities(nodes=16, pods=64))
    chaos = DeviceChaos(DeviceChaosConfig(seed=3, launch_error_rate=1.0))
    sched.fault_injector = chaos
    hub.create_node(mknode("plain"))
    hub.create_node(mknode("accel"))
    hub.create_resource_slice(mkslice("accel", 2))
    hub.create_resource_claim(mkclaim("c1"))
    pod = mkpod("p", claim="c1")
    hub.create_pod(pod)
    sched.run_until_idle()
    assert chaos.stats["injected_launch_errors"] >= 1
    assert sched.stats["device_fallbacks"] >= 1
    assert hub.get_pod(pod.metadata.uid).spec.node_name == "accel", \
        "host fallback must still allocate the claim's device"
    claim = hub.get_resource_claim("default", "c1")
    assert claim.status.allocation is not None
    assert claim.status.allocation.node_name == "accel"
    sched.close()
