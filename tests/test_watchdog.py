"""SLO watchdog + incident autopsy (ISSUE 20).

Rule units (SLO breach / counter delta / unattributed compile / fleet
health), the watchdog's poll cadence + incident routing, the autopsy
store's rate-limit / retention / atomic-write contract including torn
readers, the ``python -m kubernetes_tpu.telemetry autopsy`` CLI over
fixture bundles, per-pod critical-path attribution, and one end-to-end
pass: a real scheduler with an unholdable SLO files a parseable
``slo_breach`` bundle from its own maintenance tick.
"""

import json
import os
from types import SimpleNamespace

import pytest

from kubernetes_tpu.metrics import SchedulerMetrics
from kubernetes_tpu.telemetry.autopsy import (
    AutopsyStore,
    critical_path,
    diff_bundles,
    list_bundles,
    load_bundle,
)
from kubernetes_tpu.telemetry.watchdog import (
    CounterDeltaRule,
    FleetUnhealthyRule,
    SloRule,
    UnattributedCompileRule,
    Watchdog,
)
from kubernetes_tpu.utils.tracing import PodTimelines

pytestmark = pytest.mark.autopsy


def mkpodref(uid, name="p", namespace="default"):
    return SimpleNamespace(metadata=SimpleNamespace(
        uid=uid, name=name, namespace=namespace))


def timelines_with_binds(latencies_s):
    """A PodTimelines where pod i bound ``latencies_s[i]`` seconds after
    its enqueue — exactly what time_to_bind_stats reads."""
    tl = PodTimelines()
    for i, lat in enumerate(latencies_s):
        pod = mkpodref(f"u{i}", name=f"p{i}")
        tl.event(pod, "enqueued", t=100.0)
        tl.event(pod, "bound", t=100.0 + lat)
    return tl


# ------------------------------ rules ------------------------------


def test_slo_rule_trips_on_breach_and_gates_on_min_binds():
    sched = SimpleNamespace(timelines=timelines_with_binds([0.5] * 4))
    rule = SloRule({"time_to_bind_p99_ms": 100.0}, min_binds=8)
    # 4 binds < min_binds: a cold start never breaches
    assert rule.evaluate(sched) == []
    sched = SimpleNamespace(timelines=timelines_with_binds([0.5] * 8))
    hits = rule.evaluate(sched)
    assert len(hits) == 1 and hits[0]["kind"] == "slo_breach"
    assert "time_to_bind_p99_ms" in hits[0]["reason"]
    assert hits[0]["details"]["stats"]["count"] == 8
    # a holdable SLO does not trip
    ok_rule = SloRule({"time_to_bind_p99_ms": 10_000.0}, min_binds=8)
    assert ok_rule.evaluate(sched) == []
    # no SLO configured: the rule is inert
    assert SloRule({}, min_binds=0).evaluate(sched) == []


def test_counter_delta_rule_baselines_then_trips():
    box = {"v": 7.0}
    rule = CounterDeltaRule("my_total", "throttle_shed",
                            lambda s: box["v"])
    sched = SimpleNamespace()
    # first poll only baselines — a warm restart must not replay
    # history as a fresh incident
    assert rule.evaluate(sched) == []
    assert rule.evaluate(sched) == []          # flat: no trip
    box["v"] = 10.0
    hits = rule.evaluate(sched)
    assert len(hits) == 1 and hits[0]["kind"] == "throttle_shed"
    assert hits[0]["details"]["delta"] == 3.0
    assert rule.evaluate(sched) == []          # re-baselined
    # a broken/missing counter is not an incident
    broken = CounterDeltaRule("gone", "x",
                              lambda s: s.metrics.nope.value())
    assert broken.evaluate(sched) == []


def test_unattributed_compile_rule_reads_profiler_delta():
    prof = SimpleNamespace(compile_causes={"unattributed": 2})
    sched = SimpleNamespace(profiler=prof)
    rule = UnattributedCompileRule()
    assert rule.evaluate(sched) == []          # baseline
    prof.compile_causes["unattributed"] = 5
    hits = rule.evaluate(sched)
    assert len(hits) == 1
    assert hits[0]["kind"] == "unattributed_compile"
    assert hits[0]["details"] == {"delta": 3, "total": 5}
    assert UnattributedCompileRule().evaluate(
        SimpleNamespace()) == []               # no profiler attached


def test_fleet_unhealthy_rule_names_the_bad_endpoints():
    summary = {"ok": False, "healthy": 1, "total": 2, "endpoints": [
        {"component": "hub", "url": "http://h:1", "healthy": True},
        {"component": "relay", "url": "http://r:2", "healthy": False},
    ]}
    sched = SimpleNamespace(
        fleet=SimpleNamespace(summary=lambda: summary))
    hits = FleetUnhealthyRule().evaluate(sched)
    assert len(hits) == 1 and hits[0]["kind"] == "fleet_unhealthy"
    assert hits[0]["details"]["unhealthy"] == ["relay@http://r:2"]
    summary["ok"] = True
    assert FleetUnhealthyRule().evaluate(sched) == []
    assert FleetUnhealthyRule().evaluate(SimpleNamespace()) == []


# --------------------------- the watchdog ---------------------------


class TripOnce:
    name = "trip_once"
    min_interval_s = 0.0

    def __init__(self):
        self.fired = False

    def evaluate(self, sched):
        if self.fired:
            return []
        self.fired = True
        return [{"kind": "test_trip", "reason": "once"}]


class Broken:
    name = "broken"
    min_interval_s = 0.0

    def evaluate(self, sched):
        raise RuntimeError("rule bug")


def test_watchdog_poll_throttles_counts_and_survives_broken_rules():
    clock = {"t": 1000.0}
    m = SchedulerMetrics()
    sched = SimpleNamespace(metrics=m)
    tripper = TripOnce()
    wd = Watchdog(sched, rules=[Broken(), tripper], store=None,
                  interval_s=5.0, now=lambda: clock["t"])
    assert wd.poll() == 1                      # broken rule skipped
    assert wd.incidents == 1
    assert m.watchdog_incidents.value(kind="test_trip") == 1
    assert m.watchdog_rules_tripped.value(rule="trip_once") == 1
    clock["t"] += 1.0
    assert wd.poll() == 0                      # inside the interval
    assert m.watchdog_evals.value() == 1
    clock["t"] += 5.0
    assert wd.poll() == 0                      # evaluated, no trips
    assert m.watchdog_evals.value() == 2


def test_watchdog_per_rule_min_interval(tmp_path):
    clock = {"t": 0.0}

    class Counting:
        name = "counting"
        min_interval_s = 30.0

        def __init__(self):
            self.calls = 0

        def evaluate(self, sched):
            self.calls += 1
            return []

    rule = Counting()
    wd = Watchdog(SimpleNamespace(metrics=None), rules=[rule],
                  interval_s=0.0, now=lambda: clock["t"])
    wd.poll()
    clock["t"] = 10.0
    wd.poll()                                  # rule's own gate holds
    assert rule.calls == 1
    clock["t"] = 31.0
    wd.poll()
    assert rule.calls == 2


def test_incident_routes_to_store_and_never_raises(tmp_path):
    m = SchedulerMetrics()
    store = AutopsyStore(str(tmp_path), rate_limit_s=0.0, metrics=m)
    sched = SimpleNamespace(metrics=m)
    wd = Watchdog(sched, rules=[], store=store, interval_s=0.0)
    wd.incident("quarantine", reason="poison pod", rule="",
                details={"pod": "default/p0"})
    rows = store.list()
    assert len(rows) == 1 and rows[0]["kind"] == "quarantine"
    doc = store.load(rows[0]["name"])
    assert doc["trigger"]["details"] == {"pod": "default/p0"}
    # collection walked a bare SimpleNamespace: partial bundle, named
    # failures, never an exception out of incident()
    assert doc.get("collect_errors")
    assert m.watchdog_incidents.value(kind="quarantine") == 1


def test_module_incident_helper_noops_without_watchdog():
    from kubernetes_tpu import telemetry

    telemetry.incident(SimpleNamespace(), "whatever")  # must not raise


# --------------------------- the store ---------------------------


def test_store_rate_limits_per_class(tmp_path):
    clock = {"t": 0.0}
    m = SchedulerMetrics()
    store = AutopsyStore(str(tmp_path), rate_limit_s=30.0,
                         now=lambda: clock["t"], metrics=m)
    calls = {"n": 0}

    def collect():
        calls["n"] += 1
        return {"queue": {"stats": {}}}

    assert store.capture({"kind": "quarantine"}, collect) is not None
    # same class inside the window: dropped BEFORE collection runs
    assert store.capture({"kind": "quarantine"}, collect) is None
    assert calls["n"] == 1
    # a different class has its own window
    assert store.capture({"kind": "drift"}, collect) is not None
    clock["t"] = 31.0
    assert store.capture({"kind": "quarantine"}, collect) is not None
    assert m.autopsy_bundles_dropped.value(reason="rate_limited") == 1
    assert m.autopsy_bundles.value(trigger="quarantine") == 2


def test_store_retention_prunes_oldest_by_count_and_bytes(tmp_path):
    m = SchedulerMetrics()
    store = AutopsyStore(str(tmp_path), max_bundles=2, rate_limit_s=0.0,
                         metrics=m)
    for i in range(4):
        store.capture({"kind": f"k{i}"}, lambda: {"pad": "x" * 64})
    rows = store.list()
    assert [r["seq"] for r in rows] == [3, 4]   # newest two survive
    assert m.autopsy_bundles_dropped.value(reason="retention") == 2
    assert list(m.autopsy_store_bytes.collect().values()) == [
        sum(r["bytes"] for r in rows)]
    # bytes cap: a store too small for two bundles keeps only the newest
    small = AutopsyStore(str(tmp_path / "small"), max_bundles=100,
                         max_bytes=4096, rate_limit_s=0.0)
    for i in range(3):
        small.capture({"kind": "big"}, lambda: {"pad": "y" * 3000})
    assert len(small.list()) == 1


def test_store_resumes_seq_after_restart(tmp_path):
    store = AutopsyStore(str(tmp_path), rate_limit_s=0.0)
    store.capture({"kind": "drift"}, lambda: {})
    store.capture({"kind": "drift"}, lambda: {})
    reborn = AutopsyStore(str(tmp_path), rate_limit_s=0.0)
    reborn.capture({"kind": "drift"}, lambda: {})
    assert [r["seq"] for r in reborn.list()] == [1, 2, 3]


def test_failed_collection_still_files_trigger_only_bundle(tmp_path):
    store = AutopsyStore(str(tmp_path), rate_limit_s=0.0)

    def explode():
        raise RuntimeError("collector bug")

    path = store.capture({"kind": "cycle_crash", "reason": "r"}, explode)
    doc = load_bundle(path)
    assert doc["trigger"]["kind"] == "cycle_crash"
    assert doc["collect_errors"]


def test_torn_bundle_listing_and_strict_load(tmp_path):
    store = AutopsyStore(str(tmp_path), rate_limit_s=0.0)
    store.capture({"kind": "drift"}, lambda: {})
    torn = tmp_path / "autopsy-000099-torn.json"
    torn.write_text('{"format": 1, "trigger": {"kind": "dri')
    # a writer killed mid-replace leaves only a .tmp — never listed
    (tmp_path / "autopsy-000100-x.json.tmp").write_text("{}")
    rows = list_bundles(str(tmp_path))
    assert len(rows) == 2
    assert "error" not in rows[0]
    assert "error" in rows[1]
    with pytest.raises(ValueError, match="torn or invalid"):
        load_bundle(str(torn))
    # not-a-bundle and future-format docs are rejected strictly
    notb = tmp_path / "autopsy-000101-n.json"
    notb.write_text("[1, 2]")
    with pytest.raises(ValueError, match="not an autopsy bundle"):
        load_bundle(str(notb))
    newer = tmp_path / "autopsy-000102-f.json"
    newer.write_text(json.dumps({"format": 99, "trigger": {}}))
    with pytest.raises(ValueError, match="newer than this reader"):
        load_bundle(str(newer))


# ------------------------ diff + critical path ------------------------


def fixture_timeline():
    return {
        "uid": "u-cp", "name": "cp", "namespace": "default",
        "events": [
            {"t": 1.0, "event": "enqueued", "detail": ""},
            {"t": 1.5, "event": "popped", "detail": "attempt 1"},
            {"t": 2.0, "event": "popped", "detail": "attempt 2"},
            {"t": 2.5, "event": "bound", "detail": "node-0"},
        ],
        "wire": {"created": {"t": 0.5}, "bound": {"t": 2.6},
                 "kubelet_recv": {"t": 2.7}, "acked": {"t": 2.8}},
    }


def test_critical_path_attributes_every_leg():
    rep = critical_path(fixture_timeline())
    assert rep["pod"] == "default/cp"
    assert rep["missing"] == []
    by_leg = {l["leg"]: l for l in rep["legs"]}
    assert by_leg["watch"]["ms"] == 500.0
    assert by_leg["queue"]["ms"] == 500.0
    assert by_leg["retries"]["ms"] == 500.0
    assert by_leg["schedule"]["ms"] == 500.0
    assert by_leg["hub_commit"]["ms"] == pytest.approx(100.0)
    assert rep["attributed_ms"] == {
        "binder": pytest.approx(100.0),
        "device": pytest.approx(500.0),
        "fabric": pytest.approx(700.0),
        "queue": pytest.approx(1000.0)}
    assert rep["total_ms"] == pytest.approx(2300.0)


def test_critical_path_names_missing_legs():
    tl = fixture_timeline()
    tl["wire"] = {}
    rep = critical_path(tl)
    assert set(rep["missing"]) == {"watch", "hub_commit",
                                   "fabric_relay", "kubelet_ack"}
    # total falls back to enqueued -> bound
    assert rep["total_ms"] == pytest.approx(1500.0)


def test_diff_bundles_reports_stat_phase_and_slo_movement(tmp_path):
    a = {"seq": 1, "captured_at": 10.0, "trigger": {"kind": "drift"},
         "queue": {"stats": {"bound": 4, "attempts": 6}},
         "flight": {"phases": {"device_launch": {"p99_ms": 2.0}}},
         "slo_stats": {"time_to_bind_p99_ms": 40.0}}
    b = {"seq": 2, "captured_at": 12.5, "trigger": {"kind": "drift"},
         "queue": {"stats": {"bound": 9, "attempts": 6}},
         "flight": {"phases": {"device_launch": {"p99_ms": 3.5}}},
         "slo_stats": {"time_to_bind_p99_ms": 55.0}}
    d = diff_bundles(a, b)
    assert d["seconds_apart"] == 2.5
    assert d["stats_delta"] == {"bound": 5}
    assert d["phase_p99_delta"]["device_launch"] == {
        "p99_ms_a": 2.0, "p99_ms_b": 3.5}
    assert d["slo_delta"]["time_to_bind_p99_ms"] == {"a": 40.0,
                                                     "b": 55.0}


# ------------------------------ the CLI ------------------------------


def make_fixture_store(tmp_path):
    store = AutopsyStore(str(tmp_path), rate_limit_s=0.0)
    store.capture(
        {"kind": "device_fallback", "reason": "nan batch", "rule": ""},
        lambda: {"queue": {"stats": {"bound": 4}},
                 "timelines": [fixture_timeline()]})
    store.capture(
        {"kind": "slo_breach", "reason": "p99 over", "rule": "slo"},
        lambda: {"queue": {"stats": {"bound": 9}},
                 "timelines": [fixture_timeline()]})
    return store


def cli(args):
    from kubernetes_tpu.telemetry.__main__ import main

    return main(args)


def test_cli_list_show_diff_critical_path(tmp_path, capsys):
    make_fixture_store(tmp_path)
    d = str(tmp_path)
    assert cli(["autopsy", "list", "--dir", d]) == 0
    out = capsys.readouterr().out
    assert "device_fallback" in out and "slo_breach" in out

    assert cli(["autopsy", "list", "--dir", d, "--json"]) == 0
    rows = json.loads(capsys.readouterr().out)
    assert [r["seq"] for r in rows] == [1, 2]

    name = rows[1]["name"]
    assert cli(["autopsy", "show", name, "--dir", d]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["trigger"]["rule"] == "slo"
    assert cli(["autopsy", "show", name, "--dir", d,
                "--section", "queue"]) == 0
    assert json.loads(capsys.readouterr().out) == {"stats": {"bound": 9}}
    assert cli(["autopsy", "show", name, "--dir", d,
                "--section", "nope"]) == 1
    capsys.readouterr()

    assert cli(["autopsy", "diff", rows[0]["name"], name,
                "--dir", d]) == 0
    dd = json.loads(capsys.readouterr().out)
    assert dd["stats_delta"] == {"bound": 5}

    assert cli(["autopsy", "critical-path", name, "--dir", d,
                "--json"]) == 0
    reps = json.loads(capsys.readouterr().out)
    assert reps[0]["pod"] == "default/cp"
    assert cli(["autopsy", "critical-path", name, "--dir", d,
                "--pod", "default/cp"]) == 0
    assert "default/cp" in capsys.readouterr().out
    assert cli(["autopsy", "critical-path", name, "--dir", d,
                "--pod", "ghost"]) == 1
    capsys.readouterr()


def test_cli_errors_nonzero_on_torn_bundle(tmp_path, capsys):
    torn = tmp_path / "autopsy-000001-torn.json"
    torn.write_text('{"trigger": ')
    assert cli(["autopsy", "show", torn.name,
                "--dir", str(tmp_path)]) == 1
    assert "error:" in capsys.readouterr().err


# --------------------------- end to end ---------------------------


def test_scheduler_files_slo_breach_bundle_end_to_end(tmp_path):
    from kubernetes_tpu.api.objects import (
        Container,
        LABEL_HOSTNAME,
        Node,
        NodeStatus,
        ObjectMeta,
        Pod,
        PodSpec,
        ResourceRequirements,
    )
    from kubernetes_tpu.config.types import default_config
    from kubernetes_tpu.hub import Hub
    from kubernetes_tpu.ops.features import Capacities
    from kubernetes_tpu.scheduler import Scheduler

    hub = Hub()
    hub.create_node(Node(
        metadata=ObjectMeta(name="n0",
                            labels={LABEL_HOSTNAME: "n0"}),
        status=NodeStatus(allocatable={"cpu": "8", "memory": "16Gi",
                                       "pods": "110"})))
    cfg = default_config()
    cfg.batch_size = 4
    cfg.autopsy_dir = str(tmp_path)
    cfg.autopsy_rate_limit_s = 0.0
    cfg.watchdog_interval_s = 0.0
    cfg.watchdog_min_binds = 1
    # no real scheduler can bind in a femtosecond: guaranteed breach
    cfg.watchdog_slo = {"time_to_bind_p99_ms": 1e-9}
    sched = Scheduler(hub, cfg, caps=Capacities(nodes=4, pods=16))
    try:
        for i in range(4):
            hub.create_pod(Pod(
                metadata=ObjectMeta(name=f"e2e-{i}"),
                spec=PodSpec(containers=[Container(
                    name="c", resources=ResourceRequirements(
                        requests={"cpu": "100m"}))])))
        sched.run_until_idle()
        sched.run_maintenance()
    finally:
        sched.close()
    rows = [r for r in list_bundles(str(tmp_path))
            if r.get("kind") == "slo_breach"]
    assert rows, "watchdog never filed the breach bundle"
    doc = load_bundle(os.path.join(str(tmp_path), rows[0]["name"]))
    assert doc["trigger"]["rule"] == "slo"
    assert doc["slo_stats"]["count"] == 4
    # the bundle's timelines drive the critical-path CLI
    reps = [critical_path(t) for t in doc["timelines"]]
    assert any(r["total_ms"] is not None for r in reps)
    assert sched.watchdog.incidents >= 1
