"""Full LabelSelector / namespaceSelector / mismatchLabelKeys semantics for
pod-(anti)affinity terms and topology-spread constraints.

Reference semantics: framework/types.go:537 (AffinityTerm.Matches),
interpodaffinity/plugin.go:123 (mergeAffinityTermNamespacesIfNotEmpty),
registry/core/pod/strategy.go:846-903 (match/mismatchLabelKeys merged as
In/NotIn requirements). Built with real objects through the
Cache -> Snapshot -> Mirror path, evaluated via the batched pipeline."""

import numpy as np

from kubernetes_tpu.api.objects import (
    Affinity,
    Container,
    LABEL_HOSTNAME,
    LABEL_ZONE,
    LabelSelector,
    LabelSelectorRequirement,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodAffinity,
    PodAffinityTerm,
    PodAntiAffinity,
    PodSpec,
    ResourceRequirements,
    TopologySpreadConstraint,
)
from kubernetes_tpu.backend.cache import Cache
from kubernetes_tpu.backend.mirror import Mirror
from kubernetes_tpu.backend.snapshot import Snapshot
from kubernetes_tpu.models.pipeline import default_weights, launch_batch
from kubernetes_tpu.ops.features import Capacities

CAPS = Capacities(nodes=16, pods=64, domains=16)


def mknode(name, zone):
    return Node(metadata=ObjectMeta(name=name, labels={
        LABEL_HOSTNAME: name, LABEL_ZONE: zone}),
        status=NodeStatus(allocatable={"cpu": "32", "memory": "64Gi",
                                       "pods": "110"}))


def mkpod(name, labels=None, node=None, affinity=None, tsc=None, ns="default"):
    return Pod(
        metadata=ObjectMeta(name=name, namespace=ns, labels=labels or {}),
        spec=PodSpec(
            node_name=node or "",
            containers=[Container(name="c", resources=ResourceRequirements(
                requests={"cpu": "100m", "memory": "64Mi"}))],
            affinity=affinity,
            topology_spread_constraints=tsc or [],
        ))


def expr(key, op, *values):
    return LabelSelectorRequirement(key=key, operator=op, values=list(values))


def anti_term(topokey, selector=None, **kw):
    return Affinity(pod_anti_affinity=PodAntiAffinity(required=[
        PodAffinityTerm(topology_key=topokey, label_selector=selector, **kw)]))


def aff_term(topokey, selector=None, **kw):
    return Affinity(pod_affinity=PodAffinity(required=[
        PodAffinityTerm(topology_key=topokey, label_selector=selector, **kw)]))


class Cluster:
    def __init__(self, nodes, scheduled=(), namespaces=None):
        self.cache = Cache()
        for n in nodes:
            self.cache.add_node(n)
        for name, labels in (namespaces or {}).items():
            self.cache.set_namespace(name, labels)
        for p in scheduled:
            self.cache.add_pod(p)
        self.snap = Snapshot()
        self.cache.update_snapshot(self.snap)
        self.mirror = Mirror(caps=CAPS)
        self.mirror.sync(self.snap)

    def resync(self):
        self.cache.update_snapshot(self.snap)
        self.mirror.sync(self.snap)

    def run(self, pods):
        spec = self.mirror.prepare_launch(pods, 8)
        out = launch_batch(spec, self.mirror.well_known(),
                           default_weights(), CAPS)
        names = [self.mirror.name_of_row(int(r)) if r >= 0 else None
                 for r in np.asarray(out.node_row)[: len(pods)]]
        return names, out


ZONES = [mknode("n1", "z1"), mknode("n2", "z1"), mknode("n3", "z2")]


# --------------- full selector operators in affinity terms ---------------


def test_anti_affinity_notin_expression():
    """NotIn: the term matches pods whose label is NOT in the set — the
    incoming pod must avoid the zone of every pod with env != prod."""
    cl = Cluster(ZONES, [
        mkpod("a", {"env": "dev"}, node="n1"),
        mkpod("b", {"env": "prod"}, node="n3"),
    ])
    sel = LabelSelector(match_expressions=[expr("env", "NotIn", "prod")])
    names, _ = cl.run([mkpod("p", affinity=anti_term(LABEL_ZONE, sel))])
    assert names == ["n3"]  # z1 hosts the env=dev pod (matched by NotIn)


def test_anti_affinity_exists_expression():
    cl = Cluster(ZONES, [mkpod("a", {"gpu": "yes"}, node="n3")])
    sel = LabelSelector(match_expressions=[expr("gpu", "Exists")])
    names, _ = cl.run([mkpod("p", affinity=anti_term(LABEL_ZONE, sel))])
    assert names[0] in ("n1", "n2")


def test_anti_affinity_multi_value_in():
    cl = Cluster(ZONES, [
        mkpod("a", {"app": "web"}, node="n1"),
        mkpod("b", {"app": "api"}, node="n3"),
    ])
    sel = LabelSelector(match_expressions=[expr("app", "In", "web", "api")])
    names, _ = cl.run([mkpod("p", affinity=anti_term(LABEL_ZONE, sel))])
    assert names == [None]  # both zones blocked


def test_affinity_doesnotexist_expression():
    """Required affinity whose selector matches pods lacking a label."""
    cl = Cluster(ZONES, [
        mkpod("plain", {}, node="n3"),
        mkpod("labeled", {"special": "1"}, node="n1"),
    ])
    sel = LabelSelector(match_expressions=[expr("special", "DoesNotExist")])
    names, _ = cl.run([mkpod("p", affinity=aff_term(LABEL_ZONE, sel))])
    assert names == ["n3"]


def test_unknown_operator_matches_nothing():
    """Malformed operator: the requirement matches no pod (parse-error ->
    no-match), so a required-affinity term can never be satisfied."""
    cl = Cluster(ZONES, [mkpod("a", {"app": "web"}, node="n1")])
    sel = LabelSelector(match_expressions=[expr("app", "Bogus", "web")])
    names, _ = cl.run([mkpod("p", affinity=aff_term(LABEL_ZONE, sel))])
    assert names == [None]


# --------------- namespaceSelector ---------------


def test_namespace_selector_unrolled():
    """Anti-affinity with a namespaceSelector applies across the selected
    namespaces (term owner in 'default', victim pod in 'team-a')."""
    other = mkpod("o", {"app": "web"}, node="n1", ns="team-a")
    cl = Cluster(ZONES, [other],
                 namespaces={"team-a": {"tier": "x"}, "team-b": {}})
    sel = LabelSelector(match_labels={"app": "web"})
    nssel = LabelSelector(match_labels={"tier": "x"})
    names, _ = cl.run([mkpod("p", affinity=anti_term(
        LABEL_ZONE, sel, namespace_selector=nssel))])
    assert names == ["n3"]
    # without the nsSelector the term only covers the owner's namespace
    names2, _ = cl.run([mkpod("q", affinity=anti_term(LABEL_ZONE, sel))])
    assert names2[0] in ("n1", "n2", "n3")  # team-a pod not matched


def test_empty_namespace_selector_matches_all():
    other = mkpod("o", {"app": "web"}, node="n1", ns="anywhere")
    cl = Cluster(ZONES, [other], namespaces={"anywhere": {}})
    sel = LabelSelector(match_labels={"app": "web"})
    names, _ = cl.run([mkpod("p", affinity=anti_term(
        LABEL_ZONE, sel, namespace_selector=LabelSelector()))])
    assert names == ["n3"]


def test_table_pod_ns_selector_repacks_on_namespace_change():
    """An existing pod's anti-affinity with namespaceSelector must see
    namespaces created AFTER it was packed (mirror repacks on ns change)."""
    sel = LabelSelector(match_labels={"app": "web"})
    nssel = LabelSelector(match_labels={"tier": "x"})
    guard = mkpod("guard", {}, node="n1", affinity=anti_term(
        LABEL_ZONE, sel, namespace_selector=nssel))
    cl = Cluster(ZONES, [guard])
    # incoming web pod from team-a: no namespace labeled tier=x yet
    p1 = mkpod("p1", {"app": "web"}, ns="team-a")
    names, _ = cl.run([p1])
    assert names[0] in ("n1", "n2", "n3")
    # label team-a as tier=x -> the guard's unrolled term now covers it
    cl.cache.set_namespace("team-a", {"tier": "x"})
    cl.resync()
    names2, _ = cl.run([mkpod("p2", {"app": "web"}, ns="team-a")])
    assert names2 == ["n3"]


def test_ns_selector_matches_namespace_without_object():
    """A namespace with no Namespace object has nil labels; a DoesNotExist
    namespaceSelector requirement must match it (AffinityTerm.Matches with
    empty nsLabels) even though it never appears in the store."""
    other = mkpod("o", {"app": "web"}, node="n1", ns="no-object-ns")
    cl = Cluster(ZONES, [other])  # note: no namespaces fed at all
    sel = LabelSelector(match_labels={"app": "web"})
    nssel = LabelSelector(match_expressions=[expr("restricted",
                                                  "DoesNotExist")])
    names, _ = cl.run([mkpod("p", affinity=anti_term(
        LABEL_ZONE, sel, namespace_selector=nssel))])
    assert names == ["n3"]


# --------------- match/mismatchLabelKeys ---------------


def test_mismatch_label_keys_anti_affinity():
    """mismatchLabelKeys merges 'key NotIn (own value)': anti-affinity to
    other apps' pods but not to the pod's own app group."""
    cl = Cluster(ZONES, [
        mkpod("same", {"app": "me", "kind": "w"}, node="n3"),
        mkpod("other", {"app": "you", "kind": "w"}, node="n1"),
    ])
    sel = LabelSelector(match_labels={"kind": "w"})
    p = mkpod("p", {"app": "me"}, affinity=anti_term(
        LABEL_ZONE, sel, mismatch_label_keys=["app"]))
    names, _ = cl.run([p])
    # z1 blocked (app=you, kind=w matches); z3's pod shares app=me -> excluded
    assert names == ["n3"]


def test_match_label_keys_affinity():
    """matchLabelKeys merges 'key In (own value)': co-locate only with the
    same version group."""
    cl = Cluster(ZONES, [
        mkpod("v1", {"app": "w", "ver": "1"}, node="n1"),
        mkpod("v2", {"app": "w", "ver": "2"}, node="n3"),
    ])
    sel = LabelSelector(match_labels={"app": "w"})
    p = mkpod("p", {"ver": "2"}, affinity=aff_term(
        LABEL_ZONE, sel, match_label_keys=["ver"]))
    names, _ = cl.run([p])
    assert names == ["n3"]


# --------------- spread constraints with full selectors ---------------


def test_spread_selector_expressions():
    """Spread counts pods via matchExpressions (In with two values)."""
    tsc = TopologySpreadConstraint(
        max_skew=1, topology_key=LABEL_ZONE, when_unsatisfiable="DoNotSchedule",
        label_selector=LabelSelector(match_expressions=[
            expr("app", "In", "web", "api")]))
    cl = Cluster(ZONES, [
        mkpod("a", {"app": "web"}, node="n1"),
        mkpod("b", {"app": "api"}, node="n1"),
    ])
    p = mkpod("p", {"app": "web"}, tsc=[tsc])
    names, _ = cl.run([p])
    assert names == ["n3"]  # z1 has 2 matches, z2 has 0: skew forces z2


# --------------- host oracle parity ---------------


def test_host_oracle_matches_device_semantics():
    m = Mirror(caps=CAPS)
    owner = mkpod("o", {"app": "me"})
    term = PodAffinityTerm(
        topology_key=LABEL_ZONE,
        label_selector=LabelSelector(match_expressions=[
            expr("env", "NotIn", "prod")]),
        mismatch_label_keys=["app"])
    # env=dev matches NotIn; app differs -> mismatch NotIn passes
    assert m.term_matches_pod(term, owner, mkpod("t1", {"env": "dev",
                                                        "app": "you"}))
    # same app -> excluded by mismatchLabelKeys
    assert not m.term_matches_pod(term, owner, mkpod("t2", {"env": "dev",
                                                            "app": "me"}))
    # env=prod -> NotIn fails
    assert not m.term_matches_pod(term, owner, mkpod("t3", {"env": "prod",
                                                            "app": "you"}))
    # label absent -> NotIn passes
    assert m.term_matches_pod(term, owner, mkpod("t4", {"app": "you"}))
    # nil selector matches nothing
    nil_term = PodAffinityTerm(topology_key=LABEL_ZONE)
    assert not m.term_matches_pod(nil_term, owner, mkpod("t5", {}))


# suite-tier discipline (tests/test_markers.py): area marker
import pytest  # noqa: E402
pytestmark = pytest.mark.core
