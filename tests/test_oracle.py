"""Randomized equivalence suites (SURVEY §7.2 hard part 2):

1. Serial-oracle replay: fuzzed workloads (resources, anti-affinity,
   affinity, hard spread) run through the device commit scan; a plain-
   python oracle replays the placements in batch order asserting every
   commit was feasible AT ITS TURN, no node was ever overcommitted, and
   every unschedulable verdict had no feasible node.
2. Auction-vs-scan property: no-topology fuzzed workloads at 1k nodes run
   through BOTH commit modes; placement counts must match, neither mode
   may overcommit, and the load balance must agree within tolerance.
"""

import random

import numpy as np
import pytest

from kubernetes_tpu.api.objects import (
    Affinity,
    Container,
    LABEL_HOSTNAME,
    LABEL_ZONE,
    LabelSelector,
    Node,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodAffinity,
    PodAffinityTerm,
    PodAntiAffinity,
    PodSpec,
    ResourceRequirements,
    TopologySpreadConstraint,
)
from kubernetes_tpu.api.labels import label_selector_matches
from kubernetes_tpu.api.resources import pod_request
from kubernetes_tpu.backend.cache import Cache
from kubernetes_tpu.backend.mirror import Mirror
from kubernetes_tpu.backend.snapshot import Snapshot
from kubernetes_tpu.models.pipeline import default_weights, launch_batch
from kubernetes_tpu.ops.features import Capacities

WEIGHTS = default_weights()


def mknode(i, rng):
    name = f"node-{i}"
    return Node(
        metadata=ObjectMeta(name=name, labels={
            LABEL_HOSTNAME: name, LABEL_ZONE: f"z{i % 3}"}),
        spec=NodeSpec(),
        status=NodeStatus(allocatable={
            "cpu": f"{rng.choice([2, 4, 8])}",
            "memory": f"{rng.choice([4, 8, 16])}Gi",
            "pods": "110"}))


def fuzz_pod(i, rng):
    labels = {}
    if rng.random() < 0.5:
        labels["app"] = f"a{rng.randrange(3)}"
    affinity = None
    tsc = []
    r = rng.random()
    sel = LabelSelector(match_labels={"app": f"a{rng.randrange(3)}"})
    key = rng.choice([LABEL_HOSTNAME, LABEL_ZONE])
    if r < 0.15:
        affinity = Affinity(pod_anti_affinity=PodAntiAffinity(
            required=[PodAffinityTerm(topology_key=key,
                                      label_selector=sel)]))
    elif r < 0.25:
        affinity = Affinity(pod_affinity=PodAffinity(
            required=[PodAffinityTerm(topology_key=key,
                                      label_selector=sel)]))
    elif r < 0.40:
        tsc = [TopologySpreadConstraint(
            max_skew=rng.choice([1, 2]), topology_key=key,
            when_unsatisfiable="DoNotSchedule", label_selector=sel)]
    return Pod(
        metadata=ObjectMeta(name=f"pod-{i}", labels=labels),
        spec=PodSpec(containers=[Container(
            name="c", resources=ResourceRequirements(requests={
                "cpu": f"{rng.choice([100, 250, 500, 1000])}m",
                "memory": f"{rng.choice([128, 256, 512])}Mi"}))],
            affinity=affinity,
            topology_spread_constraints=tsc))


# --------------------------- the host oracle ---------------------------


def _dom(node, key):
    return node.metadata.labels.get(key)


def _matches_term(term, other: Pod, pending_ns="default"):
    namespaces = term.namespaces or [pending_ns]
    if other.metadata.namespace not in namespaces:
        return False
    return label_selector_matches(term.label_selector, other.metadata.labels)


class Oracle:
    """Plain-python as-if-serial state: nodes + (existing and committed)
    pods, with the same filter semantics as the device kernels."""

    def __init__(self, nodes):
        self.nodes = {n.metadata.name: n for n in nodes}
        self.free = {}
        for n in nodes:
            r = pod_request(Pod())  # zero
            from kubernetes_tpu.api.resources import Resource

            alloc = Resource.from_map(n.status.allocatable)
            self.free[n.metadata.name] = [alloc.milli_cpu, alloc.memory]
        self.placed: dict[str, list[Pod]] = {n.metadata.name: []
                                             for n in nodes}

    def all_pods(self):
        for pods in self.placed.values():
            yield from pods

    def commit(self, pod, node_name):
        req = pod_request(pod)
        self.free[node_name][0] -= req.milli_cpu
        self.free[node_name][1] -= req.memory
        self.placed[node_name].append(pod)

    def feasible(self, pod, node_name) -> bool:
        node = self.nodes[node_name]
        req = pod_request(pod)
        if req.milli_cpu > self.free[node_name][0] \
                or req.memory > self.free[node_name][1]:
            return False
        aff = pod.spec.affinity
        # the pod's own required anti-affinity
        if aff is not None and aff.pod_anti_affinity is not None:
            for term in aff.pod_anti_affinity.required:
                d = _dom(node, term.topology_key)
                if d is None:
                    continue
                for other_name, pods in self.placed.items():
                    if _dom(self.nodes[other_name],
                            term.topology_key) != d:
                        continue
                    if any(_matches_term(term, q) for q in pods):
                        return False
        # existing pods' required anti-affinity vs the incoming pod
        for other_name, pods in self.placed.items():
            for q in pods:
                qa = q.spec.affinity
                if qa is None or qa.pod_anti_affinity is None:
                    continue
                for term in qa.pod_anti_affinity.required:
                    if not _matches_term(term, pod,
                                         q.metadata.namespace):
                        continue
                    dq = _dom(self.nodes[other_name], term.topology_key)
                    if dq is not None \
                            and dq == _dom(node, term.topology_key):
                        return False
        # required affinity (incl. the first-pod-of-a-group rule)
        if aff is not None and aff.pod_affinity is not None:
            terms = aff.pod_affinity.required
            any_match = any(
                _matches_term(t, q)
                for t in terms for q in self.all_pods())
            per_term_ok = True
            for term in terms:
                d = _dom(node, term.topology_key)
                if d is None:
                    per_term_ok = False
                    break
                found = False
                for other_name, pods in self.placed.items():
                    if _dom(self.nodes[other_name],
                            term.topology_key) != d:
                        continue
                    if any(_matches_term(term, q) for q in pods):
                        found = True
                        break
                if not found:
                    per_term_ok = False
                    break
            if not per_term_ok:
                self_ok = (not any_match
                           and all(_dom(node, t.topology_key) is not None
                                   for t in terms)
                           and all(label_selector_matches(
                               t.label_selector, pod.metadata.labels)
                               for t in terms))
                if not self_ok:
                    return False
        # hard topology spread
        for c in pod.spec.topology_spread_constraints:
            if c.when_unsatisfiable != "DoNotSchedule":
                continue
            d = _dom(node, c.topology_key)
            if d is None:
                return False
            counts: dict[str, int] = {}
            for other_name in self.nodes:
                od = _dom(self.nodes[other_name], c.topology_key)
                if od is None:
                    continue
                counts.setdefault(od, 0)
                counts[od] += sum(
                    1 for q in self.placed[other_name]
                    if q.metadata.namespace == pod.metadata.namespace
                    and label_selector_matches(c.label_selector,
                                               q.metadata.labels))
            if not counts:
                return False
            min_cnt = min(counts.values())
            self_match = 1 if label_selector_matches(
                c.label_selector, pod.metadata.labels) else 0
            if counts[d] + self_match - min_cnt > c.max_skew:
                return False
        return True


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_serial_oracle_replay(seed):
    rng = random.Random(seed)
    caps = Capacities(nodes=16, pods=128)
    nodes = [mknode(i, rng) for i in range(12)]
    pods = [fuzz_pod(i, rng) for i in range(48)]
    cache = Cache()
    for n in nodes:
        cache.add_node(n)
    snap = Snapshot()
    cache.update_snapshot(snap)
    mirror = Mirror(caps=caps)
    mirror.sync(snap)
    spec = mirror.prepare_launch(pods, 64)
    out = launch_batch(spec, mirror.well_known(), WEIGHTS, caps)
    rows = np.asarray(out.node_row)[: len(pods)].tolist()

    oracle = Oracle(nodes)
    for pod, row in zip(pods, rows):
        if row >= 0:
            name = mirror.name_of_row(row)
            assert oracle.feasible(pod, name), \
                f"{pod.metadata.name} placed on infeasible {name}"
            oracle.commit(pod, name)
        else:
            bad = [n for n in oracle.nodes
                   if oracle.feasible(pod, n)]
            assert not bad, \
                f"{pod.metadata.name} unschedulable but {bad} feasible"
    # no overcommit anywhere
    for name, (cpu, mem) in oracle.free.items():
        assert cpu >= 0 and mem >= 0, f"{name} overcommitted"


# --------------------- auction vs scan at 1k nodes ---------------------


@pytest.mark.parametrize("seed", [0, 1])
def test_auction_vs_scan_property_1k_nodes(seed):
    rng = random.Random(100 + seed)
    caps = Capacities(nodes=1024, pods=256)
    nodes = [mknode(i, rng) for i in range(1000)]
    pods = []
    for i in range(128):
        p = fuzz_pod(i, rng)
        p.spec.affinity = None          # no-topology fuzz: auction domain
        p.spec.topology_spread_constraints = []
        pods.append(p)
    cache = Cache()
    for n in nodes:
        cache.add_node(n)
    snap = Snapshot()
    cache.update_snapshot(snap)
    mirror = Mirror(caps=caps)
    mirror.sync(snap)
    spec = mirror.prepare_launch(pods, 128)
    assert not spec.enable_topology

    results = {}
    for mode in ("scan", "auction"):
        out = launch_batch(spec, mirror.well_known(), WEIGHTS, caps,
                           serial_scan=(mode == "scan"))
        rows = np.asarray(out.node_row)[: len(pods)].tolist()
        oracle = Oracle(nodes)
        for pod, row in zip(pods, rows):
            if row >= 0:
                oracle.commit(pod, mirror.name_of_row(row))
        for name, (cpu, mem) in oracle.free.items():
            assert cpu >= 0 and mem >= 0, \
                f"{mode}: {name} overcommitted"
        placed = [r for r in rows if r >= 0]
        results[mode] = {
            "count": len(placed),
            "per_node": np.bincount(placed, minlength=caps.nodes),
        }
    assert results["scan"]["count"] == results["auction"]["count"], \
        "both commit modes must place the same number of pods"
    # balance: neither mode may hotspot relative to the other
    assert abs(int(results["scan"]["per_node"].max())
               - int(results["auction"]["per_node"].max())) <= 3


# suite-tier discipline (tests/test_markers.py): area marker
pytestmark = pytest.mark.core
