"""Out-of-process control-plane fabric (ISSUE 11): shard processes,
the stateless bin1 router, per-shard resume cursors, ring rebalancing,
and relay auto-topology.

Most tests run the REAL wire with in-thread shard servers (the routing
and cursor logic is identical; threads keep tier-1 fast); the
subprocess tests spawn actual OS processes — a seconds-scale
two-process smoke stays tier-1, the storm-scale batteries are
slow-marked (bench.py --fanout-smoke / chaos --storm proc run them at
full size).
"""

from __future__ import annotations

import os
import threading
import time

import pytest

from kubernetes_tpu.fabric.cluster import (
    RING_SLOTS,
    ClusterClient,
    ProcShardHub,
    StateCore,
    ring_slot,
)
from kubernetes_tpu.fabric.router import RouterServer, fetch_topology
from kubernetes_tpu.hub import EventHandlers, Fenced, NotFound
from kubernetes_tpu.hubclient import RemoteHub
from kubernetes_tpu.hubserver import HubServer
from kubernetes_tpu.testing import MakeNode, MakePod

pytestmark = pytest.mark.fabric_proc


class _ThreadCluster:
    """The full fabric topology with in-thread shard servers: real
    HTTP, real routing, real cursors — no subprocess spawn cost."""

    def __init__(self, pod_shards: int = 2, tmp_path=None,
                 wal_codec: str = "bin1"):
        self.pod_names = [f"pods-{i}" for i in range(pod_shards)]
        self.state_core = StateCore(pod_shards=self.pod_names)
        self.state_srv = HubServer(self.state_core).start()
        self.state_url = self.state_srv.address
        self.hubs: dict[str, ProcShardHub] = {}
        self.servers: dict[str, HubServer] = {}
        self._state_clients: list[RemoteHub] = []
        specs = [("nodes", ["nodes"]), ("events", ["events"]),
                 ("meta", ["*"])]
        specs += [(n, ["pods"]) for n in self.pod_names]
        for name, kinds in specs:
            sc = RemoteHub(self.state_url, timeout=10.0)
            self._state_clients.append(sc)
            wal = str(tmp_path / f"{name}.wal") if tmp_path else None
            hub = ProcShardHub(name, sc, wal_path=wal,
                               wal_codec=wal_codec)
            srv = HubServer(hub).start()
            self.hubs[name] = hub
            self.servers[name] = srv
            self.state_core.fabric_register_shard(
                name, srv.address, kinds, os.getpid())
        self.router = RouterServer(self.state_url).start()
        self.router_url = self.router.address

    def restart_shard(self, name: str, tmp_path=None,
                      wal_codec: str = "bin1"):
        """The in-thread analog of a process restart: tear the shard's
        server down (watchers cut), rebuild the hub from its WAL, and
        re-register on a NEW port."""
        self.servers[name].stop()
        self.hubs[name].close()
        sc = RemoteHub(self.state_url, timeout=10.0)
        self._state_clients.append(sc)
        wal = str(tmp_path / f"{name}.wal") if tmp_path else None
        hub = ProcShardHub(name, sc, wal_path=wal, wal_codec=wal_codec)
        srv = HubServer(hub).start()
        self.hubs[name] = hub
        self.servers[name] = srv
        kinds = ["pods"] if name in self.pod_names else \
            {"nodes": ["nodes"], "events": ["events"],
             "meta": ["*"]}[name]
        self.state_core.fabric_register_shard(name, srv.address, kinds,
                                              os.getpid())
        return srv

    def stop(self) -> None:
        self.router.stop()
        for srv in self.servers.values():
            srv.stop()
        for hub in self.hubs.values():
            hub.close()
        for sc in self._state_clients:
            sc.close()
        self.state_srv.stop()


@pytest.fixture()
def cluster(tmp_path):
    c = _ThreadCluster(pod_shards=2, tmp_path=tmp_path)
    yield c
    c.stop()


# ------------------------- shared-state shard -------------------------


def test_state_shard_rv_allocation_and_fencing():
    core = StateCore(pod_shards=["pods-0"])
    srv = HubServer(core).start()
    a = RemoteHub(srv.address, timeout=10.0)
    b = RemoteHub(srv.address, timeout=10.0)
    try:
        seen = [a.rv.next(), b.rv.next(), a.rv.next()]
        assert seen == sorted(seen) and len(set(seen)) == 3
        assert b.rv.last() == seen[-1]
        a.rv.advance_to(100)
        assert b.rv.next() == 101
        # fencing epochs over the wire
        from kubernetes_tpu.leaderelection import Lease

        assert a.leases.epoch_of("kube-scheduler") == 0
        a.leases.update(Lease(name="kube-scheduler",
                              holder_identity="x", renew_time=1.0,
                              acquire_time=1.0), None)
        assert b.leases.epoch_of("kube-scheduler") == 1
        # ring CAS
        ring = a.fabric_ring()
        assert ring["epoch"] == 1 and len(ring["slots"]) == RING_SLOTS
        assert not a.fabric_set_ring(
            {"epoch": 5, "slots": ring["slots"]}, 99)
    finally:
        a.close()
        b.close()
        srv.stop()


# ----------------------- router: /call + /watch -----------------------


def test_router_routes_and_tags_events(cluster):
    client = RemoteHub(cluster.router_url, timeout=10.0)
    try:
        client.create_node(MakeNode().name("rn").obj())
        pods = [MakePod().name(f"rp{i}").namespace(f"ns-{i}").obj()
                for i in range(8)]
        for p in pods:
            client.create_pod(p)
        assert len(client.list_pods()) == 8
        assert cluster.hubs["nodes"].commits == 1
        spread = [h.commits for n, h in cluster.hubs.items()
                  if n.startswith("pods-")]
        assert sum(spread) == 8 and all(spread), \
            "namespace ring must spread pods over both shard procs"
        evs = []
        client.watch_kinds({"pods": EventHandlers(
            on_event=lambda ev: evs.append(ev))})
        assert len(evs) == 8
        assert {e.shard for e in evs} == {"pods-0", "pods-1"}
        # live events keep their source tag
        client.create_pod(MakePod().name("live").namespace("zz").obj())
        deadline = time.time() + 5
        while len(evs) < 9 and time.time() < deadline:
            time.sleep(0.02)
        assert evs[-1].shard in ("pods-0", "pods-1")
        # uid ops probe the right shard; fencing is hub-wide
        client.bind(pods[0], "rn")
        assert client.get_pod(pods[0].metadata.uid).spec.node_name \
            == "rn"
        from kubernetes_tpu.leaderelection import Lease

        client.leases.update(Lease(name="kube-scheduler",
                                   holder_identity="x", renew_time=1.0,
                                   acquire_time=1.0), None)
        client.leases.update(Lease(name="kube-scheduler",
                                   holder_identity="y", renew_time=2.0,
                                   acquire_time=2.0), "x")
        with pytest.raises(Fenced):
            client.bind(pods[1], "rn", 1)   # stale epoch (positional:
        #                                     the wire carries no kwargs)
    finally:
        client.close()


def test_router_cursor_resume_is_exact(cluster):
    client = RemoteHub(cluster.router_url, timeout=10.0)
    try:
        for i in range(6):
            client.create_pod(MakePod().name(f"c{i}")
                              .namespace(f"ns-{i}").obj())
        evs = []
        client.watch_kinds({"pods": EventHandlers(
            on_event=lambda ev: evs.append(ev))})
        cursors: dict[str, int] = {}
        for e in evs:
            cursors[e.shard] = max(cursors.get(e.shard, 0), e.rv)
        for i in range(6, 9):
            client.create_pod(MakePod().name(f"c{i}")
                              .namespace(f"ns-{i}").obj())
        # a fresh client resuming at the captured composite cursor
        # gets EXACTLY the commits it missed, across both shards
        late = RemoteHub(cluster.router_url, timeout=10.0)
        try:
            evs2 = []
            late.watch_kinds({"pods": EventHandlers(
                on_event=lambda ev: evs2.append(ev))},
                cursors=cursors)
            deadline = time.time() + 5
            while len(evs2) < 3 and time.time() < deadline:
                time.sleep(0.02)
            assert sorted(e.new.metadata.name for e in evs2) \
                == ["c6", "c7", "c8"]
        finally:
            late.close()
        # a resume point beyond the revision space answers 410 -> the
        # reflector relists (counted) instead of pinning phantom state
        relist = RemoteHub(cluster.router_url, timeout=10.0)
        try:
            evs3 = []
            relist.watch_kinds({"pods": EventHandlers(
                on_event=lambda ev: evs3.append(ev))},
                since_rv=10_000)
            assert len(evs3) == 9, "410 must degrade to a full LIST"
            assert relist.resilience_stats()["watch_relists"] == 0, \
                "the first-dial 410 fallback is not a mid-life relist"
        finally:
            relist.close()
    finally:
        client.close()


def test_shard_restart_with_wal_replay_heals_router(cluster, tmp_path):
    client = RemoteHub(cluster.router_url, timeout=10.0,
                       retry_deadline=15.0)
    try:
        pods = [MakePod().name(f"w{i}").namespace(f"ns-{i}").obj()
                for i in range(6)]
        for p in pods:
            client.create_pod(p)
        evs = []
        client.watch_kinds({"pods": EventHandlers(
            on_event=lambda ev: evs.append(ev))})
        n0 = len(evs)
        rv_before = client.rv.last()
        cluster.restart_shard("pods-0", tmp_path=tmp_path)
        # the revision space continues (allocator survives the shard)
        assert client.rv.last() >= rv_before
        # writes heal once the router re-resolves the new port
        deadline = time.time() + 20
        landed = False
        while time.time() < deadline and not landed:
            try:
                client.create_pod(MakePod().name("post-restart")
                                  .namespace("ns-0").obj())
                landed = True
            except Exception:  # noqa: BLE001 — mid-restart window
                time.sleep(0.2)
        assert landed
        assert len(client.list_pods()) == 7, \
            "WAL replay must resurrect the shard's pods"
        # the cut watcher resumed (cursors) and sees the new commit
        deadline = time.time() + 15
        while time.time() < deadline and not any(
                e.new is not None
                and e.new.metadata.name == "post-restart"
                for e in evs[n0:]):
            time.sleep(0.1)
        assert any(e.new is not None
                   and e.new.metadata.name == "post-restart"
                   for e in evs[n0:])
        assert client.resilience_stats()["watch_relists"] == 0
    finally:
        client.close()


# --------------------------- ring rebalance ---------------------------


def test_rebalance_is_event_silent_and_reroutes(cluster):
    client = RemoteHub(cluster.router_url, timeout=10.0)
    try:
        for i in range(6):
            client.create_pod(MakePod().name(f"m{i}")
                              .namespace(f"ns-{i}").obj())
        evs = []
        client.watch_kinds({"pods": EventHandlers(
            on_event=lambda ev: evs.append(ev))})
        n0 = len(evs)
        slot = ring_slot("ns-0", RING_SLOTS)
        src = client.fabric_ring()["slots"][slot]
        dst = "pods-1" if src == "pods-0" else "pods-0"
        r = client.rebalance_segment([slot], dst)
        assert r["moved"].get(src, 0) >= 1
        assert r["pending_drops"] == []
        time.sleep(0.3)
        assert len(evs) == n0, "a segment move must emit NO events"
        # post-move commits land on (and are tagged with) the target
        client.create_pod(MakePod().name("moved").namespace("ns-0")
                          .obj())
        deadline = time.time() + 5
        while time.time() < deadline and not any(
                e.new is not None and e.new.metadata.name == "moved"
                for e in evs):
            time.sleep(0.05)
        tagged = [e for e in evs if e.new is not None
                  and e.new.metadata.name == "moved"]
        assert tagged and tagged[0].shard == dst
        # no duplicates, no holes in a fresh merged LIST
        assert len(client.list_pods()) == 7
    finally:
        client.close()


def test_rebalance_property_resume_points_survive(cluster):
    """The satellite property test: for ANY ring move, every live
    watch's composite cursor remains servable (0 relists) and
    list_changes never skips a commit that landed around the handoff.
    Seeded random segment moves with commits interleaved."""
    import random

    rng = random.Random(1711)
    client = RemoteHub(cluster.router_url, timeout=10.0)
    try:
        namespaces = [f"prop-{i}" for i in range(10)]
        n_created = 0

        def commit(n: int) -> None:
            nonlocal n_created
            for _ in range(n):
                client.create_pod(
                    MakePod().name(f"pp-{n_created}")
                    .namespace(rng.choice(namespaces)).obj())
                n_created += 1

        commit(6)
        evs = []
        client.watch_kinds({"pods": EventHandlers(
            on_event=lambda ev: evs.append(ev))})
        for round_no in range(4):
            # capture a composite cursor from the live watch
            cursors: dict[str, int] = {}
            for e in evs:
                if e.shard:
                    cursors[e.shard] = max(cursors.get(e.shard, 0),
                                           e.rv)
            snap_rv = client.rv.last()
            seen_before = len(evs)
            commit(2)
            # any segment, any direction, mid-commit
            slot = ring_slot(rng.choice(namespaces), RING_SLOTS)
            ring = client.fabric_ring()
            src = ring["slots"][slot]
            dst = rng.choice([n for n in cluster.pod_names
                              if n != src])
            client.rebalance_segment([slot], dst)
            commit(2)
            # (a) the captured cursor resumes exactly: a fresh client
            # must receive precisely the 4 commits after the capture
            probe = RemoteHub(cluster.router_url, timeout=10.0)
            try:
                got = []
                probe.watch_kinds({"pods": EventHandlers(
                    on_event=lambda ev: got.append(ev))},
                    cursors=dict(cursors))
                deadline = time.time() + 10
                while len(got) < 4 and time.time() < deadline:
                    time.sleep(0.02)
                names = sorted(g.new.metadata.name for g in got)
                want = sorted(f"pp-{i}" for i in
                              range(n_created - 4, n_created))
                assert names == want, \
                    f"round {round_no}: resume skipped/duplicated: " \
                    f"{names} != {want}"
                assert probe.resilience_stats()["watch_relists"] == 0
            finally:
                probe.close()
            # (b) the live watch saw every commit (no move events, no
            # holes) ...
            deadline = time.time() + 10
            while len(evs) < seen_before + 4 \
                    and time.time() < deadline:
                time.sleep(0.02)
            assert len(evs) == seen_before + 4
            # (c) ... and list_changes from the snapshot rv never
            # skips a commit that landed around the handoff
            changes = client.list_changes(snap_rv, ("pods",))
            assert not changes["too_old"]
            got_rvs = {c["rv"] for c in changes["changes"]}
            new_rvs = {e.rv for e in evs[seen_before:]}
            assert new_rvs <= got_rvs, \
                f"round {round_no}: list_changes skipped " \
                f"{new_rvs - got_rvs}"
        assert client.resilience_stats()["watch_relists"] == 0
    finally:
        client.close()


# ------------------------ relay auto-topology ------------------------


def test_relay_advertise_discover_and_reparent(cluster):
    from kubernetes_tpu.fabric.relay import (
        RelayCore,
        RelayServer,
        discover_relay_url,
        pick_relay,
    )

    client = RemoteHub(cluster.router_url, timeout=10.0)
    l1a = RelayServer(
        RelayCore(cluster.router_url, kinds=("pods",), timeout=10.0),
        advertise={"state_url": cluster.router_url, "name": "l1-a",
                   "parent": cluster.router_url,
                   "interval_s": 0.2}).start()
    l1b = RelayServer(
        RelayCore(cluster.router_url, kinds=("pods",), timeout=10.0),
        advertise={"state_url": cluster.router_url, "name": "l1-b",
                   "parent": cluster.router_url,
                   "interval_s": 0.2}).start()
    l2 = None
    try:
        for i in range(4):
            client.create_pod(MakePod().name(f"t{i}")
                              .namespace(f"ns-{i}").obj())
        deadline = time.time() + 10
        topo = {}
        while time.time() < deadline:
            topo = fetch_topology(cluster.router_url)
            if len(topo.get("relays", [])) >= 2:
                break
            time.sleep(0.1)
        assert sorted(r["name"] for r in topo["relays"]) \
            == ["l1-a", "l1-b"]
        assert topo["routers"], "the router must register itself"
        assert pick_relay(topo, seed=3) is not None
        url = discover_relay_url(cluster.router_url, seed=3)
        assert url in (l1a.address, l1b.address)
        # an L2 relay discovers its parent instead of being flagged
        from kubernetes_tpu.fabric.relay import RelayCore as RC

        l2 = RC(url, kinds=("pods",), timeout=10.0)
        sub = l2.subscribe(("pods",))
        assert len(sub.drain()) == 4
        # re-parent onto the sibling: per-shard cursors carry over,
        # the move costs a resume, downstream sees every later event
        other = l1b.address if url == l1a.address else l1a.address
        l2.reparent(other)
        client.create_pod(MakePod().name("after-reparent")
                          .namespace("ns-7").obj())
        deadline = time.time() + 10
        seen = False
        while time.time() < deadline and not seen:
            sub.event.wait(0.1)
            seen = any(d["new"] is not None
                       and d["new"].metadata.name == "after-reparent"
                       for d in sub.drain())
        assert seen
        assert l2.client.resilience_stats()["watch_relists"] == 0
    finally:
        if l2 is not None:
            l2.close()
        l1a.stop()
        l1b.stop()
        client.close()


def test_relay_cursor_resume_through_router(cluster):
    from kubernetes_tpu.fabric.relay import RelayCore

    client = RemoteHub(cluster.router_url, timeout=10.0)
    core = RelayCore(cluster.router_url, kinds=("pods",), timeout=10.0)
    try:
        for i in range(5):
            client.create_pod(MakePod().name(f"rr{i}")
                              .namespace(f"ns-{i}").obj())
        deadline = time.time() + 10
        while core.last_rv < client.rv.last() \
                and time.time() < deadline:
            time.sleep(0.05)
        sub = core.subscribe(("pods",))
        backlog = sub.drain()
        assert len(backlog) == 5
        assert all(d.get("sh") for d in backlog)
        curs = {k: v for k, v in sub.cursors.items() if k}
        core.unsubscribe(sub)
        client.create_pod(MakePod().name("gap").namespace("ns-0")
                          .obj())
        deadline = time.time() + 10
        while time.time() < deadline:
            with core._lock:
                caught = any(rv >= client.rv.last() for rv in
                             core._ring_rv.values())
            if caught:
                break
            time.sleep(0.05)
        sub2 = core.subscribe(("pods",), since_rv=sub.cursor,
                              cursors=curs)
        got = [d["new"].metadata.name for d in sub2.drain()
               if d["new"] is not None]
        assert got == ["gap"], "composite-cursor resume must replay " \
                               "exactly the gap"
        assert core.resume_serves == 1
    finally:
        core.close()
        client.close()


def test_relay_watchdog_auto_reparents_on_upstream_kill(cluster):
    """ISSUE-13 satellite: an L1 relay dies (SIGKILL analog — its
    server stops answering mid-stream) and its downstream L2 relay
    auto-reparents onto the advertised sibling via the liveness
    watchdog — a cursor-carrying RESUME, so the downstream subscriber
    sees every later event exactly once with 0 relists."""
    from kubernetes_tpu.fabric.relay import RelayCore, RelayServer

    client = RemoteHub(cluster.router_url, timeout=10.0)
    l1a = RelayServer(
        RelayCore(cluster.router_url, kinds=("pods",), timeout=5.0),
        advertise={"state_url": cluster.router_url, "name": "l1-a",
                   "parent": cluster.router_url,
                   "interval_s": 0.2}).start()
    l1b = RelayServer(
        RelayCore(cluster.router_url, kinds=("pods",), timeout=5.0),
        advertise={"state_url": cluster.router_url, "name": "l1-b",
                   "parent": cluster.router_url,
                   "interval_s": 0.2}).start()
    l2 = None
    try:
        for i in range(4):
            client.create_pod(MakePod().name(f"wd{i}")
                              .namespace(f"ns-{i}").obj())
        # both L1s must be on the served map before the kill, so the
        # watchdog has a sibling to discover
        from kubernetes_tpu.fabric.router import fetch_topology

        deadline = time.time() + 10
        while time.time() < deadline:
            if len(fetch_topology(cluster.router_url)
                   .get("relays", [])) >= 2:
                break
            time.sleep(0.1)
        l2 = RelayCore(l1a.address, kinds=("pods",), timeout=5.0,
                       watchdog={"topology_url": cluster.router_url,
                                 "deadline_s": 0.8,
                                 "interval_s": 0.2})
        sub = l2.subscribe(("pods",))
        got = {d["new"].metadata.name for d in sub.drain()
               if d["new"] is not None}
        assert len(got) == 4
        # SIGKILL analog: the upstream stops answering, no drain
        l1a.stop()
        # the watchdog must notice, discover l1-b, and resume there
        deadline = time.time() + 20
        while time.time() < deadline and l2.watchdog_reparents == 0:
            time.sleep(0.1)
        assert l2.watchdog_reparents >= 1, \
            "watchdog never reparented off the dead upstream"
        assert l2.upstream_url == l1b.address
        # later events flow through the new parent, exactly once each
        for i in range(3):
            client.create_pod(MakePod().name(f"post-wd{i}")
                              .namespace(f"ns-{i}").obj())
        want = {f"post-wd{i}" for i in range(3)}
        seen: list[str] = []
        deadline = time.time() + 15
        while time.time() < deadline and not want <= set(seen):
            sub.event.wait(0.1)
            seen.extend(d["new"].metadata.name for d in sub.drain()
                        if d["new"] is not None)
        assert want <= set(seen), f"lost events after reparent: {seen}"
        assert len(seen) == len(set(seen)), f"duplicates: {seen}"
        # the reparent was a RESUME off the sibling's rings, not a
        # relist — downstream continuity is the whole point
        assert l2.client.resilience_stats()["watch_relists"] == 0
    finally:
        if l2 is not None:
            l2.close()
        try:
            l1a.stop()
        except Exception:  # noqa: BLE001 — already stopped
            pass
        l1b.stop()
        client.close()


def test_two_router_concurrent_rebalance_fencing(cluster):
    """ISSUE-13 satellite: a second router keeps writing through its
    own (deliberately stale — TTL pinned high) ring while the first
    router migrates the written segment back and forth. Shard-side
    ring-epoch fencing must redirect every misrouted write (StaleRing
    → re-resolve → retry): zero pods lost, zero duplicated, and every
    pod ends on the shard the final ring assigns."""
    import threading

    from kubernetes_tpu.fabric.router import RouterServer

    # router B re-reads the ring ONLY when fenced: the stale window is
    # guaranteed, not racy
    writer_cluster = ClusterClient(cluster.state_url, ring_ttl_s=60.0)
    router_b = RouterServer(cluster.state_url, name="router-b",
                            cluster=writer_cluster).start()
    admin = RemoteHub(cluster.router_url, timeout=10.0)
    writer = RemoteHub(router_b.address, timeout=10.0,
                       retry_deadline=10.0)
    stop = threading.Event()
    created: list[str] = []
    errors: list[str] = []

    def write_loop() -> None:
        i = 0
        while not stop.is_set():
            name = f"w2r-{i}"
            try:
                writer.create_pod(MakePod().name(name)
                                  .namespace("two-router").obj())
                created.append(name)
            except Exception as e:  # noqa: BLE001 — a write may park
                errors.append(f"{name}: {e!r}")   # during the window
            i += 1
            time.sleep(0.01)

    t = threading.Thread(target=write_loop, daemon=True)
    try:
        slot = ring_slot("two-router", RING_SLOTS)
        t.start()
        deadline = time.time() + 10
        while not created and time.time() < deadline:
            time.sleep(0.02)
        # migrate the written segment back and forth under the writes
        for _ in range(4):
            ring = admin.fabric_ring()
            src = ring["slots"][slot]
            dst = next(n for n in cluster.pod_names if n != src)
            admin.rebalance_segment([slot], dst)
            time.sleep(0.15)
        stop.set()
        t.join(timeout=10)
        assert created, "writer never landed a pod"
        assert not errors, f"writes failed outright: {errors[:3]}"
        # no pod lost or duplicated across the whole churn
        pods = [p for p in admin.list_pods()
                if p.metadata.namespace == "two-router"]
        names = sorted(p.metadata.name for p in pods)
        assert names == sorted(created), \
            f"lost={set(created) - set(names)} " \
            f"extra={set(names) - set(created)}"
        # the stale writer was actually fenced and redirected at least
        # once (ring TTL 60s: only StaleRing can have re-resolved it)
        assert writer_cluster.stale_ring_retries >= 1
        # final ownership agrees with the final ring: the segment's
        # pods live ONLY on the assigned shard
        final_owner = admin.fabric_ring()["slots"][slot]
        for name, hub in cluster.hubs.items():
            if not name.startswith("pods-"):
                continue
            here = [p.metadata.name for p in hub.list_pods()
                    if p.metadata.namespace == "two-router"]
            if name == final_owner:
                assert sorted(here) == sorted(created)
            else:
                assert here == [], \
                    f"stray segment copy on {name}: {here[:3]}"
        # and the two routers cannot both win one epoch: a racing CAS
        # loses cleanly (Conflict → rolled back), never half-applies
        ring = admin.fabric_ring()
        src = ring["slots"][slot]
        dst = next(n for n in cluster.pod_names if n != src)
        results: list = [None, None]

        def race(idx, client_) -> None:
            try:
                results[idx] = client_.rebalance_segment([slot], dst)
            except Exception as e:  # noqa: BLE001 — the loser's verdict
                results[idx] = e

        ra = threading.Thread(target=race, args=(0, admin))
        rb = threading.Thread(target=race, args=(1, writer))
        ra.start()
        rb.start()
        ra.join(15)
        rb.join(15)
        wins = [r for r in results if isinstance(r, dict)]
        assert len(wins) >= 1, results
        assert len(admin.list_pods()) >= len(created), results
    finally:
        stop.set()
        admin.close()
        writer.close()
        router_b.stop()


# ----------------------- real OS processes -----------------------


def test_two_process_smoke(tmp_path):
    """Tier-1 process smoke: state + ONE all-kinds shard as real OS
    processes (the minimal fabric), an in-thread router, CRUD + watch
    + kill -9 + restart-with-WAL-replay — seconds, not minutes."""
    from kubernetes_tpu.fabric.supervisor import spawn_local_cluster

    c = spawn_local_cluster(pod_shards=1, kind_shards=False,
                            wal_dir=str(tmp_path), router=False)
    router = RouterServer(c.state_url).start()
    client = RemoteHub(router.address, timeout=10.0)
    try:
        assert len(c.sup.procs) == 2, sorted(c.sup.procs)
        client.create_node(MakeNode().name("n").obj())
        for i in range(4):
            client.create_pod(MakePod().name(f"s{i}")
                              .namespace(f"ns-{i}").obj())
        evs = []
        client.watch_kinds({"pods": EventHandlers(
            on_event=lambda ev: evs.append(ev))})
        assert len(evs) == 4 and evs[0].shard == "pods-0"
        rv = client.rv.last()
        # kill -9: no drain, no WAL close — the replay must cover it
        c.sup.kill_shard("pods-0")
        c.sup.restart_shard("pods-0")
        deadline = time.time() + 20
        landed = False
        while time.time() < deadline and not landed:
            try:
                client.create_pod(MakePod().name("back")
                                  .namespace("ns-0").obj())
                landed = True
            except Exception:  # noqa: BLE001 — router re-resolving
                time.sleep(0.2)
        assert landed
        assert len(client.list_pods()) == 5
        assert client.get_node("n") is not None
        assert client.rv.last() > rv
        deadline = time.time() + 15
        while time.time() < deadline and not any(
                e.new is not None and e.new.metadata.name == "back"
                for e in evs):
            time.sleep(0.1)
        assert any(e.new is not None and e.new.metadata.name == "back"
                   for e in evs), "the cut watcher must resume"
        assert client.resilience_stats()["watch_relists"] == 0
    finally:
        client.close()
        router.stop()
        c.stop()


@pytest.mark.slow
def test_fanout_smoke_procs_small():
    """The process-mode storm battery at reduced scale (the full 50k
    run is bench.py --fanout-smoke's procs column)."""
    from kubernetes_tpu.fabric.fanout import run_fanout_smoke_procs

    r = run_fanout_smoke_procs(subscribers=200, pods=40, churn=20,
                               cuts=4, resub=40, timeout_s=240)
    assert r["ok"], r
    assert r["upstream_relists"] == 0
    assert r["event_count_min"] == r["event_count_max"] \
        == r["pod_events"]
    assert r["wal_replay_ratio"] >= 3.0
    assert all(v <= 2 for v in r["shard_pod_watchers"].values())


@pytest.mark.slow
def test_proc_crash_storm_small():
    """Process-level kill -9 + WAL-replay chaos (the full battery is
    chaos --storm proc / bench.py --chaos-smoke)."""
    from kubernetes_tpu.chaos import run_proc_crash_storm

    r = run_proc_crash_storm(pods=80, nodes=8, timeout_s=180)
    assert r["ok"], r
    assert r["duplicate_binds"] == {}
    assert r["epoch_after_restart"] >= r["epoch_before_kill"] >= 1
    assert r["stale_epoch_fenced"]
