"""L0 storage: event journal, compaction, watch-resume, WAL, /debug authz.

The etcd-analog layer (kubernetes_tpu/storage): ring wraparound advances
the compaction watermark correctly, the ``since_rv == compacted_rv``
boundary resumes, RvTooOld fires below it; hub watches resume in-process
and over the HTTP wire (where 410 drives the client's relist fallback);
a WAL-backed hub replays its state across restarts; broken CEL selectors
surface as hub Events + dra_cel_errors_total instead of silently parking
pods; /debug endpoints stay behind the pluggable auth callback."""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from kubernetes_tpu.api.objects import (
    DeviceClass,
    DeviceSelector,
    ObjectMeta,
)
from kubernetes_tpu.hub import EventHandlers, Hub, RvTooOld
from kubernetes_tpu.hubclient import RemoteHub
from kubernetes_tpu.hubserver import HubServer
from kubernetes_tpu.serving import ServingEndpoints, token_auth
from kubernetes_tpu.storage import Journal, JournalEvent
from kubernetes_tpu.testing import MakeNode, MakePod


def _wait(cond, timeout=10.0, interval=0.02):
    end = time.monotonic() + timeout
    while time.monotonic() < end:
        if cond():
            return True
        time.sleep(interval)
    return cond()


# ---------------------------------------------------------------- journal


def test_ring_wraparound_advances_watermark():
    j = Journal(capacity=4)
    for rv in range(1, 11):
        j.append(JournalEvent(rv=rv, kind="pods", type="add"))
    # ring holds rvs 7..10; the newest DROPPED event (rv 6) is the
    # watermark
    assert j.compacted_rv("pods") == 6
    assert [e.rv for e in j.events_after("pods", 7)] == [8, 9, 10]
    st = j.stats()["pods"]
    assert st["depth"] == 4 and st["last_rv"] == 10


def test_since_rv_equals_watermark_boundary_resumes():
    j = Journal(capacity=4)
    for rv in range(1, 11):
        j.append(JournalEvent(rv=rv, kind="pods", type="add"))
    # inclusive boundary: a client that saw exactly rv 6 (the last
    # compacted event) still has a complete history ahead of it
    assert [e.rv for e in j.events_after("pods", 6)] == [7, 8, 9, 10]
    with pytest.raises(RvTooOld) as ei:
        j.events_after("pods", 5)
    assert ei.value.compacted_rv == 6
    # a never-journaled kind has watermark 0: any resume point is legal
    assert j.events_after("nodes", 0) == []


def test_journal_rv_gaps_across_kinds_are_complete_per_kind():
    j = Journal(capacity=8)
    for rv in range(1, 9):
        kind = "pods" if rv % 2 else "nodes"
        j.append(JournalEvent(rv=rv, kind=kind, type="add"))
    assert [e.rv for e in j.events_after("pods", 1)] == [3, 5, 7]
    assert [e.rv for e in j.events_after("nodes", 0)] == [2, 4, 6, 8]


# ---------------------------------------------------------------- hub


def test_hub_watch_resume_in_process():
    hub = Hub()
    p1 = MakePod().name("p1").obj()
    p2 = MakePod().name("p2").obj()
    hub.create_pod(p1)
    rv = hub.current_rv
    hub.create_pod(p2)
    hub.delete_pod(p1.metadata.uid)
    got = []
    cur = hub.watch_pods(
        EventHandlers(on_add=lambda o: got.append(("add", o.metadata.name)),
                      on_delete=lambda o: got.append(
                          ("del", o.metadata.name))),
        since_rv=rv)
    # only the journal suffix replays — no synthetic adds of the world
    assert got == [("add", "p2"), ("del", "p1")]
    assert cur == hub.current_rv
    # the delete consumed a revision of its own (etcd stamps deletions)
    assert cur == rv + 2


def test_hub_watch_resume_raises_rv_too_old_before_registering():
    hub = Hub(journal_capacity=4)
    for i in range(10):
        hub.create_pod(MakePod().name(f"p{i}").obj())
    h = EventHandlers(on_add=lambda o: None)
    with pytest.raises(RvTooOld):
        hub.watch_pods(h, since_rv=1)
    # the failed watch must not have left a registered handler behind
    assert h not in hub._pods.handlers
    # boundary: resuming exactly AT the watermark works
    wm = hub.journal.compacted_rv("pods")
    got = []
    hub.watch_pods(EventHandlers(on_add=lambda o: got.append(1)),
                   since_rv=wm)
    assert len(got) == 4


def test_record_event_dedups_and_bumps_count():
    hub = Hub()
    hub.record_event("DeviceClass", "gpu", "CELSelectorError", "boom 1")
    hub.record_event("DeviceClass", "gpu", "CELSelectorError", "boom 2")
    hub.record_event("DeviceClass", "other", "CELSelectorError", "x")
    evs = hub.list_events(ref_kind="DeviceClass", ref_key="gpu")
    assert len(evs) == 1
    assert evs[0].count == 2 and evs[0].message == "boom 2"
    assert len(hub.list_events(ref_kind="DeviceClass")) == 2


# ---------------------------------------------------------------- WAL


def test_wal_replay_rebuilds_hub_state(tmp_path):
    wal = str(tmp_path / "hub.wal")
    h1 = Hub(wal_path=wal)
    n = MakeNode().name("n1").capacity(cpu="8").obj()
    h1.create_node(n)
    pods = [MakePod().name(f"p{i}").obj() for i in range(3)]
    for p in pods:
        h1.create_pod(p)
    h1.bind(pods[0], "n1")
    h1.delete_pod(pods[2].metadata.uid)
    rv_end = h1.current_rv
    watch_rv = h1.current_rv
    h1.close()

    h2 = Hub(wal_path=wal)
    # revision space continues, stores + secondary indexes rebuilt
    assert h2.current_rv == rv_end
    assert h2.get_node("n1").metadata.uid == n.metadata.uid
    assert h2.get_pod(pods[0].metadata.uid).spec.node_name == "n1"
    assert h2.get_pod(pods[2].metadata.uid) is None
    assert len(h2.list_pods()) == 2
    # the journal rings replayed too: a client at a pre-restart rv
    # resumes across the hub restart
    h2.create_pod(MakePod().name("post").obj())
    assert h2.current_rv == rv_end + 1
    got = []
    h2.watch_pods(EventHandlers(on_add=lambda o: got.append(
        o.metadata.name)), since_rv=watch_rv)
    assert got == ["post"]
    # and new mutations keep appending to the same WAL
    h2.close()
    h3 = Hub(wal_path=wal)
    assert h3.get_pod(pods[0].metadata.uid).spec.node_name == "n1"
    assert any(p.metadata.name == "post" for p in h3.list_pods())
    h3.close()


def test_wal_tolerates_and_repairs_torn_final_line(tmp_path):
    wal = str(tmp_path / "hub.wal")
    h1 = Hub(wal_path=wal)
    h1.create_pod(MakePod().name("whole").obj())
    h1.close()
    with open(wal, "a") as f:
        f.write('{"rv": 99, "kind": "pods", "ty')   # torn mid-append
    h2 = Hub(wal_path=wal)
    assert len(h2.list_pods()) == 1
    assert h2.current_rv == 1
    # the torn tail was TRUNCATED on boot: appending now must start a
    # clean line, not merge into the partial record (which would become
    # interior corruption and brick every later boot)
    h2.create_pod(MakePod().name("after-tear").obj())
    h2.close()
    h3 = Hub(wal_path=wal)
    assert sorted(p.metadata.name for p in h3.list_pods()) == \
        ["after-tear", "whole"]
    h3.close()
    # a record cut exactly between the json and its newline is torn too
    with open(wal, "rb+") as f:
        f.seek(-1, 2)
        assert f.read(1) == b"\n"
        f.seek(-1, 2)
        f.truncate()                         # strip the final newline
    h4 = Hub(wal_path=wal)
    assert [p.metadata.name for p in h4.list_pods()] == ["whole"], \
        "newline-less tail never committed"
    h4.close()


def test_watch_resume_from_future_rv_is_rv_too_old():
    """A since_rv beyond the hub's newest revision means the client
    watched a DIFFERENT revision space (a hub reborn without its WAL):
    'no events' would pin phantom state in the client forever, so the
    hub answers RvTooOld and the wire answers 410 → relist, whose diff
    deletes the phantoms."""
    hub = Hub()
    hub.create_pod(MakePod().name("p").obj())
    with pytest.raises(RvTooOld):
        hub.watch_pods(EventHandlers(on_add=lambda o: None), since_rv=99)
    # end-to-end: reflector synced against hub A resumes against a
    # fresh empty hub B on the same port -> relist-as-deletes
    hub_a = Hub()
    server = HubServer(hub_a).start()
    host, port = server._httpd.server_address[:2]
    for i in range(5):
        hub_a.create_node(MakeNode().name(f"n{i}").obj())
    client = RemoteHub(server.address, retry_base=0.02, retry_cap=0.2)
    adds, dels = [], []
    try:
        client.watch_nodes(EventHandlers(
            on_add=lambda o: adds.append(o.metadata.name),
            on_delete=lambda o: dels.append(o.metadata.name)))
        assert len(adds) == 5
        server.stop()
        server = HubServer(Hub(), host=host, port=port).start()
        assert _wait(lambda: len(dels) == 5, 15), \
            f"phantom objects not deleted: dels={dels}"
        stats = client.resilience_stats()
        assert stats["watch_relists"] >= 1
        assert stats["watch_resumes"] == 0
    finally:
        client.close()
        server.stop()


def test_wal_boot_compaction_bounds_the_file(tmp_path):
    """A WAL whose history dwarfs the live objects is snapshot-rewritten
    on boot: the file shrinks to (compact record + live objects), state
    survives further restarts, revisions continue above the floor, and a
    resume from below the floor relists via RvTooOld on the NEXT boot."""
    wal = str(tmp_path / "hub.wal")
    h1 = Hub(wal_path=wal)
    keep = MakePod().name("keeper").obj()
    h1.create_pod(keep)
    for i in range(200):                  # churn: 400 events, 1 survivor
        p = MakePod().name(f"churn{i}").obj()
        h1.create_pod(p)
        h1.delete_pod(p.metadata.uid)
    rv_end = h1.current_rv
    pre_resume_rv = rv_end - 10
    h1.close()
    size_before = len(open(wal).read().splitlines())
    assert size_before > 400

    h2 = Hub(wal_path=wal)                # boot compaction triggers here
    assert len(open(wal).read().splitlines()) < 10
    assert h2.current_rv == rv_end
    assert [p.metadata.name for p in h2.list_pods()] == ["keeper"]
    # this boot's rings still hold the real history: resume works
    got = []
    h2.watch_pods(EventHandlers(on_add=lambda o: got.append(1),
                                on_delete=lambda o: got.append(-1)),
                  since_rv=pre_resume_rv)
    assert got, "in-memory rings still serve pre-compaction resumes"
    h2.close()

    h3 = Hub(wal_path=wal)                # replays the compacted snapshot
    assert h3.current_rv == rv_end
    assert [p.metadata.name for p in h3.list_pods()] == ["keeper"]
    with pytest.raises(RvTooOld):
        h3.watch_pods(EventHandlers(on_add=lambda o: None),
                      since_rv=pre_resume_rv)
    # at/above the floor is fine
    h3.watch_pods(EventHandlers(on_add=lambda o: None), since_rv=rv_end)
    h3.close()


def test_wal_interior_corruption_raises(tmp_path):
    wal = str(tmp_path / "hub.wal")
    h1 = Hub(wal_path=wal)
    h1.create_pod(MakePod().name("a").obj())
    h1.create_pod(MakePod().name("b").obj())
    h1.close()
    lines = open(wal).read().splitlines()
    lines[0] = lines[0][: len(lines[0]) // 2]      # corrupt the interior
    with open(wal, "w") as f:
        f.write("\n".join(lines) + "\n")
    with pytest.raises(ValueError):
        Hub(wal_path=wal)


# ------------------------------------------------------------- the wire


@pytest.fixture()
def served():
    hub = Hub()
    server = HubServer(hub).start()
    client = RemoteHub(server.address, retry_base=0.02, retry_cap=0.2)
    yield hub, server, client
    client.close()
    server.stop()


def test_watch_endpoint_since_rv_and_410(served):
    hub, server, _client = served
    for i in range(3):
        hub.create_pod(MakePod().name(f"p{i}").obj())
    # a raw since_rv stream: only the suffix, then a sync marker with rv
    resp = urllib.request.urlopen(
        f"{server.address}/watch?kind=pods&since_rv=1", timeout=5)
    lines = []
    for raw in resp:
        ev = json.loads(raw)
        lines.append(ev)
        if ev.get("synced"):
            break
    resp.close()
    assert [e["rv"] for e in lines[:-1]] == [2, 3]
    assert lines[-1] == {"synced": True, "rv": 3}
    # compacted gap -> 410 with the RvTooOld error body
    small = Hub(journal_capacity=2)
    srv2 = HubServer(small).start()
    try:
        for i in range(6):
            small.create_pod(MakePod().name(f"q{i}").obj())
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"{srv2.address}/watch?kind=pods&since_rv=1", timeout=5)
        assert ei.value.code == 410
        assert json.loads(ei.value.read())["error"] == "RvTooOld"
    finally:
        srv2.stop()


def test_reflector_resumes_after_server_restart_without_relist():
    """The PR-1 scenario that used to force a relist-as-deletes diff:
    the hub server dies mid-watch and comes back (same hub, same port).
    With the journal, the reflector reconnects with since_rv and replays
    only the gap — watch_resumes counts it, watch_relists stays 0."""
    hub = Hub()
    server = HubServer(hub).start()
    host, port = server._httpd.server_address[:2]
    for i in range(5):
        hub.create_node(MakeNode().name(f"n{i}").obj())
    client = RemoteHub(server.address, retry_base=0.02, retry_cap=0.2)
    adds, dels = [], []
    try:
        client.watch_nodes(EventHandlers(
            on_add=lambda o: adds.append(o.metadata.name),
            on_delete=lambda o: dels.append(o.metadata.name)))
        assert len(adds) == 5
        server.stop()                      # the cut
        # the gap: one add + one delete while no stream exists
        hub.create_node(MakeNode().name("gap-add").obj())
        hub.delete_node(hub.get_node("n0").metadata.uid)
        server = HubServer(hub, host=host, port=port).start()
        assert _wait(lambda: "gap-add" in adds and "n0" in dels)
        stats = client.resilience_stats()
        assert stats["watch_resumes"] >= 1
        assert stats["watch_relists"] == 0
        assert len(adds) == 6              # no duplicate adds either
    finally:
        client.close()
        server.stop()


def test_reflector_falls_back_to_relist_on_rv_too_old():
    """When the outage outlives the ring, the 410 answer drives the old
    relist path — including the relist-as-deletes diff for objects that
    vanished during the gap."""
    hub = Hub(journal_capacity=4)
    server = HubServer(hub).start()
    host, port = server._httpd.server_address[:2]
    nodes = [MakeNode().name(f"n{i}").obj() for i in range(6)]
    for n in nodes:
        hub.create_node(n)
    client = RemoteHub(server.address, retry_base=0.02, retry_cap=0.2)
    adds, dels = [], []
    try:
        client.watch_nodes(EventHandlers(
            on_add=lambda o: adds.append(o.metadata.name),
            on_delete=lambda o: dels.append(o.metadata.name)))
        assert len(adds) == 6
        server.stop()
        # churn far beyond the 4-slot ring: compaction passes the
        # client's resume point
        hub.delete_node(nodes[0].metadata.uid)
        for i in range(10):
            hub.create_node(MakeNode().name(f"extra{i}").obj())
        server = HubServer(hub, host=host, port=port).start()
        assert _wait(lambda: "n0" in dels
                     and sum(1 for a in adds
                             if a.startswith("extra")) == 10)
        stats = client.resilience_stats()
        assert stats["watch_relists"] >= 1
    finally:
        client.close()
        server.stop()


def test_cut_mid_list_replay_never_arms_resume():
    """A stream cut in the middle of the initial LIST replay must NOT
    arm watch-resume: LIST replay is insertion-ordered, so the highest
    rv seen mid-replay can lie beyond objects never delivered — resuming
    from it would skip them silently forever. The reconnect must run a
    full relist instead (watch_resumes == 0)."""
    import socket as socketlib
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from kubernetes_tpu.utils.wire import to_wire

    hub = Hub()
    nodes = [MakeNode().name(f"n{i}").obj() for i in range(5)]
    for n in nodes:
        hub.create_node(n)
    # n0 updated LAST: insertion order replays it FIRST with the
    # highest rv — the poisoned resume point
    upd = hub.get_node("n0").clone()
    upd.metadata.labels["x"] = "1"
    hub.update_node(upd)
    top_rv = hub.current_rv

    class TruncatingHandler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *args):
            pass

        def do_GET(self):  # noqa: N802
            # serve TWO replay events (n0 at top_rv included), then die
            # before the rest of the LIST or any sync marker
            self.send_response(200)
            self.send_header("Content-Type", "application/jsonlines")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            for obj in [hub.get_node("n0"), hub.get_node("n1")]:
                line = (json.dumps(
                    {"type": "add", "rv": obj.metadata.resource_version,
                     "old": None, "new": to_wire(obj)}).encode() + b"\n")
                self.wfile.write(f"{len(line):x}\r\n".encode() + line
                                 + b"\r\n")
                self.wfile.flush()
            try:
                self.connection.shutdown(socketlib.SHUT_RDWR)
            except OSError:
                pass
            self.close_connection = True

    fake = ThreadingHTTPServer(("127.0.0.1", 0), TruncatingHandler)
    fake.daemon_threads = True
    port = fake.server_address[1]
    t = threading.Thread(target=fake.serve_forever, daemon=True)
    t.start()
    client = RemoteHub(f"http://127.0.0.1:{port}", retry_base=0.02,
                       retry_cap=0.2)
    adds = []
    server = None
    try:
        # initial connect hits the truncating server; swap in the real
        # one on the same port before the reflector's reconnect dials
        watcher = threading.Thread(
            target=lambda: client.watch_nodes(EventHandlers(
                on_add=lambda o: adds.append(o.metadata.name))),
            daemon=True)
        watcher.start()
        assert _wait(lambda: len(adds) >= 2, 10), "truncated replay seen"
        fake.shutdown()
        fake.server_close()
        server = HubServer(hub, port=port).start()
        assert _wait(lambda: len(set(adds)) == 5, 15), \
            f"objects skipped after mid-LIST cut: {sorted(set(adds))}"
        stats = client.resilience_stats()
        assert stats["watch_resumes"] == 0, \
            f"resume armed from a partial LIST: {stats}"
        assert stats["watch_relists"] >= 1
    finally:
        client.close()
        fake.shutdown()
        fake.server_close()
        if server is not None:
            server.stop()


# ----------------------------------------------- CEL errors surfaced


def test_broken_cel_selector_records_event_and_stats():
    from kubernetes_tpu.api.objects import Device
    from kubernetes_tpu.plugins.dra import DynamicResources

    hub = Hub()
    plugin = DynamicResources(hub)
    dc = DeviceClass(metadata=ObjectMeta(name="tpu"),
                     selectors=[DeviceSelector(
                         cel_expression="device.nope.missing(")])
    hub.create_device_class(dc)
    dev = Device(name="d0")
    entry = ("drv", "pool", dev)
    assert not plugin._device_matches(entry, "tpu", dc, [], "ns/claim")
    # once per (object, expression), not per device
    assert not plugin._device_matches(
        ("drv", "pool", Device(name="d1")), "tpu", dc, [], "ns/claim")
    assert plugin.cel_error_stats() == {"DeviceClass/tpu": 1}
    evs = hub.list_events(ref_kind="DeviceClass", ref_key="tpu")
    assert len(evs) == 1 and evs[0].reason == "CELSelectorError"
    # claim-side selectors attribute to the claim
    sel = [DeviceSelector(cel_expression="device.driver ==")]
    assert not plugin._device_matches(entry, "", None, sel, "ns/claim")
    assert plugin.cel_error_stats()["ResourceClaim/ns/claim"] == 1
    assert hub.list_events(ref_kind="ResourceClaim", ref_key="ns/claim")


# ------------------------------------------------------- /debug authz


def _tiny_sched(hub):
    from kubernetes_tpu.config.types import default_config
    from kubernetes_tpu.ops.features import Capacities
    from kubernetes_tpu.scheduler import Scheduler

    cfg = default_config()
    cfg.batch_size = 4
    return Scheduler(hub, cfg, caps=Capacities(nodes=8, pods=16))


def _get(url, token=None):
    req = urllib.request.Request(url)
    if token:
        req.add_header("Authorization", f"Bearer {token}")
    return urllib.request.urlopen(req, timeout=5)


def test_debug_endpoints_require_auth_callback():
    hub = Hub()
    sched = _tiny_sched(hub)
    try:
        # no callback configured: the surface answers 403, never data
        srv = ServingEndpoints(sched, port=0)
        srv.start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(f"http://127.0.0.1:{srv.port}/debug/cache")
            assert ei.value.code == 403
        finally:
            srv.stop()
        # with token_auth: wrong/missing token 401, right token 200
        srv = ServingEndpoints(sched, port=0,
                               debug_auth=token_auth("s3cret"))
        srv.start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(f"{base}/debug/cache")
            assert ei.value.code == 401
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(f"{base}/debug/cache", token="wrong")
            assert ei.value.code == 401
            body = json.loads(_get(f"{base}/debug/cache",
                                   token="s3cret").read())
            assert "nodes" in body
            q = json.loads(_get(f"{base}/debug/queue",
                                token="s3cret").read())
            assert "pending" in q
            js = json.loads(_get(f"{base}/debug/journal",
                                 token="s3cret").read())
            assert "kinds" in js
            # non-debug endpoints stay open
            assert _get(f"{base}/healthz").read() == b"ok"
        finally:
            srv.stop()
    finally:
        sched.close()


def test_readme_bench_table_matches_committed_artifact():
    """The --readme-check CI gate: README's generated bench table must
    equal what the committed artifact renders to (the round-5 DRA
    template row shipped 243 pods/s over a 44.8 artifact — mechanical
    generation makes that class of drift a red suite)."""
    import bench

    assert bench.readme_check(write=False), \
        "README bench table drifted from the committed artifact; " \
        "run `python bench.py --readme-update`"


def test_journal_metrics_exported_on_scheduler():
    hub = Hub()
    sched = _tiny_sched(hub)
    try:
        hub.create_node(MakeNode().name("n0").capacity(cpu="8").obj())
        hub.create_pod(MakePod().name("p").req(cpu="1").obj())
        sched.run_until_idle()
        sched.run_maintenance()
        text = sched.metrics.registry.render_text()
        assert "hub_watch_resumes_total" in text
        assert "hub_watch_relists_total" in text
        assert 'hub_journal_depth{kind="pods"}' in text
        assert "dra_cel_errors_total" in text
    finally:
        sched.close()


# suite-tier discipline (tests/test_markers.py): area marker
pytestmark = pytest.mark.core


# ----------------------- bin1 WAL codec (ISSUE 11) -----------------------


def test_bin1_wal_roundtrip_and_size(tmp_path):
    """The bin1 WAL replays identically to the JSON-lines WAL and is
    several times smaller on disk (positional structs: field names
    never hit the file)."""
    paths = {}
    for codec in ("json", "bin1"):
        wal = str(tmp_path / f"h-{codec}.wal")
        hub = Hub(wal_path=wal, wal_codec=codec)
        for i in range(20):
            hub.create_pod(MakePod().name(f"b{i}")
                           .namespace(f"ns-{i % 3}").obj())
        hub.bind(hub.list_pods()[0], "n-x")
        rv = hub.current_rv
        hub.close()
        paths[codec] = (wal, rv)
        hub2 = Hub(wal_path=wal, wal_codec=codec)
        assert hub2.current_rv == rv
        assert len(hub2.list_pods()) == 20
        assert sum(1 for p in hub2.list_pods()
                   if p.spec.node_name) == 1
        # rings replayed too: resumes across the restart serve
        assert hub2.journal.events_after("pods", 0)
        hub2.close()
    import os as _os

    jb = _os.path.getsize(paths["json"][0])
    bb = _os.path.getsize(paths["bin1"][0])
    assert jb / bb >= 3.0, f"bin1 WAL must be ≥3x smaller ({jb}/{bb})"


def test_bin1_wal_torn_tail_tolerated(tmp_path):
    wal = str(tmp_path / "torn.wal")
    hub = Hub(wal_path=wal, wal_codec="bin1")
    for i in range(5):
        hub.create_pod(MakePod().name(f"t{i}").obj())
    hub.close()
    # a frame cut mid-write: bogus length prefix + partial payload
    with open(wal, "ab") as f:
        f.write(b"\x00\x00\x02\x00only-part-of-a-frame")
    hub2 = Hub(wal_path=wal, wal_codec="bin1")
    assert len(hub2.list_pods()) == 5
    # repair truncated the tail: the next restart replays cleanly too
    hub2.create_pod(MakePod().name("after-torn").obj())
    hub2.close()
    hub3 = Hub(wal_path=wal, wal_codec="bin1")
    assert len(hub3.list_pods()) == 6
    hub3.close()


def test_json_wal_upgrades_in_place_to_bin1(tmp_path):
    """Mixed-format replay: an old JSON-lines WAL opened under
    wal_codec='bin1' replays fine and is rewritten as bin1 on the
    spot (the in-place upgrade), preserving revisions and state."""
    wal = str(tmp_path / "up.wal")
    hub = Hub(wal_path=wal)            # JSON era
    for i in range(8):
        hub.create_pod(MakePod().name(f"u{i}").obj())
    rv = hub.current_rv
    hub.close()
    with open(wal, "rb") as f:
        assert f.read(1) == b"{"
    hub2 = Hub(wal_path=wal, wal_codec="bin1")
    assert hub2.current_rv == rv
    assert len(hub2.list_pods()) == 8
    assert hub2.journal.wal_format == "bin1", \
        "first replay must rewrite the file in the configured codec"
    with open(wal, "rb") as f:
        assert f.read(1) != b"{"
    hub2.create_pod(MakePod().name("post-upgrade").obj())
    hub2.close()
    hub3 = Hub(wal_path=wal, wal_codec="bin1")
    assert len(hub3.list_pods()) == 9
    assert hub3.current_rv == rv + 1
    hub3.close()


def test_segment_transfer_control_records_replay(tmp_path):
    """Ring-rebalance segment transfers persist as WAL control
    records: a restart replays attaches/detaches silently (no events,
    original revisions)."""
    wal_a = str(tmp_path / "a.wal")
    wal_b = str(tmp_path / "b.wal")
    a = Hub(wal_path=wal_a, wal_codec="bin1")
    b = Hub(wal_path=wal_b, wal_codec="bin1")
    for i in range(6):
        a.create_pod(MakePod().name(f"x{i}").namespace(f"ns-{i}").obj())
    moved = a.export_segment([0], 1)        # every slot -> slot 0
    assert len(moved) == 6
    assert b.import_segment(moved) == 6
    assert a.drop_segment([0], 1) == 6
    a.close()
    b.close()
    a2 = Hub(wal_path=wal_a, wal_codec="bin1")
    b2 = Hub(wal_path=wal_b, wal_codec="bin1")
    assert a2.list_pods() == []
    got = sorted(p.metadata.name for p in b2.list_pods())
    assert got == [f"x{i}" for i in range(6)]
    # original revisions survived the transfer
    assert {p.metadata.resource_version
            for p in b2.list_pods()} == set(range(1, 7))
    a2.close()
    b2.close()
