"""Control-plane fabric (ISSUE 9): sharded hub, codec negotiation, and
the watch relay tree.

Covers the three pillars end to end: a ShardedHub behind a HubServer
with RemoteHub clients (and a full Scheduler) behaving exactly like the
single hub; binary-vs-JSON codec negotiation in every skew direction;
relay nodes serving LIST/resume/live downstream from ONE upstream
socket, with slow-subscriber eviction and a 2-level chain.
"""

from __future__ import annotations

import threading
import time

import pytest

from kubernetes_tpu.fabric import codec as binwire
from kubernetes_tpu.fabric.relay import RelayCore, RelayServer
from kubernetes_tpu.fabric.sharded import ShardedHub
from kubernetes_tpu.hub import Conflict, EventHandlers, Hub, NotFound
from kubernetes_tpu.hubclient import RemoteHub
from kubernetes_tpu.hubserver import HubServer
from kubernetes_tpu.storage import RvTooOld
from kubernetes_tpu.testing import MakeNode, MakePod

pytestmark = pytest.mark.fabric


# ------------------------------- sharded hub -------------------------------


def test_sharded_hub_routes_and_merges():
    hub = ShardedHub(pod_shards=3)
    for i in range(4):
        hub.create_node(MakeNode().name(f"n{i}").obj())
    pods = [MakePod().name(f"p{i}").namespace(f"ns-{i % 5}").obj()
            for i in range(10)]
    for p in pods:
        hub.create_pod(p)
    assert len(hub.list_nodes()) == 4
    assert len(hub.list_pods()) == 10
    # uid routing probes the right shard
    got = hub.get_pod(pods[3].metadata.uid)
    assert got is not None and got.metadata.name == "p3"
    hub.bind(got, "n0")
    assert hub.get_pod(got.metadata.uid).spec.node_name == "n0"
    with pytest.raises(Conflict):
        hub.bind(got, "n1")
    hub.delete_pod(pods[4].metadata.uid)
    with pytest.raises(NotFound):
        hub.delete_pod(pods[4].metadata.uid)
    assert len(hub.list_pods()) == 9
    # namespaces land on deterministic shards: same ns, same shard
    a = hub._pod_shard("ns-1")
    assert a is hub._pod_shard("ns-1")
    # commits spread across shards (5 namespaces over 3 shards)
    js = hub.get_journal_stats()
    pod_commits = [v["commits"] for k, v in js["shards"].items()
                   if k.startswith("pods-")]
    assert sum(pod_commits) == 12            # 10 adds + bind + delete
    assert sum(1 for c in pod_commits if c) >= 2, \
        "namespace hashing must actually spread pods over shards"
    assert js["shards"]["nodes"]["commits"] == 4
    assert js["rv"] == hub.current_rv == 16
    hub.close()


def test_sharded_hub_merged_pod_watch_and_resume():
    hub = ShardedHub(pod_shards=3)
    for i in range(6):
        hub.create_pod(MakePod().name(f"w{i}")
                       .namespace(f"ns-{i % 3}").obj())
    seen: list[str] = []
    h = EventHandlers(on_add=lambda o: seen.append(o.metadata.name))
    rv = hub.watch_pods(h)
    assert sorted(seen) == [f"w{i}" for i in range(6)]
    assert rv == hub.current_rv
    # live events from every shard reach the one handler
    hub.create_pod(MakePod().name("live-a").namespace("ns-0").obj())
    hub.create_pod(MakePod().name("live-b").namespace("ns-1").obj())
    assert "live-a" in seen and "live-b" in seen
    hub.unwatch(h)
    # cross-shard resume: events after rv arrive rv-tagged, merged
    resumed: list[int] = []
    h2 = EventHandlers(on_event=lambda ev: resumed.append(ev.rv))
    hub.watch_pods(h2, since_rv=rv)
    assert len(resumed) == 2 and resumed == sorted(resumed)
    hub.unwatch(h2)
    # a future resume point is a revision-space reset: relist
    with pytest.raises(RvTooOld):
        hub.watch_pods(EventHandlers(), since_rv=hub.current_rv + 10)
    hub.close()


def test_sharded_hub_wal_restart(tmp_path):
    wal_dir = str(tmp_path / "shards")
    hub = ShardedHub(pod_shards=2, wal_dir=wal_dir)
    hub.create_node(MakeNode().name("n1").obj())
    pods = [MakePod().name(f"r{i}").namespace(f"ns-{i}").obj()
            for i in range(4)]
    for p in pods:
        hub.create_pod(p)
    hub.bind(pods[0], "n1")
    rv = hub.current_rv
    hub.close()
    hub2 = ShardedHub(pod_shards=2, wal_dir=wal_dir)
    assert hub2.current_rv == rv, "allocator must resume past every shard"
    assert len(hub2.list_pods()) == 4
    assert hub2.get_pod(pods[0].metadata.uid).spec.node_name == "n1"
    assert hub2.get_node("n1") is not None
    # the revision space continues, not restarts
    hub2.create_pod(MakePod().name("post").obj())
    assert hub2.current_rv == rv + 1
    hub2.close()


def test_sharded_hub_fencing_is_hub_wide():
    from kubernetes_tpu.hub import Fenced

    hub = ShardedHub(pod_shards=2)
    pod = MakePod().name("fence").namespace("a").obj()
    hub.create_pod(pod)
    # acquire epoch 1 then depose it with a new holder (epoch 2)
    from kubernetes_tpu.leaderelection import Lease

    hub.leases.update(Lease(name="kube-scheduler", holder_identity="x",
                            renew_time=1.0, acquire_time=1.0), None)
    hub.leases.update(Lease(name="kube-scheduler", holder_identity="y",
                            renew_time=2.0, acquire_time=2.0), "x")
    with pytest.raises(Fenced):
        hub.bind(pod, "n1", epoch=1)
    with pytest.raises(Fenced):
        hub.delete_pod(pod.metadata.uid, epoch=1)
    hub.bind(pod, "n1", epoch=hub.leases.epoch_of("kube-scheduler"))
    hub.close()


def test_scheduler_schedules_against_sharded_hub_over_wire():
    """The tentpole's API-preservation claim: HubServer(ShardedHub()) +
    RemoteHub + a full Scheduler, pods across namespaces (hence
    shards), all bound."""
    from kubernetes_tpu.config.types import default_config
    from kubernetes_tpu.ops.features import Capacities
    from kubernetes_tpu.scheduler import Scheduler

    hub = ShardedHub(pod_shards=3)
    server = HubServer(hub).start()
    client = RemoteHub(server.address)
    try:
        for i in range(4):
            client.create_node(MakeNode().name(f"sn-{i}").obj())
        cfg = default_config()
        cfg.batch_size = 8
        sched = Scheduler(client, cfg, caps=Capacities(nodes=16,
                                                       pods=64))
        pods = [MakePod().name(f"sp-{i}").namespace(f"ns-{i % 3}")
                .req(cpu="500m").obj() for i in range(9)]
        for p in pods:
            client.create_pod(p)
        deadline = time.time() + 30
        while time.time() < deadline:
            sched.run_until_idle()
            if all(hub.get_pod(p.metadata.uid).spec.node_name
                   for p in pods):
                break
            time.sleep(0.05)
        assert all(hub.get_pod(p.metadata.uid).spec.node_name
                   for p in pods), "every pod binds through the router"
        # the wire negotiated the binary codec for the hot path
        assert client.codec == binwire.CODEC_BINARY
        sched.close()
    finally:
        client.close()
        server.stop()
        hub.close()


# ---------------------------- codec negotiation ----------------------------


def _wire_msgs(client: RemoteHub, codec_name: str) -> int:
    return client.resilience_stats()["wire"][codec_name]["msgs"]


def test_negotiation_binary_both_ends():
    hub = Hub()
    server = HubServer(hub).start()
    client = RemoteHub(server.address)
    try:
        client.create_node(MakeNode().name("b1").obj())
        assert client.codec == binwire.CODEC_BINARY
        assert client.get_node("b1").metadata.name == "b1"
        assert _wire_msgs(client, binwire.CODEC_BINARY) > 0
        # watches ride the binary frames too
        seen = []
        client.watch_nodes(EventHandlers(
            on_add=lambda o: seen.append(o.metadata.name)))
        assert seen == ["b1"]
    finally:
        client.close()
        server.stop()


def test_negotiation_binary_client_json_only_server():
    """An old (JSON-only) server: the offer is ignored, the client pins
    JSON, everything works — version skew degrades, never breaks."""
    hub = Hub()
    server = HubServer(hub, codecs=(binwire.CODEC_JSON,)).start()
    client = RemoteHub(server.address)
    try:
        client.create_node(MakeNode().name("j1").obj())
        assert client.codec == binwire.CODEC_JSON
        assert _wire_msgs(client, binwire.CODEC_BINARY) == 0
        seen = []
        client.watch_nodes(EventHandlers(
            on_add=lambda o: seen.append(o.metadata.name)))
        assert seen == ["j1"]
        hub.create_node(MakeNode().name("j2").obj())
        deadline = time.time() + 5
        while "j2" not in seen and time.time() < deadline:
            time.sleep(0.02)
        assert "j2" in seen
    finally:
        client.close()
        server.stop()


def test_negotiation_json_client_binary_server():
    """A JSON-pinned client against a binary-capable server: no offer,
    JSON responses."""
    hub = Hub()
    server = HubServer(hub).start()
    client = RemoteHub(server.address, codec=binwire.CODEC_JSON)
    try:
        client.create_pod(MakePod().name("jj").obj())
        assert client.codec == binwire.CODEC_JSON
        assert client.get_pod(
            client.list_pods()[0].metadata.uid) is not None
        assert _wire_msgs(client, binwire.CODEC_BINARY) == 0
    finally:
        client.close()
        server.stop()


def test_binary_errors_still_map_to_typed_exceptions():
    hub = Hub()
    server = HubServer(hub).start()
    client = RemoteHub(server.address)
    try:
        pod = MakePod().name("e1").obj()
        client.create_pod(pod)
        assert client.codec == binwire.CODEC_BINARY
        with pytest.raises(Conflict):
            client.create_pod(pod)
        with pytest.raises(NotFound):
            client.delete_pod("nope")
    finally:
        client.close()
        server.stop()


def test_multiplexed_watch_one_socket_counts_once():
    """watch_kinds: several kinds on ONE connection; a server restart
    is ONE resume/relist in resilience_stats, not one per kind (the
    satellite fix)."""
    import socket

    hub = Hub()
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    server = HubServer(hub, port=port).start()
    client = RemoteHub(f"http://127.0.0.1:{port}", timeout=10.0,
                       retry_base=0.01, retry_cap=0.2)
    try:
        hub.create_node(MakeNode().name("m-n").obj())
        hub.create_pod(MakePod().name("m-p").obj())
        added = {"pods": [], "nodes": []}
        client.watch_kinds({
            "pods": EventHandlers(
                on_add=lambda o: added["pods"].append(o.metadata.name)),
            "nodes": EventHandlers(
                on_add=lambda o: added["nodes"].append(
                    o.metadata.name))})
        assert added == {"pods": ["m-p"], "nodes": ["m-n"]}
        # one connection only
        assert len(client._watchers) == 1
        server.stop()
        hub.create_pod(MakePod().name("m-p2").obj())
        hub.create_node(MakeNode().name("m-n2").obj())
        server2 = HubServer(hub, port=port).start()
        deadline = time.time() + 15
        while time.time() < deadline and (
                "m-p2" not in added["pods"]
                or "m-n2" not in added["nodes"]):
            time.sleep(0.05)
        assert "m-p2" in added["pods"] and "m-n2" in added["nodes"]
        stats = client.resilience_stats()
        assert stats["watch_reconnects"] == 1, \
            "one cut of a multiplexed socket must count ONCE"
        assert stats["watch_resumes"] + stats["watch_relists"] == 1
        assert len(client._watchers) == 1, "stale handles must prune"
        server2.stop()
    finally:
        client.close()


# ------------------------------- relay tree -------------------------------


@pytest.fixture()
def relayed_hub():
    hub = Hub()
    server = HubServer(hub).start()
    core = RelayCore(server.address, kinds=("pods",), ring_capacity=256)
    relay = RelayServer(core).start()
    yield hub, server, core, relay
    relay.stop()
    server.stop()
    hub.close()


def test_relay_serves_list_resume_and_live(relayed_hub):
    hub, server, core, relay = relayed_hub
    p0 = MakePod().name("r0").obj()
    hub.create_pod(p0)
    # downstream reflector through the relay's HTTP face
    client = RemoteHub(relay.address)
    try:
        added, deleted = [], []
        client.watch_pods(EventHandlers(
            on_add=lambda o: added.append(o.metadata.name),
            on_delete=lambda o: deleted.append(o.metadata.name)))
        assert added == ["r0"], "relay must serve the LIST itself"
        hub.create_pod(MakePod().name("r1").obj())
        hub.delete_pod(p0.metadata.uid)
        deadline = time.time() + 10
        while time.time() < deadline and ("r1" not in added
                                          or "r0" not in deleted):
            time.sleep(0.02)
        assert "r1" in added and deleted == ["r0"]
        # writes pass through the relay to the hub
        client.create_node(MakeNode().name("via-relay").obj())
        assert hub.get_node("via-relay") is not None
        # the hub carries ONE pod watcher (the relay), not one per client
        assert len(hub._pods.handlers) == 1
    finally:
        client.close()


def test_relay_downstream_resume_from_ring(relayed_hub):
    hub, server, core, relay = relayed_hub
    for i in range(3):
        hub.create_pod(MakePod().name(f"ring-{i}").obj())
    deadline = time.time() + 10
    while core.last_rv < hub.current_rv and time.time() < deadline:
        time.sleep(0.02)                  # relay catches up upstream
    sub = core.subscribe(("pods",))
    backlog = sub.drain()
    assert len(backlog) == 3
    cursor = sub.cursor
    core.unsubscribe(sub)
    hub.create_pod(MakePod().name("gap").obj())
    deadline = time.time() + 5
    while core.last_rv <= cursor and time.time() < deadline:
        time.sleep(0.02)
    sub2 = core.subscribe(("pods",), since_rv=cursor)
    got = [d["new"].metadata.name for d in sub2.drain()]
    assert got == ["gap"], "resume must replay exactly the gap"
    assert core.resume_serves == 1
    # a cursor below the ring FLOOR answers RvTooOld -> caller relists.
    # A relay that syncs via LIST cannot serve resumes from before its
    # sync revision (LIST replay is not rv-ordered), so a fresh core's
    # floor is the hub's current revision
    late = RelayCore(server.address, kinds=("pods",), ring_capacity=256)
    try:
        with pytest.raises(RvTooOld):
            late.subscribe(("pods",), since_rv=0)
        # ...and the relist it forces is served from the state mirror
        relisted = late.subscribe(("pods",))
        assert len(relisted.drain()) == 4     # ring-0/1/2 + gap live
    finally:
        late.close()


def test_relay_slow_subscriber_evicted_not_wedged(relayed_hub):
    hub, server, core, relay = relayed_hub
    slow = core.subscribe(("pods",), queue_limit=2)
    fast = core.subscribe(("pods",), queue_limit=1000)
    for i in range(6):
        hub.create_pod(MakePod().name(f"flood-{i}").obj())
    deadline = time.time() + 10
    while not slow.evicted and time.time() < deadline:
        time.sleep(0.02)
    assert slow.evicted, "a consumer that stops draining must be cut"
    assert core.slow_evictions == 1
    # the fast sibling saw everything; backpressure never spread
    deadline = time.time() + 10
    while time.time() < deadline and \
            sum(1 for _ in fast.queue) < 6:
        time.sleep(0.02)
    assert len(fast.drain()) == 6
    # the evicted consumer reconnects and resumes where it stood
    back = core.subscribe(("pods",), since_rv=slow.cursor)
    assert len(back.drain()) >= 4, "the missed flood resumes in"


def test_relay_chain_two_levels(relayed_hub):
    hub, server, core, relay = relayed_hub
    l2 = RelayCore(relay.address, kinds=("pods",), ring_capacity=256)
    try:
        hub.create_pod(MakePod().name("deep").obj())
        sub = l2.subscribe(("pods",))
        deadline = time.time() + 10
        names = []
        while time.time() < deadline and "deep" not in names:
            sub.event.wait(0.1)
            names += [d["new"].metadata.name for d in sub.drain()
                      if d["new"] is not None]
        hub.create_pod(MakePod().name("deep2").obj())
        deadline = time.time() + 10
        while time.time() < deadline and "deep2" not in names:
            sub.event.wait(0.1)
            names += [d["new"].metadata.name for d in sub.drain()
                      if d["new"] is not None]
        assert "deep" in names and "deep2" in names
        # one upstream socket per level: hub sees the L1 relay only
        assert len(hub._pods.handlers) == 1
    finally:
        l2.close()


def test_relay_debug_fabric_authz():
    from kubernetes_tpu.serving import token_auth

    hub = Hub()
    server = HubServer(hub).start()
    core = RelayCore(server.address, kinds=("pods",))
    relay = RelayServer(core, debug_auth=token_auth("s3cret")).start()
    try:
        import json as _json
        import urllib.error
        import urllib.request

        url = relay.address + "/debug/fabric"
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(url)
        assert ei.value.code == 401
        req = urllib.request.Request(
            url, headers={"Authorization": "Bearer s3cret"})
        with urllib.request.urlopen(req) as resp:
            payload = _json.loads(resp.read())
        assert payload["upstream"] == server.address
        assert payload["kinds"] == ["pods"]
        assert "subscriber_cursors" in payload
    finally:
        relay.stop()
        server.stop()


def test_scheduler_debug_fabric_surface():
    """Authz-gated /debug/fabric on the scheduler's serving endpoints:
    shard map + per-shard journal state for a sharded hub."""
    import json as _json
    import urllib.request

    from kubernetes_tpu.config.types import default_config
    from kubernetes_tpu.ops.features import Capacities
    from kubernetes_tpu.scheduler import Scheduler
    from kubernetes_tpu.serving import ServingEndpoints, token_auth

    hub = ShardedHub(pod_shards=2)
    hub.create_node(MakeNode().name("dbg-n").obj())
    sched = Scheduler(hub, default_config(),
                      caps=Capacities(nodes=8, pods=32))
    serving = ServingEndpoints(sched, debug_auth=token_auth("tok"))
    serving.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{serving.port}/debug/fabric",
            headers={"Authorization": "Bearer tok"})
        with urllib.request.urlopen(req) as resp:
            payload = _json.loads(resp.read())
        assert payload["shard_map"]["nodes"] == "nodes"
        assert payload["shard_map"]["pods"] == ["pods-0", "pods-1"]
        assert "nodes" in payload["shards"]
    finally:
        serving.stop()
        sched.close()
        hub.close()


def test_fanout_smoke_small():
    """The --fanout-smoke battery at unit scale: every gate (resume-
    only reconnects, exact fan-out counts, socket accounting, eviction,
    wire ratio, drift zero-LIST) on 150 subscribers."""
    from kubernetes_tpu.fabric.fanout import run_fanout_smoke

    r = run_fanout_smoke(subscribers=150, l1_count=2, l2_count=3,
                         pods=25, churn=30, cuts=3, resub=30,
                         timeout_s=120)
    assert r["ok"], r
    assert r["upstream_relists"] == 0
    assert r["event_count_min"] == r["event_count_max"] \
        == r["pod_events"]
    assert r["hub_pod_watchers"] <= 2
    assert r["wire_ratio"] >= 3.0
    assert r["drift"]["steady_lists"] == 0


def test_sharded_list_changes_rv_precedes_mid_scan_commits():
    """The merged incremental LIST advertises a consistency rv read
    BEFORE the shard scan: a commit landing on an already-scanned shard
    mid-merge must be re-examined by the next resume, never skipped."""
    hub = ShardedHub(pod_shards=2)
    hub.create_pod(MakePod().name("pre").namespace("a").obj())
    base = hub.list_changes(0, ("pods",))
    # interleave: while one shard is being scanned, commit to a shard
    # the router may already have passed
    victim = hub._pod_shards[1]
    orig = victim.list_changes
    sneaky = MakePod().name("sneak").namespace("z").obj()

    def racing(since_rv, kinds=("pods", "nodes")):
        hub.create_pod(sneaky)             # lands on SOME shard now
        return orig(since_rv, kinds)

    victim.list_changes = racing
    res = hub.list_changes(base["rv"], ("pods",))
    victim.list_changes = orig
    assert not res["too_old"]
    missed = [c for c in res["changes"]
              if c["obj"].metadata.name == "sneak"]
    if not missed:
        # the racer's event is absent from this answer: the advertised
        # rv must leave it visible to the NEXT resume
        follow = hub.list_changes(res["rv"], ("pods",))
        assert any(c["obj"].metadata.name == "sneak"
                   for c in follow["changes"]), \
            "a mid-scan commit must never vanish between resumes"
    hub.close()


def test_relay_ring_suspect_during_upstream_relist_window():
    """While an upstream RELIST is replaying (LIST order, not rv
    order), the relay must refuse ring resumes (RvTooOld -> state-
    mirror relist) instead of serving a suffix with holes; the sync
    marker resets the ring and resumes work again."""
    from kubernetes_tpu.storage import JournalEvent

    hub = Hub()
    server = HubServer(hub).start()
    core = RelayCore(server.address, kinds=("pods",), ring_capacity=64)
    try:
        on_event = core._make_on_event("pods")
        p5 = MakePod().name("p5").obj()
        p3 = MakePod().name("p3").obj()
        p5.metadata.resource_version = 5
        p3.metadata.resource_version = 3
        on_event(JournalEvent(rv=5, kind="pods", type="add", new=p5))
        on_event(JournalEvent(rv=3, kind="pods", type="add", new=p3))
        assert core._ring_suspect, "out-of-order rv = relist in flight"
        with pytest.raises(RvTooOld):
            core.subscribe(("pods",), since_rv=5)
        core._on_sync(6, relisted=True)
        assert not core._ring_suspect
        # resumes from the new floor serve again; below it, 410
        sub = core.subscribe(("pods",), since_rv=6)
        assert sub.drain() == []
        with pytest.raises(RvTooOld):
            core.subscribe(("pods",), since_rv=5)
    finally:
        core.close()
        server.stop()


def test_sharded_journal_stats_merge_sums_hashed_kind():
    hub = ShardedHub(pod_shards=3)
    for i in range(9):
        hub.create_pod(MakePod().name(f"js-{i}")
                       .namespace(f"ns-{i}").obj())
    js = hub.get_journal_stats()
    # the merged per-kind view must SUM depth across the hashed shards
    # (dict.update would report only the last shard's slice)
    assert js["kinds"]["pods"]["depth"] == 9
    assert js["kinds"]["pods"]["last_rv"] == hub.current_rv
    hub.close()


def test_incremental_drift_falls_back_on_pre_fabric_peer():
    """A remote hub without list_changes answers the /call wire's 400
    ValueError — the comparer must translate that to RvTooOld (full-
    diff fallback), not crash the maintenance loop."""
    from kubernetes_tpu.backend.cache import Cache

    class PreFabricHub:
        def list_changes(self, since_rv, kinds=()):
            raise ValueError("unknown method 'list_changes'")

    cache = Cache()
    with pytest.raises(RvTooOld):
        cache.drift_report(PreFabricHub(), since_rv=7)


def test_sharded_wal_dir_rejects_existing_file(tmp_path):
    """Upgrading a single-hub deployment's --wal FILE to --hub-shards
    must fail with a clear verdict, not makedirs' FileExistsError."""
    wal_file = tmp_path / "hub.wal"
    wal_file.write_text("{}\n")
    with pytest.raises(ValueError, match="WAL directory"):
        ShardedHub(pod_shards=2, wal_dir=str(wal_file))
