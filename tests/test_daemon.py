"""Run() daemon, maintenance timers, Permit WAIT, and the async binding
cycle (reference: scheduler.go Run, scheduling_queue.go:378-386 flush
goroutines, runtime/waiting_pods_map.go, schedule_one.go:124/270 binding
goroutine + :337 bind-failure requeue)."""

import threading
import time

from kubernetes_tpu.api.objects import (
    Container,
    LABEL_HOSTNAME,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    ResourceRequirements,
)
from kubernetes_tpu.config.types import default_config
from kubernetes_tpu.framework.interface import Code, PermitPlugin, Status
from kubernetes_tpu.hub import Hub
from kubernetes_tpu.ops.features import Capacities
from kubernetes_tpu.plugins.registry import PluginDescriptor, in_tree_registry
from kubernetes_tpu.scheduler import Scheduler


class Clock:
    def __init__(self):
        self.t = 1000.0

    def now(self):
        return self.t

    def tick(self, dt):
        self.t += dt


def mknode(i, cpu="16"):
    name = f"node-{i}"
    return Node(metadata=ObjectMeta(name=name, labels={LABEL_HOSTNAME: name}),
                status=NodeStatus(allocatable={"cpu": cpu, "memory": "32Gi",
                                               "pods": "110"}))


def mkpod(name, cpu="100m"):
    return Pod(metadata=ObjectMeta(name=name),
               spec=PodSpec(containers=[Container(
                   name="c", resources=ResourceRequirements(
                       requests={"cpu": cpu, "memory": "64Mi"}))]))


def mksched(hub, clock=None, registry=None, batch=16):
    cfg = default_config()
    cfg.batch_size = batch
    clock = clock or Clock()
    return Scheduler(hub, cfg, caps=Capacities(nodes=16, pods=64),
                     now=clock.now, registry=registry), clock


def bound_node(hub, pod):
    p = hub.get_pod(pod.metadata.uid)
    return p.spec.node_name if p else None


class GatePermit(PermitPlugin):
    """Test permit plugin: WAITs every pod until allowed externally."""

    NAME = "GatePermit"

    def __init__(self, timeout=60.0):
        self.timeout = timeout
        self.seen = []

    def permit(self, state, pod, node_name):
        self.seen.append(pod.metadata.name)
        return Status(code=Code.WAIT, plugin=self.NAME), self.timeout


def registry_with_permit(plugin):
    reg = in_tree_registry()
    reg["GatePermit"] = PluginDescriptor(
        name="GatePermit", points=("permit",),
        factory=lambda args: plugin)
    return reg


def enable_plugin(cfg, name):
    from kubernetes_tpu.config.types import Plugin

    cfg.profiles[0].plugins.multi_point.enabled.append(Plugin(name, 0))


def test_permit_wait_then_allow_binds():
    hub = Hub()
    permit = GatePermit()
    cfg = default_config()
    cfg.batch_size = 16
    enable_plugin(cfg, "GatePermit")
    clock = Clock()
    sched = Scheduler(hub, cfg, caps=Capacities(nodes=16, pods=64),
                      now=clock.now,
                      registry=registry_with_permit(permit))
    hub.create_node(mknode(0))
    p = mkpod("p")
    hub.create_pod(p)
    sched.run_until_idle()
    # parked at permit: reservation held (assumed), not bound, not failed
    assert bound_node(hub, p) == ""
    assert len(sched.framework.waiting_pods) == 1
    assert sched.cache.assumed_pod_count() == 1
    assert sched.stats["scheduled"] == 0
    # an approver allows it: next cycle binds
    wp = sched.framework.waiting_pods.get(p.metadata.uid)
    wp.allow("GatePermit")
    sched.run_until_idle()
    assert bound_node(hub, p) == "node-0"
    assert sched.stats["scheduled"] == 1
    assert sched.cache.assumed_pod_count() == 0


def test_permit_wait_timeout_requeues():
    hub = Hub()
    permit = GatePermit(timeout=30.0)
    cfg = default_config()
    cfg.batch_size = 16
    enable_plugin(cfg, "GatePermit")
    clock = Clock()
    sched = Scheduler(hub, cfg, caps=Capacities(nodes=16, pods=64),
                      now=clock.now,
                      registry=registry_with_permit(permit))
    hub.create_node(mknode(0))
    p = mkpod("p")
    hub.create_pod(p)
    sched.run_until_idle()
    assert len(sched.framework.waiting_pods) == 1
    # the timeout passes with no allow: unreserve + UNSCHEDULABLE requeue
    # attributed to the timing-out plugin (schedule_one.go:270)
    clock.tick(31.0)
    sched.run_maintenance()
    assert len(sched.framework.waiting_pods) == 0
    assert sched.cache.assumed_pod_count() == 0
    assert sched.stats["unschedulable"] == 1
    assert sched.stats["errors"] == 0
    cond = hub.get_pod(p.metadata.uid).status.conditions[0]
    assert cond.reason == "Unschedulable"


def test_permit_reject_while_waiting():
    hub = Hub()
    permit = GatePermit()
    cfg = default_config()
    cfg.batch_size = 16
    enable_plugin(cfg, "GatePermit")
    clock = Clock()
    sched = Scheduler(hub, cfg, caps=Capacities(nodes=16, pods=64),
                      now=clock.now,
                      registry=registry_with_permit(permit))
    hub.create_node(mknode(0))
    p = mkpod("p")
    hub.create_pod(p)
    sched.run_until_idle()
    wp = sched.framework.waiting_pods.get(p.metadata.uid)
    wp.reject("GatePermit", "not today")
    sched.run_until_idle()
    assert bound_node(hub, p) == ""
    assert sched.cache.assumed_pod_count() == 0


def test_bind_failure_unreserves_and_requeues():
    hub = Hub()
    sched, clock = mksched(hub)
    hub.create_node(mknode(0))
    fails = {"n": 0}
    orig_bind = hub.bind

    def flaky_bind(pod, node_name):
        if fails["n"] == 0:
            fails["n"] += 1
            raise RuntimeError("apiserver hiccup")
        orig_bind(pod, node_name)

    sched.framework.instance("DefaultBinder")._binder = flaky_bind
    p = mkpod("p")
    hub.create_pod(p)
    sched.run_until_idle()
    clock.tick(2.0)
    sched.queue.flush_backoff_completed()
    sched.run_until_idle()
    # first attempt failed at bind (Forget + error-class requeue recorded);
    # the retry then bound cleanly
    assert sched.stats["errors"] == 1
    assert fails["n"] == 1
    assert bound_node(hub, p) == "node-0"
    assert sched.stats["scheduled"] == 1
    assert sched.cache.assumed_pod_count() == 0
    cond_reasons = [c.reason for c in
                    hub.get_pod(p.metadata.uid).status.conditions]
    assert "SchedulerError" in cond_reasons


def test_unschedulable_timeout_flush_without_events():
    """A pod whose rejecting plugin never sees a matching event escapes via
    the 5min cap (scheduling_queue.go:378's flushUnschedulablePodsLeftover),
    driven by run_maintenance's 30s tick."""
    hub = Hub()
    sched, clock = mksched(hub)
    hub.create_node(mknode(0, cpu="1"))
    big = mkpod("big", cpu="8")
    hub.create_pod(big)
    sched.run_until_idle()
    assert sched.stats["unschedulable"] == 1
    # grow the node quietly (no hub event => no requeue signal)
    sched.queue._unschedulable[big.metadata.uid].unschedulable_plugins = set()
    clock.tick(301.0)
    sched.run_maintenance()
    counts = sched.queue.pending_counts()
    assert counts["unschedulable"] == 0, "flushed by the 5min cap"
    assert counts["active"] + counts["backoff"] == 1


def test_daemon_thread_schedules_and_stops():
    """start()/stop(): pods created from a foreign thread while the daemon
    runs are scheduled without explicit drains."""
    hub = Hub()
    cfg = default_config()
    cfg.batch_size = 16
    sched = Scheduler(hub, cfg, caps=Capacities(nodes=16, pods=64))
    hub.create_node(mknode(0))
    sched.start()
    try:
        pods = [mkpod(f"p{i}") for i in range(10)]
        for p in pods:
            hub.create_pod(p)
        deadline = time.time() + 30
        while time.time() < deadline:
            if all(bound_node(hub, p) for p in pods):
                break
            time.sleep(0.02)
        assert all(bound_node(hub, p) for p in pods)
    finally:
        sched.stop()
    assert sched._daemon is None


# suite-tier discipline (tests/test_markers.py): area marker
import pytest  # noqa: E402
pytestmark = pytest.mark.core
