from kubernetes_tpu.api.labels import (
    find_untolerated_taint,
    label_selector_matches,
    node_selector_matches,
    node_selector_term_matches,
    pod_matches_node_selector_and_affinity,
)
from kubernetes_tpu.api.objects import (
    Affinity,
    LabelSelector,
    LabelSelectorRequirement,
    Node,
    NodeAffinity,
    NodeSelector,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    ObjectMeta,
    Pod,
    PodSpec,
    Taint,
    Toleration,
)


def node(name="n1", labels=None):
    return Node(metadata=ObjectMeta(name=name, labels=labels or {}))


def test_label_selector():
    assert label_selector_matches(LabelSelector(), {"a": "b"})  # empty matches all
    assert not label_selector_matches(None, {"a": "b"})  # nil matches nothing
    sel = LabelSelector(match_labels={"app": "web"})
    assert label_selector_matches(sel, {"app": "web", "x": "y"})
    assert not label_selector_matches(sel, {"app": "db"})
    sel = LabelSelector(match_expressions=[
        LabelSelectorRequirement("tier", "In", ["fe", "be"]),
        LabelSelectorRequirement("canary", "DoesNotExist"),
    ])
    assert label_selector_matches(sel, {"tier": "fe"})
    assert not label_selector_matches(sel, {"tier": "fe", "canary": "1"})
    assert not label_selector_matches(sel, {"tier": "db"})


def test_node_selector_ops():
    n = node(labels={"zone": "us-1a", "cpus": "32"})
    term = NodeSelectorTerm(match_expressions=[
        NodeSelectorRequirement("zone", "In", ["us-1a", "us-1b"])])
    assert node_selector_term_matches(term, n)
    term = NodeSelectorTerm(match_expressions=[
        NodeSelectorRequirement("cpus", "Gt", ["16"])])
    assert node_selector_term_matches(term, n)
    term = NodeSelectorTerm(match_expressions=[
        NodeSelectorRequirement("cpus", "Lt", ["16"])])
    assert not node_selector_term_matches(term, n)
    # Gt with non-integer label: no match
    term = NodeSelectorTerm(match_expressions=[
        NodeSelectorRequirement("zone", "Gt", ["16"])])
    assert not node_selector_term_matches(term, n)
    # empty term matches nothing
    assert not node_selector_term_matches(NodeSelectorTerm(), n)


def test_match_fields():
    n = node(name="special")
    term = NodeSelectorTerm(match_fields=[
        NodeSelectorRequirement("metadata.name", "In", ["special"])])
    assert node_selector_term_matches(term, n)
    assert not node_selector_term_matches(term, node(name="other"))


def test_node_selector_or_terms():
    n = node(labels={"a": "1"})
    sel = NodeSelector(node_selector_terms=[
        NodeSelectorTerm(match_expressions=[NodeSelectorRequirement("b", "Exists")]),
        NodeSelectorTerm(match_expressions=[NodeSelectorRequirement("a", "In", ["1"])]),
    ])
    assert node_selector_matches(sel, n)
    assert node_selector_matches(None, n)  # nil matches everything


def test_pod_node_selector_and_affinity():
    n = node(labels={"disk": "ssd"})
    pod = Pod(spec=PodSpec(node_selector={"disk": "ssd"}))
    assert pod_matches_node_selector_and_affinity(pod, n)
    pod = Pod(spec=PodSpec(node_selector={"disk": "hdd"}))
    assert not pod_matches_node_selector_and_affinity(pod, n)
    pod = Pod(spec=PodSpec(affinity=Affinity(node_affinity=NodeAffinity(
        required=NodeSelector(node_selector_terms=[
            NodeSelectorTerm(match_expressions=[
                NodeSelectorRequirement("disk", "In", ["ssd"])])])))))
    assert pod_matches_node_selector_and_affinity(pod, n)


def test_tolerations():
    taints = [Taint(key="gpu", value="true", effect="NoSchedule")]
    assert find_untolerated_taint(taints, []) is not None
    assert find_untolerated_taint(
        taints, [Toleration(key="gpu", operator="Equal", value="true",
                            effect="NoSchedule")]) is None
    assert find_untolerated_taint(
        taints, [Toleration(key="gpu", operator="Exists")]) is None
    assert find_untolerated_taint(taints, [Toleration(operator="Exists")]) is None
    # PreferNoSchedule taints never fail the filter
    soft = [Taint(key="x", effect="PreferNoSchedule")]
    assert find_untolerated_taint(soft, []) is None


def test_interner():
    from kubernetes_tpu.utils.interner import NONE, Interner

    it = Interner()
    a = it.intern("app")
    b = it.intern("web")
    assert it.intern("app") == a != b
    assert it.string(a) == "app"
    assert it.lookup("nope") == NONE
    n = it.intern("42")
    assert it.numeric(n) == 42.0
    import math
    assert math.isnan(it.numeric(a))
    assert it.string(0) == ""


# suite-tier discipline (tests/test_markers.py): area marker
import pytest  # noqa: E402
pytestmark = pytest.mark.core
