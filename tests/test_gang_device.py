"""Device-side gang packing (ISSUE 12): the ops/gang.pack_gangs kernel
(all-or-nothing verdict, topology-close packing, sequential in-launch
gang commits, the folded capacity bound) and the scheduler's device gang
path — differential against the host Permit-quorum path over randomized
gangs, atomic unit rollback, the async PreFilter bound, and the DRR
backfill around credit-gated gangs."""

import random

import numpy as np
import pytest

from kubernetes_tpu.api.objects import (
    LABEL_POD_GROUP,
    LABEL_QUEUE,
    LABEL_ZONE,
    ObjectMeta,
    PodGroup,
)
from kubernetes_tpu.backend.cache import Cache
from kubernetes_tpu.backend.jobqueue import JobQueue
from kubernetes_tpu.backend.mirror import Mirror
from kubernetes_tpu.backend.snapshot import Snapshot
from kubernetes_tpu.config.types import default_config
from kubernetes_tpu.hub import Hub
from kubernetes_tpu.ops.features import Capacities, PodBlobs
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.testing import MakeNode, MakePod

pytestmark = pytest.mark.gang


class Clock:
    def __init__(self):
        self.t = 1000.0

    def now(self):
        return self.t

    def tick(self, dt):
        self.t += dt


def gang_pod(name, gang, cpu="100m", tenant="t", priority=None):
    mk = MakePod().name(name).req(cpu=cpu)
    p = mk.obj()
    p.metadata.labels[LABEL_POD_GROUP] = gang
    p.metadata.labels[LABEL_QUEUE] = tenant
    if priority is not None:
        p.spec.priority = priority
    return p


def group(name, min_member, timeout=10.0):
    return PodGroup(metadata=ObjectMeta(name=name), min_member=min_member,
                    queue="t", schedule_timeout_seconds=timeout)


# ------------------------------------------------- the packer kernel


def _mini_cluster(node_cpus, zones=None):
    """(mirror, caps) over nodes with the given cpu strings; zones[i]
    labels node i's zone when given."""
    caps = Capacities(nodes=16, pods=128)
    cache, snap, mirror = Cache(), Snapshot(), Mirror(caps=caps)
    for i, cpu in enumerate(node_cpus):
        n = (MakeNode().name(f"n{i}")
             .capacity(cpu=cpu, memory="32Gi", pods="110").obj())
        if zones is not None:
            n.metadata.labels[LABEL_ZONE] = zones[i]
        cache.add_node(n)
    cache.update_snapshot(snap)
    mirror.sync(snap)
    return mirror, caps


def _pack(mirror, caps, reps, needs, g_bucket=4):
    from kubernetes_tpu.models.pipeline import extract_state_jit
    from kubernetes_tpu.ops.gang import pack_gangs_jit

    import jax.numpy as jnp

    feats = mirror.launch_features(reps)
    pfields = mirror.pod_fields(feats, False)
    f32, i32 = mirror._pack_batch_np(reps, g_bucket, pfields)
    tk, d_bucket = mirror.gang_pack_domain()
    need = np.zeros((g_bucket,), np.int32)
    need[:len(needs)] = needs
    cblobs = mirror.to_blobs()
    return pack_gangs_jit(
        cblobs, PodBlobs(f32=jnp.asarray(f32), i32=jnp.asarray(i32)),
        mirror.well_known(), caps, need, np.int32(tk), d_cap=d_bucket,
        enabled_filters=(True,) * 8, active=feats, pfields=pfields,
        ptmpl=mirror.pod_template_blobs(),
        state=extract_state_jit(cblobs, caps))


def test_packer_all_or_nothing():
    """A gang past total capacity places NOTHING; a fitting one places
    exactly `need` members."""
    mirror, caps = _mini_cluster(["2", "2"])       # 2 nodes x 2 cpu
    rep = MakePod().name("r").req(cpu="900m").obj()  # 2 fit per node
    out = _pack(mirror, caps, [rep, rep], [4, 5])
    ok = np.asarray(out.ok)
    alloc = np.asarray(out.alloc)
    assert bool(ok[0]) and alloc[0].sum() == 4
    # gang 1 runs AFTER gang 0 committed: zero capacity left
    assert not bool(ok[1]) and alloc[1].sum() == 0
    assert int(np.asarray(out.cap)[1]) == 0


def test_packer_sequential_gangs_chain_usage():
    mirror, caps = _mini_cluster(["4", "4"])
    rep = MakePod().name("r").req(cpu="1900m").obj()  # 2 per node
    out = _pack(mirror, caps, [rep, rep], [2, 2])
    ok = np.asarray(out.ok)
    assert bool(ok[0]) and bool(ok[1])
    # 4 members of 1900m over 2x4cpu: both gangs land, cluster full
    assert np.asarray(out.alloc)[:2].sum() == 4
    assert int(np.asarray(out.cap)[1]) == 2   # bound AFTER gang 0 commits


def test_packer_topology_close_packing():
    """A gang that FITS one zone lands in one zone even when spreading
    would also be feasible — the co-location criterion."""
    zones = ["z0", "z0", "z1", "z1", "z2", "z2", "z3", "z3"]
    mirror, caps = _mini_cluster(["4"] * 8, zones=zones)
    rep = MakePod().name("r").req(cpu="900m").obj()   # 4 per node
    out = _pack(mirror, caps, [rep], [8])             # one zone holds 8
    assert bool(np.asarray(out.ok)[0])
    assert int(np.asarray(out.spans)[0]) == 1
    # and a gang bigger than any one zone spans exactly two
    out2 = _pack(mirror, caps, [rep, rep], [12, 0])
    assert bool(np.asarray(out2.ok)[0])
    assert int(np.asarray(out2.spans)[0]) == 2


def test_packer_respects_static_filters():
    """A tainted node contributes no member capacity (the bound is
    static-filter-aware, tighter than the old free-matrix bound)."""
    from kubernetes_tpu.api.objects import Taint

    caps = Capacities(nodes=16, pods=128)
    cache, snap, mirror = Cache(), Snapshot(), Mirror(caps=caps)
    n0 = MakeNode().name("n0").capacity(cpu="4", memory="8Gi",
                                        pods="110").obj()
    n1 = MakeNode().name("n1").capacity(cpu="4", memory="8Gi",
                                        pods="110").obj()
    n1.spec.taints = [Taint(key="k", value="v", effect="NoSchedule")]
    cache.add_node(n0)
    cache.add_node(n1)
    cache.update_snapshot(snap)
    mirror.sync(snap)
    rep = MakePod().name("r").req(cpu="900m").obj()
    out = _pack(mirror, caps, [rep], [8])      # would fit over both
    assert not bool(np.asarray(out.ok)[0])     # only n0's 4 count
    assert int(np.asarray(out.cap)[0]) == 4


# ------------------------------------------------- scheduler device path


def _sched(hub, clock, nodes=4, cpu="2", device=True, zones=None,
           batch=64):
    for i in range(nodes):
        n = (MakeNode().name(f"n{i}")
             .capacity(cpu=cpu, memory="8Gi", pods="110").obj())
        if zones is not None:
            n.metadata.labels[LABEL_ZONE] = zones[i % len(zones)]
        hub.create_node(n)
    cfg = default_config()
    cfg.batch_size = batch
    cfg.gang_device_packing = device
    return Scheduler(hub, cfg, caps=Capacities(nodes=16, pods=256),
                     now=clock.now)


def test_device_path_one_launch_per_gang_wave():
    """O(1) device launches per gang, not O(members): a 12-member gang
    binds whole off ONE fused pack launch, no Permit assembly."""
    hub, clock = Hub(), Clock()
    sched = _sched(hub, clock, nodes=4, cpu="4")
    try:
        hub.create_pod_group(group("big", 12))
        for i in range(12):
            hub.create_pod(gang_pod(f"b-{i}", "big", cpu="900m"))
        sched.run_until_idle()
        bound = [p for p in hub.list_pods() if p.spec.node_name]
        assert len(bound) == 12
        assert sched.stats["gang_device_launches"] == 1
        assert sched._gang.stats["device_admitted"] == 1
        assert sched.metrics.gang_device_launches.value() == 1
        # no quorum assembly happened: nothing ever waited at Permit
        assert not sched._gang._assembling
        assert sched.cache.assumed_pod_count() == 0
    finally:
        sched.close()


def test_device_infeasible_parks_without_reservations():
    hub, clock = Hub(), Clock()
    sched = _sched(hub, clock, nodes=2, cpu="1")
    try:
        hub.create_pod_group(group("huge", 4))
        for i in range(4):
            hub.create_pod(gang_pod(f"x-{i}", "huge", cpu="900m"))
        sched.run_until_idle()
        assert all(not p.spec.node_name for p in hub.list_pods())
        assert sched.cache.assumed_pod_count() == 0
        assert sum(len(fw.waiting_pods)
                   for fw in sched.frameworks.values()) == 0
        assert sched.stats["gang_device_launches"] >= 1
    finally:
        sched.close()


def test_device_members_land_topology_close():
    hub, clock = Hub(), Clock()
    zones = ["z0", "z0", "z1", "z1", "z2", "z2"]
    sched = _sched(hub, clock, nodes=6, cpu="4", zones=zones)
    try:
        hub.create_pod_group(group("co", 8))
        for i in range(8):
            hub.create_pod(gang_pod(f"c-{i}", "co", cpu="900m"))
        sched.run_until_idle()
        node_zone = {n.metadata.name: n.metadata.labels.get(LABEL_ZONE)
                     for n in hub.list_nodes()}
        used = {node_zone[p.spec.node_name] for p in hub.list_pods()
                if p.spec.node_name}
        assert len(used) == 1, f"gang spread over zones {used}"
    finally:
        sched.close()


def test_device_unit_rollback_is_atomic():
    """A member whose Reserve fails mid-unit rolls the WHOLE unit back
    before anything reaches the binder: no partial gang, no leaked
    reservation."""
    hub, clock = Hub(), Clock()
    sched = _sched(hub, clock, nodes=4, cpu="4")
    try:
        hub.create_pod_group(group("frag", 4))
        pods = [gang_pod(f"f-{i}", "frag", cpu="500m") for i in range(4)]
        for p in pods:
            hub.create_pod(p)
        victim_uid = pods[2].metadata.uid
        fw = sched.framework
        real_reserve = fw.run_reserve_plugins

        def failing_reserve(state, pod, node):
            if pod.metadata.uid == victim_uid:
                raise RuntimeError("reserve poison")
            return real_reserve(state, pod, node)

        fw.run_reserve_plugins = failing_reserve
        sched.run_until_idle()
        assert all(not p.spec.node_name for p in hub.list_pods())
        assert sched.cache.assumed_pod_count() == 0, \
            "rollback must release every reservation"
        assert sched._gang.stats["rollbacks"] >= 1
        assert not sched._gang._device_admitted
        # and after the poison clears, the gang schedules whole (peers
        # parked unschedulable-class: the 5-minute park cap re-activates)
        fw.run_reserve_plugins = real_reserve
        clock.tick(301.0)
        sched.queue.flush_backoff_completed()
        sched.queue.flush_unschedulable_timeout()
        sched.run_until_idle()
        assert sum(1 for p in hub.list_pods() if p.spec.node_name) == 4
    finally:
        sched.close()


def test_device_fault_falls_back_to_permit_path():
    """A raising pack launch degrades the unit to the host Permit path
    (the ladder), which still schedules it."""
    from kubernetes_tpu.ops import gang as G

    hub, clock = Hub(), Clock()
    sched = _sched(hub, clock, nodes=4, cpu="4")
    real = G.pack_gangs_jit
    try:
        hub.create_pod_group(group("lad", 3))
        for i in range(3):
            hub.create_pod(gang_pod(f"l-{i}", "lad", cpu="500m"))

        def boom(*a, **kw):
            raise RuntimeError("xla fault")

        G.pack_gangs_jit = boom
        sched.run_until_idle()
        assert sum(1 for p in hub.list_pods() if p.spec.node_name) == 3
        assert sched.stats["gang_fallbacks"] >= 1
        assert sched._gang.stats["device_admitted"] == 0
        assert sched._gang.stats["admitted"] >= 1   # Permit quorum did it
    finally:
        G.pack_gangs_jit = real
        sched.close()


def test_prefilter_bound_rides_cycle_pull():
    """The host-fallback capacity bound never blocks: PreFilter leaves a
    pending device scalar, the per-cycle pull resolves it into the memo,
    and a later attempt under the same token enforces the bound."""
    import jax

    hub, clock = Hub(), Clock()
    sched = _sched(hub, clock, nodes=2, cpu="1", device=False)
    try:
        hub.create_pod_group(group("cap", 4, timeout=5.0))
        for i in range(4):
            hub.create_pod(gang_pod(f"q-{i}", "cap", cpu="900m"))
        sched.run_until_idle()
        gang = sched._gang
        key = "default/cap"
        # the run resolved the bound through the ride-along pull
        assert gang._cap_cache.get(key) is not None
        # settle: time out the two waiting reservations so the free
        # matrix (and therefore the bound) reflects an empty cluster
        _settle(sched, clock, waves=1)
        assert all(not p.spec.node_name for p in hub.list_pods())
        assert sched.cache.assumed_pod_count() == 0
        # a fresh attempt under a SETTLED mirror: the first pre_filter
        # may re-dispatch (token drift from the run's last sync); its
        # pending scalar resolves through the same public plumbing the
        # scheduler uses, and the next call rejects from the memo
        pod = next(p for p in hub.list_pods())
        gang.pre_filter(None, pod, None)
        for ckey, ctok, arr in gang.take_pending_caps():
            gang.resolve_cap(ckey, ctok, int(jax.device_get(arr)))
        assert not gang._pending_caps
        s = gang.pre_filter(None, pod, None)
        assert s.is_rejected()
        assert "capacity bound 2" in s.message()
    finally:
        sched.close()


# ------------------------------------------------- differential fuzz


def _settle(sched, clock, waves: int = 4) -> None:
    """Drive the host arm to a settled state: each wave times out any
    Permit waiters (small ticks past the gang timeout, which re-activate
    nothing else), then re-activates unschedulable parks past the
    5-minute cap for another attempt (the capacity-bound memo converges
    across waves); ends with a waiter-drain so no reservation is held
    merely because the clock stopped."""
    def drain_waiters():
        for _ in range(4):
            clock.tick(7.0)
            sched.run_until_idle()
            waiting = sum(len(fw.waiting_pods)
                          for fw in sched.frameworks.values())
            if waiting == 0 and sched.cache.assumed_pod_count() == 0:
                return

    for _ in range(waves):
        drain_waiters()
        clock.tick(301.0)
        sched.queue.flush_backoff_completed()
        sched.queue.flush_unschedulable_timeout()
        sched.run_until_idle()
    drain_waiters()


def _scenario(seed: int):
    """Randomized but ORDER-INDEPENDENT multi-gang scenario: gangs whose
    sizes sum under cluster capacity (must all bind, either arm) plus —
    half the time — one standalone-infeasible gang (must bind nothing).
    Which-gang-wins-under-contention is legitimately order-dependent
    between a per-member serial placement and a per-unit packer, so the
    verdict comparison sticks to the decidable class; the contended
    class keeps the invariant checks (test below)."""
    rng = random.Random(seed)
    nodes = rng.randint(3, 8)
    node_cpu = rng.choice(["1", "2", "4"])
    member_cpu = rng.choice(["500m", "900m", "1100m"])
    per_node = int(node_cpu) * 1000 // int(member_cpu[:-1])
    capacity = nodes * per_node
    sizes = []
    left = capacity
    for _ in range(rng.randint(1, 3)):
        if left <= 0:
            break
        s = rng.randint(1, min(6, left))
        sizes.append(s)
        left -= s
    if rng.random() < 0.5:
        sizes.append(capacity + rng.randint(1, 4))
    rng.shuffle(sizes)
    return nodes, node_cpu, member_cpu, sizes, capacity


def _run_arm(seed: int, device: bool):
    nodes, node_cpu, member_cpu, sizes, capacity = _scenario(seed)
    hub, clock = Hub(), Clock()
    sched = _sched(hub, clock, nodes=nodes, cpu=node_cpu, device=device)
    try:
        for g, size in enumerate(sizes):
            hub.create_pod_group(group(f"g{g}", size, timeout=6.0))
        for g, size in enumerate(sizes):
            for m in range(size):
                hub.create_pod(gang_pod(f"g{g}-m{m}", f"g{g}",
                                        cpu=member_cpu))
        sched.run_until_idle()
        _settle(sched, clock)
        bound: dict[str, int] = {f"g{g}": 0 for g in range(len(sizes))}
        for p in hub.list_pods():
            if p.spec.node_name:
                bound[p.metadata.labels[LABEL_POD_GROUP]] += 1
        # invariants shared by both arms: zero partial gangs, zero
        # leaked reservations
        assert sched.cache.assumed_pod_count() == 0, f"seed {seed}"
        for g, size in enumerate(sizes):
            assert bound[f"g{g}"] in (0, size), \
                f"seed {seed}: partial gang g{g}: {bound} of {sizes}"
        return bound, sizes, capacity
    finally:
        sched.close()


def _differential(seed: int):
    dev, sizes, capacity = _run_arm(seed, device=True)
    host, _sizes, _cap = _run_arm(seed, device=False)
    assert dev == host, (f"seed {seed}: device verdicts {dev} != "
                         f"host verdicts {host} (sizes {sizes}, "
                         f"capacity {capacity})")
    for g, size in enumerate(sizes):
        want = 0 if size > capacity else size
        assert dev[f"g{g}"] == want, \
            (f"seed {seed}: gang g{g} size {size} capacity {capacity}: "
             f"bound {dev[f'g{g}']}, want {want}")


@pytest.mark.parametrize("seed", range(8))
def test_differential_device_vs_permit_path(seed):
    """Tier-1 slice: same admit/reject verdict per gang under both
    arms, zero partial gangs, zero leaked reservations."""
    _differential(seed)


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(8, 60))
def test_differential_device_vs_permit_path_full(seed):
    _differential(seed)


@pytest.mark.parametrize("seed", (101, 102, 103))
def test_contended_gangs_atomic_in_both_arms(seed):
    """Over-subscribed contention (sum of sizes past capacity): which
    gang wins is order-dependent, but BOTH arms must keep every gang
    all-or-nothing with zero leaked reservations and never place more
    members than capacity."""
    rng = random.Random(seed)
    nodes = rng.randint(2, 5)
    sizes = [rng.randint(2, 6) for _ in range(3)]
    capacity = nodes * 2                       # 2-cpu nodes, 900m members
    for device in (True, False):
        hub, clock = Hub(), Clock()
        sched = _sched(hub, clock, nodes=nodes, cpu="2", device=device)
        try:
            for g, size in enumerate(sizes):
                hub.create_pod_group(group(f"g{g}", size, timeout=6.0))
            for g, size in enumerate(sizes):
                for m in range(size):
                    hub.create_pod(gang_pod(f"g{g}-m{m}", f"g{g}",
                                            cpu="900m"))
            sched.run_until_idle()
            _settle(sched, clock, waves=3)
            bound = {f"g{g}": 0 for g in range(len(sizes))}
            for p in hub.list_pods():
                if p.spec.node_name:
                    bound[p.metadata.labels[LABEL_POD_GROUP]] += 1
            assert sched.cache.assumed_pod_count() == 0
            assert sum(bound.values()) <= capacity
            for g, size in enumerate(sizes):
                assert bound[f"g{g}"] in (0, size), \
                    (f"seed {seed} device={device}: partial gang "
                     f"g{g}: {bound} of {sizes}")
        finally:
            sched.close()


# ------------------------------------------------- DRR backfill


def test_singles_backfill_around_credit_gated_gang():
    """Small jobs flow around a credit-gated gang the very round it
    blocks — and the gang still releases within its bounded wait
    (deficit accrues to the gang, backfill rides bounded debt)."""
    from tests.test_gang import FakePQ, tenant_pod
    from tests.test_gang import group as tgroup

    jq = JobQueue({"a": {"weight": 1.0}, "b": {"weight": 1.0}})
    jq.set_group(tgroup("g8", 8, queue="a"))
    for i in range(8):
        jq.add(tenant_pod(f"g-{i}", "a", gang="g8"))
    for i in range(4):
        jq.add(tenant_pod(f"s-{i}", "a"))
        jq.add(tenant_pod(f"b-{i}", "b"))      # persistent contention
    pq = FakePQ()
    jq.release(pq, budget=4)
    names = [p.metadata.name for p in pq.pods]
    assert any(n.startswith("s-") for n in names), \
        "singles must backfill around the credit-gated gang"
    assert not any(n.startswith("g-") for n in names)
    # the gang's deficit was NOT spent by the backfill: it releases
    # within the same bounded wait as without backfill
    for _ in range(12):
        jq.release(pq, budget=16)
        if any(p.metadata.name.startswith("g-") for p in pq.pods):
            break
    else:
        raise AssertionError("backfill starved the earmarked gang")
    assert sum(1 for p in pq.pods
               if p.metadata.name.startswith("g-")) == 8


def test_device_permit_failure_rolls_back_whole_unit():
    """All-or-nothing holds through the PERMIT stage too: one member's
    permit rejection undoes every reserved peer before any member
    reaches the binder (review finding: undoing only the failing member
    left its peers binding as a partial gang)."""
    hub, clock = Hub(), Clock()
    sched = _sched(hub, clock, nodes=4, cpu="4")
    try:
        hub.create_pod_group(group("pfail", 4))
        pods = [gang_pod(f"p-{i}", "pfail", cpu="500m") for i in range(4)]
        for p in pods:
            hub.create_pod(p)
        victim_uid = pods[1].metadata.uid
        fw = sched.framework
        real_permit = fw.run_permit_plugins

        def failing_permit(state, pod, node):
            if pod.metadata.uid == victim_uid:
                from kubernetes_tpu.framework.interface import Status

                return Status.unschedulable("quota veto",
                                            plugin="ExtraPermit"), 0.0
            return real_permit(state, pod, node)

        fw.run_permit_plugins = failing_permit
        sched.run_until_idle()
        assert all(not p.spec.node_name for p in hub.list_pods()), \
            "a permit-stage failure must place NO member"
        assert sched.cache.assumed_pod_count() == 0
        assert sched._gang.stats["rollbacks"] >= 1
        assert sched._gang.stats["device_admitted"] == 0
        fw.run_permit_plugins = real_permit
        clock.tick(301.0)
        sched.queue.flush_backoff_completed()
        sched.queue.flush_unschedulable_timeout()
        sched.run_until_idle()
        assert sum(1 for p in hub.list_pods() if p.spec.node_name) == 4
    finally:
        sched.close()


def test_chunk_fault_never_redispatches_committed_units():
    """>GANG_PACK_BUCKET units with a fault in the SECOND chunk: chunk
    1's committed gangs stay committed (exactly once), only uncommitted
    members degrade to the Permit path (review finding)."""
    from kubernetes_tpu.ops import gang as G

    hub, clock = Hub(), Clock()
    sched = _sched(hub, clock, nodes=10, cpu="4", batch=256)
    n_units = sched.GANG_PACK_BUCKET + 2
    real = G.pack_gangs_jit
    calls = []
    try:
        for g in range(n_units):
            hub.create_pod_group(group(f"ch-{g}", 2))
        for g in range(n_units):
            for m in range(2):
                hub.create_pod(gang_pod(f"ch-{g}-m{m}", f"ch-{g}",
                                        cpu="100m"))

        def second_chunk_boom(*a, **kw):
            calls.append(1)
            if len(calls) == 2:
                raise RuntimeError("chunk 2 xla fault")
            return real(*a, **kw)

        G.pack_gangs_jit = second_chunk_boom
        sched.run_until_idle()
        bound = {}
        for p in hub.list_pods():
            if p.spec.node_name:
                g = p.metadata.labels[LABEL_POD_GROUP]
                bound[g] = bound.get(g, 0) + 1
        # every gang landed exactly once — chunk 1 via the device path,
        # the faulted tail via the Permit fallback
        assert all(n == 2 for n in bound.values()), bound
        assert len(bound) == n_units
        assert sched.cache.assumed_pod_count() == 0
        assert sched.stats["gang_fallbacks"] >= 2
    finally:
        G.pack_gangs_jit = real
        sched.close()


def test_infeasible_for_all_but_quorum_feasible_falls_back():
    """min_member=2 with 4 members present and capacity for only 2: the
    packer cannot place all 4, but the Permit path admits the quorum
    subset — the unit must FALL BACK, not park (review finding)."""
    hub, clock = Hub(), Clock()
    sched = _sched(hub, clock, nodes=2, cpu="1")    # capacity: 2 x 900m
    try:
        hub.create_pod_group(group("sub", 2, timeout=8.0))
        for i in range(4):
            hub.create_pod(gang_pod(f"s-{i}", "sub", cpu="900m"))
        sched.run_until_idle()
        _settle(sched, clock, waves=2)
        n_bound = sum(1 for p in hub.list_pods() if p.spec.node_name)
        assert n_bound == 2, \
            f"the quorum subset must schedule via the fallback ({n_bound})"
        assert sched.cache.assumed_pod_count() == 0
    finally:
        sched.close()


def test_ff_does_not_credit_idle_tenant():
    """The virtual-clock fast-forward must not bank deficit for an
    idle (fully quota-blocked) tenant (review finding)."""
    from tests.test_gang import FakePQ, tenant_pod
    from tests.test_gang import group as tgroup

    jq = JobQueue({"blocked": {"quota": {"pods": "1"}},
                   "gangs": {"weight": 1.0}})
    jq.add(tenant_pod("b-keep", "blocked"))
    pq = FakePQ()
    jq.release(pq, budget=8)                 # blocked uses its quota
    for i in range(6):
        jq.add(tenant_pod(f"b-{i}", "blocked"))   # quota-blocked backlog
    jq.set_group(tgroup("g8", 8, queue="gangs"))
    for i in range(8):
        jq.add(tenant_pod(f"g-{i}", "gangs", gang="g8"))
    for _ in range(6):
        jq.release(pq, budget=8)             # ff fires for the gang
    assert jq._tenants["blocked"].deficit == 0.0, \
        "fast-forward must not credit an idle tenant"
    # and the gang did release via the fast-forward
    assert sum(1 for p in pq.pods
               if p.metadata.name.startswith("g-")) == 8


def test_big_gang_overdraw_survives_debt_repayment():
    """Repayment only draws from POSITIVE deficit: a big gang's negative
    post-release overdraw must persist (the fairness penalty), not be
    forgiven into inflated backfill debt (review finding)."""
    from tests.test_gang import FakePQ, tenant_pod
    from tests.test_gang import group as tgroup

    jq = JobQueue({"a": {"weight": 1.0}, "b": {"weight": 1.0}})
    jq.set_group(tgroup("g20", 20, queue="a"))
    for i in range(20):
        jq.add(tenant_pod(f"g-{i}", "a", gang="g20"))
    for i in range(3):
        jq.add(tenant_pod(f"s-{i}", "a"))
        jq.add(tenant_pod(f"b-{i}", "b"))
    pq = FakePQ()
    for _ in range(8):
        jq.release(pq, budget=32)
        if any(p.metadata.name.startswith("g-") for p in pq.pods):
            break
    t = jq._tenants["a"]
    assert sum(1 for p in pq.pods
               if p.metadata.name.startswith("g-")) == 20
    # without the positive-deficit clamp, "repaying" from the gang's
    # negative overdraw inflated the debt past the one-gang cap (and
    # forgave the overdraw): debt must stay within [0, gang cost]
    assert 0.0 <= t.backfill_debt <= 20.0, t.backfill_debt


def test_backfill_debt_is_bounded_and_repaid():
    from tests.test_gang import FakePQ, tenant_pod
    from tests.test_gang import group as tgroup

    jq = JobQueue({"a": {"weight": 1.0}, "b": {"weight": 1.0}})
    jq.set_group(tgroup("g6", 6, queue="a"))
    for i in range(6):
        jq.add(tenant_pod(f"g-{i}", "a", gang="g6"))
    for i in range(20):
        jq.add(tenant_pod(f"s-{i}", "a"))
        jq.add(tenant_pod(f"b-{i}", "b"))
    pq = FakePQ()
    jq.release(pq, budget=4)
    t = jq._tenants["a"]
    # debt never exceeds one blocked-gang's cost
    assert 0.0 < t.backfill_debt <= 6.0
    for _ in range(20):
        jq.release(pq, budget=8)
    # gang released and the debt has been repaid from its surplus
    assert sum(1 for p in pq.pods
               if p.metadata.name.startswith("g-")) == 6
    assert t.backfill_debt == 0.0
