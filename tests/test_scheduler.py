"""End-to-end: Scheduler + in-process Hub (the rung-2 integration tests of
SURVEY.md §4 — real loop, real queue/cache/mirror, fake API hub; asserts on
bindings and conditions exactly like test/integration/scheduler)."""

import numpy as np

from kubernetes_tpu.api.objects import (
    Affinity,
    Container,
    LABEL_HOSTNAME,
    LABEL_ZONE,
    LabelSelector,
    Node,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodAffinityTerm,
    PodAntiAffinity,
    PodSchedulingGate,
    PodSpec,
    ResourceRequirements,
    Taint,
    TopologySpreadConstraint,
)
from kubernetes_tpu.config.types import default_config
from kubernetes_tpu.hub import Hub
from kubernetes_tpu.ops.features import Capacities
from kubernetes_tpu.scheduler import Scheduler


class Clock:
    def __init__(self):
        self.t = 1000.0

    def now(self):
        return self.t

    def tick(self, dt):
        self.t += dt


def mknode(i, zone="z1", cpu="16", taints=None):
    name = f"node-{i}"
    return Node(metadata=ObjectMeta(name=name, labels={
        LABEL_HOSTNAME: name, LABEL_ZONE: zone}),
        spec=NodeSpec(taints=taints or []),
        status=NodeStatus(allocatable={"cpu": cpu, "memory": "32Gi",
                                       "pods": "110"}))


def mkpod(name, cpu="500m", labels=None, affinity=None, tsc=None, gates=None):
    return Pod(metadata=ObjectMeta(name=name, labels=labels or {}),
               spec=PodSpec(
                   containers=[Container(name="c",
                                         resources=ResourceRequirements(
                                             requests={"cpu": cpu,
                                                       "memory": "256Mi"}))],
                   affinity=affinity,
                   topology_spread_constraints=tsc or [],
                   scheduling_gates=gates or []))


def mksched(hub, clock=None, batch=16):
    cfg = default_config()
    cfg.batch_size = batch
    clock = clock or Clock()
    return Scheduler(hub, cfg, caps=Capacities(nodes=16, pods=64),
                     now=clock.now), clock


def bound_node(hub, pod):
    return hub.get_pod(pod.metadata.uid).spec.node_name


def test_end_to_end_basic():
    hub = Hub()
    sched, _ = mksched(hub)
    for i in range(4):
        hub.create_node(mknode(i))
    pods = [mkpod(f"p{i}") for i in range(10)]
    for p in pods:
        hub.create_pod(p)
    sched.run_until_idle()
    assert sched.stats["scheduled"] == 10
    nodes = {bound_node(hub, p) for p in pods}
    assert all(n for n in nodes)
    # cache confirmed all bindings (no assumed leftovers)
    assert sched.cache.assumed_pod_count() == 0
    assert sched.cache.pod_count() == 10


def test_unschedulable_then_node_add_requeues():
    hub = Hub()
    sched, clock = mksched(hub)
    hub.create_node(mknode(0, cpu="1"))
    big = mkpod("big", cpu="8")
    hub.create_pod(big)
    sched.run_until_idle()
    assert sched.stats["unschedulable"] == 1
    assert bound_node(hub, big) == ""
    cond = hub.get_pod(big.metadata.uid).status.conditions[0]
    assert cond.reason == "Unschedulable"
    assert "NodeResourcesFit" in cond.message
    # a big node appears: the registered NodeResourcesFit event requeues
    hub.create_node(mknode(1, cpu="16"))
    clock.tick(2.0)  # clear backoff
    sched.queue.flush_backoff_completed()
    sched.run_until_idle()
    assert bound_node(hub, big) == "node-1"


def test_tainted_cluster_toleration():
    hub = Hub()
    sched, _ = mksched(hub)
    hub.create_node(mknode(0, taints=[Taint("dedicated", "infra",
                                            "NoSchedule")]))
    hub.create_node(mknode(1))
    p = mkpod("p")
    hub.create_pod(p)
    sched.run_until_idle()
    assert bound_node(hub, p) == "node-1"


def test_zone_anti_affinity_e2e():
    hub = Hub()
    sched, _ = mksched(hub)
    hub.create_node(mknode(0, zone="east"))
    hub.create_node(mknode(1, zone="east"))
    hub.create_node(mknode(2, zone="west"))
    anti = Affinity(pod_anti_affinity=PodAntiAffinity(required=[
        PodAffinityTerm(topology_key=LABEL_ZONE,
                        label_selector=LabelSelector(
                            match_labels={"app": "web"}))]))
    pods = [mkpod(f"w{i}", labels={"app": "web"}, affinity=anti)
            for i in range(3)]
    for p in pods:
        hub.create_pod(p)
    sched.run_until_idle()
    zones = {"node-0": "east", "node-1": "east", "node-2": "west"}
    placed = [bound_node(hub, p) for p in pods]
    ok = [n for n in placed if n]
    assert len(ok) == 2, "two zones -> only two such pods can run"
    assert {zones[n] for n in ok} == {"east", "west"}
    assert sched.stats["unschedulable"] >= 1


def test_spread_e2e():
    hub = Hub()
    sched, _ = mksched(hub)
    for i in range(3):
        hub.create_node(mknode(i))
    tsc = [TopologySpreadConstraint(
        max_skew=1, topology_key=LABEL_HOSTNAME,
        when_unsatisfiable="DoNotSchedule",
        label_selector=LabelSelector(match_labels={"app": "s"}))]
    pods = [mkpod(f"s{i}", labels={"app": "s"}, tsc=tsc) for i in range(3)]
    for p in pods:
        hub.create_pod(p)
    sched.run_until_idle()
    assert sorted(bound_node(hub, p) for p in pods) == [
        "node-0", "node-1", "node-2"]


def test_gated_pod_waits_for_gate_removal():
    hub = Hub()
    sched, _ = mksched(hub)
    hub.create_node(mknode(0))
    gated = mkpod("g", gates=[PodSchedulingGate("corp/hold")])
    hub.create_pod(gated)
    sched.run_until_idle()
    assert bound_node(hub, gated) == ""
    assert sched.queue.pending_counts()["gated"] == 1
    # remove the gate via pod update
    new = hub.get_pod(gated.metadata.uid).clone()
    new.spec.scheduling_gates = []
    hub.update_pod(new)
    sched.run_until_idle()
    assert bound_node(hub, gated) == "node-0"


def test_capacity_rebucket_grows_nodes():
    hub = Hub()
    sched, _ = mksched(hub)
    for i in range(20):  # exceeds the 16-node bucket
        hub.create_node(mknode(i))
    pods = [mkpod(f"p{i}") for i in range(30)]
    for p in pods:
        hub.create_pod(p)
    sched.run_until_idle()
    assert sched.stats["scheduled"] == 30
    assert sched.caps.nodes >= 20


def test_node_deleted_while_pods_pending():
    hub = Hub()
    sched, clock = mksched(hub)
    n = mknode(0)
    hub.create_node(n)
    p = mkpod("p")
    hub.create_pod(p)
    sched.run_until_idle()
    assert bound_node(hub, p) == "node-0"
    # delete the node; a new pod must go unschedulable
    hub.delete_node(n.metadata.uid)
    p2 = mkpod("p2")
    hub.create_pod(p2)
    sched.run_until_idle()
    assert bound_node(hub, p2) == ""
    assert sched.stats["unschedulable"] >= 1


# suite-tier discipline (tests/test_markers.py): area marker
import pytest  # noqa: E402
pytestmark = pytest.mark.core
