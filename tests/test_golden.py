"""Golden parity tables ported from the reference's plugin unit tests
(SURVEY §4 rung 1): case data re-expressed from
- noderesources/fit_test.go TestEnoughRequests (node 10m cpu / 20Mi mem /
  32 pods / 5 example.com/aaa),
- podtopologyspread/filtering_test.go TestSingleConstraint /
  TestMultipleConstraints (node-a/b in zone1, node-x/y in zone2),
- interpodaffinity/filtering_test.go (zone/hostname terms, symmetry,
  first-pod-of-a-group rule).

Each case runs through the REAL device pipeline: per-node feasibility via
ops.preempt.preempt_feasible (the full filter set for one pod over all
nodes) and plugin attribution via a 1-pod launch's reject_counts.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from kubernetes_tpu.api.objects import (
    Affinity,
    Container,
    LABEL_HOSTNAME,
    LabelSelector,
    LabelSelectorRequirement,
    Node,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodAffinity,
    PodAffinityTerm,
    PodAntiAffinity,
    PodSpec,
    ResourceRequirements,
    TopologySpreadConstraint,
)
from kubernetes_tpu.backend.cache import Cache
from kubernetes_tpu.backend.mirror import Mirror
from kubernetes_tpu.backend.snapshot import Snapshot
from kubernetes_tpu.models.pipeline import (
    FILTER_PLUGINS,
    default_weights,
    launch_batch,
)
from kubernetes_tpu.ops.features import Capacities
from kubernetes_tpu.ops.preempt import preempt_feasible_jit

CAPS = Capacities(nodes=16, pods=64)
WEIGHTS = default_weights()


def _mknode(name, labels=None, cpu="100", mem="100Gi", pods="110",
            ext=None):
    alloc = {"cpu": cpu, "memory": mem, "pods": pods}
    alloc.update(ext or {})
    return Node(metadata=ObjectMeta(name=name, labels=labels or {}),
                spec=NodeSpec(), status=NodeStatus(allocatable=alloc))


def _mkpod(name, labels=None, ns="default", req=None, init=None,
           affinity=None, tsc=None, node=""):
    containers = [Container(name="c", resources=ResourceRequirements(
        requests=req or {}))]
    inits = [Container(name=f"i{j}", resources=ResourceRequirements(
        requests=r)) for j, r in enumerate(init or [])]
    return Pod(metadata=ObjectMeta(name=name, namespace=ns,
                                   labels=labels or {}),
               spec=PodSpec(containers=containers, init_containers=inits,
                            affinity=affinity,
                            topology_spread_constraints=tsc or [],
                            node_name=node))


def _build(nodes, existing):
    cache = Cache()
    for n in nodes:
        cache.add_node(n)
    for p in existing:
        cache.add_pod(p)
    snap = Snapshot()
    cache.update_snapshot(snap)
    mirror = Mirror(caps=CAPS)
    mirror.sync(snap)
    return mirror


def feasible_set(pod, nodes, existing=()):
    """Which nodes pass the FULL filter set for ``pod``."""
    mirror = _build(nodes, list(existing))
    pblobs = mirror.pack_batch_blobs([pod], 1)
    tval = jnp.asarray(np.ones((CAPS.pods,), bool))
    free = jnp.asarray(mirror.free_matrix())
    enable = (mirror.table_has_topology()
              or mirror.batch_has_topology([pod]))
    feas = np.asarray(preempt_feasible_jit(
        mirror.to_blobs(), pblobs, mirror.well_known(), CAPS, tval, free,
        enable, mirror.domain_bucket()))
    return {n.metadata.name for n in nodes
            if feas[mirror.row_of(n.metadata.name)]}


def reject_plugins(pod, nodes, existing=()):
    """(scheduled_node | None, {plugin names with rejects})."""
    mirror = _build(nodes, list(existing))
    spec = mirror.prepare_launch([pod], 8)
    out = launch_batch(spec, mirror.well_known(), WEIGHTS, CAPS)
    row = int(np.asarray(out.node_row)[0])
    rejects = np.asarray(out.reject_counts)[0]
    plugins = {FILTER_PLUGINS[i] for i, c in enumerate(rejects.tolist())
               if c > 0}
    return (mirror.name_of_row(row) if row >= 0 else None), plugins


# ---------------------------------------------------------------- fit ---
# TestEnoughRequests: ONE node, allocatable cpu=10m mem=20Mi pods=32
# example.com/aaa=5; `used` = requests of one existing bound pod.
# want: None = fits, else the rejecting plugin.

def R(cpu=0, mem=0, ext=0, storage=0):
    req = {}
    if cpu:
        req["cpu"] = f"{cpu}m"
    if mem:
        req["memory"] = f"{mem}Mi"
    if ext:
        req["example.com/aaa"] = str(ext)
    if storage:
        req["ephemeral-storage"] = f"{storage}Mi"
    return req


FIT_CASES = [
    # (name, request, init requests, existing usage, want rejecting plugin)
    ("no resources requested always fits", R(), None, R(cpu=10, mem=20),
     None),
    ("too many resources fails", R(cpu=1, mem=1), None, R(cpu=10, mem=20),
     "NodeResourcesFit"),
    ("too many resources fails due to init container cpu",
     R(cpu=1, mem=1), [R(cpu=3, mem=1)], R(cpu=8, mem=19),
     "NodeResourcesFit"),
    ("too many resources fails due to highest init container cpu",
     R(cpu=1, mem=1), [R(cpu=3, mem=1), R(cpu=2, mem=1)], R(cpu=8, mem=19),
     "NodeResourcesFit"),
    ("too many resources fails due to init container memory",
     R(cpu=1, mem=1), [R(cpu=1, mem=3)], R(cpu=9, mem=19),
     "NodeResourcesFit"),
    ("too many resources fails due to highest init container memory",
     R(cpu=1, mem=1), [R(cpu=1, mem=3), R(cpu=1, mem=2)], R(cpu=9, mem=19),
     "NodeResourcesFit"),
    ("init container fits because it's the max, not sum",
     R(cpu=1, mem=1), [R(cpu=1, mem=1)], R(cpu=9, mem=19), None),
    ("multiple init containers fit (max, not sum)",
     R(cpu=1, mem=1), [R(cpu=1, mem=1), R(cpu=1, mem=1)], R(cpu=9, mem=19),
     None),
    ("both resources fit", R(cpu=1, mem=1), None, R(cpu=5, mem=5), None),
    ("one resource memory fits", R(cpu=2, mem=1), None, R(cpu=9, mem=5),
     "NodeResourcesFit"),
    ("one resource cpu fits", R(cpu=1, mem=2), None, R(cpu=5, mem=19),
     "NodeResourcesFit"),
    ("equal edge case", R(cpu=5, mem=1), None, R(cpu=5, mem=19), None),
    ("equal edge case for init container", R(cpu=4, mem=1),
     [R(cpu=5, mem=1)], R(cpu=5, mem=19), None),
    ("extended resource fits", R(ext=1), None, R(), None),
    ("extended resource fits for init container", R(), [R(ext=1)], R(),
     None),
    ("extended resource capacity enforced", R(ext=10), None, R(),
     "NodeResourcesFit"),
    ("extended resource capacity enforced for init container",
     R(), [R(ext=10)], R(), "NodeResourcesFit"),
    ("extended resource allocatable enforced", R(ext=1), None, R(ext=5),
     "NodeResourcesFit"),
    ("extended resource allocatable enforced for init container",
     R(), [R(ext=1)], R(ext=5), "NodeResourcesFit"),
    ("extended resource allocatable enforced vs existing usage",
     R(ext=4), None, R(ext=2), "NodeResourcesFit"),
    ("extended resource fits alongside existing usage",
     R(ext=3), None, R(ext=2), None),
    ("extended resource allocatable admits multiple init containers",
     R(), [R(ext=3), R(ext=2)], R(ext=2), None),
    ("extended resource allocatable enforced for multiple init containers",
     R(), [R(ext=3), R(ext=4)], R(ext=2), "NodeResourcesFit"),
    ("ephemeral-storage fits", R(storage=10), None, R(), None),
    ("ephemeral-storage capacity enforced", R(storage=25000), None, R(),
     "NodeResourcesFit"),
    ("cpu fits exactly at the limit", R(cpu=10), None, R(), None),
    ("memory fits exactly at the limit", R(mem=20), None, R(), None),
    ("cpu over by one", R(cpu=11), None, R(), "NodeResourcesFit"),
    ("memory over by one", R(mem=21), None, R(), "NodeResourcesFit"),
    ("usage plus request over cpu", R(cpu=6), None, R(cpu=5), "NodeResourcesFit"),
    ("usage plus request at cpu limit", R(cpu=5), None, R(cpu=5), None),
]


@pytest.mark.parametrize("name,req,init,used,want",
                         FIT_CASES, ids=[c[0] for c in FIT_CASES])
def test_fit_golden(name, req, init, used, want):
    node = _mknode("node-0", cpu="10m", mem="20Mi", pods="32",
                   ext={"example.com/aaa": "5",
                        "ephemeral-storage": "20000Mi"})
    existing = []
    if any(used.values()):
        existing.append(_mkpod("used", req=used, node="node-0"))
    pod = _mkpod("p", req=req, init=init)
    scheduled, plugins = reject_plugins(pod, [node], existing)
    if want is None:
        assert scheduled == "node-0", f"{name}: expected fit, got {plugins}"
    else:
        assert scheduled is None, f"{name}: expected rejection"
        assert want in plugins, f"{name}: got {plugins}"


# ------------------------------------------------------------- spread ---
# TestSingleConstraint grid: node-a/node-b in zone1, node-x/node-y in
# zone2 (all also labeled with their own hostname); existing pods by node.

ZONE = "topology.kubernetes.io/zone"


def _grid(node_b_zone_key=ZONE):
    return [
        _mknode("node-a", {ZONE: "zone1", LABEL_HOSTNAME: "node-a"}),
        _mknode("node-b", {node_b_zone_key: "zone1",
                           LABEL_HOSTNAME: "node-b"}),
        _mknode("node-x", {ZONE: "zone2", LABEL_HOSTNAME: "node-x"}),
        _mknode("node-y", {ZONE: "zone2", LABEL_HOSTNAME: "node-y"}),
    ]


def _foo_pods(spec):
    """spec: {node: count} of existing foo-labeled pods."""
    out = []
    for node, cnt in spec.items():
        for i in range(cnt):
            out.append(_mkpod(f"e-{node}-{i}", labels={"foo": ""},
                              node=node))
    return out


def _sc(skew, key, sel="foo", min_domains=None):
    selector = (LabelSelector(match_expressions=[LabelSelectorRequirement(
        key=sel, operator="Exists")]) if sel else None)
    return TopologySpreadConstraint(
        max_skew=skew, topology_key=key, when_unsatisfiable="DoNotSchedule",
        label_selector=selector, min_domains=min_domains)


SPREAD_CASES = [
    # (name, constraints, existing {node: n}, want feasible set)
    ("no existing pods", [_sc(1, ZONE)], {},
     {"node-a", "node-b", "node-x", "node-y"}),
    ("no existing pods, incoming pod doesn't match itself",
     [_sc(1, ZONE, sel="bar")], {},
     {"node-a", "node-b", "node-x", "node-y"}),
    ("existing pods do not match null selector",
     [_sc(1, ZONE, sel=None)], {"node-x": 1, "node-y": 1},
     {"node-a", "node-b", "node-x", "node-y"}),
    ("pods spread across zones as 3/3, all nodes fit",
     [_sc(1, ZONE)], {"node-a": 2, "node-b": 1, "node-y": 3},
     {"node-a", "node-b", "node-x", "node-y"}),
    ("pods spread across zones as 2/4, only zone1 fits",
     [_sc(1, ZONE)], {"node-a": 1, "node-b": 1, "node-x": 2, "node-y": 2},
     {"node-a", "node-b"}),
    ("pod cannot be scheduled as all nodes don't have label 'rack'",
     [_sc(1, "rack")], {}, set()),
    ("pods spread across nodes as 2/1/0/3, only node-x fits",
     [_sc(1, "kubernetes.io/hostname")],
     {"node-a": 2, "node-b": 1, "node-y": 3}, {"node-x"}),
    ("pods spread across nodes as 2/1/0/3, maxSkew is 2, node-b and node-x fit",
     [_sc(2, "kubernetes.io/hostname")],
     {"node-a": 2, "node-b": 1, "node-y": 3}, {"node-b", "node-x"}),
    ("pods spread across nodes as 2/1/0/3 and 3/3 on zones, only node-x fits both",
     [_sc(1, ZONE), _sc(1, "kubernetes.io/hostname")],
     {"node-a": 2, "node-b": 1, "node-y": 3}, {"node-x"}),
    ("zone skew 0/4 with maxSkew 1: only empty zone fits",
     [_sc(1, ZONE)], {"node-x": 2, "node-y": 2}, {"node-a", "node-b"}),
    ("maxSkew 4 still blocks the full side of a 0/4 split (4+1-0 > 4)",
     [_sc(4, ZONE)], {"node-x": 2, "node-y": 2},
     {"node-a", "node-b"}),
    ("maxSkew 5 tolerates a 0/4 split everywhere",
     [_sc(5, ZONE)], {"node-x": 2, "node-y": 2},
     {"node-a", "node-b", "node-x", "node-y"}),
    ("minDomains unsatisfied: global min treated as 0, 1/0 zone blocked",
     [_sc(1, ZONE, min_domains=3)], {"node-a": 1},
     {"node-x", "node-y"}),
]


@pytest.mark.parametrize("name,constraints,existing,want",
                         SPREAD_CASES, ids=[c[0] for c in SPREAD_CASES])
def test_spread_golden(name, constraints, existing, want):
    pod = _mkpod("p", labels={"foo": ""}, tsc=constraints)
    got = feasible_set(pod, _grid(), _foo_pods(existing))
    assert got == want, f"{name}: got {got}"


def test_spread_golden_missing_zone_label():
    """'pods spread across zones as 1/2 due to absence of label zone on
    node-b': node-b (no zone label) is filtered out; zone1 count=1 vs
    zone2 count=2 -> only zone1's labeled node fits."""
    nodes = _grid(node_b_zone_key="zon")
    existing = _foo_pods({"node-a": 1, "node-b": 1, "node-x": 1,
                          "node-y": 1})
    pod = _mkpod("p", labels={"foo": ""}, tsc=[_sc(1, ZONE)])
    got = feasible_set(pod, nodes, existing)
    assert got == {"node-a"}


def test_spread_golden_different_namespace_not_counted():
    nodes = _grid()
    existing = (_foo_pods({"node-x": 1, "node-y": 1})
                + [_mkpod("o1", labels={"foo": ""}, ns="ns1",
                          node="node-a"),
                   _mkpod("o2", labels={"foo": ""}, ns="ns2",
                          node="node-a")])
    pod = _mkpod("p", labels={"foo": ""}, tsc=[_sc(1, ZONE)])
    got = feasible_set(pod, nodes, existing)
    assert got == {"node-a", "node-b"}, \
        "zone1 has 0 same-ns matches vs zone2's 2"


# --------------------------------------------------------- interpod -----

def _aff(zone_sel=None, host_sel=None, anti_zone=None, anti_host=None,
         ns=None):
    def term(key, sel, namespaces):
        return PodAffinityTerm(
            topology_key=key,
            label_selector=sel,
            namespaces=namespaces or [])

    aff_terms = []
    anti_terms = []
    if zone_sel is not None:
        aff_terms.append(term(ZONE, zone_sel, ns))
    if host_sel is not None:
        aff_terms.append(term(LABEL_HOSTNAME, host_sel, ns))
    if anti_zone is not None:
        anti_terms.append(term(ZONE, anti_zone, ns))
    if anti_host is not None:
        anti_terms.append(term(LABEL_HOSTNAME, anti_host, ns))
    return Affinity(
        pod_affinity=PodAffinity(required=aff_terms) if aff_terms else None,
        pod_anti_affinity=(PodAntiAffinity(required=anti_terms)
                           if anti_terms else None))


def SEL(**match):
    return LabelSelector(match_labels=match)


def SELX(key, op, *values):
    return LabelSelector(match_expressions=[LabelSelectorRequirement(
        key=key, operator=op, values=list(values))])


AFFINITY_CASES = [
    # (name, pod labels, affinity, existing [(node, labels)], want set)
    ("affinity In matches existing pod in same zone",
     {"app": "web"}, _aff(zone_sel=SELX("service", "In", "securityscan")),
     [("node-a", {"service": "securityscan"})],
     {"node-a", "node-b"}),           # whole zone1 satisfies the term
    ("affinity mismatch leaves no feasible node",
     {"app": "web"}, _aff(zone_sel=SELX("service", "In", "db")),
     [("node-a", {"service": "securityscan"})],
     set()),
    ("affinity NotIn matches pods lacking the value",
     {}, _aff(zone_sel=SELX("service", "NotIn", "db")),
     [("node-x", {"service": "securityscan"})],
     {"node-x", "node-y"}),
    ("affinity Exists operator",
     {}, _aff(zone_sel=SELX("service", "Exists")),
     [("node-y", {"service": "anything"})],
     {"node-x", "node-y"}),
    ("affinity DoesNotExist: no match anywhere, but the label-less pod "
     "matches its own selector (first-pod-of-group rule)",
     {}, _aff(zone_sel=SELX("service", "DoesNotExist")),
     [("node-a", {"service": "x"})],
     {"node-a", "node-b", "node-x", "node-y"}),
    ("affinity DoesNotExist satisfied by an unlabeled existing pod",
     {"service": "x"}, _aff(zone_sel=SELX("service", "DoesNotExist")),
     [("node-x", {"other": "y"})],
     {"node-x", "node-y"}),
    ("hostname-scoped affinity pins to the pod's node",
     {}, _aff(host_sel=SEL(app="db")),
     [("node-x", {"app": "db"})],
     {"node-x"}),
    ("first pod of a group may go anywhere (self-match rule)",
     {"app": "db"}, _aff(host_sel=SEL(app="db")), [],
     {"node-a", "node-b", "node-x", "node-y"}),
    ("first-pod rule needs the pod to match its own selector",
     {"app": "web"}, _aff(host_sel=SEL(app="db")), [],
     set()),
    ("anti-affinity forbids the matching pod's zone",
     {}, _aff(anti_zone=SEL(app="web")),
     [("node-a", {"app": "web"})],
     {"node-x", "node-y"}),
    ("anti-affinity hostname only forbids the node itself",
     {}, _aff(anti_host=SEL(app="web")),
     [("node-a", {"app": "web"})],
     {"node-b", "node-x", "node-y"}),
    ("anti-affinity with no matching pods allows everything",
     {}, _aff(anti_zone=SEL(app="web")), [],
     {"node-a", "node-b", "node-x", "node-y"}),
    ("incoming pod matching its own anti selector still placeable",
     {"app": "web"}, _aff(anti_zone=SEL(app="web")), [],
     {"node-a", "node-b", "node-x", "node-y"}),
    ("affinity AND anti-affinity together",
     {}, _aff(zone_sel=SEL(app="db"), anti_host=SEL(app="db")),
     [("node-a", {"app": "db"})],
     {"node-b"}),                      # same zone, different host
    ("multiple affinity terms must all be satisfied",
     {}, _aff(zone_sel=SEL(app="db"), host_sel=SEL(app="db")),
     [("node-a", {"app": "db"})],
     {"node-a"}),
]


@pytest.mark.parametrize("name,labels,aff,existing,want",
                         AFFINITY_CASES, ids=[c[0] for c in AFFINITY_CASES])
def test_interpod_golden(name, labels, aff, existing, want):
    nodes = _grid()
    pods = [_mkpod(f"e{i}", labels=lab, node=node)
            for i, (node, lab) in enumerate(existing)]
    pod = _mkpod("p", labels=labels, affinity=aff)
    got = feasible_set(pod, nodes, pods)
    assert got == want, f"{name}: got {got}"


def test_existing_pod_anti_affinity_symmetry():
    """satisfyExistingPodsAntiAffinity: a RUNNING pod's required
    anti-affinity forbids incoming pods matching it (filtering_test.go's
    symmetry cases)."""
    nodes = _grid()
    blocker = _mkpod("blocker", labels={"team": "x"}, node="node-a",
                     affinity=_aff(anti_zone=SEL(app="web")))
    incoming = _mkpod("p", labels={"app": "web"})
    got = feasible_set(incoming, nodes, [blocker])
    assert got == {"node-x", "node-y"}, \
        "the blocker's zone is forbidden for matching incomers"
    unrelated = _mkpod("q", labels={"app": "batch"})
    got2 = feasible_set(unrelated, nodes, [blocker])
    assert got2 == {"node-a", "node-b", "node-x", "node-y"}


def test_affinity_namespaces_respected():
    nodes = _grid()
    other_ns = _mkpod("e", labels={"app": "db"}, ns="other", node="node-a")
    pod = _mkpod("p", affinity=_aff(zone_sel=SEL(app="db")))
    assert feasible_set(pod, nodes, [other_ns]) == set(), \
        "matches in another namespace don't count by default"
    pod2 = _mkpod("p2", affinity=_aff(zone_sel=SEL(app="db"),
                                      ns=["other"]))
    assert feasible_set(pod2, nodes, [other_ns]) == {"node-a", "node-b"}


# suite-tier discipline (tests/test_markers.py): area marker
pytestmark = pytest.mark.core
