"""CI/tooling satellite (ISSUE 10): marker discipline cannot rot.

Two static checks over the test tree, no imports (importing 40+ test
modules to introspect them would drag jax into a lint):

* every test module carries at least one marker REGISTERED in
  pyproject.toml (module-level ``pytestmark`` or a mark decorator) —
  so tier-1 vs slow vs area membership is an explicit, greppable
  property of each module as the suite grows;
* every marker USED anywhere in tests/ is registered — a typo'd
  ``slwo`` would otherwise silently run in tier-1 instead of being
  excluded (``--strict-markers`` in pyproject enforces this at collect
  time too; this test makes the failure message name the file).
"""

import re
from pathlib import Path

import pytest

pytestmark = pytest.mark.core

TESTS_DIR = Path(__file__).parent
PYPROJECT = TESTS_DIR.parent / "pyproject.toml"

# pytest.mark.<name> and pytest.mark.<name>(...) both count; so does
# a pytestmark list assignment
_MARK_RE = re.compile(r"pytest\.mark\.([A-Za-z_][A-Za-z0-9_]*)")

# built-in marks that need no registration
_BUILTIN = {"skip", "skipif", "xfail", "parametrize", "usefixtures",
            "filterwarnings", "timeout"}


def registered_markers() -> set[str]:
    text = PYPROJECT.read_text()
    m = re.search(r"markers\s*=\s*\[(.*?)\]", text, re.DOTALL)
    assert m, "pyproject.toml lost its [tool.pytest.ini_options] markers"
    names = re.findall(r'"([A-Za-z_][A-Za-z0-9_]*)\s*:', m.group(1))
    assert names, "no registered markers parsed from pyproject.toml"
    return set(names)


def module_marks() -> dict[str, set[str]]:
    out = {}
    for path in sorted(TESTS_DIR.glob("test_*.py")):
        marks = set(_MARK_RE.findall(path.read_text())) - _BUILTIN
        out[path.name] = marks
    return out


def test_every_test_module_carries_a_registered_marker():
    registered = registered_markers()
    missing = [name for name, marks in module_marks().items()
               if not (marks & registered)]
    assert not missing, (
        f"test modules without any registered marker {sorted(registered)}: "
        f"{missing} — add a module-level `pytestmark = pytest.mark.<area>` "
        "so suite-tier discipline stays explicit")


def test_every_used_marker_is_registered():
    registered = registered_markers()
    rogue = {name: sorted(marks - registered)
             for name, marks in module_marks().items()
             if marks - registered}
    assert not rogue, (
        f"unregistered markers in use (typo'd marks silently run in "
        f"tier-1): {rogue}; register in pyproject.toml or fix the name")
