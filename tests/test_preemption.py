"""Preemption end-to-end: Evaluator + DefaultPreemption PostFilter +
nominator + device victim sweep.

Mirrors the reference's preemption integration tests
(test/integration/scheduler/preemption) against the in-process hub:
high-priority pods evict lower-priority victims, get a NominatedNodeName,
and bind once the victims vacate."""

from kubernetes_tpu.api.objects import (
    Container,
    LABEL_HOSTNAME,
    LABEL_ZONE,
    LabelSelector,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodDisruptionBudget,
    PodSpec,
    ResourceRequirements,
)
from kubernetes_tpu.config.types import default_config
from kubernetes_tpu.hub import Hub
from kubernetes_tpu.ops.features import Capacities
from kubernetes_tpu.scheduler import Scheduler


class Clock:
    def __init__(self):
        self.t = 1000.0

    def now(self):
        return self.t

    def tick(self, dt):
        self.t += dt


def mknode(i, cpu="4"):
    name = f"node-{i}"
    return Node(metadata=ObjectMeta(name=name, labels={
        LABEL_HOSTNAME: name, LABEL_ZONE: "z1"}),
        status=NodeStatus(allocatable={"cpu": cpu, "memory": "32Gi",
                                       "pods": "110"}))


def mkpod(name, cpu="500m", priority=0, labels=None, policy=None):
    spec = PodSpec(
        containers=[Container(name="c", resources=ResourceRequirements(
            requests={"cpu": cpu, "memory": "256Mi"}))],
        priority=priority)
    if policy:
        spec.preemption_policy = policy
    return Pod(metadata=ObjectMeta(name=name, labels=labels or {}), spec=spec)


def mksched(hub, clock=None, batch=16):
    cfg = default_config()
    cfg.batch_size = batch
    clock = clock or Clock()
    return Scheduler(hub, cfg, caps=Capacities(nodes=16, pods=64),
                     now=clock.now), clock


def drain(sched, clock, rounds=6):
    for _ in range(rounds):
        sched.run_until_idle()
        clock.tick(3.0)
        sched.queue.flush_backoff_completed()
    sched.run_until_idle()


def bound_node(hub, pod):
    p = hub.get_pod(pod.metadata.uid)
    return p.spec.node_name if p else None


def test_basic_preemption_evicts_and_binds():
    """Cluster full of low-priority pods; a high-priority pod evicts enough
    victims on one node and binds there."""
    hub = Hub()
    sched, clock = mksched(hub)
    for i in range(2):
        hub.create_node(mknode(i, cpu="2"))
    low = [mkpod(f"low-{i}", cpu="1", priority=0) for i in range(4)]
    for p in low:
        hub.create_pod(p)
    drain(sched, clock)
    assert sched.stats["scheduled"] == 4  # both nodes full

    high = mkpod("high", cpu="1500m", priority=100)
    hub.create_pod(high)
    drain(sched, clock)
    assert bound_node(hub, high) in ("node-0", "node-1")
    assert sched.stats["preemptions"] == 1
    # exactly 2 victims evicted on the chosen node (each frees 1 cpu)
    gone = [p for p in low if hub.get_pod(p.metadata.uid) is None]
    assert len(gone) == 2
    assert {bound_node(hub, p) for p in low if hub.get_pod(p.metadata.uid)} \
        != {None}


def test_no_preemption_of_equal_or_higher_priority():
    hub = Hub()
    sched, clock = mksched(hub)
    hub.create_node(mknode(0, cpu="2"))
    incumbent = mkpod("incumbent", cpu="2", priority=100)
    hub.create_pod(incumbent)
    drain(sched, clock)
    assert bound_node(hub, incumbent) == "node-0"

    challenger = mkpod("challenger", cpu="1", priority=100)
    hub.create_pod(challenger)
    drain(sched, clock)
    assert hub.get_pod(incumbent.metadata.uid) is not None
    assert bound_node(hub, challenger) == ""


def test_preemption_policy_never():
    hub = Hub()
    sched, clock = mksched(hub)
    hub.create_node(mknode(0, cpu="2"))
    low = mkpod("low", cpu="2", priority=0)
    hub.create_pod(low)
    drain(sched, clock)

    never = mkpod("never", cpu="1", priority=100, policy="Never")
    hub.create_pod(never)
    drain(sched, clock)
    assert hub.get_pod(low.metadata.uid) is not None  # not evicted
    assert bound_node(hub, never) == ""


def test_minimal_victims_lowest_priority_first():
    """Victims are the least-important prefix: evicting the single prio-1
    pod suffices; the prio-5 pod survives."""
    hub = Hub()
    sched, clock = mksched(hub)
    hub.create_node(mknode(0, cpu="2"))
    p1 = mkpod("p1", cpu="1", priority=1)
    p5 = mkpod("p5", cpu="1", priority=5)
    hub.create_pod(p1)
    hub.create_pod(p5)
    drain(sched, clock)

    high = mkpod("high", cpu="1", priority=100)
    hub.create_pod(high)
    drain(sched, clock)
    assert bound_node(hub, high) == "node-0"
    assert hub.get_pod(p1.metadata.uid) is None      # evicted
    assert hub.get_pod(p5.metadata.uid) is not None  # reprieved


def test_pdb_violations_steer_candidate_choice():
    """Two viable nodes; victims on node-0 are PDB-protected with no
    disruptions left -> node-1 is preferred (fewest PDB violations)."""
    hub = Hub()
    sched, clock = mksched(hub)
    hub.create_node(mknode(0, cpu="2"))
    hub.create_node(mknode(1, cpu="2"))
    a = mkpod("a", cpu="2", priority=0, labels={"app": "guarded"})
    b = mkpod("b", cpu="2", priority=0, labels={"app": "free"})
    hub.create_pod(a)
    hub.create_pod(b)
    drain(sched, clock)
    node_of_a = bound_node(hub, a)
    hub.create_pdb(PodDisruptionBudget(
        metadata=ObjectMeta(name="pdb"),
        selector=LabelSelector(match_labels={"app": "guarded"}),
        disruptions_allowed=0))

    high = mkpod("high", cpu="1", priority=100)
    hub.create_pod(high)
    drain(sched, clock)
    assert hub.get_pod(a.metadata.uid) is not None   # protected pod survives
    assert hub.get_pod(b.metadata.uid) is None       # unprotected evicted
    assert bound_node(hub, high) is not None
    assert bound_node(hub, high) != node_of_a


def test_nominated_reservation_not_stolen():
    """After preemption the preemptor's NominatedNodeName reserves the
    vacated room: a later lower-priority pod must not steal it."""
    hub = Hub()
    sched, clock = mksched(hub)
    hub.create_node(mknode(0, cpu="2"))
    low = mkpod("low", cpu="2", priority=0)
    hub.create_pod(low)
    drain(sched, clock)

    high = mkpod("high", cpu="2", priority=100)
    hub.create_pod(high)
    # one batch: preempt + nominate, victim deleted, high parked
    sched.run_until_idle()
    nominated = hub.get_pod(high.metadata.uid).status.nominated_node_name
    assert nominated == "node-0"
    # an opportunist shows up before high re-schedules
    opportunist = mkpod("opportunist", cpu="2", priority=0)
    hub.create_pod(opportunist)
    drain(sched, clock)
    assert bound_node(hub, high) == "node-0"
    assert bound_node(hub, opportunist) == ""


def test_preemption_for_anti_affinity_blocked_pod():
    """The pod FITS resource-wise everywhere, but a low-priority victim's
    presence violates the preemptor's required anti-affinity on every node;
    evicting the victim (not freeing resources) is what helps — the
    full-pipeline dry-run finds it, the resource-only sweep could not
    (default_preemption.go:219 removes victims then re-runs ALL filters)."""
    from kubernetes_tpu.api.objects import (
        Affinity,
        PodAffinityTerm,
        PodAntiAffinity,
    )

    hub = Hub()
    sched, clock = mksched(hub)
    hub.create_node(mknode(0, cpu="8"))
    # a low-priority pod labeled app=red sits on the only node
    blocker = mkpod("blocker", cpu="100m", priority=0,
                    labels={"app": "red"})
    hub.create_pod(blocker)
    drain(sched, clock)
    assert bound_node(hub, blocker) == "node-0"

    # high-priority pod with required anti-affinity against app=red:
    # resources are plentiful; only the blocker's eviction helps
    anti = Affinity(pod_anti_affinity=PodAntiAffinity(required=[
        PodAffinityTerm(topology_key=LABEL_HOSTNAME,
                        label_selector=LabelSelector(
                            match_labels={"app": "red"}))]))
    high = mkpod("high", cpu="100m", priority=100)
    high.spec.affinity = anti
    hub.create_pod(high)
    drain(sched, clock)
    assert hub.get_pod(blocker.metadata.uid) is None, "blocker evicted"
    assert bound_node(hub, high) == "node-0"
    assert sched.stats["preemptions"] >= 1


def test_no_useless_eviction_when_anti_affinity_unresolvable():
    """The preemptor's anti-affinity blocker is a HIGHER-priority pod: no
    victim set can help, so nothing must be evicted even though plenty of
    lower-priority victims exist (the exact dry-run discards the node)."""
    from kubernetes_tpu.api.objects import (
        Affinity,
        PodAffinityTerm,
        PodAntiAffinity,
    )

    hub = Hub()
    sched, clock = mksched(hub)
    hub.create_node(mknode(0, cpu="8"))
    blocker = mkpod("blocker", cpu="100m", priority=200,
                    labels={"app": "red"})
    filler = mkpod("filler", cpu="100m", priority=0)
    hub.create_pod(blocker)
    hub.create_pod(filler)
    drain(sched, clock)

    anti = Affinity(pod_anti_affinity=PodAntiAffinity(required=[
        PodAffinityTerm(topology_key=LABEL_HOSTNAME,
                        label_selector=LabelSelector(
                            match_labels={"app": "red"}))]))
    high = mkpod("high", cpu="100m", priority=100)
    high.spec.affinity = anti
    hub.create_pod(high)
    drain(sched, clock)
    assert bound_node(hub, high) == ""
    assert hub.get_pod(filler.metadata.uid) is not None, \
        "no useless eviction of the unrelated filler"
    assert sched.stats.get("preemptions", 0) == 0


def test_pdb_violating_victims_reprieved_first():
    """Two equal candidates for reprieve; the PDB-protected victim must be
    the one KEPT when either alone would satisfy the preemptor."""
    hub = Hub()
    sched, clock = mksched(hub)
    hub.create_node(mknode(0, cpu="2"))
    protected = mkpod("protected", cpu="1", priority=0,
                      labels={"app": "guarded"})
    plain = mkpod("plain", cpu="1", priority=0)
    hub.create_pod(protected)
    hub.create_pod(plain)
    hub.create_pdb(PodDisruptionBudget(
        metadata=ObjectMeta(name="pdb"),
        selector=LabelSelector(match_labels={"app": "guarded"}),
        disruptions_allowed=0))
    drain(sched, clock)
    assert sched.stats["scheduled"] == 2

    high = mkpod("high", cpu="1", priority=100)
    hub.create_pod(high)
    drain(sched, clock)
    assert bound_node(hub, high) == "node-0"
    assert hub.get_pod(protected.metadata.uid) is not None, \
        "PDB-protected victim reprieved"
    assert hub.get_pod(plain.metadata.uid) is None


def test_async_gate_holds_preemptor_until_victims_gone():
    """While the eviction work is queued, the preemptor is gated out of the
    activeQ (DefaultPreemption PreEnqueue); once flush_evictions runs, the
    deletion events requeue and it binds."""
    hub = Hub()
    sched, clock = mksched(hub)
    hub.create_node(mknode(0, cpu="2"))
    low = [mkpod(f"low-{i}", cpu="1", priority=0) for i in range(2)]
    for p in low:
        hub.create_pod(p)
    drain(sched, clock)

    high = mkpod("high", cpu="2", priority=100)
    hub.create_pod(high)
    drain(sched, clock)
    assert bound_node(hub, high) == "node-0"
    assert all(hub.get_pod(p.metadata.uid) is None for p in low)
    assert not sched.preemption.preempting, "gate cleared after evictions"


def test_sweep_never_drops_inactive_resource_constraint():
    """Column-subset sweep regression: victims free ONLY memory (cpu-less
    requests), the preemptor needs more CPU than the node has — eviction
    can never help, so preemption must find no candidate and evict
    nothing (the padding-alias bug silently deleted the CPU constraint
    from the sweep)."""
    hub = Hub()
    hub.create_node(mknode(0, cpu="4"))
    # an UNEVICTABLE cpu hog pins the node's cpu (priority above the
    # preemptor), so cpu stays scarce no matter what gets evicted
    hog = mkpod("cpu-hog", cpu="3500m", priority=100)
    hub.create_pod(hog)
    # low-priority victims request memory only: the freed-column set is
    # {memory, pods}, cpu inactive
    victims = []
    for i in range(3):
        v = Pod(metadata=ObjectMeta(name=f"memhog-{i}"),
                spec=PodSpec(containers=[Container(
                    name="c", resources=ResourceRequirements(
                        requests={"memory": "8Gi"}))], priority=50))
        victims.append(v)
        hub.create_pod(v)
    sched, clock = mksched(hub)
    drain(sched, clock, rounds=2)
    assert all(hub.get_pod(v.metadata.uid).spec.node_name
               for v in victims), "victims must be running"
    # preemptor: 2 CPU (only 500m free; no victim frees cpu) AND 8Gi
    # memory (only ~7.7Gi free; victims DO free memory). Eviction makes
    # the memory half fit but never the cpu half, and cpu is within
    # allocatable so the unresolvable guard does not fire — only the
    # sweep's cpu constraint stands between this pod and a useless
    # eviction at kmin>=1
    pre = Pod(metadata=ObjectMeta(name="cpu-hungry"),
              spec=PodSpec(containers=[Container(
                  name="c", resources=ResourceRequirements(
                      requests={"cpu": "2", "memory": "8Gi"}))],
                  priority=60))
    hub.create_pod(pre)
    drain(sched, clock, rounds=3)
    assert hub.get_pod(pre.metadata.uid).spec.node_name == ""
    assert sched.stats.get("preemptions", 0) == 0, \
        "no nomination may come from a sweep that ignored the cpu column"
    for v in victims:
        assert hub.get_pod(v.metadata.uid) is not None, \
            "no victim may be evicted for an unresolvable preemptor"


# suite-tier discipline (tests/test_markers.py): area marker
import pytest  # noqa: E402
pytestmark = pytest.mark.core
