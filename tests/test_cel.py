"""CEL-subset evaluator (utils/cel.py) vs the reference's DRA selector
expressions (dra/templates/resourceclaim-with-selector.yaml,
deviceclass.yaml; cel-go semantics for the covered subset)."""

import pytest

from kubernetes_tpu.utils.cel import CelDevice, CelError, evaluate


def dev(driver="test-driver.cdi.k8s.io", attributes=None, capacity=None):
    return CelDevice(driver, attributes or {}, capacity or {})


def test_driver_equality():
    d = dev()
    assert evaluate('device.driver == "test-driver.cdi.k8s.io"', d)
    assert not evaluate('device.driver == "other"', d)
    assert evaluate("device.driver != 'other'", d)


def test_bool_attribute():
    d = dev(attributes={"preallocate": True})
    assert evaluate(
        "device.attributes['test-driver.cdi.k8s.io'].preallocate", d)
    d2 = dev(attributes={"preallocate": False})
    assert not evaluate(
        "device.attributes['test-driver.cdi.k8s.io'].preallocate", d2)
    assert evaluate(
        "!device.attributes['test-driver.cdi.k8s.io'].preallocate", d2)


def test_qualified_attribute_domains():
    d = dev(attributes={"dra.example.com/slice": 7, "model": "a100"})
    assert evaluate("device.attributes['dra.example.com'].slice == 7", d)
    # plain names live under the driver's own domain
    assert evaluate(
        "device.attributes['test-driver.cdi.k8s.io'].model == 'a100'", d)


def test_capacity_compare_to_quantity():
    d = dev(capacity={"counters": "2"})
    expr = ("device.capacity['test-driver.cdi.k8s.io'].counters"
            ".compareTo(quantity('2')) >= 0")
    assert evaluate(expr, d)
    d_small = dev(capacity={"counters": "1"})
    assert not evaluate(expr, d_small)
    d_gi = dev(capacity={"mem": "2Gi"})
    assert evaluate("device.capacity['test-driver.cdi.k8s.io'].mem"
                    ".compareTo(quantity('1Gi')) > 0", d_gi)


def test_reference_selector_expression_verbatim():
    # resourceclaim-with-selector.yaml's exact two-line expression
    expr = ("device.capacity['test-driver.cdi.k8s.io'].counters"
            ".compareTo(quantity('2')) >= 0 &&\n"
            "device.attributes['test-driver.cdi.k8s.io'].preallocate")
    good = dev(attributes={"preallocate": True},
               capacity={"counters": "2"})
    bad = dev(attributes={"preallocate": False},
              capacity={"counters": "2"})
    assert evaluate(expr, good)
    assert not evaluate(expr, bad)


def test_boolean_operators():
    d = dev(attributes={"a": True, "b": False})
    dom = "device.attributes['test-driver.cdi.k8s.io']"
    assert evaluate(f"{dom}.a || {dom}.b", d)
    assert not evaluate(f"{dom}.a && {dom}.b", d)
    assert evaluate(f"{dom}.a && !{dom}.b", d)


def test_int_and_string_comparisons():
    d = dev(attributes={"gen": 3, "family": "tpu-v5e"})
    dom = "device.attributes['test-driver.cdi.k8s.io']"
    assert evaluate(f"{dom}.gen >= 2", d)
    assert not evaluate(f"{dom}.gen > 3", d)
    assert evaluate(f"{dom}.family.startsWith('tpu')", d)
    assert evaluate(f"{dom}.family.matches('v5e$')", d)


def test_errors_raise_cel_error():
    d = dev()
    with pytest.raises(CelError):
        evaluate("import os", d)
    with pytest.raises(CelError):
        evaluate("__import__('os')", d)
    with pytest.raises(CelError):
        evaluate("device.__class__", d)
    with pytest.raises(CelError):
        evaluate("device.attributes['x'].missing", d)
    with pytest.raises(CelError):
        evaluate("(lambda: 1)()", d)
    with pytest.raises(CelError):
        evaluate("device.driver == ", d)


# suite-tier discipline (tests/test_markers.py): area marker
pytestmark = pytest.mark.core
