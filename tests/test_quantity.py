from kubernetes_tpu.utils.quantity import parse_bytes, parse_cpu_milli, parse_int


def test_cpu_milli():
    assert parse_cpu_milli("100m") == 100
    assert parse_cpu_milli("2") == 2000
    assert parse_cpu_milli("0.5") == 500
    assert parse_cpu_milli("1500m") == 1500
    assert parse_cpu_milli(4) == 4000
    # rounds up
    assert parse_cpu_milli("1m") == 1
    assert parse_cpu_milli("0.0001") == 1


def test_bytes():
    assert parse_bytes("128974848") == 128974848
    assert parse_bytes("129e6") == 129000000
    assert parse_bytes("123Mi") == 123 * 1024 * 1024
    assert parse_bytes("1G") == 10**9
    assert parse_bytes("1Gi") == 2**30
    assert parse_bytes("500M") == 500 * 10**6
    assert parse_bytes("1Ki") == 1024
    assert parse_bytes("2Ti") == 2 * 2**40


def test_pods():
    assert parse_int("110") == 110
    assert parse_int("1k") == 1000


# suite-tier discipline (tests/test_markers.py): area marker
import pytest  # noqa: E402
pytestmark = pytest.mark.core
