"""Pipelined scheduling waves (ISSUE 19): A/B parity of the pipelined
arm against strict launch->commit alternation, chain-surviving churn,
off-thread commit containment, fused auction rounds, preemptor
next-wave activation, and the zero-recompile gate."""

import numpy as np

from kubernetes_tpu.chaos import DeviceChaos, DeviceChaosConfig
from kubernetes_tpu.config.types import default_config
from kubernetes_tpu.hub import Hub
from kubernetes_tpu.models.pipeline import launch_cache_size
from kubernetes_tpu.ops.features import Capacities
from kubernetes_tpu.scheduler import PIPELINE_DEPTH, Scheduler
from kubernetes_tpu.testing import MakeNode, MakePod


def mksched(hub, pipelined=True, batch=16, nodes=16, pods=256, seed=7):
    cfg = default_config()
    cfg.batch_size = batch
    cfg.pipelined_waves = pipelined
    cfg.tie_break_seed = seed
    return Scheduler(hub, cfg, caps=Capacities(nodes=nodes, pods=pods))


def mkcluster(n=8, cpu="32"):
    hub = Hub()
    for i in range(n):
        hub.create_node(MakeNode().name(f"node-{i}")
                        .capacity(cpu=cpu, memory="64Gi", pods="110").obj())
    return hub


def placements(hub):
    return {p.metadata.name: p.spec.node_name for p in hub.list_pods()}


# ---------------- A/B parity (satellite 4) ----------------


def test_pipelined_ab_parity_churn_free():
    """Identical placements on a churn-free workload under a fixed tie
    seed: the chain is the same state either way, only its lifetime
    differs between the pipelined and strict-alternation arms."""
    outs = []
    for pipelined in (True, False):
        hub = mkcluster()
        s = mksched(hub, pipelined=pipelined)
        try:
            for i in range(60):
                hub.create_pod(MakePod().name(f"p-{i}")
                               .req(cpu=f"{100 + i}m", memory="64Mi").obj())
            s.run_until_idle()
            outs.append(placements(hub))
        finally:
            s.close()
    assert outs[0] == outs[1]
    assert all(n is not None for n in outs[0].values())


def test_pipelined_ab_parity_under_churn():
    """Same churn sequence (foreign deletes + late arrivals between
    drains) lands identical placements whether the churn is folded into
    the live chain (patches) or invalidates it wholesale."""
    outs, stats = [], []
    for pipelined in (True, False):
        hub = mkcluster()
        s = mksched(hub, pipelined=pipelined)
        try:
            for i in range(40):
                hub.create_pod(MakePod().name(f"p-{i}")
                               .req(cpu="100m", memory="64Mi").obj())
            s.run_until_idle()
            victims = sorted((p for p in hub.list_pods()
                              if p.spec.node_name),
                             key=lambda p: p.metadata.name)[:6]
            for v in victims:
                hub.delete_pod(v.metadata.uid)
            for i in range(40, 72):
                hub.create_pod(MakePod().name(f"p-{i}")
                               .req(cpu="150m", memory="64Mi").obj())
            s.run_until_idle()
            outs.append(placements(hub))
            stats.append(dict(s.stats))
            assert s.cache.compare_with_hub(hub) == []
        finally:
            s.close()
    assert outs[0] == outs[1]
    # the pipelined arm actually exercised the patch path (the deletes
    # between drains are foreign-pod deltas scattered into the chain)
    assert stats[0]["chain_patches"] > 0
    assert stats[0]["chain_patch_rows"] > 0
    assert stats[1]["chain_patches"] == 0


# ---------------- pipeline depth (satellite 1) ----------------


def test_pipeline_depth_recovers_after_host_batch():
    """A non-chainable (host-port) batch mid-drain must not strand the
    pipeline shallow: depth returns to PIPELINE_DEPTH afterwards."""
    hub = mkcluster()
    s = mksched(hub, batch=8)
    try:
        for i in range(40):
            hub.create_pod(MakePod().name(f"a-{i}")
                           .req(cpu="100m", memory="64Mi").obj())
        # the host-port pod forces its batch through the snapshot-sync
        # (unchained) path
        hub.create_pod(MakePod().name("hp").req(cpu="100m", memory="64Mi")
                       .host_port(8080).obj())
        for i in range(40):
            hub.create_pod(MakePod().name(f"b-{i}")
                           .req(cpu="100m", memory="64Mi").obj())
        s.run_until_idle()
        depths = [c["depth"] for c in s.flight.last(400) if c.get("depth")]
        assert max(depths) == PIPELINE_DEPTH
        # find the stall (a dispatch that found the pipeline drained) and
        # demand full depth again afterwards
        shallow = [i for i, d in enumerate(depths) if d == 1]
        assert shallow, "expected at least the first dispatch at depth 1"
        assert any(d == PIPELINE_DEPTH
                   for d in depths[shallow[-1]:]), \
            "pipeline never refilled after the last shallow dispatch"
        assert all(p.spec.node_name for p in hub.list_pods())
    finally:
        s.close()


def test_off_arm_strict_alternation():
    """pipelined_waves=False commits every wave before the next
    dispatch: recorded depth never exceeds 1."""
    hub = mkcluster()
    s = mksched(hub, pipelined=False, batch=8)
    try:
        for i in range(40):
            hub.create_pod(MakePod().name(f"p-{i}")
                           .req(cpu="100m", memory="64Mi").obj())
        s.run_until_idle()
        depths = [c["depth"] for c in s.flight.last(400) if c.get("depth")]
        assert depths and max(depths) == 1
    finally:
        s.close()


# ---------------- occupancy (satellite 2) ----------------


def test_occupancy_stat_recorded():
    hub = mkcluster()
    s = mksched(hub)
    try:
        for i in range(48):
            hub.create_pod(MakePod().name(f"p-{i}")
                           .req(cpu="100m", memory="64Mi").obj())
        s.run_until_idle()
        occ = s.flight.occupancy_stats()
        assert occ["n"] > 0
        assert 0.0 <= occ["p50"] <= 1.0
        assert 0.0 <= occ["mean"] <= 1.0
        assert 0.0 <= occ["p99"] <= 1.0
    finally:
        s.close()


def test_pipelined_commit_pull_attribution():
    """Host-tail attribution under pipelined waves (ISSUE 20 satellite):
    pipelined cycles book the commit thread's device pull as the
    overlapped "commit_pull" phase, device_launch carries only the loop
    thread's blocked wait, and neither the cycle total nor occupancy
    double-counts the pull. The strict-alternation arm books no
    commit_pull at all (the pull runs inline inside device_launch)."""
    for pipelined in (True, False):
        hub = mkcluster()
        s = mksched(hub, pipelined=pipelined, batch=8)
        try:
            for i in range(48):
                hub.create_pod(MakePod().name(f"p-{i}")
                               .req(cpu="100m", memory="64Mi").obj())
            s.run_until_idle()
            cycles = [c for c in s.flight.last(400) if c.get("pods")]
            assert cycles
            pulled = [c for c in cycles
                      if "commit_pull" in c.get("phases_ms", {})]
            if not pipelined:
                assert not pulled
                continue
            # pipelined cycles past the first dispatch ride the chain
            assert pulled, "no pipelined cycle booked a commit_pull"
            for c in pulled:
                ph = c["phases_ms"]
                # the exported total sums the booked phases WITHOUT the
                # overlap (and without the dra_*/compile views)
                from kubernetes_tpu.utils.tracing import EXCLUDED_PHASES
                booked = sum(v for k, v in ph.items()
                             if k not in EXCLUDED_PHASES)
                # phases_ms round per-phase to 3 decimals, total_ms
                # rounds once — allow half-ulp per booked phase
                assert abs(c["total_ms"] - booked) < 0.0005 * (len(ph) + 1)
                assert ph["commit_pull"] >= 0.0
                # occupancy stays a fraction of the cycle wall even
                # though the pull overlapped it
                if c.get("occupancy") is not None:
                    assert 0.0 <= c["occupancy"] <= 1.0
        finally:
            s.close()


# ---------------- zero-recompile gate (satellite 3) ----------------


def test_no_recompiles_in_steady_churn():
    """After a first drain warmed every bucket (including the chain-patch
    kernels), steady churn at the same batch buckets compiles nothing."""
    hub = mkcluster()
    s = mksched(hub, batch=16)
    try:
        for i in range(48):        # buckets: 16, 16, 16
            hub.create_pod(MakePod().name(f"w-{i}")
                           .req(cpu="100m", memory="64Mi").obj())
        s.run_until_idle()
        before = launch_cache_size()
        for rnd in range(3):
            victims = [p for p in hub.list_pods() if p.spec.node_name][:4]
            for v in victims:
                hub.delete_pod(v.metadata.uid)
            for i in range(16):    # one full bucket per round
                hub.create_pod(MakePod().name(f"c-{rnd}-{i}")
                               .req(cpu="100m", memory="64Mi").obj())
            s.run_until_idle()
        assert s.stats["chain_patches"] > 0
        assert launch_cache_size() == before, \
            "steady-state churn triggered a recompile"
    finally:
        s.close()


# ---------------- fused auction rounds (tentpole front 1) -------------


def test_auction_unroll_bit_identical():
    """The cond-gated unrolled auction body is bit-identical to the
    one-round-per-iteration loop (the body is idempotent at its fixed
    point, so over-stepping past convergence is a no-op)."""
    from kubernetes_tpu.models.pipeline import (
        extract_state_jit,
        schedule_batch_jit,
    )

    hub = mkcluster(n=6, cpu="8")
    s = mksched(hub, nodes=8, pods=64, batch=32)
    try:
        pods = [MakePod().name(f"p-{i}").req(cpu="900m", memory="64Mi")
                .obj() for i in range(30)]
        for p in pods:
            hub.create_pod(p)
        s.cache.update_snapshot(s.snapshot)
        s.mirror.sync(s.snapshot)
        spec = s.mirror.prepare_launch(pods, 32)
        pcfg = s._profile_cfg["default-scheduler"]
        state = extract_state_jit(spec.cblobs, s.caps)

        def run(unroll):
            return schedule_batch_jit(
                spec.cblobs, spec.pblobs, s.mirror.well_known(),
                pcfg["weights"], s.caps, spec.enable_topology, spec.d_cap,
                pcfg["filters"], serial_scan=False, state=state,
                active=spec.active, pfields=spec.pfields, ptmpl=spec.ptmpl,
                auction_unroll=unroll)

        o1, o4 = run(1), run(4)
        assert np.array_equal(np.asarray(o1.node_row),
                              np.asarray(o4.node_row))
        assert np.array_equal(np.asarray(o1.free), np.asarray(o4.free))
        assert np.array_equal(np.asarray(o1.nzr), np.asarray(o4.nzr))
        assert (np.asarray(o1.node_row) >= 0).sum() == len(pods)
    finally:
        s.close()


# ---------------- commit-thread containment (satellite 5) -------------


def test_commit_pull_fault_contained():
    """A commit-thread exception surfaces through the wave's future and
    takes the SAME _finish_contained ladder as an inline launch fault:
    every pod still binds exactly once, nothing is lost."""
    hub = mkcluster()
    s = mksched(hub)
    chaos = DeviceChaos(DeviceChaosConfig(seed=3,
                                          commit_pull_error_rate=0.5))
    s.fault_injector = chaos
    try:
        for i in range(48):
            hub.create_pod(MakePod().name(f"p-{i}")
                           .req(cpu="100m", memory="64Mi").obj())
        s.run_until_idle()
        assert chaos.stats["injected_pull_errors"] > 0
        assert s.stats["device_fallbacks"] > 0
        pods = hub.list_pods()
        assert len(pods) == 48
        assert all(p.spec.node_name for p in pods)
        assert s.cache.compare_with_hub(hub) == []
    finally:
        s.close()


# ---------------- preemptor next-wave activation (front 4) ------------


def test_preemptor_rides_next_wave():
    """After the eviction flush fires, the preemptor is activated and
    binds within the SAME drain — no backoff wait into a later one."""
    hub = Hub()
    for i in range(2):
        hub.create_node(MakeNode().name(f"node-{i}")
                        .capacity(cpu="2", memory="32Gi", pods="110").obj())
    s = mksched(hub, nodes=16, pods=64)
    try:
        for i in range(4):
            hub.create_pod(MakePod().name(f"low-{i}")
                           .req(cpu="1", memory="256Mi").priority(0).obj())
        s.run_until_idle()
        assert s.stats["scheduled"] == 4
        high = MakePod().name("high").req(cpu="1500m", memory="256Mi") \
            .priority(100).obj()
        hub.create_pod(high)
        s.run_until_idle()
        hp = hub.get_pod(high.metadata.uid)
        assert hp.spec.node_name in ("node-0", "node-1")
        assert s.stats["preemptions"] == 1
    finally:
        s.close()


# suite-tier discipline (tests/test_markers.py): area marker
import pytest  # noqa: E402
pytestmark = pytest.mark.core
