"""DynamicResources (DRA) end-to-end: claim-backed pods, device-count
pressure, allocation persistence across scheduler restart (reference:
plugins/dynamicresources/dynamicresources.go:105-888)."""

import pytest

pytestmark = pytest.mark.dra

from kubernetes_tpu.api.objects import (
    Container,
    Device,
    DeviceRequest,
    LABEL_HOSTNAME,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodResourceClaim,
    PodSpec,
    ResourceClaim,
    ResourceClaimSpec,
    ResourceRequirements,
    ResourceSlice,
)
from kubernetes_tpu.config.types import default_config
from kubernetes_tpu.hub import Hub
from kubernetes_tpu.ops.features import Capacities
from kubernetes_tpu.scheduler import Scheduler


def mknode(name):
    return Node(metadata=ObjectMeta(name=name,
                                    labels={LABEL_HOSTNAME: name}),
                status=NodeStatus(allocatable={"cpu": "16",
                                               "memory": "32Gi",
                                               "pods": "110"}))


def mkslice(node, n_devices, driver="tpu.example.com", cls="tpu"):
    return ResourceSlice(
        metadata=ObjectMeta(name=f"slice-{node}"),
        node_name=node, driver=driver, pool=node,
        devices=[Device(name=f"dev-{i}", device_class_name=cls)
                 for i in range(n_devices)])


def mkclaim(name, count=1, cls="tpu"):
    return ResourceClaim(
        metadata=ObjectMeta(name=name),
        spec=ResourceClaimSpec(device_requests=[
            DeviceRequest(name="accel", device_class_name=cls,
                          count=count)]))


def mkpod(name, claim=None):
    claims = []
    if claim:
        claims = [PodResourceClaim(name="accel",
                                   resource_claim_name=claim)]
    return Pod(metadata=ObjectMeta(name=name),
               spec=PodSpec(containers=[Container(
                   name="c", resources=ResourceRequirements(
                       requests={"cpu": "100m"}))],
                   resource_claims=claims))


def mksched(hub):
    cfg = default_config()
    cfg.batch_size = 16
    return Scheduler(hub, cfg, caps=Capacities(nodes=16, pods=64))


def bound(hub, pod):
    return hub.get_pod(pod.metadata.uid).spec.node_name


def test_claim_backed_pod_schedules_on_device_node():
    hub = Hub()
    sched = mksched(hub)
    hub.create_node(mknode("plain"))
    hub.create_node(mknode("accel"))
    hub.create_resource_slice(mkslice("accel", 4))
    hub.create_resource_claim(mkclaim("c1"))
    p = mkpod("p", claim="c1")
    hub.create_pod(p)
    sched.run_until_idle()
    assert bound(hub, p) == "accel", "only the slice-backed node fits"
    claim = hub.get_resource_claim("default", "c1")
    assert claim.status.allocation is not None
    assert claim.status.allocation.node_name == "accel"
    assert len(claim.status.allocation.devices) == 1
    assert claim.status.allocation.devices[0].device == "dev-0"
    assert p.metadata.uid in claim.status.reserved_for


def test_missing_claim_unresolvable():
    hub = Hub()
    sched = mksched(hub)
    hub.create_node(mknode("n"))
    p = mkpod("p", claim="nope")
    hub.create_pod(p)
    sched.run_until_idle()
    assert bound(hub, p) == ""
    msg = hub.get_pod(p.metadata.uid).status.conditions[0].message
    assert "DynamicResources" in msg


def test_device_exhaustion_spreads_then_rejects():
    hub = Hub()
    sched = mksched(hub)
    hub.create_node(mknode("a"))
    hub.create_node(mknode("b"))
    hub.create_resource_slice(mkslice("a", 1))
    hub.create_resource_slice(mkslice("b", 1))
    pods = []
    for i in range(3):
        hub.create_resource_claim(mkclaim(f"c{i}"))
        pods.append(mkpod(f"p{i}", claim=f"c{i}"))
        hub.create_pod(pods[-1])
    sched.run_until_idle()
    placed = [bound(hub, p) for p in pods if bound(hub, p)]
    assert sorted(placed) == ["a", "b"], "one device per node"
    loser = [p for p in pods if not bound(hub, p)]
    assert len(loser) == 1
    # no device double-booked
    devs = set()
    for i in range(3):
        claim = hub.get_resource_claim("default", f"c{i}")
        if claim.status.allocation is not None:
            for d in claim.status.allocation.devices:
                key = (d.driver, d.pool, d.device)
                assert key not in devs
                devs.add(key)


def test_multi_device_claim():
    hub = Hub()
    sched = mksched(hub)
    hub.create_node(mknode("small"))
    hub.create_node(mknode("big"))
    hub.create_resource_slice(mkslice("small", 1))
    hub.create_resource_slice(mkslice("big", 4))
    hub.create_resource_claim(mkclaim("c2", count=2))
    p = mkpod("p", claim="c2")
    hub.create_pod(p)
    sched.run_until_idle()
    assert bound(hub, p) == "big"
    claim = hub.get_resource_claim("default", "c2")
    assert len(claim.status.allocation.devices) == 2


def test_allocation_survives_restart_replay():
    """A restarted scheduler rebuilds its device view from claim statuses:
    the surviving allocation keeps its devices booked, and a pre-allocated
    pending claim pins its pod to the allocated node."""
    hub = Hub()
    sched1 = mksched(hub)
    hub.create_node(mknode("a"))
    hub.create_node(mknode("b"))
    hub.create_resource_slice(mkslice("a", 1))
    hub.create_resource_slice(mkslice("b", 1))
    hub.create_resource_claim(mkclaim("c1"))
    p1 = mkpod("p1", claim="c1")
    hub.create_pod(p1)
    sched1.run_until_idle()
    first_node = bound(hub, p1)
    assert first_node in ("a", "b")
    sched1.close()

    # "restart": a brand-new scheduler over the same hub state
    sched2 = mksched(hub)
    hub.create_resource_claim(mkclaim("c2"))
    p2 = mkpod("p2", claim="c2")
    hub.create_pod(p2)
    sched2.run_until_idle()
    other = "b" if first_node == "a" else "a"
    assert bound(hub, p2) == other, \
        "the restarted scheduler must see c1's device as taken"
    c1 = hub.get_resource_claim("default", "c1")
    assert c1.status.allocation.node_name == first_node, \
        "c1's allocation untouched by the restart"
    c2 = hub.get_resource_claim("default", "c2")
    assert c2.status.allocation.node_name == other
    assert (c1.status.allocation.devices[0].pool
            != c2.status.allocation.devices[0].pool)


def test_preallocated_claim_pins_pod_after_restart():
    hub = Hub()
    sched1 = mksched(hub)
    hub.create_node(mknode("a"))
    hub.create_node(mknode("b"))
    hub.create_resource_slice(mkslice("a", 2))
    hub.create_resource_slice(mkslice("b", 2))
    hub.create_resource_claim(mkclaim("c1"))
    p1 = mkpod("p1", claim="c1")
    hub.create_pod(p1)
    sched1.run_until_idle()
    node1 = bound(hub, p1)
    sched1.close()

    # the pod is deleted but its claim stays allocated (DRA claims outlive
    # pods until deallocated); a new pod reusing the claim must land on
    # the allocation's node
    hub.delete_pod(p1.metadata.uid)
    c1 = hub.get_resource_claim("default", "c1")
    assert c1.status.allocation is not None, \
        "standalone claim keeps its allocation across consumers"
    sched2 = mksched(hub)
    p2 = mkpod("p2", claim="c1")
    hub.create_pod(p2)
    sched2.run_until_idle()
    assert bound(hub, p2) == node1, "pinned to the claim's allocation"


def test_claim_deletion_frees_devices_pod_deletion_does_not():
    """A deleted consumer only leaves reservedFor (the standalone claim
    keeps its devices); deleting the CLAIM is what returns them to the
    pool and unsticks the waiting pod."""
    hub = Hub()
    sched = mksched(hub)
    hub.create_node(mknode("a"))
    hub.create_resource_slice(mkslice("a", 1))
    hub.create_resource_claim(mkclaim("c1"))
    hub.create_resource_claim(mkclaim("c2"))
    p1 = mkpod("p1", claim="c1")
    p2 = mkpod("p2", claim="c2")
    hub.create_pod(p1)
    hub.create_pod(p2)
    sched.run_until_idle()
    first = p1 if bound(hub, p1) else p2
    second = p2 if first is p1 else p1
    first_claim = "c1" if first is p1 else "c2"
    assert bound(hub, first) == "a" and bound(hub, second) == ""
    import time as _t

    # pod deletion alone: reservedFor drops, allocation persists,
    # the loser still cannot get the device
    hub.delete_pod(first.metadata.uid)
    held = hub.get_resource_claim("default", first_claim)
    assert held.status.reserved_for == []
    assert held.status.allocation is not None
    _t.sleep(1.2)
    sched.queue.flush_backoff_completed()
    sched.run_until_idle()
    assert bound(hub, second) == ""
    # claim deletion frees the device: the loser requeues and wins
    # (its accumulated backoff can reach ~10s of real time)
    hub.delete_resource_claim(held.metadata.uid)
    for _ in range(30):
        sched.queue.flush_backoff_completed()
        sched.run_until_idle()
        if bound(hub, second):
            break
        _t.sleep(0.5)
    assert bound(hub, second) == "a"


def test_dra_shared_across_profiles_no_double_booking():
    """The reference shares one DRA manager across profiles
    (scheduler.go:311-333 SharedDRAManager): all frameworks must hold the
    SAME DynamicResources instance, and two same-batch pods from
    different profiles competing for the last device must never
    double-book it."""
    from kubernetes_tpu.config.types import SchedulerProfile, default_plugins

    hub = Hub()
    hub.create_node(mknode("n1"))
    hub.create_resource_slice(mkslice("n1", 1))     # ONE device
    hub.create_resource_claim(mkclaim("c-a"))
    hub.create_resource_claim(mkclaim("c-b"))
    cfg = default_config()
    cfg.profiles.append(SchedulerProfile(scheduler_name="second",
                                         plugins=default_plugins()))
    cfg.batch_size = 8
    sched = Scheduler(hub, cfg, caps=Capacities(nodes=16, pods=64))
    insts = {id(fw.instance("DynamicResources"))
             for fw in sched.frameworks.values()}
    assert len(insts) == 1, "profiles must share one DRA assume overlay"
    pa = mkpod("pod-a", claim="c-a")
    pb = mkpod("pod-b", claim="c-b")
    pb.spec.scheduler_name = "second"
    hub.create_pod(pa)
    hub.create_pod(pb)
    sched.run_until_idle()
    allocated = [hub.get_resource_claim("default", n)
                 for n in ("c-a", "c-b")]
    devices = [tuple((d.driver, d.pool, d.device)
                     for d in c.status.allocation.devices)
               for c in allocated if c.status.allocation is not None]
    assert len(devices) == 1, \
        f"exactly one claim may win the single device, got {devices}"
    bound = [p for p in (pa, pb)
             if hub.get_pod(p.metadata.uid).spec.node_name]
    assert len(bound) == 1
    # the loser is parked unschedulable (not an error): capacity races
    # and exhaustion are rejections with plugin attribution
    assert sched.stats["errors"] == 0
    sched.close()
