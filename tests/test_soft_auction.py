"""ISSUE 15: soft-topology auction, daemonset pin fast path, batched
eviction waves, bucket hysteresis, and the device-dead preemption rung.

The differential discipline mirrors tests/test_dra_fuzz.py: the device
soft-score terms are pinned against (a) a plain-python host oracle of the
static (table) halves and (b) the serial commit scan — whose own parity
with the reference semantics tests/test_oracle.py already pins — over
randomized pods/nodes/tables.
"""

import random

import numpy as np
import pytest

import jax.numpy as jnp

from kubernetes_tpu.api.objects import (
    Affinity,
    Container,
    LABEL_HOSTNAME,
    LABEL_ZONE,
    LabelSelector,
    Node,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodAffinity,
    PodAffinityTerm,
    PodAntiAffinity,
    PodSpec,
    ResourceRequirements,
    TopologySpreadConstraint,
    WeightedPodAffinityTerm,
)
from kubernetes_tpu.api.labels import label_selector_matches
from kubernetes_tpu.backend.cache import Cache
from kubernetes_tpu.backend.mirror import Mirror
from kubernetes_tpu.backend.snapshot import Snapshot
from kubernetes_tpu.models.pipeline import (
    default_weights,
    launch_batch,
)
from kubernetes_tpu.ops.features import Capacities

pytestmark = pytest.mark.core

CAPS = Capacities(nodes=32, pods=512)
WEIGHTS = default_weights()


def mknode(i, zones=3):
    name = f"node-{i}"
    return Node(
        metadata=ObjectMeta(name=name, labels={
            LABEL_HOSTNAME: name, LABEL_ZONE: f"z{i % zones}"}),
        spec=NodeSpec(),
        status=NodeStatus(allocatable={
            "cpu": "8", "memory": "16Gi", "pods": "110"}))


def soft_pod(name, rng, ns="default"):
    """A pod whose ONLY topology work is soft: preferred (anti)affinity
    and/or a ScheduleAnyway spread constraint."""
    labels = {"app": f"a{rng.randrange(3)}"}
    sel = LabelSelector(match_labels={"app": f"a{rng.randrange(3)}"})
    key = rng.choice([LABEL_HOSTNAME, LABEL_ZONE])
    kind = rng.random()
    aff = None
    tsc = []
    if kind < 0.35:
        aff = Affinity(pod_affinity=PodAffinity(preferred=[
            WeightedPodAffinityTerm(
                weight=rng.choice([1, 5, 10, 50]),
                pod_affinity_term=PodAffinityTerm(
                    topology_key=key, label_selector=sel))]))
    elif kind < 0.7:
        aff = Affinity(pod_anti_affinity=PodAntiAffinity(preferred=[
            WeightedPodAffinityTerm(
                weight=rng.choice([1, 5, 10, 50]),
                pod_affinity_term=PodAffinityTerm(
                    topology_key=key, label_selector=sel))]))
    else:
        tsc = [TopologySpreadConstraint(
            max_skew=rng.choice([1, 3, 5]), topology_key=key,
            when_unsatisfiable="ScheduleAnyway", label_selector=sel)]
    return Pod(
        metadata=ObjectMeta(name=name, labels=labels, namespace=ns),
        spec=PodSpec(
            containers=[Container(name="c", resources=ResourceRequirements(
                requests={"cpu": "100m", "memory": "200Mi"}))],
            affinity=aff, topology_spread_constraints=tsc))


def build(rng, n_nodes=12, n_table=8):
    cache, snap, m = Cache(), Snapshot(), Mirror(caps=CAPS)
    for i in range(n_nodes):
        cache.add_node(mknode(i))
    table = []
    for i in range(n_table):
        p = soft_pod(f"bound-{i}", rng)
        p.metadata.uid = f"bound-{i}"
        p.spec.node_name = f"node-{rng.randrange(n_nodes)}"
        cache.add_pod(p)
        table.append(p)
    cache.update_snapshot(snap)
    m.sync(snap)
    return table, snap, m


def host_ipa_static(pod, table_pods, node_zone_of, n_nodes):
    """Plain-python oracle of the TABLE half of the preferred IPA score
    (scoring.go processExistingPod, soft directions + existing preferred
    both kinds; no required terms exist in the soft-only fuzz)."""
    scores = np.zeros(n_nodes)

    def dom_nodes(key, value):
        if key == LABEL_HOSTNAME:
            return [int(value.split("-")[1])]
        return [n for n in range(n_nodes) if node_zone_of(n) == value]

    def terms(p, kind):
        a = p.spec.affinity
        if a is None:
            return []
        grp = a.pod_affinity if kind == "aff" else a.pod_anti_affinity
        return grp.preferred if grp is not None else []

    for tp in table_pods:
        node_i = int(tp.spec.node_name.split("-")[1])
        # incoming pod's preferred terms vs table pod tp
        for sign, kind in ((1.0, "aff"), (-1.0, "anti")):
            for w in terms(pod, kind):
                t = w.pod_affinity_term
                if tp.metadata.namespace != pod.metadata.namespace:
                    continue
                if not label_selector_matches(t.label_selector,
                                              tp.metadata.labels):
                    continue
                key = t.topology_key
                val = (tp.spec.node_name if key == LABEL_HOSTNAME
                       else f"z{node_i % 3}")
                for n in dom_nodes(key, val):
                    scores[n] += sign * w.weight
        # table pod tp's preferred terms vs the incoming pod
        for sign, kind in ((1.0, "aff"), (-1.0, "anti")):
            for w in terms(tp, kind):
                t = w.pod_affinity_term
                if tp.metadata.namespace != pod.metadata.namespace:
                    continue
                if not label_selector_matches(t.label_selector,
                                              pod.metadata.labels):
                    continue
                key = t.topology_key
                val = (tp.spec.node_name if key == LABEL_HOSTNAME
                       else f"z{node_i % 3}")
                for n in dom_nodes(key, val):
                    scores[n] += sign * w.weight
    return scores


SEEDS_T1 = range(8)
SEEDS_SLOW = range(8, 40)


@pytest.mark.parametrize("seed", SEEDS_T1)
def test_soft_static_ipa_matches_host_oracle(seed):
    """The _soft_statics table half == the python oracle, per node."""
    import jax

    import kubernetes_tpu.models.pipeline as P
    import kubernetes_tpu.ops.topology as T
    from kubernetes_tpu.ops.features import unpack_cluster, unpack_pods

    rng = random.Random(seed)
    table_pods, snap, m = build(rng)
    pods = [soft_pod(f"p-{i}", rng) for i in range(6)]
    for i, p in enumerate(pods):
        p.metadata.uid = f"p-{i}"
    spec = m.prepare_launch(pods, 8)
    assert spec.topo_soft
    ct = unpack_cluster(spec.cblobs, CAPS)
    pf = unpack_pods(spec.pblobs, CAPS, spec.pfields, spec.ptmpl)
    pods_rep = jax.tree.map(lambda x: x[spec.rep], pf)
    tds = T.slot_topo_dom(ct)
    soft = P._soft_statics(
        ct, pf, pods_rep, spec.gid, spec.g_cap, spec.d_cap, tds,
        m.well_known(), (True,) * P.NUM_FILTER_PLUGINS,
        frozenset(P.ALL_FEATURES), True,
        lambda fn, tree, n: jax.vmap(fn)(tree))
    ipa_raw = np.asarray(soft.ipa_raw_g)
    gid = np.asarray(spec.gid)
    for b, pod in enumerate(pods):
        want = host_ipa_static(pod, table_pods,
                               lambda n: f"z{n % 3}", 12)
        got = ipa_raw[gid[b]]
        # mirror rows are allocated in node order for this build
        rows = [m.row_of(f"node-{n}") for n in range(12)]
        np.testing.assert_allclose(got[rows], want, atol=1e-4,
                                   err_msg=f"pod {b} seed {seed}")


def _compare_single_pod(seed):
    """B=1 batches: the auction IS as-if-serial, so soft-auction and
    serial-scan placements + winning scores must agree exactly."""
    rng = random.Random(seed)
    _table, snap, m = build(rng)
    pod = soft_pod("solo", rng)
    pod.metadata.uid = "solo"
    spec = m.prepare_launch([pod], 2)
    assert spec.topo_soft
    out_s = launch_batch(spec, m.well_known(), WEIGHTS, CAPS,
                         serial_scan=True)
    out_a = launch_batch(spec, m.well_known(), WEIGHTS, CAPS,
                         serial_scan=False)
    rs, ra = int(out_s.node_row[0]), int(out_a.node_row[0])
    assert rs == ra, (seed, rs, ra)
    np.testing.assert_allclose(float(out_s.score[0]),
                               float(out_a.score[0]), atol=1e-3)


@pytest.mark.parametrize("seed", SEEDS_T1)
def test_soft_auction_single_pod_parity(seed):
    _compare_single_pod(seed)


@pytest.mark.slow
@pytest.mark.parametrize("seed", SEEDS_SLOW)
def test_soft_auction_single_pod_parity_slow(seed):
    _compare_single_pod(seed)


@pytest.mark.parametrize("seed", SEEDS_T1)
def test_soft_auction_batch_places_everything(seed):
    """Multi-pod soft batches: every pod places, scores carry the soft
    terms (no NaN guard trips), and in-batch paff attraction shows up —
    colocation-seeking pods land in fewer distinct zones than spreading
    pods."""
    rng = random.Random(seed)
    _table, snap, m = build(rng)
    pods = [soft_pod(f"p-{i}", rng) for i in range(8)]
    for i, p in enumerate(pods):
        p.metadata.uid = f"p-{i}"
    spec = m.prepare_launch(pods, 8)
    out = launch_batch(spec, m.well_known(), WEIGHTS, CAPS,
                       serial_scan=False)
    rows = np.asarray(out.node_row)[:8]
    assert (rows >= 0).all()
    assert int(out.guard) == 0


def test_soft_auction_inbatch_affinity_colocates():
    """Strong preferred affinity toward existing matching pods PLUS the
    in-batch delta: the batch must colocate into the seeded zone. (A
    fully cold identical batch may scatter in round 1 — the auction
    scores against round-start state, its documented approximation; the
    realistic warm-table shape is what the preferred-band workloads
    run.)"""
    cache, snap, m = Cache(), Snapshot(), Mirror(caps=CAPS)
    for i in range(12):
        cache.add_node(mknode(i))
    term = WeightedPodAffinityTerm(weight=100, pod_affinity_term=(
        PodAffinityTerm(topology_key=LABEL_ZONE,
                        label_selector=LabelSelector(
                            match_labels={"team": "x"}))))

    def co_pod(name, bound_to=None):
        p = Pod(metadata=ObjectMeta(name=name, uid=name,
                                    labels={"team": "x"}),
                spec=PodSpec(
                    containers=[Container(
                        name="c", resources=ResourceRequirements(
                            requests={"cpu": "100m"}))],
                    affinity=Affinity(pod_affinity=PodAffinity(
                        preferred=[term]))))
        if bound_to:
            p.spec.node_name = bound_to
        return p

    # two matching pods already bound in zone z0 (nodes 0 and 3)
    cache.add_pod(co_pod("seed-0", "node-0"))
    cache.add_pod(co_pod("seed-1", "node-3"))
    cache.update_snapshot(snap)
    m.sync(snap)
    pods = [co_pod(f"co-{i}") for i in range(6)]
    spec = m.prepare_launch(pods, 8)
    assert spec.topo_soft
    out = launch_batch(spec, m.well_known(), WEIGHTS, CAPS,
                       serial_scan=False)
    rows = np.asarray(out.node_row)[:6]
    assert (rows >= 0).all()
    zones = [int(r) % 3 for r in rows]
    assert zones == [0] * 6, f"batch left the seeded zone: {zones}"


def test_required_terms_keep_serial_scan():
    """A batch with ANY required term is not soft-only."""
    m = Mirror(caps=CAPS)
    hard = Pod(metadata=ObjectMeta(name="h", uid="h",
                                   labels={"a": "b"}),
               spec=PodSpec(
                   containers=[Container(name="c")],
                   affinity=Affinity(pod_anti_affinity=PodAntiAffinity(
                       required=[PodAffinityTerm(
                           topology_key=LABEL_HOSTNAME,
                           label_selector=LabelSelector(
                               match_labels={"a": "b"}))]))))
    soft = Pod(metadata=ObjectMeta(name="s", uid="s"),
               spec=PodSpec(
                   containers=[Container(name="c")],
                   topology_spread_constraints=[TopologySpreadConstraint(
                       max_skew=1, topology_key=LABEL_ZONE,
                       when_unsatisfiable="ScheduleAnyway",
                       label_selector=LabelSelector(
                           match_labels={"a": "b"}))]))
    assert not m.batch_topology_soft_only([hard, soft])
    assert m.batch_topology_soft_only([soft])
    hard_tsc = Pod(metadata=ObjectMeta(name="t", uid="t"),
                   spec=PodSpec(
                       containers=[Container(name="c")],
                       topology_spread_constraints=[
                           TopologySpreadConstraint(
                               max_skew=1, topology_key=LABEL_ZONE,
                               when_unsatisfiable="DoNotSchedule",
                               label_selector=LabelSelector(
                                   match_labels={"a": "b"}))]))
    assert not m.batch_topology_soft_only([hard_tsc])


# ---------------------------- daemonset pin ----------------------------


def test_daemonset_pin_feature_and_placement():
    from kubernetes_tpu.perf.workloads import _daemonset_pod, _node

    cache, snap, m = Cache(), Snapshot(), Mirror(caps=CAPS)
    for i in range(16):
        cache.add_node(_node(i))
    cache.update_snapshot(snap)
    m.sync(snap)
    pods = [_daemonset_pod(i) for i in range(8)]
    spec = m.prepare_launch(pods, 8)
    assert spec.active == ("nodeaffinity_pin",)
    assert "aff_pin" in spec.pfields
    assert "sel_col" not in spec.pfields       # the selector kernels are out
    out = launch_batch(spec, m.well_known(), WEIGHTS, CAPS,
                       serial_scan=False)
    names = [m.name_of_row(int(r)) for r in np.asarray(out.node_row)[:8]]
    assert names == [f"node-{i}" for i in range(8)]


def test_pin_mixed_with_general_affinity_stays_full():
    """A batch mixing pins with a general selector keeps the full
    kernels — and the pin pod still lands on its pinned node."""
    from kubernetes_tpu.api.objects import (
        NodeAffinity,
        NodeSelector,
        NodeSelectorRequirement,
        NodeSelectorTerm,
    )
    from kubernetes_tpu.perf.workloads import _daemonset_pod, _node

    cache, snap, m = Cache(), Snapshot(), Mirror(caps=CAPS)
    for i in range(8):
        cache.add_node(_node(i, zones=["z1", "z2"]))
    cache.update_snapshot(snap)
    m.sync(snap)
    pin = _daemonset_pod(3)
    general = Pod(
        metadata=ObjectMeta(name="gen", uid="gen"),
        spec=PodSpec(
            containers=[Container(name="c", resources=ResourceRequirements(
                requests={"cpu": "100m"}))],
            affinity=Affinity(node_affinity=NodeAffinity(
                required=NodeSelector(node_selector_terms=[
                    NodeSelectorTerm(match_expressions=[
                        NodeSelectorRequirement(
                            key=LABEL_ZONE, operator="In",
                            values=["z2"])])])))))
    spec = m.prepare_launch([pin, general], 2)
    assert spec.active == ("nodeaffinity",)
    out = launch_batch(spec, m.well_known(), WEIGHTS, CAPS,
                       serial_scan=False)
    rows = np.asarray(out.node_row)
    assert m.name_of_row(int(rows[0])) == "node-3"
    assert int(rows[1]) % 2 == 1               # z2 nodes are odd rows


# ------------------------- batched eviction wave ------------------------


def test_delete_pods_wave():
    from kubernetes_tpu.hub import Hub

    hub = Hub()
    for i in range(5):
        hub.create_pod(Pod(metadata=ObjectMeta(name=f"v-{i}",
                                               uid=f"v-{i}"),
                           spec=PodSpec(containers=[Container(name="c")])))
    deletes = []
    from kubernetes_tpu.hub import EventHandlers

    hub.watch_pods(EventHandlers(on_delete=lambda p: deletes.append(
        p.metadata.uid)), replay=False)
    gone = hub.delete_pods(["v-0", "v-2", "missing", "v-4"])
    assert gone == ["v-0", "v-2", "v-4"]
    assert sorted(deletes) == ["v-0", "v-2", "v-4"]
    assert hub.get_pod("v-1") is not None
    # replay of the same wave is idempotent
    assert hub.delete_pods(["v-0", "v-2", "v-4"]) == []


def test_delete_pods_fenced():
    from kubernetes_tpu.hub import Fenced, Hub
    from kubernetes_tpu.leaderelection import Lease

    hub = Hub()
    hub.create_pod(Pod(metadata=ObjectMeta(name="v", uid="v"),
                       spec=PodSpec(containers=[Container(name="c")])))
    hub.leases.update(Lease(name="kube-scheduler",
                            holder_identity="other"), None)
    with pytest.raises(Fenced):
        hub.delete_pods(["v"], epoch=0)
    assert hub.get_pod("v") is not None


def test_flush_uses_one_delete_wave():
    """The preemption flush commits its victims through ONE delete_pods
    call instead of one delete_pod per victim."""
    from kubernetes_tpu.backend.nominator import Nominator
    from kubernetes_tpu.framework.preemption import Candidate, Evaluator
    from kubernetes_tpu.hub import Hub

    calls = {"delete_pod": 0, "delete_pods": 0}

    class SpyHub(Hub):
        def delete_pod(self, uid, epoch=None,
                       lease_name="kube-scheduler"):
            calls["delete_pod"] += 1
            return super().delete_pod(uid, epoch, lease_name)

        def delete_pods(self, uids, epoch=None,
                        lease_name="kube-scheduler"):
            calls["delete_pods"] += 1
            return super().delete_pods(uids, epoch, lease_name)

    hub = SpyHub()
    victims = []
    for i in range(6):
        p = Pod(metadata=ObjectMeta(name=f"v-{i}", uid=f"v-{i}"),
                spec=PodSpec(containers=[Container(name="c")]))
        p.spec.node_name = f"node-{i % 2}"
        hub.create_pod(p)
        victims.append(p)
    ev = Evaluator(hub, lambda: None, lambda: None, lambda pod=None: (),
                   Nominator())
    preemptor = Pod(metadata=ObjectMeta(name="hi", uid="hi"),
                    spec=PodSpec(containers=[Container(name="c")],
                                 priority=10))
    ev.prepare_candidate(Candidate(node_name="node-0", row=-1,
                                   victims=victims[:3],
                                   pdb_violations=0), preemptor)
    preemptor2 = Pod(metadata=ObjectMeta(name="hi2", uid="hi2"),
                     spec=PodSpec(containers=[Container(name="c")],
                                  priority=10))
    ev.prepare_candidate(Candidate(node_name="node-1", row=-1,
                                   victims=victims[3:],
                                   pdb_violations=0), preemptor2)
    n = ev.flush_evictions()
    assert n == 2
    assert calls["delete_pods"] == 1
    assert calls["delete_pod"] == 0
    assert all(hub.get_pod(v.metadata.uid) is None for v in victims)
    assert not ev.preempting


def test_queue_coalescing_window():
    """Inside a coalescing window a gated pod's PreEnqueue gate runs once
    per WAVE, not once per event, and requeues still land."""
    from kubernetes_tpu.backend.queue import PriorityQueue
    from kubernetes_tpu.framework.interface import (
        ActionType as A,
        ClusterEvent,
        ClusterEventWithHint,
        EventResource as R,
        Status,
    )

    probes = {"n": 0}
    gate_open = {"open": False}

    def pre_enqueue(pod):
        probes["n"] += 1
        return (Status() if gate_open["open"]
                else Status.unschedulable("gated", plugin="G",
                                          resolvable=False))

    q = PriorityQueue(less_fn=lambda a, b: a.timestamp < b.timestamp,
                      pre_enqueue=pre_enqueue,
                      queueing_hints={"G": [ClusterEventWithHint(
                          event=ClusterEvent(R.ASSIGNED_POD,
                                             A.DELETE))]})
    pod = Pod(metadata=ObjectMeta(name="p", uid="p"),
              spec=PodSpec(containers=[Container(name="c")]))
    q.add(pod)          # gated at add time
    assert q.pending_counts()["gated"] == 1
    probes["n"] = 0
    gate_open["open"] = True
    ev = ClusterEvent(R.ASSIGNED_POD, A.DELETE)
    with q.coalescing():
        for i in range(10):
            q.move_all_to_active_or_backoff(ev, None, None)
    # one gate probe by the batched pass + one by the re-enqueue of the
    # now-ungated pod — per-EVENT processing would have paid 2 per event
    assert probes["n"] == 2, probes["n"]
    assert q.pending_counts()["active"] == 1


# --------------------------- bucket hysteresis ---------------------------


def test_g_cap_oscillation_mints_no_new_shapes():
    """Alternating batch compositions (the churn-pod shape) must settle
    on a BOUNDED set of static shapes — each composition maps to ONE
    stable g_cap, so the oscillation compiles at most once per
    composition and then runs cached. (g_cap is deliberately NOT sticky:
    padding a homogeneous measure phase to a past heterogeneous batch's
    bucket would tax every launch with dead per-group statics.)"""
    rng = random.Random(0)
    _table, snap, m = build(rng, n_nodes=8, n_table=2)
    homog = [soft_pod(f"h-{i}", random.Random(1)) for i in range(4)]
    for i, p in enumerate(homog):
        p.metadata.uid = f"h-{i}"
    odd = [soft_pod(f"odd-{s}", random.Random(40 + s)) for s in range(3)]
    for s, p in enumerate(odd):
        p.metadata.uid = f"odd-{s}"
    mixed = homog[:1] + odd
    shapes = []
    for i in range(12):
        spec = m.prepare_launch(homog if i % 2 else mixed, 4)
        shapes.append((spec.g_cap, spec.d_cap))
    assert len(set(shapes)) <= 2, shapes
    # each composition's shape is STABLE across repeats (no drift that
    # would mint fresh compiles every swing)
    assert shapes[0::2] == [shapes[0]] * 6
    assert shapes[1::2] == [shapes[1]] * 6
    # and a homogeneous batch never pays a past heterogeneous batch's
    # group bucket
    assert shapes[1][0] < shapes[0][0]


def test_d_cap_hysteresis_survives_rebucket():
    rng = random.Random(0)
    _table, snap, m = build(rng)
    d1 = m.launch_d_cap(True)
    m2 = Mirror(caps=CAPS)
    m2.adopt_hysteresis(m)
    assert m2.launch_d_cap(True) >= d1


# ------------------- device-dead preemption mini-path -------------------


def test_device_dead_scheduler_still_preempts():
    """The fallback ladder's bottom rung: with the device path dead for
    EVERY batch, a high-priority pod on a full cluster must still evict
    a victim and bind (it used to park forever)."""
    from kubernetes_tpu.hub import Hub
    from kubernetes_tpu.scheduler import Scheduler

    hub = Hub()
    for i in range(2):
        hub.create_node(Node(
            metadata=ObjectMeta(name=f"node-{i}",
                                labels={LABEL_HOSTNAME: f"node-{i}"}),
            spec=NodeSpec(),
            status=NodeStatus(allocatable={
                "cpu": "1", "memory": "4Gi", "pods": "10"})))
    sched = Scheduler(hub, caps=Capacities(nodes=8, pods=64))

    class DeviceDead:
        def on_pack(self, pods):
            raise RuntimeError("device dead (injected)")

        def on_result(self, out):
            return out

    sched.fault_injector = DeviceDead()
    try:
        # fill both nodes with low-priority 900m pods
        for i in range(2):
            hub.create_pod(Pod(
                metadata=ObjectMeta(name=f"low-{i}", uid=f"low-{i}"),
                spec=PodSpec(containers=[Container(
                    name="c", resources=ResourceRequirements(
                        requests={"cpu": "900m"}))], priority=0)))
        sched.run_until_idle()
        sched.run_maintenance()
        assert all(hub.get_pod(f"low-{i}").spec.node_name
                   for i in range(2))
        hub.create_pod(Pod(
            metadata=ObjectMeta(name="hi", uid="hi"),
            spec=PodSpec(containers=[Container(
                name="c", resources=ResourceRequirements(
                    requests={"cpu": "900m"}))], priority=100)))
        import time as _time

        bound = False
        for _ in range(30):
            sched.run_until_idle()
            sched.run_maintenance()
            sched.queue.flush_backoff_completed()
            p = hub.get_pod("hi")
            if p is not None and p.spec.node_name:
                bound = True
                break
            _time.sleep(0.2)    # let the unschedulable backoff expire
        assert bound, "high-priority pod never bound on the host rung"
        assert sched.stats.get("preemptions", 0) >= 1
        live = [p.metadata.name for p in hub.list_pods()
                if p.spec.node_name]
        assert len(live) == 2, live       # one victim evicted
    finally:
        sched.close()
