"""DRA structured parameters end-to-end: ResourceClaimTemplates (the
resourceclaim controller), CEL device selectors + DeviceClass selectors,
All/ExactCount allocation modes, firstAvailable alternatives
(DRAPrioritizedList), adminAccess, matchAttribute constraints, and the
incremental allocated-device ledger.

Reference: plugins/dynamicresources/dynamicresources.go:105-888, the
structured allocator under staging/src/k8s.io/dynamic-resource-allocation,
and the dra scheduler_perf templates (resourceclaimtemplate*.yaml,
resourceclaim-with-selector.yaml, deviceclass.yaml)."""

import pytest

pytestmark = pytest.mark.dra

from kubernetes_tpu.api.objects import (
    ALLOCATION_MODE_ALL,
    Container,
    Device,
    DeviceClass,
    DeviceConstraint,
    DeviceRequest,
    DeviceSelector,
    DeviceSubRequest,
    LABEL_HOSTNAME,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodResourceClaim,
    PodSpec,
    ResourceClaim,
    ResourceClaimSpec,
    ResourceClaimTemplate,
    ResourceRequirements,
    ResourceSlice,
)
from kubernetes_tpu.config.types import default_config
from kubernetes_tpu.hub import Hub
from kubernetes_tpu.ops.features import Capacities
from kubernetes_tpu.plugins.dra import ResourceClaimController
from kubernetes_tpu.scheduler import Scheduler

DRIVER = "test-driver.cdi.k8s.io"


def mknode(name):
    return Node(metadata=ObjectMeta(name=name,
                                    labels={LABEL_HOSTNAME: name}),
                status=NodeStatus(allocatable={"cpu": "16",
                                               "memory": "32Gi",
                                               "pods": "110"}))


def mkdevice(name, cls="", **attrs):
    capacity = attrs.pop("capacity", {})
    return Device(name=name, device_class_name=cls, attributes=attrs,
                  capacity=capacity)


def mkslice(node, devices, driver=DRIVER):
    return ResourceSlice(metadata=ObjectMeta(name=f"slice-{node}"),
                         node_name=node, driver=driver, pool=node,
                         devices=devices)


def mkpod(name, claim_name="", template_name="", cpu="100m"):
    claims = []
    if claim_name or template_name:
        claims = [PodResourceClaim(
            name="resource", resource_claim_name=claim_name,
            resource_claim_template_name=template_name)]
    return Pod(metadata=ObjectMeta(name=name),
               spec=PodSpec(containers=[Container(
                   name="c", resources=ResourceRequirements(
                       requests={"cpu": cpu}))],
                   resource_claims=claims))


def mksched(hub):
    cfg = default_config()
    cfg.batch_size = 16
    return Scheduler(hub, cfg, caps=Capacities(nodes=16, pods=64))


def bound(hub, pod):
    return hub.get_pod(pod.metadata.uid).spec.node_name


def test_claim_template_materializes_and_schedules():
    """pod-with-claim-template.yaml: the controller stamps a per-pod claim
    from the template, the pod schedules against it, and the claim dies
    with the pod."""
    hub = Hub()
    ResourceClaimController(hub)
    sched = mksched(hub)
    hub.create_node(mknode("accel"))
    hub.create_resource_slice(mkslice(
        "accel", [mkdevice(f"d{i}", cls="test-class") for i in range(2)]))
    hub.create_resource_claim_template(ResourceClaimTemplate(
        metadata=ObjectMeta(name="test-claim-template"),
        spec=ResourceClaimSpec(device_requests=[
            DeviceRequest(name="req-0", device_class_name="test-class")])))
    p = mkpod("pod-a", template_name="test-claim-template")
    hub.create_pod(p)
    sched.run_until_idle()
    assert bound(hub, p) == "accel"
    generated = hub.get_resource_claim("default", "pod-a-resource")
    assert generated is not None
    assert generated.status.allocation is not None
    assert generated.status.allocation.node_name == "accel"
    assert p.metadata.uid in hub.get_resource_claim(
        "default", "pod-a-resource").status.reserved_for
    stored = hub.get_pod(p.metadata.uid)
    assert stored.status.resource_claim_statuses == {
        "resource": "pod-a-resource"}
    # the generated claim is owned by the pod: deletion releases devices
    hub.delete_pod(p.metadata.uid)
    assert hub.get_resource_claim("default", "pod-a-resource") is None


def test_cel_selector_picks_matching_devices_only():
    """resourceclaim-with-selector.yaml: capacity + attribute CEL."""
    hub = Hub()
    sched = mksched(hub)
    hub.create_node(mknode("n1"))
    hub.create_node(mknode("n2"))
    # n1's devices fail the selector (capacity 1 / preallocate False)
    hub.create_resource_slice(mkslice("n1", [
        mkdevice("small", cls="test-class", preallocate=True,
                 capacity={"counters": "1"}),
        mkdevice("nopre", cls="test-class", preallocate=False,
                 capacity={"counters": "4"})]))
    hub.create_resource_slice(mkslice("n2", [
        mkdevice("good", cls="test-class", preallocate=True,
                 capacity={"counters": "2"})]))
    expr = (f"device.capacity['{DRIVER}'].counters"
            ".compareTo(quantity('2')) >= 0 && "
            f"device.attributes['{DRIVER}'].preallocate")
    hub.create_resource_claim(ResourceClaim(
        metadata=ObjectMeta(name="sel-claim"),
        spec=ResourceClaimSpec(device_requests=[
            DeviceRequest(name="req-0", device_class_name="test-class",
                          selectors=[DeviceSelector(
                              cel_expression=expr)])])))
    p = mkpod("p", claim_name="sel-claim")
    hub.create_pod(p)
    sched.run_until_idle()
    assert bound(hub, p) == "n2"
    alloc = hub.get_resource_claim("default", "sel-claim").status.allocation
    assert [d.device for d in alloc.devices] == ["good"]


def test_device_class_cel_selectors():
    """deviceclass.yaml: the class itself selects by CEL over the driver;
    devices need no pre-assigned class name."""
    hub = Hub()
    sched = mksched(hub)
    hub.create_node(mknode("n1"))
    hub.create_node(mknode("n2"))
    hub.create_device_class(DeviceClass(
        metadata=ObjectMeta(name="test-class"),
        selectors=[DeviceSelector(
            cel_expression=f'device.driver == "{DRIVER}"')]))
    hub.create_resource_slice(mkslice("n1", [mkdevice("other")],
                                      driver="other-driver"))
    hub.create_resource_slice(mkslice("n2", [mkdevice("mine")]))
    hub.create_resource_claim(ResourceClaim(
        metadata=ObjectMeta(name="c"),
        spec=ResourceClaimSpec(device_requests=[
            DeviceRequest(name="req-0",
                          device_class_name="test-class")])))
    p = mkpod("p", claim_name="c")
    hub.create_pod(p)
    sched.run_until_idle()
    assert bound(hub, p) == "n2"


def test_allocation_mode_all():
    hub = Hub()
    sched = mksched(hub)
    hub.create_node(mknode("n1"))
    hub.create_resource_slice(mkslice("n1", [
        mkdevice(f"d{i}", cls="test-class") for i in range(3)]))
    hub.create_resource_claim(ResourceClaim(
        metadata=ObjectMeta(name="all-claim"),
        spec=ResourceClaimSpec(device_requests=[
            DeviceRequest(name="req-0", device_class_name="test-class",
                          allocation_mode=ALLOCATION_MODE_ALL)])))
    p = mkpod("p", claim_name="all-claim")
    hub.create_pod(p)
    sched.run_until_idle()
    assert bound(hub, p) == "n1"
    alloc = hub.get_resource_claim("default",
                                   "all-claim").status.allocation
    assert sorted(d.device for d in alloc.devices) == ["d0", "d1", "d2"]
    # the node's devices are exhausted: a second exact-count claim parks
    hub.create_resource_claim(ResourceClaim(
        metadata=ObjectMeta(name="late"),
        spec=ResourceClaimSpec(device_requests=[
            DeviceRequest(name="r", device_class_name="test-class")])))
    p2 = mkpod("p2", claim_name="late")
    hub.create_pod(p2)
    sched.run_until_idle()
    assert bound(hub, p2) in ("", None)


def test_first_available_prioritized_list():
    """resourceclaimtemplate-first-available.yaml: sub-0 names a class
    with no devices, sub-1 matches — the allocation uses sub-1."""
    hub = Hub()
    sched = mksched(hub)
    hub.create_node(mknode("n1"))
    hub.create_resource_slice(mkslice("n1", [
        mkdevice("d0", cls="test-class")]))
    hub.create_resource_claim(ResourceClaim(
        metadata=ObjectMeta(name="fa"),
        spec=ResourceClaimSpec(device_requests=[
            DeviceRequest(name="req-0", first_available=[
                DeviceSubRequest(name="sub-0",
                                 device_class_name="no-such-class"),
                DeviceSubRequest(name="sub-1",
                                 device_class_name="test-class")])])))
    p = mkpod("p", claim_name="fa")
    hub.create_pod(p)
    sched.run_until_idle()
    assert bound(hub, p) == "n1"
    alloc = hub.get_resource_claim("default", "fa").status.allocation
    assert alloc.devices[0].request == "req-0/sub-1"
    assert alloc.devices[0].device == "d0"


def test_match_attribute_constraint():
    """resourceclaimtemplate-for-two-devices.yaml: two devices whose
    'dra.example.com/slice' attribute must match — n1 mixes slices, n2
    has a matched pair."""
    hub = Hub()
    sched = mksched(hub)
    hub.create_node(mknode("n1"))
    hub.create_node(mknode("n2"))
    hub.create_resource_slice(mkslice("n1", [
        mkdevice("a", cls="test-class",
                 **{"dra.example.com/slice": 1}),
        mkdevice("b", cls="test-class",
                 **{"dra.example.com/slice": 2})]))
    hub.create_resource_slice(mkslice("n2", [
        mkdevice("c", cls="test-class",
                 **{"dra.example.com/slice": 3}),
        mkdevice("d", cls="test-class",
                 **{"dra.example.com/slice": 3})]))
    hub.create_resource_claim(ResourceClaim(
        metadata=ObjectMeta(name="pair"),
        spec=ResourceClaimSpec(
            device_requests=[DeviceRequest(
                name="req-0", device_class_name="test-class", count=2)],
            constraints=[DeviceConstraint(
                requests=["req-0"],
                match_attribute="dra.example.com/slice")])))
    p = mkpod("p", claim_name="pair")
    hub.create_pod(p)
    sched.run_until_idle()
    assert bound(hub, p) == "n2"
    alloc = hub.get_resource_claim("default", "pair").status.allocation
    assert sorted(d.device for d in alloc.devices) == ["c", "d"]


def test_match_attribute_anchor_backtracking():
    """[A, B, B] with count=2 and a matchAttribute constraint: a greedy
    first pick would lock A and fail; the allocator must anchor on B."""
    hub = Hub()
    sched = mksched(hub)
    hub.create_node(mknode("n1"))
    hub.create_resource_slice(mkslice("n1", [
        mkdevice("a", cls="test-class", numa="A"),
        mkdevice("b1", cls="test-class", numa="B"),
        mkdevice("b2", cls="test-class", numa="B")]))
    hub.create_resource_claim(ResourceClaim(
        metadata=ObjectMeta(name="pair"),
        spec=ResourceClaimSpec(
            device_requests=[DeviceRequest(
                name="req-0", device_class_name="test-class", count=2)],
            constraints=[DeviceConstraint(
                requests=["req-0"], match_attribute="numa")])))
    p = mkpod("p", claim_name="pair")
    hub.create_pod(p)
    sched.run_until_idle()
    assert bound(hub, p) == "n1"
    alloc = hub.get_resource_claim("default", "pair").status.allocation
    assert sorted(d.device for d in alloc.devices) == ["b1", "b2"]


def test_constraint_binds_first_available_subrequests():
    """A constraint naming the PARENT request binds every firstAvailable
    subrequest's picks."""
    hub = Hub()
    sched = mksched(hub)
    hub.create_node(mknode("n1"))
    hub.create_resource_slice(mkslice("n1", [
        mkdevice("a", cls="test-class", numa="A"),
        mkdevice("b1", cls="test-class", numa="B"),
        mkdevice("b2", cls="test-class", numa="B")]))
    hub.create_resource_claim(ResourceClaim(
        metadata=ObjectMeta(name="fa-pair"),
        spec=ResourceClaimSpec(
            device_requests=[DeviceRequest(name="req-0", first_available=[
                DeviceSubRequest(name="sub-0",
                                 device_class_name="no-such-class",
                                 count=2),
                DeviceSubRequest(name="sub-1",
                                 device_class_name="test-class",
                                 count=2)])],
            constraints=[DeviceConstraint(
                requests=["req-0"], match_attribute="numa")])))
    p = mkpod("p", claim_name="fa-pair")
    hub.create_pod(p)
    sched.run_until_idle()
    assert bound(hub, p) == "n1"
    alloc = hub.get_resource_claim("default", "fa-pair").status.allocation
    assert sorted(d.device for d in alloc.devices) == ["b1", "b2"]
    assert all(d.request == "req-0/sub-1" for d in alloc.devices)


def test_template_created_after_pod_still_materializes():
    """The reference controller retries via its workqueue; ours re-stamps
    waiting pods from the template watch."""
    hub = Hub()
    ResourceClaimController(hub)
    clock = [1000.0]
    cfg = default_config()
    cfg.batch_size = 16
    sched = Scheduler(hub, cfg, caps=Capacities(nodes=16, pods=64),
                      now=lambda: clock[0])
    hub.create_node(mknode("accel"))
    hub.create_resource_slice(mkslice(
        "accel", [mkdevice("d0", cls="test-class")]))
    p = mkpod("late", template_name="late-template")
    hub.create_pod(p)
    sched.run_until_idle()
    assert bound(hub, p) in ("", None)      # no template yet
    hub.create_resource_claim_template(ResourceClaimTemplate(
        metadata=ObjectMeta(name="late-template"),
        spec=ResourceClaimSpec(device_requests=[
            DeviceRequest(name="req-0", device_class_name="test-class")])))
    for _ in range(4):
        sched.run_until_idle()
        clock[0] += 3.0
        sched.queue.flush_backoff_completed()
    sched.run_until_idle()
    assert bound(hub, p) == "accel"


def test_admin_access_ignores_and_leaves_in_use():
    """An adminAccess request allocates an already-allocated device and
    does not block normal allocation of it."""
    hub = Hub()
    sched = mksched(hub)
    hub.create_node(mknode("n1"))
    hub.create_resource_slice(mkslice("n1", [
        mkdevice("d0", cls="test-class")]))
    hub.create_resource_claim(ResourceClaim(
        metadata=ObjectMeta(name="admin"),
        spec=ResourceClaimSpec(device_requests=[
            DeviceRequest(name="monitor", device_class_name="test-class",
                          admin_access=True)])))
    hub.create_resource_claim(ResourceClaim(
        metadata=ObjectMeta(name="normal"),
        spec=ResourceClaimSpec(device_requests=[
            DeviceRequest(name="use", device_class_name="test-class")])))
    pa = mkpod("pa", claim_name="admin")
    pb = mkpod("pb", claim_name="normal")
    hub.create_pod(pa)
    hub.create_pod(pb)
    sched.run_until_idle()
    assert bound(hub, pa) == "n1"
    assert bound(hub, pb) == "n1"    # admin allocation didn't consume d0
    admin_alloc = hub.get_resource_claim("default",
                                         "admin").status.allocation
    assert admin_alloc.devices[0].admin_access


def test_ledger_tracks_claim_lifecycle():
    """The incremental ledger replaces the O(claims) rescan: allocations
    appear on claim update, vanish on claim delete, and the freed device
    is immediately allocatable."""
    hub = Hub()
    clock = [1000.0]
    cfg = default_config()
    cfg.batch_size = 16
    sched = Scheduler(hub, cfg, caps=Capacities(nodes=16, pods=64),
                      now=lambda: clock[0])
    plugin = sched.framework.instance("DynamicResources")
    hub.create_node(mknode("n1"))
    hub.create_resource_slice(mkslice("n1", [
        mkdevice("d0", cls="test-class")]))
    hub.create_resource_claim(ResourceClaim(
        metadata=ObjectMeta(name="c1"),
        spec=ResourceClaimSpec(device_requests=[
            DeviceRequest(name="r", device_class_name="test-class")])))
    p1 = mkpod("p1", claim_name="c1")
    hub.create_pod(p1)
    sched.run_until_idle()
    assert bound(hub, p1) == "n1"
    assert (DRIVER, "n1", "d0") in plugin._in_use_view(set())
    # a second claim for the same single device parks
    hub.create_resource_claim(ResourceClaim(
        metadata=ObjectMeta(name="c2"),
        spec=ResourceClaimSpec(device_requests=[
            DeviceRequest(name="r", device_class_name="test-class")])))
    p2 = mkpod("p2", claim_name="c2")
    hub.create_pod(p2)
    sched.run_until_idle()
    assert bound(hub, p2) in ("", None)
    # deleting the first claim frees the device and requeues p2
    claim = hub.get_resource_claim("default", "c1")
    hub.delete_resource_claim(claim.metadata.uid)
    assert (DRIVER, "n1", "d0") not in plugin._in_use_view(set())
    for _ in range(4):
        sched.run_until_idle()
        clock[0] += 3.0
        sched.queue.flush_backoff_completed()
    sched.run_until_idle()
    assert bound(hub, p2) == "n1"
