"""Golden parity: device filter/score kernels vs the host-side oracles.

Mirrors the reference's plugin unit-test tables (fit_test.go,
taint_toleration_test.go, node_affinity_test.go...) — each case builds real
objects, packs them through the Mirror, runs the JAX kernel over all nodes,
and compares with the exact host-semantics implementation."""

import jax
import numpy as np
import pytest

from kubernetes_tpu.api.labels import (
    find_untolerated_taint,
    pod_matches_node_selector_and_affinity,
)
from kubernetes_tpu.api.objects import (
    Affinity,
    Container,
    ContainerImage,
    ContainerPort,
    Node,
    NodeAffinity,
    NodeSelector,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    PreferredSchedulingTerm,
    ResourceRequirements,
    Taint,
    Toleration,
)
from kubernetes_tpu.backend.cache import Cache
from kubernetes_tpu.backend.mirror import Mirror
from kubernetes_tpu.backend.snapshot import Snapshot
from kubernetes_tpu.ops import filters as _OF
from kubernetes_tpu.ops import scores as _OS
from kubernetes_tpu.ops.features import Capacities


class _Jitted:
    """Jit-wrap every kernel so the 29 parity cases share compiled code
    (same Capacities -> same shapes -> one compile per kernel)."""

    def __init__(self, mod):
        self._mod = mod
        self._cache = {}

    def __getattr__(self, name):
        fn = self._cache.get(name)
        if fn is None:
            fn = self._cache[name] = jax.jit(getattr(self._mod, name))
        return fn


OF = _Jitted(_OF)
OS = _Jitted(_OS)


def mknode(name, cpu="4", mem="8Gi", labels=None, taints=None, unsched=False,
           images=None):
    return Node(
        metadata=ObjectMeta(name=name, labels=labels or {}),
        spec=NodeSpec(unschedulable=unsched, taints=taints or []),
        status=NodeStatus(
            allocatable={"cpu": cpu, "memory": mem, "pods": "110"},
            images=[ContainerImage(names=[n], size_bytes=s) for n, s in (images or [])],
        ),
    )


def mkpod(name, cpu="0", mem="0", **kw):
    requests = {}
    if cpu != "0":
        requests["cpu"] = cpu
    if mem != "0":
        requests["memory"] = mem
    ports = [ContainerPort(host_port=p, protocol=proto, host_ip=ip)
             for ip, proto, p in kw.pop("host_ports", [])]
    image = kw.pop("image", "")
    return Pod(
        metadata=ObjectMeta(name=name, labels=kw.pop("labels", {})),
        spec=PodSpec(
            containers=[Container(resources=ResourceRequirements(requests=requests),
                                  ports=ports, image=image)],
            **kw,
        ),
    )


class Rig:
    """cache -> snapshot -> mirror -> device tensors, one call."""

    def __init__(self, nodes, scheduled=None):
        self.cache = Cache()
        for n in nodes:
            self.cache.add_node(n)
        for p in scheduled or []:
            self.cache.add_pod(p)
        self.snap = Snapshot()
        self.cache.update_snapshot(self.snap)
        self.mirror = Mirror(caps=Capacities(nodes=16, pods=64, vocab=1024))
        self.mirror.sync(self.snap)
        self.ct = self.mirror.to_device()
        self.names = [ni.name for ni in self.snap.node_info_list]
        self.rows = [self.mirror.row_of(n) for n in self.names]

    def pod_features(self, pod):
        return self.mirror.pack_batch([pod], 1)

    def mask_by_name(self, device_mask):
        m = np.asarray(device_mask)
        return {name: bool(m[row]) for name, row in zip(self.names, self.rows)}


def unbatch(pf):
    import jax
    return jax.tree.map(lambda x: x[0], pf)


def test_fit_parity():
    nodes = [mknode("big", cpu="8", mem="16Gi"), mknode("small", cpu="1", mem="1Gi")]
    rig = Rig(nodes, scheduled=[mkpod("busy", cpu="500m", mem="512Mi",
                                      node_name="small")])
    pod = mkpod("p", cpu="600m", mem="256Mi")
    pf = unbatch(rig.pod_features(pod))
    ok, unresolvable = OF.resources_fit(rig.ct, pf)
    got = rig.mask_by_name(ok)
    assert got == {"big": True, "small": False}
    # 600m > 1000m-500m on small but 600m < 1000m allocatable -> resolvable
    assert not rig.mask_by_name(unresolvable)["small"]
    # a pod requesting more than allocatable anywhere is unresolvable there
    giant = unbatch(rig.pod_features(mkpod("g", cpu="32")))
    ok2, unres2 = OF.resources_fit(rig.ct, giant)
    assert not any(rig.mask_by_name(ok2).values())
    assert all(rig.mask_by_name(unres2).values())


def test_node_name_parity():
    rig = Rig([mknode("a"), mknode("b")])
    pf = unbatch(rig.pod_features(mkpod("p", node_name="")))
    assert all(rig.mask_by_name(OF.node_name(rig.ct, pf)).values())
    pf = unbatch(rig.pod_features(mkpod("p2", node_name="b")))
    assert rig.mask_by_name(OF.node_name(rig.ct, pf)) == {"a": False, "b": True}


def test_unschedulable_parity():
    rig = Rig([mknode("ok"), mknode("cordoned", unsched=True)])
    wk = rig.mirror.well_known()
    pf = unbatch(rig.pod_features(mkpod("p")))
    got = rig.mask_by_name(
        OF.node_unschedulable(rig.ct, pf, wk["unschedulable_taint_key"]))
    assert got == {"ok": True, "cordoned": False}
    # toleration lets it through
    tol = mkpod("p2", tolerations=[Toleration(
        key="node.kubernetes.io/unschedulable", operator="Exists",
        effect="NoSchedule")])
    pf = unbatch(rig.pod_features(tol))
    got = rig.mask_by_name(
        OF.node_unschedulable(rig.ct, pf, wk["unschedulable_taint_key"]))
    assert got == {"ok": True, "cordoned": True}


TAINT_CASES = [
    ([], [], True),
    ([Taint("gpu", "true", "NoSchedule")], [], False),
    ([Taint("gpu", "true", "NoSchedule")],
     [Toleration(key="gpu", operator="Equal", value="true", effect="NoSchedule")],
     True),
    ([Taint("gpu", "true", "NoSchedule")],
     [Toleration(key="gpu", operator="Equal", value="false", effect="NoSchedule")],
     False),
    ([Taint("gpu", "true", "NoSchedule")],
     [Toleration(key="gpu", operator="Exists")], True),
    ([Taint("gpu", "true", "NoSchedule")], [Toleration(operator="Exists")], True),
    ([Taint("soft", effect="PreferNoSchedule")], [], True),  # soft taint passes filter
    ([Taint("evict", "x", "NoExecute")], [], False),
    ([Taint("a", effect="NoSchedule"), Taint("b", effect="NoSchedule")],
     [Toleration(key="a", operator="Exists", effect="NoSchedule")], False),
    # malformed object: unrecognized effect string must pack without error
    # and be ignored by the filter (the reference tolerates arbitrary strings)
    ([Taint("weird", "x", "SomeFutureEffect")], [], True),
]


@pytest.mark.parametrize("taints,tols,want", TAINT_CASES)
def test_taint_toleration_parity(taints, tols, want):
    rig = Rig([mknode("n", taints=taints)])
    pf = unbatch(rig.pod_features(mkpod("p", tolerations=tols)))
    got = rig.mask_by_name(OF.taint_toleration(rig.ct, pf))["n"]
    oracle = find_untolerated_taint(taints, tols) is None
    assert got == oracle == want


def _affinity_pod(terms=None, node_selector=None, preferred=None):
    aff = None
    if terms is not None or preferred is not None:
        aff = Affinity(node_affinity=NodeAffinity(
            required=NodeSelector(node_selector_terms=terms) if terms else None,
            preferred=preferred or []))
    return mkpod("p", node_selector=node_selector or {}, affinity=aff)


AFFINITY_NODES = [
    mknode("ssd-east", labels={"disk": "ssd", "zone": "east", "cpus": "32"}),
    mknode("hdd-west", labels={"disk": "hdd", "zone": "west", "cpus": "8"}),
    mknode("bare", labels={}),
]

AFFINITY_PODS = [
    _affinity_pod(),                                        # no constraints
    _affinity_pod(node_selector={"disk": "ssd"}),
    _affinity_pod(terms=[NodeSelectorTerm(match_expressions=[
        NodeSelectorRequirement("zone", "In", ["east", "north"])])]),
    _affinity_pod(terms=[NodeSelectorTerm(match_expressions=[
        NodeSelectorRequirement("disk", "NotIn", ["hdd"])])]),
    _affinity_pod(terms=[NodeSelectorTerm(match_expressions=[
        NodeSelectorRequirement("cpus", "Gt", ["16"])])]),
    _affinity_pod(terms=[NodeSelectorTerm(match_expressions=[
        NodeSelectorRequirement("cpus", "Lt", ["16"])])]),
    _affinity_pod(terms=[NodeSelectorTerm(match_expressions=[
        NodeSelectorRequirement("disk", "Exists")])]),
    _affinity_pod(terms=[NodeSelectorTerm(match_expressions=[
        NodeSelectorRequirement("disk", "DoesNotExist")])]),
    # OR of two terms
    _affinity_pod(terms=[
        NodeSelectorTerm(match_expressions=[
            NodeSelectorRequirement("zone", "In", ["west"])]),
        NodeSelectorTerm(match_expressions=[
            NodeSelectorRequirement("disk", "In", ["ssd"])]),
    ]),
    # AND within a term
    _affinity_pod(terms=[NodeSelectorTerm(match_expressions=[
        NodeSelectorRequirement("disk", "In", ["ssd"]),
        NodeSelectorRequirement("zone", "In", ["west"])])]),
    # matchFields on metadata.name
    _affinity_pod(terms=[NodeSelectorTerm(match_fields=[
        NodeSelectorRequirement("metadata.name", "In", ["bare"])])]),
    # nodeSelector AND affinity together
    _affinity_pod(node_selector={"zone": "east"},
                  terms=[NodeSelectorTerm(match_expressions=[
                      NodeSelectorRequirement("disk", "In", ["ssd", "hdd"])])]),
]


@pytest.mark.parametrize("pod", AFFINITY_PODS)
def test_node_affinity_parity(pod):
    rig = Rig(AFFINITY_NODES)
    pf = unbatch(rig.pod_features(pod))
    got = rig.mask_by_name(OF.node_affinity(rig.ct, pf))
    for node in AFFINITY_NODES:
        oracle = pod_matches_node_selector_and_affinity(pod, node)
        assert got[node.name] == oracle, (
            f"node {node.name}: device={got[node.name]} oracle={oracle}")


def test_node_ports_parity():
    busy = mkpod("busy", node_name="n1", host_ports=[("", "TCP", 8080)])
    busy2 = mkpod("busy2", node_name="n2", host_ports=[("10.0.0.1", "TCP", 9000)])
    rig = Rig([mknode("n1"), mknode("n2"), mknode("n3")], scheduled=[busy, busy2])
    wk = rig.mirror.well_known()

    pf = unbatch(rig.pod_features(mkpod("p", host_ports=[("", "TCP", 8080)])))
    got = rig.mask_by_name(OF.node_ports(rig.ct, pf, wk["wildcard_ip"]))
    assert got == {"n1": False, "n2": True, "n3": True}

    # wildcard vs specific-ip clash
    pf = unbatch(rig.pod_features(mkpod("p2", host_ports=[("", "TCP", 9000)])))
    got = rig.mask_by_name(OF.node_ports(rig.ct, pf, wk["wildcard_ip"]))
    assert got == {"n1": True, "n2": False, "n3": True}

    # different protocol is fine
    pf = unbatch(rig.pod_features(mkpod("p3", host_ports=[("", "UDP", 8080)])))
    assert all(rig.mask_by_name(OF.node_ports(rig.ct, pf, wk["wildcard_ip"])).values())


def test_least_most_balanced_scores():
    rig = Rig([mknode("empty", cpu="10", mem="10Gi"),
               mknode("half", cpu="10", mem="10Gi")],
              scheduled=[mkpod("busy", cpu="5", mem="5Gi", node_name="half")])
    pod = mkpod("p", cpu="1", mem="1Gi")
    pf = unbatch(rig.pod_features(pod))
    least = rig.mask_by_name_float(OS.least_allocated(rig.ct, pf)) \
        if hasattr(rig, "mask_by_name_float") else None
    s = np.asarray(OS.least_allocated(rig.ct, pf))
    by = {n: s[r] for n, r in zip(rig.names, rig.rows)}
    # empty node: frac = (100m? no: 1000m/10000m)=0.1, mem 1/10 -> least = 90
    assert by["empty"] > by["half"]
    assert abs(by["empty"] - 90.0) < 1.0
    s = np.asarray(OS.most_allocated(rig.ct, pf))
    by = {n: s[r] for n, r in zip(rig.names, rig.rows)}
    assert by["half"] > by["empty"]
    # balanced: both fractions equal on each node -> std 0 -> 100 for both
    s = np.asarray(OS.balanced_allocation(rig.ct, pf))
    by = {n: s[r] for n, r in zip(rig.names, rig.rows)}
    assert abs(by["empty"] - 100.0) < 0.5 and abs(by["half"] - 100.0) < 0.5


def test_preferred_node_affinity_score():
    rig = Rig(AFFINITY_NODES)
    pod = _affinity_pod(preferred=[
        PreferredSchedulingTerm(weight=5, preference=NodeSelectorTerm(
            match_expressions=[NodeSelectorRequirement("disk", "In", ["ssd"])])),
        PreferredSchedulingTerm(weight=2, preference=NodeSelectorTerm(
            match_expressions=[NodeSelectorRequirement("zone", "Exists")])),
    ])
    pf = unbatch(rig.pod_features(pod))
    s = np.asarray(OS.node_affinity_score(rig.ct, pf))
    by = {n: s[r] for n, r in zip(rig.names, rig.rows)}
    assert by == {"ssd-east": 7.0, "hdd-west": 2.0, "bare": 0.0}


def test_taint_toleration_score():
    rig = Rig([mknode("clean"), mknode("soft", taints=[
        Taint("a", effect="PreferNoSchedule"), Taint("b", effect="PreferNoSchedule")])])
    pf = unbatch(rig.pod_features(mkpod("p")))
    s = np.asarray(OS.taint_toleration_score(rig.ct, pf))
    by = {n: s[r] for n, r in zip(rig.names, rig.rows)}
    assert by == {"clean": 0.0, "soft": 2.0}


def test_image_locality_score():
    import jax.numpy as jnp
    big = 800 * 1024 * 1024
    rig = Rig([mknode("has", images=[("redis:7", big)]), mknode("not")])
    pf = unbatch(rig.pod_features(mkpod("p", image="redis:7")))
    s = np.asarray(OS.image_locality(rig.ct, pf, jnp.int32(2)))
    by = {n: s[r] for n, r in zip(rig.names, rig.rows)}
    assert by["has"] > by["not"] == 0.0


# suite-tier discipline (tests/test_markers.py): area marker
pytestmark = pytest.mark.core
