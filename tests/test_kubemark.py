"""Kubemark hollow-node feeder end-to-end ACROSS PROCESSES: a real
`python -m kubernetes_tpu.kubemark` subprocess registers nodes over the
HTTP hub and acks bindings; the scheduler (through its own RemoteHub
client) schedules a daemonset-shaped wave onto them
(pkg/kubemark/hollow_kubelet.go:63, cmd/kubemark/hollow-node.go)."""

import subprocess
import sys
import time

from kubernetes_tpu.config.types import default_config
from kubernetes_tpu.hub import Hub
from kubernetes_tpu.hubclient import RemoteHub
from kubernetes_tpu.hubserver import HubServer
from kubernetes_tpu.ops.features import Capacities
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.testing import MakePod

N_NODES = 50
N_PODS = 100


def test_hollow_nodes_feed_scheduler_across_processes():
    hub = Hub()
    server = HubServer(hub).start()
    feeder = None
    client = None
    sched = None
    try:
        feeder = subprocess.Popen(
            [sys.executable, "-m", "kubernetes_tpu.kubemark",
             "--hub", server.address,
             "--nodes", str(N_NODES), "--zones", "4",
             "--heartbeat", "0.5"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        # wait for the feeder's nodes to land in the hub
        deadline = time.time() + 30
        while time.time() < deadline:
            if len(hub.list_nodes()) >= N_NODES:
                break
            time.sleep(0.1)
        assert len(hub.list_nodes()) == N_NODES, \
            "the external feeder must register every hollow node"

        client = RemoteHub(server.address)
        cfg = default_config()
        cfg.batch_size = 64
        sched = Scheduler(client, cfg,
                          caps=Capacities(nodes=64, pods=256))
        pods = [MakePod().name(f"w-{i}").req(cpu="100m").obj()
                for i in range(N_PODS)]
        for p in pods:
            client.create_pod(p)
        # drain with real time: the feeder's concurrent acks/heartbeats
        # race the drain, and transient conflicts retry through backoff
        deadline = time.time() + 60
        while time.time() < deadline:
            sched.run_until_idle()
            sched.queue.flush_backoff_completed()
            placed = [hub.get_pod(p.metadata.uid) for p in pods]
            if all(s.spec.node_name for s in placed):
                break
            time.sleep(0.3)
        unplaced = [s.metadata.name for s in placed
                    if not s.spec.node_name]
        assert not unplaced, f"unscheduled: {unplaced[:5]}..."
        assert all(s.spec.node_name.startswith("hollow-")
                   for s in placed)
        # ... and the feeder ACKED each binding: phase driven to Running
        # by the external process (the kubelet half of the contract)
        deadline = time.time() + 30
        while time.time() < deadline:
            running = sum(1 for p in pods
                          if hub.get_pod(p.metadata.uid).status.phase
                          == "Running")
            if running == N_PODS:
                break
            time.sleep(0.2)
        assert running == N_PODS, \
            f"feeder acked only {running}/{N_PODS} bindings"
        # heartbeats flow: some node carries a recent heartbeat stamp
        hb = [n for n in hub.list_nodes()
              if "kubemark.alpha/heartbeat" in n.metadata.annotations]
        assert hb, "heartbeat updates must reach the hub"
    finally:
        if sched is not None:
            sched.close()
        if client is not None:
            client.close()
        if feeder is not None:
            feeder.terminate()
            feeder.wait(timeout=10)
        server.stop()


# suite-tier discipline (tests/test_markers.py): area marker
import pytest  # noqa: E402
pytestmark = pytest.mark.fabric
