"""Framework runtime: MultiPoint expansion, weights, gates, device config.

Mirrors the reference's framework runtime tests (runtime/framework_test.go:
multipoint expansion order, override semantics, scorePluginWeight) and
schedulinggates/queuesort plugin tests."""

import numpy as np

from kubernetes_tpu.api.objects import (
    ObjectMeta,
    Pod,
    PodSchedulingGate,
    PodSpec,
)
from kubernetes_tpu.config.types import (
    Plugin,
    PluginSet,
    default_config,
    default_plugins,
)
from kubernetes_tpu.config.validation import validate_config
from kubernetes_tpu.framework.runtime import Framework
from kubernetes_tpu.models.pipeline import FILTER_PLUGINS
from kubernetes_tpu.plugins.registry import in_tree_registry


def mkfw(mutate=None) -> Framework:
    cfg = default_config()
    if mutate:
        mutate(cfg.profiles[0])
    return Framework(cfg.profiles[0])


def test_default_expansion():
    fw = mkfw()
    assert [n for n, _ in fw.points["filter"]] == [
        "NodeUnschedulable", "NodeName", "TaintToleration", "NodeAffinity",
        "NodePorts", "NodeResourcesFit", "VolumeRestrictions",
        "NodeVolumeLimits", "VolumeBinding", "VolumeZone",
        "DynamicResources", "PodTopologySpread", "InterPodAffinity",
        "GangScheduling"]
    scores = dict(fw.points["score"])
    assert scores["TaintToleration"] == 3
    assert scores["NodeAffinity"] == 2
    assert scores["NodeResourcesFit"] == 1
    assert scores["PodTopologySpread"] == 2
    assert [n for n, _ in fw.points["pre_enqueue"]] == [
        "SchedulingGates", "DefaultPreemption"]
    assert [n for n, _ in fw.points["bind"]] == ["DefaultBinder"]


def test_disable_star_wipes_point():
    fw = mkfw(lambda p: setattr(p.plugins, "score",
                                PluginSet(disabled=[Plugin("*")])))
    assert fw.points["score"] == []
    # filters untouched (8 device + 4 volume + DynamicResources +
    # GangScheduling host)
    assert len(fw.points["filter"]) == 14


def test_disable_single_filter_reflected_in_device_flags():
    fw = mkfw(lambda p: setattr(p.plugins, "filter",
                                PluginSet(disabled=[Plugin("TaintToleration")])))
    flags = fw.enabled_filters()
    assert flags[FILTER_PLUGINS.index("TaintToleration")] is False
    assert sum(flags) == len(FILTER_PLUGINS) - 1
    # score for the same plugin remains enabled
    assert dict(fw.points["score"])["TaintToleration"] == 3


def test_explicit_weight_overrides_multipoint():
    fw = mkfw(lambda p: setattr(p.plugins, "score", PluginSet(
        enabled=[Plugin("NodeAffinity", 10)])))
    assert dict(fw.points["score"])["NodeAffinity"] == 10
    w = fw.score_weights()
    assert float(w.node_affinity) == 10.0
    assert float(w.taint_toleration) == 3.0


def test_scheduling_gates_pre_enqueue():
    fw = mkfw()
    gated = Pod(metadata=ObjectMeta(name="g"),
                spec=PodSpec(scheduling_gates=[PodSchedulingGate("corp/hold")]))
    s = fw.run_pre_enqueue_plugins(gated)
    assert s.is_rejected() and s.plugin == "SchedulingGates"
    assert fw.run_pre_enqueue_plugins(Pod()).is_success()


def test_queue_sort_priority_then_fifo():
    from types import SimpleNamespace

    fw = mkfw()
    hi = SimpleNamespace(pod=Pod(spec=PodSpec(priority=10)), timestamp=2.0)
    lo = SimpleNamespace(pod=Pod(spec=PodSpec(priority=1)), timestamp=1.0)
    assert fw.queue_sort_less(hi, lo)
    early = SimpleNamespace(pod=Pod(), timestamp=1.0)
    late = SimpleNamespace(pod=Pod(), timestamp=2.0)
    assert fw.queue_sort_less(early, late)


def test_events_to_register_union():
    fw = mkfw()
    ev = fw.events_to_register()
    assert "NodeResourcesFit" in ev and "InterPodAffinity" in ev
    assert "PrioritySort" not in ev  # no events registered


def test_validation():
    cfg = default_config()
    assert validate_config(cfg, in_tree_registry()) == []
    cfg.batch_size = 0
    cfg.profiles[0].plugins.filter.enabled.append(Plugin("NoSuchPlugin"))
    errs = validate_config(cfg, in_tree_registry())
    assert any("batch_size" in e for e in errs)
    assert any("NoSuchPlugin" in e for e in errs)


def test_disabled_filter_device_semantics():
    """Disabling TaintToleration on device: tainted node becomes feasible."""
    from kubernetes_tpu.api.objects import (
        Container, Node, NodeSpec, NodeStatus, ResourceRequirements, Taint)
    from kubernetes_tpu.backend.cache import Cache
    from kubernetes_tpu.backend.mirror import Mirror
    from kubernetes_tpu.backend.snapshot import Snapshot
    from kubernetes_tpu.models.pipeline import launch_batch
    from kubernetes_tpu.ops.features import Capacities

    caps = Capacities(nodes=16, pods=32)
    cache = Cache()
    cache.add_node(Node(
        metadata=ObjectMeta(name="t"),
        spec=NodeSpec(taints=[Taint(key="k", value="v", effect="NoSchedule")]),
        status=NodeStatus(allocatable={"cpu": "4", "memory": "8Gi",
                                       "pods": "110"})))
    snap = Snapshot()
    cache.update_snapshot(snap)
    mirror = Mirror(caps=caps)
    mirror.sync(snap)
    pod = Pod(metadata=ObjectMeta(name="p"), spec=PodSpec(containers=[
        Container(name="c", resources=ResourceRequirements(
            requests={"cpu": "1", "memory": "1Gi"}))]))

    fw_off = mkfw(lambda p: setattr(p.plugins, "filter",
                                    PluginSet(disabled=[Plugin("TaintToleration")])))
    spec = mirror.prepare_launch([pod], 4)
    out = launch_batch(spec, mirror.well_known(), fw_off.score_weights(),
                       caps, fw_off.enabled_filters())
    assert int(out.node_row[0]) == 0, "tainted node allowed when disabled"

    fw_on = mkfw()
    out2 = launch_batch(spec, mirror.well_known(), fw_on.score_weights(),
                        caps, fw_on.enabled_filters())
    assert int(out2.node_row[0]) == -1


def test_validation_deep():
    """validation.go parity: queue-sort uniformity, extender entries,
    scoring-strategy args, weight bounds."""
    from kubernetes_tpu.config.types import SchedulerProfile, default_plugins
    from kubernetes_tpu.extender import ExtenderConfig

    cfg = default_config()
    # queue-sort uniformity across profiles (profile.go:57): profile B
    # wipes PrioritySort from its queue_sort point, so the two profiles
    # resolve to different effective sort sets under MultiPoint expansion
    second = SchedulerProfile(scheduler_name="other",
                              plugins=default_plugins())
    second.plugins.queue_sort.disabled.append(Plugin("*"))
    cfg.profiles.append(second)
    errs = validate_config(cfg, in_tree_registry())
    assert any("queueSort" in e for e in errs)

    cfg = default_config()
    cfg.extenders.append(ExtenderConfig(url_prefix="", weight=-1,
                                        prioritize_verb="prioritize"))
    errs = validate_config(cfg)
    assert any("url_prefix" in e for e in errs)
    assert any("weight" in e for e in errs)
    # weight only matters with a prioritize verb (validation.go)
    cfg = default_config()
    cfg.extenders.append(ExtenderConfig(url_prefix="http://x",
                                        filter_verb="filter", weight=0))
    assert validate_config(cfg) == []

    cfg = default_config()
    cfg.profiles[0].plugin_config["NodeResourcesFit"] = {
        "scoring_strategy": {"type": "RequestedToCapacityRatio",
                             "requested_to_capacity_ratio": {"shape": [
                                 {"utilization": 80, "score": 5},
                                 {"utilization": 20, "score": 200},
                             ]}}}
    errs = validate_config(cfg, in_tree_registry())
    assert any("strictly increasing" in e for e in errs)
    assert any("not in [0, 10]" in e for e in errs)

    cfg = default_config()
    cfg.profiles[0].plugins.score.enabled.append(Plugin("ImageLocality", 500))
    errs = validate_config(cfg, in_tree_registry())
    assert any("weight > 100" in e for e in errs)
    cfg = default_config()
    cfg.binding_workers = 0
    assert any("binding_workers" in e for e in validate_config(cfg))


# suite-tier discipline (tests/test_markers.py): area marker
import pytest  # noqa: E402
pytestmark = pytest.mark.core
