"""Perf harness unit tests (tiny scales, fake-free real clock): op DSL
execution, collector windowing/percentiles, churn injection, threshold
verdicts — the rung the reference covers with scheduler_perf's own
integration-test label (misc/performance-config.yaml workloads labeled
integration-test run tiny through the same driver)."""

from kubernetes_tpu.perf.collector import ThroughputCollector, percentile
from kubernetes_tpu.perf.harness import (
    Churn,
    CreateNodes,
    CreatePods,
    Workload,
    run_workload,
)
from kubernetes_tpu.perf.workloads import (
    ALL_WORKLOADS,
    _anti_affinity_pod,
    _node,
    _pod,
    preemption_async,
    scheduling_basic,
)


def small(w: Workload) -> Workload:
    w.node_capacity = 64
    w.pod_capacity = 256
    w.batch_size = 16
    return w


def test_percentile_nearest_rank():
    vals = sorted([10.0, 20.0, 30.0, 40.0])
    assert percentile(vals, 50) == 20.0
    assert percentile(vals, 99) == 40.0
    assert percentile([], 50) == 0.0


def test_collector_windows():
    t = [0.0]
    col = ThroughputCollector({"a", "b", "c"}, now=lambda: t[0])
    col.begin()

    class P:
        def __init__(self, uid, node):
            self.metadata = type("M", (), {"uid": uid})()
            self.spec = type("S", (), {"node_name": node})()

    col.on_update(None, P("a", "n1"))
    t[0] = 0.5
    col.on_update(None, P("b", "n1"))
    t[0] = 1.5
    col.on_update(None, P("c", "n1"))
    assert col.done()
    s = col.summarize(end=2.0)
    assert s.pods_scheduled == 3
    assert s.windows == [2, 1]
    assert s.pods_per_sec == 3 / 2.0


def test_scheduling_basic_tiny():
    w = small(scheduling_basic(init_nodes=4, init_pods=2, measure_pods=10))
    r = run_workload(w)
    assert r["pods_scheduled"] == 10
    assert r["stats"]["scheduled"] == 12
    assert "vs_baseline" in r and "passed" in r


def test_all_workload_defs_have_thresholds():
    for factory in ALL_WORKLOADS:
        w = factory()
        assert w.threshold > 0
        assert w.ops, w.name


def test_preemption_tiny_evicts_and_schedules():
    # 2 nodes x 4 low-priority 900m fillers; churn interval so large no
    # churn pod fires; measured pods fit in the 400m leftover
    w = small(preemption_async(init_nodes=2, init_pods=8, measure_pods=4))
    r = run_workload(w)
    assert r["pods_scheduled"] == 4


def test_churn_injects_by_clock():
    # a churn op + measured pods that need the churn pod NOT to exist:
    # verify injection happens on the interval clock
    t = [1000.0]

    def now():
        return t[0]

    def sleep(dt):
        t[0] += dt

    w = small(Workload(
        name="churn-test", threshold=1,
        ops=[
            CreateNodes(2, _node),
            Churn([lambda i: _pod(f"c{i}")], interval_ms=100),
            CreatePods(5, lambda i: _pod(f"m-{i}"), collect_metrics=True),
        ]))
    r = run_workload(w, now=now, sleep=sleep)
    assert r["pods_scheduled"] == 5
    # time passed during the drain => at least one churn pod was created
    # (created beyond the 5 measured + any init)
    assert r["stats"]["attempts"] >= 5


def test_anti_affinity_workload_tiny():
    from kubernetes_tpu.perf.workloads import scheduling_pod_anti_affinity

    w = small(scheduling_pod_anti_affinity(
        init_nodes=6, init_pods=2, measure_pods=3))
    r = run_workload(w)
    # 6 hosts, 5 green pods with hostname anti-affinity: all schedule
    assert r["pods_scheduled"] == 3
    assert r["stats"]["unschedulable"] == 0


def test_anti_affinity_pod_template():
    p = _anti_affinity_pod(0, "sched-1")
    assert p.metadata.namespace == "sched-1"
    terms = p.spec.affinity.pod_anti_affinity.required
    assert terms[0].namespaces == ["sched-1", "sched-0"]


def test_unschedulable_workload_tiny():
    """Parked unschedulable churn pods must not block the measured flow."""
    from kubernetes_tpu.perf.workloads import unschedulable

    w = small(unschedulable(init_nodes=4, init_pods=2, measure_pods=10))
    r = run_workload(w)
    assert r["pods_scheduled"] == 10


def test_mixed_churn_workload_tiny():
    from kubernetes_tpu.perf.workloads import mixed_churn

    w = small(mixed_churn(init_nodes=4, measure_pods=10))
    r = run_workload(w)
    assert r["pods_scheduled"] == 10


def test_churn_recreate_keeps_one_alive():
    from kubernetes_tpu.hub import Hub
    from kubernetes_tpu.perf.harness import Churn, _ChurnState

    t = [1000.0]
    hub = Hub()
    st = _ChurnState(Churn([lambda i: _pod(f"c{i}")], interval_ms=100,
                           mode="recreate"), now=lambda: t[0])
    t[0] = 1000.55
    st.inject(hub, t[0])
    assert len(hub.list_pods()) == 1, "recreate keeps exactly one copy"


def test_daemonset_workload_tiny():
    from kubernetes_tpu.perf.workloads import scheduling_daemonset

    w = small(scheduling_daemonset(init_nodes=6, measure_pods=6))
    w.warm_full_nodes = False
    r = run_workload(w)
    assert r["pods_scheduled"] == 6
    # daemonset pinning: pod i landed exactly on node-i (matchFields)
    assert r["stats"]["scheduled"] == 6


def test_while_gated_workload_tiny():
    from kubernetes_tpu.perf.workloads import scheduling_while_gated

    w = small(scheduling_while_gated(gated_pods=8, measure_pods=10))
    r = run_workload(w)
    # measured pods all bound; gated pods parked, never scheduled
    assert r["pods_scheduled"] == 10
    assert r["stats"]["scheduled"] == 10
    assert r["stats"]["unschedulable"] == 0


def test_preferred_affinity_workloads_tiny():
    from kubernetes_tpu.perf.workloads import (
        preferred_pod_affinity,
        preferred_pod_anti_affinity,
    )

    for factory in (preferred_pod_affinity, preferred_pod_anti_affinity):
        w = small(factory(init_nodes=6, init_pods=2, measure_pods=8))
        r = run_workload(w)
        assert r["pods_scheduled"] == 8, w.name
        assert r["stats"]["unschedulable"] == 0


def test_ns_selector_anti_affinity_tiny():
    from kubernetes_tpu.perf.workloads import ns_selector_anti_affinity

    w = small(ns_selector_anti_affinity(init_nodes=8, init_pods=3,
                                        measure_pods=5, namespaces=2))
    w.warm_full_nodes = False
    r = run_workload(w)
    # hostname anti-affinity across ns-selected namespaces: all 8 pods
    # must land on distinct nodes
    assert r["pods_scheduled"] == 5
    assert r["stats"]["scheduled"] == 8


def test_bench_workload_names_in_sync():
    """bench.py names its subprocess workloads; they must be exactly
    workloads.BENCH_WORKLOADS (by function name) or a new bench workload
    silently never runs."""
    from kubernetes_tpu.perf.workloads import BENCH_WORKLOADS

    bench = _load_bench()
    assert tuple(bench.BENCH_WORKLOAD_FNS) == tuple(
        f.__name__ for f in BENCH_WORKLOADS)


def test_dra_steady_state_tiny():
    from kubernetes_tpu.perf.workloads import dra_steady_state

    w = small(dra_steady_state(init_nodes=4, measure_pods=6))
    r = run_workload(w)
    assert r["pods_scheduled"] == 6
    assert r["stats"]["unschedulable"] == 0


def test_dra_cel_in_tiny():
    """The CEL `in` membership variant: half the fleet's devices match
    the selector, every pod still places (device allocator path)."""
    from kubernetes_tpu.perf.workloads import dra_steady_state_cel_in

    w = small(dra_steady_state_cel_in(init_nodes=4, measure_pods=6))
    r = run_workload(w)
    assert r["pods_scheduled"] == 6
    assert r["stats"]["unschedulable"] == 0


def test_dra_multi_request_tiny():
    """The two-request claim variant: 3 devices per pod across a class
    match + an attribute selector, greedy multi-request walk."""
    from kubernetes_tpu.perf.workloads import dra_multi_request

    w = small(dra_multi_request(init_nodes=4, measure_pods=6))
    r = run_workload(w)
    assert r["pods_scheduled"] == 6
    assert r["stats"]["unschedulable"] == 0


def _load_bench():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    return bench


def test_profile_workload_names_in_sync():
    """bench.py --profile names its offender set; it must be exactly
    workloads.PROFILE_WORKLOADS or a profiled workload silently drops."""
    from kubernetes_tpu.perf.workloads import PROFILE_WORKLOADS

    bench = _load_bench()
    assert tuple(bench.PROFILE_WORKLOAD_FNS) == tuple(PROFILE_WORKLOADS)


def test_run_workload_profile_breakdown():
    """profile=True: the result carries the flight recorder's per-phase
    p50/p99 (incl. the dra_* views when DRA plugins ran) and the
    host-tail share — what bench.py --profile publishes per offender."""
    w = small(scheduling_basic(init_nodes=4, init_pods=2, measure_pods=10))
    r = run_workload(w, profile=True)
    fl = r["flight"]
    assert fl["enabled"] and fl["cycles_recorded"] >= 1
    for phase in ("queue_pop", "device_launch", "commit"):
        assert phase in fl["phases"], phase
        assert fl["phases"][phase]["count"] >= 1
        assert fl["phases"][phase]["p99_ms"] >= fl["phases"][phase]["p50_ms"]
    assert fl["plugins"], "per-plugin timings present"
    assert 0.0 <= fl["host_tail_share"] <= 1.0


def test_run_workload_cycle_times_capture():
    """cycle_times collects exact raw per-cycle durations (the
    --trace-overhead arms compare medians of these, not
    bucket-quantized histogram reads)."""
    w = small(scheduling_basic(init_nodes=4, init_pods=2, measure_pods=10))
    times = []
    r = run_workload(w, cycle_times=times)
    assert len(times) >= 1
    assert all(t >= 0.0 for t in times)
    assert r["pods_scheduled"] == 10


def test_qhints_variant_tiny():
    from kubernetes_tpu.perf.workloads import scheduling_basic_qhints

    w = small(scheduling_basic_qhints(init_nodes=4, init_pods=2,
                                      measure_pods=10))
    assert w.feature_gates == {"SchedulerQueueingHints": True}
    r = run_workload(w)
    assert r["pods_scheduled"] == 10


def test_preemption_async_enabled_variant_tiny():
    from kubernetes_tpu.perf.workloads import preemption_async_enabled

    w = small(preemption_async_enabled(init_nodes=2, init_pods=8,
                                       measure_pods=4))
    assert w.feature_gates == {"SchedulerAsyncPreemption": True}
    r = run_workload(w)
    assert r["pods_scheduled"] == 4


def test_ns_selector_preferred_anti_affinity_tiny():
    from kubernetes_tpu.perf.workloads import (
        ns_selector_preferred_anti_affinity,
    )

    w = small(ns_selector_preferred_anti_affinity(
        init_nodes=8, init_pods=3, measure_pods=5, namespaces=2))
    w.warm_full_nodes = False
    r = run_workload(w)
    # PREFERRED anti-affinity: soft avoidance only, everything schedules
    assert r["pods_scheduled"] == 5
    assert r["stats"]["unschedulable"] == 0


def test_gang_topology_packing_tiny():
    """The co-location workload's validate hook passes under the device
    packer: every gang lands in ONE zone (ISSUE-12 acceptance)."""
    from kubernetes_tpu.perf.workloads import gang_topology_packing

    w = small(gang_topology_packing(init_nodes=16, zones=4, gangs=3))
    w.batch_size = 64       # a gang unit must fit one pop batch
    r = run_workload(w)
    col = r["colocation"]
    assert col["gangs"] == 3
    assert col["mean_zone_spans"] == 1.0
    assert r["gangs"]["device_admitted"] == 3


def test_gang_topology_packing_validate_rejects_scatter():
    """The validate hook is a real gate: a scattered placement raises."""
    from kubernetes_tpu.api.objects import (
        LABEL_POD_GROUP,
        LABEL_ZONE,
    )
    from kubernetes_tpu.hub import Hub
    from kubernetes_tpu.perf.workloads import _colocation_validate
    from kubernetes_tpu.testing import MakeNode, MakePod

    hub = Hub()
    for i in range(4):
        n = MakeNode().name(f"n{i}").capacity(cpu="4", memory="8Gi",
                                              pods="10").obj()
        n.metadata.labels[LABEL_ZONE] = f"z{i}"
        hub.create_node(n)
    for i in range(4):
        p = MakePod().name(f"m{i}").req(cpu="100m").obj()
        p.metadata.labels[LABEL_POD_GROUP] = "scattered"
        hub.create_pod(p)
        hub.bind(p, f"n{i}")
    with pytest.raises(AssertionError):
        _colocation_validate(hub, {})


# suite-tier discipline (tests/test_markers.py): area marker
import pytest  # noqa: E402
pytestmark = pytest.mark.perf
