from kubernetes_tpu.api.objects import Container, Pod, PodSpec, ResourceRequirements
from kubernetes_tpu.api.resources import (
    DEFAULT_MEMORY_REQUEST,
    DEFAULT_MILLI_CPU_REQUEST,
    Resource,
    pod_request,
)


def ctr(cpu=None, mem=None, restart=None, **scalar):
    req = {}
    if cpu is not None:
        req["cpu"] = cpu
    if mem is not None:
        req["memory"] = mem
    req.update(scalar)
    return Container(resources=ResourceRequirements(requests=req), restart_policy=restart)


def test_from_map():
    r = Resource.from_map({"cpu": "2", "memory": "1Gi", "pods": "110",
                           "ephemeral-storage": "10Gi", "nvidia.com/gpu": "4"})
    assert r.milli_cpu == 2000
    assert r.memory == 2**30
    assert r.allowed_pod_number == 110
    assert r.ephemeral_storage == 10 * 2**30
    assert r.scalar == {"nvidia.com/gpu": 4}


def test_pod_request_sum_of_containers():
    pod = Pod(spec=PodSpec(containers=[ctr("100m", "1Gi"), ctr("200m", "2Gi")]))
    r = pod_request(pod)
    assert r.milli_cpu == 300
    assert r.memory == 3 * 2**30


def test_pod_request_init_max():
    # max(sum(app), max(init)): a big init container dominates
    pod = Pod(spec=PodSpec(
        containers=[ctr("100m", "1Gi")],
        init_containers=[ctr("500m", "512Mi"), ctr("2", "128Mi")],
    ))
    r = pod_request(pod)
    assert r.milli_cpu == 2000  # max init 2 cores > 100m app
    assert r.memory == 1 * 2**30  # app memory > either init


def test_pod_request_sidecars_accumulate():
    pod = Pod(spec=PodSpec(
        containers=[ctr("100m", "1Gi")],
        init_containers=[ctr("50m", "100Mi", restart="Always"), ctr("1", "1Gi")],
    ))
    r = pod_request(pod)
    # app 100m + sidecar 50m = 150m; init peak = 50m sidecar + 1000m = 1050m
    assert r.milli_cpu == 1050
    # memory: app 1Gi + 100Mi sidecar vs init peak 100Mi + 1Gi -> equal = 1Gi+100Mi
    assert r.memory == 2**30 + 100 * 2**20


def test_pod_request_overhead():
    pod = Pod(spec=PodSpec(containers=[ctr("100m", "1Gi")],
                           overhead={"cpu": "10m", "memory": "64Mi"}))
    r = pod_request(pod)
    assert r.milli_cpu == 110
    assert r.memory == 2**30 + 64 * 2**20


def test_non_zero_defaults():
    pod = Pod(spec=PodSpec(containers=[Container()]))
    assert pod_request(pod).is_zero()
    nz = pod_request(pod, non_zero=True)
    assert nz.milli_cpu == DEFAULT_MILLI_CPU_REQUEST
    assert nz.memory == DEFAULT_MEMORY_REQUEST


# suite-tier discipline (tests/test_markers.py): area marker
import pytest  # noqa: E402
pytestmark = pytest.mark.core
