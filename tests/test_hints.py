"""QueueingHintFn unit tests: QUEUE vs SKIP per plugin on targeted events
(reference: fit.go:265, node_affinity.go:95, taint_toleration.go:205,
interpodaffinity/plugin.go:92, podtopologyspread/plugin.go:160) + the
end-to-end effect: a non-helpful event leaves the pod parked."""

from kubernetes_tpu.api.objects import (
    Affinity,
    Container,
    LABEL_HOSTNAME,
    LABEL_ZONE,
    LabelSelector,
    Node,
    NodeAffinity,
    NodeSelector,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodAffinity,
    PodAffinityTerm,
    PodAntiAffinity,
    PodSpec,
    ResourceRequirements,
    Taint,
    Toleration,
    TopologySpreadConstraint,
)
from kubernetes_tpu.framework.interface import QueueingHint
from kubernetes_tpu.plugins.hints import (
    fit_hint,
    inter_pod_affinity_hint,
    node_affinity_hint,
    taint_toleration_hint,
    topology_spread_hint,
)

QUEUE, SKIP = QueueingHint.QUEUE, QueueingHint.SKIP


def mknode(name="n", cpu="4", labels=None, taints=None):
    return Node(metadata=ObjectMeta(name=name, labels=labels or {}),
                spec=NodeSpec(taints=taints or []),
                status=NodeStatus(allocatable={"cpu": cpu, "memory": "8Gi",
                                               "pods": "110"}))


def mkpod(name="p", cpu="1", labels=None, ns="default"):
    return Pod(metadata=ObjectMeta(name=name, namespace=ns,
                                   labels=labels or {}),
               spec=PodSpec(containers=[Container(
                   name="c", resources=ResourceRequirements(
                       requests={"cpu": cpu}))]))


def test_fit_hint_node_events():
    pod = mkpod(cpu="8")
    assert fit_hint(pod, None, mknode(cpu="16")) == QUEUE
    assert fit_hint(pod, None, mknode(cpu="2")) == SKIP, \
        "a too-small node cannot help"


def test_fit_hint_pod_deletion():
    pod = mkpod(cpu="2")
    scheduled = mkpod("dead", cpu="4")
    scheduled.spec.node_name = "n"
    assert fit_hint(pod, scheduled, None) == QUEUE, \
        "a scheduled pod's deletion frees capacity (incl. its pod slot)"
    pending = mkpod("never-ran", cpu="4")
    assert fit_hint(pod, pending, None) == SKIP, \
        "an unscheduled pod's deletion frees nothing (fit.go:273)"


def test_node_affinity_hint():
    pod = mkpod()
    pod.spec.affinity = Affinity(node_affinity=NodeAffinity(
        required=NodeSelector(node_selector_terms=[NodeSelectorTerm(
            match_expressions=[NodeSelectorRequirement(
                key=LABEL_ZONE, operator="In", values=["east"])])])))
    assert node_affinity_hint(
        pod, None, mknode(labels={LABEL_ZONE: "east"})) == QUEUE
    assert node_affinity_hint(
        pod, None, mknode(labels={LABEL_ZONE: "west"})) == SKIP


def test_taint_toleration_hint():
    pod = mkpod()
    tainted = mknode(taints=[Taint("dedicated", "infra", "NoSchedule")])
    assert taint_toleration_hint(pod, None, tainted) == SKIP
    assert taint_toleration_hint(pod, None, mknode()) == QUEUE
    pod.spec.tolerations = [Toleration(key="dedicated", operator="Equal",
                                       value="infra", effect="NoSchedule")]
    assert taint_toleration_hint(pod, None, tainted) == QUEUE


def test_inter_pod_affinity_hint():
    pod = mkpod()
    pod.spec.affinity = Affinity(pod_affinity=PodAffinity(required=[
        PodAffinityTerm(topology_key=LABEL_ZONE,
                        label_selector=LabelSelector(
                            match_labels={"app": "db"}))]))
    db = mkpod("db", labels={"app": "db"})
    web = mkpod("web", labels={"app": "web"})
    assert inter_pod_affinity_hint(pod, None, db) == QUEUE
    assert inter_pod_affinity_hint(pod, None, web) == SKIP
    # anti-affinity: only DELETIONS of matching pods help
    anti = mkpod()
    anti.spec.affinity = Affinity(pod_anti_affinity=PodAntiAffinity(
        required=[PodAffinityTerm(topology_key=LABEL_HOSTNAME,
                                  label_selector=LabelSelector(
                                      match_labels={"app": "db"}))]))
    assert inter_pod_affinity_hint(anti, db, None) == QUEUE
    assert inter_pod_affinity_hint(anti, web, None) == SKIP
    assert inter_pod_affinity_hint(anti, None, db) == SKIP, \
        "an ADDED matching pod cannot fix an anti-affinity rejection"
    # relabel OUT of the anti selector: QUEUE
    db2 = mkpod("db", labels={"app": "cache"})
    assert inter_pod_affinity_hint(anti, db, db2) == QUEUE
    # existing-pod anti-affinity relief: a term-less pending pod requeues
    # when a departing pod's anti selector could have matched IT
    plain = mkpod("plain", labels={"tier": "web"})
    blocker = mkpod("blocker", labels={"x": "y"})
    blocker.spec.affinity = Affinity(pod_anti_affinity=PodAntiAffinity(
        required=[PodAffinityTerm(topology_key=LABEL_HOSTNAME,
                                  label_selector=LabelSelector(
                                      match_labels={"tier": "web"}))]))
    assert inter_pod_affinity_hint(plain, blocker, None) == QUEUE
    # a departing blocker whose selector could NOT match us is noise
    unrelated_blocker = mkpod("ub", labels={"x": "y"})
    unrelated_blocker.spec.affinity = Affinity(
        pod_anti_affinity=PodAntiAffinity(
            required=[PodAffinityTerm(topology_key=LABEL_HOSTNAME,
                                      label_selector=LabelSelector(
                                          match_labels={"other": "app"}))]))
    assert inter_pod_affinity_hint(plain, unrelated_blocker, None) == SKIP
    assert inter_pod_affinity_hint(plain, web, None) == SKIP


def test_topology_spread_hint():
    pod = mkpod()
    pod.spec.topology_spread_constraints = [TopologySpreadConstraint(
        max_skew=1, topology_key=LABEL_ZONE,
        when_unsatisfiable="DoNotSchedule",
        label_selector=LabelSelector(match_labels={"app": "s"}))]
    match = mkpod("m", labels={"app": "s"})
    other = mkpod("o", labels={"app": "x"})
    foreign = mkpod("f", labels={"app": "s"}, ns="other")
    assert topology_spread_hint(pod, match, None) == QUEUE
    assert topology_spread_hint(pod, other, None) == SKIP
    assert topology_spread_hint(pod, foreign, None) == SKIP
    # node events: only nodes carrying the constraint's topology key matter
    assert topology_spread_hint(
        pod, None, mknode(labels={LABEL_ZONE: "z"})) == QUEUE
    assert topology_spread_hint(pod, None, mknode(labels={})) == SKIP


def test_end_to_end_unhelpful_node_stays_parked():
    """A rejected pod stays parked when the arriving node cannot help, and
    requeues when one can (the whole point of queueing hints)."""
    from kubernetes_tpu.config.types import default_config
    from kubernetes_tpu.hub import Hub
    from kubernetes_tpu.ops.features import Capacities
    from kubernetes_tpu.scheduler import Scheduler

    class Clock:
        t = 1000.0

        def now(self):
            return self.t

    hub = Hub()
    clock = Clock()
    cfg = default_config()
    cfg.batch_size = 16
    sched = Scheduler(hub, cfg, caps=Capacities(nodes=16, pods=64),
                      now=clock.now)
    hub.create_node(mknode("small", cpu="1"))
    big = mkpod("big", cpu="8")
    hub.create_pod(big)
    sched.run_until_idle()
    assert sched.queue.pending_counts()["unschedulable"] == 1
    # another too-small node arrives: fit_hint says SKIP -> still parked
    hub.create_node(mknode("small2", cpu="1"))
    assert sched.queue.pending_counts()["unschedulable"] == 1
    # a big node arrives: QUEUE -> moved out of the unschedulable pool
    hub.create_node(mknode("big-node", cpu="16"))
    assert sched.queue.pending_counts()["unschedulable"] == 0
    Clock.t += 2.0
    sched.queue.flush_backoff_completed()
    sched.run_until_idle()
    assert hub.get_pod(big.metadata.uid).spec.node_name == "big-node"


# --------------- volume / DRA / gates / ports hints ---------------


def test_node_ports_hint_conflicting_port_only():
    from kubernetes_tpu.api.objects import ContainerPort
    from kubernetes_tpu.plugins.hints import node_ports_hint

    pod = mkpod("want")
    pod.spec.containers[0].ports = [ContainerPort(
        container_port=80, host_port=8080, protocol="TCP")]
    holder = mkpod("holder")
    holder.spec.node_name = "n"
    holder.spec.containers[0].ports = [ContainerPort(
        container_port=80, host_port=8080, protocol="TCP")]
    assert node_ports_hint(pod, holder, None) == QUEUE
    unrelated = mkpod("unrelated")
    unrelated.spec.node_name = "n"
    unrelated.spec.containers[0].ports = [ContainerPort(
        container_port=80, host_port=9999, protocol="TCP")]
    assert node_ports_hint(pod, unrelated, None) == SKIP


def test_dra_hint_claim_scoping():
    from kubernetes_tpu.api.objects import (
        AllocationResult,
        PodResourceClaim,
        ResourceClaim,
    )
    from kubernetes_tpu.plugins.hints import dra_hint

    pod = mkpod("dra")
    pod.spec.resource_claims = [PodResourceClaim(
        name="accel", resource_claim_name="my-claim")]
    mine = ResourceClaim(metadata=ObjectMeta(name="my-claim"))
    theirs = ResourceClaim(metadata=ObjectMeta(name="someone-elses"))
    assert dra_hint(pod, None, mine) == QUEUE
    assert dra_hint(pod, None, theirs) == SKIP
    # any claim's deletion frees devices
    assert dra_hint(pod, theirs, None) == QUEUE
    # another claim DEALLOCATING frees devices too
    was = ResourceClaim(metadata=ObjectMeta(name="someone-elses"))
    was.status.allocation = AllocationResult(node_name="n")
    assert dra_hint(pod, was, theirs) == QUEUE


def test_volume_binding_hint_pvc_scoping():
    from kubernetes_tpu.api.objects import (
        PersistentVolumeClaim,
        PersistentVolumeClaimVolumeSource,
        Volume,
    )
    from kubernetes_tpu.plugins.hints import volume_binding_hint

    pod = mkpod("vol")
    pod.spec.volumes = [Volume(
        name="data", persistent_volume_claim=(
            PersistentVolumeClaimVolumeSource(claim_name="data")))]
    mine = PersistentVolumeClaim(metadata=ObjectMeta(name="data"))
    other = PersistentVolumeClaim(metadata=ObjectMeta(name="other"))
    foreign = PersistentVolumeClaim(metadata=ObjectMeta(name="data",
                                                        namespace="ns2"))
    assert volume_binding_hint(pod, None, mine) == QUEUE
    assert volume_binding_hint(pod, None, other) == SKIP
    assert volume_binding_hint(pod, None, foreign) == SKIP


def test_end_to_end_pvc_event_requeues_exactly_owner():
    """The VERDICT done-condition: a PVC event requeues exactly the
    parked pods it can help — the owner requeues, a stranger with a
    different claim stays parked."""
    from kubernetes_tpu.api.objects import (
        PersistentVolumeClaimVolumeSource,
        Volume,
    )
    from kubernetes_tpu.config.types import default_config
    from kubernetes_tpu.hub import Hub
    from kubernetes_tpu.ops.features import Capacities
    from kubernetes_tpu.scheduler import Scheduler

    hub = Hub()
    cfg = default_config()
    cfg.batch_size = 16
    clock = [1000.0]
    sched = Scheduler(hub, cfg, caps=Capacities(nodes=16, pods=64),
                      now=lambda: clock[0])
    hub.create_node(Node(
        metadata=ObjectMeta(name="n", labels={LABEL_HOSTNAME: "n"}),
        status=NodeStatus(allocatable={"cpu": "8", "memory": "16Gi",
                                       "pods": "110"})))

    def volpod(name, claim):
        p = mkpod(name)
        p.spec.volumes = [Volume(
            name=claim, persistent_volume_claim=(
                PersistentVolumeClaimVolumeSource(claim_name=claim)))]
        return p

    a = volpod("pod-a", "claim-a")
    b = volpod("pod-b", "claim-b")
    hub.create_pod(a)
    hub.create_pod(b)
    sched.run_until_idle()
    counts = sched.queue.pending_counts()
    assert counts["unschedulable"] == 2, "both parked on missing claims"
    # claim-a appears (bound Immediate claims schedule directly)
    from kubernetes_tpu.api.objects import (
        PersistentVolume,
        PersistentVolumeClaim,
        PersistentVolumeClaimSpec,
        PersistentVolumeSpec,
        READ_WRITE_ONCE,
    )

    hub.create_pv(PersistentVolume(
        metadata=ObjectMeta(name="pv-a"),
        spec=PersistentVolumeSpec(capacity={"storage": "10Gi"},
                                  access_modes=[READ_WRITE_ONCE])))
    hub.create_pvc(PersistentVolumeClaim(
        metadata=ObjectMeta(name="claim-a"),
        spec=PersistentVolumeClaimSpec(
            access_modes=[READ_WRITE_ONCE], volume_name="pv-a",
            requests={"storage": "1Gi"})))
    for _ in range(4):
        sched.run_until_idle()
        clock[0] += 3.0
        sched.queue.flush_backoff_completed()
    sched.run_until_idle()
    assert hub.get_pod(a.metadata.uid).spec.node_name == "n", \
        "the claim's owner requeued and scheduled"
    assert hub.get_pod(b.metadata.uid).spec.node_name in ("", None), \
        "the stranger stayed parked"
    sched.close()


# suite-tier discipline (tests/test_markers.py): area marker
import pytest  # noqa: E402
pytestmark = pytest.mark.core
