"""The fleet telemetry plane (ISSUE 10): wire trace propagation,
fleet-wide metrics aggregation, and the device-launch profiler.

Covers: TraceContext on both codecs and across relay hops (hop data
degrades, events never drop), the JSON-era-middlebox (chaos proxy)
path, WAL persistence of trace stamps, the PodTimelines end-to-end
join (hub commit -> relay -> scheduler -> bind -> kubelet ack), the
strict exposition parser + FleetView merge, the DeviceProfiler's
compile attribution, and the hub-client stream-counter tail flush.
"""

import json
import time
import urllib.error
import urllib.request

import pytest

from kubernetes_tpu.fabric import codec as binwire
from kubernetes_tpu.hub import EventHandlers, Hub
from kubernetes_tpu.storage import JournalEvent
from kubernetes_tpu.telemetry.fleet import (
    FleetView,
    hub_metrics_text,
    kubemark_metrics_text,
    merge_expositions,
    parse_exposition,
    relay_metrics_text,
)
from kubernetes_tpu.telemetry.profiler import DeviceProfiler, shape_key
from kubernetes_tpu.telemetry.trace import (
    TraceContext,
    format_ack_trace,
    joined_latency,
    latency_summary,
    new_context,
    parse_ack_trace,
)
from kubernetes_tpu.testing import MakeNode, MakePod
from kubernetes_tpu.utils.wire import from_wire, to_wire

pytestmark = pytest.mark.observability


# ----------------------------------------------- trace context basics


def test_trace_context_wire_round_trip_both_codecs():
    tr = TraceContext(origin="pods-3", ts=123.456789, hops=2)
    # JSON wire
    assert from_wire(to_wire(tr)) == tr
    # bin1 wire (registered kind -> positional struct)
    assert binwire.decode(binwire.encode(tr)) == tr


def test_trace_hop_is_derivation_not_mutation():
    tr = new_context("hub")
    h1 = tr.hop()
    assert (h1.origin, h1.ts, h1.hops) == (tr.origin, tr.ts, 1)
    assert tr.hops == 0


def test_ack_trace_baggage_round_trip_and_malformed():
    tr = TraceContext(origin="hub", ts=11.5, hops=2)
    assert parse_ack_trace(format_ack_trace(tr)) == \
        TraceContext("hub", 11.5, 2)
    assert parse_ack_trace("garbage") is None
    assert parse_ack_trace("") is None


def test_hub_commit_stamps_trace_and_wal_persists_it(tmp_path):
    wal = str(tmp_path / "hub.wal")
    hub = Hub(wal_path=wal)
    got = []
    hub.watch_pods(EventHandlers(on_event=got.append))
    hub.create_pod(MakePod().name("t0").obj())
    assert got and got[0].trace is not None
    assert got[0].trace.origin == "hub"
    assert got[0].trace.hops == 0
    assert got[0].trace.ts > 0
    hub.close()
    # a restarted hub's ring still serves STAMPED events
    hub2 = Hub(wal_path=wal)
    evs = hub2.journal.events_after("pods", 0)
    assert evs and evs[0].trace is not None
    assert evs[0].trace.origin == "hub"
    hub2.close()


def test_sharded_hub_trace_origin_names_the_shard():
    from kubernetes_tpu.fabric.sharded import ShardedHub

    hub = ShardedHub(pod_shards=2)
    got = []
    hub.watch_pods(EventHandlers(on_event=got.append))
    hub.create_pod(MakePod().name("s0").namespace("nsa").obj())
    assert got[0].trace.origin.startswith("pods-")
    hub.close()


def test_joined_latency_requires_all_three_stamps():
    tl = {"wire": {"created": {"t": 1.0, "origin": "hub", "hops": 0},
                   "bound": {"t": 1.5, "origin": "hub", "hops": 0}}}
    assert joined_latency(tl) is None       # no ack yet
    tl["wire"]["acked"] = {"t": 2.0, "origin": "hub", "hops": 0}
    j = joined_latency(tl)
    assert j["create_to_ack_s"] == 1.0
    assert j["create_to_bind_s"] == 0.5
    tl["wire"]["kubelet_recv"] = {"t": 1.7, "origin": "hub", "hops": 2}
    j = joined_latency(tl)
    assert j["bind_to_kubelet_s"] == pytest.approx(0.2)
    assert j["relay_hops"] == 2
    assert joined_latency(None) is None


def test_latency_summary_percentiles():
    s = latency_summary([0.1 * i for i in range(1, 101)])
    assert s["count"] == 100
    assert s["p99_s"] == pytest.approx(10.0)
    assert latency_summary([]) == {"count": 0}


# --------------------------------------- wire + relay hop propagation


def _collect_stream(url, n_events, timeout=10.0):
    """Read a watch stream's JSON lines until n_events non-marker
    events arrived."""
    events = []
    resp = urllib.request.urlopen(url, timeout=timeout)
    deadline = time.monotonic() + timeout
    for raw in resp:
        line = raw.strip()
        if not line or time.monotonic() > deadline:
            break
        d = json.loads(line)
        if d.get("synced") or not d:
            continue
        events.append(d)
        if len(events) >= n_events:
            break
    resp.close()
    return events


def test_trace_survives_hubserver_json_wire():
    from kubernetes_tpu.hubserver import HubServer

    hub = Hub()
    srv = HubServer(hub).start()
    try:
        # connect FIRST: live events carry the commit stamp (a LIST
        # replay synthesizes adds — those are the documented trace=None
        # degradation, asserted below)
        resp = urllib.request.urlopen(
            srv.address + "/watch?kind=pods&replay=1", timeout=10.0)
        hub.create_pod(MakePod().name("w0").obj())
        live = replayed = None
        deadline = time.monotonic() + 10.0
        for raw in resp:
            if time.monotonic() > deadline:
                break
            line = raw.strip()
            if not line:
                continue
            d = json.loads(line)
            if d.get("synced") or not d:
                continue
            live = d
            break
        resp.close()
        assert live is not None and "trace" in live
        tr = from_wire(live["trace"])
        assert isinstance(tr, TraceContext) and tr.origin == "hub"
        # now a replayed LIST: the synthetic add has no stamp but the
        # event itself is delivered (degraded, never dropped)
        evs = _collect_stream(srv.address + "/watch?kind=pods&replay=1",
                              1)
        assert evs and evs[0].get("trace") is None
        replayed = evs[0]
        assert replayed["new"] is not None
    finally:
        srv.stop()
        hub.close()


def test_trace_rides_bin1_and_json_only_server_fallback():
    """Negotiation matrix: on the bin1 wire the stamp arrives as a
    positional struct; against a JSON-only server (fingerprint-era
    skew) the client degrades to JSON and the stamp STILL arrives."""
    from kubernetes_tpu.hubclient import RemoteHub
    from kubernetes_tpu.hubserver import HubServer

    for codecs in ((binwire.CODEC_BINARY, binwire.CODEC_JSON),
                   (binwire.CODEC_JSON,)):
        hub = Hub()
        srv = HubServer(hub, codecs=codecs).start()
        client = RemoteHub(srv.address, timeout=10.0)
        got = []
        try:
            client.list_pods()          # settle codec negotiation
            client.watch_pods(EventHandlers(on_event=got.append))
            hub.create_pod(MakePod().name("nb0").obj())
            deadline = time.monotonic() + 10.0
            while not got and time.monotonic() < deadline:
                time.sleep(0.05)
            assert got, f"no event over codecs={codecs}"
            assert isinstance(got[0].trace, TraceContext)
            assert got[0].trace.origin == "hub"
            expect = binwire.CODEC_BINARY if len(codecs) == 2 \
                else binwire.CODEC_JSON
            assert client.codec == expect
        finally:
            client.close()
            srv.stop()
            hub.close()


def test_trace_survives_chaos_proxy_json_fallback():
    """The JSON-era middlebox: the chaos proxy strips the CODEC offer
    (forcing the JSON wire) but the in-body trace stamp passes through
    — hop data degraded nowhere, zero events dropped."""
    from kubernetes_tpu.chaos import ChaosConfig, ChaosProxy
    from kubernetes_tpu.hubclient import RemoteHub
    from kubernetes_tpu.hubserver import HubServer

    hub = Hub()
    srv = HubServer(hub).start()
    proxy = ChaosProxy(srv.address, config=ChaosConfig(seed=7)).start()
    client = RemoteHub(proxy.address, timeout=10.0)
    got = []
    try:
        client.watch_pods(EventHandlers(on_event=got.append))
        for i in range(5):
            hub.create_pod(MakePod().name(f"cp-{i}").obj())
        deadline = time.monotonic() + 10.0
        while len(got) < 5 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert len(got) == 5, "all events delivered through the proxy"
        assert all(ev.trace is not None and ev.trace.origin == "hub"
                   for ev in got)
        # the proxy pinned the stream to JSON — negotiation degraded,
        # trace did not
        assert client.resilience_stats()["codec"] in ("json",
                                                      "negotiating")
    finally:
        client.close()
        proxy.stop()
        srv.stop()
        hub.close()


def test_relay_increments_hops_and_ring_resume_keeps_trace():
    from kubernetes_tpu.fabric.relay import RelayCore
    from kubernetes_tpu.hubserver import HubServer

    hub = Hub()
    srv = HubServer(hub).start()
    core = None
    try:
        core = RelayCore(srv.address, kinds=("pods",), timeout=10.0)
        sub = core.subscribe(("pods",))
        hub.create_pod(MakePod().name("r0").obj())
        deadline = time.monotonic() + 10.0
        evs = []
        while time.monotonic() < deadline:
            evs += sub.drain()
            if evs:
                break
            time.sleep(0.05)
        assert evs and evs[0]["trace"].hops == 1
        assert evs[0]["trace"].origin == "hub"
        # a resume off the ring re-serves the SAME stamped event
        sub2 = core.subscribe(("pods",), since_rv=0)
        resumed = sub2.drain()
        assert resumed and resumed[0]["trace"].hops == 1
        # a state-mirror LIST replay has no events to stamp: degraded
        sub3 = core.subscribe(("pods",), replay=True)
        listed = sub3.drain()
        assert listed and listed[0]["trace"] is None
    finally:
        if core is not None:
            core.close()
        srv.stop()
        hub.close()


def test_scheduler_joins_end_to_end_timeline_with_kubelet_ack():
    """The whole pillar-(a) loop in-process: hub commit stamps ->
    scheduler timeline join -> kubelet ack baggage -> joined e2e."""
    from kubernetes_tpu.config.types import default_config
    from kubernetes_tpu.kubemark import HollowNodes
    from kubernetes_tpu.ops.features import Capacities
    from kubernetes_tpu.scheduler import Scheduler

    hub = Hub()
    hollow = HollowNodes(hub, 2, prefix="tn", cpu="8")
    cfg = default_config()
    cfg.batch_size = 16
    sched = Scheduler(hub, cfg, caps=Capacities(nodes=16, pods=64))
    try:
        pods = [MakePod().name(f"j{i}").req(cpu="100m").obj()
                for i in range(3)]
        for p in pods:
            hub.create_pod(p)
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            sched.run_until_idle()
            joins = [sched.timelines.joined(p.metadata.uid)
                     for p in pods]
            if all(j is not None for j in joins):
                break
            time.sleep(0.05)
        joins = [sched.timelines.joined(p.metadata.uid) for p in pods]
        assert all(j is not None for j in joins), joins
        for j in joins:
            assert j["create_to_ack_s"] >= 0.0
            assert j["create_to_bind_s"] >= 0.0
            # in-process: no relay between kubelet and hub -> 0 hops,
            # but the kubelet-recv leg is still stamped via baggage
            assert "bind_to_kubelet_s" in j
        # /debug/pod serves the join
        tl = sched.timelines.get(name="j0")
        assert tl["joined"] is not None
        assert {"created", "bound", "acked",
                "kubelet_recv"} <= set(tl["wire"])
    finally:
        sched.close()
        hollow.stop()
        hub.close()


def test_trace_export_placement_rows_carry_wire_stamps(tmp_path):
    """The v2 export's placement rows gain the commit-time wire stamps
    (created hub-commit ts + hops) — the offline join anchor."""
    from kubernetes_tpu.config.types import default_config
    from kubernetes_tpu.ops.features import Capacities
    from kubernetes_tpu.scheduler import Scheduler

    path = str(tmp_path / "tr.jsonl")
    hub = Hub()
    hub.create_node(MakeNode().name("xn").capacity(cpu="8").obj())
    cfg = default_config()
    cfg.batch_size = 16
    cfg.trace_export_path = path
    cfg.trace_export_max_bytes = 0
    sched = Scheduler(hub, cfg, caps=Capacities(nodes=16, pods=64))
    try:
        hub.create_pod(MakePod().name("xp").req(cpu="100m").obj())
        sched.run_until_idle()
    finally:
        sched.close()
        hub.close()
    rows = [json.loads(ln) for ln in open(path)]
    placed = [p for r in rows for p in r.get("placements", [])
              if p["pod"].endswith("/xp")]
    assert placed and placed[0]["node"]
    assert placed[0]["wire"]["created"]["t"] > 0
    assert placed[0]["wire"]["created"]["origin"] == "hub"


def test_hubclient_flushes_stream_counters_on_short_stream_eof():
    """Satellite: a stream shorter than the 64-event flush batch must
    still land its tail in wire_codec_* when the connection dies."""
    from kubernetes_tpu.hubclient import RemoteHub
    from kubernetes_tpu.hubserver import HubServer

    hub = Hub()
    srv = HubServer(hub).start()
    client = RemoteHub(srv.address, timeout=10.0)
    got = []
    try:
        client.watch_pods(EventHandlers(on_event=got.append))
        for i in range(5):          # well under the 64-event batch
            hub.create_pod(MakePod().name(f"f{i}").obj())
        deadline = time.monotonic() + 10.0
        while len(got) < 5 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert len(got) == 5
    finally:
        client.close()              # cuts the stream mid-batch
        srv.stop()
    wire = client.resilience_stats()["wire"]
    total_msgs = sum(w["msgs"] for w in wire.values())
    total_recv = sum(w["bytes_recv"] for w in wire.values())
    # 5 events + sync marker rode the stream; the close() above must
    # have flushed them (plus /call probe traffic) deterministically
    assert total_msgs >= 6, wire
    assert total_recv > 0
    hub.close()


# ------------------------------------------------- fleet aggregation


def test_parse_exposition_strict_accepts_and_rejects():
    good = ('# HELP m_total a "quoted" help\n'
            '# TYPE m_total counter\n'
            'm_total{a="x\\ny",b="z\\"q\\\\w"} 3.5\n'
            'plain_gauge 1\n')
    exp = parse_exposition(good)
    assert exp.type["m_total"] == "counter"
    assert exp.samples[0].labels == {"a": "x\ny", "b": 'z"q\\w'}
    assert exp.samples[1].name == "plain_gauge"
    for bad in ('1bad_name 3\n',
                'm{bad-label="x"} 1\n',
                'm{a="unterminated} 1\n',
                'm notafloat\n',
                '# TYPE m wrongtype\n'):
        with pytest.raises(ValueError):
            parse_exposition(bad)


def test_merge_expositions_injects_component_labels():
    a = parse_exposition("# TYPE x_total counter\nx_total 1\n")
    b = parse_exposition("# TYPE x_total counter\n"
                         'x_total{z="1"} 2\n')
    merged = merge_expositions([({"component": "hub"}, a),
                                ({"component": "relay",
                                  "shard": "l1-0"}, b)])
    exp = parse_exposition(merged)       # merged output re-parses
    assert len(exp.samples) == 2
    assert exp.samples[0].labels["component"] == "hub"
    assert exp.samples[1].labels == {"component": "relay",
                                     "shard": "l1-0", "z": "1"}


def test_component_metrics_render_and_parse():
    from kubernetes_tpu.fabric.relay import RelayCore
    from kubernetes_tpu.hubserver import HubServer
    from kubernetes_tpu.kubemark import HollowNodes

    hub = Hub()
    srv = HubServer(hub).start()
    core = None
    hollow = None
    try:
        hub.create_pod(MakePod().name("m0").obj())
        core = RelayCore(srv.address, kinds=("pods",), timeout=10.0)
        hollow = HollowNodes(hub, 2, prefix="mk")
        for text, needle in (
                (hub_metrics_text(hub), "hub_journal_depth"),
                (relay_metrics_text(core), "relay_events_in_total"),
                (kubemark_metrics_text(hollow),
                 "kubemark_hollow_nodes")):
            exp = parse_exposition(text)    # strict parse = the lint
            assert any(s.name.startswith(needle) for s in exp.samples)
    finally:
        if hollow is not None:
            hollow.stop()
        if core is not None:
            core.close()
        srv.stop()
        hub.close()


def test_fleet_view_scrape_merge_and_summary():
    from kubernetes_tpu.fabric.relay import RelayCore, RelayServer
    from kubernetes_tpu.hubserver import HubServer

    hub = Hub()
    srv = HubServer(hub).start()
    relay = RelayServer(RelayCore(srv.address, kinds=("pods",),
                                  timeout=10.0)).start()
    try:
        hub.create_pod(MakePod().name("fv0").obj())
        fleet = FleetView([
            {"component": "hub", "shard": "hub", "url": srv.address},
            {"component": "relay", "shard": "l1-0",
             "url": relay.address},
            {"component": "ghost", "shard": "",
             "url": "http://127.0.0.1:1"},     # dead endpoint
        ], timeout=5.0)
        summary = fleet.summary()
        assert summary["total"] == 3
        assert summary["healthy"] == 2
        assert not summary["ok"]               # the ghost is reported
        ghost = [r for r in summary["endpoints"]
                 if r["component"] == "ghost"][0]
        assert ghost["error"] and not ghost["healthy"]
        merged = parse_exposition(fleet.render_text())
        comps = {s.labels.get("component") for s in merged.samples}
        assert comps == {"hub", "relay"}       # dead one skipped
        shards = {s.labels.get("shard") for s in merged.samples}
        assert {"hub", "l1-0"} <= shards
    finally:
        relay.stop()
        srv.stop()
        hub.close()


def test_scheduler_metrics_exposition_passes_strict_parser():
    """Metrics-lint half 2: the scheduler's full /metrics body (label
    escaping included) round-trips the strict parser."""
    from kubernetes_tpu.metrics import SchedulerMetrics

    m = SchedulerMetrics()
    # poison a label value with everything the spec escapes
    m.schedule_attempts.inc(result='we"ird\\label\nvalue',
                            profile="default")
    m.phase_duration.observe(0.01, phase="commit")
    exp = parse_exposition(m.registry.render_text())
    assert any(s.labels.get("result") == 'we"ird\\label\nvalue'
               for s in exp.samples)


# ------------------------------------------------- device profiler


def test_device_profiler_attributes_compiles():
    sizes = [0]

    def cache():
        return sizes[0]

    from kubernetes_tpu.ops.features import Capacities

    caps = Capacities(nodes=64, pods=128)
    prof = DeviceProfiler(cache_size_fn=cache, now=lambda: 0.0)

    def shape(c, b):
        return shape_key(c, b, False, 0, 0, True, False, False, False)

    # first launch compiles
    sizes[0] = 1
    assert prof.note_launch(shape(caps, 32)) is True
    assert prof.compile_causes == {"first": 1}
    # same shape again, cache unchanged: no compile
    assert prof.note_launch(shape(caps, 32)) is False
    # batch bucket grows -> compile attributed to batch_bucket
    sizes[0] = 2
    assert prof.note_launch(shape(caps, 64)) is True
    assert prof.compile_causes["batch_bucket"] == 1
    # capacity doubled (re-bucket churn) -> rebucket
    import dataclasses

    caps2 = dataclasses.replace(caps, nodes=128)
    sizes[0] = 3
    assert prof.note_launch(shape(caps2, 64)) is True
    assert prof.compile_causes["rebucket"] == 1
    # cache grew on an ALREADY-SEEN shape: unattributed (the alarm)
    sizes[0] = 4
    assert prof.note_launch(shape(caps2, 64)) is True
    snap = prof.snapshot()
    assert snap["unattributed_compiles"] == 1
    assert snap["launches"] == 5 and snap["compiles"] == 4
    assert len(snap["recent_compiles"]) == 4
    prof.observe_walltime(shape(caps2, 64), 0.5)
    snap = prof.snapshot()
    assert any(s["walltime_s"] == 0.5 for s in snap["shapes"])


def test_device_profiler_on_live_scheduler_rebucket():
    """Every recompile in a churn-with-growth run attributes to a
    bucket-shape transition (the MixedChurn acceptance criterion in
    miniature: capacity growth forces a re-bucket -> new shape ->
    compile attributed, never 'unattributed')."""
    from kubernetes_tpu.config.types import default_config
    from kubernetes_tpu.ops.features import Capacities
    from kubernetes_tpu.scheduler import Scheduler

    hub = Hub()
    for i in range(4):
        hub.create_node(MakeNode().name(f"pn-{i}")
                        .capacity(cpu="64").obj())
    cfg = default_config()
    cfg.batch_size = 16
    sched = Scheduler(hub, cfg, caps=Capacities(nodes=8, pods=16))
    try:
        # more pods than the pod-table bucket: forces _grow (re-bucket)
        for i in range(40):
            hub.create_pod(MakePod().name(f"g{i}")
                           .req(cpu="50m").obj())
        sched.run_until_idle()
        snap = sched.profiler.snapshot()
        assert snap["launches"] >= 2
        assert snap["compiles"] >= 1
        assert snap["unattributed_compiles"] == 0, snap
        assert snap["buffer_bytes"].get("cluster", 0) > 0
        # the compile counter mirrored into the registry
        total = sum(
            sched.metrics.device_compiles._values.values())
        assert total == snap["compiles"]
        # the device_compile view phase recorded for compiling cycles
        phases = [tr.phases for tr in sched.flight.ring]
        assert any("device_compile" in p for p in phases)
    finally:
        sched.close()
        hub.close()


# ------------------------------------------------- authz matrices


def _get(url, token=None):
    req = urllib.request.Request(url)
    if token:
        req.add_header("Authorization", f"Bearer {token}")
    return urllib.request.urlopen(req, timeout=10.0)


def test_relay_debug_authz_matrix():
    """Satellite: RelayServer /debug/fabric — no auth configured 403,
    wrong token 401, good token 200 (mirrors the scheduler's)."""
    from kubernetes_tpu.fabric.relay import RelayCore, RelayServer
    from kubernetes_tpu.hubserver import HubServer
    from kubernetes_tpu.serving import token_auth

    hub = Hub()
    srv = HubServer(hub).start()
    open_relay = RelayServer(RelayCore(srv.address, kinds=("pods",),
                                       timeout=10.0)).start()
    gated = RelayServer(RelayCore(srv.address, kinds=("pods",),
                                  timeout=10.0),
                        debug_auth=token_auth("rtok")).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(open_relay.address + "/debug/fabric")
        assert ei.value.code == 403
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(gated.address + "/debug/fabric")
        assert ei.value.code == 401
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(gated.address + "/debug/fabric", token="wrong")
        assert ei.value.code == 401
        d = json.loads(_get(gated.address + "/debug/fabric",
                            token="rtok").read())
        assert "subscribers" in d
        # /metrics and /healthz are the OPEN fleet surface (scrapers
        # don't bear debug tokens), on both relays
        for relay in (open_relay, gated):
            assert _get(relay.address + "/healthz").status == 200
            body = _get(relay.address + "/metrics").read().decode()
            parse_exposition(body)
    finally:
        gated.stop()
        open_relay.stop()
        srv.stop()
        hub.close()


def test_scheduler_fleet_endpoints_authz_matrix():
    """Satellite: /debug/fleet follows the /debug authz matrix; the
    merged /metrics/fleet exposition is open like /metrics."""
    from kubernetes_tpu.config.types import default_config
    from kubernetes_tpu.hubserver import HubServer
    from kubernetes_tpu.ops.features import Capacities
    from kubernetes_tpu.scheduler import Scheduler
    from kubernetes_tpu.serving import ServingEndpoints, token_auth

    hub = Hub()
    hub_srv = HubServer(hub).start()
    cfg = default_config()
    cfg.batch_size = 16
    sched = Scheduler(hub, cfg, caps=Capacities(nodes=16, pods=64))
    sched.fleet = FleetView([{"component": "hub", "shard": "hub",
                              "url": hub_srv.address}])
    try:
        # no debug_auth: 403 for /debug/fleet
        srv = ServingEndpoints(sched, port=0)
        srv.start()
        base = f"http://127.0.0.1:{srv.port}"
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(base + "/debug/fleet")
            assert ei.value.code == 403
            # the merged exposition is open (scrape surface)
            merged = _get(base + "/metrics/fleet").read().decode()
            exp = parse_exposition(merged)
            assert all(s.labels.get("component") == "hub"
                       for s in exp.samples)
        finally:
            srv.stop()
        srv = ServingEndpoints(sched, port=0,
                               debug_auth=token_auth("ftok"))
        srv.start()
        base = f"http://127.0.0.1:{srv.port}"
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(base + "/debug/fleet")
            assert ei.value.code == 401
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(base + "/debug/fleet", token="wrong")
            assert ei.value.code == 401
            d = json.loads(_get(base + "/debug/fleet",
                                token="ftok").read())
            assert d["total"] == 1 and d["healthy"] == 1
            # /debug/trace now carries the device profiler column
            tr = json.loads(_get(base + "/debug/trace",
                                 token="ftok").read())
            assert "device" in tr
        finally:
            srv.stop()
    finally:
        sched.close()
        hub_srv.stop()
        hub.close()


def test_hubserver_metrics_and_healthz():
    from kubernetes_tpu.hubserver import HubServer

    hub = Hub()
    srv = HubServer(hub).start()
    try:
        assert _get(srv.address + "/healthz").status == 200
        hub.create_pod(MakePod().name("hm0").obj())
        exp = parse_exposition(
            _get(srv.address + "/metrics").read().decode())
        assert any(s.name == "hub_rv" and s.value >= 1
                   for s in exp.samples)
    finally:
        srv.stop()
        hub.close()


def test_journal_event_trace_default_none_back_compat():
    ev = JournalEvent(rv=1, kind="pods", type="add", new=None)
    assert ev.trace is None
