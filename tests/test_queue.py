"""PriorityQueue semantics vs the reference's queue tests
(backend/queue/scheduling_queue_test.go): tier transitions, backoff math,
queueing hints, in-flight event replay, gates, flush timers. Virtual clock
throughout (the reference uses testingclock the same way)."""

from kubernetes_tpu.api.objects import (
    ObjectMeta,
    Pod,
    PodSchedulingGate,
    PodSpec,
)
from kubernetes_tpu.backend.queue import PriorityQueue, QueuedPodInfo
from kubernetes_tpu.framework.interface import (
    ActionType,
    ClusterEvent,
    ClusterEventWithHint,
    EventResource,
    QueueingHint,
    Status,
)


class Clock:
    def __init__(self):
        self.t = 1000.0

    def now(self):
        return self.t

    def tick(self, dt):
        self.t += dt


def less(a, b):
    if a.pod.priority() != b.pod.priority():
        return a.pod.priority() > b.pod.priority()
    return a.timestamp < b.timestamp


def mkpod(name, priority=0, gates=()):
    return Pod(metadata=ObjectMeta(name=name),
               spec=PodSpec(priority=priority,
                            scheduling_gates=[PodSchedulingGate(g)
                                              for g in gates]))


NODE_ADD = ClusterEvent(EventResource.NODE, ActionType.ADD)
POD_DELETE = ClusterEvent(EventResource.ASSIGNED_POD, ActionType.DELETE)


def gate_fn(pod):
    if pod.spec.scheduling_gates:
        return Status.unschedulable("gated", plugin="SchedulingGates",
                                    resolvable=False)
    return Status()


def mkq(clock=None, hints=None):
    clock = clock or Clock()
    q = PriorityQueue(less_fn=less, pre_enqueue=gate_fn,
                      queueing_hints=hints or {}, now=clock.now)
    return q, clock


def test_priority_then_fifo_order():
    q, _ = mkq()
    q.add(mkpod("low", 1))
    q.add(mkpod("high", 10))
    q.add(mkpod("mid", 5))
    assert [q.pop().pod.name for _ in range(3)] == ["high", "mid", "low"]


def test_unschedulable_then_event_requeues_with_backoff():
    hints = {"NodeResourcesFit": [ClusterEventWithHint(NODE_ADD)]}
    q, clock = mkq(hints=hints)
    q.add(mkpod("p"))
    qp = q.pop()
    qp.unschedulable_count += 1
    qp.unschedulable_plugins = {"NodeResourcesFit"}
    q.add_unschedulable_if_not_present(qp)
    assert q.pending_counts()["unschedulable"] == 1
    # an unrelated event must not move it
    q.move_all_to_active_or_backoff(POD_DELETE)
    assert q.pending_counts()["unschedulable"] == 1
    # the registered event moves it to backoff (1s not yet elapsed)
    q.move_all_to_active_or_backoff(NODE_ADD)
    assert q.pending_counts()["backoff"] == 1
    # backoff expires -> flush to active
    clock.tick(1.1)
    assert q.flush_backoff_completed() == 1
    assert q.pending_counts()["active"] == 1


def test_backoff_is_exponential_and_capped():
    q, clock = mkq()
    qp = QueuedPodInfo(pod=mkpod("p"), timestamp=clock.now())
    for attempts, want in ((1, 1.0), (2, 2.0), (3, 4.0), (5, 10.0),
                           (10, 10.0)):
        qp.unschedulable_count = attempts
        assert q.backoff_remaining(qp) == want


def test_queueing_hint_fn_skip_blocks_requeue():
    def hint(pod, old, new):
        return QueueingHint.SKIP

    hints = {"NodeResourcesFit": [ClusterEventWithHint(NODE_ADD, hint)]}
    q, _ = mkq(hints=hints)
    q.add(mkpod("p"))
    qp = q.pop()
    qp.unschedulable_plugins = {"NodeResourcesFit"}
    q.add_unschedulable_if_not_present(qp)
    q.move_all_to_active_or_backoff(NODE_ADD)
    assert q.pending_counts()["unschedulable"] == 1


def test_in_flight_event_replay():
    """An event arriving DURING a pod's failed cycle requeues it immediately
    instead of parking it in unschedulable (active_queue.go:147-169)."""
    hints = {"NodeResourcesFit": [ClusterEventWithHint(NODE_ADD)]}
    q, clock = mkq(hints=hints)
    q.add(mkpod("p"))
    qp = q.pop()
    q.move_all_to_active_or_backoff(NODE_ADD)  # concurrent with the cycle
    qp.unschedulable_count += 1
    qp.unschedulable_plugins = {"NodeResourcesFit"}
    q.add_unschedulable_if_not_present(qp)
    assert q.pending_counts()["unschedulable"] == 0
    assert q.pending_counts()["backoff"] == 1


def test_gated_pod_held_until_gates_removed():
    q, _ = mkq()
    old = mkpod("g", gates=("corp/hold",))
    q.add(old)
    assert q.pending_counts()["gated"] == 1
    assert q.pop() is None
    # unrelated events never touch the gated pool (the index skips it)
    q.move_all_to_active_or_backoff(NODE_ADD)
    assert q.pending_counts()["gated"] == 1
    # gates removed: the pod's own spec update re-runs PreEnqueue
    # (eventhandlers route pod updates through queue.update)
    q.update(old, Pod(metadata=old.metadata, spec=PodSpec()))
    assert q.pending_counts()["gated"] == 0
    assert q.pop().pod.name == "g"


def test_unschedulable_timeout_flush():
    q, clock = mkq()
    q.add(mkpod("p"))
    qp = q.pop()
    qp.unschedulable_plugins = {"NodeResourcesFit"}
    q.add_unschedulable_if_not_present(qp)
    assert q.flush_unschedulable_timeout() == 0
    clock.tick(301)
    assert q.flush_unschedulable_timeout() == 1
    assert q.pending_counts()["active"] == 1


def test_pop_batch_drains_in_order():
    q, _ = mkq()
    for i in range(5):
        q.add(mkpod(f"p{i}", priority=i))
    batch = q.pop_batch(3)
    assert [qp.pod.name for qp in batch] == ["p4", "p3", "p2"]
    assert q.in_flight_count() == 3
    for qp in batch:
        q.done(qp.uid)
    assert q.in_flight_count() == 0


def test_error_backoff_separate_counter():
    q, clock = mkq()
    qp = QueuedPodInfo(pod=mkpod("p"), timestamp=clock.now())
    qp.consecutive_errors_count = 3
    assert q.backoff_remaining(qp) == 4.0


# suite-tier discipline (tests/test_markers.py): area marker
import pytest  # noqa: E402
pytestmark = pytest.mark.core
