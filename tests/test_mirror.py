"""Mirror packing semantics at the f32 representability boundary."""


def test_non_mi_granular_quantities_round_conservatively():
    """Exact-integer fit semantics at the f32 boundary (fitsRequest,
    fit.go:509-592): odd-byte memory requests beyond float32's 2^24-MiB
    exact range must never FALSELY fit. Demand rounds UP, capacity
    rounds DOWN, so free = alloc_down - req_up understates headroom."""
    import numpy as np

    from kubernetes_tpu.api.objects import (
        Container,
        Node,
        NodeStatus,
        ObjectMeta,
        Pod,
        PodSpec,
        ResourceRequirements,
    )
    from kubernetes_tpu.backend.cache import Cache
    from kubernetes_tpu.backend.mirror import MI, Mirror, _f32_ceil, \
        _f32_floor
    from kubernetes_tpu.backend.snapshot import Snapshot
    from kubernetes_tpu.ops.features import COL_MEM, Capacities

    tib16 = 16 * 1024 ** 4              # 16 TiB = 2^24 MiB: f32-exact edge
    # one byte above: 2^24 MiB + 2^-20 MiB is NOT f32-representable
    odd = tib16 + 1

    assert float(_f32_ceil(odd / MI)) > odd / MI
    assert float(_f32_floor(odd / MI)) < odd / MI
    # Mi-granular values stay EXACT (no rounding perturbation)
    assert float(_f32_ceil(tib16 / MI)) == tib16 / MI
    assert float(_f32_floor(tib16 / MI)) == tib16 / MI

    cache = Cache()
    node = Node(metadata=ObjectMeta(name="n"),
                status=NodeStatus(allocatable={
                    "cpu": "64", "memory": str(odd), "pods": "110"}))
    cache.add_node(node)
    snap = Snapshot()
    cache.update_snapshot(snap)
    mirror = Mirror(caps=Capacities(nodes=8, pods=16))
    mirror.sync(snap)
    row = mirror.row_of("n")
    free_mem = mirror.free_matrix()[row, COL_MEM]
    # capacity rounded DOWN: the node never advertises the odd byte
    assert float(free_mem) <= odd / MI

    # a pod requesting the full odd size: request rounds UP, so the
    # device compare req <= free must REJECT (capacity was floored)
    pod = Pod(metadata=ObjectMeta(name="p"),
              spec=PodSpec(containers=[Container(
                  name="c", resources=ResourceRequirements(
                      requests={"memory": str(odd)}))]))
    from kubernetes_tpu.api.resources import pod_request

    req = mirror._res_row(pod_request(pod))
    assert float(req[COL_MEM]) >= odd / MI
    assert not bool(np.all(req[COL_MEM] <= free_mem)), \
        "odd-byte request must not falsely fit the floored capacity"

    # the Mi-granular pod of the same nominal size still fits exactly
    pod2 = Pod(metadata=ObjectMeta(name="p2"),
               spec=PodSpec(containers=[Container(
                   name="c", resources=ResourceRequirements(
                       requests={"memory": str(tib16)}))]))
    req2 = mirror._res_row(pod_request(pod2))
    assert float(req2[COL_MEM]) == tib16 / MI
    assert bool(req2[COL_MEM] <= free_mem)


# suite-tier discipline (tests/test_markers.py): area marker
import pytest  # noqa: E402
pytestmark = pytest.mark.core
