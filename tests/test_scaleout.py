"""Horizontal scheduler scale-out (ISSUE 16): the slice ring
(rebalance math, board CAS, slice-lease fencing), the SliceManager's
join/death/release lifecycle, partition filters in both queues (gangs
route whole by their group's namespace), the journal-replay bind audit,
the replicated sched-ring surviving leader failover, and an in-thread
two-replica partition drain.

Everything here runs at tier-1 speed; the 4-replica kill -9 storm is
slow-marked (it also runs in ``chaos --storm scaleout`` and the
``bench --chaos-smoke`` battery).
"""

from __future__ import annotations

import time

import pytest

from kubernetes_tpu.api.objects import (
    LABEL_HOSTNAME,
    LABEL_POD_GROUP,
    LABEL_QUEUE,
    pod_group_key,
)
from kubernetes_tpu.backend.jobqueue import JobQueue
from kubernetes_tpu.backend.queue import PriorityQueue
from kubernetes_tpu.config.types import default_config
from kubernetes_tpu.fabric.replica import StateReplica
from kubernetes_tpu.framework.interface import Status
from kubernetes_tpu.hub import Conflict, Fenced, Hub
from kubernetes_tpu.hubclient import RemoteHub
from kubernetes_tpu.hubserver import HubServer
from kubernetes_tpu.leaderelection import (
    RING_SLOTS,
    SCHED_SLICE_LEASE,
    SliceBoard,
    SliceManager,
    rebalance_slots,
    ring_slot,
)
from kubernetes_tpu.ops.features import Capacities
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.testing import MakeNode, MakePod, audit_bind_journal

pytestmark = pytest.mark.scaleout


# ------------------------------------------------ ring / rebalance math


def test_ring_slot_stable_and_in_range():
    for ns in ("default", "team-a", "team-b", "", "ns-11"):
        s = ring_slot(ns)
        assert 0 <= s < RING_SLOTS
        assert s == ring_slot(ns), "hash must be stable"


def test_rebalance_even_split_and_deterministic():
    out = rebalance_slots([], ["a", "b", "c", "d"])
    assert len(out) == RING_SLOTS
    counts = {r: out.count(r) for r in "abcd"}
    assert all(c == RING_SLOTS // 4 for c in counts.values()), counts
    # deterministic: every replica proposes the same map from the same
    # inputs, so CAS racers collide on the epoch, not on divergent maps
    assert out == rebalance_slots([], ["d", "c", "b", "a"])


def test_rebalance_minimal_churn_on_join():
    base = rebalance_slots([], ["a"])
    after = rebalance_slots(base, ["a", "b"])
    # a keeps exactly its even share; only the overflow moved to b
    moved = sum(1 for i in range(RING_SLOTS) if base[i] != after[i])
    assert after.count("a") == after.count("b") == RING_SLOTS // 2
    assert moved == RING_SLOTS // 2, "join must move only the overflow"


def test_rebalance_reassigns_orphans_on_death():
    both = rebalance_slots(rebalance_slots([], ["a"]), ["a", "b"])
    after = rebalance_slots(both, ["a"])
    assert after.count("a") == RING_SLOTS
    # a's surviving slots never churned
    for i in range(RING_SLOTS):
        if both[i] == "a":
            assert after[i] == "a"


def test_rebalance_empty_live_keeps_map():
    cur = rebalance_slots([], ["a", "b"])
    assert rebalance_slots(cur, []) == cur


# ------------------------------------------------ slice board


def test_slice_board_register_ttl_and_cas():
    board = SliceBoard(ring_slots=8)
    reg = board.register("a", url="http://a", pid=1)
    assert reg["ring"] == {"epoch": 0, "slots": []}
    board.register("b")
    assert set(board.schedulers()) == {"a", "b"}
    assert set(board.live(ttl_s=60.0)) == {"a", "b"}
    assert board.live(ttl_s=0.0) in ({}, board.live(ttl_s=0.0))
    # CAS by epoch: stale expect loses, winner's map sticks
    assert board.set_ring({"epoch": 1, "slots": ["a"] * 8}, 0) is True
    assert board.set_ring({"epoch": 1, "slots": ["b"] * 8}, 0) is False
    assert board.ring() == {"epoch": 1, "slots": ["a"] * 8}
    board.unregister("b")
    assert set(board.schedulers()) == {"a"}


# ------------------------------------------------ slice manager lifecycle


def _tick(sm, hb=0.01):
    time.sleep(hb * 2)
    return sm.tick()


def test_single_manager_owns_everything():
    hub = Hub()
    sm = SliceManager(hub, "solo", heartbeat_s=0.01, ttl_s=5.0)
    assert sm.tick() is True
    assert sm.owned == frozenset(range(RING_SLOTS))
    assert sm.is_leader()
    assert sm.ring_epoch == 1
    assert sm.epoch >= 1, "fence lease must be stamped with the map"
    assert sm.owns_namespace("default") and sm.owns_namespace("x")
    hub.close()


def test_two_managers_split_fence_bumps_and_release_rehomes():
    hub = Hub()
    a = SliceManager(hub, "a", heartbeat_s=0.01, ttl_s=5.0)
    b = SliceManager(hub, "b", heartbeat_s=0.01, ttl_s=5.0)
    assert a.tick()
    fence1 = a.epoch
    assert _tick(b), "joiner rebalances in and owns its share"
    assert _tick(a), "incumbent adopts the new map"
    assert a.owned and b.owned and not (a.owned & b.owned)
    assert a.owned | b.owned == frozenset(range(RING_SLOTS))
    assert a.ring_epoch == b.ring_epoch == 2
    # each committed rebalance is exactly one holder change => one
    # fresh fencing epoch; re-applied syncs are no-ops
    assert a.epoch == b.epoch > fence1
    fence2 = a.epoch
    assert _tick(a) and a.epoch == fence2, "steady-state must not bump"
    # every namespace has exactly one owner
    for ns in ("default", "team-a", "ns-7", "zz"):
        assert a.owns_namespace(ns) != b.owns_namespace(ns)
    # graceful departure re-homes NOW (no TTL wait)
    b.release()
    assert not b.is_leader() and not b.owned
    assert _tick(a)
    assert a.owned == frozenset(range(RING_SLOTS))
    assert set(hub.fabric_schedulers()) == {"a"}
    hub.close()


class _CuttableHub:
    """Hub proxy whose fabric_* verbs can be severed (board outage)."""

    def __init__(self, hub):
        self._hub = hub
        self.broken = False

    def __getattr__(self, name):
        if self.broken and name.startswith("fabric_"):
            raise ConnectionError("board unreachable")
        return getattr(self._hub, name)


def test_manager_survives_blip_demotes_past_ttl():
    clock = {"t": 1000.0}
    hub = _CuttableHub(Hub())
    sm = SliceManager(hub, "a", heartbeat_s=1.0, ttl_s=5.0,
                      now=lambda: clock["t"])
    assert sm.tick() is True
    hub.broken = True
    clock["t"] += 2.0
    assert sm.tick() is True, "a blip inside the TTL keeps the slices"
    assert sm.transport_errors == 1
    clock["t"] += 10.0
    assert sm.tick() is False, "past the TTL peers re-homed our slices"
    assert not sm.is_leader()
    hub._hub.close()


def test_deposed_map_loses_the_fence():
    hub = Hub()
    hub.create_node(MakeNode().name("n").label(LABEL_HOSTNAME, "n")
                    .capacity(cpu="8", memory="16Gi", pods="110").obj())
    a = SliceManager(hub, "a", heartbeat_s=0.01, ttl_s=5.0)
    b = SliceManager(hub, "b", heartbeat_s=0.01, ttl_s=5.0)
    assert a.tick()
    stale = a.epoch              # fence as of the single-replica map
    assert _tick(b) and _tick(a)  # rebalance bumped the fence
    pod = MakePod().name("p").req(cpu="100m").obj()
    hub.create_pod(pod)
    with pytest.raises(Fenced):
        hub.bind(pod, "n", stale, SCHED_SLICE_LEASE)
    assert hub.get_pod(pod.metadata.uid).spec.node_name == "", \
        "a bind from a deposed slice map must not land"
    hub.bind(pod, "n", a.epoch, a.lease_name)
    assert hub.get_pod(pod.metadata.uid).spec.node_name == "n"
    with pytest.raises(Conflict):
        hub.bind(pod, "n", b.epoch, b.lease_name)  # bind-once holds
    hub.close()


# ------------------------------------------------ partition filters


def test_gang_routes_by_group_namespace_never_splits():
    hub = Hub()
    cfg = default_config()
    sched = Scheduler(hub, cfg, caps=Capacities(nodes=8, pods=32))

    class _Slices:
        is_slice_manager = True

        def owns_namespace(self, ns):
            return ns == "mine"

    sched._slices = _Slices()
    solo = MakePod().name("solo").namespace("mine").obj()
    foreign = MakePod().name("f").namespace("theirs").obj()
    member = MakePod().name("m0").namespace("mine").obj()
    member.metadata.labels[LABEL_POD_GROUP] = "g1"
    assert pod_group_key(member) == "mine/g1"
    assert sched._owns_pod(solo) is True
    assert sched._owns_pod(foreign) is False
    # the gang member routes by its GROUP's namespace — every member
    # of mine/g1 lands on the same replica, whatever else changes
    assert sched._owns_pod(member) is True
    sched.close()
    hub.close()


def test_queue_drain_unowned_sweeps_every_pool():
    def pre(pod):
        if pod.metadata.name.startswith("gate"):
            return Status.unschedulable("gated", plugin="G",
                                        resolvable=False)
        return Status()

    q = PriorityQueue(less_fn=lambda a, b: a.timestamp < b.timestamp,
                      pre_enqueue=pre)

    def mk(name, ns):
        return MakePod().name(name).namespace(ns).uid(name).obj()

    unsched = mk("u", "foreign")
    q.add(unsched)
    qp = q.pop()
    qp.unschedulable_plugins = {"X"}
    q.add_unschedulable_if_not_present(qp)
    back = mk("bk", "foreign")
    q.add(back)
    qp = q.pop()
    qp.consecutive_errors_count = 1
    q.add_unschedulable_if_not_present(qp)       # error-class -> backoff
    inflight = mk("infl", "foreign")
    q.add(inflight)
    assert q.pop().uid == "infl"                 # stays in flight
    q.add(mk("act", "foreign"))
    q.add(mk("keep", "default"))
    q.add(mk("gate", "foreign"))

    drained = {p.metadata.name
               for p in q.drain_unowned(
                   lambda p: p.metadata.namespace == "default")}
    # every pool swept; in-flight left to finish and fence at bind
    assert drained == {"u", "bk", "act", "gate"}, drained
    counts = q.pending_counts()
    assert counts["active"] == 1 and counts["gated"] == 0
    assert counts["backoff"] == 0 and counts["unschedulable"] == 0


def test_jobqueue_drain_unowned_rehomes_whole_unit():
    jq = JobQueue()

    def gpod(name, ns, gang=None, tenant="t"):
        p = MakePod().name(name).namespace(ns).uid(name).obj()
        p.metadata.labels[LABEL_QUEUE] = tenant
        if gang:
            p.metadata.labels[LABEL_POD_GROUP] = gang
        return p

    for i in range(3):
        jq.add(gpod(f"g-{i}", "mlns", gang="train"))
    jq.add(gpod("keep", "default"))
    assert len(jq) == 4
    drained = jq.drain_unowned(
        lambda p: p.metadata.namespace == "default")
    # the unit moves WHOLE — members never split across replicas
    assert {p.metadata.name for p in drained} == {"g-0", "g-1", "g-2"}
    assert len(jq) == 1 and jq.holds("keep")
    assert jq.drain_unowned(lambda p: True) == []


# ------------------------------------------------ journal bind audit


def test_audit_clean_journal_passes():
    hub = Hub()
    hub.create_node(MakeNode().name("n").label(LABEL_HOSTNAME, "n")
                    .capacity(cpu="8", memory="16Gi", pods="110").obj())
    uids = []
    for i in range(3):
        p = MakePod().name(f"p{i}").req(cpu="100m").obj()
        hub.create_pod(p)
        uids.append(p.metadata.uid)
        hub.bind(p, "n")
    report = audit_bind_journal(hub=hub, expected_uids=uids)
    assert report["ok"], report
    assert report["binds"] == 3 and not report["lost"]
    hub.close()


def _row(rv, uid, node, ctype="update"):
    return {"rv": rv, "kind": "pods", "type": ctype,
            "obj": {"metadata": {"uid": uid},
                    "spec": {"node_name": node}}}


def test_audit_flags_rebound_lost_and_too_old():
    rebound = audit_bind_journal(changes=[
        _row(1, "u1", ""), _row(2, "u1", "n1"), _row(3, "u1", "n2")])
    assert not rebound["ok"]
    assert rebound["double_binds"][0]["violation"] == "rebound"
    assert rebound["double_binds"][0]["second_node"] == "n2"

    unbound = audit_bind_journal(changes=[
        _row(1, "u1", "n1"), _row(2, "u1", "")])
    assert [v["violation"] for v in unbound["double_binds"]] == ["unbound"]

    lost = audit_bind_journal(changes=[_row(1, "u1", "n1")],
                              expected_uids=["u1", "u2"])
    assert lost["lost"] == ["u2"] and not lost["ok"]

    ok = audit_bind_journal(changes=[
        _row(1, "u1", "n1"), _row(2, "u1", "n1"),   # same-node re-apply
        _row(3, "u1", "", "delete")])
    assert ok["ok"] and ok["binds"] == 1

    compacted = audit_bind_journal(
        changes={"too_old": True, "rv": 9, "changes": [_row(9, "u", "n")]})
    assert compacted["too_old"] and not compacted["ok"]


# ------------------------------------------------ replicated sched ring


FAST = {"heartbeat_s": 0.05, "election_timeout_s": (0.25, 0.5)}


def test_sched_ring_survives_leader_failover(tmp_path):
    names = ["state-0", "state-1", "state-2"]
    replicas, servers = {}, {}
    for n in names:
        replicas[n] = StateReplica(n, pod_shards=["pods-0"],
                                   wal_path=str(tmp_path / f"{n}.wal"),
                                   **FAST)
        servers[n] = HubServer(replicas[n])
    peer_map = {n: servers[n].address for n in names}
    for n in names:
        replicas[n].set_peers(peer_map)
        servers[n].start()
    for n in names:
        replicas[n].start()

    def leader(alive):
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            for n in alive:
                if replicas[n].fabric_replica_status()["role"] == "leader":
                    return n
            time.sleep(0.05)
        raise AssertionError("no leader elected")

    try:
        first = leader(names)
        hub = RemoteHub(peer_map[first], timeout=5.0)
        try:
            reg = hub.fabric_register_scheduler("sched-a", "", 1)
            assert reg["ring"]["epoch"] == 0
            want = {"epoch": 1, "slots": ["sched-a"] * RING_SLOTS}
            assert hub.fabric_set_sched_ring(want, 0)
            assert not hub.fabric_set_sched_ring(
                {"epoch": 1, "slots": ["x"] * RING_SLOTS}, 0), \
                "the CAS must go through the log exactly once"
            assert hub.fabric_sched_ring() == want
        finally:
            hub.close()
        # kill -9 the leader: the ring is LOGGED state and must survive
        servers[first].stop()
        replicas[first].close()
        rest = [n for n in names if n != first]
        second = leader(rest)
        hub2 = RemoteHub(peer_map[second], timeout=5.0)
        try:
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                try:
                    if hub2.fabric_sched_ring() == want:
                        break
                except Exception:  # noqa: BLE001 — election settling
                    pass
                time.sleep(0.05)
            assert hub2.fabric_sched_ring() == want
            # the registry is soft state: gossiped, not logged — it may
            # or may not survive, but reads must serve
            assert isinstance(hub2.fabric_schedulers(), dict)
        finally:
            hub2.close()
    finally:
        for n in names:
            try:
                servers[n].stop()
            except Exception:  # noqa: BLE001 — already stopped
                pass
            try:
                replicas[n].close()
            except Exception:  # noqa: BLE001
                pass


# ------------------------------------------------ two-replica drain


def test_two_replicas_partition_and_bind_everything():
    hub = Hub()
    hub.create_node(MakeNode().name("n").label(LABEL_HOSTNAME, "n")
                    .capacity(cpu="64", memory="256Gi", pods="220").obj())
    cfg = default_config()
    cfg.batch_size = 8
    sm_a = SliceManager(hub, "sched-a", heartbeat_s=0.01, ttl_s=5.0)
    sm_b = SliceManager(hub, "sched-b", heartbeat_s=0.01, ttl_s=5.0)
    assert sm_a.tick() and _tick(sm_b) and _tick(sm_a)
    slots = hub.fabric_sched_ring()["slots"]
    ns_a = [ns for ns in (f"ns{i}" for i in range(64))
            if slots[ring_slot(ns, len(slots))] == "sched-a"][:4]
    ns_b = [ns for ns in (f"ns{i}" for i in range(64))
            if slots[ring_slot(ns, len(slots))] == "sched-b"][:4]
    assert len(ns_a) == 4 and len(ns_b) == 4

    sa = Scheduler(hub, cfg, caps=Capacities(nodes=8, pods=256))
    sb = Scheduler(hub, cfg, caps=Capacities(nodes=8, pods=256))
    sa.start(elector=sm_a)
    sb.start(elector=sm_b)
    uids = []
    try:
        for i in range(24):
            ns = (ns_a + ns_b)[i % 8]
            p = (MakePod().name(f"p{i}").namespace(ns)
                 .req(cpu="50m").obj())
            hub.create_pod(p)
            uids.append(p.metadata.uid)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            bound = sum(1 for u in uids
                        if hub.get_pod(u).spec.node_name)
            if bound == len(uids):
                break
            time.sleep(0.05)
        assert bound == len(uids), f"only {bound}/{len(uids)} bound"
        report = audit_bind_journal(hub=hub, expected_uids=uids)
        assert report["ok"], report
        # both replicas actually drained their own slices, and each
        # penned the other's pods instead of scheduling them (the
        # counters lag the hub commit by one result-drain, so poll)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if sa.stats["scheduled"] + sb.stats["scheduled"] == len(uids):
                break
            time.sleep(0.05)
        assert sa.stats["scheduled"] > 0 and sb.stats["scheduled"] > 0
        assert sa.stats["scheduled"] + sb.stats["scheduled"] == len(uids)
        assert sa.stats["foreign_stashed"] > 0
        assert sb.stats["foreign_stashed"] > 0
    finally:
        sa.stop()
        sb.stop()
        sa.close()
        sb.close()
        hub.close()


def test_undo_commit_survives_foreign_confirm_race():
    """Regression for the scaleout-storm flake: a sibling replica wins
    a post-rebalance race — its bind lands through our informer
    (add_pod replaces our ASSUMED entry with confirmed truth) while
    our own bind attempt is failing with Conflict. The failure path's
    forget_pod would raise KeyError("confirmed, cannot forget"); the
    guard must instead drop our claim and retire the pod unrequeued —
    the pod is placed, and it is the sibling's."""
    from kubernetes_tpu.backend.queue import QueuedPodInfo
    from kubernetes_tpu.framework.cycle_state import CycleState

    hub = Hub()
    hub.create_node(MakeNode().name("n1").capacity(cpu="64").obj())
    hub.create_node(MakeNode().name("n2").capacity(cpu="64").obj())
    cfg = default_config()
    sched = Scheduler(hub, cfg, caps=Capacities(nodes=8, pods=64))
    try:
        # the pod stays off the hub: creating it there would have the
        # informer enqueue it, muddying the requeue assertion below
        pod = MakePod().name("racy").req(cpu="100m").obj()
        assumed = pod.clone()
        assumed.spec.node_name = "n1"
        sched.cache.assume_pod(assumed)
        # the sibling's bind arrives via the informer: truth wins,
        # the assumed entry becomes a CONFIRMED placement on n2
        foreign = pod.clone()
        foreign.spec.node_name = "n2"
        sched.cache.add_pod(foreign)
        assert not sched.cache.is_assumed_pod(assumed)
        assert sched.cache.get_pod(assumed) is not None
        # now our own bind answers Conflict and unwinds — this raised
        # KeyError("confirmed, cannot forget") before the guard
        qp = QueuedPodInfo(pod=pod)
        sched._undo_commit(qp, CycleState(), assumed, "n1",
                           "bind failed: Conflict")
        # the foreign placement survived untouched, and the pod was
        # NOT requeued for a re-schedule of an already-bound pod
        assert sched.cache.get_pod(assumed).spec.node_name == "n2"
        assert sched.queue.pop_batch(8) == []
        # the timeline tells the story: this pod's /debug/pod (and any
        # autopsy bundle) shows WHO bound it, not a silent drop
        tl = sched.timelines.get(uid=pod.metadata.uid)
        evs = [e for e in tl["events"] if e["event"] == "foreign_bound"]
        assert len(evs) == 1
        assert "n2" in evs[0]["detail"]
        assert "undo-commit" in evs[0]["detail"]
    finally:
        sched.close()
        hub.close()


def test_commit_drops_attempt_when_foreign_bind_confirmed_first():
    """The commit-side half of the same race: the sibling's bind
    confirms through our informer BETWEEN the pop and _commit.
    assume_pod would raise KeyError("already in cache") — which took
    whole device batches down the host-fallback ladder in the storm —
    so _commit must drop the attempt instead of assuming."""
    from kubernetes_tpu.backend.queue import QueuedPodInfo

    hub = Hub()
    hub.create_node(MakeNode().name("n1").capacity(cpu="64").obj())
    hub.create_node(MakeNode().name("n2").capacity(cpu="64").obj())
    cfg = default_config()
    sched = Scheduler(hub, cfg, caps=Capacities(nodes=8, pods=64))
    try:
        pod = MakePod().name("racy2").req(cpu="100m").obj()
        foreign = pod.clone()
        foreign.spec.node_name = "n2"
        sched.cache.add_pod(foreign)       # sibling's confirmed bind
        qp = QueuedPodInfo(pod=pod)
        sched._commit(qp, "n1")            # raised KeyError before
        # no assumed state leaked, no binder-pool work was enqueued
        assumed = pod.clone()
        assumed.spec.node_name = "n1"
        assert not sched.cache.is_assumed_pod(assumed)
        assert sched.cache.get_pod(foreign).spec.node_name == "n2"
        assert sched.queue.pop_batch(8) == []
        # the pre-commit drop stamps the same foreign_bound story
        tl = sched.timelines.get(uid=pod.metadata.uid)
        evs = [e for e in tl["events"] if e["event"] == "foreign_bound"]
        assert len(evs) == 1
        assert "n2" in evs[0]["detail"]
        assert "pre-commit" in evs[0]["detail"]
    finally:
        sched.close()
        hub.close()


# ------------------------------------------------ the kill -9 storm


@pytest.mark.slow
def test_scaleout_storm_kill9_mid_wave():
    from kubernetes_tpu.chaos import run_scaleout_storm

    report = run_scaleout_storm(pods=120, nodes=8, replicas=3,
                                timeout_s=180.0)
    assert report["ok"], report
