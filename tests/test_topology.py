"""InterPodAffinity + PodTopologySpread kernel parity.

Scenarios mirror the reference's plugin unit-test tables
(interpodaffinity/filtering_test.go, scoring_test.go,
podtopologyspread/filtering_test.go) — built with real objects through the
Cache -> Snapshot -> Mirror path, evaluated via the batched pipeline."""

import numpy as np

from kubernetes_tpu.api.objects import (
    Affinity,
    Container,
    LABEL_HOSTNAME,
    LABEL_ZONE,
    LabelSelector,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodAffinity,
    PodAffinityTerm,
    PodAntiAffinity,
    PodSpec,
    ResourceRequirements,
    TopologySpreadConstraint,
    WeightedPodAffinityTerm,
)
from kubernetes_tpu.backend.cache import Cache
from kubernetes_tpu.backend.mirror import Mirror
from kubernetes_tpu.backend.snapshot import Snapshot
from kubernetes_tpu.models.pipeline import (
    FILTER_PLUGINS,
    default_weights,
    launch_batch,
)
from kubernetes_tpu.ops.features import Capacities

CAPS = Capacities(nodes=16, pods=64, domains=16)


def mknode(name, zone):
    return Node(metadata=ObjectMeta(name=name, labels={
        LABEL_HOSTNAME: name, LABEL_ZONE: zone}),
        status=NodeStatus(allocatable={"cpu": "32", "memory": "64Gi",
                                       "pods": "110"}))


def mkpod(name, labels=None, node=None, affinity=None, tsc=None, ns="default"):
    return Pod(
        metadata=ObjectMeta(name=name, namespace=ns, labels=labels or {}),
        spec=PodSpec(
            node_name=node or "",
            containers=[Container(name="c", resources=ResourceRequirements(
                requests={"cpu": "100m", "memory": "64Mi"}))],
            affinity=affinity,
            topology_spread_constraints=tsc or [],
        ))


def anti(topokey, **match):
    return Affinity(pod_anti_affinity=PodAntiAffinity(required=[
        PodAffinityTerm(topology_key=topokey,
                        label_selector=LabelSelector(match_labels=match))]))


def aff(topokey, **match):
    return Affinity(pod_affinity=PodAffinity(required=[
        PodAffinityTerm(topology_key=topokey,
                        label_selector=LabelSelector(match_labels=match))]))


class Cluster:
    def __init__(self, nodes, scheduled=()):
        self.cache = Cache()
        for n in nodes:
            self.cache.add_node(n)
        for p in scheduled:
            self.cache.add_pod(p)
        self.snap = Snapshot()
        self.cache.update_snapshot(self.snap)
        self.mirror = Mirror(caps=CAPS)
        self.mirror.sync(self.snap)

    def run(self, pods):
        spec = self.mirror.prepare_launch(pods, 8)
        out = launch_batch(spec, self.mirror.well_known(),
                           default_weights(), CAPS)
        names = [self.mirror.name_of_row(int(r)) if r >= 0 else None
                 for r in np.asarray(out.node_row)[: len(pods)]]
        return names, out


ZONES = [mknode("n1", "z1"), mknode("n2", "z1"), mknode("n3", "z2")]


def test_incoming_anti_affinity_zone():
    """Pod with zone anti-affinity to app=web avoids all of z1."""
    cl = Cluster(ZONES, [mkpod("w", {"app": "web"}, node="n1")])
    names, out = cl.run([mkpod("p", affinity=anti(LABEL_ZONE, app="web"))])
    assert names == ["n3"]
    ipa_idx = FILTER_PLUGINS.index("InterPodAffinity")
    assert np.asarray(out.reject_counts)[0, ipa_idx] == 2


def test_incoming_anti_affinity_hostname():
    cl = Cluster(ZONES, [mkpod("w", {"app": "web"}, node="n1")])
    names, _ = cl.run([mkpod("p", affinity=anti(LABEL_HOSTNAME, app="web"))])
    assert names[0] in ("n2", "n3")


def test_existing_pod_anti_affinity_blocks():
    """An existing pod's anti-affinity term keeps matching pods out of its
    whole zone (satisfyExistingPodsAntiAffinity)."""
    guard = mkpod("guard", {"team": "a"}, node="n1",
                  affinity=anti(LABEL_ZONE, app="web"))
    cl = Cluster(ZONES, [guard])
    names, _ = cl.run([mkpod("p", {"app": "web"})])
    assert names == ["n3"]


def test_required_affinity_follows():
    cl = Cluster(ZONES, [mkpod("w", {"app": "db"}, node="n3")])
    names, out = cl.run([mkpod("p", affinity=aff(LABEL_ZONE, app="db"))])
    assert names == ["n3"]


def test_required_affinity_first_pod_of_group():
    """No matching pod anywhere, but the pod matches its own term: allowed
    (the first pod of a self-affine group must be schedulable)."""
    cl = Cluster(ZONES)
    names, _ = cl.run([mkpod("p", {"app": "db"},
                             affinity=aff(LABEL_ZONE, app="db"))])
    assert names[0] is not None


def test_required_affinity_unsatisfiable_when_not_self_matching():
    cl = Cluster(ZONES)
    names, _ = cl.run([mkpod("p", affinity=aff(LABEL_ZONE, app="db"))])
    assert names == [None]


def test_in_batch_anti_affinity():
    """As-if-serial: two self-anti-affine pods in ONE batch must land in
    different zones, and a third must be unschedulable (2 zones)."""
    cl = Cluster(ZONES)
    pods = [mkpod(f"p{i}", {"app": "web"},
                  affinity=anti(LABEL_ZONE, app="web")) for i in range(3)]
    names, out = cl.run(pods)
    z = {"n1": "z1", "n2": "z1", "n3": "z2"}
    assert names[0] is not None and names[1] is not None
    assert z[names[0]] != z[names[1]]
    assert names[2] is None, "only two zones exist"


def test_in_batch_anti_affinity_matches_sequential():
    """One batch == sequential single-pod batches with host resync between."""
    def run_seq(cl, pods):
        placed = []
        for p in pods:
            names, _ = cl.run([p])
            placed.append(names[0])
            if names[0] is not None:
                bound = p.clone()
                bound.spec.node_name = names[0]
                cl.cache.add_pod(bound)
                cl.cache.update_snapshot(cl.snap)
                cl.mirror.sync(cl.snap)
        return placed

    mk = lambda i: mkpod(f"p{i}", {"app": "web"},
                         affinity=anti(LABEL_HOSTNAME, app="web"))
    batched, _ = Cluster(ZONES).run([mk(i) for i in range(4)])
    sequential = run_seq(Cluster(ZONES), [mk(i) for i in range(4)])
    assert batched == sequential


def test_in_batch_affinity_follows_batch_commit():
    """Pod 2's required affinity is satisfied by pod 1's in-batch commit."""
    cl = Cluster(ZONES)
    leader = mkpod("leader", {"app": "grp"},
                   affinity=aff(LABEL_ZONE, app="grp"))  # self-match rule
    follower = mkpod("follower", affinity=aff(LABEL_ZONE, app="grp"))
    names, _ = cl.run([leader, follower])
    z = {"n1": "z1", "n2": "z1", "n3": "z2"}
    assert names[0] is not None and names[1] is not None
    assert z[names[0]] == z[names[1]]


def test_in_batch_spread_counts():
    """Hard hostname spread within one batch: 3 pods, 3 nodes, one each."""
    cl = Cluster(ZONES)
    pods = [mkpod(f"p{i}", {"app": "s"},
                  tsc=[hard_spread(LABEL_HOSTNAME, app="s")])
            for i in range(4)]
    names, _ = cl.run(pods)
    assert sorted(names[:3]) == ["n1", "n2", "n3"]
    # 4th pod: every node at count 1, min 1 -> skew 1+1-1 = 1 <= 1: fits
    assert names[3] is not None


def hard_spread(key, max_skew=1, **sel):
    return TopologySpreadConstraint(
        max_skew=max_skew, topology_key=key, when_unsatisfiable="DoNotSchedule",
        label_selector=LabelSelector(match_labels=sel))


def soft_spread(key, max_skew=1, **sel):
    return TopologySpreadConstraint(
        max_skew=max_skew, topology_key=key, when_unsatisfiable="ScheduleAnyway",
        label_selector=LabelSelector(match_labels=sel))


def test_spread_filter_zone():
    """2 matching pods in z1, 0 in z2, maxSkew=1: z1 nodes rejected."""
    cl = Cluster(ZONES, [mkpod("a", {"app": "s"}, node="n1"),
                         mkpod("b", {"app": "s"}, node="n2")])
    names, out = cl.run([mkpod("p", {"app": "s"},
                               tsc=[hard_spread(LABEL_ZONE, app="s")])])
    assert names == ["n3"]
    sp_idx = FILTER_PLUGINS.index("PodTopologySpread")
    assert np.asarray(out.reject_counts)[0, sp_idx] == 2


def test_spread_filter_allows_balanced():
    cl = Cluster(ZONES, [mkpod("a", {"app": "s"}, node="n1"),
                         mkpod("b", {"app": "s"}, node="n3")])
    names, _ = cl.run([mkpod("p", {"app": "s"},
                             tsc=[hard_spread(LABEL_ZONE, app="s")])])
    assert names[0] is not None


def test_spread_hostname_sequential():
    """Hostname spreading drains one pod per node as the table fills."""
    cl = Cluster(ZONES)
    seen = []
    for i in range(3):
        p = mkpod(f"p{i}", {"app": "s"},
                  tsc=[hard_spread(LABEL_HOSTNAME, app="s")])
        names, _ = cl.run([p])
        assert names[0] is not None
        seen.append(names[0])
        bound = mkpod(f"p{i}", {"app": "s"}, node=names[0],
                      tsc=[hard_spread(LABEL_HOSTNAME, app="s")])
        cl.cache.add_pod(bound)
        cl.cache.update_snapshot(cl.snap)
        cl.mirror.sync(cl.snap)
    assert sorted(seen) == ["n1", "n2", "n3"]


def test_spread_soft_scores_less_crowded():
    """ScheduleAnyway: prefers the zone with fewer matching pods."""
    cl = Cluster(ZONES, [mkpod("a", {"app": "s"}, node="n1"),
                         mkpod("b", {"app": "s"}, node="n2")])
    names, _ = cl.run([mkpod("p", {"app": "s"},
                             tsc=[soft_spread(LABEL_ZONE, app="s")])])
    assert names == ["n3"]


def test_min_domains():
    """minDomains=3 with only 2 zones: global min treated as 0, so any node
    with matchNum >= maxSkew is rejected."""
    t = hard_spread(LABEL_ZONE, app="s")
    t.min_domains = 3
    cl = Cluster(ZONES, [mkpod("a", {"app": "s"}, node="n1")])
    names, _ = cl.run([mkpod("p", {"app": "s"}, tsc=[t])])
    # z1 has 1 matching pod: skew = 1 + 1 - 0 = 2 > 1 -> n1/n2 rejected;
    # z2 has 0: skew = 0 + 1 - 0 = 1 <= 1 -> n3 allowed
    assert names == ["n3"]


def test_preferred_affinity_scores():
    """Preferred zone affinity pulls the pod toward the matching zone."""
    w = Affinity(pod_affinity=PodAffinity(preferred=[
        WeightedPodAffinityTerm(weight=100, pod_affinity_term=PodAffinityTerm(
            topology_key=LABEL_ZONE,
            label_selector=LabelSelector(match_labels={"app": "db"})))]))
    cl = Cluster(ZONES, [mkpod("db", {"app": "db"}, node="n3")])
    names, _ = cl.run([mkpod("p", affinity=w)])
    assert names == ["n3"]


def test_new_topology_key_first_launch():
    """A topology key first referenced by the batch itself (not
    pre-registered) must be live on device for that same launch — the
    prepare_launch ordering guarantee (topo_dom backfill)."""
    nodes = [mknode("n1", "z1"), mknode("n2", "z2")]
    nodes[0].metadata.labels["rack"] = "r1"
    nodes[1].metadata.labels["rack"] = "r2"
    cl = Cluster(nodes, [mkpod("db", {"app": "db"}, node="n1")])
    names, _ = cl.run([mkpod("p", affinity=aff("rack", app="db"))])
    assert names == ["n1"]


def test_soft_spread_on_unlabeled_key_keeps_hard_filtering():
    """A ScheduleAnyway constraint on a key no node carries must not disable
    a DoNotSchedule constraint (eligibility sets are per-hardness)."""
    cl = Cluster(ZONES, [mkpod("a", {"app": "s"}, node="n1"),
                         mkpod("b", {"app": "s"}, node="n2")])
    names, out = cl.run([mkpod("p", {"app": "s"},
                               tsc=[hard_spread(LABEL_ZONE, app="s"),
                                    soft_spread("rack", app="s")])])
    assert names == ["n3"]
    sp_idx = FILTER_PLUGINS.index("PodTopologySpread")
    assert np.asarray(out.reject_counts)[0, sp_idx] == 2


def test_nil_spread_selector_matches_nothing():
    """labelSelector=None on a spread constraint selects no pods
    (labels.Nothing()): no rejects anywhere."""
    t = TopologySpreadConstraint(max_skew=1, topology_key=LABEL_ZONE,
                                 when_unsatisfiable="DoNotSchedule",
                                 label_selector=None)
    cl = Cluster(ZONES, [mkpod("a", {"app": "s"}, node="n1"),
                         mkpod("b", {"app": "s"}, node="n1"),
                         mkpod("c", {"app": "s"}, node="n1")])
    names, out = cl.run([mkpod("p", {"app": "s"}, tsc=[t])])
    sp_idx = FILTER_PLUGINS.index("PodTopologySpread")
    assert np.asarray(out.reject_counts)[0, sp_idx] == 0
    assert names[0] is not None


def test_preferred_anti_affinity_scores():
    w = Affinity(pod_anti_affinity=PodAntiAffinity(preferred=[
        WeightedPodAffinityTerm(weight=100, pod_affinity_term=PodAffinityTerm(
            topology_key=LABEL_ZONE,
            label_selector=LabelSelector(match_labels={"app": "db"})))]))
    cl = Cluster(ZONES, [mkpod("db", {"app": "db"}, node="n1")])
    names, _ = cl.run([mkpod("p", affinity=w)])
    assert names == ["n3"]


# suite-tier discipline (tests/test_markers.py): area marker
import pytest  # noqa: E402
pytestmark = pytest.mark.core
