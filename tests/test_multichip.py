"""Multi-chip sharding parity: the batched scheduling step under an 8-device
mesh with the node axis sharded must produce bit-identical placements to the
unsharded run (SURVEY.md §5.8: node rows are the data-parallel axis; argmax
and score normalizations become XLA collectives over the mesh).

Runs on the virtual 8-device CPU platform forced by conftest.py — the same
configuration the driver uses for `__graft_entry__.dryrun_multichip`.
"""

from functools import partial

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from kubernetes_tpu.models.pipeline import default_weights, schedule_batch
from kubernetes_tpu.models.testbed import build_cluster, make_pod
from kubernetes_tpu.ops.features import Capacities


N_DEV = 8


@pytest.fixture(scope="module")
def example():
    caps = Capacities(nodes=16 * N_DEV, pods=256)
    _, _, mirror = build_cluster(12 * N_DEV, caps=caps)
    # full-schema pod blobs: the sharded parity check runs the default
    # (subset-free) unpack path
    pblobs = mirror.pack_batch_blobs([make_pod(i) for i in range(8)], 8)
    cblobs = mirror.to_blobs()
    return caps, cblobs, pblobs, mirror.well_known(), default_weights()


def test_devices_available():
    assert len(jax.devices()) >= N_DEV


def test_sharded_matches_unsharded(example):
    caps, cblobs, pblobs, wk, weights = example
    fn = partial(schedule_batch, caps=caps)

    base = jax.jit(fn)(cblobs, pblobs, wk, weights)

    import __graft_entry__ as g

    mesh = Mesh(np.asarray(jax.devices()[:N_DEV]), ("nodes",))
    in_sh = g.mesh_shardings(mesh, pblobs, wk, weights)
    sharded = jax.jit(fn, in_shardings=in_sh)(cblobs, pblobs, wk, weights)
    jax.block_until_ready(sharded)

    np.testing.assert_array_equal(np.asarray(base.node_row),
                                  np.asarray(sharded.node_row))
    np.testing.assert_array_equal(np.asarray(base.feasible_count),
                                  np.asarray(sharded.feasible_count))
    np.testing.assert_array_equal(np.asarray(base.reject_counts),
                                  np.asarray(sharded.reject_counts))
    np.testing.assert_allclose(np.asarray(base.score),
                               np.asarray(sharded.score), rtol=1e-5)
    assert int((np.asarray(sharded.node_row) >= 0).sum()) == 8


def test_graft_dryrun_entrypoint():
    """The exact function the driver invokes must succeed in-process."""
    import __graft_entry__ as g

    g.dryrun_multichip(N_DEV)
