"""Multi-chip sharding parity: the batched scheduling step under an 8-device
mesh with the node axis sharded must produce bit-identical placements to the
unsharded run (SURVEY.md §5.8: node rows are the data-parallel axis; argmax
and score normalizations become XLA collectives over the mesh).

Runs on the virtual 8-device CPU platform forced by conftest.py — the same
configuration the driver uses for `__graft_entry__.dryrun_multichip`.
"""

from functools import partial

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from kubernetes_tpu.models.pipeline import default_weights, schedule_batch
from kubernetes_tpu.models.testbed import build_cluster, make_pod
from kubernetes_tpu.ops.features import Capacities


N_DEV = 8


@pytest.fixture(scope="module")
def example():
    caps = Capacities(nodes=16 * N_DEV, pods=256)
    _, _, mirror = build_cluster(12 * N_DEV, caps=caps)
    # full-schema pod blobs: the sharded parity check runs the default
    # (subset-free) unpack path
    pblobs = mirror.pack_batch_blobs([make_pod(i) for i in range(8)], 8)
    cblobs = mirror.to_blobs()
    return caps, cblobs, pblobs, mirror.well_known(), default_weights()


def test_devices_available():
    assert len(jax.devices()) >= N_DEV


def test_sharded_matches_unsharded(example):
    caps, cblobs, pblobs, wk, weights = example
    fn = partial(schedule_batch, caps=caps)

    base = jax.jit(fn)(cblobs, pblobs, wk, weights)

    import __graft_entry__ as g

    mesh = Mesh(np.asarray(jax.devices()[:N_DEV]), ("nodes",))
    in_sh = g.mesh_shardings(mesh, pblobs, wk, weights)
    sharded = jax.jit(fn, in_shardings=in_sh)(cblobs, pblobs, wk, weights)
    jax.block_until_ready(sharded)

    np.testing.assert_array_equal(np.asarray(base.node_row),
                                  np.asarray(sharded.node_row))
    np.testing.assert_array_equal(np.asarray(base.feasible_count),
                                  np.asarray(sharded.feasible_count))
    np.testing.assert_array_equal(np.asarray(base.reject_counts),
                                  np.asarray(sharded.reject_counts))
    np.testing.assert_allclose(np.asarray(base.score),
                               np.asarray(sharded.score), rtol=1e-5)
    assert int((np.asarray(sharded.node_row) >= 0).sum()) == 8


def test_graft_dryrun_entrypoint():
    """The exact function the driver invokes must succeed in-process."""
    import __graft_entry__ as g

    g.dryrun_multichip(N_DEV)


# ---------------------------------------------------------------------------
# Production-path parity: the REAL Scheduler drain loop (queue -> cache ->
# mirror -> batched launches -> commit/bind) runs under a mesh handed to
# Scheduler(mesh=...) and must place every pod on the same node as the
# unsharded scheduler. Covers, at 1k nodes: the parallel-rounds auction
# (plain pods), the serial topology commit scan (anti-affinity + spread
# batches), and the preemption sweep (victim cumsum on sharded blobs).
# ---------------------------------------------------------------------------

from kubernetes_tpu.config.types import default_config  # noqa: E402
from kubernetes_tpu.hub import Hub  # noqa: E402
from kubernetes_tpu.ops.features import Capacities  # noqa: E402
from kubernetes_tpu.scheduler import Scheduler  # noqa: E402
from kubernetes_tpu.testing.parity import (  # noqa: E402
    drive_production_scenario,
    make_node as parity_node,
    make_pod as parity_pod,
)


def _run_production(mesh, n_nodes=1024):
    """The shared scenario driver at 1k-node scale: 64 auction pods, 16
    anti-affinity + 16 spread topology pods, 8 fillers saturating a
    4-node gold pool, 4 preemptors."""
    return drive_production_scenario(
        mesh, n_nodes, Capacities(nodes=n_nodes, pods=512),
        zones=8, gold_nodes=4, plain=64, anti=16, spread=16, low=8,
        high=4, batch_size=16, drain_rounds=6)


def test_mesh_survives_capacity_growth():
    """A CapacityError re-bucket (_grow) rebuilds the mirror — it must keep
    the mesh, or a sharded scheduler silently degrades to single-device
    exactly when the node table just outgrew one chip."""
    hub = Hub()
    cfg = default_config()
    cfg.batch_size = 8
    cfg.async_binding = False
    mesh = Mesh(np.asarray(jax.devices()[:N_DEV]), ("nodes",))
    sched = Scheduler(hub, cfg, caps=Capacities(nodes=16, pods=64),
                      mesh=mesh)
    # 40 nodes overflow the 16-row bucket: sync raises CapacityError and
    # _grow re-buckets the mirror mid-dispatch
    for i in range(40):
        hub.create_node(parity_node(i, zone=f"z{i % 2}"))
    for i in range(8):
        hub.create_pod(parity_pod(f"p-{i}"))
    sched.run_until_idle()
    assert sched.caps.nodes >= 40
    assert sched.mirror.mesh is mesh
    blob = sched.mirror.to_blobs().node_f32
    assert len(blob.sharding.device_set) == N_DEV
    assert all(p.spec.node_name for p in hub.list_pods())


def test_production_scheduler_mesh_parity_1k_nodes():
    base, s_base = _run_production(None)
    assert s_base.mirror.mesh is None
    mesh = Mesh(np.asarray(jax.devices()[:N_DEV]), ("nodes",))
    sharded, s_sh = _run_production(mesh)
    # the sharded scheduler really holds sharded resident blobs
    blob = s_sh.mirror.to_blobs().node_f32
    assert len(blob.sharding.device_set) == N_DEV
    assert not blob.sharding.is_fully_replicated
    # identical surviving pod sets (victim evictions included) and
    # identical placements, pod by pod
    assert set(base) == set(sharded)
    # evictions happened: some low-priority victims were deleted
    assert len(base) < 64 + 32 + 8 + 4
    diffs = {k: (base[k], sharded[k]) for k in base
             if base[k] != sharded[k]}
    assert not diffs, diffs
    # phase C actually preempted: all 4 high pods landed in the gold pool
    for i in range(4):
        assert sharded[f"high-{i}"] is not None
        row = int(sharded[f"high-{i}"].split("-")[1])
        assert row < 4
    # every pod (including later-evicted victims) was scheduled at least once
    assert s_sh.stats["scheduled"] == 64 + 32 + 8 + 4
    assert s_sh.stats["scheduled"] == s_base.stats["scheduled"]


# suite-tier discipline (tests/test_markers.py): area marker
pytestmark = pytest.mark.core
