"""Learned scoring subsystem: checkpoint format + hot reload, replay
trainer determinism, the fused MLP term's differential parity and
fallback-ladder containment, trace-export placements (v2) + rotation,
and the tie-break seed.

The tier-1 slice keeps a <30s smoke train on a tiny synthetic replay
(the CI guarantee the ISSUE asks for); heavier end-to-end loops are
slow-marked.
"""

import dataclasses
import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_tpu.api.objects import (
    Container,
    LABEL_HOSTNAME,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    ResourceRequirements,
)
from kubernetes_tpu.config.types import Plugin, default_config
from kubernetes_tpu.hub import Hub
from kubernetes_tpu.learn.checkpoint import (
    CheckpointError,
    CheckpointWatcher,
    load_checkpoint,
    save_checkpoint,
)
from kubernetes_tpu.learn.replay import (
    build_dataset,
    synthetic_dataset,
)
from kubernetes_tpu.learn.train import (
    TrainConfig,
    identity_params,
    init_params,
    train,
)
from kubernetes_tpu.models.pipeline import default_weights, launch_batch
from kubernetes_tpu.ops.features import Capacities
from kubernetes_tpu.ops.learned import (
    FEATURE_VERSION,
    NUM_FEATURES,
    mlp_apply,
)
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.utils.tracing import FlightRecorder

pytestmark = pytest.mark.learned

CAPS = Capacities(nodes=16, pods=64)


def mknode(i, cpu="8"):
    return Node(metadata=ObjectMeta(name=f"node-{i}",
                                    labels={LABEL_HOSTNAME: f"node-{i}"}),
                status=NodeStatus(allocatable={"cpu": cpu,
                                               "memory": "16Gi",
                                               "pods": "110"}))


def mkpod(name, cpu="100m"):
    return Pod(metadata=ObjectMeta(name=name),
               spec=PodSpec(containers=[Container(
                   name="c", resources=ResourceRequirements(
                       requests={"cpu": cpu}))]))


def _bound_node(hub, name):
    for p in hub.list_pods():
        if p.metadata.name == name:
            return p.spec.node_name
    return None


def _mirror_for(nodes, pods=()):
    from kubernetes_tpu.backend.cache import Cache
    from kubernetes_tpu.backend.mirror import Mirror
    from kubernetes_tpu.backend.snapshot import Snapshot

    cache = Cache()
    for n in nodes:
        cache.add_node(n)
    for p in pods:
        cache.add_pod(p)
    snap = Snapshot()
    cache.update_snapshot(snap)
    mirror = Mirror(caps=CAPS)
    mirror.sync(snap)
    return mirror


def _learned_cfg(ckpt_path, weight=1.0, **cfg_kw):
    cfg = default_config()
    cfg.batch_size = 16
    prof = cfg.profiles[0]
    prof.plugins.score.enabled.append(Plugin("LearnedScore", weight))
    prof.plugin_config["LearnedScore"] = {"checkpoint_path": ckpt_path}
    for k, v in cfg_kw.items():
        setattr(cfg, k, v)
    return cfg


# ------------------------------------------------------ checkpoint ---


def test_checkpoint_roundtrip(tmp_path):
    path = str(tmp_path / "ck.json")
    params = init_params(seed=3, hidden=(8,))
    doc = save_checkpoint(path, params, meta={"version": 7})
    assert doc["meta"]["fingerprint"]
    from kubernetes_tpu.ops.learned import FEATURE_VERSION

    loaded, meta = load_checkpoint(path)
    assert meta["version"] == 7
    assert meta["feature_version"] == FEATURE_VERSION
    assert len(loaded) == 2
    for (w0, b0), (w1, b1) in zip(params, loaded):
        np.testing.assert_array_equal(np.asarray(w0, np.float32), w1)
        np.testing.assert_array_equal(np.asarray(b0, np.float32), b1)


@pytest.mark.parametrize("payload", [
    "not json at all {",
    json.dumps({"format_version": 99, "layers": []}),
    json.dumps({"format_version": 1, "feature_version": 99,
                "layers": [{"w": [[1.0]], "b": [0.0]}]}),
    json.dumps({"format_version": 1, "feature_version": FEATURE_VERSION,
                "layers": [{"w": [[1.0] * 3] * NUM_FEATURES,
                            "b": [0.0] * 3}]}),   # head not scalar
    json.dumps({"format_version": 1, "feature_version": FEATURE_VERSION,
                "layers": [{"w": [[1.0]], "b": [0.0]}]}),  # wrong fan-in
], ids=["garbage", "format", "feature", "head", "fanin"])
def test_checkpoint_corrupt_rejected(tmp_path, payload):
    path = str(tmp_path / "bad.json")
    with open(path, "w") as f:
        f.write(payload)
    with pytest.raises(CheckpointError):
        load_checkpoint(path)


def test_watcher_missing_file_is_waiting_not_error(tmp_path):
    """Scheduler-before-trainer deployment order: polling a checkpoint
    that has not been published yet is a clean waiting state, not a
    load error (the corrupt-file alert must stay meaningful)."""
    path = str(tmp_path / "later.json")
    w = CheckpointWatcher(path)
    assert not w.poll() and not w.poll()
    assert w.load_errors == 0 and w.last_error is None
    save_checkpoint(path, identity_params(), meta={"version": 1})
    assert w.poll() and w.loads == 1 and w.load_errors == 0


def test_watcher_retries_transient_read_failure(tmp_path, monkeypatch):
    """A transient READ failure on a freshly published version must not
    permanently skip it: the next poll retries (parse errors, by
    contrast, keep the stamp — no per-cycle re-parse of a corrupt
    file)."""
    import kubernetes_tpu.learn.checkpoint as ck

    path = str(tmp_path / "ck.json")
    save_checkpoint(path, identity_params(), meta={"version": 1})
    w = CheckpointWatcher(path)
    real = ck.load_checkpoint

    def blip(p):
        raise CheckpointError("unreadable") from OSError("nfs blip")

    monkeypatch.setattr(ck, "load_checkpoint", blip)
    assert not w.poll() and w.load_errors == 1 and w.params is None
    monkeypatch.setattr(ck, "load_checkpoint", real)
    assert w.poll(), "same version retried after the transient failure"
    assert w.meta["version"] == 1


def test_watcher_keeps_last_good_params(tmp_path):
    path = str(tmp_path / "ck.json")
    save_checkpoint(path, identity_params(), meta={"version": 1})
    w = CheckpointWatcher(path)
    assert w.poll() and w.params is not None and w.loads == 1
    assert not w.poll(), "unchanged mtime is a no-op"
    good = w.params
    with open(path, "w") as f:
        f.write("corrupt{")
    os.utime(path, (1e9, 1e9))     # force a distinct stamp
    assert not w.poll()
    assert w.load_errors == 1 and w.last_error
    assert w.params is good, "corrupt overwrite keeps the last good stack"
    save_checkpoint(path, identity_params(), meta={"version": 2})
    assert w.poll() and w.meta["version"] == 2


# --------------------------------------------------------- trainer ---


def test_smoke_train_is_deterministic_and_learns():
    """The tier-1 smoke train: tiny synthetic replay, seconds on CPU."""
    ds = synthetic_dataset(seed=1, n=256)
    cfg = TrainConfig(hidden=(8,), seed=5, bc_epochs=60, ft_epochs=20)
    p1, info1 = train(ds, cfg)
    p2, info2 = train(ds, cfg)
    for (w1, b1), (w2, b2) in zip(p1, p2):
        np.testing.assert_array_equal(w1, w2)
        np.testing.assert_array_equal(b1, b2)
    assert info1["bc_loss_last"] < info1["bc_loss_first"], \
        "behavior cloning must reduce the loss"
    assert info1 == info2


@pytest.mark.slow
def test_fine_tune_moves_scorer_toward_outcomes():
    """The reward-weighted fine-tune must move the policy OFF the
    cloned hand-tuned aggregate in the direction the outcome labels
    point: synthetic rewards favor low-utilization placements, so the
    fine-tuned scorer widens the empty-vs-hot node score gap relative
    to the BC-only scorer."""
    ds = synthetic_dataset(seed=3, n=2048)
    bc, _ = train(ds, TrainConfig(hidden=(16,), seed=1, bc_epochs=400,
                                  ft_epochs=0))
    ft, _ = train(ds, TrainConfig(hidden=(16,), seed=1, bc_epochs=400,
                                  ft_epochs=400))
    lo = np.full((1, NUM_FEATURES), 0.5, np.float32)
    hi = lo.copy()
    lo[0, 0] = lo[0, 1] = 0.0    # empty node
    hi[0, 0] = hi[0, 1] = 1.0    # hot node

    def gap(params):
        p = tuple((jnp.asarray(w), jnp.asarray(b)) for w, b in params)
        return (float(mlp_apply(p, jnp.asarray(lo))[0])
                - float(mlp_apply(p, jnp.asarray(hi))[0]))

    assert gap(ft) > gap(bc), \
        "fine-tune should favor the low-utilization placement more"


def test_identity_params_reproduce_hand_tuned_aggregate():
    # on feature rows where every score is s/100, the identity stack
    # returns the hand-tuned aggregate rescaled to 0..100 (since v3 the
    # feature vector carries the spread/ipa columns too — derived from
    # the LIVE hand_weight_vector, so the fixture tracks the layout)
    from kubernetes_tpu.ops.learned import hand_weight_vector

    n_scores = NUM_FEATURES - 2          # frac_cpu/frac_mem carry w=0
    feats = np.zeros((4, NUM_FEATURES), np.float32)
    feats[0, 2:] = 1.0
    feats[2, 2:] = 0.5
    feats[3, 2] = 1.0                    # fit-only row
    hand = hand_weight_vector()
    fit_only = 100.0 * hand[2] / hand.sum()
    out = np.asarray(mlp_apply(identity_params(), jnp.asarray(feats)))
    np.testing.assert_allclose(out, [100.0, 0.0, 50.0, fit_only],
                               atol=1e-4)
    assert n_scores == hand[2:].size


# ---------------------------------------------------------- replay ---


def _trace_line(start, placements, v=2):
    return json.dumps({"v": v, "cycle": 1, "start": start, "pods": 2,
                       "phases_ms": {}, "placements": placements})


def test_build_dataset_from_export(tmp_path):
    path = str(tmp_path / "t.jsonl")
    feat = [0.1] * NUM_FEATURES
    with open(path, "w") as f:
        # first attempt fails (time-to-bind anchor), second binds
        f.write(_trace_line(10.0, [
            {"pod": "default/a", "uid": "u-a", "node": None}]) + "\n")
        f.write(_trace_line(12.0, [
            {"pod": "default/a", "uid": "u-a", "node": "n1",
             "score": 400.0, "feat": feat},
            {"pod": "default/b", "uid": "u-b", "node": "n2",
             "score": 800.0, "feat": feat}]) + "\n")
        f.write("torn{line\n")
        f.write(_trace_line(1.0, [], v=1) + "\n")   # pre-v2: skipped
    ds = build_dataset([path])
    assert len(ds) == 2
    assert ds.x.shape == (2, NUM_FEATURES)
    # BC targets come from the feature rows (feat 0.1 everywhere ->
    # (0.1 * 8) * 100/8 = 10), NOT the topology-contaminated aggregate;
    # the exported aggregate rides along for analysis
    assert ds.y[0] == pytest.approx(10.0) and ds.y[1] == pytest.approx(10.0)
    assert ds.agg_score.tolist() == [400.0, 800.0]
    # pod a took 2s vs the 0s median peer: its reward is shaded below b's
    assert ds.reward[0] < ds.reward[1]
    assert ds.meta["skipped_pre_v2"] == 1


def test_build_dataset_requires_v2_rows(tmp_path):
    path = str(tmp_path / "t.jsonl")
    with open(path, "w") as f:
        f.write(_trace_line(1.0, [], v=1) + "\n")
    with pytest.raises(ValueError):
        build_dataset([path])


# ------------------------------------------- differential parity -----


def _launch_rows(mirror, pods, weights, learned=None, tie_seed=None):
    spec = mirror.prepare_launch(pods, 8)
    out = launch_batch(spec, mirror.well_known(), weights, CAPS,
                       learned=learned, tie_seed=tie_seed)
    return np.asarray(out.node_row)[:len(pods)].tolist()


def test_zero_weight_learned_matches_baseline_exactly():
    """weights.learned == 0: the MLP term contributes exactly 0.0 to the
    aggregate, so placements match the baseline on every scenario."""
    nodes = [mknode(i, cpu=str(2 + i)) for i in range(5)]
    pods = [mkpod(f"p{i}", cpu=f"{200 + 100 * i}m") for i in range(6)]
    mirror = _mirror_for(nodes)
    base = _launch_rows(mirror, pods, default_weights())
    params = tuple((jnp.asarray(w), jnp.asarray(b))
                   for w, b in init_params(seed=9, hidden=(8,)))
    got = _launch_rows(mirror, pods, default_weights(), learned=params)
    assert got == base


def test_identity_init_learned_matches_baseline_placements():
    """Identity-init at weight 1 only rescales the aggregate on
    topology-free batches -> identical placements (the golden-fixture
    differential the ISSUE asks for, on the fit scenarios)."""
    nodes = [mknode(i, cpu=str(2 + i)) for i in range(5)]
    pods = [mkpod(f"p{i}", cpu=f"{200 + 100 * i}m") for i in range(6)]
    mirror = _mirror_for(nodes)
    base = _launch_rows(mirror, pods, default_weights())
    params = tuple((jnp.asarray(w), jnp.asarray(b))
                   for w, b in identity_params())
    w = dataclasses.replace(default_weights(), learned=jnp.float32(1.0))
    got = _launch_rows(mirror, pods, w, learned=params)
    assert got == base


def test_tie_seed_runs_are_reproducible():
    nodes = [mknode(i) for i in range(8)]      # identical: all tie
    pods = [mkpod(f"p{i}") for i in range(6)]
    mirror = _mirror_for(nodes)
    seed = np.uint32(424242)
    a = _launch_rows(mirror, pods, default_weights(), tie_seed=seed)
    b = _launch_rows(mirror, pods, default_weights(), tie_seed=seed)
    assert a == b, "same seed, same batch -> identical placements"
    unseeded = _launch_rows(mirror, pods, default_weights(),
                            tie_seed=np.uint32(0))
    legacy = _launch_rows(mirror, pods, default_weights())
    assert unseeded == legacy, "seed 0 is the historical hash stream"


# --------------------------------------- scheduler integration -------


def test_nan_checkpoint_file_rejected_at_load(tmp_path):
    """A well-formed checkpoint carrying NaN weights (diverged training
    run) must be REJECTED at load — it must never become the watcher's
    'last good' params and put the scheduler into perpetual fallback."""
    path = str(tmp_path / "nan.json")
    w = np.full((NUM_FEATURES, 1), np.nan, np.float32)
    save_checkpoint(path, ((w, np.zeros((1,), np.float32)),),
                    meta={"version": 13})
    with pytest.raises(CheckpointError, match="non-finite"):
        load_checkpoint(path)
    # a scheduler pointed at it keeps scheduling hand-tuned, errors
    # counted, nothing degrades
    hub = Hub()
    sched = Scheduler(hub, _learned_cfg(path),
                      caps=Capacities(nodes=16, pods=64))
    try:
        hub.create_node(mknode(0))
        hub.create_pod(mkpod("p0"))
        sched.run_until_idle()
        assert _bound_node(hub, "p0")
        assert sched.stats["device_fallbacks"] == 0
        mgr = sched._profile_cfg["default-scheduler"]["learned"]
        assert mgr.params() is None
        assert mgr.stats()["load_errors"] >= 1
    finally:
        sched.close()


def test_nan_params_fire_fallback_ladder(tmp_path):
    """Params that go bad PAST the loader (in-memory corruption, a
    future loader gap) trip the launch guard and degrade THAT batch to
    the host path — scheduling continues on hand-tuned weights."""
    path = str(tmp_path / "good.json")
    save_checkpoint(path, identity_params(), meta={"version": 1})
    hub = Hub()
    sched = Scheduler(hub, _learned_cfg(path),
                      caps=Capacities(nodes=16, pods=64))
    try:
        mgr = sched._profile_cfg["default-scheduler"]["learned"]
        mgr.maybe_reload()
        assert mgr.params() is not None
        nan_w = jnp.full((NUM_FEATURES, 1), jnp.nan, jnp.float32)
        mgr._device_params = ((nan_w, jnp.zeros((1,), jnp.float32)),)
        mgr.maybe_reload = lambda: False      # keep the poison served
        hub.create_node(mknode(0))
        for i in range(3):
            hub.create_pod(mkpod(f"p{i}"))
        sched.run_until_idle()
        assert sched.stats["scheduled"] == 3, \
            "the fallback ladder must keep scheduling"
        assert sched.stats["device_fallbacks"] >= 1, \
            "the NaN params must have tripped the guard"
        for i in range(3):
            assert _bound_node(hub, f"p{i}")
    finally:
        sched.close()


def test_smoke_train_checkpoint_hot_reload_schedule_loop(tmp_path):
    """The end-to-end loop on CPU: smoke-train -> checkpoint -> schedule
    with the learned profile -> publish a new checkpoint -> hot reload
    at snapshot-sync time -> keep scheduling."""
    path = str(tmp_path / "scorer.json")
    params, info = train(synthetic_dataset(seed=2, n=128),
                         TrainConfig(hidden=(8,), bc_epochs=40,
                                     ft_epochs=10,
                                     meta={"version": 1}))
    save_checkpoint(path, params, meta=info)
    hub = Hub()
    sched = Scheduler(hub, _learned_cfg(path),
                      caps=Capacities(nodes=16, pods=64))
    try:
        mgr = sched._profile_cfg["default-scheduler"]["learned"]
        assert mgr is not None
        hub.create_node(mknode(0))
        hub.create_pod(mkpod("p0"))
        sched.run_until_idle()
        assert _bound_node(hub, "p0")
        assert mgr.params() is not None and mgr.version == 1
        assert sched.stats["device_fallbacks"] == 0
        assert sched.metrics.learned_magnitude.total_count() >= 1
        # publish v2; force a distinct mtime stamp for coarse clocks
        save_checkpoint(path, params, meta={**info, "version": 2})
        os.utime(path, (2e9, 2e9))
        hub.create_pod(mkpod("p1"))
        sched.run_until_idle()
        assert _bound_node(hub, "p1")
        assert mgr.version == 2 and mgr.reloads == 1
        # a manual publish (no loop generation in meta) counts under
        # generation "0" — the promoted-vs-manual fleet distinction
        assert sched.metrics.learned_reloads.value(
            profile="default-scheduler", generation="0") == 1.0
    finally:
        sched.close()


def test_profile_off_passes_no_learned_params(tmp_path):
    hub = Hub()
    cfg = default_config()
    cfg.batch_size = 16
    sched = Scheduler(hub, cfg, caps=Capacities(nodes=16, pods=64))
    try:
        assert sched._profile_cfg["default-scheduler"]["learned"] is None
        hub.create_node(mknode(0))
        hub.create_pod(mkpod("p0"))
        sched.run_until_idle()
        assert sched.metrics.learned_magnitude.total_count() == 0
    finally:
        sched.close()


# ------------------------------------ export placements + rotation ---


def test_export_v2_placements_feed_the_dataset_builder(tmp_path):
    export = str(tmp_path / "traces.jsonl")
    hub = Hub()
    cfg = default_config()
    cfg.batch_size = 16
    cfg.trace_export_path = export
    cfg.trace_export_features = True
    sched = Scheduler(hub, cfg, caps=Capacities(nodes=16, pods=64))
    try:
        hub.create_node(mknode(0))
        for i in range(4):
            hub.create_pod(mkpod(f"p{i}"))
        sched.run_until_idle()
    finally:
        sched.close()
    lines = [json.loads(x) for x in open(export) if x.strip()]
    # writer emits the current format; v2 rows remain valid replay input
    from kubernetes_tpu.utils.tracing import EXPORT_VERSION
    assert lines and all(ln["v"] == EXPORT_VERSION for ln in lines)
    rows = [r for ln in lines for r in ln.get("placements", [])]
    placed = [r for r in rows if r["node"]]
    assert len(placed) == 4
    for r in placed:
        assert r["node"] == "node-0"
        assert len(r["feat"]) == NUM_FEATURES
        assert r["score"] > 0
    # and the builder accepts the real export end to end
    ds = build_dataset([export])
    assert len(ds) == 4 and ds.x.shape[1] == NUM_FEATURES


def test_export_without_feature_optin_omits_feat(tmp_path):
    """trace_export_path alone stays the cheap PR-4 surface: placement
    rows carry (pod, node, score) but no feature vectors, and the
    launch is compiled without the feature kernels."""
    export = str(tmp_path / "t.jsonl")
    hub = Hub()
    cfg = default_config()
    cfg.batch_size = 16
    cfg.trace_export_path = export
    sched = Scheduler(hub, cfg, caps=Capacities(nodes=16, pods=64))
    try:
        assert sched._export_feats is False
        hub.create_node(mknode(0))
        hub.create_pod(mkpod("p0"))
        sched.run_until_idle()
    finally:
        sched.close()
    rows = [r for ln in (json.loads(x) for x in open(export) if x.strip())
            for r in ln.get("placements", [])]
    placed = [r for r in rows if r["node"]]
    assert placed and all("feat" not in r and r["score"] > 0
                          for r in placed)


def test_export_rotation_bounds_disk(tmp_path):
    path = str(tmp_path / "t.jsonl")
    rec = FlightRecorder(capacity=8, export_path=path,
                         export_max_bytes=2000)
    for i in range(100):
        tr = rec.begin(start=float(i), pods=1)
        tr.add("commit", 0.001)
        rec.record(tr)
    rec.close()
    assert os.path.exists(path + ".1"), "keep-last-1 rotation happened"
    assert os.path.getsize(path) <= 2200
    assert os.path.getsize(path + ".1") <= 2200
    # every surviving line is intact JSON (rotation never tears a line)
    for p in (path, path + ".1"):
        for line in open(p):
            json.loads(line)


def test_export_rotation_failure_disables_export(tmp_path, monkeypatch):
    """A failed rotation (permissions/directory gone) must DISABLE the
    export, not fall back to unbounded appends — the size bound is the
    feature's contract."""
    path = str(tmp_path / "t.jsonl")
    rec = FlightRecorder(capacity=8, export_path=path,
                         export_max_bytes=500)

    def deny(*_a):
        raise OSError("denied")

    monkeypatch.setattr("kubernetes_tpu.utils.tracing.os.replace", deny)
    for i in range(50):
        tr = rec.begin(start=float(i), pods=1)
        tr.add("commit", 0.001)
        rec.record(tr)
    assert not rec.exporting, "failed rotation disables the export"
    assert os.path.getsize(path) <= 700, "writes stopped at the bound"
    rec.close()


# ------------------------------------------------------------- CLI ---


def test_cli_train_and_inspect(tmp_path, capsys):
    from kubernetes_tpu.learn.__main__ import main

    out = str(tmp_path / "ck.json")
    assert main(["train", "--synthetic", "64", "--out", out,
                 "--bc-epochs", "20", "--ft-epochs", "5",
                 "--version", "3"]) == 0
    capsys.readouterr()
    assert main(["inspect", out]) == 0
    meta = json.loads(capsys.readouterr().out)["meta"]
    assert meta["version"] == 3
    params, _ = load_checkpoint(out)
    assert params[0][0].shape == (NUM_FEATURES, 8)
