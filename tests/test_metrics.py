"""Metrics + async recorder + serving endpoints (reference:
metrics/metrics.go:147-335, metric_recorder.go, app/server.go:252)."""

import json
import urllib.request

from kubernetes_tpu.metrics import (
    AsyncRecorder,
    Counter,
    Histogram,
    Registry,
    SchedulerMetrics,
)
from kubernetes_tpu.serving import ServingEndpoints

from kubernetes_tpu.api.objects import (
    Container,
    LABEL_HOSTNAME,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    ResourceRequirements,
)
from kubernetes_tpu.config.types import default_config
from kubernetes_tpu.hub import Hub
from kubernetes_tpu.ops.features import Capacities
from kubernetes_tpu.scheduler import Scheduler


def test_histogram_percentiles_and_text():
    h = Histogram("h", "help", buckets=(0.01, 0.1, 1.0), label_names=("r",))
    for _ in range(90):
        h.observe(0.005, r="ok")
    for _ in range(10):
        h.observe(0.5, r="ok")
    assert h.count(r="ok") == 100
    assert h.percentile(50) == 0.01
    assert h.percentile(95) == 1.0


def test_exposition_label_escaping():
    """Prometheus exposition spec: backslash, double quote and line feed
    in label VALUES must be escaped — a failure message or plugin name
    carrying any of them used to emit unparseable exposition text."""
    r = Registry()
    c = r.register(Counter("weird_total", "", ("msg",)))
    c.inc(msg='say "hi" to C:\\temp\nplease')
    text = r.render_text()
    assert ('weird_total{msg="say \\"hi\\" to C:\\\\temp\\nplease"} 1.0'
            in text)
    # no raw newline survives; every inner quote is backslash-escaped
    line = next(ln for ln in text.splitlines()
                if ln.startswith("weird_total"))
    assert "\n" not in line
    inner = line[line.index('="') + 2:line.rindex('"')]
    assert all(inner[i - 1] == "\\" for i, ch in enumerate(inner)
               if ch == '"')


def test_exposition_help_escaping():
    """HELP lines escape backslash and line feed (quotes stay raw)."""
    r = Registry()
    r.register(Counter("h_total", 'multi\nline "help" with \\slash'))
    text = r.render_text()
    assert ('# HELP h_total multi\\nline "help" with \\\\slash' in text)


def test_exposition_histogram_label_escaping():
    r = Registry()
    h = r.register(Histogram("lat", "", buckets=(0.1, 1.0),
                             label_names=("plugin",)))
    h.observe(0.05, plugin='odd"name\\')
    text = r.render_text()
    assert 'plugin="odd\\"name\\\\"' in text
    assert 'le="0.1"' in text


def test_counter_labels():
    c = Counter("c", label_names=("result",))
    c.inc(result="scheduled")
    c.inc(result="scheduled")
    c.inc(result="error")
    assert c.value(result="scheduled") == 2
    assert c.value(result="error") == 1


def test_async_recorder_buffers_until_flush():
    h = Histogram("h")
    c = Counter("c")
    t = [0.0]
    rec = AsyncRecorder(flush_interval=1.0, now=lambda: t[0])
    rec.observe(h, 0.25)
    rec.inc(c, 2.0)
    assert h.total_count() == 0 and c.value() == 0, "buffered"
    n = rec.flush()
    assert n == 2
    assert h.total_count() == 1
    assert c.value() == 2.0
    # non-forced flush respects the interval
    rec.observe(h, 0.25)
    rec.flush(force=True)
    rec.observe(h, 0.25)
    assert rec.flush(force=False) == 0, "interval not elapsed"
    t[0] = 2.0
    assert rec.flush(force=False) == 1


def mknode(i):
    return Node(metadata=ObjectMeta(name=f"node-{i}",
                                    labels={LABEL_HOSTNAME: f"node-{i}"}),
                status=NodeStatus(allocatable={"cpu": "8", "memory": "16Gi",
                                               "pods": "110"}))


def mkpod(name, cpu="100m"):
    return Pod(metadata=ObjectMeta(name=name),
               spec=PodSpec(containers=[Container(
                   name="c", resources=ResourceRequirements(
                       requests={"cpu": cpu}))]))


def _small_sched(hub):
    cfg = default_config()
    cfg.batch_size = 16
    return Scheduler(hub, cfg, caps=Capacities(nodes=16, pods=64))


def test_scheduler_records_attempts_and_durations():
    hub = Hub()
    sched = _small_sched(hub)
    hub.create_node(mknode(0))
    pods = [mkpod(f"p{i}") for i in range(5)]
    for p in pods:
        hub.create_pod(p)
    big = mkpod("big", cpu="64")
    hub.create_pod(big)
    sched.run_until_idle()
    m = sched.metrics
    assert m.schedule_attempts.value(
        result="scheduled", profile="default-scheduler") == 5
    assert m.schedule_attempts.value(
        result="unschedulable", profile="default-scheduler") >= 1
    assert m.attempt_duration.count(result="scheduled") == 5
    assert m.batch_duration.total_count() >= 1
    assert m.algorithm_duration.total_count() >= 1
    assert m.extension_point_duration.count(extension_point="Filter") >= 1
    # binder-thread observations land after the recorder flush
    assert m.extension_point_duration.count(extension_point="Bind") >= 1
    assert m.pod_scheduling_attempts.total_count() == 5
    snap = m.registry.snapshot()
    assert "schedule_attempts_total" in snap
    assert "pending_pods" in snap


def test_serving_endpoints():
    hub = Hub()
    sched = _small_sched(hub)
    hub.create_node(mknode(0))
    hub.create_pod(mkpod("p"))
    sched.run_until_idle()
    srv = ServingEndpoints(sched, port=0)
    srv.start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        body = urllib.request.urlopen(f"{base}/metrics").read().decode()
        assert "schedule_attempts_total" in body
        assert 'result="scheduled"' in body
        assert "scheduling_attempt_duration_seconds_bucket" in body
        assert urllib.request.urlopen(
            f"{base}/healthz").read() == b"ok"
        cfg = json.loads(urllib.request.urlopen(
            f"{base}/configz").read().decode())
        assert cfg["batch_size"] == 16
    finally:
        srv.stop()


def test_pending_pods_gauge_live():
    hub = Hub()
    sched = _small_sched(hub)
    # no nodes: the pod parks unschedulable
    hub.create_pod(mkpod("p"))
    sched.run_until_idle()
    gauge = sched.metrics.pending_pods.snapshot()
    assert gauge["{'queue': 'unschedulable'}"] == 1


def test_trace_spans_and_threshold():
    """utiltrace-style spans: silent under threshold, full dump over it."""
    import logging

    from kubernetes_tpu.utils.tracing import Trace

    t = [0.0]

    def now():
        return t[0]

    tr = Trace("cycle", now=now, pods=4)
    with tr.span("launch"):
        t[0] += 0.08
        with tr.span("pull"):
            t[0] += 0.01
    with tr.span("commit"):
        t[0] += 0.05
    assert abs(tr.total() - 0.14) < 1e-9
    records = []

    class Cap(logging.Handler):
        def emit(self, rec):
            records.append(rec.getMessage())

    log = logging.getLogger("trace-test")
    log.addHandler(Cap())
    log.setLevel(logging.INFO)
    assert tr.log_if_long(1.0, log) is False, "under threshold: silent"
    assert not records
    assert tr.log_if_long(0.1, log) is True
    assert "Trace[cycle]" in records[0]
    assert "launch" in records[0] and "pull" in records[0]


def test_slow_cycle_emits_trace(caplog):
    """A scheduling cycle over the 100ms threshold logs the phase trace."""
    import logging

    from kubernetes_tpu.hub import Hub
    from kubernetes_tpu.scheduler import Scheduler

    class SlowClock:
        t = 1000.0
        calls = 0

        def now(self):
            # each clock read advances: any measured phase looks slow
            SlowClock.t += 0.05
            return SlowClock.t

    hub = Hub()
    cfg = default_config()
    cfg.batch_size = 16
    sched = Scheduler(hub, cfg, caps=Capacities(nodes=16, pods=64),
                      now=SlowClock().now)
    hub.create_node(mknode(0))
    hub.create_pod(mkpod("p"))
    with caplog.at_level(logging.INFO, logger="kubernetes_tpu.scheduler"):
        sched.run_until_idle()
    assert any("Trace[schedule_cycle]" in r.message for r in caplog.records)
    sched.close()


# suite-tier discipline (tests/test_markers.py): area marker
import pytest  # noqa: E402
pytestmark = pytest.mark.observability


# -------------------------- metrics lint (ISSUE-10 satellite) --------


def test_registry_metric_names_and_labels_conform():
    """Every metric registered in metrics.py obeys the Prometheus
    grammar — name [a-zA-Z_:][a-zA-Z0-9_:]*, labels
    [a-zA-Z_][a-zA-Z0-9_]* — and mirrored-gauge vs true-counter naming
    stays honest (_total only on Counters)."""
    from kubernetes_tpu.metrics import (
        Counter as MCounter,
        Histogram as MHistogram,
        SchedulerMetrics,
    )
    from kubernetes_tpu.telemetry.fleet import (
        LABEL_NAME_RE,
        METRIC_NAME_RE,
    )

    m = SchedulerMetrics()
    for name, metric in m.registry._metrics.items():
        assert METRIC_NAME_RE.match(name), name
        assert name == metric.name
        for ln in getattr(metric, "label_names", ()) or ():
            assert LABEL_NAME_RE.match(ln), f"{name}{{{ln}}}"
        if name.endswith("_total"):
            assert isinstance(metric, MCounter), (
                f"{name}: _total is reserved for true counters")
        if isinstance(metric, MHistogram):
            assert not name.endswith(("_total", "_bucket", "_sum",
                                      "_count")), name


def test_full_exposition_round_trips_strict_parser():
    """The complete /metrics body — histograms, escaped label values,
    callback gauges — re-parses under telemetry.fleet's strict parser
    (locks in the PR-4 escaping fix; the fleet merge ingests this)."""
    from kubernetes_tpu.metrics import SchedulerMetrics
    from kubernetes_tpu.telemetry.fleet import parse_exposition

    m = SchedulerMetrics(pending_fn=lambda: {"activeQ": 3})
    m.schedule_attempts.inc(result='nasty "quotes" and \\slashes\n',
                            profile="default")
    m.phase_duration.observe(0.004, phase="device_launch")
    m.pod_e2e_duration.observe(0.5, attempts="2")
    m.device_compiles.inc(cause="rebucket")
    m.device_live_buffer_bytes.set(1024.0, buffer="cluster")
    # the watchdog/autopsy family (ISSUE-20) rides the same exposition
    m.watchdog_evals.inc()
    m.watchdog_incidents.inc(kind="slo_breach")
    m.watchdog_rules_tripped.inc(rule="slo")
    m.autopsy_bundles.inc(trigger="device_fallback")
    m.autopsy_bundles_dropped.inc(reason="rate_limited")
    m.autopsy_store_bytes.set(2048.0)
    exp = parse_exposition(m.registry.render_text())
    names = {s.name for s in exp.samples}
    assert "scheduler_device_compiles_total" in names
    assert "scheduling_phase_duration_seconds_bucket" in names
    assert "pending_pods" in names
    assert "scheduler_watchdog_evals_total" in names
    assert "scheduler_autopsy_store_bytes" in names
    assert any(s.name == "scheduler_watchdog_incidents_total"
               and s.labels.get("kind") == "slo_breach"
               for s in exp.samples)
    assert any(s.name == "scheduler_autopsy_bundles_total"
               and s.labels.get("trigger") == "device_fallback"
               for s in exp.samples)
    assert any(s.name == "scheduler_autopsy_bundles_dropped_total"
               and s.labels.get("reason") == "rate_limited"
               for s in exp.samples)
    # the nasty label survived the escape/unescape round trip
    assert any(s.labels.get("result") == 'nasty "quotes" and '
               "\\slashes\n" for s in exp.samples)
