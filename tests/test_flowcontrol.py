"""Flow control & overload protection (fabric/flowcontrol + the 429
wire contract + scheduler brownout): the stack's analog of the
reference's API Priority and Fairness
(staging/src/k8s.io/apiserver/pkg/util/flowcontrol) — priority levels
with bounded concurrency shares, shuffle-sharded fair queues,
queue-wait deadlines, and honest typed rejections (HTTP 429 +
Retry-After) that clients retry WITHIN their existing budget, never
blindly for non-idempotent verbs."""

import threading
import time

import pytest

from kubernetes_tpu.config.types import default_config
from kubernetes_tpu.fabric.flowcontrol import (
    DEFAULT_LEVELS,
    FlowController,
    LevelConfig,
    classify_call,
)
from kubernetes_tpu.hub import Hub, TooManyRequests
from kubernetes_tpu.hubclient import RemoteHub
from kubernetes_tpu.hubserver import HubServer
from kubernetes_tpu.ops.features import Capacities
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.testing import MakeNode, MakePod

pytestmark = pytest.mark.flowcontrol


# ------------------------------------------------------------------
# classification: identity ≻ verb ≻ anonymity
# ------------------------------------------------------------------


def test_classify_identity_outranks_verb():
    # a scheduler's LIST is scheduler traffic, not best-effort
    assert classify_call("list_pods", [], "scheduler-3") == \
        ("scheduler", "scheduler-3")
    assert classify_call("list_pods", [], "relay-east") == \
        ("system", "relay-east")
    # verb outranks anonymity: an unidentified bind still rides the
    # binding level (progress over protocol)
    level, _ = classify_call("bind", [], None)
    assert level == "scheduler"


def test_classify_tenant_and_anonymous():
    pod = MakePod().name("w").namespace("team-a").obj()
    assert classify_call("create_pod", [pod], None) == \
        ("tenant", "team-a")
    # ns/name key strings attribute the same way
    assert classify_call("get_pod_group", ["team-b/pg"], None) == \
        ("tenant", "team-b")
    # attributed-but-namespace-less callers are tenants of their own
    # identity; fully anonymous namespace-less reads are best-effort
    assert classify_call("list_nodes", [], "ci-bot") == \
        ("tenant", "ci-bot")
    assert classify_call("list_nodes", [], None) == \
        ("best-effort", "anon")


# ------------------------------------------------------------------
# admission: seats, bounded queues, deadlines, seat handoff
# ------------------------------------------------------------------


def test_seats_then_bounded_queue_then_429():
    fc = FlowController(total_concurrency=10, levels={
        "best-effort": LevelConfig(share=0.1, queues=1, queue_depth=2,
                                   queue_wait_s=0.2)})
    # share 0.1 of 10 -> exactly 1 seat
    fc.admit("best-effort", "anon")
    started, admitted = [], []

    def waiter():
        started.append(1)
        fc.admit("best-effort", "anon")
        admitted.append(1)
        fc.release("best-effort")

    threads = [threading.Thread(target=waiter) for _ in range(2)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + 2.0
    while fc.stats()["levels"]["best-effort"]["queue_depth"] < 2 \
            and time.monotonic() < deadline:
        time.sleep(0.005)
    # seat taken + queue at its bound: the next request is rejected
    # IMMEDIATELY (full queue), with an honest Retry-After hint
    with pytest.raises(TooManyRequests) as ei:
        fc.admit("best-effort", "anon")
    assert ei.value.retry_after > 0
    # releasing the seat hands it to a queued waiter (no 429 for them)
    fc.release("best-effort")
    for t in threads:
        t.join(timeout=2.0)
    assert len(admitted) == 2
    s = fc.stats()["levels"]["best-effort"]
    assert s["rejected_full"] == 1
    assert s["rejected_timeout"] == 0
    assert s["depth_peak"] <= s["queue_depth_bound"]


def test_queue_wait_deadline_answers_429():
    fc = FlowController(total_concurrency=10, levels={
        "best-effort": LevelConfig(share=0.1, queues=1, queue_depth=4,
                                   queue_wait_s=0.05)})
    fc.admit("best-effort", "anon")      # hold the only seat
    t0 = time.monotonic()
    with pytest.raises(TooManyRequests):
        fc.admit("best-effort", "anon")  # queues, then deadline fires
    assert time.monotonic() - t0 >= 0.05
    s = fc.stats()["levels"]["best-effort"]
    assert s["rejected_timeout"] == 1
    fc.release("best-effort")
    assert fc.stats()["levels"]["best-effort"]["in_flight"] == 0


def test_levels_are_isolated():
    """One level at its share does not consume another level's seats —
    the priority-isolation property the overload storm gates on."""
    fc = FlowController(total_concurrency=10)
    # saturate best-effort completely (seats + queue)
    fc.admit("best-effort", "anon")
    # system and scheduler admission is untouched
    for lv in ("system", "scheduler", "tenant"):
        fc.admit(lv, "x")
        fc.release(lv)
    s = fc.stats()["levels"]
    assert s["system"]["rejected_full"] == 0
    assert s["scheduler"]["rejected_full"] == 0
    fc.release("best-effort")


def test_default_levels_shares_cover_the_budget():
    total = sum(cfg.share for cfg in DEFAULT_LEVELS.values())
    assert total == pytest.approx(1.0)
    fc = FlowController(total_concurrency=64)
    seats = {n: lv["seats"] for n, lv in fc.stats()["levels"].items()}
    assert seats["system"] >= seats["tenant"] >= seats["best-effort"]


# ------------------------------------------------------------------
# the 429 wire contract: typed rejections, retry budget, idempotency
# ------------------------------------------------------------------


@pytest.fixture()
def throttled_hub():
    """A served hub whose best-effort level is a single seat with no
    queue to speak of — held by the fixture, so every anonymous call
    is shed with a 429 until the seat is released."""
    hub = Hub()
    flow = FlowController(total_concurrency=10, levels={
        "best-effort": LevelConfig(share=0.1, queues=1, queue_depth=1,
                                   queue_wait_s=0.05)})
    server = HubServer(hub, flow=flow).start()
    yield hub, flow, server
    server.stop()


def test_429_roundtrip_typed_with_hint(throttled_hub):
    hub, flow, server = throttled_hub
    flow.admit("best-effort", "anon")
    client = RemoteHub(server.address, timeout=5.0, retry_deadline=0.3,
                       retry_base=0.01, retry_cap=0.05)
    try:
        with pytest.raises(TooManyRequests) as ei:
            client.list_nodes()
        # the server's Retry-After hint survived the wire
        assert ei.value.retry_after > 0
        s = client.resilience_stats()
        assert s["throttled_429s"] >= 1
        # throttles are NOT transport faults: no degraded mode entered
        assert not s["degraded"]
    finally:
        flow.release("best-effort")
        client.close()


def test_429_idempotent_retry_within_budget(throttled_hub):
    """An idempotent read shed by flow control retries with the server
    hint inside the NORMAL retry budget and succeeds once the seat
    frees — the client never gives up early, never spins."""
    hub, flow, server = throttled_hub
    hub.create_node(MakeNode().name("n1").obj())
    flow.admit("best-effort", "anon")
    released = threading.Timer(0.25,
                               lambda: flow.release("best-effort"))
    client = RemoteHub(server.address, timeout=5.0, retry_deadline=3.0,
                       retry_base=0.01, retry_cap=0.05)
    try:
        t0 = time.monotonic()
        released.start()
        nodes = client.list_nodes()     # throttled, retried, lands
        elapsed = time.monotonic() - t0
        assert [n.metadata.name for n in nodes] == ["n1"]
        assert elapsed >= 0.2           # it actually waited the storm out
        s = client.resilience_stats()
        assert s["throttled_429s"] >= 1
        assert s["throttle_retries"] >= 1
        assert s["throttle_retries"] <= s["throttled_429s"]
    finally:
        released.cancel()
        client.close()


def test_429_non_idempotent_never_replayed(throttled_hub):
    """The audit the issue demands: a throttled non-idempotent verb
    surfaces the typed verdict IMMEDIATELY — no blind replay, no
    double-apply — and the request provably never ran server-side."""
    hub, flow, server = throttled_hub
    # an anonymous namespace-less create classifies best-effort
    pod = MakePod().name("shed-me").obj()
    pod.metadata.namespace = ""
    flow.admit("best-effort", "anon")
    client = RemoteHub(server.address, timeout=5.0, retry_deadline=3.0,
                       retry_base=0.01, retry_cap=0.05)
    try:
        t0 = time.monotonic()
        with pytest.raises(TooManyRequests):
            client.create_pod(pod)
        # no retry loop: the verdict came back in one round trip even
        # though the retry deadline allowed for seconds of patience
        assert time.monotonic() - t0 < 1.0
        s = client.resilience_stats()
        assert s["throttled_429s"] >= 1
        assert s["throttle_retries"] == 0
        # the flow controller rejected BEFORE dispatch: nothing ran
        assert hub.get_pod(pod.metadata.uid) is None
    finally:
        flow.release("best-effort")
        client.close()


def test_flow_metrics_ride_the_server_exposition(throttled_hub):
    hub, flow, server = throttled_hub
    flow.admit("best-effort", "anon")
    client = RemoteHub(server.address, timeout=5.0, retry_deadline=0.2,
                       retry_base=0.01, retry_cap=0.05)
    try:
        with pytest.raises(TooManyRequests):
            client.list_nodes()
    finally:
        flow.release("best-effort")
        client.close()
    import urllib.request
    text = urllib.request.urlopen(server.address + "/metrics",
                                  timeout=5.0).read().decode()
    assert "hub_flow_seats" in text
    assert 'hub_flow_rejected_total{level="best-effort"' in text


def test_flow_metrics_round_trip_strict_parser():
    """The hand-rolled hub_flow_* exposition re-parses under
    telemetry.fleet's strict parser (the lint every fabric component's
    metrics_text must pass — the fleet merge ingests this)."""
    from kubernetes_tpu.telemetry.fleet import parse_exposition

    fc = FlowController(total_concurrency=10, levels={
        "best-effort": LevelConfig(share=0.1, queues=1, queue_depth=1,
                                   queue_wait_s=0.01)})
    fc.admit("best-effort", "anon")
    with pytest.raises(TooManyRequests):
        fc.admit("best-effort", "anon")     # deadline -> rejected row
    fc.release("best-effort")
    exp = parse_exposition(fc.metrics_text())
    names = {s.name for s in exp.samples}
    assert {"hub_flow_seats", "hub_flow_in_flight",
            "hub_flow_queue_depth", "hub_flow_admitted_total",
            "hub_flow_rejected_total"} <= names
    rej = [s for s in exp.samples if s.name == "hub_flow_rejected_total"
           and s.labels.get("level") == "best-effort"
           and s.labels.get("reason") == "timeout"]
    assert rej and rej[0].value == 1.0


# ------------------------------------------------------------------
# scheduler brownout: shed-aware self-protection
# ------------------------------------------------------------------


def _brownout_scheduler(threshold: int = 5):
    hub = Hub()
    hub.create_node(MakeNode().name("n1").capacity(cpu="64").obj())
    cfg = default_config()
    cfg.batch_size = 64
    cfg.brownout_throttle_threshold = threshold
    cfg.brownout_clear_windows = 2
    cfg.tenants = {"prio": {"weight": 8.0}, "scav": {"weight": 0.1}}
    sched = Scheduler(hub, cfg, caps=Capacities(nodes=4, pods=128))
    throttled = {"n": 0.0}
    hub.resilience_stats = lambda: {"throttled_429s": throttled["n"]}
    return sched, throttled


def _tick_brownout(sched):
    # defeat the ≤1/s evaluation gate so the test drives windows
    sched._last_brownout_eval = 0.0
    sched._evaluate_brownout()


def test_brownout_enters_shrinks_and_recovers():
    sched, throttled = _brownout_scheduler()
    sched.drift_check_interval = 10.0
    try:
        assert sched._effective_batch() == 64
        _tick_brownout(sched)               # baseline window: 0 throttles
        throttled["n"] += 20                # a sustained shed window
        _tick_brownout(sched)
        assert sched.brownout
        assert sched._effective_batch() < 64
        assert sched.drift_check_interval > 10.0
        assert "scav" in sched.jobqueue.parked     # parked best-effort
        assert "prio" not in sched.jobqueue.parked
        st = sched.brownout_state()
        assert st["active"] and st["enters"] == 1
        # still shedding: stays browned out
        throttled["n"] += 20
        _tick_brownout(sched)
        assert sched.brownout
        # two consecutive clean windows: un-brown, restore everything
        _tick_brownout(sched)
        assert sched.brownout               # one clean window is not enough
        _tick_brownout(sched)
        assert not sched.brownout
        assert sched._effective_batch() == 64
        assert sched.drift_check_interval == 10.0
        assert not sched.jobqueue.parked
        assert sched.stats["brownout_exits"] == 1
        # the transitions made it to the exposition
        text = sched.metrics.registry.render_text()
        assert 'scheduler_brownout_transitions_total{phase="enter"}' \
            in text
    finally:
        sched.close()


def test_brownout_disabled_by_zero_threshold():
    sched, throttled = _brownout_scheduler(threshold=0)
    try:
        throttled["n"] += 1000
        _tick_brownout(sched)
        assert not sched.brownout
    finally:
        sched.close()


def test_parked_tenants_release_nothing_and_bank_no_credit():
    """While parked, a best-effort tenant sits out the DRR rotation
    entirely; un-parking must not let it burst past its weight, so
    deficits are zeroed while parked, not accumulated."""
    from kubernetes_tpu.api.objects import LABEL_QUEUE
    from kubernetes_tpu.backend.jobqueue import JobQueue

    class FakePQ:
        def __init__(self):
            self.pods = []

        def add(self, pod):
            self.pods.append(pod)

    jq = JobQueue({"prio": {"weight": 8.0}, "scav": {"weight": 0.1}})
    for i in range(4):
        for tenant in ("prio", "scav"):
            p = MakePod().name(f"{tenant}-{i}").req(cpu="100m").obj()
            p.metadata.labels[LABEL_QUEUE] = tenant
            jq.add(p)
    assert jq.park_below(0.25) == ["scav"]
    pq = FakePQ()
    assert jq.release(pq, budget=64) == 4
    assert all(p.metadata.name.startswith("prio-") for p in pq.pods)
    assert jq.tenant_stats()["scav"]["parked"]
    assert not jq.tenant_stats()["prio"]["parked"]
    # parked while the rotation ran repeatedly: no credit banked
    for _ in range(5):
        jq.release(FakePQ(), budget=64)
    assert jq.unpark_all() == ["scav"]
    pq2 = FakePQ()
    assert jq.release(pq2, budget=64) == 4
    assert sorted(p.metadata.name for p in pq2.pods) == \
        [f"scav-{i}" for i in range(4)]
