"""Leader election (server.go:284-317) + HTTP extender (extender.go)."""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from kubernetes_tpu.api.objects import (
    Container,
    LABEL_HOSTNAME,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    ResourceRequirements,
)
from kubernetes_tpu.config.types import default_config
from kubernetes_tpu.extender import ExtenderConfig
from kubernetes_tpu.hub import Hub
from kubernetes_tpu.leaderelection import LeaderElector
from kubernetes_tpu.ops.features import Capacities
from kubernetes_tpu.scheduler import Scheduler


class Clock:
    def __init__(self):
        self.t = 1000.0

    def now(self):
        return self.t


def test_leader_election_acquire_renew_takeover():
    hub = Hub()
    clock = Clock()
    a = LeaderElector(hub.leases, "a", now=clock.now)
    b = LeaderElector(hub.leases, "b", now=clock.now)
    assert a.try_acquire_or_renew() is True
    assert b.try_acquire_or_renew() is False
    assert a.is_leader() and not b.is_leader()
    # renewals keep the lease
    clock.t += 10
    assert a.try_acquire_or_renew() is True
    clock.t += 10
    assert b.try_acquire_or_renew() is False, "a renewed 10s ago"
    # a goes silent past the lease duration: b takes over
    clock.t += 16
    assert b.try_acquire_or_renew() is True
    assert not a.try_acquire_or_renew()
    assert not a.is_leader()
    lease = hub.leases.get("kube-scheduler")
    assert lease.holder_identity == "b"
    assert lease.lease_transitions == 1


def test_fencing_epoch_monotonic_per_acquisition():
    """The store stamps a fresh epoch on every ACQUISITION (vacant ->
    holder, steal), never on renewals; electors track their newest
    acquisition's epoch (the fencing token for hub writes)."""
    hub = Hub()
    clock = Clock()
    a = LeaderElector(hub.leases, "a", now=clock.now)
    b = LeaderElector(hub.leases, "b", now=clock.now)
    assert a.try_acquire_or_renew()
    assert a.epoch == 1
    clock.t += 5
    assert a.try_acquire_or_renew()            # renewal: same epoch
    assert a.epoch == 1
    assert hub.leases.epoch_of("kube-scheduler") == 1
    clock.t += 16                              # a expires; b steals
    assert b.try_acquire_or_renew()
    assert b.epoch == 2
    assert a.epoch == 1, "deposed holder keeps its old token"
    assert hub.leases.epoch_of("kube-scheduler") == 2
    b.release()
    assert a.try_acquire_or_renew()            # re-acquire after vacancy
    assert a.epoch == 3


def test_hub_rejects_fenced_writes():
    """Hub.bind / patch_pod_condition from a deposed epoch raise Fenced;
    the current epoch's writes land (satellite: fenced binds)."""
    import pytest as _pytest

    from kubernetes_tpu.api.objects import PodCondition
    from kubernetes_tpu.hub import Conflict, Fenced
    from kubernetes_tpu.testing import MakeNode, MakePod

    hub = Hub()
    clock = Clock()
    a = LeaderElector(hub.leases, "a", now=clock.now)
    b = LeaderElector(hub.leases, "b", now=clock.now)
    hub.create_node(MakeNode().name("n").obj())
    pod = MakePod().name("p").req(cpu="100m").obj()
    hub.create_pod(pod)
    assert a.try_acquire_or_renew()
    clock.t += 16
    assert b.try_acquire_or_renew()            # b deposes a
    with _pytest.raises(Fenced):
        hub.bind(pod, "n", a.epoch, a.lease_name)
    assert hub.get_pod(pod.metadata.uid).spec.node_name == "", \
        "a fenced bind must not land"
    with _pytest.raises(Fenced):
        hub.patch_pod_condition(pod, PodCondition(
            type="PodScheduled", status="False", reason="x"),
            None, a.epoch, a.lease_name)
    hub.bind(pod, "n", b.epoch, b.lease_name)  # the new leader binds
    assert hub.get_pod(pod.metadata.uid).spec.node_name == "n"
    with _pytest.raises(Conflict):
        hub.bind(pod, "n", b.epoch, b.lease_name)   # bind-once holds
    # unfenced callers (no elector) are untouched
    pod2 = MakePod().name("p2").req(cpu="100m").obj()
    hub.create_pod(pod2)
    hub.bind(pod2, "n")
    assert hub.get_pod(pod2.metadata.uid).spec.node_name == "n"


def test_leader_election_release():
    hub = Hub()
    clock = Clock()
    a = LeaderElector(hub.leases, "a", now=clock.now)
    b = LeaderElector(hub.leases, "b", now=clock.now)
    a.try_acquire_or_renew()
    a.release()
    assert b.try_acquire_or_renew() is True, "vacated lease acquired"


def test_only_leader_schedules():
    hub = Hub()
    hub.create_node(Node(
        metadata=ObjectMeta(name="n", labels={LABEL_HOSTNAME: "n"}),
        status=NodeStatus(allocatable={"cpu": "8", "memory": "16Gi",
                                       "pods": "110"})))
    cfg = default_config()
    cfg.batch_size = 16
    sched = Scheduler(hub, cfg, caps=Capacities(nodes=16, pods=64))
    # another instance holds the lease
    other = LeaderElector(hub.leases, "other")
    assert other.try_acquire_or_renew()
    follower = LeaderElector(hub.leases, "me", retry_period=0.01)
    sched.start(elector=follower)
    try:
        p = Pod(metadata=ObjectMeta(name="p"),
                spec=PodSpec(containers=[Container(
                    name="c", resources=ResourceRequirements(
                        requests={"cpu": "1"}))]))
        hub.create_pod(p)
        import time

        time.sleep(0.5)
        assert hub.get_pod(p.metadata.uid).spec.node_name == "", \
            "a non-leader must not bind"
        # the holder releases: our follower acquires and schedules
        other.release()
        deadline = time.time() + 20
        while time.time() < deadline:
            if hub.get_pod(p.metadata.uid).spec.node_name:
                break
            time.sleep(0.05)
        assert hub.get_pod(p.metadata.uid).spec.node_name == "n"
    finally:
        sched.stop()
        sched.close()


# ---------------------------- extender ----------------------------


class _StubExtender(BaseHTTPRequestHandler):
    reject = set()
    scores = {}
    calls = []

    def log_message(self, *a):
        pass

    preempt_veto = set()    # candidate nodes dropped by /preempt
    bound = []              # (podName, node) seen by /bind

    def do_POST(self):  # noqa: N802
        body = json.loads(self.rfile.read(
            int(self.headers["Content-Length"])).decode())
        type(self).calls.append((self.path, body))
        if self.path.endswith("/filter"):
            names = body.get("nodenames")
            if names is None:   # non-nodeCacheCapable: full node objects
                names = [n["metadata"]["name"] for n in body["nodes"]]
            passed = [n for n in names if n not in type(self).reject]
            out = {"nodenames": passed,
                   "failedNodes": {n: "vetoed" for n in type(self).reject
                                   if n in names}}
        elif self.path.endswith("/bind"):
            type(self).bound.append((body["podName"], body["node"]))
            out = {}
        elif self.path.endswith("/preempt"):
            out = {"nodeNameToVictims": {
                node: entry
                for node, entry in body["nodeNameToVictims"].items()
                if node not in type(self).preempt_veto}}
        else:
            names = body.get("nodenames")
            if names is None:
                names = [n["metadata"]["name"] for n in body["nodes"]]
            out = [{"host": n, "score": type(self).scores.get(n, 0)}
                   for n in names]
        data = json.dumps(out).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)


def _with_stub(fn):
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _StubExtender)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    try:
        fn(f"http://127.0.0.1:{srv.server_address[1]}")
    finally:
        srv.shutdown()
        srv.server_close()


def _cluster(url, managed=None):
    hub = Hub()
    for n in ("n0", "n1", "n2"):
        hub.create_node(Node(
            metadata=ObjectMeta(name=n, labels={LABEL_HOSTNAME: n}),
            status=NodeStatus(allocatable={"cpu": "8", "memory": "16Gi",
                                           "pods": "110"})))
    cfg = default_config()
    cfg.batch_size = 16
    cfg.extenders = [ExtenderConfig(
        url_prefix=url, filter_verb="filter", prioritize_verb="prioritize",
        weight=100.0, managed_resources=managed or [])]
    return hub, Scheduler(hub, cfg, caps=Capacities(nodes=16, pods=64))


def test_extender_filter_vetoes_nodes():
    _StubExtender.reject = {"n0", "n2"}
    _StubExtender.scores = {}
    _StubExtender.calls = []

    def run(url):
        hub, sched = _cluster(url)
        p = Pod(metadata=ObjectMeta(name="p"),
                spec=PodSpec(containers=[Container(
                    name="c", resources=ResourceRequirements(
                        requests={"cpu": "1"}))]))
        hub.create_pod(p)
        sched.run_until_idle()
        assert hub.get_pod(p.metadata.uid).spec.node_name == "n1"
        assert any(path.endswith("/filter")
                   for path, _ in _StubExtender.calls)
        sched.close()

    _with_stub(run)


def test_extender_prioritize_steers_choice():
    _StubExtender.reject = set()
    _StubExtender.scores = {"n2": 10}
    _StubExtender.calls = []

    def run(url):
        hub, sched = _cluster(url)
        p = Pod(metadata=ObjectMeta(name="p"),
                spec=PodSpec(containers=[Container(
                    name="c", resources=ResourceRequirements(
                        requests={"cpu": "1"}))]))
        hub.create_pod(p)
        sched.run_until_idle()
        assert hub.get_pod(p.metadata.uid).spec.node_name == "n2", \
            "weighted extender score dominates"
        sched.close()

    _with_stub(run)


def test_extender_managed_resources_gate():
    _StubExtender.reject = {"n0", "n1", "n2"}
    _StubExtender.calls = []

    def run(url):
        hub, sched = _cluster(url, managed=["example.com/fpga"])
        plain = Pod(metadata=ObjectMeta(name="plain"),
                    spec=PodSpec(containers=[Container(
                        name="c", resources=ResourceRequirements(
                            requests={"cpu": "1"}))]))
        hub.create_pod(plain)
        sched.run_until_idle()
        assert hub.get_pod(plain.metadata.uid).spec.node_name, \
            "uninterested extender never consulted"
        assert not _StubExtender.calls
        sched.close()

    _with_stub(run)


def test_extender_unreachable_nonignorable_fails_pod():
    hub = Hub()
    hub.create_node(Node(
        metadata=ObjectMeta(name="n", labels={LABEL_HOSTNAME: "n"}),
        status=NodeStatus(allocatable={"cpu": "8", "memory": "16Gi",
                                       "pods": "110"})))
    cfg = default_config()
    cfg.batch_size = 16
    cfg.extenders = [ExtenderConfig(
        url_prefix="http://127.0.0.1:1", filter_verb="filter",
        timeout_seconds=0.2)]
    sched = Scheduler(hub, cfg, caps=Capacities(nodes=16, pods=64))
    p = Pod(metadata=ObjectMeta(name="p"),
            spec=PodSpec(containers=[Container(
                name="c", resources=ResourceRequirements(
                    requests={"cpu": "1"}))]))
    hub.create_pod(p)
    sched.run_until_idle()
    assert hub.get_pod(p.metadata.uid).spec.node_name == ""
    sched.close()


def test_extender_unreachable_ignorable_skipped():
    hub = Hub()
    hub.create_node(Node(
        metadata=ObjectMeta(name="n", labels={LABEL_HOSTNAME: "n"}),
        status=NodeStatus(allocatable={"cpu": "8", "memory": "16Gi",
                                       "pods": "110"})))
    cfg = default_config()
    cfg.batch_size = 16
    cfg.extenders = [ExtenderConfig(
        url_prefix="http://127.0.0.1:1", filter_verb="filter",
        ignorable=True, timeout_seconds=0.2)]
    sched = Scheduler(hub, cfg, caps=Capacities(nodes=16, pods=64))
    p = Pod(metadata=ObjectMeta(name="p"),
            spec=PodSpec(containers=[Container(
                name="c", resources=ResourceRequirements(
                    requests={"cpu": "1"}))]))
    hub.create_pod(p)
    sched.run_until_idle()
    assert hub.get_pod(p.metadata.uid).spec.node_name == "n"
    sched.close()


def test_config_file_loading(tmp_path):
    """cmd-level config loading: profiles, plugin args, extenders, knobs."""
    from kubernetes_tpu.config.load import load_config

    doc = {
        "batch_size": 128,
        "async_binding": False,
        "profiles": [
            {"scheduler_name": "default-scheduler",
             "plugin_config": [
                 {"name": "NodeResourcesFit",
                  "args": {"scoring_strategy": {"type": "MostAllocated"}}}]},
            {"scheduler_name": "second",
             "plugins": {"score": {"disabled": [{"name": "ImageLocality"}]}}},
        ],
        "extenders": [
            {"url_prefix": "http://127.0.0.1:9999", "filter_verb": "filter",
             "weight": 3, "ignorable": True}],
    }
    path = tmp_path / "config.json"
    path.write_text(json.dumps(doc))
    cfg = load_config(str(path))
    assert cfg.batch_size == 128
    assert cfg.async_binding is False
    assert [p.scheduler_name for p in cfg.profiles] == [
        "default-scheduler", "second"]
    assert cfg.profiles[0].plugin_config["NodeResourcesFit"][
        "scoring_strategy"]["type"] == "MostAllocated"
    assert cfg.extenders[0].weight == 3
    assert cfg.extenders[0].ignorable is True
    # the loaded config actually constructs a working scheduler
    hub = Hub()
    sched = Scheduler(hub, cfg, caps=Capacities(nodes=16, pods=64))
    assert "second" in sched.frameworks
    sched.close()


def test_cli_validate_only(tmp_path):
    from kubernetes_tpu.__main__ import main

    path = tmp_path / "cfg.json"
    path.write_text(json.dumps({"batch_size": 64}))
    assert main(["--config", str(path), "--validate-only"]) == 0
    path.write_text(json.dumps({"batch_size": 0}))
    assert main(["--config", str(path), "--validate-only"]) == 1


def test_feature_gates():
    """Gates toggle hint consultation and async preemption; unknown gates
    fail validation."""
    from kubernetes_tpu.config.types import default_config
    from kubernetes_tpu.config.validation import validate_config
    from kubernetes_tpu.plugins.registry import in_tree_registry

    cfg = default_config()
    cfg.feature_gates["NoSuchGate"] = True
    assert any("NoSuchGate" in e
               for e in validate_config(cfg, in_tree_registry()))

    # hints OFF: an unhelpful node still requeues the parked pod
    cfg2 = default_config()
    cfg2.batch_size = 16
    cfg2.feature_gates["SchedulerQueueingHints"] = False
    hub = Hub()
    sched = Scheduler(hub, cfg2, caps=Capacities(nodes=16, pods=64))
    hub.create_node(Node(
        metadata=ObjectMeta(name="small", labels={LABEL_HOSTNAME: "small"}),
        status=NodeStatus(allocatable={"cpu": "1", "memory": "8Gi",
                                       "pods": "110"})))
    big = Pod(metadata=ObjectMeta(name="big"),
              spec=PodSpec(containers=[Container(
                  name="c", resources=ResourceRequirements(
                      requests={"cpu": "8"}))]))
    hub.create_pod(big)
    sched.run_until_idle()
    assert sched.queue.pending_counts()["unschedulable"] == 1
    hub.create_node(Node(
        metadata=ObjectMeta(name="small2",
                            labels={LABEL_HOSTNAME: "small2"}),
        status=NodeStatus(allocatable={"cpu": "1", "memory": "8Gi",
                                       "pods": "110"})))
    assert sched.queue.pending_counts()["unschedulable"] == 0, \
        "hints disabled: any matching event requeues"
    sched.close()


# ------------------- extender bind / preempt / payload verbs -------------------


def test_extender_bind_verb_delegates_binding():
    """extender.go:361 Bind: the first interested binder extender performs
    the binding instead of the default binder; the hub still reflects it."""
    _StubExtender.reject = set()
    _StubExtender.scores = {}
    _StubExtender.calls = []
    _StubExtender.bound = []

    def run(url):
        hub = Hub()
        hub.create_node(Node(
            metadata=ObjectMeta(name="n0", labels={LABEL_HOSTNAME: "n0"}),
            status=NodeStatus(allocatable={"cpu": "8", "memory": "16Gi",
                                           "pods": "110"})))
        cfg = default_config()
        cfg.batch_size = 16
        cfg.extenders = [ExtenderConfig(url_prefix=url, bind_verb="bind")]
        sched = Scheduler(hub, cfg, caps=Capacities(nodes=16, pods=64))
        p = Pod(metadata=ObjectMeta(name="delegated"),
                spec=PodSpec(containers=[Container(
                    name="c", resources=ResourceRequirements(
                        requests={"cpu": "1"}))]))
        hub.create_pod(p)
        sched.run_until_idle()
        assert hub.get_pod(p.metadata.uid).spec.node_name == "n0"
        assert _StubExtender.bound == [("delegated", "n0")]
        sched.close()

    _with_stub(run)


def test_extender_process_preemption_vetoes_candidate():
    """preemption.go:335 callExtenders: a ProcessPreemption veto removes
    the candidate node; the preemptor lands on a surviving candidate."""
    _StubExtender.reject = set()
    _StubExtender.scores = {}
    _StubExtender.calls = []
    _StubExtender.preempt_veto = {"n0"}

    def run(url):
        hub = Hub()
        for n in ("n0", "n1"):
            hub.create_node(Node(
                metadata=ObjectMeta(name=n, labels={LABEL_HOSTNAME: n}),
                status=NodeStatus(allocatable={"cpu": "4",
                                               "memory": "16Gi",
                                               "pods": "110"})))
        cfg = default_config()
        cfg.batch_size = 16
        cfg.extenders = [ExtenderConfig(url_prefix=url,
                                        preempt_verb="preempt")]
        clock = [1000.0]
        sched = Scheduler(hub, cfg, caps=Capacities(nodes=16, pods=64),
                          now=lambda: clock[0])
        # saturate both nodes with evictable low-priority pods
        for n in ("n0", "n1"):
            for j in range(2):
                hub.create_pod(Pod(
                    metadata=ObjectMeta(name=f"low-{n}-{j}"),
                    spec=PodSpec(containers=[Container(
                        name="c", resources=ResourceRequirements(
                            requests={"cpu": "1800m"}))], priority=0)))
        sched.run_until_idle()
        high = Pod(metadata=ObjectMeta(name="high"),
                   spec=PodSpec(containers=[Container(
                       name="c", resources=ResourceRequirements(
                           requests={"cpu": "1800m"}))], priority=100))
        hub.create_pod(high)
        for _ in range(6):
            sched.run_until_idle()
            clock[0] += 3.0
            sched.queue.flush_backoff_completed()
        sched.run_until_idle()
        assert hub.get_pod(high.metadata.uid).spec.node_name == "n1", \
            "vetoed candidate n0 must not be chosen"
        assert any(path.endswith("/preempt")
                   for path, _ in _StubExtender.calls)
        # the payload carried the FULL pod (priority visible to extender)
        preempt_body = next(b for path, b in _StubExtender.calls
                            if path.endswith("/preempt"))
        assert preempt_body["pod"]["spec"]["priority"] == 100
        victims = next(iter(
            preempt_body["nodeNameToVictims"].values()))["pods"]
        assert victims[0]["spec"]["containers"][0]["resources"][
            "requests"]["cpu"] == "1800m"
        sched.close()

    _with_stub(run)


def test_extender_non_node_cache_capable_gets_full_nodes():
    """extender.go:258: a non-nodeCacheCapable extender receives full
    node objects in the filter payload."""
    _StubExtender.reject = {"n0"}
    _StubExtender.scores = {}
    _StubExtender.calls = []

    def run(url):
        hub = Hub()
        for n in ("n0", "n1"):
            hub.create_node(Node(
                metadata=ObjectMeta(name=n, labels={LABEL_HOSTNAME: n}),
                status=NodeStatus(allocatable={"cpu": "8",
                                               "memory": "16Gi",
                                               "pods": "110"})))
        cfg = default_config()
        cfg.batch_size = 16
        cfg.extenders = [ExtenderConfig(url_prefix=url,
                                        filter_verb="filter",
                                        node_cache_capable=False)]
        sched = Scheduler(hub, cfg, caps=Capacities(nodes=16, pods=64))
        p = Pod(metadata=ObjectMeta(name="p"),
                spec=PodSpec(containers=[Container(
                    name="c", resources=ResourceRequirements(
                        requests={"cpu": "1"}))]))
        hub.create_pod(p)
        sched.run_until_idle()
        assert hub.get_pod(p.metadata.uid).spec.node_name == "n1"
        body = next(b for path, b in _StubExtender.calls
                    if path.endswith("/filter"))
        assert "nodes" in body and "nodenames" not in body
        names = {n["metadata"]["name"] for n in body["nodes"]}
        assert names == {"n0", "n1"}
        assert body["nodes"][0]["status"]["allocatable"]["cpu"] == "8"
        sched.close()

    _with_stub(run)


def test_extender_preempt_meta_victims_for_cache_capable():
    """extender.go:150: a nodeCacheCapable extender exchanges
    NodeNameToMetaVictims — pod uid references, not full objects."""
    _StubExtender.reject = set()
    _StubExtender.scores = {}
    _StubExtender.calls = []
    _StubExtender.preempt_veto = set()

    class _MetaStub(_StubExtender):
        def do_POST(self):  # noqa: N802
            body = json.loads(self.rfile.read(
                int(self.headers["Content-Length"])).decode())
            _StubExtender.calls.append((self.path, body))
            assert "nodeNameToMetaVictims" in body
            out = {"nodeNameToMetaVictims": body["nodeNameToMetaVictims"]}
            data = json.dumps(out).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

    srv = ThreadingHTTPServer(("127.0.0.1", 0), _MetaStub)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        url = f"http://127.0.0.1:{srv.server_address[1]}"
        hub = Hub()
        hub.create_node(Node(
            metadata=ObjectMeta(name="n0", labels={LABEL_HOSTNAME: "n0"}),
            status=NodeStatus(allocatable={"cpu": "4", "memory": "16Gi",
                                           "pods": "110"})))
        cfg = default_config()
        cfg.batch_size = 16
        cfg.extenders = [ExtenderConfig(url_prefix=url,
                                        preempt_verb="preempt",
                                        node_cache_capable=True)]
        clock = [1000.0]
        sched = Scheduler(hub, cfg, caps=Capacities(nodes=16, pods=64),
                          now=lambda: clock[0])
        for j in range(2):
            hub.create_pod(Pod(
                metadata=ObjectMeta(name=f"low-{j}"),
                spec=PodSpec(containers=[Container(
                    name="c", resources=ResourceRequirements(
                        requests={"cpu": "1800m"}))], priority=0)))
        sched.run_until_idle()
        high = Pod(metadata=ObjectMeta(name="high"),
                   spec=PodSpec(containers=[Container(
                       name="c", resources=ResourceRequirements(
                           requests={"cpu": "1800m"}))], priority=100))
        hub.create_pod(high)
        for _ in range(6):
            sched.run_until_idle()
            clock[0] += 3.0
            sched.queue.flush_backoff_completed()
        sched.run_until_idle()
        assert hub.get_pod(high.metadata.uid).spec.node_name == "n0"
        body = next(b for path, b in _StubExtender.calls
                    if path.endswith("/preempt"))
        victims = next(iter(
            body["nodeNameToMetaVictims"].values()))["pods"]
        assert victims and set(victims[0]) == {"uid"}
        sched.close()
    finally:
        srv.shutdown()
        srv.server_close()


# suite-tier discipline (tests/test_markers.py): area marker
import pytest  # noqa: E402
pytestmark = pytest.mark.core
