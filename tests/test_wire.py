"""Wire codec round-trips (utils/wire.py) — the hub transport's analog of
apimachinery serialization. Sets must survive the boundary typed (tagged
as {"__set__": [...]}), not silently decay to lists."""

import json

from kubernetes_tpu.api.objects import (
    Node,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    Taint,
)
from kubernetes_tpu.utils.wire import from_wire, to_wire


def rt(v):
    return from_wire(json.loads(json.dumps(to_wire(v))))


def test_scalars_and_containers_round_trip():
    assert rt(5) == 5
    assert rt("x") == "x"
    assert rt([1, 2]) == [1, 2]
    assert rt({"a": [1, {"b": None}]}) == {"a": [1, {"b": None}]}


def test_sets_round_trip_typed():
    assert rt({"b", "a"}) == {"a", "b"}
    assert isinstance(rt({"a"}), set)
    assert rt(frozenset({3, 1})) == {1, 3}
    # mixed-type sets must not crash on ordering
    got = rt({1, "a"})
    assert got == {1, "a"}
    # nested inside dicts/lists
    assert rt({"k": [{"x", "y"}]}) == {"k": [{"x", "y"}]}


def test_dataclasses_round_trip():
    n = Node(metadata=ObjectMeta(name="n1", labels={"zone": "z1"}),
             spec=NodeSpec(taints=[Taint(key="k", value="v",
                                         effect="NoSchedule")]),
             status=NodeStatus(allocatable={"cpu": "4"}))
    got = rt(n)
    assert got == n
    p = Pod(metadata=ObjectMeta(name="p1"), spec=PodSpec())
    assert rt(p) == p


def test_unknown_kind_raises():
    import pytest
    with pytest.raises(ValueError):
        from_wire({"__kind__": "NoSuchKind"})


# suite-tier discipline (tests/test_markers.py): area marker
import pytest  # noqa: E402
pytestmark = pytest.mark.fabric
