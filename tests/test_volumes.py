"""Volume plugin family end-to-end: host filters ANDed into the device
result with per-plugin attribution, VolumeBinding assume/bind lifecycle
(reference: plugins/volumezone, volumerestrictions, nodevolumelimits,
volumebinding + util/assumecache)."""

from kubernetes_tpu.api.objects import (
    LABEL_HOSTNAME,
    LABEL_ZONE,
    READ_WRITE_ONCE,
    READ_WRITE_ONCE_POD,
    VOLUME_BINDING_WAIT,
    ClaimRef,
    Container,
    Node,
    NodeSelector,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    PersistentVolume,
    PersistentVolumeClaim,
    PersistentVolumeClaimSpec,
    PersistentVolumeClaimVolumeSource,
    PersistentVolumeSpec,
    Pod,
    PodSpec,
    ResourceRequirements,
    StorageClass,
    Volume,
)
from kubernetes_tpu.config.types import default_config
from kubernetes_tpu.hub import Hub
from kubernetes_tpu.ops.features import Capacities
from kubernetes_tpu.scheduler import Scheduler


def mknode(i, zone="z1", extra=None):
    name = f"node-{i}"
    alloc = {"cpu": "16", "memory": "32Gi", "pods": "110"}
    alloc.update(extra or {})
    return Node(metadata=ObjectMeta(name=name, labels={
        LABEL_HOSTNAME: name, LABEL_ZONE: zone}),
        spec=NodeSpec(), status=NodeStatus(allocatable=alloc))


def mkpod(name, volumes=None, ns="default"):
    return Pod(metadata=ObjectMeta(name=name, namespace=ns),
               spec=PodSpec(
                   containers=[Container(name="c",
                                         resources=ResourceRequirements(
                                             requests={"cpu": "100m"}))],
                   volumes=volumes or []))


def pvc_vol(claim):
    return Volume(name=claim, persistent_volume_claim=(
        PersistentVolumeClaimVolumeSource(claim_name=claim)))


def mkpvc(name, volume_name="", access=None, sc="", ns="default",
          storage="1Gi"):
    return PersistentVolumeClaim(
        metadata=ObjectMeta(name=name, namespace=ns),
        spec=PersistentVolumeClaimSpec(
            access_modes=access or [READ_WRITE_ONCE],
            storage_class_name=sc, volume_name=volume_name,
            requests={"storage": storage}))


def mkpv(name, zone=None, sc="", access=None, storage="10Gi",
         node_affinity=None, csi_driver=""):
    labels = {}
    if zone:
        labels[LABEL_ZONE] = zone
    return PersistentVolume(
        metadata=ObjectMeta(name=name, labels=labels),
        spec=PersistentVolumeSpec(
            capacity={"storage": storage},
            access_modes=access or [READ_WRITE_ONCE],
            storage_class_name=sc,
            node_affinity=node_affinity,
            csi_driver=csi_driver))


def mksched(hub, batch=16):
    cfg = default_config()
    cfg.batch_size = batch
    return Scheduler(hub, cfg, caps=Capacities(nodes=16, pods=64))


def bound_node(hub, pod):
    return hub.get_pod(pod.metadata.uid).spec.node_name


def cond_message(hub, pod):
    conds = hub.get_pod(pod.metadata.uid).status.conditions
    return conds[0].message if conds else ""


def test_volume_zone_mismatch_rejects_with_plugin_name():
    hub = Hub()
    sched = mksched(hub)
    hub.create_node(mknode(0, zone="east"))
    hub.create_pv(mkpv("pv-west", zone="west"))
    hub.create_pvc(mkpvc("claim", volume_name="pv-west"))
    p = mkpod("p", volumes=[pvc_vol("claim")])
    hub.create_pod(p)
    sched.run_until_idle()
    assert bound_node(hub, p) == ""
    assert "VolumeZone" in cond_message(hub, p)


def test_volume_zone_match_schedules_on_matching_node():
    hub = Hub()
    sched = mksched(hub)
    hub.create_node(mknode(0, zone="east"))
    hub.create_node(mknode(1, zone="west"))
    hub.create_pv(mkpv("pv-west", zone="west"))
    hub.create_pvc(mkpvc("claim", volume_name="pv-west"))
    p = mkpod("p", volumes=[pvc_vol("claim")])
    hub.create_pod(p)
    sched.run_until_idle()
    assert bound_node(hub, p) == "node-1"


def test_volume_restrictions_gce_pd_conflict():
    hub = Hub()
    sched = mksched(hub)
    hub.create_node(mknode(0))
    hub.create_node(mknode(1))
    disk = Volume(name="d", gce_pd_name="pd-1")
    a, b = mkpod("a", volumes=[disk]), mkpod("b", volumes=[disk])
    hub.create_pod(a)
    hub.create_pod(b)
    sched.run_until_idle()
    na, nb = bound_node(hub, a), bound_node(hub, b)
    assert na and nb and na != nb, "same disk never shares a node"


def test_volume_restrictions_single_node_unschedulable():
    hub = Hub()
    sched = mksched(hub)
    hub.create_node(mknode(0))
    disk = Volume(name="d", gce_pd_name="pd-1")
    a, b = mkpod("a", volumes=[disk]), mkpod("b", volumes=[disk])
    hub.create_pod(a)
    hub.create_pod(b)
    sched.run_until_idle()
    placed = [p for p in (a, b) if bound_node(hub, p)]
    assert len(placed) == 1
    loser = a if bound_node(hub, a) == "" else b
    assert "VolumeRestrictions" in cond_message(hub, loser)


def test_read_write_once_pod_conflict():
    hub = Hub()
    sched = mksched(hub)
    hub.create_node(mknode(0))
    hub.create_node(mknode(1))
    hub.create_pv(mkpv("pv1"))
    hub.create_pvc(mkpvc("rwop", volume_name="pv1",
                         access=[READ_WRITE_ONCE_POD]))
    a, b = (mkpod("a", volumes=[pvc_vol("rwop")]),
            mkpod("b", volumes=[pvc_vol("rwop")]))
    hub.create_pod(a)
    hub.create_pod(b)
    sched.run_until_idle()
    placed = [p for p in (a, b) if bound_node(hub, p)]
    assert len(placed) == 1, "ReadWriteOncePod is cluster-exclusive"
    loser = a if bound_node(hub, a) == "" else b
    assert "VolumeRestrictions" in cond_message(hub, loser)


def test_node_volume_limits():
    hub = Hub()
    sched = mksched(hub)
    hub.create_node(mknode(0, extra={"attachable-volumes-csi-x": "1"}))
    hub.create_node(mknode(1, extra={"attachable-volumes-csi-x": "1"}))
    for i in range(2):
        hub.create_pv(mkpv(f"pv{i}", csi_driver="x"))
        hub.create_pvc(mkpvc(f"c{i}", volume_name=f"pv{i}"))
    a, b = (mkpod("a", volumes=[pvc_vol("c0")]),
            mkpod("b", volumes=[pvc_vol("c1")]))
    hub.create_pod(a)
    hub.create_pod(b)
    sched.run_until_idle()
    na, nb = bound_node(hub, a), bound_node(hub, b)
    assert na and nb and na != nb, "limit 1 per node forces a spread"


def test_unbound_immediate_claim_is_unresolvable():
    hub = Hub()
    sched = mksched(hub)
    hub.create_node(mknode(0))
    hub.create_pvc(mkpvc("claim"))      # no storage class => Immediate
    p = mkpod("p", volumes=[pvc_vol("claim")])
    hub.create_pod(p)
    sched.run_until_idle()
    assert bound_node(hub, p) == ""
    assert "VolumeBinding" in cond_message(hub, p)


def test_wait_for_first_consumer_binds_pv_at_prebind():
    hub = Hub()
    sched = mksched(hub)
    hub.create_node(mknode(0))
    hub.create_node(mknode(1))
    hub.create_storage_class(StorageClass(
        metadata=ObjectMeta(name="wffc"),
        volume_binding_mode=VOLUME_BINDING_WAIT))
    # PV restricted to node-1 via node affinity
    aff = NodeSelector(node_selector_terms=[NodeSelectorTerm(
        match_expressions=[NodeSelectorRequirement(
            key=LABEL_HOSTNAME, operator="In", values=["node-1"])])])
    hub.create_pv(mkpv("pv1", sc="wffc", node_affinity=aff))
    hub.create_pvc(mkpvc("claim", sc="wffc"))
    p = mkpod("p", volumes=[pvc_vol("claim")])
    hub.create_pod(p)
    sched.run_until_idle()
    assert bound_node(hub, p) == "node-1", "only node-1 matches the PV"
    pv = hub.get_pv("pv1")
    pvc = hub.get_pvc("default", "claim")
    assert pv.spec.claim_ref is not None
    assert pv.spec.claim_ref.name == "claim"
    assert pv.status.phase == "Bound"
    assert pvc.spec.volume_name == "pv1"
    assert pvc.status.phase == "Bound"


def test_wffc_no_matching_pv_no_provisioner_rejects():
    hub = Hub()
    sched = mksched(hub)
    hub.create_node(mknode(0))
    hub.create_storage_class(StorageClass(
        metadata=ObjectMeta(name="wffc"),
        volume_binding_mode=VOLUME_BINDING_WAIT))
    hub.create_pvc(mkpvc("claim", sc="wffc", storage="100Gi"))
    hub.create_pv(mkpv("small", sc="wffc", storage="1Gi"))  # too small
    p = mkpod("p", volumes=[pvc_vol("claim")])
    hub.create_pod(p)
    sched.run_until_idle()
    assert bound_node(hub, p) == ""
    assert "VolumeBinding" in cond_message(hub, p)


def test_two_pods_one_pv_serialized():
    """Two pods wanting the same unbound claim family: host-serial deferral
    keeps them in separate batches; only one PV exists, so only one claim
    binds and the other pod parks."""
    hub = Hub()
    sched = mksched(hub)
    hub.create_node(mknode(0))
    hub.create_node(mknode(1))
    hub.create_storage_class(StorageClass(
        metadata=ObjectMeta(name="wffc"),
        volume_binding_mode=VOLUME_BINDING_WAIT))
    hub.create_pv(mkpv("pv1", sc="wffc"))
    hub.create_pvc(mkpvc("c1", sc="wffc"))
    hub.create_pvc(mkpvc("c2", sc="wffc"))
    a = mkpod("a", volumes=[pvc_vol("c1")])
    b = mkpod("b", volumes=[pvc_vol("c2")])
    hub.create_pod(a)
    hub.create_pod(b)
    sched.run_until_idle()
    bound = [p for p in (a, b) if bound_node(hub, p)]
    assert len(bound) == 1
    pv = hub.get_pv("pv1")
    assert pv.spec.claim_ref is not None


def test_volume_pod_and_plain_pods_mix():
    """Volume-less pods ride the normal fast path in the same batch."""
    hub = Hub()
    sched = mksched(hub)
    hub.create_node(mknode(0, zone="east"))
    hub.create_node(mknode(1, zone="west"))
    hub.create_pv(mkpv("pv-east", zone="east"))
    hub.create_pvc(mkpvc("claim", volume_name="pv-east"))
    vol_pod = mkpod("vp", volumes=[pvc_vol("claim")])
    plain = [mkpod(f"p{i}") for i in range(5)]
    hub.create_pod(vol_pod)
    for p in plain:
        hub.create_pod(p)
    sched.run_until_idle()
    assert bound_node(hub, vol_pod) == "node-0"
    assert all(bound_node(hub, p) for p in plain)
    assert sched.stats["scheduled"] == 6


# suite-tier discipline (tests/test_markers.py): area marker
import pytest  # noqa: E402
pytestmark = pytest.mark.core
