from kubernetes_tpu.api.objects import (
    LABEL_ZONE,
    Container,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    ResourceRequirements,
)
from kubernetes_tpu.backend.cache import Cache
from kubernetes_tpu.backend.node_info import NodeInfo
from kubernetes_tpu.backend.snapshot import Snapshot


def mknode(name, zone=None, cpu="4", mem="8Gi"):
    labels = {LABEL_ZONE: zone} if zone else {}
    return Node(metadata=ObjectMeta(name=name, labels=labels),
                status=NodeStatus(allocatable={"cpu": cpu, "memory": mem, "pods": "110"}))


def mkpod(name, node="", cpu="100m", uid=None):
    meta = ObjectMeta(name=name)
    if uid:
        meta.uid = uid
    return Pod(metadata=meta,
               spec=PodSpec(node_name=node, containers=[
                   Container(resources=ResourceRequirements(requests={"cpu": cpu}))]))


def test_node_info_aggregates():
    ni = NodeInfo(mknode("n1"))
    assert ni.allocatable.milli_cpu == 4000
    p = mkpod("p1", "n1", cpu="500m")
    ni.add_pod(p)
    assert ni.requested.milli_cpu == 500
    assert len(ni.pods) == 1
    assert ni.remove_pod(p)
    assert ni.requested.milli_cpu == 0
    assert not ni.pods


def test_assume_confirm_flow():
    c = Cache()
    c.add_node(mknode("n1"))
    p = mkpod("p1", "n1", cpu="1")
    c.assume_pod(p)
    assert c.is_assumed_pod(p)
    assert c.pod_count() == 1
    c.finish_binding(p)
    # informer confirms
    c.add_pod(p)
    assert not c.is_assumed_pod(p)
    assert c.pod_count() == 1
    c.remove_pod(p)
    assert c.pod_count() == 0


def test_forget_pod():
    c = Cache()
    c.add_node(mknode("n1"))
    p = mkpod("p1", "n1")
    c.assume_pod(p)
    c.forget_pod(p)
    assert c.pod_count() == 0
    assert not c.is_assumed_pod(p)


def test_assumed_pod_ttl_expiry():
    t = [100.0]
    c = Cache(ttl=30.0, now=lambda: t[0])
    c.add_node(mknode("n1"))
    p = mkpod("p1", "n1")
    c.assume_pod(p)
    c.finish_binding(p)
    assert c.cleanup_assumed_pods() == []
    t[0] = 131.0
    expired = c.cleanup_assumed_pods()
    assert [e.metadata.uid for e in expired] == [p.metadata.uid]
    assert c.pod_count() == 0


def test_snapshot_incremental():
    c = Cache()
    snap = Snapshot()
    c.add_node(mknode("n1"))
    c.add_node(mknode("n2"))
    c.update_snapshot(snap)
    assert snap.num_nodes() == 2
    gen1 = snap.generation

    # adding a pod touches only n1's row
    c.add_pod(mkpod("p1", "n1", cpu="2"))
    c.update_snapshot(snap)
    assert snap.generation > gen1
    assert snap.get("n1").requested.milli_cpu == 2000
    assert snap.get("n2").requested.milli_cpu == 0

    # removing a node shrinks the list
    c.remove_node(mknode("n2"))
    c.update_snapshot(snap)
    assert snap.num_nodes() == 1
    assert snap.get("n2") is None


def test_snapshot_is_immutable_view():
    c = Cache()
    snap = Snapshot()
    c.add_node(mknode("n1"))
    c.update_snapshot(snap)
    before = snap.get("n1").requested.milli_cpu
    c.add_pod(mkpod("p1", "n1", cpu="3"))
    # cache changed, snapshot not yet refreshed
    assert snap.get("n1").requested.milli_cpu == before


def test_zone_interleaving():
    c = Cache()
    snap = Snapshot()
    for i in range(4):
        c.add_node(mknode(f"a{i}", zone="za"))
    for i in range(2):
        c.add_node(mknode(f"b{i}", zone="zb"))
    c.update_snapshot(snap)
    order = [ni.name for ni in snap.node_info_list]
    # round-robin: zones alternate while both have nodes
    first_four = order[:4]
    assert {first_four[0][0], first_four[1][0]} == {"a", "b"}
    assert {first_four[2][0], first_four[3][0]} == {"a", "b"}


def test_remove_node_with_pods_keeps_info():
    c = Cache()
    n = mknode("n1")
    c.add_node(n)
    c.add_pod(mkpod("p1", "n1"))
    c.remove_node(n)
    snap = Snapshot()
    c.update_snapshot(snap)
    # node-less info is excluded from the snapshot list
    assert snap.num_nodes() == 0
    # but pod removal later fully cleans up
    assert c.pod_count() == 1


def test_imaginary_node_from_early_pod():
    c = Cache()
    c.add_pod(mkpod("p1", "ghost"))
    assert c.pod_count() == 1
    snap = Snapshot()
    c.update_snapshot(snap)
    assert snap.num_nodes() == 0
    c.add_node(mknode("ghost"))
    c.update_snapshot(snap)
    assert snap.num_nodes() == 1
    assert snap.get("ghost").requested.milli_cpu == 100


def test_host_port_conflicts():
    from kubernetes_tpu.backend.node_info import HostPortInfo

    h = HostPortInfo()
    h.add("", "TCP", 8080)
    assert h.conflicts("", "TCP", 8080)
    assert h.conflicts("10.0.0.1", "TCP", 8080)  # wildcard clashes with any ip
    assert not h.conflicts("", "UDP", 8080)
    assert not h.conflicts("", "TCP", 8081)
    h2 = HostPortInfo()
    h2.add("10.0.0.1", "TCP", 443)
    assert h2.conflicts("0.0.0.0", "TCP", 443)
    assert h2.conflicts("10.0.0.1", "TCP", 443)
    assert not h2.conflicts("10.0.0.2", "TCP", 443)
    h2.remove("10.0.0.1", "TCP", 443)
    assert not h2.conflicts("0.0.0.0", "TCP", 443)


def test_incremental_device_push_matches_full_upload():
    """After incremental syncs, the scattered device buffers must equal a
    fresh full pack (the device half of UpdateSnapshot integrity,
    cache.go:266-277 snapshot-recovery invariant)."""
    import numpy as np

    from kubernetes_tpu.backend.mirror import Mirror
    from kubernetes_tpu.models.testbed import build_cluster, make_node, make_pod
    from kubernetes_tpu.ops.features import Capacities

    caps = Capacities(nodes=32, pods=64)
    cache, snap, mirror = build_cluster(10, caps=caps)
    _ = mirror.to_blobs()  # first full upload
    # churn: add pods, remove a node, add a node
    for i in range(5):
        p = make_pod(i)
        p.spec.node_name = f"node-{i}"
        cache.add_pod(p)
    cache.remove_node(cache._nodes["node-7"].info.node)
    cache.add_node(make_node(20))
    cache.update_snapshot(snap)
    mirror.sync(snap)
    blobs = mirror.to_blobs()  # incremental scatter path
    np.testing.assert_array_equal(np.asarray(blobs.node_f32), mirror.node_f32)
    np.testing.assert_array_equal(np.asarray(blobs.node_i32), mirror.node_i32)
    np.testing.assert_array_equal(np.asarray(blobs.pods_i32), mirror.pods_i32)


def test_cache_comparer_against_hub():
    """backend/cache/debugger/comparer.go CompareNodes/ComparePods."""
    from kubernetes_tpu.hub import Hub

    hub = Hub()
    cache = Cache()
    n = mknode("n0")
    hub.create_node(n)
    cache.add_node(n)
    p = mkpod("p", node="n0")
    hub.create_pod(p)
    cache.add_pod(p)
    assert cache.compare_with_hub(hub) == [], "consistent views"
    # a node the cache never learned about
    hub.create_node(mknode("n1"))
    problems = cache.compare_with_hub(hub)
    assert any("n1 in apiserver but not in cache" in s for s in problems)
    cache.add_node(mknode("n1"))
    # a pod bound in the hub the cache missed
    q = mkpod("q", node="n1")
    hub.create_pod(q)
    problems = cache.compare_with_hub(hub)
    assert any("bound in apiserver but not in cache" in s
               for s in problems)
    # assumed pods lead the API: not a discrepancy
    cache.add_pod(q)
    a = mkpod("a")
    assumed = a.clone()
    assumed.spec.node_name = "n0"
    cache.assume_pod(assumed)
    assert cache.compare_with_hub(hub) == []


# suite-tier discipline (tests/test_markers.py): area marker
import pytest  # noqa: E402
pytestmark = pytest.mark.core
