"""Test bootstrap: force JAX onto a virtual 8-device CPU platform so all
sharding/mesh tests run without TPU hardware (the driver separately
dry-run-compiles the multi-chip path via __graft_entry__.dryrun_multichip).

XLA compilation on this box is slow (~2-8s per jit even for trivial
programs), so the persistent compilation cache is enabled with no size/time
floor: the first full test run pays the compiles, subsequent runs hit disk.
"""

import os

# Hard-set (not setdefault): the harness environment pre-sets
# JAX_PLATFORMS=axon, which would silently route the whole suite through the
# tunneled single TPU chip — slow, and no 8-device mesh for sharding tests.
# The axon plugin ignores the env var, so the config API below is the one
# that actually sticks; the env var is set too for subprocesses.
os.environ["JAX_PLATFORMS"] = "cpu"
import re as _re

_flags = os.environ.get("XLA_FLAGS", "")
_m = _re.search(r"--xla_force_host_platform_device_count=(\d+)", _flags)
if _m is None:
    _flags += " --xla_force_host_platform_device_count=8"
elif int(_m.group(1)) < 8:  # replace a pre-set smaller count
    _flags = (_flags[:_m.start()]
              + "--xla_force_host_platform_device_count=8" + _flags[_m.end():])
os.environ["XLA_FLAGS"] = _flags.strip()

# persistent compile cache: the JAX_* env vars are not honored by this JAX
# build (verified: cache stays "disabled/not initialized"), so use the config
# API via the shared setup helper; respects a pre-set KTPU_JAX_CACHE.
_repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
import sys  # noqa: E402

sys.path.insert(0, _repo)
from kubernetes_tpu.utils.jaxsetup import setup as _jax_setup  # noqa: E402

_jax_setup(os.path.join(_repo, ".jax_cache"))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
