"""Scenario engine (ISSUE 17): trace codecs, generator determinism,
node-lifecycle injection, SLO helpers, the replay driver's gates, and
the filed-regression-trace ratchet.

Tier-1 keeps the codec/generator/lifecycle/SLO units plus a
seconds-scale replay smoke and the replay of every filed regression
trace (the permanent gate the fuzzer arms); the fuzzer search loop
itself is slow-marked.
"""

from __future__ import annotations

import glob
import os
import random

import pytest

from kubernetes_tpu.api.objects import (
    Node,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
)
from kubernetes_tpu.hub import Hub
from kubernetes_tpu.scenario.generators import (
    GENERATORS,
    REPLAY_CONFIG,
    generate,
)
from kubernetes_tpu.scenario.lifecycle import NodeLifecycle
from kubernetes_tpu.scenario.replay import replay_trace
from kubernetes_tpu.scenario.trace import (
    MAGIC,
    Trace,
    TraceEvent,
    load_trace,
    save_trace,
)
from kubernetes_tpu.telemetry.slo import (
    evaluate_slo,
    percentile,
    time_to_bind_stats,
)
from kubernetes_tpu.utils.tracing import PodTimelines

pytestmark = pytest.mark.scenario

TRACE_DIR = os.path.join(os.path.dirname(__file__), "regression_traces")


# ------------------------------------------------------------- codecs


def _random_trace(rng: random.Random, n_events: int = 40) -> Trace:
    tr = Trace(name=f"fuzz-{rng.randrange(1 << 20)}", generator="fuzz",
               seed=rng.randrange(1 << 16),
               params={"x": rng.random(), "n": rng.randrange(100)},
               config=dict(REPLAY_CONFIG),
               slo={"time_to_bind_p99_ms": rng.randrange(1, 10000)},
               meta={"nested": {"list": [1, "two", None, 3.5]}})
    t = 0.0
    for i in range(n_events):
        t += rng.random()
        kind = rng.choice(("pod", "node_up", "node_down", "node_cordon",
                           "node_uncordon", "group", "obj"))
        tr.events.append(TraceEvent(
            t=round(t, 6), kind=kind,
            data={"name": f"obj-{i}", "i": i,
                  "payload": {"deep": [rng.random(), "s"]}}))
    return tr


def test_codec_round_trip_fuzz():
    rng = random.Random(7)
    for _ in range(25):
        tr = _random_trace(rng, n_events=rng.randrange(0, 60))
        js = tr.to_bytes("jsonl")
        bn = tr.to_bytes("bin1")
        assert bn[:4] == MAGIC
        r_js = Trace.from_bytes(js)
        r_bn = Trace.from_bytes(bn)
        # jsonl ↔ bin1 ↔ original agree event-for-event and header-for-
        # header (re-serialization is the canonical comparison)
        assert r_js.to_bytes("jsonl") == js
        assert r_bn.to_bytes("jsonl") == js
        assert r_bn.to_bytes("bin1") == bn


def test_codec_torn_tail_tolerance():
    """A trace cut mid-write (crash / torn copy) must yield the
    decodable prefix — the WAL-resume semantics — in BOTH formats."""
    rng = random.Random(11)
    tr = _random_trace(rng, n_events=30)
    for fmt in ("jsonl", "bin1"):
        raw = tr.to_bytes(fmt)
        for cut in (len(raw) - 1, len(raw) - 7, len(raw) // 2):
            torn = Trace.from_bytes(raw[:cut])
            assert len(torn.events) <= len(tr.events)
            # the surviving prefix is intact, not half-decoded
            for got, want in zip(torn.events, tr.events):
                assert (got.t, got.kind, got.data) == \
                    (want.t, want.kind, want.data)


def test_codec_torn_header_raises():
    tr = _random_trace(random.Random(3), n_events=2)
    with pytest.raises(ValueError):
        Trace.from_bytes(tr.to_bytes("bin1")[:6])
    with pytest.raises(ValueError):
        Trace.from_bytes(b"")


def test_save_load_by_suffix(tmp_path):
    tr = _random_trace(random.Random(5), n_events=10)
    pj = str(tmp_path / "t.jsonl")
    pb = str(tmp_path / "t.bin")
    save_trace(tr, pj)
    save_trace(tr, pb)
    assert open(pj, "rb").read()[:1] == b"{"      # git-diffable
    assert open(pb, "rb").read()[:4] == MAGIC
    assert load_trace(pj).to_bytes("jsonl") == \
        load_trace(pb).to_bytes("jsonl")


# --------------------------------------------------------- generators


@pytest.mark.parametrize("name", sorted(GENERATORS))
def test_generator_determinism_byte_identical(name):
    a = generate(name, seed=12)
    b = generate(name, seed=12)
    assert a.to_bytes("jsonl") == b.to_bytes("jsonl")
    assert a.to_bytes("bin1") == b.to_bytes("bin1")
    # a different seed must actually move the trace
    assert generate(name, seed=13).to_bytes("jsonl") != \
        a.to_bytes("jsonl")


@pytest.mark.parametrize("name", sorted(GENERATORS))
def test_generator_traces_are_wellformed(name):
    tr = generate(name, seed=1)
    assert tr.generator == name
    assert tr.slo, "every regime declares an intent SLO"
    assert tr.events == sorted(tr.events, key=lambda e: e.t)
    counts = tr.counts()
    # feasibility discipline: pods never terminate, so the trace must
    # fit the shared replay capacities or replay wedges forever
    assert counts.get("pod", 0) <= REPLAY_CONFIG["pod_capacity"]
    uids = [e.data["pod"]["metadata"]["uid"] for e in tr.events
            if e.kind == "pod"]
    assert len(uids) == len(set(uids)), "pod uids must be unique"
    assert GENERATORS[name].bounds, "every regime is fuzzable"
    # fuzz bounds only name real parameters
    assert set(GENERATORS[name].bounds) <= set(GENERATORS[name].defaults)


def test_generator_params_override_and_unknown_regime():
    tr = generate("zone_outage", {"outage_len": 8.0}, seed=2)
    assert tr.params["outage_len"] == 8.0
    with pytest.raises(KeyError):
        generate("nope")


# ------------------------------------------------------ node lifecycle


def _mknode(name: str) -> Node:
    return Node(metadata=ObjectMeta(name=name,
                                    labels={"kubernetes.io/hostname": name}),
                spec=NodeSpec(),
                status=NodeStatus(allocatable={"cpu": "4"}))


def test_node_lifecycle_add_remove_cordon():
    hub = Hub()
    life = NodeLifecycle(hub)
    life.add(_mknode("n1"))
    assert hub.get_node("n1") is not None
    # cordon flips spec.unschedulable on the stored object; repeat is a
    # no-op (idempotent across torn-tail replay resume)
    assert life.cordon("n1") is True
    assert hub.get_node("n1").spec.unschedulable is True
    assert life.cordon("n1") is False
    assert life.uncordon("n1") is True
    assert hub.get_node("n1").spec.unschedulable is False
    assert life.remove("n1") is True
    assert hub.get_node("n1") is None
    # all verbs tolerate missing targets
    assert life.remove("n1") is False
    assert life.cordon("ghost") is False
    assert life.uncordon("ghost") is False


def test_harness_churn_routes_nodes_through_lifecycle():
    """The Churn op and the replayer share ONE node code path."""
    import inspect

    from kubernetes_tpu.perf import harness
    src = inspect.getsource(harness._ChurnState)
    assert "NodeLifecycle" in src


# ------------------------------------------------------------ slo math


def test_percentile_interpolation():
    assert percentile([], 99) == 0.0
    assert percentile([5.0], 50) == 5.0
    vals = [float(i) for i in range(1, 101)]
    assert percentile(vals, 0) == 1.0
    assert percentile(vals, 100) == 100.0
    assert abs(percentile(vals, 50) - 50.5) < 1e-9


def _timelines_with(binds: dict[str, tuple[float, float]]) -> PodTimelines:
    tl = PodTimelines(capacity=64, now=lambda: 0.0)
    for uid, (enq, bnd) in binds.items():
        pod = Pod(metadata=ObjectMeta(name=uid, uid=uid))
        tl.event(pod, "enqueued", t=enq)
        if bnd is not None:
            tl.event(pod, "bound", t=bnd)
    return tl


def test_time_to_bind_stats_filter_and_scale():
    tl = _timelines_with({
        "a": (0.0, 0.1), "b": (0.0, 0.2), "c": (1.0, 2.0),
        "never": (0.0, None),
    })
    assert set(tl.bind_latencies()) == {"a", "b", "c"}
    s = time_to_bind_stats(tl)
    assert s["count"] == 3
    assert s["time_to_bind_max_ms"] == 1000.0
    # uid filter (replay excludes warmup pods this way)
    s2 = time_to_bind_stats(tl, uids={"a", "b"})
    assert s2["count"] == 2 and s2["time_to_bind_max_ms"] == 200.0
    # scale converts wall->trace time at a compression factor
    s3 = time_to_bind_stats(tl, uids={"c"}, scale=3.0)
    assert s3["time_to_bind_p50_ms"] == 3000.0


def test_evaluate_slo_breaches_and_unknown_metric():
    stats = {"time_to_bind_p99_ms": 900.0}
    assert evaluate_slo(stats, {"time_to_bind_p99_ms": 1000.0})["ok"]
    v = evaluate_slo(stats, {"time_to_bind_p99_ms": 800.0})
    assert not v["ok"] and v["breaches"][0]["value"] == 900.0
    # a typo'd gate key fails LOUDLY instead of silently passing
    assert not evaluate_slo(stats, {"time_to_bind_p9_ms": 1e9})["ok"]
    assert evaluate_slo(stats, None)["ok"]
    assert evaluate_slo(stats, {})["ok"]


def test_harness_quality_rows_carry_ttb_p50_p99_max():
    """bench quality rows and scenario SLO gates share one PodTimelines
    pass (satellite 1) — the keys must exist on a tiny real run."""
    from kubernetes_tpu.perf.harness import (
        CreateNodes,
        CreatePods,
        Workload,
        run_workload,
    )
    from kubernetes_tpu.perf.workloads import _node, _pod

    w = Workload(name="ttb-smoke", ops=[
        CreateNodes(4, _node),
        CreatePods(8, lambda i: _pod(f"q-{i}")),
    ], node_capacity=8, pod_capacity=32, batch_size=8)
    r = run_workload(w)
    q = r["quality"]
    for k in ("time_to_bind_p50_ms", "time_to_bind_p99_ms",
              "time_to_bind_max_ms"):
        assert k in q and q[k] >= 0.0
    assert q["time_to_bind_p50_ms"] <= q["time_to_bind_p99_ms"] \
        <= q["time_to_bind_max_ms"]


# ------------------------------------------------------- replay driver


def test_replay_smoke_seconds_scale():
    """Tier-1 replay smoke: a shrunken quota storm replays in seconds —
    completed, exactly-once, SLO green, scenario metrics populated."""
    tr = generate("quota_storm",
                  {"tenants": 8, "pods_per_tenant": 4, "nodes": 8,
                   "window": 1.0}, seed=4)
    # speed 3 is the calibration speed: trace-time stats are wall × 3,
    # so compute latency is judged at the margin the SLOs were set at
    rep = replay_trace(tr, speed=3.0, timeout_s=120.0)
    assert rep["completed"], rep
    assert rep["audit"]["ok"], rep["audit"]
    assert rep["slo"]["ok"], rep["slo"]
    assert rep["stats"]["count"] == rep["pods"] == 32
    assert rep["injected"] == rep["events"]
    # wall stats scale to trace-time stats by exactly `speed`
    assert rep["stats"]["time_to_bind_p99_ms"] == pytest.approx(
        rep["stats_wall"]["time_to_bind_p99_ms"] * rep["speed"], abs=0.05)
    # the warmup pre-compiled every shape family this trace exercises:
    # a mid-replay compile would poison latency SLOs with a one-off
    # multi-second stall that is a HARNESS artifact, not a regression
    assert rep["device"]["warmup_compiles"] > 0
    assert rep["device"]["mid_replay_compiles"] == 0, rep["device"]


def test_overload_stampede_gates_priority_pods_only():
    """Tier-1 overload smoke: a shrunken best-effort stampede replays
    green — the time-to-bind SLO is judged over the priority pods ONLY
    (``slo_uid_prefix``), because best-effort pods waiting out the
    storm is the shed working, not a regression — while the journal
    audit still covers every pod exactly-once."""
    tr = generate("overload_stampede",
                  {"nodes": 8, "be_tenants": 4, "pods_per_tenant": 8,
                   "prio_pods": 12, "burst_at": 1.0, "burst_window": 0.5,
                   "duration": 4.0}, seed=9)
    assert tr.config["slo_uid_prefix"] == "uid-prio-"
    rep = replay_trace(tr, speed=3.0, timeout_s=120.0)
    assert rep["completed"], rep
    assert rep["audit"]["ok"], rep["audit"]
    assert rep["slo"]["ok"], rep["slo"]
    # the SLO was scoped: 12 priority pods judged, all 44 audited
    assert rep["pods"] == 4 * 8 + 12
    assert rep["slo_pods"] == 12
    assert rep["stats"]["count"] == 12
    assert rep["device"]["mid_replay_compiles"] == 0, rep["device"]


def test_replay_gates_on_filed_regression_traces():
    """The permanent ratchet: every fuzzer-filed trace must replay
    green against its gate (observed-at-filing × headroom) with
    journal-audit exactly-once, at the speed its verdict was judged."""
    paths = sorted(glob.glob(os.path.join(TRACE_DIR, "*.jsonl")))
    assert paths, ("tests/regression_traces/ is empty — the fuzzer "
                   "must keep at least one filed losing trace")
    for path in paths:
        tr = load_trace(path)
        assert tr.gate, f"{path} filed without a ratchet gate"
        assert tr.meta.get("filed_speed"), f"{path} lost its speed"
        # the filed evidence: at filing time the trace BREACHED its
        # regime intent SLO (that's why it was filed)
        assert tr.meta.get("breaches"), path
        rep = replay_trace(tr, speed=float(tr.meta["filed_speed"]),
                           timeout_s=150.0)
        assert rep["completed"], (path, rep)
        assert rep["audit"]["ok"], (path, rep["audit"])
        assert rep["gate"]["ok"], (path, rep["gate"])


# ------------------------------------------------------------- fuzzer


@pytest.mark.slow
def test_fuzz_budgeted_search_files_breaching_trace(tmp_path):
    """A bounded fuzz over zone_outage finds a parameter cell breaching
    the regime SLO, files it, and the filed trace reproduces its
    breach deterministically."""
    from kubernetes_tpu.scenario.fuzz import fuzz

    rep = fuzz(regimes=["zone_outage"], budget_s=90.0, seed=0,
               speed=3.0, out_dir=str(tmp_path))
    assert rep["candidates"] >= 1
    assert rep["filed"], rep["worst"]
    filed = load_trace(rep["filed"][0])
    # regenerating from the filed header reproduces the trace bytes
    regen = generate(filed.generator, filed.params, seed=filed.seed)
    regen.gate, regen.meta = filed.gate, filed.meta
    assert regen.to_bytes("jsonl") == filed.to_bytes("jsonl")
    r2 = replay_trace(filed, speed=float(filed.meta["filed_speed"]))
    assert r2["completed"] and r2["audit"]["ok"]
    assert not r2["slo"]["ok"], "filed breach must reproduce"
    assert r2["gate"]["ok"], "ratchet gate must hold at filing margin"
