"""NodeResourcesFit scoring strategies + multi-profile routing
(reference: most_allocated.go, requested_to_capacity_ratio.go,
profile/profile.go:47-66 frameworkForPod)."""

from kubernetes_tpu.api.objects import (
    Container,
    LABEL_HOSTNAME,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    ResourceRequirements,
)
from kubernetes_tpu.config.types import (
    SchedulerProfile,
    default_config,
    default_plugins,
)
from kubernetes_tpu.hub import Hub
from kubernetes_tpu.ops.features import Capacities
from kubernetes_tpu.scheduler import Scheduler


def mknode(name, cpu="10"):
    return Node(metadata=ObjectMeta(name=name,
                                    labels={LABEL_HOSTNAME: name}),
                status=NodeStatus(allocatable={"cpu": cpu,
                                               "memory": "32Gi",
                                               "pods": "110"}))


def mkpod(name, cpu="1", scheduler=None):
    spec = PodSpec(containers=[Container(
        name="c", resources=ResourceRequirements(
            requests={"cpu": cpu, "memory": "1Gi"}))])
    if scheduler:
        spec.scheduler_name = scheduler
    return Pod(metadata=ObjectMeta(name=name), spec=spec)


def mksched(hub, cfg=None):
    cfg = cfg or default_config()
    cfg.batch_size = 16
    return Scheduler(hub, cfg, caps=Capacities(nodes=16, pods=64))


def _fit_only(cfg):
    """Score only by NodeResourcesFit so the strategy decides the node."""
    from kubernetes_tpu.config.types import Plugin, PluginSet

    cfg.profiles[0].plugins.score = PluginSet(disabled=[
        Plugin("TaintToleration"), Plugin("NodeAffinity"),
        Plugin("NodeResourcesBalancedAllocation"), Plugin("ImageLocality")])


def test_least_allocated_default_prefers_empty_node():
    hub = Hub()
    cfg = default_config()
    _fit_only(cfg)
    sched = mksched(hub, cfg)
    hub.create_node(mknode("busy"))
    hub.create_node(mknode("idle"))
    filler = mkpod("filler", cpu="6")
    hub.create_pod(filler)
    sched.run_until_idle()
    busy_node = hub.get_pod(filler.metadata.uid).spec.node_name
    p = mkpod("p")
    hub.create_pod(p)
    sched.run_until_idle()
    assert hub.get_pod(p.metadata.uid).spec.node_name != busy_node


def test_most_allocated_prefers_packed_node():
    hub = Hub()
    cfg = default_config()
    _fit_only(cfg)
    cfg.profiles[0].plugin_config["NodeResourcesFit"] = {
        "scoring_strategy": {"type": "MostAllocated"}}
    sched = mksched(hub, cfg)
    hub.create_node(mknode("busy"))
    hub.create_node(mknode("idle"))
    filler = mkpod("filler", cpu="6")
    hub.create_pod(filler)
    sched.run_until_idle()
    busy_node = hub.get_pod(filler.metadata.uid).spec.node_name
    p = mkpod("p")
    hub.create_pod(p)
    sched.run_until_idle()
    assert hub.get_pod(p.metadata.uid).spec.node_name == busy_node, \
        "MostAllocated bin-packs onto the busy node"


def test_requested_to_capacity_ratio_shape():
    """A bin-packing shape (score rises with utilization) behaves like
    MostAllocated; requested_to_capacity_ratio.go:60."""
    hub = Hub()
    cfg = default_config()
    _fit_only(cfg)
    cfg.profiles[0].plugin_config["NodeResourcesFit"] = {
        "scoring_strategy": {
            "type": "RequestedToCapacityRatio",
            "requested_to_capacity_ratio": {"shape": [
                {"utilization": 0, "score": 0},
                {"utilization": 100, "score": 10},
            ]}}}
    sched = mksched(hub, cfg)
    hub.create_node(mknode("busy"))
    hub.create_node(mknode("idle"))
    filler = mkpod("filler", cpu="6")
    hub.create_pod(filler)
    sched.run_until_idle()
    busy_node = hub.get_pod(filler.metadata.uid).spec.node_name
    p = mkpod("p")
    hub.create_pod(p)
    sched.run_until_idle()
    assert hub.get_pod(p.metadata.uid).spec.node_name == busy_node


def test_multi_profile_routing_and_foreign_pods_skipped():
    hub = Hub()
    cfg = default_config()
    # second profile: bin-packing flavor under its own name
    packy = SchedulerProfile(scheduler_name="packy",
                             plugins=default_plugins())
    packy.plugin_config["NodeResourcesFit"] = {
        "scoring_strategy": {"type": "MostAllocated"}}
    cfg.profiles.append(packy)
    sched = mksched(hub, cfg)
    hub.create_node(mknode("n0"))
    hub.create_node(mknode("n1"))
    ours = mkpod("ours")
    theirs = mkpod("theirs", scheduler="packy")
    foreign = mkpod("foreign", scheduler="somebody-else")
    for p in (ours, theirs, foreign):
        hub.create_pod(p)
    sched.run_until_idle()
    assert hub.get_pod(ours.metadata.uid).spec.node_name
    assert hub.get_pod(theirs.metadata.uid).spec.node_name
    assert hub.get_pod(foreign.metadata.uid).spec.node_name == "", \
        "a foreign schedulerName pod is another scheduler's business"
    assert sched.stats["scheduled"] == 2
    assert len(sched.queue) == 0, "foreign pod never enqueued"


def test_two_profiles_different_strategies_in_one_drain():
    """default (LeastAllocated) spreads; packy (MostAllocated) packs —
    both served from one queue, one launch per profile per batch."""
    hub = Hub()
    cfg = default_config()
    _fit_only(cfg)
    packy = SchedulerProfile(scheduler_name="packy",
                             plugins=default_plugins())
    packy.plugin_config["NodeResourcesFit"] = {
        "scoring_strategy": {"type": "MostAllocated"}}
    from kubernetes_tpu.config.types import Plugin, PluginSet

    packy.plugins.score = PluginSet(disabled=[
        Plugin("TaintToleration"), Plugin("NodeAffinity"),
        Plugin("NodeResourcesBalancedAllocation"), Plugin("ImageLocality")])
    cfg.profiles.append(packy)
    sched = mksched(hub, cfg)
    hub.create_node(mknode("busy"))
    hub.create_node(mknode("idle"))
    filler = mkpod("filler", cpu="6")
    hub.create_pod(filler)
    sched.run_until_idle()
    busy_node = hub.get_pod(filler.metadata.uid).spec.node_name
    spread_pod = mkpod("spread-me")
    pack_pod = mkpod("pack-me", scheduler="packy")
    hub.create_pod(spread_pod)
    hub.create_pod(pack_pod)
    sched.run_until_idle()
    assert hub.get_pod(spread_pod.metadata.uid).spec.node_name != busy_node
    assert hub.get_pod(pack_pod.metadata.uid).spec.node_name == busy_node


# suite-tier discipline (tests/test_markers.py): area marker
import pytest  # noqa: E402
pytestmark = pytest.mark.core
