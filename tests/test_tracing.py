"""Flight recorder + pod lifecycle timelines + /debug trace surface.

The always-on CycleTrace recorder (utils/tracing.py): every scheduling
cycle's phases into a bounded ring + the phase/plugin histograms, pod
lifecycle stamps behind /debug/pod, and the authz-gated serving
endpoints that expose both. The slow-cycle Trace (log_if_long) keeps its
coverage in test_metrics.py.
"""

import json
import urllib.error
import urllib.request

import pytest

from kubernetes_tpu.api.objects import (
    Container,
    LABEL_HOSTNAME,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    ResourceRequirements,
)
from kubernetes_tpu.config.types import default_config
from kubernetes_tpu.hub import Hub
from kubernetes_tpu.metrics import FINE_DURATION_BUCKETS, Histogram
from kubernetes_tpu.ops.features import Capacities
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.serving import ServingEndpoints, token_auth
from kubernetes_tpu.utils.tracing import (
    CYCLE_PHASES,
    CycleTrace,
    DRA_VIEW_PHASES,
    FlightRecorder,
    HOST_PHASES,
    PodTimelines,
)


def mknode(i):
    return Node(metadata=ObjectMeta(name=f"node-{i}",
                                    labels={LABEL_HOSTNAME: f"node-{i}"}),
                status=NodeStatus(allocatable={"cpu": "8", "memory": "16Gi",
                                               "pods": "110"}))


def mkpod(name, cpu="100m"):
    return Pod(metadata=ObjectMeta(name=name),
               spec=PodSpec(containers=[Container(
                   name="c", resources=ResourceRequirements(
                       requests={"cpu": cpu}))]))


def _sched(hub, recorder_capacity=256, export_path=None):
    cfg = default_config()
    cfg.batch_size = 16
    cfg.flight_recorder_capacity = recorder_capacity
    cfg.trace_export_path = export_path
    return Scheduler(hub, cfg, caps=Capacities(nodes=16, pods=64))


# ------------------------------------------------- CycleTrace units


def test_cycle_trace_accumulates_and_totals():
    tr = CycleTrace(cycle=1, start=100.0, pods=8)
    tr.add("host_plugins", 0.01)
    tr.add("host_plugins", 0.02)   # touched twice: accumulates
    tr.add("device_launch", 0.1)
    tr.add("dra_mask_compile", 0.001)  # VIEWS: excluded from total()
    tr.add("dra_device_eval", 0.004)
    assert abs(tr.phases["host_plugins"] - 0.03) < 1e-12
    assert abs(tr.total() - 0.13) < 1e-12
    d = tr.to_dict()
    assert d["phases_ms"]["dra_device_eval"] == 4.0


def test_phase_vocabulary():
    # host-tail arithmetic depends on these set relations
    assert set(HOST_PHASES) < set(CYCLE_PHASES)
    assert set(DRA_VIEW_PHASES) < set(CYCLE_PHASES)
    assert not set(DRA_VIEW_PHASES) & set(HOST_PHASES)
    assert "device_launch" not in HOST_PHASES


# --------------------------------------------- FlightRecorder units


def _hists():
    phase = Histogram("phase", buckets=FINE_DURATION_BUCKETS,
                      label_names=("phase",))
    plugin = Histogram("plugin", buckets=FINE_DURATION_BUCKETS,
                       label_names=("plugin", "extension_point"))
    return phase, plugin


def test_recorder_ring_is_bounded_and_feeds_histograms():
    phase, plugin = _hists()
    rec = FlightRecorder(phase_hist=phase, plugin_hist=plugin, capacity=4)
    for i in range(10):
        tr = rec.begin(start=float(i), pods=2)
        tr.add("queue_pop", 0.001)
        tr.add("commit", 0.002)
        rec.record(tr)
    assert len(rec.ring) == 4, "ring bounded at capacity"
    assert [t["cycle"] for t in rec.last(2)] == [9, 10]
    assert rec.last(0) == [] and rec.last(-5) == [], \
        "n<=0 asks for nothing, not the whole ring"
    assert phase.count(phase="queue_pop") == 10
    assert phase.count(phase="commit") == 10
    pct = rec.phase_percentiles()
    assert set(pct) == {"queue_pop", "commit"}
    assert pct["commit"]["count"] == 10


def test_recorder_disabled_paths():
    rec = FlightRecorder(capacity=0)
    assert not rec.enabled
    tr = rec.begin(start=0.0, pods=4)
    tr.add("commit", 1.0)            # null trace: add is a no-op
    assert tr.phases == {}
    rec.record(tr)
    rec.observe_phase("commit", 1.0)
    rec.plugin_observe("NodeAffinity", "Filter", 1.0)
    assert len(rec.ring) == 0
    assert rec.phase_percentiles() == {} or rec.phase_hist is None


def test_plugin_observe_feeds_dra_view():
    phase, plugin = _hists()
    rec = FlightRecorder(phase_hist=phase, plugin_hist=plugin)
    tr = rec.begin(start=0.0, pods=1)
    rec.plugin_observe("NodeAffinity", "Filter", 0.001)
    rec.plugin_observe("DynamicResources", "Filter", 0.002)
    rec.plugin_observe("DynamicResources", "Reserve", 0.003)
    rec.record(tr)
    # per-plugin timings land on the current cycle...
    assert tr.plugins["NodeAffinity/Filter"] == 0.001
    # ...and DynamicResources time additionally fills the split dra_*
    # phase views: host Filter time -> dra_device_eval, commit-time
    # Reserve bookkeeping -> dra_commit
    assert abs(tr.phases["dra_device_eval"] - 0.002) < 1e-12
    assert abs(tr.phases["dra_commit"] - 0.003) < 1e-12
    assert plugin.count(plugin="DynamicResources",
                        extension_point="Filter") == 1
    keys = set(rec.plugin_percentiles())
    assert {"NodeAffinity/Filter", "DynamicResources/Reserve"} <= keys


def test_recorder_resume_reattaches_dispatched_cycle():
    phase, plugin = _hists()
    rec = FlightRecorder(phase_hist=phase, plugin_hist=plugin)
    tr_k = rec.begin(start=0.0, pods=1)
    tr_k1 = rec.begin(start=1.0, pods=1)   # pipelined: k+1 dispatched
    assert rec.current is tr_k1
    rec.resume(tr_k)                        # finishing k: plugins land on k
    rec.plugin_observe("DynamicResources", "Reserve", 0.001)
    assert "dra_commit" in tr_k.phases
    assert "dra_commit" not in tr_k1.phases
    rec.record(tr_k)
    assert rec.current is None or rec.current is tr_k1


def test_host_tail_share():
    phase, _ = _hists()
    rec = FlightRecorder(phase_hist=phase)
    tr = rec.begin(start=0.0, pods=1)
    tr.add("host_plugins", 0.03)           # host
    tr.add("device_launch", 0.06)          # device
    tr.add("commit", 0.01)                 # host
    tr.add("dra_device_eval", 0.02)        # view: excluded
    rec.record(tr)
    assert abs(rec.host_tail_share() - 0.4) < 1e-9


def test_commit_pull_overlap_excluded_from_total_and_tail():
    """Pipelined waves: the commit thread's device pull is booked as the
    "commit_pull" overlap phase — rendered per cycle, but excluded from
    total() and host_tail_share(); device_launch carries only the loop
    thread's actual blocked wait. Before the split the pull landed in
    device_launch on the pipelined arm, counting overlapped commit-thread
    time as if the loop had been stalled on it."""
    from kubernetes_tpu.utils.tracing import (
        EXCLUDED_PHASES,
        OVERLAP_PHASES,
        VIEW_PHASES,
    )

    assert "commit_pull" in CYCLE_PHASES
    assert "commit_pull" in OVERLAP_PHASES
    assert set(EXCLUDED_PHASES) == set(VIEW_PHASES) | set(OVERLAP_PHASES)
    phase, _ = _hists()
    rec = FlightRecorder(phase_hist=phase)
    tr = rec.begin(start=0.0, pods=1)
    tr.add("host_plugins", 0.03)           # host
    tr.add("device_launch", 0.06)          # loop-thread blocked wait
    tr.add("commit", 0.01)                 # host
    tr.add("commit_pull", 0.05)            # commit-thread pull: overlap
    rec.record(tr)
    # the pull never inflates the cycle total...
    assert abs(tr.total() - 0.10) < 1e-12
    assert tr.to_dict()["total_ms"] == 100.0
    # ...or the host-tail attribution...
    assert abs(rec.host_tail_share() - 0.4) < 1e-9
    # ...but still renders per cycle for /debug/trace readers
    assert tr.to_dict()["phases_ms"]["commit_pull"] == 50.0


def test_recorder_jsonl_export(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    rec = FlightRecorder(capacity=8, export_path=path)
    for i in range(3):
        tr = rec.begin(start=float(i), pods=1)
        tr.add("commit", 0.001 * (i + 1))
        rec.record(tr)
    rec.close()
    lines = [json.loads(ln) for ln in open(path)]
    assert [ln["cycle"] for ln in lines] == [1, 2, 3]
    assert lines[2]["phases_ms"]["commit"] == 3.0


# --------------------------------------------------- PodTimelines


def test_timelines_lru_and_lookup():
    tl = PodTimelines(capacity=2, now=lambda: 1.0)
    pods = [mkpod(f"p{i}") for i in range(3)]
    for p in pods:
        tl.event(p, "enqueued")
    assert len(tl) == 2, "LRU bounded"
    assert tl.get(name="p0") is None, "oldest evicted"
    got = tl.get(name="p2")
    assert got["events"][0]["event"] == "enqueued"
    assert tl.get(uid=pods[1].metadata.uid)["name"] == "p1"
    tl.forget(pods[1].metadata.uid)
    assert tl.get(name="p1") is None


def test_timelines_event_cap_keeps_head_and_tail():
    tl = PodTimelines(now=lambda: 0.0)
    p = mkpod("stormy")
    tl.event(p, "enqueued")
    for i in range(200):
        tl.event(p, "popped", f"attempt {i}")
    events = tl.get(name="stormy")["events"]
    assert len(events) <= PodTimelines.MAX_EVENTS_PER_POD
    assert events[0]["event"] == "enqueued", "timeline anchor survives"
    assert events[-1]["detail"] == "attempt 199", "newest tail survives"


def test_timelines_diagnosis():
    tl = PodTimelines(now=lambda: 5.0)
    p = mkpod("sick")
    tl.diagnose(p, {"NodeResourcesFit": 12}, {"VolumeZone": 1},
                "no feasible node")
    d = tl.get(name="sick")["diagnosis"]
    assert d["device_rejects"] == {"NodeResourcesFit": 12}
    assert d["host_rejects"] == {"VolumeZone": 1}
    assert d["at"] == 5.0


# -------------------------------------- scheduler integration


def test_scheduler_records_cycle_phases_and_timelines():
    hub = Hub()
    sched = _sched(hub)
    try:
        hub.create_node(mknode(0))
        for i in range(5):
            hub.create_pod(mkpod(f"p{i}"))
        sched.run_until_idle()
        assert len(sched.flight.ring) >= 1
        cyc = sched.flight.last(1)[0]
        for phase in ("queue_pop", "snapshot_sync", "pack",
                      "device_dispatch", "device_launch", "commit"):
            assert phase in cyc["phases_ms"], phase
        assert cyc["scheduled"] >= 1
        # phase histogram fed (the /metrics surface)
        m = sched.metrics
        assert m.phase_duration.count(phase="commit") >= 1
        # per-plugin timing under the new plugin label
        assert m.plugin_duration.total_count() >= 1
        # the reference's e2e pod_scheduling_duration_seconds by attempts
        assert m.pod_e2e_duration.count(attempts="1") == 5
        # timelines: wire-created -> enqueued -> popped -> bound (the
        # hub commit's trace stamp now anchors the timeline)
        t = sched.timelines.get(name="p0")
        evs = [e["event"] for e in t["events"]]
        assert evs[0] == "wire:created"
        assert evs[1] == "enqueued"
        assert "popped" in evs and "bound" in evs
        # the cross-wire join: created + bound stamps present (no
        # kubelet in this harness, so no ack — joined stays None)
        assert "created" in t["wire"] and "bound" in t["wire"]
        assert t["joined"] is None
        text = m.registry.render_text()
        assert "scheduling_phase_duration_seconds_bucket" in text
        assert "plugin_execution_duration_seconds_bucket" in text
        assert "pod_scheduling_duration_seconds_bucket" in text
    finally:
        sched.close()


def test_scheduler_unschedulable_diagnosis():
    hub = Hub()
    sched = _sched(hub)
    try:
        hub.create_node(mknode(0))
        hub.create_pod(mkpod("big", cpu="64"))   # never fits the 8-cpu node
        sched.run_until_idle()
        t = sched.timelines.get(name="big")
        assert t is not None
        evs = [e["event"] for e in t["events"]]
        assert "unschedulable" in evs and "bound" not in evs
        d = t["diagnosis"]
        assert d is not None
        # the device filter that rejected, from the pulled reject_counts
        assert "NodeResourcesFit" in d["device_rejects"]
        assert d["device_rejects"]["NodeResourcesFit"] >= 1
    finally:
        sched.close()


def test_scheduler_recorder_disabled_still_schedules():
    hub = Hub()
    sched = _sched(hub, recorder_capacity=0)
    try:
        assert not sched.flight.enabled
        hub.create_node(mknode(0))
        hub.create_pod(mkpod("p"))
        sched.run_until_idle()
        assert hub.get_pod(
            [p for p in hub.list_pods()][0].metadata.uid
        ).spec.node_name, "pod bound with the recorder off"
        assert len(sched.flight.ring) == 0
        assert sched.metrics.phase_duration.total_count() == 0
    finally:
        sched.close()


def test_scheduler_trace_export(tmp_path):
    path = str(tmp_path / "cycles.jsonl")
    hub = Hub()
    sched = _sched(hub, export_path=path)
    try:
        hub.create_node(mknode(0))
        hub.create_pod(mkpod("p"))
        sched.run_until_idle()
    finally:
        sched.close()
    lines = [json.loads(ln) for ln in open(path)]
    assert lines and "phases_ms" in lines[0]


# --------------------------------------- /debug/trace + /debug/pod


def _get(url, token=None):
    req = urllib.request.Request(url)
    if token:
        req.add_header("Authorization", f"Bearer {token}")
    return urllib.request.urlopen(req, timeout=5)


def test_debug_trace_and_pod_endpoints_authz():
    hub = Hub()
    sched = _sched(hub)
    try:
        hub.create_node(mknode(0))
        hub.create_pod(mkpod("p0"))
        hub.create_pod(mkpod("big", cpu="64"))
        sched.run_until_idle()

        # no authz callback: 403 for the whole /debug surface
        srv = ServingEndpoints(sched, port=0)
        srv.start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            for ep in ("/debug/trace", "/debug/pod?name=p0"):
                with pytest.raises(urllib.error.HTTPError) as ei:
                    _get(base + ep)
                assert ei.value.code == 403, ep
        finally:
            srv.stop()

        # token authz: bad/missing bearer 401, good token 200 + data
        srv = ServingEndpoints(sched, port=0,
                               debug_auth=token_auth("s3cret"))
        srv.start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            for ep in ("/debug/trace", "/debug/pod?name=p0"):
                with pytest.raises(urllib.error.HTTPError) as ei:
                    _get(base + ep)
                assert ei.value.code == 401, ep
                with pytest.raises(urllib.error.HTTPError) as ei:
                    _get(base + ep, token="wrong")
                assert ei.value.code == 401, ep

            tr = json.loads(_get(f"{base}/debug/trace?n=4",
                                 token="s3cret").read())
            assert tr["enabled"] is True
            assert tr["cycles"], "ring exposed"
            assert len(tr["cycles"]) <= 4
            assert "commit" in tr["phases"]
            assert 0.0 <= tr["host_tail_share"] <= 1.0

            pd = json.loads(_get(f"{base}/debug/pod?name=p0",
                                 token="s3cret").read())
            assert pd["name"] == "p0"
            assert [e["event"] for e in pd["events"]][:2] \
                == ["wire:created", "enqueued"]
            # the unschedulable pod's diagnosis rides the same endpoint
            sick = json.loads(_get(f"{base}/debug/pod?name=big",
                                   token="s3cret").read())
            assert sick["diagnosis"] is not None

            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(f"{base}/debug/pod?name=nope", token="s3cret")
            assert ei.value.code == 404
        finally:
            srv.stop()
    finally:
        sched.close()


# suite-tier discipline (tests/test_markers.py): area marker
pytestmark = pytest.mark.observability
