"""Gang scheduling + multi-tenant job queues (ISSUE 6): the JobQueue's
DRR/quota/gang gating, the GangScheduling plugin's all-or-nothing Permit
(quorum assembly, timeout rollback with zero leaked reservations), gang
poison quarantine, and whole-gang preemption expansion."""

import pytest

from kubernetes_tpu.api.objects import (
    LABEL_POD_GROUP,
    LABEL_QUEUE,
    ObjectMeta,
    PodGroup,
    pod_group_key,
)
from kubernetes_tpu.backend.jobqueue import JobQueue
from kubernetes_tpu.config.types import default_config
from kubernetes_tpu.hub import Hub
from kubernetes_tpu.ops.features import Capacities
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.testing import MakeNode, MakePod
from kubernetes_tpu.utils.wire import from_wire, to_wire

pytestmark = pytest.mark.gang


class Clock:
    def __init__(self):
        self.t = 1000.0

    def now(self):
        return self.t

    def tick(self, dt):
        self.t += dt


class FakePQ:
    """Release sink standing in for the PriorityQueue."""

    def __init__(self):
        self.pods = []

    def add(self, pod):
        self.pods.append(pod)


def tenant_pod(name, tenant, cpu="100m", gang=None):
    p = MakePod().name(name).req(cpu=cpu).obj()
    p.metadata.labels[LABEL_QUEUE] = tenant
    if gang is not None:
        p.metadata.labels[LABEL_POD_GROUP] = gang
    return p


def group(name, min_member, queue="default", timeout=30.0, ns="default"):
    return PodGroup(metadata=ObjectMeta(name=name, namespace=ns),
                    min_member=min_member, queue=queue,
                    schedule_timeout_seconds=timeout)


# --------------------------------------------------------- JobQueue


def test_routing_only_labeled_pods():
    jq = JobQueue()
    plain = MakePod().name("plain").obj()
    assert not JobQueue.wants(plain)
    assert JobQueue.wants(tenant_pod("t", "a"))
    assert JobQueue.wants(tenant_pod("g", "a", gang="g1"))
    # an un-labeled pod never creates queue state
    assert len(jq) == 0


def test_drr_weighted_fairness_under_contention():
    """Weight 2:1 must yield a 2:1 admission ratio while both tenants
    have backlog — the fairness half of the acceptance criteria."""
    jq = JobQueue({"a": {"weight": 2.0}, "b": {"weight": 1.0}})
    for i in range(40):
        jq.add(tenant_pod(f"a-{i}", "a"))
        jq.add(tenant_pod(f"b-{i}", "b"))
    pq = FakePQ()
    released = jq.release(pq, budget=30)
    assert released == 30 == len(pq.pods)
    by_tenant = {"a": 0, "b": 0}
    for p in pq.pods:
        by_tenant[p.metadata.labels[LABEL_QUEUE]] += 1
    # DRR with integer rounding: 2:1 within one quantum of slack
    assert 18 <= by_tenant["a"] <= 21, by_tenant
    assert 9 <= by_tenant["b"] <= 12, by_tenant


def test_quota_blocks_tenant_without_starving_others():
    jq = JobQueue({"greedy": {"quota": {"pods": "2"}},
                   "free": {}})
    for i in range(5):
        jq.add(tenant_pod(f"g-{i}", "greedy"))
        jq.add(tenant_pod(f"f-{i}", "free"))
    pq = FakePQ()
    jq.release(pq, budget=64)
    admitted = [p.metadata.name for p in pq.pods]
    assert sum(1 for n in admitted if n.startswith("g-")) == 2
    assert sum(1 for n in admitted if n.startswith("f-")) == 5, \
        "a quota-blocked tenant must not starve other tenants"
    assert jq.tenant_stats()["greedy"]["quota_blocked"] > 0
    # deleting an admitted pod credits the reservation: one more admits
    victim = next(p for p in pq.pods if p.metadata.name.startswith("g-"))
    jq.remove(victim)
    pq2 = FakePQ()
    jq.release(pq2, budget=64)
    assert [p.metadata.name[:2] for p in pq2.pods] == ["g-"]


def test_cpu_quota_blocks_oversized_unit_not_smaller_ones():
    jq = JobQueue({"a": {"quota": {"cpu": "1"}}})
    jq.add(tenant_pod("big", "a", cpu="900m"))
    jq.add(tenant_pod("small", "a", cpu="100m"))
    pq = FakePQ()
    jq.release(pq, budget=8)
    names = {p.metadata.name for p in pq.pods}
    assert names == {"big", "small"}      # 900m + 100m fits exactly
    jq.add(tenant_pod("third", "a", cpu="100m"))
    pq2 = FakePQ()
    jq.release(pq2, budget=8)
    assert pq2.pods == [], "over-quota unit must stay queued"
    assert jq.tenant_stats()["a"]["quota_blocked"] >= 1
    # crediting an admitted pod's reservation unblocks the queued one
    jq.remove(next(p for p in pq.pods if p.metadata.name == "big"))
    pq3 = FakePQ()
    jq.release(pq3, budget=8)
    assert [p.metadata.name for p in pq3.pods] == ["third"]


def test_gang_gates_on_group_and_min_member():
    """Members queue behind min_member; the whole gang releases at once
    (all-or-nothing) only when the group is known and assembled."""
    jq = JobQueue()
    # members arrive BEFORE their PodGroup: orphan pool
    m0 = tenant_pod("g-0", "a", gang="job1")
    m1 = tenant_pod("g-1", "a", gang="job1")
    jq.add(m0)
    jq.add(m1)
    pq = FakePQ()
    assert jq.release(pq, budget=8) == 0, "no PodGroup yet"
    # group arrives, min_member=3: still assembling
    jq.set_group(group("job1", 3, queue="a"))
    assert jq.release(pq, budget=8) == 0, "below min_member"
    assert jq.debug_state()["gangs"]["default/job1"][
        "members_present"] == 2
    # third member completes the gang: all 3 release together
    jq.add(tenant_pod("g-2", "a", gang="job1"))
    assert jq.release(pq, budget=8) == 3
    assert {p.metadata.name for p in pq.pods} == {"g-0", "g-1", "g-2"}
    assert jq.was_admitted(m0.metadata.uid)


def test_gang_release_is_atomic_even_over_budget():
    """A gang never splits across release budgets: min_member=4 with
    budget=2 releases 4 (overdraw) or nothing — never a partial gang."""
    jq = JobQueue()
    jq.set_group(group("big", 4, queue="a"))
    for i in range(4):
        jq.add(tenant_pod(f"b-{i}", "a", gang="big"))
    pq = FakePQ()
    released = jq.release(pq, budget=2)
    assert released in (0, 4)
    if released == 0:           # credit accrues across calls
        for _ in range(8):
            released += jq.release(pq, budget=2)
            if released:
                break
    assert released == 4
    assert len(pq.pods) == 4


def test_assembling_gang_does_not_block_singles_behind_it():
    jq = JobQueue()
    jq.set_group(group("stuck", 5, queue="a"))
    jq.add(tenant_pod("stuck-0", "a", gang="stuck"))
    jq.add(tenant_pod("single", "a"))
    pq = FakePQ()
    assert jq.release(pq, budget=8) == 1
    assert pq.pods[0].metadata.name == "single"
    assert jq.pending_count() == 1


def test_group_delete_returns_unit_to_orphans():
    """Deleting a PodGroup must not wedge its queued members: the unit
    falls back to the orphan pool and re-joins when the group returns."""
    jq = JobQueue()
    jq.set_group(group("j", 2, queue="a"))
    jq.add(tenant_pod("j-0", "a", gang="j"))
    jq.add(tenant_pod("j-1", "a", gang="j"))
    jq.remove_group("default/j")
    pq = FakePQ()
    assert jq.release(pq, budget=8) == 0
    assert jq.pending_count() == 2, "members must survive group delete"
    assert jq.debug_state()["gangs"]["default/j"].get("orphan")
    jq.set_group(group("j", 2, queue="a"))       # group re-created
    assert jq.release(pq, budget=8) == 2

def test_quota_blocked_counts_once_per_release_call():
    jq = JobQueue({"a": {"quota": {"pods": "1"}}})
    jq.add(tenant_pod("p0", "a"))
    jq.add(tenant_pod("p1", "a"))
    pq = FakePQ()
    jq.release(pq, budget=64)        # p0 admits, p1 quota-denied once
    # one denial per unit per release() call, not per DRR scan round —
    # and a FULLY blocked tenant then parks idle: subsequent calls skip
    # the re-probe entirely instead of re-counting the same denial
    assert jq.tenant_stats()["a"]["quota_blocked"] == 1
    jq.release(pq, budget=64)        # idle: no probe, no new denial
    assert jq.tenant_stats()["a"]["quota_blocked"] == 1
    jq.add(tenant_pod("p2", "a"))    # fresh work wakes the tenant
    jq.release(pq, budget=64)        # p1 + p2 each denied once
    assert jq.tenant_stats()["a"]["quota_blocked"] == 3

def test_blocked_tenant_does_not_bank_drr_credit():
    """A quota-blocked tenant must not accrue deficit while blocked —
    banked credit would let it burst past its weight when unblocked."""
    jq = JobQueue({"burst": {"weight": 1.0, "quota": {"pods": "1"}},
                   "steady": {"weight": 1.0}})
    jq.add(tenant_pod("b-keep", "burst"))
    pq = FakePQ()
    jq.release(pq, budget=8)                     # burst uses its quota
    for i in range(30):
        jq.add(tenant_pod(f"b-{i}", "burst"))    # blocked backlog
        jq.add(tenant_pod(f"s-{i}", "steady"))
    for _ in range(50):                          # many blocked rounds
        jq.release(pq, budget=4)
    assert jq._tenants["burst"].deficit == 0.0, \
        "an unproductive turn must zero the deficit, not bank it"

def test_credit_gated_gang_not_starved_by_single_trickle():
    """A gang awaiting DRR credit at the head of its tenant queue must
    not be starved by a trickle of same-tenant singles behind it: the
    tenant's turn STOPS at the credit-gated gang so its deficit accrues
    (bounded wait), instead of singles spending it to zero every round."""
    jq = JobQueue({"a": {"weight": 1.0}, "b": {"weight": 1.0}})
    jq.set_group(group("g8", 8, queue="a"))
    for i in range(8):
        jq.add(tenant_pod(f"g-{i}", "a", gang="g8"))
    pq = FakePQ()
    for cycle in range(20):
        jq.add(tenant_pod(f"s-{cycle}", "a"))     # same-tenant trickle
        jq.add(tenant_pod(f"b-{cycle}", "b"))     # persistent contention
        jq.release(pq, budget=4)
        if any(LABEL_POD_GROUP in p.metadata.labels for p in pq.pods):
            break
    else:
        raise AssertionError(
            "credit-gated gang starved behind same-tenant singles")


def test_bound_member_replayed_before_group_charges_group_tenant():
    """Restart replay order (pods before PodGroups): a bound gang
    member's quota charge defers until its group arrives and lands on
    the group's queue — charging the pod's own label would misattribute
    permanently (charge-once) and let the real tenant exceed quota."""
    jq = JobQueue({"team": {"quota": {"pods": "4"}}})
    p = MakePod().name("old-0").req(cpu="100m").obj()
    p.metadata.labels[LABEL_POD_GROUP] = "j"      # no LABEL_QUEUE
    p.spec.node_name = "n0"
    jq.note_bound(p)                              # group not seen yet
    stats = jq.tenant_stats()
    assert stats.get("default", {}).get("usage", {}).get("pods", 0) == 0, \
        "deferred charge must not land on the label-derived tenant"
    jq.set_group(group("j", 2, queue="team"))
    assert jq.tenant_stats()["team"]["usage"]["pods"] == 1
    assert jq.was_admitted(p.metadata.uid)
    jq.remove(p)                                  # delete credits back
    assert jq.tenant_stats()["team"]["usage"]["pods"] == 0


def test_gang_routes_by_group_queue_despite_member_labels():
    """One gang whose pods carry inconsistent queue labels must not
    split into same-keyed units under several tenants (none could ever
    reach min_member): the PodGroup's queue is authoritative."""
    jq = JobQueue()
    jq.set_group(group("j", 4, queue="a"))
    for i, tenant in enumerate(["a", "a", "b", "b"]):
        jq.add(tenant_pod(f"j-{i}", tenant, gang="j"))
    pq = FakePQ()
    assert jq.release(pq, budget=8) == 4
    assert jq.tenant_stats()["a"]["admitted"] == 4
    assert jq.pending_count() == 0


def test_group_queue_change_rehomes_queued_unit():
    """A PodGroup updated to a different queue must drag its queued unit
    along: members enqueued under the old tenant plus members routed to
    the new one would otherwise form two same-keyed halves, neither ever
    reaching min_member."""
    jq = JobQueue()
    jq.set_group(group("j", 4, queue="a"))
    jq.add(tenant_pod("j-0", "a", gang="j"))
    jq.add(tenant_pod("j-1", "a", gang="j"))
    jq.set_group(group("j", 4, queue="b"))     # queue changed mid-assembly
    jq.add(tenant_pod("j-2", "b", gang="j"))
    jq.add(tenant_pod("j-3", "b", gang="j"))
    pq = FakePQ()
    assert jq.release(pq, budget=8) == 4
    assert jq.tenant_stats()["b"]["admitted"] == 4
    assert jq.pending_count() == 0


def test_jobqueue_counts_bound_members_from_shared_registry():
    """Half-bound gang after failover: the queue's min_member gate reads
    informer-confirmed binds from the gang coordinator's registry (one
    copy of the bound-member set — the queue keeps none of its own)."""
    from kubernetes_tpu.plugins.gang import GangScheduling

    g = GangScheduling()
    g.set_group(group("j", 4, queue="a"))
    jq = JobQueue(bound_fn=g.bound_count)
    jq.set_group(group("j", 4, queue="a"))
    for i in range(2):
        old = tenant_pod(f"old-{i}", "a", gang="j")
        old.spec.node_name = f"n{i}"
        g.note_bound(old)
    jq.add(tenant_pod("tail-0", "a", gang="j"))
    jq.add(tenant_pod("tail-1", "a", gang="j"))
    pq = FakePQ()
    assert jq.release(pq, budget=8) == 2, \
        "2 queued + 2 bound members satisfy min_member=4"


def test_podgroup_wire_roundtrip():
    g = group("j", 3, queue="team-x", timeout=12.5)
    back = from_wire(to_wire(g))
    assert back == g and back.key() == "default/j"
    p = tenant_pod("m", "team-x", gang="j")
    assert pod_group_key(p) == "default/j"


# ------------------------------------------- scheduler integration


def _sched(hub, clock, nodes=4, cpu="2"):
    for i in range(nodes):
        hub.create_node(MakeNode().name(f"n{i}")
                        .capacity(cpu=cpu, memory="8Gi", pods="110").obj())
    cfg = default_config()
    cfg.batch_size = 16
    return Scheduler(hub, cfg, caps=Capacities(nodes=16, pods=128),
                     now=clock.now)


def test_gang_binds_all_members_together():
    hub = Hub()
    clock = Clock()
    sched = _sched(hub, clock)
    try:
        hub.create_pod_group(group("job", 3, queue="t"))
        for i in range(3):
            hub.create_pod(tenant_pod(f"m-{i}", "t", gang="job"))
        sched.run_until_idle()
        bound = [p for p in hub.list_pods() if p.spec.node_name]
        assert len(bound) == 3, [p.metadata.name for p in hub.list_pods()]
        assert sched._gang.stats["admitted"] >= 1
        assert sched.metrics.gang_admitted.value() >= 1
        assert sched.cache.assumed_pod_count() == 0
    finally:
        sched.close()


def test_gang_permit_timeout_rolls_back_all_reservations():
    """The atomicity half of the acceptance criteria: min_member=3 with
    only 2 members present — both reserve and WAIT; after the gang
    timeout every reservation is rolled back, zero assumed pods leak,
    and no member is bound."""
    hub = Hub()
    clock = Clock()
    sched = _sched(hub, clock)
    try:
        hub.create_pod_group(group("half", 3, queue="t", timeout=5.0))
        hub.create_pod(tenant_pod("h-0", "t", gang="half"))
        hub.create_pod(tenant_pod("h-1", "t", gang="half"))
        # the queue holds them below min_member — force-feed the gang to
        # the framework instead, modeling members already past admission
        # (e.g. readmitted after a relist) whose third peer never shows
        sched.jobqueue.release(sched.queue, 16)
        assert sched.queue.pending_counts()["active"] == 0
        for uid, (_, key) in list(sched.jobqueue._where.items()):
            pod = hub.get_pod(uid)
            sched.jobqueue.remove(pod)
            sched.queue.add(pod)
        sched.run_until_idle()
        # both members reserved, waiting at Permit for the quorum
        waiting = sum(len(fw.waiting_pods)
                      for fw in sched.frameworks.values())
        assert waiting == 2
        assert sched.cache.assumed_pod_count() == 2
        clock.tick(6.0)                  # past schedule_timeout_seconds
        sched.run_until_idle()
        assert all(not p.spec.node_name for p in hub.list_pods()), \
            "a timed-out gang must place NO member"
        assert sched.cache.assumed_pod_count() == 0, \
            "rollback must release every reservation"
        assert sched._gang.stats["rollbacks"] >= 1
        assert sched._gang.stats["timeouts"] >= 1
        assert sched.metrics.gang_rollbacks.value() >= 1
        assert not sched._gang._assembling
    finally:
        sched.close()


def test_gang_prefilter_rejects_provably_impossible_gang():
    """min_member beyond the cluster's capacity bound parks at PreFilter
    without reserving anything (ops/gang.gang_capacity)."""
    hub = Hub()
    clock = Clock()
    sched = _sched(hub, clock, nodes=2, cpu="1")   # 2 nodes x 1 cpu
    try:
        hub.create_pod_group(group("huge", 4, queue="t"))
        for i in range(4):
            hub.create_pod(tenant_pod(f"x-{i}", "t", gang="huge",
                                      cpu="900m"))   # 1 fits per node
        sched.run_until_idle()
        assert all(not p.spec.node_name for p in hub.list_pods())
        assert sched.cache.assumed_pod_count() == 0
        assert sum(len(fw.waiting_pods)
                   for fw in sched.frameworks.values()) == 0, \
            "impossible gangs must not camp in the wait room"
    finally:
        sched.close()


def test_poisoned_member_holds_whole_gang():
    """Plugin-level: poisoning a gang rolls back its assembly and makes
    every member unschedulable until released."""
    from kubernetes_tpu.plugins.gang import GangScheduling

    class WMap:
        def __init__(self):
            self.rejected = []

        def get(self, uid):
            class WP:
                def __init__(s):
                    s.uid = uid

                def reject(s, plugin, msg):
                    rejected.append(uid)
            rejected = self.rejected
            return WP()

    g = GangScheduling()
    g.set_group(group("j", 3))
    wmap = WMap()
    g.register_waiting_map(wmap)
    m = tenant_pod("m", "t", gang="j")
    s, _ = g.permit(None, m, "n0")
    assert s.code.name == "WAIT"
    g.poison("default/j", "device fault")
    assert wmap.rejected == [m.metadata.uid], \
        "poison must reject the waiting member (atomic rollback)"
    assert g.stats["rollbacks"] == 1
    st = g.pre_filter(None, tenant_pod("m2", "t", gang="j"), None)
    assert not st.is_success() and "quarantined" in st.message()
    g.release_poison("default/j")
    st = g.pre_filter(None, tenant_pod("m3", "t", gang="j"), None)
    assert st.is_skip() or st.is_success()


def test_informer_bound_peer_completes_waiting_quorum():
    """Post-failover liveness: a member WAITing at Permit must be allowed
    when the informer confirms enough peer binds to satisfy min_member —
    not sit out its timeout and park with no wake-up event left."""
    from kubernetes_tpu.plugins.gang import GangScheduling

    class WP:
        def __init__(self, uid):
            self.uid = uid
            self.allowed = []

        def allow(self, plugin):
            self.allowed.append(plugin)

        def reject(self, plugin, msg):
            raise AssertionError("must allow, not reject")

    class WMap(dict):
        def get(self, uid):
            return super().get(uid)

    g = GangScheduling()
    g.set_group(group("j", 3))
    wmap = WMap()
    g.register_waiting_map(wmap)
    tail = tenant_pod("tail", "t", gang="j")
    s, _ = g.permit(None, tail, "n0")
    assert s.code.name == "WAIT"          # quorum 1 < 3
    wmap[tail.metadata.uid] = WP(tail.metadata.uid)
    for i in range(2):                    # peers' binds confirm late
        peer = tenant_pod(f"peer-{i}", "t", gang="j")
        peer.spec.node_name = f"n{i}"
        g.note_bound(peer)
    assert wmap[tail.metadata.uid].allowed == [g.NAME], \
        "informer-confirmed peers must complete the waiting quorum"
    assert g.stats["admitted"] == 1
    assert not g._assembling


def test_poison_is_refcounted_across_members():
    """Two quarantined members: releasing ONE must not unpoison the
    gang — the remainder would assemble, wait out the permit timeout
    holding node reservations, and roll back on repeat while the second
    member serves out its (possibly hour-capped) quarantine."""
    from kubernetes_tpu.plugins.gang import GangScheduling

    g = GangScheduling()
    g.set_group(group("j", 4))
    g.poison("default/j", "fault A", uid="u-a")
    g.poison("default/j", "fault B", uid="u-b")
    st = g.pre_filter(None, tenant_pod("m", "t", gang="j"), None)
    assert not st.is_success() and "quarantined" in st.message()
    g.release_poison("default/j", "u-a")
    st = g.pre_filter(None, tenant_pod("m2", "t", gang="j"), None)
    assert not st.is_success(), \
        "gang must stay poisoned while u-b remains quarantined"
    g.release_poison("default/j", "u-b")
    st = g.pre_filter(None, tenant_pod("m3", "t", gang="j"), None)
    assert st.is_skip() or st.is_success()


def test_flush_fetches_one_pod_list_for_all_gang_candidates():
    """The eviction flush shares ONE lazily-fetched cluster pod list
    across its whole backlog — per-candidate list_pods() would pay a
    full-cluster RPC for every gang eviction queued."""
    from kubernetes_tpu.backend.nominator import Nominator
    from kubernetes_tpu.framework.preemption import Candidate, Evaluator

    hub = Hub()
    victims = []
    for i in range(4):
        p = tenant_pod(f"v-{i}", "t", gang=f"low-{i % 2}")
        p.spec.node_name = f"n{i}"
        hub.create_pod(p)
        victims.append(p)
    calls = {"n": 0}
    real_list = hub.list_pods

    def counting_list():
        calls["n"] += 1
        return real_list()

    hub.list_pods = counting_list
    ev = Evaluator(hub, lambda: None, lambda: None, lambda: [],
                   Nominator())
    for i in range(2):
        pre = MakePod().name(f"pre-{i}").req(cpu="100m") \
            .priority(10).obj()
        ev.prepare_candidate(
            Candidate(node_name=f"n{i}", row=i,
                      victims=[victims[i]], pdb_violations=0), pre)
    ev.flush_evictions()
    assert calls["n"] == 1, \
        f"one shared list per flush, got {calls['n']}"


def test_preemption_expands_victims_to_whole_gang():
    """framework/preemption._expand_gang_victims: a gang victim pulls in
    every bound member of its gang — never a partial eviction."""
    from kubernetes_tpu.framework.preemption import Evaluator

    hub = Hub()
    members = []
    for i in range(3):
        p = tenant_pod(f"v-{i}", "t", gang="lowjob")
        p.spec.node_name = f"n{i}"
        hub.create_pod(p)
        members.append(p)
    loner = MakePod().name("loner").req(cpu="100m").obj()
    loner.spec.node_name = "n0"
    hub.create_pod(loner)
    ev = Evaluator(hub, lambda: None, lambda: None, lambda: [], None)
    preemptor = MakePod().name("pre").req(cpu="100m").priority(10).obj()
    out, blocked = ev._expand_gang_victims([members[0]], preemptor)
    assert not blocked
    assert {p.metadata.name for p in out} == {"v-0", "v-1", "v-2"}
    # non-gang victims expand to themselves only
    assert ev._expand_gang_victims([loner], preemptor) == ([loner], "")
    # a pulled-in co-member that outranks the preemptor blocks the WHOLE
    # gang eviction (co-members bypassed candidate selection, so they
    # get their own guard — and partial eviction is never an option)
    members[2].spec.priority = 100
    hub.update_pod(members[2])
    out, blocked = ev._expand_gang_victims(
        [hub.get_pod(members[0].metadata.uid)], preemptor)
    assert "outranks" in blocked and len(out) == 1


def test_gang_expansion_counts_victims_against_pdb_budget():
    """A pulled-in co-member is only safe against the PDB budget LEFT
    after the original victims (evicted in the same flush) draw it down
    — a fresh-budget check would let a whole-gang eviction overdraw a
    PDB with disruptions_allowed=1 covering victim and co-member."""
    from kubernetes_tpu.api.objects import (LabelSelector,
                                            PodDisruptionBudget)
    from kubernetes_tpu.framework.preemption import Evaluator

    hub = Hub()
    members = []
    for i in range(2):
        p = tenant_pod(f"v-{i}", "t", gang="lowjob")
        p.spec.node_name = f"n{i}"
        hub.create_pod(p)
        members.append(p)
    tight = PodDisruptionBudget(
        metadata=ObjectMeta(name="pdb"),
        selector=LabelSelector(match_labels={LABEL_POD_GROUP: "lowjob"}),
        disruptions_allowed=1)
    hub.create_pdb(tight)
    ev = Evaluator(hub, lambda: None, lambda: None, lambda: [], None)
    preemptor = MakePod().name("pre").req(cpu="100m").priority(10).obj()
    out, blocked = ev._expand_gang_victims([members[0]], preemptor)
    assert "exhausted PDB" in blocked and len(out) == 1
    # with budget for both, the whole gang expands
    hub.delete_pdb(tight.metadata.uid)
    hub.create_pdb(PodDisruptionBudget(
        metadata=ObjectMeta(name="pdb"),
        selector=LabelSelector(match_labels={LABEL_POD_GROUP: "lowjob"}),
        disruptions_allowed=2))
    out, blocked = ev._expand_gang_victims([members[0]], preemptor)
    assert not blocked and len(out) == 2


def test_gang_quarantine_poisons_and_releases_with_pod_delete():
    """Scheduler-level: quarantining a gang member poisons the whole
    gang; deleting the poisoned member releases it."""
    hub = Hub()
    clock = Clock()
    sched = _sched(hub, clock)
    try:
        hub.create_pod_group(group("j", 2, queue="t"))
        bad = tenant_pod("bad", "t", gang="j")
        hub.create_pod(bad)

        class QP:
            pod = bad
            uid = bad.metadata.uid

        sched._quarantine_pod(QP(), "injected fault")
        assert "default/j" in sched._gang.poisoned_gangs()
        sched._on_pod_delete(bad)
        assert "default/j" not in sched._gang.poisoned_gangs()
    finally:
        sched.close()
