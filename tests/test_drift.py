"""Drift sentinel: cache/mirror-vs-hub divergence detection + targeted
repair (backend/cache/debugger/comparer.go promoted from a SIGUSR2 debug
hook to a periodic maintenance-loop sentinel, ISSUE 3 tentpole layer 4).
"""

import pytest

from kubernetes_tpu.backend.cache import Cache
from kubernetes_tpu.config.types import default_config
from kubernetes_tpu.hub import Hub
from kubernetes_tpu.ops.features import Capacities
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.testing import MakeNode, MakePod


def _bound_pod(name: str, node: str):
    pod = MakePod().name(name).req(cpu="100m").obj()
    pod.spec.node_name = node
    return pod


def test_drift_report_structured_and_rendered():
    """drift_report finds every divergence class; compare_with_hub stays
    the human-readable rendering of the same findings."""
    hub = Hub()
    cache = Cache()
    for i in range(3):
        node = MakeNode().name(f"n-{i}").capacity(cpu="8").obj()
        hub.create_node(node)
        if i < 2:
            cache.add_node(node)          # n-2 missing from the cache
    ghost = MakeNode().name("ghost").obj()
    cache.add_node(ghost)                 # stale: cache-only node
    p_ok = _bound_pod("ok", "n-0")
    hub.create_pod(p_ok)
    cache.add_pod(p_ok)
    p_missing = _bound_pod("missing", "n-1")
    hub.create_pod(p_missing)             # bound in hub, absent in cache
    p_stale = _bound_pod("stale", "n-0")
    cache.add_pod(p_stale)                # cached, never bound in hub
    p_moved = _bound_pod("moved", "n-1")
    hub.create_pod(p_moved)
    cached_moved = p_moved.clone()
    cached_moved.spec.node_name = "n-0"
    cache.add_pod(cached_moved)           # node mismatch
    report = cache.drift_report(hub)
    assert report.nodes_stale == ["ghost"]
    assert [n.metadata.name for n in report.nodes_missing] == ["n-2"]
    assert [p.metadata.name for p in report.pods_stale] == ["stale"]
    assert [p.metadata.name for p in report.pods_missing] == ["missing"]
    assert [(c.metadata.name, p.spec.node_name)
            for c, p in report.pods_misplaced] == [("moved", "n-1")]
    assert report.count() == 5
    assert sorted(report.render()) == sorted(cache.compare_with_hub(hub))


def test_targeted_repair_converges_without_rebuild():
    """repair_from_hub fixes exactly the drifted entries; a second
    report is clean and the repair count matches the findings."""
    hub = Hub()
    cache = Cache()
    node = MakeNode().name("n-0").capacity(cpu="8").obj()
    hub.create_node(node)
    cache.add_node(node)
    cache.add_node(MakeNode().name("ghost").obj())
    p = _bound_pod("p", "n-0")
    hub.create_pod(p)                     # missing from cache
    stale = _bound_pod("stale", "n-0")
    cache.add_pod(stale)
    report = cache.drift_report(hub)
    assert report.count() == 3
    assert cache.repair_from_hub(hub, report) == 3
    assert cache.drift_report(hub).count() == 0
    assert cache.compare_with_hub(hub) == []
    # assumed pods are optimistic writes, never "repaired" away
    ghost = MakePod().name("assumed").req(cpu="100m").obj()
    ghost.spec.node_name = "n-0"
    cache.assume_pod(ghost)
    assert cache.drift_report(hub).count() == 0
    assert cache.repair_from_hub(hub) == 0
    assert cache.assumed_pod_count() == 1


def test_sentinel_repairs_corruption_within_one_period():
    """Acceptance: an artificially corrupted cache entry is detected and
    repaired within ONE maintenance period, by targeted re-sync (no
    relist, no rebuild), with the drift metrics advancing."""
    clock = [1000.0]
    hub = Hub()
    hub.create_node(MakeNode().name("n").capacity(cpu="8").obj())
    cfg = default_config()
    cfg.async_binding = False
    sched = Scheduler(hub, cfg, caps=Capacities(nodes=8, pods=64),
                      now=lambda: clock[0])
    try:
        pod = MakePod().name("p").req(cpu="100m").obj()
        hub.create_pod(pod)
        sched.run_until_idle()
        stored = hub.get_pod(pod.metadata.uid)
        assert stored.spec.node_name == "n"
        # corrupt the cache: drop the confirmed placement
        sched.cache.remove_pod(stored)
        assert sched.cache.compare_with_hub(hub) != []
        clock[0] += sched.drift_check_interval + 1.0
        sched.run_maintenance()           # ONE period later: sentinel runs
        assert sched.cache.compare_with_hub(hub) == []
        assert sched.metrics.drift_detected.value() == 1
        assert sched.metrics.drift_repaired.value() == 1
        assert sched.metrics.drift_rebuilds.value() == 0
        assert sched.stats["drift_repairs"] == 1
        # clean period: strikes reset, nothing repaired
        clock[0] += sched.drift_check_interval + 1.0
        sched.run_maintenance()
        assert sched.metrics.drift_repaired.value() == 1
        assert sched._drift_strikes == 0
    finally:
        sched.close()


def test_sentinel_escalates_to_full_rebuild(monkeypatch):
    """Targeted repair that cannot converge (mirror corrupt in ways the
    host diff can't see) escalates to the mirror/snapshot rebuild after
    three strikes."""
    clock = [1000.0]
    hub = Hub()
    hub.create_node(MakeNode().name("n").capacity(cpu="8").obj())
    sched = Scheduler(hub, default_config(),
                      caps=Capacities(nodes=8, pods=64),
                      now=lambda: clock[0])
    try:
        monkeypatch.setattr(
            sched.cache, "drift_report",
            lambda _hub: type("R", (), {
                "count": lambda self: 1,
                "render": lambda self: ["synthetic drift"]})())
        monkeypatch.setattr(sched.cache, "repair_from_hub",
                            lambda _hub, _r: 0)
        old_mirror = sched.mirror
        for i in range(3):
            clock[0] += sched.drift_check_interval + 1.0
            sched.run_maintenance()
        assert sched.metrics.drift_rebuilds.value() == 1
        assert sched.mirror is not old_mirror, "last resort rebuilds"
        assert sched._drift_strikes == 0
    finally:
        sched.close()


def test_sentinel_skipped_while_degraded():
    """Everything looks drifted during an outage; the sentinel must not
    'repair' phantom divergence while the hub is unreachable."""
    from kubernetes_tpu.chaos import ChaosHub

    clock = [1000.0]
    hub = Hub()
    chub = ChaosHub(hub)
    chub.create_node(MakeNode().name("n").capacity(cpu="8").obj())
    sched = Scheduler(chub, default_config(),
                      caps=Capacities(nodes=8, pods=64),
                      now=lambda: clock[0])
    try:
        chub.partition_for(3600.0)
        sched._hub_down = True
        clock[0] += sched.drift_check_interval + 1.0
        sched.run_maintenance()
        assert sched.metrics.drift_detected.value() == 0
    finally:
        sched.close()


def test_drift_check_interval_zero_disables():
    hub = Hub()
    sched = Scheduler(hub, default_config(),
                      caps=Capacities(nodes=8, pods=64))
    try:
        sched.drift_check_interval = 0.0
        sched.run_maintenance()
        assert sched.metrics.drift_detected.value() == 0
    finally:
        sched.close()


if __name__ == "__main__":
    raise SystemExit(pytest.main([__file__, "-q"]))


# ---------------- incremental diffing (ISSUE 9 satellite) ----------------


def test_incremental_drift_report_finds_changed_divergence():
    """drift_report(since_rv=) compares ONLY journal-changed objects and
    finds the same divergence classes the full diff would."""
    hub = Hub()
    cache = Cache()
    for i in range(3):
        node = MakeNode().name(f"inc-n{i}").capacity(cpu="8").obj()
        hub.create_node(node)
        cache.add_node(node)
    base = cache.drift_report(hub)
    assert base.count() == 0 and isinstance(base.rv, int)
    # divergences that all surface as journal events after base.rv:
    fresh = MakeNode().name("inc-new").obj()
    hub.create_node(fresh)                       # missing from cache
    p = _bound_pod("inc-p", "inc-n0")
    hub.create_pod(p)                            # bound pod cache missed
    moved = _bound_pod("inc-m", "inc-n1")
    hub.create_pod(moved)
    cached_moved = moved.clone()
    cached_moved.spec.node_name = "inc-n0"
    cache.add_pod(cached_moved)                  # cache has stale node
    report = cache.drift_report(hub, since_rv=base.rv)
    assert report.incremental
    assert [n.metadata.name for n in report.nodes_missing] \
        == ["inc-new"]
    assert [x.metadata.name for x in report.pods_missing] == ["inc-p"]
    assert [(c.metadata.name, h.spec.node_name)
            for c, h in report.pods_misplaced] == [("inc-m", "inc-n1")]
    # repair consumes the incremental report unchanged
    repaired = cache.repair_from_hub(hub, report)
    assert repaired == 3
    follow = cache.drift_report(hub, since_rv=report.rv)
    assert follow.count() == 0
    # deletes surface too: remove the node and its pods from hub
    hub.delete_pod(p.metadata.uid)
    report2 = cache.drift_report(hub, since_rv=follow.rv)
    assert [x.metadata.name for x in report2.pods_stale] == ["inc-p"]


def test_incremental_drift_falls_back_on_compacted_gap():
    from kubernetes_tpu.storage import RvTooOld

    hub = Hub(journal_capacity=4)
    cache = Cache()
    base = cache.drift_report(hub)
    for i in range(10):                   # blow past the tiny ring
        hub.create_node(MakeNode().name(f"cp-{i}").obj())
    with pytest.raises(RvTooOld):
        cache.drift_report(hub, since_rv=base.rv)


def test_steady_state_maintenance_pass_issues_zero_lists():
    """THE regression gate: after the first full diff, a steady-state
    drift-sentinel pass must issue ZERO cluster LIST calls — repair
    cost is O(changes), not O(cluster)."""

    from kubernetes_tpu.testing import CountingHub

    hub = Hub()
    counting = CountingHub(hub)
    for i in range(4):
        hub.create_node(MakeNode().name(f"zl-{i}").capacity(
            cpu="16").obj())
    sched = Scheduler(counting, default_config(),
                      caps=Capacities(nodes=16, pods=64))
    try:
        for i in range(6):
            hub.create_pod(MakePod().name(f"zp-{i}").req(
                cpu="100m").obj())
        sched.run_until_idle()
        sched.drift_check_interval = 1e-9
        sched._last_drift_check = 0.0
        sched._run_drift_sentinel()               # first pass: full
        assert counting.lists > 0
        assert isinstance(sched._drift_rv, int)
        counting.lists = 0
        sched._last_drift_check = 0.0
        sched._run_drift_sentinel()               # steady state
        assert counting.lists == 0, \
            "steady-state sentinel pass must not LIST the cluster"
        assert sched.stats["drift_incremental"] == 1
        # a change keeps it incremental: still zero LISTs
        hub.create_pod(MakePod().name("zp-late").req(cpu="100m").obj())
        sched.run_until_idle()
        counting.lists = 0
        sched._last_drift_check = 0.0
        sched._run_drift_sentinel()
        assert counting.lists == 0
        assert sched.stats["drift_full_lists"] == 1
    finally:
        sched.close()
        hub.close()


def test_incremental_drift_node_recreated_same_name_is_not_stale():
    """A node deleted and recreated under the same name (new uid)
    between passes must NOT surface as stale: node events reduce by
    NAME, like the cache and the full diff — a uid-keyed reduction
    would let the old uid's delete repair a LIVE node out of the
    cache."""
    hub = Hub()
    cache = Cache()
    node = MakeNode().name("reborn").capacity(cpu="8").obj()
    hub.create_node(node)
    cache.add_node(node)
    base = cache.drift_report(hub)
    assert base.count() == 0
    hub.delete_node(node.metadata.uid)
    node2 = MakeNode().name("reborn").capacity(cpu="8").obj()
    hub.create_node(node2)                 # same name, fresh uid
    cache.remove_node(node)                # informer applied both
    cache.add_node(node2)
    report = cache.drift_report(hub, since_rv=base.rv)
    assert report.count() == 0, report.render()


# suite-tier discipline (tests/test_markers.py): area marker
pytestmark = pytest.mark.core
