"""Golden parity tables, round 2 (SURVEY §4 rung 1): NodeAffinity
operator semantics (nodeaffinity/node_affinity_test.go TestNodeAffinity),
taints/tolerations (tainttoleration/taint_toleration_test.go), and host
ports (nodeports/node_ports_test.go TestNodePorts) — each case runs the
REAL device pipeline via the same harness as tests/test_golden.py."""

import pytest

from kubernetes_tpu.api.objects import (
    Affinity,
    Container,
    ContainerPort,
    NodeAffinity,
    NodeSelector,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    Taint,
    Toleration,
)
from tests.test_golden import _mknode, _mkpod, feasible_set, reject_plugins


def _aff(match_expressions=None, match_fields=None, terms=None):
    if terms is None:
        terms = [NodeSelectorTerm(
            match_expressions=match_expressions or [],
            match_fields=match_fields or [])]
    return Affinity(node_affinity=NodeAffinity(
        required=NodeSelector(node_selector_terms=terms)))


def req(key, op, *values):
    return NodeSelectorRequirement(key=key, operator=op,
                                   values=list(values))


# node_affinity_test.go TestNodeAffinity, re-expressed: one node with
# labels {foo: bar, gpu: "2"}; want = does the pod fit it?
NODE_AFFINITY_CASES = [
    ("no affinity matches everything", None, True),
    ("In matches", _aff([req("foo", "In", "bar", "value2")]), True),
    ("In mismatch", _aff([req("foo", "In", "value1", "value2")]), False),
    ("In on absent key", _aff([req("no-such", "In", "bar")]), False),
    ("NotIn matches when value differs",
     _aff([req("foo", "NotIn", "value1")]), True),
    ("NotIn rejects matching value", _aff([req("foo", "NotIn", "bar")]),
     False),
    ("NotIn matches when key absent",
     _aff([req("no-such", "NotIn", "bar")]), True),
    ("Exists matches present key", _aff([req("foo", "Exists")]), True),
    ("Exists rejects absent key", _aff([req("no-such", "Exists")]), False),
    ("DoesNotExist matches absent key",
     _aff([req("no-such", "DoesNotExist")]), True),
    ("DoesNotExist rejects present key",
     _aff([req("foo", "DoesNotExist")]), False),
    ("Gt matches larger value", _aff([req("gpu", "Gt", "1")]), True),
    ("Gt rejects equal value", _aff([req("gpu", "Gt", "2")]), False),
    ("Lt matches smaller value", _aff([req("gpu", "Lt", "3")]), True),
    ("Lt rejects equal value", _aff([req("gpu", "Lt", "2")]), False),
    ("two expressions AND within a term: both match",
     _aff([req("foo", "In", "bar"), req("gpu", "Exists")]), True),
    ("two expressions AND within a term: one fails",
     _aff([req("foo", "In", "bar"), req("gpu", "In", "9")]), False),
    ("terms OR across the selector: second matches",
     _aff(terms=[
         NodeSelectorTerm(match_expressions=[req("foo", "In", "nope")]),
         NodeSelectorTerm(match_expressions=[req("gpu", "In", "2")])]),
     True),
    ("matchFields metadata.name In matches",
     _aff(match_fields=[req("metadata.name", "In", "the-node")]), True),
    ("matchFields metadata.name In mismatches",
     _aff(match_fields=[req("metadata.name", "In", "other")]), False),
]


@pytest.mark.parametrize("name,aff,want", NODE_AFFINITY_CASES,
                         ids=[c[0] for c in NODE_AFFINITY_CASES])
def test_node_affinity_golden(name, aff, want):
    node = _mknode("the-node", labels={"foo": "bar", "gpu": "2"})
    pod = _mkpod("p", req={"cpu": "100m"}, affinity=aff)
    feas = feasible_set(pod, [node])
    assert (("the-node" in feas) == want), name
    if not want:
        _, plugins = reject_plugins(pod, [node])
        assert "NodeAffinity" in plugins, name


def tol(key="", op="Equal", value="", effect=""):
    return Toleration(key=key, operator=op, value=value, effect=effect)


# taint_toleration_test.go filter semantics: want = fits
TAINT_CASES = [
    ("no taints, no tolerations", [], [], True),
    ("NoSchedule taint, no toleration",
     [Taint(key="k", value="v", effect="NoSchedule")], [], False),
    ("NoSchedule taint, matching toleration",
     [Taint(key="k", value="v", effect="NoSchedule")],
     [tol("k", "Equal", "v", "NoSchedule")], True),
    ("NoSchedule taint, value mismatch",
     [Taint(key="k", value="v", effect="NoSchedule")],
     [tol("k", "Equal", "other", "NoSchedule")], False),
    ("NoSchedule taint, Exists toleration ignores value",
     [Taint(key="k", value="v", effect="NoSchedule")],
     [tol("k", "Exists", "", "NoSchedule")], True),
    ("empty-effect toleration matches any effect",
     [Taint(key="k", value="v", effect="NoSchedule")],
     [tol("k", "Equal", "v", "")], True),
    ("empty-key Exists toleration matches everything",
     [Taint(key="k", value="v", effect="NoSchedule"),
      Taint(key="k2", value="v2", effect="NoExecute")],
     [tol("", "Exists", "", "")], True),
    ("NoExecute taint, no toleration",
     [Taint(key="k", value="v", effect="NoExecute")], [], False),
    ("PreferNoSchedule taint never filters",
     [Taint(key="k", value="v", effect="PreferNoSchedule")], [], True),
    ("two taints, one tolerated",
     [Taint(key="k1", value="v1", effect="NoSchedule"),
      Taint(key="k2", value="v2", effect="NoSchedule")],
     [tol("k1", "Equal", "v1", "NoSchedule")], False),
    ("two taints, both tolerated",
     [Taint(key="k1", value="v1", effect="NoSchedule"),
      Taint(key="k2", value="v2", effect="NoSchedule")],
     [tol("k1", "Equal", "v1", "NoSchedule"),
      tol("k2", "Exists", "", "")], True),
    ("toleration for the wrong effect",
     [Taint(key="k", value="v", effect="NoExecute")],
     [tol("k", "Equal", "v", "NoSchedule")], False),
]


@pytest.mark.parametrize("name,taints,tols,want", TAINT_CASES,
                         ids=[c[0] for c in TAINT_CASES])
def test_taint_toleration_golden(name, taints, tols, want):
    node = _mknode("tainted")
    node.spec.taints = taints
    pod = _mkpod("p", req={"cpu": "100m"})
    pod.spec.tolerations = tols
    feas = feasible_set(pod, [node])
    assert (("tainted" in feas) == want), name
    if not want:
        _, plugins = reject_plugins(pod, [node])
        assert "TaintToleration" in plugins, name


def _port_pod(name, *ports, node=""):
    p = _mkpod(name, req={"cpu": "100m"}, node=node)
    p.spec.containers[0].ports = [
        ContainerPort(host_port=hp, protocol=proto, host_ip=ip)
        for hp, proto, ip in ports]
    return p


# node_ports_test.go TestNodePorts: want = fits next to `existing`
PORT_CASES = [
    ("nothing running", (8080, "TCP", ""), None, True),
    ("other port in use", (8080, "TCP", ""), (8081, "TCP", ""), True),
    ("same port conflicts", (8080, "TCP", ""), (8080, "TCP", ""), False),
    ("same port different protocol", (8080, "UDP", ""),
     (8080, "TCP", ""), True),
    ("same port different specific IPs", (8080, "TCP", "127.0.0.1"),
     (8080, "TCP", "192.168.0.1"), True),
    ("wildcard IP conflicts with specific IP", (8080, "TCP", "0.0.0.0"),
     (8080, "TCP", "127.0.0.1"), False),
    ("specific IP conflicts with wildcard", (8080, "TCP", "127.0.0.1"),
     (8080, "TCP", ""), False),
    ("no host port requested never conflicts", None, (8080, "TCP", ""),
     True),
]


@pytest.mark.parametrize("name,want_ports,existing_ports,want", PORT_CASES,
                         ids=[c[0] for c in PORT_CASES])
def test_node_ports_golden(name, want_ports, existing_ports, want):
    node = _mknode("pn")
    existing = []
    if existing_ports:
        existing.append(_port_pod("running", existing_ports, node="pn"))
    pod = (_port_pod("incoming", want_ports) if want_ports
           else _mkpod("incoming", req={"cpu": "100m"}))
    feas = feasible_set(pod, [node], existing)
    assert (("pn" in feas) == want), name
    if not want:
        _, plugins = reject_plugins(pod, [node], existing)
        assert "NodePorts" in plugins, name


# suite-tier discipline (tests/test_markers.py): area marker
pytestmark = pytest.mark.core
