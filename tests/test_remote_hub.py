"""The HTTP hub transport (hubserver + hubclient): the scheduler running
against a hub across a REAL network boundary — the stack's equivalent of
the reference's integration tests against an in-process apiserver
(test/integration/util/util.go:86), except the wire here is actual HTTP
LIST+WATCH."""

import threading

import pytest

from kubernetes_tpu.api.objects import Pod, PodSpec
from kubernetes_tpu.config.types import default_config
from kubernetes_tpu.hub import Conflict, EventHandlers, Hub, NotFound
from kubernetes_tpu.hubclient import RemoteHub
from kubernetes_tpu.hubserver import HubServer
from kubernetes_tpu.ops.features import Capacities
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.testing import MakeNode, MakePod


@pytest.fixture()
def served_hub():
    hub = Hub()
    server = HubServer(hub).start()
    client = RemoteHub(server.address)
    yield hub, client
    client.close()
    server.stop()


def test_crud_and_errors_roundtrip(served_hub):
    hub, client = served_hub
    node = MakeNode().name("n1").capacity(cpu="8").obj()
    client.create_node(node)
    # the server-side hub saw the real object
    assert hub.get_node("n1").status.allocatable["cpu"] == "8"
    got = client.get_node("n1")
    assert got.metadata.uid == node.metadata.uid
    pod = MakePod().name("p").req(cpu="1").obj()
    client.create_pod(pod)
    with pytest.raises(Conflict):
        client.create_pod(pod)            # duplicate uid -> 409 -> Conflict
    client.bind(pod, "n1")
    with pytest.raises(Conflict):
        client.bind(pod, "n1")            # already bound
    with pytest.raises(NotFound):
        client.delete_pod("no-such-uid")
    assert client.get_pod(pod.metadata.uid).spec.node_name == "n1"


def test_watch_replay_and_live_events(served_hub):
    hub, client = served_hub
    client.create_node(MakeNode().name("replayed").obj())
    seen: list[str] = []
    updates: list[tuple] = []
    done = threading.Event()
    client.watch_nodes(EventHandlers(
        on_add=lambda o: seen.append(o.metadata.name),
        on_update=lambda old, new: (updates.append(
            (old.metadata.name, new.status.allocatable.get("cpu"))),
            done.set())))
    # replay delivered synchronously before watch_nodes returned
    assert seen == ["replayed"]
    live = MakeNode().name("live").obj()
    hub.create_node(live)                 # server-side create -> live event
    live2 = MakeNode().name("live").capacity(cpu="64").obj()
    live2.metadata.uid = live.metadata.uid
    hub.update_node(live2)
    assert done.wait(5), "live update event must stream through"
    assert "live" in seen
    assert updates == [("live", "64")]


def test_scheduler_runs_against_remote_hub(served_hub):
    hub, client = served_hub
    for i in range(4):
        client.create_node(MakeNode().name(f"rn-{i}").obj())
    cfg = default_config()
    cfg.batch_size = 8
    sched = Scheduler(client, cfg, caps=Capacities(nodes=16, pods=64))
    pods = [MakePod().name(f"rp-{i}").req(cpu="500m").obj()
            for i in range(10)]
    bound = threading.Event()
    remaining = set(p.metadata.uid for p in pods)

    def on_update(old, new):
        if new.spec.node_name:
            remaining.discard(new.metadata.uid)
            if not remaining:
                bound.set()

    client.watch_pods(EventHandlers(on_update=on_update), replay=False)
    for p in pods:
        client.create_pod(p)
    # pod creations arrive via the watch stream — wait for them to reach
    # the queue, then drain
    deadline = threading.Event()
    for _ in range(100):
        sched.run_until_idle()
        if not remaining:
            break
        deadline.wait(0.05)
    assert not remaining, f"unbound: {len(remaining)}"
    # bindings are visible on the SERVER hub (went over the wire)
    assert all(hub.get_pod(p.metadata.uid).spec.node_name for p in pods)
    sched.close()


def test_hubserver_restart_mid_watch_emits_gap_diff():
    """Kill and restart the hubserver mid-watch: the reconnect's relist
    diff must emit the adds, UPDATES, and deletes that happened during
    the gap (the docstring contract at hubclient.RemoteHub._watch) —
    rv-newer objects as updates, unknown ones as adds, vanished ones as
    deletes."""
    import socket
    import time

    hub = Hub()
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    server = HubServer(hub, port=port).start()
    client = RemoteHub(f"http://127.0.0.1:{port}", timeout=10.0,
                       retry_base=0.01, retry_cap=0.2)
    kept = MakePod().name("kept").req(cpu="1").obj()
    doomed = MakePod().name("doomed").req(cpu="1").obj()
    hub.create_pod(kept)
    hub.create_pod(doomed)
    added, updated, deleted = [], [], []
    client.watch_pods(EventHandlers(
        on_add=lambda o: added.append(o.metadata.name),
        on_update=lambda old, new: updated.append(
            (new.metadata.name, new.spec.node_name)),
        on_delete=lambda o: deleted.append(o.metadata.name)))
    assert sorted(added) == ["doomed", "kept"]
    server.stop()                      # stream dies
    # mutate while the reflector is disconnected: one of each verb
    hub.delete_pod(doomed.metadata.uid)
    fresh = MakePod().name("fresh").req(cpu="1").obj()
    hub.create_pod(fresh)
    hub.bind(kept, "somewhere")        # update: kept gains a node_name
    server2 = HubServer(hub, port=port).start()
    try:
        deadline = time.time() + 15
        while time.time() < deadline and (
                "fresh" not in added or "doomed" not in deleted
                or ("kept", "somewhere") not in updated):
            time.sleep(0.05)
        assert "fresh" in added, "add missed during gap must relist in"
        assert deleted == ["doomed"], "delete during gap must be diffed in"
        assert ("kept", "somewhere") in updated, \
            "rv-newer object must dispatch as an update after the gap"
        assert added.count("kept") == 1, "no duplicate adds from relist"
    finally:
        client.close()
        server2.stop()


def test_watch_unknown_kind_fails_fast(served_hub):
    """A definitive server verdict (400 unknown kind) must surface
    immediately as RemoteError, not blind-retry to the deadline."""
    import time

    from kubernetes_tpu.hubclient import RemoteError

    hub, client = served_hub
    t0 = time.time()
    with pytest.raises(RemoteError):
        client._watch("bogus", EventHandlers(), True)
    assert time.time() - t0 < 2.0


def test_lease_rpc(served_hub):
    hub, client = served_hub
    from kubernetes_tpu.leaderelection import Lease

    lease = Lease(name="sched", holder_identity="a", renew_time=1.0,
                  acquire_time=1.0)
    assert client.leases.update(lease, None) is True
    got = client.leases.get("sched")
    assert got.holder_identity == "a"
    steal = Lease(name="sched", holder_identity="b", renew_time=2.0,
                  acquire_time=2.0)
    assert client.leases.update(steal, "wrong-holder") is False
    assert hub.leases.get("sched").holder_identity == "a"


def test_reflector_reconnects_and_relists():
    """The stream dying (server restart on the same port) must not freeze
    the informer: the reflector reconnects, relists, dedups what it saw,
    and emits the adds/deletes it missed during the gap."""
    import socket
    import time

    hub = Hub()
    # fixed port so the restarted server is reachable at the same URL
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    server = HubServer(hub, port=port).start()
    client = RemoteHub(f"http://127.0.0.1:{port}", timeout=10.0)
    kept = MakeNode().name("kept").obj()
    doomed = MakeNode().name("doomed").obj()
    hub.create_node(kept)
    hub.create_node(doomed)
    added, deleted = [], []
    client.watch_nodes(EventHandlers(
        on_add=lambda o: added.append(o.metadata.name),
        on_delete=lambda o: deleted.append(o.metadata.name)))
    assert sorted(added) == ["doomed", "kept"]
    server.stop()                      # stream dies
    # mutate while the reflector is disconnected
    hub.delete_node(doomed.metadata.uid)
    fresh = MakeNode().name("fresh").obj()
    hub.create_node(fresh)
    server2 = HubServer(hub, port=port).start()
    try:
        deadline = time.time() + 10
        while time.time() < deadline and ("fresh" not in added
                                          or "doomed" not in deleted):
            time.sleep(0.05)
        assert "fresh" in added, "missed add during gap must relist in"
        assert deleted == ["doomed"], "missed delete must be diffed in"
        assert added.count("kept") == 1, "no duplicate adds from relist"
    finally:
        client.close()
        server2.stop()


# suite-tier discipline (tests/test_markers.py): area marker
pytestmark = pytest.mark.fabric
